"""Exchange plumbing: unix-domain sockets between worker processes.

Topology: every worker hosts one **server** socket and dials one
**client** connection to every other worker — worker w's keyed operator
therefore has N inbound *edges*: N-1 sockets plus a zero-copy loopback
from its own ingest half.  Frames (cluster/framing.py) flow sender →
receiver only; there is no request/response.

The receive side runs one thread per inbound connection, decoding frames
into a bounded per-edge queue — the queue bound (plus the kernel socket
buffer) IS the exchange's backpressure, exactly like the prefetch
pump's per-partition double buffer.  The :class:`EdgeMerger` is the
single consumer: it merges data across edges, merges **watermarks** as
the min over per-edge watermarks (an edge's watermark advances via
piggybacked data-frame watermarks and explicit wm frames), aligns
**barriers** (an edge that delivered barrier E is not consumed again
until every live edge delivered E — the aligned Chandy-Lamport cut,
same invariant the join operator enforces per-epoch), and collapses to
EOS when every edge reports it.

Failure model is fail-stop: any integrity violation (torn frame, CRC
mismatch, refused reconnect) raises ``SourceError`` out of the worker,
and the coordinator restarts the cluster from the last cluster-committed
epoch.  Fault sites ``exchange.connect`` / ``exchange.send`` /
``exchange.recv`` (runtime/faults.py) make every one of those paths
reproducible on demand; ``exchange.send`` supports ``torn`` rules — the
truncated frame is genuinely written before the connection drops, so
the RECEIVER exercises its tear detection, not just the sender its
error path.
"""

from __future__ import annotations

import queue
import socket
import threading
import time

from denormalized_tpu.common.errors import SourceError
from denormalized_tpu.runtime import faults
from denormalized_tpu.cluster import framing

#: per-edge inbound queue bound (items, mostly data frames): with the
#: socket buffer this bounds memory while a barrier-blocked edge waits
EDGE_QUEUE_ITEMS = 16

_CONNECT_TIMEOUT_S = 30.0


class ExchangeClient:
    """One outbound edge: this worker's ingest half → peer ``dst``."""

    def __init__(self, src: int, dst: int, sock_path: str) -> None:
        from denormalized_tpu import obs

        self.src = src
        self.dst = dst
        self.sock_path = sock_path
        self.edge = f"{src}->{dst}"
        self._sock: socket.socket | None = None
        self._obs_frames = obs.counter(
            "dnz_exchange_frames_total", dir="send", edge=self.edge
        )
        self._obs_bytes = obs.counter(
            "dnz_exchange_bytes_total", dir="send", edge=self.edge
        )
        self._obs_send_ms = obs.histogram(
            "dnz_exchange_send_ms", edge=self.edge
        )

    def connect(self, deadline_s: float = _CONNECT_TIMEOUT_S) -> None:
        """Dial the peer's server socket (which may not be listening yet
        — workers start concurrently), then identify this edge with a
        hello frame.  Retries cover startup races only; an injected
        fault or the deadline fails the worker outright."""
        faults.inject("exchange.connect", key=self.edge)
        deadline = time.monotonic() + deadline_s
        last: Exception | None = None
        while time.monotonic() < deadline:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                s.connect(self.sock_path)
                self._sock = s
                self.send(framing.encode_hello(self.src))
                return
            except OSError as e:
                s.close()
                self._sock = None
                last = e
                time.sleep(0.05)
        raise SourceError(
            f"exchange connect {self.edge} failed after {deadline_s}s: {last}"
        )

    def send(self, frame: bytes) -> None:
        """Write one frame.  A ``torn`` fault rule truncates the bytes
        actually written and then drops the connection, so the tear is
        observed where real tears are: at the receiver."""
        if self._sock is None:
            raise SourceError(f"exchange edge {self.edge} not connected")
        t0 = time.perf_counter()
        payload = faults.inject("exchange.send", key=self.edge, payload=frame)
        try:
            self._sock.sendall(payload)
        except OSError as e:
            raise SourceError(
                f"exchange send on {self.edge} failed: {e}"
            ) from e
        if len(payload) != len(frame):
            # the torn prefix is on the wire; kill the connection so the
            # receiver sees a mid-frame EOF/CRC failure, then fail this
            # worker — exactly what a mid-send process death looks like
            self.close()
            raise SourceError(
                f"exchange frame torn by fault injection on {self.edge} "
                f"({len(payload)}/{len(frame)} bytes written)"
            )
        self._obs_frames.add(1)
        self._obs_bytes.add(len(frame))
        self._obs_send_ms.observe((time.perf_counter() - t0) * 1e3)

    def close(self) -> None:
        s, self._sock = self._sock, None
        if s is not None:
            try:
                s.close()
            except OSError:
                pass


class EdgeState:
    """Receiver-side state of one inbound edge."""

    __slots__ = ("edge_id", "queue", "wm", "aligned", "eos", "depth_gauge")

    def __init__(self, edge_id: int, depth_gauge) -> None:
        self.edge_id = edge_id
        self.queue: queue.Queue = queue.Queue(maxsize=EDGE_QUEUE_ITEMS)
        self.wm: int | None = None
        self.aligned = False  # delivered the in-flight barrier epoch
        self.eos = False
        self.depth_gauge = depth_gauge


class ExchangeServer:
    """This worker's inbound half: accepts N-1 peer connections, runs
    one decode thread per connection, and exposes the per-edge queues to
    the :class:`EdgeMerger`."""

    def __init__(
        self, worker_id: int, n_workers: int, sock_path: str, schema
    ) -> None:
        from denormalized_tpu import obs

        self.worker_id = worker_id
        self.n_workers = n_workers
        self.schema = schema
        self.sock_path = sock_path
        self.edges: dict[int, EdgeState] = {
            w: EdgeState(
                w,
                obs.gauge(
                    "dnz_exchange_edge_depth", edge=f"{w}->{worker_id}"
                ),
            )
            for w in range(n_workers)
        }
        self._obs_frames = obs.counter(
            "dnz_exchange_frames_total", dir="recv",
            edge=f"*->{worker_id}",
        )
        self._obs_bytes = obs.counter(
            "dnz_exchange_bytes_total", dir="recv",
            edge=f"*->{worker_id}",
        )
        self.wake = threading.Event()
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(sock_path)
        self._listener.listen(n_workers)
        self._threads: list[threading.Thread] = []
        self._stop = threading.Event()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"exch-accept-{worker_id}",
            daemon=True,
        )
        self._accept_thread.start()

    # -- loopback (ingest half of THIS worker) ---------------------------
    def local_put(self, item: tuple) -> None:
        """Zero-copy enqueue from this worker's own ingest half — no
        socket, no framing, no fault site (the in-process edge is not an
        I/O boundary)."""
        edge = self.edges[self.worker_id]
        edge.queue.put(item)
        edge.depth_gauge.set(edge.queue.qsize())
        self.wake.set()

    # -- socket side ------------------------------------------------------
    def _accept_loop(self) -> None:
        expected = self.n_workers - 1
        accepted = 0
        while accepted < expected and not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return  # listener closed during shutdown
            t = threading.Thread(
                target=self._recv_loop, args=(conn,),
                name=f"exch-recv-{self.worker_id}", daemon=True,
            )
            t.start()
            self._threads.append(t)
            accepted += 1
        try:
            self._listener.close()
        except OSError:
            pass

    def _recv_loop(self, conn: socket.socket) -> None:
        """Decode frames from one peer into its edge queue.  Any
        integrity failure is delivered IN-BAND as an ("err", exc) item —
        the merger re-raises on the consumer thread, the worker dies,
        the coordinator recovers (fail-stop contract)."""
        edge: EdgeState | None = None
        try:
            payload = framing.read_frame(conn)
            if payload is None:
                return  # peer connected and vanished before hello
            kind = framing.decode_frame(payload, self.schema)
            if kind[0] != "hello":
                raise SourceError(
                    f"exchange peer spoke {kind[0]!r} before hello"
                )
            edge = self.edges[kind[1]]
            while not self._stop.is_set():
                faults.inject(
                    "exchange.recv",
                    key=f"{edge.edge_id}->{self.worker_id}",
                )
                payload = framing.read_frame(conn)
                if payload is None:
                    # clean EOF without an eos frame: the peer died —
                    # surface, never silently treat as end-of-partition
                    raise SourceError(
                        f"exchange edge {edge.edge_id}->{self.worker_id} "
                        "closed without EOS"
                    )
                item = framing.decode_frame(payload, self.schema)
                self._obs_frames.add(1)
                self._obs_bytes.add(len(payload))
                edge.queue.put(item)
                edge.depth_gauge.set(edge.queue.qsize())
                self.wake.set()
                if item[0] == "eos":
                    return
        except SourceError as e:
            if edge is not None:
                edge.queue.put(("err", e))
                self.wake.set()
            # hello never arrived: no edge to poison — the merger will
            # starve and the coordinator's liveness timeout recovers
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass


class EdgeMerger:
    """Single consumer over all inbound edges: data interleaves freely,
    watermarks merge as the min over live edges, barriers align, EOS
    collapses when unanimous.  Yields engine stream items — see
    :class:`~denormalized_tpu.cluster.runtime.ExchangeSourceExec` for
    where they enter the keyed pipeline."""

    def __init__(self, server: ExchangeServer) -> None:
        self.server = server
        self._merged_wm: int | None = None

    def _merged_watermark(self) -> int | None:
        """Min over non-EOS edges; an exhausted edge leaves the min
        (same rule as finished partitions in _PartitionWatermarks)."""
        live = [
            e.wm for e in self.server.edges.values() if not e.eos
        ]
        if not live or any(w is None for w in live):
            return None
        return min(live)

    def __iter__(self):
        """→ ("data", batch) | ("wm", ts) | ("barrier", epoch) | EOS (by
        StopIteration).  Runs on the keyed half's thread."""
        edges = list(self.server.edges.values())
        barrier_epoch: int | None = None
        while True:
            progressed = False
            for e in edges:
                if e.eos or e.aligned:
                    continue
                try:
                    item = e.queue.get_nowait()
                except queue.Empty:
                    continue
                e.depth_gauge.set(e.queue.qsize())
                progressed = True
                t = item[0]
                if t == "err":
                    raise item[1]
                if t == "data":
                    _, batch, wm = item
                    if wm is not None and (e.wm is None or wm > e.wm):
                        e.wm = wm
                    yield ("data", batch)
                    merged = self._merged_watermark()
                    if merged is not None and (
                        self._merged_wm is None or merged > self._merged_wm
                    ):
                        self._merged_wm = merged
                        yield ("wm", merged)
                elif t == "wm":
                    if e.wm is None or item[1] > e.wm:
                        e.wm = item[1]
                    merged = self._merged_watermark()
                    if merged is not None and (
                        self._merged_wm is None or merged > self._merged_wm
                    ):
                        self._merged_wm = merged
                        yield ("wm", merged)
                elif t == "barrier":
                    if barrier_epoch is not None and item[1] != barrier_epoch:
                        raise SourceError(
                            f"exchange barrier overlap: epoch {item[1]} "
                            f"arrived while {barrier_epoch} is aligning "
                            "(the coordinator issues barriers serially)"
                        )
                    barrier_epoch = item[1]
                    e.aligned = True
                elif t == "eos":
                    e.eos = True
                else:
                    raise SourceError(f"unknown exchange item {t!r}")
                # an EOS edge satisfies any in-flight barrier (its
                # sender persisted final offsets coordinator-side)
                if barrier_epoch is not None and all(
                    x.aligned or x.eos for x in edges
                ):
                    for x in edges:
                        x.aligned = False
                    ep, barrier_epoch = barrier_epoch, None
                    yield ("barrier", ep)
                if all(x.eos for x in edges):
                    return
            if not progressed:
                self.server.wake.wait(timeout=0.002)
                self.server.wake.clear()
