"""Exchange wire format: length-prefixed, CRC-framed column buffers.

The cross-process sibling of the checkpoint blob format
(state/serialization.py + state/checkpoint.py framing): every frame is

::

    [4B magic "DNZX"][u32 payload_len][u32 crc32(payload)][payload]
    payload = [u32 header_len][header JSON utf-8][col buf 0][col buf 1]...

No pickle — frames are decodable across processes and a torn or
bit-flipped frame is DETECTED (magic/length/CRC mismatch raises
``SourceError``) instead of being reassembled into garbage rows.  Data
frames carry raw little-endian column buffers for numeric columns and a
JSON value list for object (string) columns; every data frame also
piggybacks the sender's current watermark so an edge that only ever
receives another worker's keys still advances event time.

Frame types (``"t"`` in the header): ``hello`` (edge identification),
``data`` (column buffers + watermark), ``wm`` (watermark-only advance),
``barrier`` (checkpoint epoch marker, in-band), ``eos`` (sender's
partitions exhausted).

``encode_data`` / ``decode_data`` are pinned hot paths
(tools/dnzlint/hotpaths.toml): per-column comprehensions only, never
per-row statements.
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

from denormalized_tpu.common.errors import SourceError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema

MAGIC = b"DNZX"
_HDR = struct.Struct("<4sII")  # magic, payload_len, payload_crc32

#: refuse frames claiming more than this — a corrupt length prefix must
#: not turn into a multi-GB allocation before the CRC check can run
MAX_FRAME_BYTES = 1 << 30


def _frame(payload: bytes) -> bytes:
    return _HDR.pack(MAGIC, len(payload), zlib.crc32(payload)) + payload


def _payload(header: dict, bufs: list[bytes]) -> bytes:
    hj = json.dumps(header, separators=(",", ":")).encode()
    return b"".join([struct.pack("<I", len(hj)), hj] + bufs)


def encode_hello(worker_id: int) -> bytes:
    return _frame(_payload({"t": "hello", "from": int(worker_id)}, []))


def encode_wm(ts_ms: int) -> bytes:
    return _frame(_payload({"t": "wm", "wm": int(ts_ms)}, []))


def encode_barrier(epoch: int) -> bytes:
    return _frame(_payload({"t": "barrier", "epoch": int(epoch)}, []))


def encode_eos() -> bytes:
    return _frame(_payload({"t": "eos"}, []))


def _col_buf(col: np.ndarray) -> bytes:
    if col.dtype == object:
        return json.dumps(col.tolist()).encode()  # dnzlint: allow(hot-loop) object (string) columns have no raw-buffer form; the JSON lane is the documented slow path for string keys
    return np.ascontiguousarray(col).tobytes()


def encode_data(batch: RecordBatch, wm_ms: int | None) -> bytes:
    """One RecordBatch → one frame.  Column order is schema order (the
    receiver rebuilds against its own copy of the same schema); masks
    ride as optional bool buffers."""
    bufs = [_col_buf(c) for c in batch.columns]
    mask_bufs = [
        np.ascontiguousarray(m).tobytes() if m is not None else b""
        for m in batch.masks
    ]
    header = {
        "t": "data",
        "wm": int(wm_ms) if wm_ms is not None else None,
        "rows": int(batch.num_rows),
        "cols": [
            {
                "dtype": "obj" if c.dtype == object else c.dtype.str,
                "nbytes": len(b),
            }
            for c, b in zip(batch.columns, bufs)
        ],
        "masks": [len(b) if m is not None else None
                  for m, b in zip(batch.masks, mask_bufs)],
    }
    return _frame(_payload(header, bufs + [b for b in mask_bufs if b]))


def decode_frame(payload: bytes, schema: Schema) -> tuple:
    """Decode one verified payload → ``(type, ...)`` tuple:

    - ``("hello", worker_id)``
    - ``("data", RecordBatch, wm_ms_or_None)``
    - ``("wm", ts_ms)``
    - ``("barrier", epoch)``
    - ``("eos",)``
    """
    if len(payload) < 4:
        raise SourceError("exchange frame too short for header length")
    (hlen,) = struct.unpack_from("<I", payload, 0)
    if 4 + hlen > len(payload):
        raise SourceError("exchange frame header overruns payload")
    try:
        header = json.loads(payload[4:4 + hlen].decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise SourceError(f"exchange frame header undecodable: {e}") from e
    t = header.get("t")
    if t == "data":
        return ("data",) + decode_data(header, payload, hlen, schema)
    if t == "wm":
        return ("wm", int(header["wm"]))
    if t == "barrier":
        return ("barrier", int(header["epoch"]))
    if t == "eos":
        return ("eos",)
    if t == "hello":
        return ("hello", int(header["from"]))
    raise SourceError(f"unknown exchange frame type {t!r}")


def _col_from(buf: bytes, spec: dict, rows: int) -> np.ndarray:
    if spec["dtype"] == "obj":
        vals = json.loads(buf.decode())
        arr = np.empty(rows, dtype=object)
        arr[:] = vals
        return arr
    return np.frombuffer(buf, dtype=np.dtype(spec["dtype"]))


def decode_data(
    header: dict, payload: bytes, hlen: int, schema: Schema
) -> tuple[RecordBatch, int | None]:
    """Data payload → (RecordBatch, piggybacked watermark).  Numeric
    columns are zero-copy views over the frame buffer (read-only —
    operators never mutate input columns)."""
    rows = int(header["rows"])
    specs = header["cols"]
    if len(specs) != len(schema):
        raise SourceError(
            f"exchange data frame has {len(specs)} columns, schema "
            f"expects {len(schema)}"
        )
    off = 4 + hlen
    cols = []
    for spec in specs:  # dnzlint: allow(hot-loop) bounded per-COLUMN sweep (schema width), never per-row; offsets are sequential so this cannot be a comprehension
        n = int(spec["nbytes"])
        cols.append(_col_from(payload[off:off + n], spec, rows))
        off += n
    masks = []
    for mspec in header["masks"]:  # dnzlint: allow(hot-loop) same bounded per-column sweep for the optional validity masks
        if mspec is None:
            masks.append(None)
        else:
            masks.append(
                np.frombuffer(payload[off:off + mspec], dtype=bool)
            )
            off += mspec
    batch = RecordBatch(schema, cols, masks)
    wm = header.get("wm")
    return batch, int(wm) if wm is not None else None


def read_exact(sock, n: int) -> bytes | None:
    """Read exactly ``n`` bytes from a socket; None on clean EOF at a
    frame boundary (0 bytes read), SourceError on EOF mid-frame (a torn
    frame — the sender died or a fault rule cut it)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise SourceError(
                f"exchange connection torn mid-frame ({got}/{n} bytes)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(sock) -> bytes | None:
    """Read + verify one frame from a socket → payload bytes, or None on
    clean EOF.  Every integrity violation (bad magic, oversize length,
    CRC mismatch, mid-frame EOF) raises ``SourceError`` — the worker
    fails stop-the-world and the coordinator restarts the cluster from
    the last committed epoch (docs/cluster.md#failure-matrix)."""
    hdr = read_exact(sock, _HDR.size)
    if hdr is None:
        return None
    magic, plen, crc = _HDR.unpack(hdr)
    if magic != MAGIC:
        raise SourceError(f"exchange frame bad magic {magic!r}")
    if plen > MAX_FRAME_BYTES:
        raise SourceError(f"exchange frame length {plen} exceeds cap")
    payload = read_exact(sock, plen)
    if payload is None:
        raise SourceError("exchange connection torn before payload")
    if zlib.crc32(payload) != crc:
        raise SourceError("exchange frame CRC mismatch (torn or corrupt)")
    return payload
