"""Exchange wire format: length-prefixed, CRC-framed column buffers.

The cross-process sibling of the checkpoint blob format
(state/serialization.py + state/checkpoint.py framing): every frame is

::

    [4B magic "DNZX"][u32 payload_len][u32 crc32(payload)][payload]
    payload = [u32 header_len][header JSON utf-8][col buf 0][col buf 1]...

No pickle — frames are decodable across processes and a torn or
bit-flipped frame is DETECTED (magic/length/CRC mismatch raises
``SourceError``) instead of being reassembled into garbage rows.  Data
frames carry raw little-endian column buffers for numeric columns and a
JSON value list for object (string) columns; every data frame also
piggybacks the sender's current watermark so an edge that only ever
receives another worker's keys still advances event time.

Frame types (``"t"`` in the header): ``hello`` (edge identification:
worker id + sender generation + the sender's pinned restore epoch),
``data`` (column buffers + watermark + optional source-partition id),
``wm`` (watermark-only advance), ``barrier`` (checkpoint epoch marker,
in-band), ``eos`` (sender's partitions exhausted), and ``resume`` — the
ONE receiver→sender frame in the protocol, written by the exchange
server right after every hello so a reconnecting sender learns where
the edge stands (frames seen, last committed barrier, rows delivered
per source partition since that barrier).  Sequence numbers are
IMPLICIT: both ends count post-hello frames per sender generation, so
the wire format needs no per-frame counter — a replayed frame keeps
its original position by construction (docs/cluster.md#rejoin).

``encode_data`` / ``decode_data`` are pinned hot paths
(tools/dnzlint/hotpaths.toml): per-column comprehensions only, never
per-row statements.
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

from denormalized_tpu.common.columns import (
    Column,
    column_from_spec,
    column_spec_and_buffers,
)
from denormalized_tpu.common.errors import SourceError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import Schema

MAGIC = b"DNZX"
_HDR = struct.Struct("<4sII")  # magic, payload_len, payload_crc32

#: refuse frames claiming more than this — a corrupt length prefix must
#: not turn into a multi-GB allocation before the CRC check can run
MAX_FRAME_BYTES = 1 << 30


def _frame(payload: bytes) -> bytes:
    return _HDR.pack(MAGIC, len(payload), zlib.crc32(payload)) + payload


def _payload(header: dict, bufs: list[bytes]) -> bytes:
    hj = json.dumps(header, separators=(",", ":")).encode()
    return b"".join([struct.pack("<I", len(hj)), hj] + bufs)


def encode_hello(
    worker_id: int, gen: int = 0, restore_epoch: int = 0
) -> bytes:
    """Edge identification.  ``gen`` is the sender's incarnation number
    (bumped by the coordinator at every spawn of that worker, full or
    partial) — the receiver resets its per-edge frame count when it
    sees a new generation.  ``restore_epoch`` is the cluster-committed
    epoch the sender was pinned to at startup (0 = fresh): a reborn
    sender's peers answer with how many rows per partition they already
    received since that barrier, so the replayed stream is deduplicated
    exactly (docs/cluster.md#rejoin)."""
    return _frame(_payload(
        {"t": "hello", "from": int(worker_id), "gen": int(gen),
         "restore": int(restore_epoch)},
        [],
    ))


def encode_resume(
    gen_seen: int,
    frames_seen: int,
    epoch: int,
    counts: dict[int, int],
    counts_ok: bool = True,
) -> bytes:
    """Receiver → sender, written once after every hello.  ``gen_seen``
    is the sender generation the receiver last heard from on this edge
    (-1 = never — fresh receiver or fresh edge), ``frames_seen`` the
    number of post-hello frames it fully processed from that
    generation, ``epoch`` the last cluster-committed barrier it knows,
    and ``counts`` the rows per source partition delivered on this edge
    since that barrier (the reborn-sender dedup ledger).  ``counts_ok``
    is False when the receiver could not attribute rows to partitions
    (unstamped batches) — the sender must then escalate to the
    full-cluster fallback rather than guess."""
    return _frame(_payload(
        {"t": "resume", "gen": int(gen_seen), "seen": int(frames_seen),
         "epoch": int(epoch),
         "counts": {str(k): int(v) for k, v in counts.items()},
         "ok": bool(counts_ok)},
        [],
    ))


def encode_wm(ts_ms: int) -> bytes:
    return _frame(_payload({"t": "wm", "wm": int(ts_ms)}, []))


def encode_barrier(
    epoch: int, skips: dict[int, int] | None = None
) -> bytes:
    """Checkpoint epoch marker.  ``skips`` is the sender's residual
    router-side skip per source partition at the moment the barrier
    entered its stream: a reborn sender that is still draining its
    dedup skip emits barriers at a stream position BEHIND the rows the
    receiver already holds, so the receiver must subtract this residual
    when snapshotting its delivered-rows ledger for the epoch —
    otherwise a second rebirth anchored at this barrier under-skips and
    duplicates rows (docs/cluster.md#rejoin)."""
    hdr: dict = {"t": "barrier", "epoch": int(epoch)}
    if skips:
        hdr["skips"] = {str(k): int(v) for k, v in skips.items()}
    return _frame(_payload(hdr, []))


def encode_eos() -> bytes:
    return _frame(_payload({"t": "eos"}, []))


def _legacy_json_lane() -> bool:
    """``DENORMALIZED_EXCHANGE_JSON=1`` forces string/nested columns onto
    the legacy JSON value-list lane (kept for one PR as the raw lane's
    differential oracle; both lanes decode everywhere)."""
    import os

    return os.environ.get("DENORMALIZED_EXCHANGE_JSON") == "1"


def _col_buf(col: np.ndarray) -> bytes:
    if col.dtype == object:
        return json.dumps(col.tolist()).encode()  # dnzlint: allow(hot-loop) plain OBJECT columns (python-decoded nested values, mixed objects) have no raw-buffer form; columnar StringColumn/NestedColumn ride the raw offsets+bytes sub-frames in _col_spec_bufs instead
    return np.ascontiguousarray(col).tobytes()


def _col_spec_bufs(col) -> tuple[dict, list[bytes]]:
    """(header spec, raw buffers) for one column.  Columnar string/nested
    columns ship their buffers VERBATIM — offsets+bytes sub-frames, no
    JSON, no per-row Python; ndarrays keep the historical single-buffer
    lanes."""
    if isinstance(col, Column) and not _legacy_json_lane():
        spec, arrs = column_spec_and_buffers(col)
        bufs = [np.ascontiguousarray(a).tobytes() for a in arrs]
        return (
            {"dtype": "col", "spec": spec, "nb": [len(b) for b in bufs],
             "nbytes": sum(len(b) for b in bufs)},
            bufs,
        )
    arr = np.asarray(col)
    b = _col_buf(arr)
    return (
        {"dtype": "obj" if arr.dtype == object else arr.dtype.str,
         "nbytes": len(b)},
        [b],
    )


def encode_data(
    batch: RecordBatch, wm_ms: int | None, part: int | None = None
) -> bytes:
    """One RecordBatch → one frame.  Column order is schema order (the
    receiver rebuilds against its own copy of the same schema); masks
    ride as optional bool buffers.  ``part`` is the GLOBAL source
    partition the batch's rows came from (batches never mix
    partitions upstream of the router) — receivers ledger rows per
    (edge, partition) against it so a reborn sender can skip exactly
    the prefix already delivered."""
    specs_bufs = [_col_spec_bufs(c) for c in batch.columns]
    bufs = [b for _, bl in specs_bufs for b in bl]
    # a columnar column already ships its validity inside its own
    # sub-frames — re-shipping the identical batch mask would cost one
    # redundant byte per row per null-bearing column (the decode side
    # rebuilds the mask from the column's validity)
    masks = [
        None
        if m is None or (
            spec["dtype"] == "col"
            and m is getattr(c, "validity", None)
        )
        else m
        for (spec, _), c, m in zip(
            specs_bufs, batch.columns, batch.masks
        )
    ]
    mask_bufs = [
        np.ascontiguousarray(m).tobytes() if m is not None else b""
        for m in masks
    ]
    header = {
        "t": "data",
        "wm": int(wm_ms) if wm_ms is not None else None,
        "rows": int(batch.num_rows),
        "cols": [s for s, _ in specs_bufs],
        "masks": [len(b) if m is not None else None
                  for m, b in zip(masks, mask_bufs)],
    }
    if part is not None:
        header["part"] = int(part)
    return _frame(_payload(header, bufs + [b for b in mask_bufs if b]))


def decode_frame(payload: bytes, schema: Schema) -> tuple:
    """Decode one verified payload → ``(type, ...)`` tuple:

    - ``("hello", worker_id, gen, restore_epoch)``
    - ``("resume", gen_seen, frames_seen, epoch, counts, counts_ok)``
    - ``("data", RecordBatch, wm_ms_or_None, part_or_None)``
    - ``("wm", ts_ms)``
    - ``("barrier", epoch, residual_skips)``
    - ``("eos",)``
    """
    if len(payload) < 4:
        raise SourceError("exchange frame too short for header length")
    (hlen,) = struct.unpack_from("<I", payload, 0)
    if 4 + hlen > len(payload):
        raise SourceError("exchange frame header overruns payload")
    try:
        header = json.loads(payload[4:4 + hlen].decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise SourceError(f"exchange frame header undecodable: {e}") from e
    t = header.get("t")
    if t == "data":
        batch, wm = decode_data(header, payload, hlen, schema)
        part = header.get("part")
        return ("data", batch, wm, int(part) if part is not None else None)
    if t == "wm":
        return ("wm", int(header["wm"]))
    if t == "barrier":
        return (
            "barrier",
            int(header["epoch"]),
            {int(k): int(v)
             for k, v in header.get("skips", {}).items()},
        )
    if t == "eos":
        return ("eos",)
    if t == "hello":
        return (
            "hello",
            int(header["from"]),
            int(header.get("gen", 0)),
            int(header.get("restore", 0)),
        )
    if t == "resume":
        return (
            "resume",
            int(header["gen"]),
            int(header["seen"]),
            int(header["epoch"]),
            {int(k): int(v) for k, v in header.get("counts", {}).items()},
            bool(header.get("ok", True)),
        )
    raise SourceError(f"unknown exchange frame type {t!r}")


def _col_from(buf: bytes, spec: dict, rows: int) -> np.ndarray:
    if spec["dtype"] == "obj":
        vals = json.loads(buf.decode())
        arr = np.empty(rows, dtype=object)
        arr[:] = vals
        return arr
    return np.frombuffer(buf, dtype=np.dtype(spec["dtype"]))


#: buffer dtypes of the raw columnar lane, in column_spec_and_buffers'
#: depth-first order — each spec kind contributes a fixed dtype sequence,
#: reconstructed by _columnar_bufs below
_SPEC_BUF_DTYPES = {
    "str": lambda s: [np.int64, np.uint8] + ([np.bool_] if s["v"] else []),
    "prim": lambda s: [
        {"i64": np.int64, "f64": np.float64, "bool": np.uint8}[s["p"]]
    ] + ([np.bool_] if s["v"] else []),
}


def _spec_buf_dtypes(spec: dict, out: list) -> None:
    k = spec["k"]
    fixed = _SPEC_BUF_DTYPES.get(k)
    if fixed is not None:
        out.extend(fixed(spec))
        return
    if spec["v"]:
        out.append(np.bool_)
    if k == "list":
        out.append(np.int64)
    for c in spec["ch"]:
        _spec_buf_dtypes(c, out)


def _columnar_col_from(spec: dict, payload: bytes, off: int):
    """Rebuild one columnar column from its raw sub-frames (zero-copy
    views over the frame buffer — read-only, like the numeric lane)."""
    dts: list = []
    _spec_buf_dtypes(spec["spec"], dts)
    lens = spec["nb"]
    if len(dts) != len(lens):
        raise SourceError(
            "exchange columnar spec/buffer count mismatch "
            f"({len(dts)} vs {len(lens)})"
        )
    arrs = []
    for dt, n in zip(dts, lens):  # dnzlint: allow(hot-loop) bounded per-BUFFER sweep (spec tree size), never per-row; offsets are sequential
        arrs.append(np.frombuffer(payload[off:off + n], dtype=dt))
        off += n
    return column_from_spec(spec["spec"], iter(arrs)), off


def decode_data(
    header: dict, payload: bytes, hlen: int, schema: Schema
) -> tuple[RecordBatch, int | None]:
    """Data payload → (RecordBatch, piggybacked watermark).  Numeric
    columns are zero-copy views over the frame buffer (read-only —
    operators never mutate input columns); columnar string/nested
    columns rebuild as zero-copy views the same way."""
    rows = int(header["rows"])
    specs = header["cols"]
    if len(specs) != len(schema):
        raise SourceError(
            f"exchange data frame has {len(specs)} columns, schema "
            f"expects {len(schema)}"
        )
    off = 4 + hlen
    cols = []
    for spec in specs:  # dnzlint: allow(hot-loop) bounded per-COLUMN sweep (schema width), never per-row; offsets are sequential so this cannot be a comprehension
        if spec["dtype"] == "col":
            col, off = _columnar_col_from(spec, payload, off)
            cols.append(col)
            continue
        n = int(spec["nbytes"])
        cols.append(_col_from(payload[off:off + n], spec, rows))
        off += n
    masks = []
    for i, mspec in enumerate(header["masks"]):  # dnzlint: allow(hot-loop) same bounded per-column sweep for the optional validity masks
        if mspec is None:
            # columnar columns carry validity in their own sub-frames;
            # surface it as the batch mask (the sender elided the
            # redundant copy)
            masks.append(getattr(cols[i], "validity", None))
        else:
            masks.append(
                np.frombuffer(payload[off:off + mspec], dtype=bool)
            )
            off += mspec
    batch = RecordBatch(schema, cols, masks)
    wm = header.get("wm")
    return batch, int(wm) if wm is not None else None


def read_exact(sock, n: int) -> bytes | None:
    """Read exactly ``n`` bytes from a socket; None on clean EOF at a
    frame boundary (0 bytes read), SourceError on EOF mid-frame (a torn
    frame — the sender died or a fault rule cut it)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise SourceError(
                f"exchange connection torn mid-frame ({got}/{n} bytes)"
            )
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(sock) -> bytes | None:
    """Read + verify one frame from a socket → payload bytes, or None on
    clean EOF.  Every integrity violation (bad magic, oversize length,
    CRC mismatch, mid-frame EOF) raises ``SourceError`` — a torn frame
    is dropped WHOLE, so the receiver's per-edge ledgers always cover
    an exact prefix of the sender's stream.  Under partial recovery the
    receiver marks the edge down and awaits reconnect; in fail-stop
    mode the worker dies and the coordinator restarts the cluster from
    the last committed epoch (docs/cluster.md#failure-matrix)."""
    hdr = read_exact(sock, _HDR.size)
    if hdr is None:
        return None
    magic, plen, crc = _HDR.unpack(hdr)
    if magic != MAGIC:
        raise SourceError(f"exchange frame bad magic {magic!r}")
    if plen > MAX_FRAME_BYTES:
        raise SourceError(f"exchange frame length {plen} exceeds cap")
    payload = read_exact(sock, plen)
    if payload is None:
        raise SourceError("exchange connection torn before payload")
    if zlib.crc32(payload) != crc:
        raise SourceError("exchange frame CRC mismatch (torn or corrupt)")
    return payload
