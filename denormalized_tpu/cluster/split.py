"""Split a logical plan at the keyed boundary.

A cluster worker runs the SAME query twice over, in two halves:

- the **ingest half** — ``Scan`` (restricted to the worker's partition
  subset) plus every stateless operator below the keyed one — feeds the
  exchange router, which hash-partitions rows on the keyed operator's
  group columns;
- the **keyed half** — the keyed operator and everything above it —
  reads from an :class:`ExchangeScan` leaf fed by the edge merger, so
  every group key is owned by exactly one worker.

The split happens AFTER the optimizer pass (projection pruning / filter
pushdown see the full plan; the exchange then ships only the pruned
columns), and is deliberately conservative about what it accepts:
exactly one keyed operator (a ``StreamingWindow`` of any window type),
column-only group exprs (the router hashes column values — a computed
group expr would need evaluation before routing; compute it with
``with_column`` first), and no joins (the two-input exchange is the
documented next step, docs/cluster.md#limitations).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable

from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.schema import Schema
from denormalized_tpu.logical import plan as lp
from denormalized_tpu.logical.expr import Column


class ExchangeScan(lp.LogicalPlan):
    """Leaf standing in for the exchange's receive side.  Holds a live
    exec factory (the plan is built inside the worker process, never
    serialized), which the planner calls through its ``create_exec``
    extension point."""

    def __init__(self, schema: Schema, exec_factory: Callable) -> None:
        self.schema = schema
        self._exec_factory = exec_factory

    def create_exec(self, planner):
        return self._exec_factory()

    def _label(self) -> str:
        return "ExchangeScan"


@dataclass
class SplitQuery:
    """The two halves of one worker's query."""

    ingest_logical: lp.LogicalPlan  # Scan .. last stateless below keyed op
    keyed_builder: Callable[[lp.LogicalPlan], lp.LogicalPlan]
    key_columns: list[str]  # routing keys, in group-expr order
    exchange_schema: Schema  # row layout on the wire (pre-keyed-op)


def _chain(plan: lp.LogicalPlan) -> list[lp.LogicalPlan]:
    """Root→leaf chain of a purely unary plan; loud error on joins."""
    chain = []
    node = plan
    while True:
        chain.append(node)
        kids = node.children
        if not kids:
            return chain
        if len(kids) > 1 or isinstance(node, lp.Join):
            raise PlanError(
                "cluster mode supports single-input (non-join) plans — "
                "the two-input exchange is not built yet "
                "(docs/cluster.md#limitations)"
            )
        node = kids[0]


def _rebuild_above(
    chain_above: list[lp.LogicalPlan], new_input: lp.LogicalPlan
) -> lp.LogicalPlan:
    """Rebuild the nodes ABOVE the split point (given leaf→root order is
    reversed here: ``chain_above`` is root-first) onto ``new_input``."""
    node = new_input
    for orig in reversed(chain_above):
        if isinstance(orig, lp.Project):
            node = lp.Project(node, orig.exprs)
        elif isinstance(orig, lp.Filter):
            node = lp.Filter(node, orig.predicate)
        elif isinstance(orig, lp.StreamingWindow):
            node = lp.StreamingWindow(
                node,
                orig.group_exprs,
                orig.aggr_exprs,
                orig.window_type,
                orig.length_ms,
                orig.slide_ms,
            )
        elif isinstance(orig, lp.Sink):
            node = lp.Sink(node, orig.sink)
        else:
            raise PlanError(
                f"cluster mode cannot rebuild {type(orig).__name__} "
                "above the exchange"
            )
    return node


def split_keyed(plan: lp.LogicalPlan) -> SplitQuery:
    """Split an OPTIMIZED plan at its (single) keyed operator."""
    chain = _chain(plan)  # root .. leaf
    keyed = [n for n in chain if isinstance(n, lp.StreamingWindow)]
    if not keyed:
        raise PlanError(
            "cluster mode needs a keyed operator (window/session "
            "aggregation) — a stateless plan has nothing to exchange; "
            "run it single-process with more partitions instead"
        )
    if len(keyed) > 1:
        raise PlanError(
            "cluster mode supports exactly one keyed operator per plan "
            "(cascaded windowed aggregations would re-key mid-stream)"
        )
    win = keyed[0]
    key_columns: list[str] = []
    for g in win.group_exprs:
        if not isinstance(g, Column):
            raise PlanError(
                f"cluster mode routes on column group keys; {g!r} is a "
                "computed expression — materialize it with with_column "
                "before the window"
            )
        key_columns.append(g.name)
    if not key_columns:
        raise PlanError(
            "cluster mode needs at least one group column to hash-route "
            "on (a global aggregate has a single key and gains nothing "
            "from the exchange)"
        )
    idx = chain.index(win)
    above = chain[:idx]  # root .. node just above win
    ingest_logical = win.input

    def keyed_builder(exchange_leaf: lp.LogicalPlan) -> lp.LogicalPlan:
        rebuilt_win = lp.StreamingWindow(
            exchange_leaf,
            win.group_exprs,
            win.aggr_exprs,
            win.window_type,
            win.length_ms,
            win.slide_ms,
        )
        return _rebuild_above(above, rebuilt_win)

    return SplitQuery(
        ingest_logical=ingest_logical,
        keyed_builder=keyed_builder,
        key_columns=key_columns,
        exchange_schema=ingest_logical.schema,
    )
