"""Worker-side runtime operators: partition subsetting, the exchange
router (ingest half) and the exchange source (keyed half).

The ingest half is the UNMODIFIED single-process pipeline — SourceExec
(prefetch pump, supervised restarts, partition watermarks) plus any
stateless operators — driven by :class:`ExchangeRouter`, which splits
each batch by ``hash(key) % n_workers`` (cluster/hashing.py) and ships
the shards: self-destined rows take the zero-copy loopback, peers get
framed column buffers.  Watermarks piggyback on data frames and
broadcast as explicit frames on advance, so an edge that carries no
rows for a worker still advances its event time; barriers broadcast
in-band on every edge after the data that precedes them.

The keyed half consumes :class:`ExchangeSourceExec` — a leaf operator
yielding merged batches, authoritative ("partition"-kind) watermark
hints at the min over inbound edges, aligned checkpoint markers, and
EOS when every edge finished.
"""

from __future__ import annotations

import time
from typing import Iterator

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.physical.base import (
    EOS,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
    WatermarkHint,
    WM_ANNOUNCE,
)
from denormalized_tpu.sources.base import PartitionReader, Source
from denormalized_tpu.cluster import framing
from denormalized_tpu.cluster.hashing import bucket_rows, partitions_for


class PartitionSubsetSource(Source):
    """A view of ``inner`` restricted to this worker's static partition
    subset (``partitions_for``): reader ``i`` of the subset is global
    partition ``worker + i * n_workers`` — the one assignment rule the
    offset rescaler inverts (cluster/rescale.py)."""

    def __init__(self, inner: Source, worker: int, n_workers: int) -> None:
        self._inner = inner
        self.worker = worker
        self.n_workers = n_workers
        self.name = f"{inner.name}@w{worker}"
        all_readers = inner.partitions()
        self.n_partitions_total = len(all_readers)
        self._pids = partitions_for(
            worker, n_workers, self.n_partitions_total
        )
        self._readers = [all_readers[p] for p in self._pids]

    @property
    def schema(self):
        return self._inner.schema

    @property
    def unbounded(self) -> bool:
        return self._inner.unbounded

    def partitions(self) -> list[PartitionReader]:
        readers, self._readers = self._readers, None
        if readers is None:
            # a second scan of the same source object rebuilds fresh
            # cursors (bounded replay sources support this) — ONE inner
            # scan, then subset, never one scan per subset partition
            all_readers = self._inner.partitions()
            readers = [all_readers[p] for p in self._pids]
        return readers

    def partition_factories(self):
        inner = self._inner.partition_factories()
        if inner is None:
            return None
        return [inner[p] for p in self._pids]

    def global_partition_ids(self) -> list[int]:
        return list(self._pids)


class ExchangeRouter:
    """Drives the ingest half and routes its output into the exchange.

    Single-threaded (the worker's ingest thread); owns the outbound
    clients.  ``run()`` returns once the ingest pipeline reached EOS and
    the EOS frames are on every edge."""

    def __init__(
        self,
        ingest_root: ExecOperator,
        key_columns: list[str],
        worker_id: int,
        n_workers: int,
        clients: dict,
        server,
    ) -> None:
        from denormalized_tpu import obs

        self.root = ingest_root
        self.key_columns = key_columns
        self.worker_id = worker_id
        self.n_workers = n_workers
        self.clients = clients  # dst -> ExchangeClient (excludes self)
        self.server = server  # loopback target
        self.wm: int | None = None
        self.source_done = False
        self.rows_routed = 0
        self.wall_s = 0.0
        self._key_idx = [
            ingest_root.schema.index_of(k) for k in key_columns
        ]
        self._obs_rows = obs.counter(
            "dnz_op_rows_out_total", op="exchange_router",
            source=f"w{worker_id}",
        )

    def _broadcast(self, frame_bytes: bytes, local_item: tuple) -> None:
        self.server.local_put(local_item)
        for dst in range(self.n_workers):
            if dst == self.worker_id:
                continue
            self.clients[dst].send(frame_bytes)

    def _route_batch(self, batch: RecordBatch) -> None:
        if batch.num_rows == 0:
            return
        self._obs_rows.add(batch.num_rows)
        self.rows_routed += batch.num_rows
        if self.n_workers == 1:
            # single worker: every key is ours — skip the hash entirely
            self.server.local_put(("data", batch, self.wm))
            return
        buckets = bucket_rows(
            [batch.columns[i] for i in self._key_idx], self.n_workers
        )
        for dst in range(self.n_workers):  # dnzlint: allow(hot-loop) bounded per-WORKER sweep; the split itself is a vectorized boolean mask per destination
            mask = buckets == dst
            if not mask.any():
                continue
            sub = batch if mask.all() else batch.filter(mask)
            if dst == self.worker_id:
                self.server.local_put(("data", sub, self.wm))
            else:
                self.clients[dst].send(framing.encode_data(sub, self.wm))

    def run(self) -> None:
        t_start = time.perf_counter()
        try:
            self._run_inner()
        finally:
            self.wall_s = time.perf_counter() - t_start

    def _run_inner(self) -> None:
        for item in self.root.run():
            if isinstance(item, RecordBatch):
                self._route_batch(item)
            elif isinstance(item, WatermarkHint):
                if item.is_announcement:
                    continue  # the merger announces downstream itself
                if self.wm is None or item.ts_ms > self.wm:
                    self.wm = item.ts_ms
                    self._broadcast(
                        framing.encode_wm(self.wm), ("wm", self.wm)
                    )
            elif isinstance(item, Marker):
                self._broadcast(
                    framing.encode_barrier(item.epoch),
                    ("barrier", item.epoch),
                )
            elif isinstance(item, EndOfStream):
                break
        self.source_done = True
        self._broadcast(framing.encode_eos(), ("eos",))
        for c in self.clients.values():
            c.close()


class ExchangeSourceExec(ExecOperator):
    """Leaf operator of the keyed half: merged exchange stream in, engine
    stream items out.  Watermark hints are authoritative per-edge-merged
    minima (kind="partition"), so the keyed operator never advances from
    raw batch timestamps — exchange interleaving across senders would
    race a max-of-min watermark exactly like multi-partition replay
    does."""

    def __init__(self, schema, merger, worker_id: int) -> None:
        from denormalized_tpu import obs

        self.schema = schema
        self.merger = merger
        self.worker_id = worker_id
        self._metrics = {"rows_out": 0, "batches_out": 0}
        self.bind_obs("exchange_source")
        self._obs_rows_out = obs.counter(
            "dnz_op_rows_out_total", op="exchange_source",
            source=f"w{worker_id}",
        )

    def metrics(self):
        return dict(self._metrics)

    def _label(self):
        return f"ExchangeSourceExec(w{self.worker_id})"

    def run(self) -> Iterator[StreamItem]:
        yield WatermarkHint(WM_ANNOUNCE, kind="partition")
        it = iter(self.merger)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                break
            self._note_input_wait(time.perf_counter() - t0)
            kind = item[0]
            if kind == "data":
                batch = item[1]
                self._metrics["rows_out"] += batch.num_rows
                self._metrics["batches_out"] += 1
                self._obs_rows_out.add(batch.num_rows)
                self._note_batch(t0, batch.num_rows)
                yield batch
            elif kind == "wm":
                yield WatermarkHint(item[1], kind="partition")
            elif kind == "barrier":
                yield Marker(item[1])
        yield EOS


def replace_scan_source(
    ingest_logical, worker: int, n_workers: int
) -> PartitionSubsetSource:
    """Swap the (possibly projection-pushed) Scan's source for this
    worker's partition subset.  The plan objects are built fresh inside
    each worker process, so in-place replacement is safe — nothing else
    holds them."""
    from denormalized_tpu.common.errors import PlanError
    from denormalized_tpu.logical import plan as lp

    node = ingest_logical
    while not isinstance(node, lp.Scan):
        kids = node.children
        if len(kids) != 1:
            raise PlanError("ingest half must be a unary chain to a Scan")
        node = kids[0]
    subset = PartitionSubsetSource(node.source, worker, n_workers)
    node.source = subset
    return subset
