"""Worker-side runtime operators: partition subsetting, the exchange
router (ingest half) and the exchange source (keyed half).

The ingest half is the UNMODIFIED single-process pipeline — SourceExec
(prefetch pump, supervised restarts, partition watermarks) plus any
stateless operators — driven by :class:`ExchangeRouter`, which splits
each batch by ``hash(key) % n_workers`` (cluster/hashing.py) and ships
the shards: self-destined rows take the zero-copy loopback, peers get
framed column buffers.  Watermarks piggyback on data frames and
broadcast as explicit frames on advance, so an edge that carries no
rows for a worker still advances its event time; barriers broadcast
in-band on every edge after the data that precedes them.

The keyed half consumes :class:`ExchangeSourceExec` — a leaf operator
yielding merged batches, authoritative ("partition"-kind) watermark
hints at the min over inbound edges, aligned checkpoint markers, and
EOS when every edge finished.
"""

from __future__ import annotations

import time
from typing import Iterator

import numpy as np

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field
from denormalized_tpu.physical.base import (
    EOS,
    EndOfStream,
    ExecOperator,
    Marker,
    StreamItem,
    WatermarkHint,
    WM_ANNOUNCE,
)
from denormalized_tpu.sources.base import PartitionReader, Source
from denormalized_tpu.cluster import framing
from denormalized_tpu.cluster.hashing import bucket_rows, partitions_for

#: batch-constant provenance column stamped at the reader (every batch
#: comes from exactly one partition cursor) and dropped by the router
#: before framing/loopback — receivers ledger delivered rows per
#: (edge, global partition) against it, which is what makes a reborn
#: sender's replay exactly deduplicatable (cluster/exchange.py)
PART_COL = "__dnz_part"


class _StampedReader(PartitionReader):
    """Delegating reader that appends the global-partition provenance
    column to every batch.  Offsets, backlog and decode reporting pass
    through untouched — the stamp is invisible to checkpointing."""

    def __init__(self, inner: PartitionReader, global_pid: int) -> None:
        self._inner = inner
        self._pid = global_pid
        self._field = Field(PART_COL, DataType.INT64, nullable=False)

    def read(self, timeout_s: float | None = None):
        batch = self._inner.read(timeout_s)
        if batch is None:
            return None
        return batch.with_column(
            self._field,
            np.full(batch.num_rows, self._pid, dtype=np.int64),
        )

    def offset_snapshot(self) -> dict:
        return self._inner.offset_snapshot()

    def offset_restore(self, snap: dict) -> None:
        self._inner.offset_restore(snap)

    def decode_fallback_rows(self) -> int:
        return self._inner.decode_fallback_rows()

    def caught_up(self):
        return self._inner.caught_up()


class PartitionSubsetSource(Source):
    """A view of ``inner`` restricted to this worker's static partition
    subset (``partitions_for``): reader ``i`` of the subset is global
    partition ``worker + i * n_workers`` — the one assignment rule the
    offset rescaler inverts (cluster/rescale.py).

    With ``stamp=True`` every reader batch carries ``PART_COL`` (the
    global partition id) for the exchange's rejoin ledgers; the
    declared ``schema`` stays the inner one — the stamp is batch-level
    provenance, invisible to planning."""

    def __init__(
        self, inner: Source, worker: int, n_workers: int,
        stamp: bool = False,
    ) -> None:
        self._inner = inner
        self.worker = worker
        self.n_workers = n_workers
        self.stamp = stamp
        self.name = f"{inner.name}@w{worker}"
        all_readers = inner.partitions()
        self.n_partitions_total = len(all_readers)
        self._pids = partitions_for(
            worker, n_workers, self.n_partitions_total
        )
        self._readers = [
            self._wrap(all_readers[p], p) for p in self._pids
        ]

    def _wrap(self, reader: PartitionReader, pid: int) -> PartitionReader:
        return _StampedReader(reader, pid) if self.stamp else reader

    @property
    def schema(self):
        return self._inner.schema

    @property
    def unbounded(self) -> bool:
        return self._inner.unbounded

    def partitions(self) -> list[PartitionReader]:
        readers, self._readers = self._readers, None
        if readers is None:
            # a second scan of the same source object rebuilds fresh
            # cursors (bounded replay sources support this) — ONE inner
            # scan, then subset, never one scan per subset partition
            all_readers = self._inner.partitions()
            readers = [
                self._wrap(all_readers[p], p) for p in self._pids
            ]
        return readers

    def partition_factories(self):
        inner = self._inner.partition_factories()
        if inner is None:
            return None

        def _stamped_factory(factory, pid):
            return lambda: self._wrap(factory(), pid)

        return [
            _stamped_factory(inner[p], p) for p in self._pids
        ]

    def global_partition_ids(self) -> list[int]:
        return list(self._pids)


class ExchangeRouter:
    """Drives the ingest half and routes its output into the exchange.

    Single-threaded (the worker's ingest thread); owns the outbound
    clients.  ``run()`` returns once the ingest pipeline reached EOS and
    the EOS frames are on every edge."""

    def __init__(
        self,
        ingest_root: ExecOperator,
        key_columns: list[str],
        worker_id: int,
        n_workers: int,
        clients: dict,
        server,
    ) -> None:
        from denormalized_tpu import obs

        self.root = ingest_root
        self.key_columns = key_columns
        self.worker_id = worker_id
        self.n_workers = n_workers
        self.clients = clients  # dst -> ExchangeClient (excludes self)
        self.server = server  # loopback target
        self.wm: int | None = None
        self.source_done = False
        self.rows_routed = 0
        self.wall_s = 0.0
        self._key_idx = [
            ingest_root.schema.index_of(k) for k in key_columns
        ]
        self._obs_rows = obs.counter(
            "dnz_op_rows_out_total", op="exchange_router",
            source=f"w{worker_id}",
        )

    def _broadcast(
        self, frame_bytes: bytes, local_item: tuple,
        kind: str, epoch: int | None = None,
    ) -> None:
        self.server.local_put(local_item)
        for dst in range(self.n_workers):
            if dst == self.worker_id:
                continue
            self.clients[dst].send(frame_bytes, kind, epoch)

    def _route_batch(self, batch: RecordBatch) -> None:
        if batch.num_rows == 0:
            return
        self._obs_rows.add(batch.num_rows)
        self.rows_routed += batch.num_rows
        pid = None
        if batch.schema.has(PART_COL):
            # batch-constant provenance stamp: record it for the rejoin
            # ledgers, then drop it — it never crosses the wire and the
            # keyed half's schema doesn't know it
            pid = int(batch.column(PART_COL)[0])
            batch = batch.drop([PART_COL])
        if self.n_workers == 1:
            # single worker: every key is ours — skip the hash entirely
            self.server.local_put(("data", batch, self.wm))
            return
        buckets = bucket_rows(
            [batch.columns[i] for i in self._key_idx], self.n_workers
        )
        for dst in range(self.n_workers):  # dnzlint: allow(hot-loop) bounded per-WORKER sweep; the split itself is a vectorized boolean mask per destination
            mask = buckets == dst
            if not mask.any():
                continue
            sub = batch if mask.all() else batch.filter(mask)
            if dst == self.worker_id:
                # the loopback never skips: a reborn worker's own state
                # restored to the same epoch its ingest replays from
                self.server.local_put(("data", sub, self.wm))
                continue
            client = self.clients[dst]
            if pid is not None:
                s = client.take_skip(pid, sub.num_rows)
                if s:
                    # the receiver already holds this prefix from my
                    # previous incarnation — per-partition sequences
                    # are deterministic, so dropping the first s rows
                    # is exact, not heuristic
                    sub = sub.slice(s, sub.num_rows - s)
            if sub.num_rows:
                client.send(
                    framing.encode_data(sub, self.wm, part=pid), "data"
                )

    def run(self) -> None:
        t_start = time.perf_counter()
        try:
            self._run_inner()
        finally:
            self.wall_s = time.perf_counter() - t_start

    def _run_inner(self) -> None:
        for item in self.root.run():
            if isinstance(item, RecordBatch):
                self._route_batch(item)
            elif isinstance(item, WatermarkHint):
                if item.is_announcement:
                    continue  # the merger announces downstream itself
                if self.wm is None or item.ts_ms > self.wm:
                    self.wm = item.ts_ms
                    self._broadcast(
                        framing.encode_wm(self.wm), ("wm", self.wm), "wm"
                    )
            elif isinstance(item, Marker):
                # barriers are per-edge frames, not one shared buffer:
                # while this (reborn) worker's dedup skip is draining,
                # each peer must learn its own residual so its ledger
                # snapshot for this epoch anchors at the barrier's
                # stream position, not at the delivered frontier
                self.server.local_put(("barrier", item.epoch))
                for dst in range(self.n_workers):
                    if dst == self.worker_id:
                        continue
                    client = self.clients[dst]
                    client.send(
                        framing.encode_barrier(
                            item.epoch, skips=client.skip_residual()
                        ),
                        "barrier", item.epoch,
                    )
            elif isinstance(item, EndOfStream):
                break
        self.source_done = True
        self._broadcast(framing.encode_eos(), ("eos",), "eos")
        for c in self.clients.values():
            c.close()


class ExchangeSourceExec(ExecOperator):
    """Leaf operator of the keyed half: merged exchange stream in, engine
    stream items out.  Watermark hints are authoritative per-edge-merged
    minima (kind="partition"), so the keyed operator never advances from
    raw batch timestamps — exchange interleaving across senders would
    race a max-of-min watermark exactly like multi-partition replay
    does."""

    def __init__(self, schema, merger, worker_id: int) -> None:
        from denormalized_tpu import obs

        self.schema = schema
        self.merger = merger
        self.worker_id = worker_id
        self._metrics = {"rows_out": 0, "batches_out": 0}
        self.bind_obs("exchange_source")
        self._obs_rows_out = obs.counter(
            "dnz_op_rows_out_total", op="exchange_source",
            source=f"w{worker_id}",
        )

    def metrics(self):
        return dict(self._metrics)

    def _label(self):
        return f"ExchangeSourceExec(w{self.worker_id})"

    def run(self) -> Iterator[StreamItem]:
        yield WatermarkHint(WM_ANNOUNCE, kind="partition")
        it = iter(self.merger)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                break
            self._note_input_wait(time.perf_counter() - t0)
            kind = item[0]
            if kind == "data":
                batch = item[1]
                self._metrics["rows_out"] += batch.num_rows
                self._metrics["batches_out"] += 1
                self._obs_rows_out.add(batch.num_rows)
                self._note_batch(t0, batch.num_rows)
                yield batch
            elif kind == "wm":
                yield WatermarkHint(item[1], kind="partition")
            elif kind == "barrier":
                yield Marker(item[1])
        yield EOS


def replace_scan_source(
    ingest_logical, worker: int, n_workers: int, stamp: bool = False
) -> PartitionSubsetSource:
    """Swap the (possibly projection-pushed) Scan's source for this
    worker's partition subset.  The plan objects are built fresh inside
    each worker process, so in-place replacement is safe — nothing else
    holds them."""
    from denormalized_tpu.common.errors import PlanError
    from denormalized_tpu.common.schema import Schema
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.logical.expr import Column

    node = ingest_logical
    projects = []
    while not isinstance(node, lp.Scan):
        kids = node.children
        if len(kids) != 1:
            raise PlanError("ingest half must be a unary chain to a Scan")
        if isinstance(node, lp.Project):
            projects.append(node)
        node = kids[0]
    subset = PartitionSubsetSource(
        node.source, worker, n_workers, stamp=stamp
    )
    node.source = subset
    if stamp:
        # the provenance stamp must survive optimizer-pushed
        # projections the same way the canonical timestamp column
        # rides along implicitly (logical/plan.py Project.__init__):
        # ProjectExec rebuilds batches to its expr list, so each
        # Project in the chain passes PART_COL through by reference
        # (Column.eval is name-based against the live batch)
        field = Field(PART_COL, DataType.INT64, nullable=False)
        for proj in projects:
            if not proj.schema.has(PART_COL):
                proj.exprs.append(Column(PART_COL))
                proj.schema = Schema(list(proj.schema) + [field])
    return subset
