"""Read-side of the cluster's exactly-once output protocol — stdlib
only (no engine imports), so soak parents and external tooling can load
it standalone, same contract as obs/readers.py.

The coordinator records one **segment** per spawn in
``meta/segments.jsonl`` (also returned as ``result["segments"]``): a
FULL record names the restore epoch plus every worker slot's output
file; a PARTIAL record (single-worker recovery) carries ``"worker"``
and only that slot's new file.  Each row line carries ``ep`` — the
in-flight CLUSTER epoch at write time.  Rows a segment emitted beyond
the epoch its successor restored from are the uncommitted suffix that
successor regenerates; the reader discards them (transactional
truncate-on-restore, reader-side — the protocol tools/soak.py
established in PR 1).

The clip boundary is per (segment, slot): a full restart re-emits
EVERY slot's uncommitted suffix, so a full record bounds all earlier
output, while a partial record re-emits only the dead worker's suffix
— survivors' rows must NOT be clipped by a peer's recovery (their
windows beyond the restore epoch were emitted once and never again).
Epochs are cluster-global, so full-record clipping still works across
worker-count changes (rescale re-maps which WORKER re-emits a window,
never which EPOCH covers it); partial records never straddle a rescale
— that path is always a full restart."""

from __future__ import annotations

import json


def _read_file(path: str) -> tuple[list, bool]:
    rows = []
    done = False
    try:
        f = open(path)
    except FileNotFoundError:
        return rows, done
    with f:
        for line in f:
            try:
                o = json.loads(line)
            except ValueError:
                continue  # torn tail (SIGKILL mid-write)
            ev = o.get("event")
            if ev == "done":
                done = True
            elif ev is None:
                rows.append(o)
    return rows, done


def read_cluster(segments: list) -> dict:
    """All segments' outputs → ``{"rows": [...], "clipped": n,
    "done_files": k, "generations": g}``.  ``segments`` is the
    coordinator's ``result["segments"]`` (or the parsed
    ``meta/segments.jsonl``), in generation order."""
    recs = []  # {"restored", "worker"|None, "slots": [(slot, rows)], "emitting", "done"}
    for seg in segments:
        files = seg.get("files", [])
        worker = seg.get("worker")
        if worker is not None:
            slots = [int(worker)]
        else:
            slots = list(range(len(files)))
        slot_rows = []
        done_files = 0
        for slot, path in zip(slots, files):
            r, d = _read_file(path)
            slot_rows.append((slot, r))
            done_files += int(d)
        recs.append({
            "restored": seg.get("restored"),
            "worker": None if worker is None else int(worker),
            "slots": slot_rows,
            "emitting": any(r for _, r in slot_rows),
            "done": done_files,
        })
    kept: list = []
    clipped = 0
    done_files = 0
    for i, rec in enumerate(recs):
        done_files += rec["done"]
        for slot, rows in rec["slots"]:
            # boundary for THIS slot: the first later emitting segment
            # that re-covers it (any full restart, or this very
            # worker's own partial respawn) — None = nothing after
            # regenerates this slot's output, keep everything
            boundary = None
            for j in range(i + 1, len(recs)):
                nxt = recs[j]
                if nxt["worker"] is not None and nxt["worker"] != slot:
                    continue  # a PEER's recovery never re-emits us
                if nxt["emitting"]:
                    boundary = nxt["restored"]
                    break
            for o in rows:
                ep = o.get("ep")
                if (
                    boundary is not None
                    and ep is not None
                    and ep > (boundary or 0)
                ):
                    clipped += 1
                    continue
                kept.append(o)
    return {
        "rows": kept,
        "clipped": clipped,
        "done_files": done_files,
        "generations": len(recs),
    }
