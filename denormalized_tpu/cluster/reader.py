"""Read-side of the cluster's exactly-once output protocol — stdlib
only (no engine imports), so soak parents and external tooling can load
it standalone, same contract as obs/readers.py.

The coordinator records one **segment** per worker generation in
``meta/segments.jsonl`` (also returned as ``result["segments"]``): the
generation's restore epoch plus every worker's output file.  Each row
line carries ``ep`` — the in-flight CLUSTER epoch at write time.  A
generation's rows tagged beyond the epoch its successor restored from
are the uncommitted suffix that successor regenerates; the reader
discards them (transactional truncate-on-restore, reader-side — the
protocol tools/soak.py established in PR 1).

Epochs are cluster-global, so clipping works across worker-count
changes (rescale re-maps which WORKER re-emits a window, never which
EPOCH covers it) — the reason the clip boundary is per generation, not
per worker slot."""

from __future__ import annotations

import json


def _read_file(path: str) -> tuple[list, bool]:
    rows = []
    done = False
    try:
        f = open(path)
    except FileNotFoundError:
        return rows, done
    with f:
        for line in f:
            try:
                o = json.loads(line)
            except ValueError:
                continue  # torn tail (SIGKILL mid-write)
            ev = o.get("event")
            if ev == "done":
                done = True
            elif ev is None:
                rows.append(o)
    return rows, done


def read_cluster(segments: list) -> dict:
    """All generations' outputs → ``{"rows": [...], "clipped": n,
    "done_files": k, "generations": g}``.  ``segments`` is the
    coordinator's ``result["segments"]`` (or the parsed
    ``meta/segments.jsonl``), in generation order."""
    gens = []  # (restored_epoch|None, rows, done_files)
    for seg in segments:
        rows: list = []
        done_files = 0
        for path in seg.get("files", []):
            r, d = _read_file(path)
            rows.extend(r)
            done_files += int(d)
        gens.append((seg.get("restored"), rows, done_files))
    kept: list = []
    clipped = 0
    done_files = 0
    for i, (_restored, rows, dn) in enumerate(gens):
        done_files += dn
        boundary = None  # None = final emitting generation: keep all
        for j in range(i + 1, len(gens)):
            if gens[j][1]:
                boundary = gens[j][0]
                break
        for o in rows:
            ep = o.get("ep")
            if boundary is not None and ep is not None and ep > (
                boundary or 0
            ):
                clipped += 1
                continue
            kept.append(o)
    return {
        "rows": kept,
        "clipped": clipped,
        "done_files": done_files,
        "generations": len(gens),
    }
