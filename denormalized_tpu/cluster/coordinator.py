"""Cluster coordinator: spawn workers, align barriers, commit epochs,
supervise, rescale on restore.

The coordinator is a small control plane — it never touches row data.
Its one durable artifact is ``meta/commits.jsonl``: an epoch appears
there only after EVERY worker acked it (offsets + keyed snapshots
durable in each worker's own store), which makes the last line the
cluster-consistent recovery point.  Worker-local commit records are
proposals; restore pins every worker to the cluster-committed epoch
(cluster/worker.py PinnedCheckpointCoordinator).

Supervision reuses the restart-budget pattern of the prefetch
supervisor one level up: any worker death, error report, or liveness
stall kills the whole incarnation and respawns it from the last
cluster-committed epoch, at most ``spec.max_restarts`` times.  Recovery
is full-cluster by design — a single worker cannot restart alone
because its exchange peers hold post-barrier rows from it (the aligned
cut is cluster-wide).  Exactly-once OUTPUT across those restarts is the
reader-side clip protocol (tools/soak.py read_emissions), applied per
worker slot.

On restore with a DIFFERENT ``n_workers`` the coordinator first runs
cluster/rescale.py, which re-buckets every worker's checkpointed keyed
and spilled state plus source offsets under the new hash map into a new
store version, then starts the new workers pinned at the same epoch.
"""

from __future__ import annotations

import json
import os
import queue
import signal
import socket
import subprocess
import sys
import threading
import time

from denormalized_tpu.common.errors import StateError
from denormalized_tpu.cluster.spec import ClusterSpec


def _fsync_append(path: str, line: str) -> None:
    with open(path, "a") as f:
        f.write(line + "\n")
        f.flush()
        os.fsync(f.fileno())


class _WorkerConn:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.wlock = threading.Lock()

    def send(self, obj: dict) -> bool:
        try:
            with self.wlock:
                self.sock.sendall((json.dumps(obj) + "\n").encode())
            return True
        except OSError:
            return False


class Coordinator:
    def __init__(
        self,
        spec: ClusterSpec,
        *,
        kill_after_commits: int | None = None,
        kill_worker_after_s: float | None = None,
        kill_worker_id: int = 0,
    ) -> None:
        self.spec = spec
        self.kill_after_commits = kill_after_commits
        self.kill_worker_after_s = kill_worker_after_s
        self.kill_worker_id = kill_worker_id
        self.workdir = spec.workdir
        for d in ("sock", "out", "obs", "meta", "state"):
            os.makedirs(os.path.join(self.workdir, d), exist_ok=True)
        self._spec_path = os.path.join(self.workdir, "meta", "spec.json")
        with open(self._spec_path, "w") as f:
            f.write(spec.to_json())
        self._manifest_path = os.path.join(
            self.workdir, "meta", "manifest.json"
        )
        self._commits_path = os.path.join(
            self.workdir, "meta", "commits.jsonl"
        )
        self._segments_path = os.path.join(
            self.workdir, "meta", "segments.jsonl"
        )
        self._procs: dict[int, subprocess.Popen] = {}
        self._conns: dict[int, _WorkerConn] = {}
        self._events: queue.Queue = queue.Queue()
        self._listener: socket.socket | None = None
        self.restarts = 0
        self.crash_log: list[str] = []  # why each incarnation died
        #: generation token: bumped before each spawn; control events
        #: are tagged with the token current when their connection was
        #: accepted, so a killed generation's buffered acks/eos can
        #: never be attributed to the respawned workers (epoch numbers
        #: REPEAT across incarnations — a stale ack for epoch E would
        #: otherwise cluster-commit E without the new workers' state)
        self._gen_token = 0
        self.out_files: dict[int, list[str]] = {
            i: [] for i in range(spec.n_workers)
        }

    # -- durable meta -----------------------------------------------------
    def read_manifest(self) -> dict | None:
        try:
            with open(self._manifest_path) as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return None

    def _write_manifest(self, manifest: dict) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path)

    def committed_epochs(self) -> list[dict]:
        out = []
        try:
            f = open(self._commits_path)
        except FileNotFoundError:
            return out
        with f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn tail from a killed coordinator
        return out

    def last_committed(self) -> int | None:
        commits = self.committed_epochs()
        return commits[-1]["epoch"] if commits else None

    def segments(self) -> list[dict]:
        """Durable incarnation history: one record per worker
        generation, each naming its restore epoch and output files —
        what the exactly-once reader (cluster/reader.py) clips across.
        Survives coordinator restarts AND worker-count changes (output
        slots re-map under rescale; epochs are cluster-global)."""
        out = []
        try:
            f = open(self._segments_path)
        except FileNotFoundError:
            return out
        with f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
        return out

    def store_dir(self, version: int, worker: int) -> str:
        return os.path.join(
            self.workdir, "state", f"v{version}", f"worker_{worker}"
        )

    # -- lifecycle --------------------------------------------------------
    def _checkpointing(self) -> bool:
        return self.spec.checkpoint_interval_s is not None

    def _start_control_server(self) -> None:
        from denormalized_tpu.cluster.worker import ctrl_sock_path

        path = ctrl_sock_path(self.workdir)
        if os.path.exists(path):
            os.unlink(path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(path)
        self._listener.listen(self.spec.n_workers * 2)
        threading.Thread(
            target=self._accept_loop, name="cluster-accept", daemon=True
        ).start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._conn_loop, args=(conn, self._gen_token),
                name="cluster-conn", daemon=True,
            ).start()

    def _conn_loop(self, conn: socket.socket, token: int) -> None:
        f = conn.makefile("r", encoding="utf-8")
        wid = None
        try:
            hello = json.loads(f.readline())
            if hello.get("ev") != "hello":
                conn.close()
                return
            wid = int(hello["worker"])
            self._conns[wid] = _WorkerConn(conn)
            self._events.put(("hello", wid, hello, token))
            for line in f:
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                self._events.put(("msg", wid, msg, token))
        except (OSError, ValueError):
            pass
        finally:
            if wid is not None:
                self._events.put(("conn_lost", wid, {}, token))
            try:
                conn.close()
            except OSError:
                pass

    def _spawn_workers(
        self, seq: int, store_version: int, restore_epoch: str
    ) -> None:
        # stale exchange sockets from a killed incarnation must not
        # accept this incarnation's connects
        sockdir = os.path.join(self.workdir, "sock")
        for name in os.listdir(sockdir):
            if name.startswith("exch_"):
                os.unlink(os.path.join(sockdir, name))
        # global generation number: unique across coordinator restarts
        # (a resumed coordinator must never append into a previous
        # incarnation's files, and the reader needs total order)
        gen = len(self.segments())
        spec_path = self._spec_path
        if gen > 0 and self.spec.fault_plan and self.spec.fault_plan_once:
            # respawned incarnations run fault-free (see ClusterSpec)
            spec_path = os.path.join(
                self.workdir, "meta", "spec_nofault.json"
            )
            if not os.path.exists(spec_path):
                import dataclasses

                clean = dataclasses.replace(self.spec, fault_plan=None)
                with open(spec_path, "w") as f:
                    f.write(clean.to_json())
        outs = []
        for i in range(self.spec.n_workers):
            os.makedirs(
                self.store_dir(store_version, i), exist_ok=True
            )
            outs.append(os.path.join(
                self.workdir, "out", f"g{gen:04d}_w{i}.jsonl"
            ))
        _fsync_append(self._segments_path, json.dumps({
            "gen": gen,
            "n_workers": self.spec.n_workers,
            "restored": (
                None if restore_epoch in ("off", "none")
                else int(restore_epoch)
            ),
            "files": outs,
        }))
        for i in range(self.spec.n_workers):
            store = self.store_dir(store_version, i)
            out = outs[i]
            self.out_files[i].append(out)
            env = dict(os.environ)
            # workers are host-side engine processes; an unset platform
            # must not auto-grab an accelerator per worker (the device
            # half stays per-worker via EngineConfig mesh settings)
            env.setdefault("JAX_PLATFORMS", "cpu")
            self._procs[i] = subprocess.Popen(
                [
                    sys.executable, "-m", "denormalized_tpu.cluster.worker",
                    "--spec", spec_path,
                    "--worker", str(i),
                    "--store", store,
                    "--restore-epoch", restore_epoch,
                    "--seq", str(seq),
                    "--out", out,
                ],
                cwd=os.path.dirname(os.path.dirname(
                    os.path.dirname(os.path.abspath(__file__))
                )),
                env=env,
            )

    def _kill_all(self) -> None:
        for p in self._procs.values():
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        for p in self._procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        self._procs.clear()
        self._conns.clear()

    def _broadcast(self, obj: dict) -> None:
        for wc in list(self._conns.values()):
            wc.send(obj)

    # -- main loop --------------------------------------------------------
    def run(self) -> dict:
        """Run the cluster to completion (or to the configured kill),
        supervising restarts.  Returns the run summary."""
        t_start = time.perf_counter()
        self._start_control_server()
        try:
            return self._run_supervised(t_start)
        finally:
            self._kill_all()
            if self._listener is not None:
                try:
                    self._listener.close()
                except OSError:
                    pass

    def _prepare_incarnation(self) -> tuple[int, str]:
        """→ (store_version, restore_epoch_arg), rescaling if the
        manifest's worker count differs from the spec's."""
        if not self._checkpointing():
            return 0, "off"
        manifest = self.read_manifest()
        committed = self.last_committed()
        if manifest is None or committed is None:
            return (manifest or {}).get("store_version", 0), "none"
        if manifest["n_workers"] != self.spec.n_workers:
            from denormalized_tpu.cluster.rescale import rescale_cluster

            new_version = manifest["store_version"] + 1
            rescale_cluster(
                self, manifest, committed, self.spec.n_workers, new_version
            )
            manifest["n_workers"] = self.spec.n_workers
            manifest["store_version"] = new_version
            self._write_manifest(manifest)
        return self.read_manifest()["store_version"], str(committed)

    def _run_supervised(self, t_start: float) -> dict:
        seq = 0
        killed_workers = 0
        exchange_faults = 0
        while True:
            store_version, restore_epoch = self._prepare_incarnation()
            status, detail = self._run_incarnation(
                seq, store_version, restore_epoch,
                already_killed=killed_workers,
            )
            seq += 1
            if status == "done":
                commits = self.committed_epochs()
                rows = detail.get("rows", {})
                meta = detail.get("meta", {})
                return {
                    "status": "done",
                    "rows_total": sum(rows.values()),
                    "rows_per_worker": rows,
                    "rows_in_total": sum(
                        int(m.get("rows_in", 0)) for m in meta.values()
                    ),
                    "ingest_wall_s_max": max(
                        [float(m.get("ingest_wall_s", 0.0))
                         for m in meta.values()] or [0.0]
                    ),
                    "worker_wall_s_max": max(
                        [float(m.get("worker_wall_s", 0.0))
                         for m in meta.values()] or [0.0]
                    ),
                    "commits": [c["epoch"] for c in commits],
                    "restarts": self.restarts,
                    "killed_workers": detail.get("killed_workers", 0),
                    "out_files": {
                        str(k): v for k, v in self.out_files.items()
                    },
                    "segments": self.segments(),
                    "crashes": list(self.crash_log),
                    "wall_s": round(time.perf_counter() - t_start, 3),
                }
            if status == "killed":
                return {
                    "status": "killed",
                    "commits": [
                        c["epoch"] for c in self.committed_epochs()
                    ],
                    "restarts": self.restarts,
                    "out_files": {
                        str(k): v for k, v in self.out_files.items()
                    },
                    "segments": self.segments(),
                    "wall_s": round(time.perf_counter() - t_start, 3),
                }
            # crash / wedge: full-cluster restart from the last commit
            self.crash_log.append(str(detail.get("why")))
            killed_workers += detail.get("killed_workers", 0)
            self.restarts += 1
            if self.restarts > self.spec.max_restarts:
                raise StateError(
                    f"cluster exceeded restart budget "
                    f"({self.spec.max_restarts}): {detail.get('why')}"
                )

    def _run_incarnation(
        self, seq: int, store_version: int, restore_epoch: str,
        already_killed: int = 0,
    ) -> tuple[str, dict]:
        spec = self.spec
        n = spec.n_workers
        # new generation: bump the token FIRST (conn threads capture it
        # at accept) and drop anything a killed generation left queued
        self._gen_token += 1
        while True:
            try:
                self._events.get_nowait()
            except queue.Empty:
                break
        self._spawn_workers(seq, store_version, restore_epoch)
        ready: dict[int, dict] = {}
        eos_rows: dict[int, int] = {}
        eos_meta: dict[int, dict] = {}
        acked: set[int] = set()
        inflight_epoch: int | None = None
        next_barrier_at: float | None = None
        committed = self.last_committed() or 0
        kill_at = (
            time.monotonic() + self.kill_worker_after_s
            if self.kill_worker_after_s is not None and already_killed == 0
            else None
        )
        killed_workers = 0
        last_liveness = time.monotonic()

        def fail(why: str) -> tuple[str, dict]:
            self._kill_all()
            return "crashed", {
                "why": why, "killed_workers": killed_workers,
            }

        while True:
            # worker process death?
            for wid, p in list(self._procs.items()):
                rc = p.poll()
                if rc is not None and rc != 0:
                    return fail(f"worker {wid} exited rc={rc}")
                if rc == 0 and wid not in eos_rows:
                    return fail(f"worker {wid} exited before EOS")
            if kill_at is not None and time.monotonic() >= kill_at:
                # chaos: SIGKILL one worker mid-stream
                p = self._procs.get(self.kill_worker_id)
                if p is not None and p.poll() is None:
                    os.kill(p.pid, signal.SIGKILL)
                    killed_workers += 1
                kill_at = None
                continue
            if (
                time.monotonic() - last_liveness
                > spec.liveness_timeout_s
            ):
                return fail("liveness timeout (no worker progress)")
            # barrier cadence: serial (commit e before issuing e+1)
            if (
                self._checkpointing()
                and len(ready) == n
                and inflight_epoch is None
                and next_barrier_at is not None
                and time.monotonic() >= next_barrier_at
                and len(eos_rows) < n
            ):
                inflight_epoch = committed + 1
                acked = set()
                self._broadcast(
                    {"cmd": "barrier", "epoch": inflight_epoch}
                )
            try:
                kind, wid, msg, token = self._events.get(timeout=0.05)
            except queue.Empty:
                continue
            if token != self._gen_token:
                continue  # a dead generation's buffered event
            last_liveness = time.monotonic()
            if kind == "hello":
                continue
            if kind == "conn_lost":
                # the process-death poll above decides whether this is a
                # crash (nonzero exit) or a clean shutdown
                continue
            ev = msg.get("ev")
            if ev == "ready":
                ready[wid] = msg
                if len(ready) == n:
                    if self.read_manifest() is None:
                        self._write_manifest({
                            "n_workers": n,
                            "store_version": store_version,
                            "n_partitions": msg.get("n_partitions"),
                            "state_keys": msg.get("state_keys"),
                            "key_columns": msg.get("key_columns"),
                            "key_dtypes": msg.get("key_dtypes"),
                        })
                    if self._checkpointing():
                        next_barrier_at = (
                            time.monotonic() + spec.checkpoint_interval_s
                        )
            elif ev == "ack":
                if int(msg["epoch"]) == inflight_epoch:
                    acked.add(wid)
                    if len(acked) == n:
                        committed = inflight_epoch
                        _fsync_append(self._commits_path, json.dumps({
                            "epoch": committed,
                            "n_workers": n,
                            "store_version": store_version,
                            "t": round(time.time(), 3),
                        }))
                        inflight_epoch = None
                        next_barrier_at = (
                            time.monotonic() + spec.checkpoint_interval_s
                        )
                        if (
                            self.kill_after_commits is not None
                            and len(self.committed_epochs())
                            >= self.kill_after_commits
                        ):
                            self._kill_all()
                            return "killed", {}
                        if len(eos_rows) == n:
                            # every worker reached EOS while this epoch
                            # was aligning — finish now that it committed
                            self._broadcast({"cmd": "stop"})
                            for p in self._procs.values():
                                try:
                                    p.wait(timeout=30)
                                except subprocess.TimeoutExpired:
                                    p.kill()
                            return "done", {
                                "rows": eos_rows,
                                "meta": eos_meta,
                                "killed_workers": (
                                    killed_workers + already_killed
                                ),
                            }
            elif ev == "eos":
                eos_rows[wid] = int(msg.get("rows", 0))
                eos_meta[wid] = msg
                if len(eos_rows) == n and inflight_epoch is None:
                    self._broadcast({"cmd": "stop"})
                    deadline = time.monotonic() + 30
                    for p in self._procs.values():
                        try:
                            p.wait(
                                timeout=max(0.1, deadline - time.monotonic())
                            )
                        except subprocess.TimeoutExpired:
                            p.kill()
                    return "done", {
                        "rows": eos_rows,
                        "meta": eos_meta,
                        "killed_workers": killed_workers + already_killed,
                    }
            elif ev == "error":
                return fail(f"worker {wid}: {msg.get('msg')}")


def run_cluster(spec: ClusterSpec, **kw) -> dict:
    """Convenience wrapper: build a coordinator, run, return summary."""
    return Coordinator(spec, **kw).run()
