"""Cluster coordinator: spawn workers, align barriers, commit epochs,
supervise, rescale on restore.

The coordinator is a small control plane — it never touches row data.
Its one durable artifact is ``meta/commits.jsonl``: an epoch appears
there only after EVERY worker acked it (offsets + keyed snapshots
durable in each worker's own store), which makes the last line the
cluster-consistent recovery point.  Worker-local commit records are
proposals; restore pins every worker to the cluster-committed epoch
(cluster/worker.py PinnedCheckpointCoordinator).

Supervision is a two-tier restart state machine
(docs/cluster.md#failure-matrix):

- **Partial recovery** (the default when checkpointing is on and at
  least one epoch cluster-committed): a single dead worker — SIGKILL,
  nonzero exit, error report, or a per-worker liveness stall while its
  peers keep streaming — is respawned ALONE, pinned to the last
  cluster-committed epoch with a bumped per-worker generation, while
  survivors never stop: their exchange senders buffer-or-reconnect and
  the rejoin handshake (cluster/exchange.py) dedupes the replay
  exactly.  Any barrier in flight at death time is ABORTED (its epoch
  number is never reused within the incarnation) because the respawn
  restores strictly below it.
- **Full-cluster restart** — the documented fallback: partial recovery
  ineligible (no commits yet / checkpointing off / ``partial_recovery``
  false), a worker-reported error tagged ``fallback: "cluster"``
  (replay-buffer gap, unstamped ledgers), a rejoin over
  ``rejoin_timeout_s``, or an exhausted per-worker budget.

Both tiers spend RATE-based budgets, the prefetch supervisor's
streak+refund pattern one level up: every restart opens a streak and a
crash-free ``restart_heal_s`` interval refunds it, so a days-long
stream with occasional healed deaths never converges to a guaranteed
kill while a crash-storm exhausts its budget promptly.  Exactly-once
OUTPUT across restarts of either tier is the reader-side clip protocol
(cluster/reader.py), applied per worker slot.

On restore with a DIFFERENT ``n_workers`` the coordinator first runs
cluster/rescale.py, which re-buckets every worker's checkpointed keyed
and spilled state plus source offsets under the new hash map into a new
store version, then starts the new workers pinned at the same epoch.
"""

from __future__ import annotations

import dataclasses
import json
import os
import queue
import signal
import socket
import subprocess
import sys
import threading
import time

from denormalized_tpu.common.errors import StateError
from denormalized_tpu.cluster.hashing import partitions_for
from denormalized_tpu.cluster.spec import ClusterSpec

#: grace between observing a worker process death and acting on it:
#: a worker that dies AFTER reporting an error (possibly tagged
#: ``fallback: "cluster"``) must be attributed by its report, not by
#: its exit code — the report decides partial vs full recovery
_DEATH_GRACE_S = 0.5


def _fsync_append(path: str, line: str) -> None:
    with open(path, "a") as f:
        f.write(line + "\n")
        f.flush()
        os.fsync(f.fileno())


class _RestartBudget:
    """Shared token pool (the prefetch supervisor's budget, one level
    up): ``take`` spends one token, ``refund`` returns healed streaks,
    capped at the initial allowance."""

    def __init__(self, cap: int) -> None:
        self._cap = max(0, int(cap))
        self._n = self._cap
        self._lock = threading.Lock()

    def take(self) -> bool:
        with self._lock:
            if self._n <= 0:
                return False
            self._n -= 1
            return True

    def refund(self, n: int = 1) -> None:
        with self._lock:
            self._n = min(self._cap, self._n + n)

    def remaining(self) -> int:
        with self._lock:
            return self._n


class _WorkerStreak:
    """One worker's restart streak against the cluster-global pool.

    ``take()`` first heals: a crash-free ``heal_s`` interval since the
    last restart refunds the whole streak to the pool.  Then it admits
    the restart only if the streak stays under the per-worker cap AND
    the pool still has a token — so one crash-looping worker cannot
    starve its peers' budgets, and spaced healed deaths never
    accumulate."""

    def __init__(self, cap: int, heal_s: float, pool: _RestartBudget) -> None:
        self.cap = int(cap)
        self.heal_s = float(heal_s)
        self.pool = pool
        self.streak = 0
        self.last = 0.0

    def take(self) -> bool:
        now = time.monotonic()
        if self.streak and now - self.last >= self.heal_s:
            self.pool.refund(self.streak)
            self.streak = 0
        if self.streak >= self.cap or not self.pool.take():
            return False
        self.streak += 1
        self.last = now
        return True


class _WorkerConn:
    def __init__(self, sock: socket.socket) -> None:
        self.sock = sock
        self.wlock = threading.Lock()

    def send(self, obj: dict) -> bool:
        try:
            with self.wlock:
                self.sock.sendall((json.dumps(obj) + "\n").encode())
            return True
        except OSError:
            return False


class Coordinator:
    def __init__(
        self,
        spec: ClusterSpec,
        *,
        kill_after_commits: int | None = None,
        kill_worker_after_s: float | None = None,
        kill_worker_id: int = 0,
        kill_plan: list | None = None,
    ) -> None:
        self.spec = spec
        self.kill_after_commits = kill_after_commits
        self.kill_worker_after_s = kill_worker_after_s
        self.kill_worker_id = kill_worker_id
        #: scripted chaos for recovery interleavings (tests): ordered
        #: entries fired one at a time — ``{"worker": w}`` plus either
        #: ``"after_s"`` (seconds into the incarnation) or ``"when"``:
        #: "inflight" (a barrier is aligning), "recovering" (some
        #: worker — optionally ``"of"`` — is mid-rejoin), or
        #: "recovered" with ``"of"`` (that worker finished a rejoin);
        #: optional ``"delay_s"`` after the condition first holds and
        #: ``"min_commits"`` (hold fire until the committed epoch
        #: reaches this — partial recovery needs a cut to exist)
        self.kill_plan = [dict(e) for e in (kill_plan or [])]
        self._kp_idx = 0
        self.workdir = spec.workdir
        for d in ("sock", "out", "obs", "meta", "state"):
            os.makedirs(os.path.join(self.workdir, d), exist_ok=True)
        self._spec_path = os.path.join(self.workdir, "meta", "spec.json")
        with open(self._spec_path, "w") as f:
            f.write(spec.to_json())
        self._manifest_path = os.path.join(
            self.workdir, "meta", "manifest.json"
        )
        self._commits_path = os.path.join(
            self.workdir, "meta", "commits.jsonl"
        )
        self._segments_path = os.path.join(
            self.workdir, "meta", "segments.jsonl"
        )
        self._cluster_state_path = os.path.join(
            self.workdir, "meta", "cluster_state.json"
        )
        self._procs: dict[int, subprocess.Popen] = {}
        self._conns: dict[int, _WorkerConn] = {}
        self._events: queue.Queue = queue.Queue()
        self._listener: socket.socket | None = None
        self.restarts = 0  # lifetime FULL-cluster restarts (reporting)
        self.worker_restarts = 0  # lifetime single-worker respawns
        self.recoveries: list[dict] = []  # {"worker", "ms"} per rejoin
        self.aborted_epochs: list[int] = []
        self.crash_log: list[str] = []  # why each (re)start happened
        #: generation token: bumped before each spawn; control events
        #: are tagged with the token current when their connection was
        #: accepted, so a killed generation's buffered acks/eos can
        #: never be attributed to the respawned workers (epoch numbers
        #: REPEAT across incarnations — a stale ack for epoch E would
        #: otherwise cluster-commit E without the new workers' state)
        self._gen_token = 0
        #: per-worker incarnation numbers within the current cluster
        #: generation: 0 at every full spawn, bumped per partial
        #: respawn — the second tag on control events (a respawned
        #: worker's peers still hold the SAME cluster token)
        self._wgen: dict[int, int] = {
            i: 0 for i in range(spec.n_workers)
        }
        # rate budgets (see module docstring): partial pool is shared
        # cluster-wide; the per-worker streak caps any one worker
        self._partial_pool = _RestartBudget(
            max(1, spec.worker_max_restarts) * spec.n_workers
        )
        self._wstreaks: dict[int, _WorkerStreak] = {
            i: _WorkerStreak(
                spec.worker_max_restarts, spec.restart_heal_s,
                self._partial_pool,
            )
            for i in range(spec.n_workers)
        }
        self._full_streak = 0
        self._full_last = 0.0
        self.out_files: dict[int, list[str]] = {
            i: [] for i in range(spec.n_workers)
        }
        from denormalized_tpu import obs

        self._obs_recovery = obs.histogram("dnz_cluster_recovery_ms")
        self._obs_wrestarts: dict[int, object] = {}

    def _obs_wrestart(self, wid: int):
        c = self._obs_wrestarts.get(wid)
        if c is None:
            from denormalized_tpu import obs

            c = obs.counter(
                "dnz_cluster_worker_restarts_total", worker=str(wid)
            )
            self._obs_wrestarts[wid] = c
        return c

    # -- durable meta -----------------------------------------------------
    def read_manifest(self) -> dict | None:
        try:
            with open(self._manifest_path) as f:
                return json.load(f)
        except (FileNotFoundError, ValueError):
            return None

    def _write_manifest(self, manifest: dict) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(manifest, f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path)

    def committed_epochs(self) -> list[dict]:
        out = []
        try:
            f = open(self._commits_path)
        except FileNotFoundError:
            return out
        with f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue  # torn tail from a killed coordinator
        return out

    def last_committed(self) -> int | None:
        commits = self.committed_epochs()
        return commits[-1]["epoch"] if commits else None

    def segments(self) -> list[dict]:
        """Durable incarnation history: one record per spawn — full
        records carry one file per worker slot, partial records carry
        ``"worker"`` and that worker's single file — each naming its
        restore epoch: what the exactly-once reader (cluster/reader.py)
        clips across, per slot.  Survives coordinator restarts AND
        worker-count changes (output slots re-map under rescale; epochs
        are cluster-global)."""
        out = []
        try:
            f = open(self._segments_path)
        except FileNotFoundError:
            return out
        with f:
            for line in f:
                try:
                    out.append(json.loads(line))
                except ValueError:
                    continue
        return out

    def store_dir(self, version: int, worker: int) -> str:
        return os.path.join(
            self.workdir, "state", f"v{version}", f"worker_{worker}"
        )

    # -- lifecycle --------------------------------------------------------
    def _checkpointing(self) -> bool:
        return self.spec.checkpoint_interval_s is not None

    def _start_control_server(self) -> None:
        from denormalized_tpu.cluster.worker import ctrl_sock_path

        path = ctrl_sock_path(self.workdir)
        if os.path.exists(path):
            os.unlink(path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(path)
        self._listener.listen(self.spec.n_workers * 2)
        threading.Thread(
            target=self._accept_loop, name="cluster-accept", daemon=True
        ).start()

    def _accept_loop(self) -> None:
        while True:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            threading.Thread(
                target=self._conn_loop, args=(conn, self._gen_token),
                name="cluster-conn", daemon=True,
            ).start()

    def _conn_loop(self, conn: socket.socket, token: int) -> None:
        f = conn.makefile("r", encoding="utf-8")
        wid = None
        wtok = 0
        try:
            hello = json.loads(f.readline())
            if hello.get("ev") != "hello":
                conn.close()
                return
            wid = int(hello["worker"])
            # second staleness tag: this worker's incarnation number at
            # connect time — a partially-respawned worker bumps it, so
            # its dead predecessor's buffered events can't leak in
            wtok = self._wgen.get(wid, 0)
            self._conns[wid] = _WorkerConn(conn)
            self._events.put(("hello", wid, hello, token, wtok))
            for line in f:
                try:
                    msg = json.loads(line)
                except ValueError:
                    continue
                self._events.put(("msg", wid, msg, token, wtok))
        except (OSError, ValueError):
            pass
        finally:
            if wid is not None:
                self._events.put(("conn_lost", wid, {}, token, wtok))
            try:
                conn.close()
            except OSError:
                pass

    def _spec_path_for(self, gen: int) -> str:
        """Spec file for spawn generation ``gen``: respawned
        incarnations run fault-free under ``fault_plan_once`` (see
        ClusterSpec) — partial respawns count, their generation index
        is global."""
        if gen > 0 and self.spec.fault_plan and self.spec.fault_plan_once:
            path = os.path.join(
                self.workdir, "meta", "spec_nofault.json"
            )
            if not os.path.exists(path):
                clean = dataclasses.replace(self.spec, fault_plan=None)
                with open(path, "w") as f:
                    f.write(clean.to_json())
            return path
        return self._spec_path

    def _worker_argv(
        self, spec_path: str, wid: int, store: str,
        restore_epoch: str, seq: int, out: str, abort_floor: int = 0,
    ) -> list[str]:
        return [
            sys.executable, "-m", "denormalized_tpu.cluster.worker",
            "--spec", spec_path,
            "--worker", str(wid),
            "--store", store,
            "--restore-epoch", restore_epoch,
            "--seq", str(seq),
            "--out", out,
            "--gen", str(self._wgen.get(wid, 0)),
            "--abort-floor", str(abort_floor),
        ]

    def _popen_worker(self, argv: list[str]) -> subprocess.Popen:
        env = dict(os.environ)
        # workers are host-side engine processes; an unset platform
        # must not auto-grab an accelerator per worker (the device
        # half stays per-worker via EngineConfig mesh settings)
        env.setdefault("JAX_PLATFORMS", "cpu")
        return subprocess.Popen(
            argv,
            cwd=os.path.dirname(os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            )),
            env=env,
        )

    def _spawn_workers(
        self, seq: int, store_version: int, restore_epoch: str
    ) -> None:
        # stale exchange sockets from a killed incarnation must not
        # accept this incarnation's connects
        sockdir = os.path.join(self.workdir, "sock")
        for name in os.listdir(sockdir):
            if name.startswith("exch_"):
                os.unlink(os.path.join(sockdir, name))
        # a full spawn resets every worker's incarnation number — the
        # cluster token (bumped by the caller) already fences the old
        # generation's events
        self._wgen = {i: 0 for i in range(self.spec.n_workers)}
        # global generation number: unique across coordinator restarts
        # (a resumed coordinator must never append into a previous
        # incarnation's files, and the reader needs total order)
        gen = len(self.segments())
        spec_path = self._spec_path_for(gen)
        outs = []
        for i in range(self.spec.n_workers):
            os.makedirs(
                self.store_dir(store_version, i), exist_ok=True
            )
            outs.append(os.path.join(
                self.workdir, "out", f"g{gen:04d}_w{i}.jsonl"
            ))
        _fsync_append(self._segments_path, json.dumps({
            "gen": gen,
            "n_workers": self.spec.n_workers,
            "restored": (
                None if restore_epoch in ("off", "none")
                else int(restore_epoch)
            ),
            "files": outs,
        }))
        for i in range(self.spec.n_workers):
            store = self.store_dir(store_version, i)
            out = outs[i]
            self.out_files[i].append(out)
            self._procs[i] = self._popen_worker(self._worker_argv(
                spec_path, i, store, restore_epoch, seq, out
            ))

    def _spawn_one(
        self, wid: int, seq: int, store_version: int,
        committed: int, abort_floor: int,
    ) -> None:
        """Respawn ONE worker pinned to the last cluster-committed
        epoch (partial recovery); its peers keep running.  Appends a
        partial segment record so the reader clips exactly this slot's
        replayed suffix."""
        gen = len(self.segments())
        out = os.path.join(
            self.workdir, "out", f"g{gen:04d}_w{wid}.jsonl"
        )
        self.out_files[wid].append(out)
        _fsync_append(self._segments_path, json.dumps({
            "gen": gen,
            "n_workers": self.spec.n_workers,
            "worker": wid,
            "restored": committed,
            "files": [out],
            "partial": True,
        }))
        store = self.store_dir(store_version, wid)
        os.makedirs(store, exist_ok=True)
        self._procs[wid] = self._popen_worker(self._worker_argv(
            self._spec_path_for(gen), wid, store, str(committed),
            seq, out, abort_floor=abort_floor,
        ))

    def _kill_all(self) -> None:
        for p in self._procs.values():
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        for p in self._procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        self._procs.clear()
        self._conns.clear()

    def _broadcast(self, obj: dict) -> None:
        for wc in list(self._conns.values()):
            wc.send(obj)

    # -- main loop --------------------------------------------------------
    def run(self) -> dict:
        """Run the cluster to completion (or to the configured kill),
        supervising restarts.  Returns the run summary."""
        t_start = time.perf_counter()
        self._start_control_server()
        try:
            return self._run_supervised(t_start)
        finally:
            self._kill_all()
            if self._listener is not None:
                try:
                    self._listener.close()
                except OSError:
                    pass

    def _prepare_incarnation(self) -> tuple[int, str]:
        """→ (store_version, restore_epoch_arg), rescaling if the
        manifest's worker count differs from the spec's."""
        if not self._checkpointing():
            return 0, "off"
        manifest = self.read_manifest()
        committed = self.last_committed()
        if manifest is None or committed is None:
            return (manifest or {}).get("store_version", 0), "none"
        if manifest["n_workers"] != self.spec.n_workers:
            from denormalized_tpu.cluster.rescale import rescale_cluster

            new_version = manifest["store_version"] + 1
            rescale_cluster(
                self, manifest, committed, self.spec.n_workers, new_version
            )
            manifest["n_workers"] = self.spec.n_workers
            manifest["store_version"] = new_version
            self._write_manifest(manifest)
        return self.read_manifest()["store_version"], str(committed)

    def _run_supervised(self, t_start: float) -> dict:
        seq = 0
        killed_workers = 0
        while True:
            store_version, restore_epoch = self._prepare_incarnation()
            status, detail = self._run_incarnation(
                seq, store_version, restore_epoch,
                already_killed=killed_workers,
            )
            seq += 1
            if status == "done":
                commits = self.committed_epochs()
                rows = detail.get("rows", {})
                meta = detail.get("meta", {})
                return {
                    "status": "done",
                    "rows_total": sum(rows.values()),
                    "rows_per_worker": rows,
                    "rows_in_total": sum(
                        int(m.get("rows_in", 0)) for m in meta.values()
                    ),
                    "ingest_wall_s_max": max(
                        [float(m.get("ingest_wall_s", 0.0))
                         for m in meta.values()] or [0.0]
                    ),
                    "worker_wall_s_max": max(
                        [float(m.get("worker_wall_s", 0.0))
                         for m in meta.values()] or [0.0]
                    ),
                    "commits": [c["epoch"] for c in commits],
                    "restarts": self.restarts,
                    "worker_restarts": self.worker_restarts,
                    "recoveries": list(self.recoveries),
                    "aborted_epochs": list(self.aborted_epochs),
                    "killed_workers": detail.get("killed_workers", 0),
                    "out_files": {
                        str(k): v for k, v in self.out_files.items()
                    },
                    "segments": self.segments(),
                    "crashes": list(self.crash_log),
                    "wall_s": round(time.perf_counter() - t_start, 3),
                }
            if status == "killed":
                return {
                    "status": "killed",
                    "commits": [
                        c["epoch"] for c in self.committed_epochs()
                    ],
                    "restarts": self.restarts,
                    "worker_restarts": self.worker_restarts,
                    "out_files": {
                        str(k): v for k, v in self.out_files.items()
                    },
                    "segments": self.segments(),
                    "wall_s": round(time.perf_counter() - t_start, 3),
                }
            # crash / wedge: full-cluster restart from the last commit.
            # The budget bounds the failure RATE: a crash-free
            # restart_heal_s interval resets the streak, a storm
            # exhausts it (lifetime ``restarts`` is reporting only).
            self.crash_log.append(str(detail.get("why")))
            killed_workers += detail.get("killed_workers", 0)
            self.restarts += 1
            now = time.monotonic()
            if (
                self._full_streak
                and now - self._full_last >= self.spec.restart_heal_s
            ):
                self._full_streak = 0
            self._full_streak += 1
            self._full_last = now
            if self._full_streak > self.spec.max_restarts:
                raise StateError(
                    f"cluster exceeded restart budget "
                    f"({self.spec.max_restarts}): {detail.get('why')}"
                )

    def _run_incarnation(
        self, seq: int, store_version: int, restore_epoch: str,
        already_killed: int = 0,
    ) -> tuple[str, dict]:
        spec = self.spec
        n = spec.n_workers
        # new generation: bump the token FIRST (conn threads capture it
        # at accept) and drop anything a killed generation left queued
        self._gen_token += 1
        while True:
            try:
                self._events.get_nowait()
            except queue.Empty:
                break
        self._spawn_workers(seq, store_version, restore_epoch)
        ready: dict[int, dict] = {}
        eos_rows: dict[int, int] = {}
        eos_meta: dict[int, dict] = {}
        acked: set[int] = set()
        last_ack: dict[int, int] = {}
        inflight_epoch: int | None = None
        next_barrier_at: float | None = None
        committed = self.last_committed() or 0
        # epochs aborted THIS incarnation: a dead worker's in-flight
        # barrier is abandoned (its respawn restores strictly below
        # it), and its number is never reused while any peer might
        # hold a snapshot cut at it — the next barrier skips past
        aborted: list[int] = []
        recovering: dict[int, dict] = {}  # wid -> {"deadline", "t0"}
        recovered: set[int] = set()  # finished a rejoin this incarnation
        pending_death: dict[int, tuple[float, str]] = {}
        kill_at = (
            time.monotonic() + self.kill_worker_after_s
            if self.kill_worker_after_s is not None and already_killed == 0
            else None
        )
        kp_armed: float | None = None
        killed_workers = 0
        inc_t0 = time.monotonic()
        last_liveness = time.monotonic()
        last_seen: dict[int, float] = {
            i: time.monotonic() for i in range(n)
        }
        partial_ok = (
            bool(spec.partial_recovery) and self._checkpointing()
        )

        def fail(why: str) -> tuple[str, dict]:
            self._kill_all()
            return "crashed", {
                "why": why, "killed_workers": killed_workers,
            }

        def write_state() -> None:
            # best-effort doctor snapshot (obs/doctor/clusterdoc.py);
            # atomic replace so readers never see a torn file
            workers = {}
            for w in range(n):
                workers[str(w)] = {
                    "gen": self._wgen.get(w, 0),
                    "last_ack_epoch": last_ack.get(w),
                    "state": (
                        "recovering" if w in recovering
                        else "eos" if w in eos_rows else "up"
                    ),
                }
            payload = {
                "t": round(time.time(), 3),
                "n_workers": n,
                "committed_epoch": committed,
                "inflight_epoch": inflight_epoch,
                "aborted_epochs": list(self.aborted_epochs),
                "worker_restarts": self.worker_restarts,
                "worker_max_restarts": spec.worker_max_restarts,
                "rejoin_timeout_s": spec.rejoin_timeout_s,
                "workers": workers,
            }
            tmp = self._cluster_state_path + ".tmp"
            try:
                with open(tmp, "w") as f:
                    json.dump(payload, f, indent=2)
                os.replace(tmp, self._cluster_state_path)
            except OSError:
                pass

        def begin_partial(wid: int, why: str):
            """Start single-worker recovery of ``wid``; returns None on
            success or the ``fail(...)`` tuple when ineligible (the
            documented full-cluster fallback)."""
            nonlocal inflight_epoch, acked
            pending_death.pop(wid, None)
            if not (partial_ok and self.last_committed() is not None):
                return fail(why)
            if not self._wstreaks[wid].take():
                return fail(
                    f"{why} [worker {wid} partial-restart budget "
                    "exhausted]"
                )
            self.crash_log.append(f"partial w{wid}: {why}")
            if inflight_epoch is not None:
                # abort the aligning barrier even if ``wid`` already
                # acked it: the respawn pins to committed < inflight,
                # so letting it commit would strand the new worker
                # below the cluster cut
                aborted.append(inflight_epoch)
                self.aborted_epochs.append(inflight_epoch)
                self._broadcast(
                    {"cmd": "abort", "epoch": inflight_epoch}
                )
                inflight_epoch = None
                acked = set()
            self._conns.pop(wid, None)
            p = self._procs.get(wid)
            if p is not None and p.poll() is None:
                try:
                    os.kill(p.pid, signal.SIGKILL)
                except OSError:
                    pass
            if p is not None:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    pass
            # only THIS worker's exchange socket: survivors' listeners
            # stay up, their senders hold buffered frames for the edge
            try:
                os.unlink(os.path.join(
                    self.workdir, "sock", f"exch_{wid}.sock"
                ))
            except FileNotFoundError:
                pass
            self._wgen[wid] += 1
            committed_now = self.last_committed() or 0
            self._spawn_one(
                wid, seq, store_version, committed_now,
                abort_floor=max([committed_now] + aborted),
            )
            recovering[wid] = {
                "deadline": time.monotonic() + spec.rejoin_timeout_s,
                "t0": time.perf_counter(),
            }
            ready.pop(wid, None)
            eos_rows.pop(wid, None)
            eos_meta.pop(wid, None)
            acked.discard(wid)
            last_seen[wid] = time.monotonic()
            self.worker_restarts += 1
            self._obs_wrestart(wid).add(1)
            write_state()
            return None

        while True:
            now = time.monotonic()
            # worker process death? Defer action for a grace interval:
            # an error event the dying worker already sent (possibly
            # ``fallback: "cluster"``) must win the attribution
            for wid, p in list(self._procs.items()):
                rc = p.poll()
                if rc is None or wid in pending_death:
                    continue
                if rc != 0:
                    pending_death[wid] = (
                        now + _DEATH_GRACE_S,
                        f"worker {wid} exited rc={rc}",
                    )
                elif wid not in eos_rows:
                    pending_death[wid] = (
                        now + _DEATH_GRACE_S,
                        f"worker {wid} exited before EOS",
                    )
            for wid, (due, why) in list(pending_death.items()):
                if now >= due:
                    r = begin_partial(wid, why)
                    if r is not None:
                        return r
            if kill_at is not None and now >= kill_at:
                # chaos: SIGKILL one worker mid-stream
                p = self._procs.get(self.kill_worker_id)
                if p is not None and p.poll() is None:
                    os.kill(p.pid, signal.SIGKILL)
                    killed_workers += 1
                kill_at = None
                continue
            if self._kp_idx < len(self.kill_plan):
                ent = self.kill_plan[self._kp_idx]
                when = ent.get("when")
                if committed < int(ent.get("min_commits", 0)):
                    cond = False  # wait until the cut exists
                elif "after_s" in ent:
                    cond = now - inc_t0 >= float(ent["after_s"])
                elif when == "inflight":
                    cond = inflight_epoch is not None
                elif when == "recovering":
                    cond = bool(recovering) and (
                        "of" not in ent or ent["of"] in recovering
                    )
                elif when == "recovered":
                    cond = ent.get("of", -1) in recovered
                else:
                    cond = False
                if cond and kp_armed is None:
                    kp_armed = now
                if (
                    kp_armed is not None
                    and now >= kp_armed + float(ent.get("delay_s", 0.0))
                ):
                    p = self._procs.get(int(ent["worker"]))
                    if (
                        p is not None and p.poll() is None
                        and int(ent["worker"]) not in pending_death
                    ):
                        os.kill(p.pid, signal.SIGKILL)
                        killed_workers += 1
                    self._kp_idx += 1
                    kp_armed = None
            if now - last_liveness > spec.liveness_timeout_s:
                return fail("liveness timeout (no worker progress)")
            # per-worker wedge: heartbeats keep live workers' last_seen
            # fresh, so ONE silent worker while peers stream is a
            # single-worker fault, not a cluster wedge
            if partial_ok:
                for w in range(n):
                    if w in eos_rows or w in recovering:
                        continue
                    if now - last_seen.get(w, now) > spec.liveness_timeout_s:
                        r = begin_partial(
                            w,
                            f"worker {w} liveness timeout "
                            "(peers still streaming)",
                        )
                        if r is not None:
                            return r
            for w, info in list(recovering.items()):
                if now >= info["deadline"]:
                    return fail(
                        f"worker {w} rejoin exceeded "
                        f"{spec.rejoin_timeout_s}s"
                    )
            # barrier cadence: serial (commit e before issuing e+1),
            # held while any worker is mid-rejoin; aborted epoch
            # numbers are never reused within this incarnation
            if (
                self._checkpointing()
                and len(ready) == n
                and not recovering
                and inflight_epoch is None
                and next_barrier_at is not None
                and now >= next_barrier_at
                and len(eos_rows) < n
            ):
                inflight_epoch = max([committed] + aborted) + 1
                acked = set()
                self._broadcast(
                    {"cmd": "barrier", "epoch": inflight_epoch}
                )
            try:
                kind, wid, msg, token, wtok = self._events.get(
                    timeout=0.05
                )
            except queue.Empty:
                continue
            if (
                token != self._gen_token
                or wtok != self._wgen.get(wid, 0)
            ):
                continue  # a dead generation/incarnation's event
            last_liveness = time.monotonic()
            last_seen[wid] = last_liveness
            if kind == "hello":
                continue
            if kind == "conn_lost":
                # the process-death poll above decides whether this is a
                # crash (nonzero exit) or a clean shutdown
                continue
            ev = msg.get("ev")
            if ev == "ready":
                if wid in recovering:
                    # rejoin handshake: the respawn must echo exactly
                    # the partition subset this slot owns — anything
                    # else means it computed a different assignment
                    # and would double- or under-replay
                    npart = int(msg.get("n_partitions") or 0)
                    if list(msg.get("partitions") or []) != (
                        partitions_for(wid, n, npart)
                    ):
                        return fail(
                            f"worker {wid} rejoin echoed wrong "
                            "partition subset"
                        )
                    info = recovering.pop(wid)
                    ms = (time.perf_counter() - info["t0"]) * 1000.0
                    self.recoveries.append(
                        {"worker": wid, "ms": round(ms, 3)}
                    )
                    self._obs_recovery.observe(ms)
                    recovered.add(wid)
                    write_state()
                ready[wid] = msg
                if len(ready) == n:
                    if self.read_manifest() is None:
                        self._write_manifest({
                            "n_workers": n,
                            "store_version": store_version,
                            "n_partitions": msg.get("n_partitions"),
                            "state_keys": msg.get("state_keys"),
                            "key_columns": msg.get("key_columns"),
                            "key_dtypes": msg.get("key_dtypes"),
                        })
                    if self._checkpointing():
                        next_barrier_at = (
                            time.monotonic() + spec.checkpoint_interval_s
                        )
                    write_state()
            elif ev == "ack":
                ep = int(msg["epoch"])
                last_ack[wid] = max(ep, last_ack.get(wid, 0))
                if ep == inflight_epoch:
                    acked.add(wid)
                    if len(acked) == n:
                        committed = inflight_epoch
                        _fsync_append(self._commits_path, json.dumps({
                            "epoch": committed,
                            "n_workers": n,
                            "store_version": store_version,
                            "t": round(time.time(), 3),
                        }))
                        inflight_epoch = None
                        # senders prune replay buffers through the
                        # cluster-committed barrier — a partial rejoin
                        # never needs frames older than this cut
                        self._broadcast(
                            {"cmd": "committed", "epoch": committed}
                        )
                        next_barrier_at = (
                            time.monotonic() + spec.checkpoint_interval_s
                        )
                        write_state()
                        if (
                            self.kill_after_commits is not None
                            and len(self.committed_epochs())
                            >= self.kill_after_commits
                        ):
                            self._kill_all()
                            return "killed", {}
                        if len(eos_rows) == n:
                            # every worker reached EOS while this epoch
                            # was aligning — finish now that it committed
                            self._broadcast({"cmd": "stop"})
                            for p in self._procs.values():
                                try:
                                    p.wait(timeout=30)
                                except subprocess.TimeoutExpired:
                                    p.kill()
                            return "done", {
                                "rows": eos_rows,
                                "meta": eos_meta,
                                "killed_workers": (
                                    killed_workers + already_killed
                                ),
                            }
            elif ev == "eos":
                eos_rows[wid] = int(msg.get("rows", 0))
                eos_meta[wid] = msg
                if len(eos_rows) == n and inflight_epoch is None:
                    self._broadcast({"cmd": "stop"})
                    deadline = time.monotonic() + 30
                    for p in self._procs.values():
                        try:
                            p.wait(
                                timeout=max(0.1, deadline - time.monotonic())
                            )
                        except subprocess.TimeoutExpired:
                            p.kill()
                    return "done", {
                        "rows": eos_rows,
                        "meta": eos_meta,
                        "killed_workers": killed_workers + already_killed,
                    }
            elif ev == "error":
                pending_death.pop(wid, None)
                why = f"worker {wid}: {msg.get('msg')}"
                if msg.get("fallback") == "cluster":
                    # the worker itself determined single-worker replay
                    # cannot be exact (replay-buffer gap, unstamped
                    # ledgers) — only the full cut is sound
                    return fail(why)
                r = begin_partial(wid, why)
                if r is not None:
                    return r


def run_cluster(spec: ClusterSpec, **kw) -> dict:
    """Convenience wrapper: build a coordinator, run, return summary."""
    return Coordinator(spec, **kw).run()
