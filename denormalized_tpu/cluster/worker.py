"""Cluster worker process entry point.

``python -m denormalized_tpu.cluster.worker --spec <file> --worker <i>
--store <dir> --restore-epoch <E|none> --seq <k> --out <file>
[--gen <g>] [--abort-floor <E>]``

``--gen`` is this worker's incarnation number (bumped by the
coordinator at every spawn, full or partial) — it rides the exchange
hello so peers distinguish a reconnecting sender from a reborn one.
``--abort-floor`` is the highest epoch the coordinator ever aborted (or
committed) before this incarnation: the merger drops stale barrier
markers at or below it, which is what makes replayed frames from
surviving peers safe to consume verbatim.

One worker = one engine process running BOTH halves of the split query
(cluster/split.py): an **ingest thread** drives the partition-subset
pipeline into the exchange router, and the **main thread** drives the
keyed half from the edge merger into the worker's sink.  A **control
thread** speaks JSON-lines to the coordinator (barriers in,
acks/heartbeats/EOS out).

Checkpoint protocol (worker side): a barrier command either enters the
stream through the source's in-band poll (ingest alive) or — after
ingest EOS — persists the final offsets directly; the keyed half
commits the epoch to the worker's own store when the aligned Marker
drains at its root, then acks.  Once the whole worker is done, the
control thread keeps servicing barriers (persist final offsets, commit,
ack) until the coordinator says stop, so the cluster's cut can keep
advancing while stragglers finish.  The cluster-committed epoch lives
coordinator-side (meta/commits.jsonl); a worker's local commit is only
a proposal until every worker acked it.

Exactly-once output: the sink tags every row with the in-flight epoch
(committed+1) and announces the restored epoch first — the same
transactional truncate-on-restore protocol tools/soak.py established in
PR 1, applied per worker slot.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import threading
import time

from denormalized_tpu.common.errors import StateError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.cluster.exchange import (
    EdgeMerger,
    ExchangeClient,
    ExchangeServer,
)
from denormalized_tpu.cluster.runtime import (
    ExchangeRouter,
    ExchangeSourceExec,
    replace_scan_source,
)
from denormalized_tpu.cluster.spec import ClusterSpec, resolve_job
from denormalized_tpu.cluster.split import ExchangeScan, split_keyed


def sock_path(workdir: str, worker: int) -> str:
    return os.path.join(workdir, "sock", f"exch_{worker}.sock")


def ctrl_sock_path(workdir: str) -> str:
    return os.path.join(workdir, "sock", "ctrl.sock")


class PinnedCheckpointCoordinator:
    """Factory for a CheckpointCoordinator that restores at exactly the
    cluster-committed epoch the coordinator dictates — a worker's own
    (possibly newer, never cluster-acked) local commit record is
    overridden, its stale epochs GC'd by the base machinery."""

    def __new__(cls, backend, pin_epoch: int | None):
        from denormalized_tpu.state.checkpoint import CheckpointCoordinator

        class _Pinned(CheckpointCoordinator):
            def _select_restore_epoch(
                self, committed, history, commit_corrupt=False
            ):
                if pin_epoch is None:
                    return None  # fresh cluster: ignore any leftovers
                ok, why = self._verify_epoch(pin_epoch)
                if not ok:
                    raise StateError(
                        f"cluster-committed epoch {pin_epoch} failed "
                        f"verification in this worker's store: {why}"
                    )
                return pin_epoch

        return _Pinned(backend)


class _ControlClient:
    """JSON-lines control channel to the coordinator."""

    def __init__(self, path: str, worker_id: int) -> None:
        self.worker_id = worker_id
        deadline = time.monotonic() + 30.0
        last = None
        while time.monotonic() < deadline:
            s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                s.connect(path)
                self._sock = s
                break
            except OSError as e:
                s.close()
                last = e
                time.sleep(0.05)
        else:
            raise StateError(f"control connect failed: {last}")
        self._wlock = threading.Lock()
        self._rfile = self._sock.makefile("r", encoding="utf-8")
        self.send({"ev": "hello", "worker": worker_id})

    def send(self, obj: dict) -> None:
        data = (json.dumps(obj) + "\n").encode()
        with self._wlock:
            try:
                self._sock.sendall(data)
            except OSError:
                # coordinator died: the worker is an orphan — exit; the
                # next coordinator incarnation respawns everything
                os._exit(3)

    def recv(self) -> dict | None:
        line = self._rfile.readline()
        if not line:
            return None
        return json.loads(line)


class WorkerRuntime:
    """Shared mutable state between the three worker threads."""

    def __init__(self, spec: ClusterSpec, args) -> None:
        self.spec = spec
        self.args = args
        self.worker_id = args.worker
        self.lock = threading.Lock()
        self.ingest_done = False
        self.keyed_done = False
        self.offsets_persisted: set[int] = set()
        self.committed: set[int] = set()
        self.src_exec = None
        self.coord = None
        self.ctrl: _ControlClient | None = None
        self.merger = None
        self.barrier_q: list[int] = []  # consumed by the source poll
        self.stop_event = threading.Event()
        self.rows_emitted = 0
        self.errors: list[str] = []

    # -- barrier plumbing -------------------------------------------------
    def poll_barrier(self) -> int | None:
        with self.lock:
            if self.barrier_q:
                return self.barrier_q.pop(0)
        return None

    def persist_offsets_once(self, epoch: int) -> None:
        with self.lock:
            if epoch in self.offsets_persisted or self.src_exec is None:
                return
            self.offsets_persisted.add(epoch)
        self.src_exec.persist_final_offsets(epoch)

    def commit_and_ack(self, epoch: int) -> None:
        with self.lock:
            if epoch in self.committed:
                return
            self.committed.add(epoch)
        self.coord.commit(epoch)
        self.ctrl.send({"ev": "ack", "epoch": epoch})

    def _commit_if_keyed_done(self, epoch: int) -> None:
        """Commit+ack an already-persisted epoch iff the keyed half can
        no longer carry its marker.  The keyed_done check runs AFTER the
        offsets persist (callers guarantee that order): either this
        check sees keyed_done=True and commits, or on_keyed_done's sweep
        — which runs after keyed_done is set — sees the epoch in
        offsets_persisted and commits; the ``committed`` set keeps the
        overlap idempotent.  Checking keyed_done BEFORE persisting would
        reopen the lost-epoch race (both paths could miss)."""
        with self.lock:
            keyed_done = self.keyed_done
        if keyed_done and self.coord is not None:
            self.commit_and_ack(epoch)

    def on_abort(self, epoch: int) -> None:
        """Control thread: the coordinator aborted in-flight epoch
        ``epoch`` (a peer died before acking it; the number is never
        reused).  Drop it from the pending barrier queue so the marker
        never enters the stream here, and raise the merger's abort
        floor so markers already in flight from peers unwind instead of
        aligning."""
        with self.lock:
            if epoch in self.barrier_q:
                self.barrier_q.remove(epoch)
        if self.merger is not None:
            self.merger.abort_to(epoch)
        if self.coord is not None:
            self.coord.note_aborted(epoch)

    def on_barrier_cmd(self, epoch: int) -> None:
        """Control thread: route one barrier command."""
        with self.lock:
            ingest_done = self.ingest_done
            if not ingest_done:
                self.barrier_q.append(epoch)
        if not ingest_done or self.coord is None:
            return  # in-band: the keyed Marker path commits+acks
        self.persist_offsets_once(epoch)
        self._commit_if_keyed_done(epoch)

    def on_ingest_done(self) -> None:
        """Ingest thread exit: any barrier still queued (raced the EOS)
        persists final offsets here so its epoch can still commit —
        and commits it NOW if the keyed half is already done (the
        marker can no longer flow, and no later event would)."""
        with self.lock:
            self.ingest_done = True
            pending, self.barrier_q = self.barrier_q, []
        for e in pending:
            if self.coord is not None:
                self.persist_offsets_once(e)
                self._commit_if_keyed_done(e)
        # otherwise the commit+ack happens when the keyed half sees the
        # marker from the other edges (alignment guarantees it), or on
        # on_keyed_done's sweep for epochs persisted here

    def on_marker(self, epoch: int) -> None:
        """Keyed thread: aligned marker drained at the worker root."""
        if self.coord is None:
            return
        with self.lock:
            ingest_done = self.ingest_done
        if ingest_done:
            self.persist_offsets_once(epoch)
        self.commit_and_ack(epoch)

    def on_keyed_done(self) -> None:
        """Keyed thread exit.  Sweep epochs persisted while the merger
        was returning: their markers never materialized, and the control
        thread's _commit_if_keyed_done may have read keyed_done=False.
        keyed_done is set BEFORE the sweep and the control thread checks
        it AFTER persisting, so the two paths can never both miss; the
        ``committed`` set keeps the overlap idempotent."""
        with self.lock:
            self.keyed_done = True
            pending = sorted(self.offsets_persisted - self.committed)
        for e in pending:
            if self.coord is not None:
                self.commit_and_ack(e)


class _EpochTaggedJsonlSink:
    """Per-worker emission sink, epoch-tagged for exactly-once reading
    (tools/soak.py read_emissions protocol)."""

    def __init__(self, path: str, runtime: WorkerRuntime, schema) -> None:
        from denormalized_tpu.physical.simple_execs import _py

        self._py = _py
        self._f = open(path, "a", buffering=1)
        self._rt = runtime
        self._names = schema.without_internal().names
        self._announced = False

    def _announce(self) -> None:
        coord = self._rt.coord
        self._f.write(json.dumps({
            "event": "restored",
            "epoch": (coord.restored_epoch or 0) if coord else None,
        }) + "\n")
        self._announced = True

    def write(self, batch: RecordBatch) -> None:
        if not self._announced:
            self._announce()
        coord = self._rt.coord
        ep = (coord.committed_epoch or 0) + 1 if coord else None
        user = batch.select(
            [n for n in self._names if batch.schema.has(n)]
        ).materialized()
        names = user.schema.names
        py = self._py
        for i in range(user.num_rows):
            rec = {n: py(user.columns[j][i]) for j, n in enumerate(names)}
            if ep is not None:
                rec["ep"] = ep
            self._f.write(json.dumps(rec) + "\n")
        self._rt.rows_emitted += batch.num_rows

    def close(self) -> None:
        """Idempotent: SinkExec closes at EOS and the worker's teardown
        may close again."""
        if self._f.closed:
            return
        if not self._announced:
            self._announce()
        self._f.write(json.dumps({
            "event": "done", "rows": self._rt.rows_emitted,
        }) + "\n")
        self._f.close()


class _CountSink:
    """Bench-mode sink: rows counted, nothing written per row."""

    def __init__(self, path: str, runtime: WorkerRuntime) -> None:
        self._path = path
        self._rt = runtime
        self._t0 = time.perf_counter()
        self._closed = False

    def write(self, batch: RecordBatch) -> None:
        self._rt.rows_emitted += batch.num_rows

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        with open(self._path, "a", buffering=1) as f:
            f.write(json.dumps({
                "event": "done",
                "rows": self._rt.rows_emitted,
                "wall_s": round(time.perf_counter() - self._t0, 4),
            }) + "\n")


def run_worker(args) -> int:
    from denormalized_tpu import obs
    from denormalized_tpu.api.context import Context, EngineConfig
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.logical.optimizer import optimize
    from denormalized_tpu.physical.base import EndOfStream, Marker
    from denormalized_tpu.physical.simple_execs import SourceExec
    from denormalized_tpu.planner.planner import Planner
    from denormalized_tpu.runtime import faults
    from denormalized_tpu.state.checkpoint import assign_node_ids, walk
    from denormalized_tpu.state.lsm import initialize_global_state_backend
    from denormalized_tpu.state.tiering import attach_spill

    with open(args.spec) as f:
        spec = ClusterSpec.from_json(f.read())
    wid, n = args.worker, spec.n_workers
    if spec.fault_plan:
        faults.arm(spec.fault_plan)
    job = resolve_job(spec)

    config = EngineConfig()
    for k, v in (job.get("engine") or {}).items():
        config.set(k, v)
    # the exchange REQUIRES authoritative watermarks on every edge
    config.partition_watermarks = True
    checkpointing = args.restore_epoch != "off"
    if checkpointing:
        config.state_backend_path = args.store
        config.checkpoint = True
    if spec.metrics_jsonl:
        config.metrics_jsonl_path = os.path.join(
            spec.workdir, "obs", f"w{wid}_seq{args.seq}.jsonl"
        )
        config.metrics_jsonl_interval_s = 0.5
    ctx = Context(config)

    rt = WorkerRuntime(spec, args)
    ctrl = _ControlClient(ctrl_sock_path(spec.workdir), wid)
    rt.ctrl = ctrl
    exporters = None
    server = None
    try:
        # -- plan: build, optimize, split, subset -------------------------
        ds = ctx.from_source(job["source"])
        ds = job["pipeline"](ds)
        reg = obs.current_registry() if config.metrics_enabled \
            else obs.disabled_registry()
        with obs.bound_registry(reg):
            plan = optimize(
                lp.Sink(ds.logical_plan(), None),
                getattr(config, "optimizer", True),
            )
        # partial recovery needs checkpointing (there is nothing to pin
        # a lone respawn to without cluster commits) — reader batches
        # are then provenance-stamped so peers can ledger deliveries
        # per partition (cluster/runtime.py PART_COL)
        partial = bool(spec.partial_recovery) and checkpointing
        pin_epoch = (
            0 if args.restore_epoch in ("none", "off")
            else int(args.restore_epoch)
        )
        sq = split_keyed(plan)
        subset = replace_scan_source(
            sq.ingest_logical, wid, n, stamp=partial
        )

        # -- exchange -----------------------------------------------------
        with obs.bound_registry(reg):
            server = ExchangeServer(
                wid, n, sock_path(spec.workdir, wid), sq.exchange_schema,
                partial=partial, last_commit=pin_epoch,
            )
            clients = {
                dst: ExchangeClient(
                    wid, dst, sock_path(spec.workdir, dst),
                    gen=args.gen, restore_epoch=pin_epoch,
                    partial=partial,
                    replay_buffer_bytes=spec.replay_buffer_bytes,
                    reconnect_deadline_s=spec.rejoin_timeout_s,
                )
                for dst in range(n) if dst != wid
            }
        merger = EdgeMerger(server)
        if args.abort_floor:
            merger.abort_to(args.abort_floor)
        rt.merger = merger

        # -- physical halves ---------------------------------------------
        sink = (
            _CountSink(args.out, rt) if spec.sink == "count"
            else _EpochTaggedJsonlSink(args.out, rt, plan.schema)
        )
        keyed_logical = sq.keyed_builder(
            ExchangeScan(
                sq.exchange_schema,
                lambda: ExchangeSourceExec(sq.exchange_schema, merger, wid),
            )
        )
        # re-point the rebuilt Sink node at the worker's sink object
        sink_node = keyed_logical
        while not isinstance(sink_node, lp.Sink):
            sink_node = sink_node.children[0]
        sink_node.sink = sink
        with obs.bound_registry(reg):
            planner = Planner(config)
            ingest_root = planner.create_physical_plan(sq.ingest_logical)
            keyed_root = planner.create_physical_plan(keyed_logical)
            exporters = obs.start_exporters(config, registry=reg)

        # -- checkpoint wiring -------------------------------------------
        coord = None
        spill = None
        state_keys: dict[str, str] = {}
        src_exec = next(
            op for op in walk(ingest_root) if isinstance(op, SourceExec)
        )
        rt.src_exec = src_exec
        if checkpointing:
            backend = initialize_global_state_backend(args.store)
            pin = (
                None if args.restore_epoch in ("none", "off")
                else int(args.restore_epoch)
            )
            with obs.bound_registry(reg):
                coord = PinnedCheckpointCoordinator(backend, pin)
                rt.coord = coord
                # spill BEFORE checkpoint wiring (tier maps rebuild
                # through the adapter, same order as the executor)
                spill = attach_spill(keyed_root, ctx)
                ing_ids = assign_node_ids(ingest_root)
                src_exec.enable_cluster_checkpointing(
                    ing_ids[id(src_exec)], coord, rt.poll_barrier
                )
                state_keys["offsets"] = f"offsets_{ing_ids[id(src_exec)]}"
                key_ids = assign_node_ids(keyed_root)
                for op in walk(keyed_root):
                    hook = getattr(op, "enable_checkpointing", None)
                    if hook is not None:
                        hook(key_ids[id(op)], coord, None)
                        ckpt = getattr(op, "_ckpt", None)
                        if ckpt is not None and ckpt[1].startswith(
                            ("window_", "session_", "udafwin_", "join_")
                        ):
                            state_keys.setdefault("keyed", ckpt[1])

        # -- control thread ----------------------------------------------
        def ctrl_loop():
            while True:
                msg = ctrl.recv()
                if msg is None:
                    os._exit(3)  # coordinator vanished
                cmd = msg.get("cmd")
                if cmd == "barrier":
                    try:
                        rt.on_barrier_cmd(int(msg["epoch"]))
                    except StateError as e:
                        ctrl.send({"ev": "error", "msg": str(e)})
                        os._exit(1)
                elif cmd == "abort":
                    rt.on_abort(int(msg["epoch"]))
                elif cmd == "committed":
                    # cluster commit: prune replay buffers (senders) and
                    # stale barrier snapshots (receiver ledgers)
                    ep = int(msg["epoch"])
                    server.note_commit(ep)
                    for c in clients.values():
                        c.note_commit(ep)
                elif cmd == "stop":
                    rt.stop_event.set()
                    return

        threading.Thread(
            target=ctrl_loop, name="cluster-ctrl", daemon=True
        ).start()

        def hb_loop():
            # liveness signal independent of barrier traffic: with
            # checkpointing off (bench mode) acks never flow, and the
            # coordinator's liveness timeout would otherwise declare a
            # long healthy stream wedged
            while not rt.stop_event.wait(timeout=5.0):
                ctrl.send({"ev": "hb"})

        threading.Thread(
            target=hb_loop, name="cluster-hb", daemon=True
        ).start()

        from denormalized_tpu.common.schema import DataType

        key_dtypes = []
        for k in sq.key_columns:
            f_ = sq.exchange_schema.field(k)
            if f_.dtype in (DataType.STRING, DataType.STRUCT,
                            DataType.LIST):
                key_dtypes.append("obj")
            else:
                import numpy as _np

                key_dtypes.append(_np.dtype(f_.dtype.to_numpy()).str)
        if args.gen > 0 and partial:
            # rejoin handshake fault site: an injected StateError here
            # surfaces as a failed rejoin — the coordinator's
            # rejoin_timeout_s / budget machinery must degrade to the
            # full-cluster restart, never wedge
            faults.inject("cluster.rejoin", key=f"w{wid}")
        ctrl.send({
            "ev": "ready",
            "restored_epoch": (
                (coord.restored_epoch or 0) if coord is not None else None
            ),
            "gen": args.gen,
            # partition subset echo: the coordinator cross-checks the
            # respawn landed on exactly the dead worker's partitions
            "partitions": subset.global_partition_ids(),
            "n_partitions": subset.n_partitions_total,
            "state_keys": state_keys,
            "key_columns": sq.key_columns,
            "key_dtypes": key_dtypes,
        })

        # -- run ----------------------------------------------------------
        router = ExchangeRouter(
            ingest_root, sq.key_columns, wid, n, clients, server
        )
        for c in clients.values():
            c.connect()
        ingest_err: list[BaseException] = []

        def ingest_main():
            try:
                with obs.bound_registry(reg):
                    router.run()
            except BaseException as e:  # dnzlint: allow(broad-except) supervisor boundary: the error is re-dispatched to the coordinator as data and the process exits nonzero — fail-stop, never silent
                ingest_err.append(e)
                msg = {"ev": "error", "msg": f"ingest: {e!r}"}
                if getattr(e, "cluster_fallback", False):
                    # partial recovery provably cannot absorb this
                    # (replay gap, reconnect budget, unstamped rows):
                    # tell the coordinator to take the full restart
                    msg["fallback"] = "cluster"
                ctrl.send(msg)
                os._exit(1)
            finally:
                rt.on_ingest_done()

        ing_t = threading.Thread(
            target=ingest_main, name="cluster-ingest", daemon=True
        )
        t_run0 = time.perf_counter()
        ing_t.start()

        with obs.bound_registry(reg):
            it = keyed_root.run()
            try:
                for item in it:
                    if isinstance(item, Marker):
                        rt.on_marker(item.epoch)
                    elif isinstance(item, EndOfStream):
                        break
            finally:
                it.close()
        rt.on_keyed_done()
        ing_t.join(timeout=30.0)
        sink.close()  # idempotent; covers a stream torn down pre-EOS
        ctrl.send({
            "ev": "eos",
            "rows": rt.rows_emitted,
            "rows_in": router.rows_routed,
            "ingest_wall_s": round(router.wall_s, 4),
            # ingest start → keyed-half EOS: the full pipeline wall
            # (the exchange's bounded queues let a small feed finish
            # ingest long before the keyed half drains — rows/s must
            # not be read off the ingest wall alone)
            "worker_wall_s": round(time.perf_counter() - t_run0, 4),
        })
        # keep servicing barriers until the coordinator releases us
        rt.stop_event.wait(timeout=spec.liveness_timeout_s)
        return 0
    except Exception as e:
        import traceback

        tb = traceback.format_exc(limit=8)
        try:
            msg = {"ev": "error", "msg": f"{e!r}\n{tb}"}
            if getattr(e, "cluster_fallback", False):
                msg["fallback"] = "cluster"
            ctrl.send(msg)
        except Exception:  # dnzlint: allow(broad-except) the control channel may be the thing that failed; the nonzero exit below still surfaces the crash to the coordinator
            pass
        raise
    finally:
        if server is not None:
            server.stop()
        if exporters is not None:
            exporters.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="denormalized_tpu.cluster.worker")
    ap.add_argument("--spec", required=True)
    ap.add_argument("--worker", type=int, required=True)
    ap.add_argument("--store", required=True)
    ap.add_argument(
        "--restore-epoch", default="off",
        help="'off' (no checkpointing), 'none' (fresh), or the pinned "
        "cluster-committed epoch",
    )
    ap.add_argument("--seq", type=int, default=0)
    ap.add_argument("--out", required=True)
    ap.add_argument(
        "--gen", type=int, default=0,
        help="incarnation number for the exchange hello (bumped by the "
        "coordinator at every spawn of this worker)",
    )
    ap.add_argument(
        "--abort-floor", type=int, default=0,
        help="highest aborted-or-committed epoch before this "
        "incarnation; barrier markers at or below it are dropped",
    )
    args = ap.parse_args(argv)
    return run_worker(args)


if __name__ == "__main__":
    sys.exit(main())
