"""Cluster job specification — the JSON contract between coordinator and
worker processes.

A **job** is a named factory ``module:function`` the worker imports and
calls with ``job_args``; it returns a dict::

    {"source":  Source,                       # the FULL source (all partitions)
     "pipeline": fn(DataStream) -> DataStream,  # the keyed query
     "engine":  {EngineConfig overrides, optional}}

No pickling anywhere: the factory is resolved by name inside each worker
process, so jobs compose exactly like soak/bench child pipelines do
(tools/soak.py child_main).  ``sys_path`` entries let tests point
workers at job modules that live outside the installed package.
"""

from __future__ import annotations

import importlib
import json
from dataclasses import asdict, dataclass, field


@dataclass
class ClusterSpec:
    """Everything a cluster run needs, JSON-serializable."""

    workdir: str  # sockets, per-worker stores, outputs, obs JSONL
    n_workers: int
    job: str  # "module:function"
    job_args: dict = field(default_factory=dict)
    sys_path: list = field(default_factory=list)
    # checkpointing: barrier cadence (None = only coordinator-triggered
    # barriers via Coordinator.trigger_barrier / none at all)
    checkpoint_interval_s: float | None = None
    # emission sink: "jsonl" (full epoch-tagged rows, the exactly-once
    # soak/test protocol) or "count" (rows counted, bench mode)
    sink: str = "jsonl"
    # supervision: full-cluster restarts allowed before giving up.
    # Budgets bound failure RATE, not lifetime: every restart opens a
    # per-scope streak, and a crash-free ``restart_heal_s`` interval
    # refunds the streak's tokens (the prefetch supervisor's
    # streak+refund pattern, one level up) — so a days-long stream with
    # occasional healed deaths never converges to a guaranteed kill,
    # while a crash-storm still exhausts the budget promptly.
    max_restarts: int = 3
    # partial recovery: a dead worker (with checkpointing on and at
    # least one cluster commit) is respawned ALONE, pinned to the last
    # committed epoch, while surviving workers keep streaming; falls
    # back to the full-cluster restart when ineligible or when the
    # rejoin exceeds its budget (docs/cluster.md#failure-matrix)
    partial_recovery: bool = True
    # single-worker respawns tolerated per worker within one heal
    # interval before that worker's failures escalate to the
    # full-cluster path (which spends ``max_restarts`` tokens)
    worker_max_restarts: int = 3
    # crash-free seconds after which restart streaks heal and their
    # tokens are refunded (per worker AND cluster-global)
    restart_heal_s: float = 30.0
    # seconds a respawned worker gets to finish the rejoin handshake
    # (ready event with echoed partition subset) before the
    # coordinator abandons partial recovery for the full restart
    rejoin_timeout_s: float = 60.0
    # sender-side replay buffer cap per edge (frames retained since the
    # last cluster-committed barrier); overflow evicts oldest and
    # forces the full-cluster fallback if a replay would have needed
    # the evicted frames
    replay_buffer_bytes: int = 64 << 20
    # seconds with no worker liveness signal before the run is declared
    # wedged (workers heartbeat on epoch acks and EOS)
    liveness_timeout_s: float = 120.0
    # obs: per-worker JSONL metrics snapshots (merged by
    # ``python -m denormalized_tpu.obs.readers merge``)
    metrics_jsonl: bool = False
    # fault plan JSON armed in every worker (DENORMALIZED_FAULT_PLAN)
    fault_plan: dict | None = None
    # arm the fault plan in the FIRST worker generation only: a
    # "times: 1" rule re-arms from zero in every respawned incarnation,
    # which would re-fire forever and burn the restart budget — the
    # soak wants one injected fault, then a clean recovery
    fault_plan_once: bool = True

    def to_json(self) -> str:
        return json.dumps(asdict(self), indent=2)

    @classmethod
    def from_json(cls, text: str) -> "ClusterSpec":
        return cls(**json.loads(text))


def resolve_job(spec: ClusterSpec) -> dict:
    """Import and call the job factory (inside the worker process)."""
    import sys

    for p in spec.sys_path:
        if p not in sys.path:
            sys.path.insert(0, p)
    mod_name, _, fn_name = spec.job.partition(":")
    if not fn_name:
        raise ValueError(
            f"job {spec.job!r} must be 'module:function'"
        )
    mod = importlib.import_module(mod_name)
    fn = getattr(mod, fn_name)
    job = fn(dict(spec.job_args))
    if "source" not in job or "pipeline" not in job:
        raise ValueError(
            f"job factory {spec.job!r} must return a dict with "
            "'source' and 'pipeline'"
        )
    return job
