"""Multi-process scale-out runtime.

The structural jump past one Python process (ROADMAP item 1): a
coordinator forks N engine **worker processes**, each owning a disjoint
static subset of the source's partitions (engine-owned assignment via
``Source.partition_factories()`` — no broker consumer groups), running
the existing prefetch/decode/operator pipeline locally.  Keyed operators
receive rows routed ``hash(key) % n_workers`` over a local-socket
**exchange** carrying column buffers (length-prefixed, CRC-framed like
checkpoints), with per-edge watermark merging and in-band barrier
alignment, so cluster checkpoints stay epoch-consistent and restore can
**rescale** — repartition checkpointed keyed + spilled state across a
changed worker count.

Layout::

    hashing.py      stable cross-process key hashing + partition math
    framing.py      exchange wire format (length-prefix + CRC32)
    exchange.py     sockets: server / client / edge merger (faults wired)
    split.py        logical-plan split at the keyed boundary
    runtime.py      ExchangeSourceExec / router / partition-subset source
    spec.py         ClusterSpec / job resolution (JSON round-trip)
    worker.py       worker process entry (python -m ...cluster.worker)
    coordinator.py  process supervision, aligned barriers, cluster commits
    rescale.py      re-bucket checkpointed state across a new worker count

See ``docs/cluster.md`` for the architecture and failure matrix.
"""

from denormalized_tpu.cluster.coordinator import Coordinator, run_cluster
from denormalized_tpu.cluster.spec import ClusterSpec

__all__ = ["ClusterSpec", "Coordinator", "run_cluster"]
