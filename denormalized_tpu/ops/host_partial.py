"""Host-side partial-aggregation stripe for the ``partial_merge`` device
strategy.

The streaming window operator can ship every decoded row to the device
(``scatter`` / ``pallas_dense``) or reduce each batch on the host first and
ship only sufficient statistics (this module).  The host keeps a *stripe*:
per-(slide-unit, sub, group) accumulators covering the slide units touched
since the last device merge.  ``flush()`` hands the stripe to the device
merge op (:func:`denormalized_tpu.ops.segment_agg.merge_partials`) which
folds it into the HBM window ring — sliding fan-out happens there, so the
host never replicates rows per overlapping window.

This is the Partial/Final split of the reference
(planner/streaming_window.rs:133-153) applied across the host↔accelerator
boundary: the right architecture whenever the link to the accelerator is
narrow relative to the ingest rate — partials scale with group cardinality
and window span, not with row count.

The hot loop is the native single-pass reducer ``native/partial_agg.cpp``;
a vectorized numpy fallback keeps no-compiler environments working.
"""

from __future__ import annotations

import ctypes

import numpy as np

from denormalized_tpu.ops import segment_agg as sa

_LIB = None
_LIB_TRIED = False


def _native():
    global _LIB, _LIB_TRIED
    if not _LIB_TRIED:
        _LIB_TRIED = True
        try:
            from denormalized_tpu.native.build import load

            lib = load("partial_agg")
            lib.partial_window_agg.restype = ctypes.c_int64
            lib.partial_window_agg.argtypes = [
                ctypes.c_void_p,  # win_rel int64
                ctypes.c_void_p,  # sub uint8 | NULL
                ctypes.c_void_p,  # gid int32
                ctypes.c_void_p,  # values f64
                ctypes.c_void_p,  # colvalid uint8 | NULL
                ctypes.c_int64,   # n
                ctypes.c_int32,   # V
                ctypes.c_int32,   # U
                ctypes.c_int32,   # SUB
                ctypes.c_int32,   # G
                ctypes.c_void_p,  # row_cnt int64
                ctypes.c_void_p,  # cnt int64
                ctypes.c_void_p,  # sum f64
                ctypes.c_void_p,  # mn f64
                ctypes.c_void_p,  # mx f64
            ]
            _LIB = lib
        except Exception as e:  # dnzlint: allow(broad-except) numpy partial-agg is the designed fallback on no-compiler boxes; logged so the downgrade is visible, gated by test_native_build_gate where g++ exists
            from denormalized_tpu.runtime.tracing import logger

            logger.warning(
                "native partial_agg unavailable (%s: %s) — host partial "
                "aggregation runs the numpy path",
                type(e).__name__, e,
            )
            _LIB = None
    return _LIB


def _ptr(a: np.ndarray | None):
    return None if a is None else a.ctypes.data_as(ctypes.c_void_p)


# fold-neutral int32 bit patterns for min/max planes in the DENSE packed
# layout (which has no validity mask): shared by the real pack and the
# prewarm no-op so the two can never diverge
NEUTRAL_BITS = {
    "min": np.float32(np.inf).view(np.int32),
    "max": np.float32(-np.inf).view(np.int32),
}


class HostPartialStripe:
    """Accumulates per-(slide-unit, sub, group) partials between device
    merges.

    ``u_base`` is the absolute slide index of stripe row 0; rows hold units
    ``u_base .. u_base + U - 1``.  ``SUB`` is 2 when ``length % slide != 0``
    (rows near the end of a unit belong to one fewer window — see
    partial_agg.cpp), else 1.
    """

    # stripe capacity in slide units; a span wider than this forces a flush
    U_MAX = 16

    def __init__(self, spec: sa.WindowKernelSpec, group_capacity: int):
        self.spec = spec
        self.G = group_capacity
        self.V = max(spec.num_value_cols, 1)
        self.SUB = 1 if spec.length_ms % spec.slide_ms == 0 else 2
        self.u_base: int | None = None
        self.u_hi = 0  # highest stripe-relative unit written (span - 1)
        self.rows = 0
        # True once ANY value column in this stripe had a null: decides
        # between the lean packed layout (per-column count planes aliased
        # to the row-count plane — valid because no-null means they are
        # equal) and the full layout
        self.nulls_seen = False
        self._alloc()

    def _alloc(self):
        U, S, G, V = self.U_MAX, self.SUB, self.G, self.V
        self.row_cnt = np.zeros((U, S, G), np.int64)
        self.cnt = np.zeros((V, U, S, G), np.int64)
        self.sum = np.zeros((V, U, S, G), np.float64)
        self.mn = np.full((V, U, S, G), np.inf)
        self.mx = np.full((V, U, S, G), -np.inf)

    # -- ingestion -----------------------------------------------------
    def add_batch(
        self,
        units: np.ndarray,      # (n) int64 absolute slide indices
        rem: np.ndarray,        # (n) int32 ts - unit*slide
        gid: np.ndarray,        # (n) int32
        values64: np.ndarray,   # (n, V) f64
        colvalid: np.ndarray | None,  # (n, V) bool or None (all valid)
        keep: np.ndarray | None,      # (n) bool rows to fold (None = all)
    ) -> None:
        n = len(units)
        if n == 0:
            return
        if keep is not None and not keep.all():
            units = units[keep]
            rem = rem[keep]
            gid = gid[keep]
            values64 = values64[keep]
            if colvalid is not None:
                colvalid = colvalid[keep]
            n = len(units)
            if n == 0:
                return
        if colvalid is not None and not self.nulls_seen and not colvalid.all():
            self.nulls_seen = True
        if self.u_base is None:
            self.u_base = int(units.min())
        # units is int64 (accumulate() normalizes), so the subtraction
        # already yields a fresh contiguous int64 array — no astype copy
        rel = units - self.u_base
        self.u_hi = max(self.u_hi, int(rel.max()))
        sub = None
        if self.SUB == 2:
            # rows with rem >= L - (k-1)*S miss the oldest overlapping
            # window (see partial_agg.cpp header)
            edge = self.spec.length_ms - (self.spec.length_units - 1) * self.spec.slide_ms
            sub = (np.asarray(rem) >= edge).astype(np.uint8)
        lib = _native()
        if lib is not None:
            rel = np.ascontiguousarray(rel, np.int64)
            gid_c = np.ascontiguousarray(gid, np.int32)
            vals_c = np.ascontiguousarray(values64, np.float64)
            cv = (
                None
                if colvalid is None
                else np.ascontiguousarray(colvalid, np.uint8)
            )
            lib.partial_window_agg(
                _ptr(rel), _ptr(sub), _ptr(gid_c), _ptr(vals_c), _ptr(cv),
                n, self.V, self.U_MAX, self.SUB, self.G,
                _ptr(self.row_cnt), _ptr(self.cnt), _ptr(self.sum),
                _ptr(self.mn), _ptr(self.mx),
            )
        else:
            self._add_numpy(rel, sub, gid, values64, colvalid)
        self.rows += n

    def _add_numpy(self, rel, sub, gid, values64, colvalid):
        """Vectorized fallback: bincount for counts/sums, sort+reduceat for
        extrema."""
        ok = (rel >= 0) & (rel < self.U_MAX) & (gid >= 0) & (gid < self.G)
        rel = rel[ok]
        gid = np.asarray(gid)[ok]
        vals = values64[ok]
        s = (sub[ok].astype(np.int64) if sub is not None else 0)
        cell = (rel * self.SUB + s) * self.G + gid
        cells = self.U_MAX * self.SUB * self.G
        self.row_cnt.reshape(-1)[:] += np.bincount(cell, minlength=cells)
        cv = colvalid[ok] if colvalid is not None else None
        order = np.argsort(cell, kind="stable")
        cell_s = cell[order]
        for v in range(self.V):
            x = vals[:, v]
            m = cv[:, v] if cv is not None else None
            cm = cell if m is None else cell[m]
            xm = x if m is None else x[m]
            self.cnt[v].reshape(-1)[:] += np.bincount(cm, minlength=cells)
            self.sum[v].reshape(-1)[:] += np.bincount(
                cm, weights=xm, minlength=cells
            )
            xs = x[order]
            ms = None if m is None else m[order]
            if ms is not None:
                cs2, xs2 = cell_s[ms], xs[ms]
            else:
                cs2, xs2 = cell_s, xs
            if len(cs2):
                starts = np.flatnonzero(np.r_[True, cs2[1:] != cs2[:-1]])
                mins = np.minimum.reduceat(xs2, starts)
                maxs = np.maximum.reduceat(xs2, starts)
                uc = cs2[starts]
                flat_mn = self.mn[v].reshape(-1)
                flat_mx = self.mx[v].reshape(-1)
                flat_mn[uc] = np.minimum(flat_mn[uc], mins)
                flat_mx[uc] = np.maximum(flat_mx[uc], maxs)

    # -- hand-off ------------------------------------------------------
    def is_empty(self) -> bool:
        return self.rows == 0

    def _component_plane(self, c: sa.AggComponent) -> np.ndarray:
        if c.kind == "count" and c.col is None:
            return self.row_cnt
        if c.kind == "count":
            return self.cnt[c.col]
        if c.kind == "sum":
            return self.sum[c.col]
        if c.kind == "min":
            return self.mn[c.col]
        if c.kind == "max":
            return self.mx[c.col]
        raise ValueError(c.kind)

    # counts per cell are shipped as exact-in-f32 integers, so a stripe
    # may never exceed 2^24 rows between merges (backend flushes earlier)
    MAX_STRIPE_ROWS = 1 << 24
    # cap on U*SUB*G cells per stripe: bounds the compacted-transfer
    # bucket so high-cardinality stripes converge on ONE compiled merge
    # program instead of walking a ladder of pow2 sizes
    MAX_STRIPE_CELLS = 1 << 19

    def transfer_buckets(self) -> list[int]:
        """The FIXED set of padded transfer sizes this stripe will ever
        use: {1024, bound/4, bound/2, bound} (deduped, pow2) where bound
        covers the largest possible active-cell count.  A fixed spec-
        derived set — instead of pow2-of-observed-A — means every merge
        program can be compiled at construction: observed sizes vary with
        pacing, and an unseen size mid-stream is a multi-second compile on
        a remote-compile backend."""
        # at least one slide unit's worth of cells: the backend chunks
        # batches so a stripe never exceeds max(one unit, the cell cap)
        bound_cells = min(
            max(self.MAX_STRIPE_CELLS, self.G * self.SUB),
            self.G * self.SUB * self.U_MAX,
        )
        bound = 1 << max(0, (bound_cells - 1)).bit_length()
        out = sorted({1024, max(1024, bound // 4), max(1024, bound // 2), bound})
        return out

    def take_packed(
        self, base_mod: int
    ) -> tuple[np.ndarray, int, int, bool, bool] | None:
        """Compact the stripe into the single int32 matrix the device
        merge op consumes, then reset.

        Returns ``(packed, a_pad, u_base, lean, dense)`` or None when
        empty — ``lean`` says per-column count planes were omitted
        (null-free stripe; the device merge aliases them to the row-count
        plane).  ``packed`` is **int32** — an int32 carrier is immune to
        jnp's x64-off canonicalization, which would silently round an f64
        matrix to f32 and corrupt cell indices beyond 2^24.  Value planes
        are f32 bitcast to int32: one plane per count/min/max component
        (counts are exact in f32 under the MAX_STRIPE_ROWS cap) and TWO
        planes per sum — the f64 host sum split into (hi, lo) f32 so no
        precision is lost in transit.  ``u_base`` and ``base_mod`` ride in
        the two tail slots of row 0.  One matrix → ONE host→device
        transfer per merge.

        Two layouts, chosen per stripe by exact transferred-byte count:

        * **compact** (``dense=False``): ``(P + 1, a_pad + 2)`` — row 0
          holds the active flat cell indices ``((u*SUB)+s)*G + g``
          (pad = −1), value planes follow.  Wins when active cells are
          sparse in the stripe's span.
        * **dense** (``dense=True``): ``(P, a_pad + 2)`` — NO index row;
          cell i is flat index i over the first ``used`` units, pad cells
          carry fold-neutral values (count 0, sum 0, min +inf, max −inf).
          Wins at high density (e.g. 100K live keys in a 131072-wide
          ring: 4 planes × active vs 3 planes × span), and skips the
          host-side gather entirely."""
        if self.rows == 0:
            return None
        used = self.u_hi + 1
        active = np.flatnonzero(self.row_cnt[:used].reshape(-1) > 0)
        A = len(active)
        # lean layout: a null-free stripe's per-column counts equal the
        # row count cell-for-cell, so their planes need not cross the
        # link — the device merge aliases them to the row-count plane
        lean = not self.nulls_seen and sa.lean_possible(self.spec)
        n_planes = self.n_planes(lean)
        # smallest member of the FIXED bucket set that covers A (see
        # transfer_buckets — all merge programs precompiled); the backend's
        # chunking keeps A within the largest bucket, but never crash the
        # stream if an invariant slips — pay a one-off compile instead
        buckets = self.transfer_buckets()
        a_pad = next(
            (b for b in buckets if b >= A),
            1 << (A - 1).bit_length(),
        )
        cells_d = used * self.SUB * self.G
        a_pad_d = next((b for b in buckets if b >= cells_d), None)
        # dense only when a precompiled bucket covers the span AND it
        # moves fewer bytes than compact (index row included)
        if a_pad_d is not None and n_planes * a_pad_d < (n_planes + 1) * a_pad:
            return self._take_packed_dense(
                base_mod, used, a_pad_d, lean, n_planes
            )
        rows: list[np.ndarray] = []
        for c in self.spec.components:
            if c.kind == "sumc":
                continue
            if lean and sa.lean_skippable(c):
                continue
            src = self._component_plane(c)[:used].reshape(-1)[active]
            if c.kind == "sum":
                hi, lo = self._split_sum(src)
                rows.append(hi)
                rows.append(lo)
            else:
                rows.append(
                    np.ascontiguousarray(src, np.float64)
                    .astype(np.float32)
                    .view(np.int32)
                )
        packed = np.zeros((len(rows) + 1, a_pad + 2), np.int32)
        packed[0, :A] = active
        packed[0, A:a_pad] = -1
        packed[0, a_pad] = self.u_base
        packed[0, a_pad + 1] = base_mod
        for i, r in enumerate(rows):
            packed[i + 1, :A] = r
        u_base = self._reset_after_take(used)
        return packed, a_pad, u_base, lean, False

    def n_planes(self, lean: bool) -> int:
        """Value planes in a packed stripe of this spec: two per sum
        (hi/lo split), one per other component; lean omits per-column
        count planes (aliased to row count device-side)."""
        return sum(
            2 if c.kind == "sum" else 1
            for c in self.spec.components
            if c.kind != "sumc" and not (lean and sa.lean_skippable(c))
        )

    def dense_noop(self, a_pad: int, lean: bool) -> np.ndarray:
        """An all-padding DENSE packed matrix (for merge-program prewarm):
        every cell fold-neutral — count/sum planes zero, min/max planes
        +inf/−inf bit patterns.  Must stay in lockstep with
        ``_take_packed_dense``'s plane order (it is derived from the same
        component walk)."""
        packed = np.zeros((self.n_planes(lean), a_pad + 2), np.int32)
        pi = 0
        for c in self.spec.components:
            if c.kind == "sumc" or (lean and sa.lean_skippable(c)):
                continue
            if c.kind == "sum":
                pi += 2
                continue
            if c.kind in NEUTRAL_BITS:
                packed[pi, :a_pad] = NEUTRAL_BITS[c.kind]
            pi += 1
        return packed

    def _split_sum(self, src: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """(hi, lo) f32 split of a host f64 sum plane, int32-bitcast —
        exact for f32 accumulators, ~1e-14 relative for f64 ones (the
        remote runtime decomposes f64, so raw-bit f64 transport is not
        portable)."""
        # overflow-to-inf in the cast and inf - inf below are deliberate
        # (handled by the nonfin branch); suppress the spurious
        # RuntimeWarnings
        with np.errstate(invalid="ignore", over="ignore"):
            hi = src.astype(np.float32)
            lo = (src - hi.astype(np.float64)).astype(np.float32)
        # a finite f64 sum beyond f32 range becomes (±inf, ∓inf) and would
        # fold to NaN; ±inf parity with an overflowed f32 accumulator is
        # right for f32 state, but an f64 accumulator would have held the
        # value — refuse loudly rather than corrupt it
        nonfin = ~np.isfinite(hi)
        if nonfin.any():
            over = nonfin & np.isfinite(src)
            if over.any() and self.spec.accum_dtype == sa.jnp.float64:
                raise OverflowError(
                    "partial_merge cannot transport f64 sums "
                    "beyond float32 range (~3.4e38); use "
                    "device_strategy='scatter' for this workload"
                )
            # overflow (finite src) and genuine ±inf/NaN sums both leave
            # lo meaningless (inf - inf = NaN): zero it so the device fold
            # yields ±inf/NaN parity with the scatter path instead of
            # poisoning cells with NaN
            lo[nonfin] = 0.0
        return hi.view(np.int32), lo.view(np.int32)

    def _take_packed_dense(
        self, base_mod: int, used: int, a_pad: int, lean: bool, n_planes: int
    ) -> tuple[np.ndarray, int, int, bool, bool]:
        """Dense (index-free) pack: plane p at row p, cell i = flat index
        i over the first ``used`` units, pad cells fold-neutral.  No host
        gather — straight reshape + dtype conversion."""
        cells = used * self.SUB * self.G
        packed = np.zeros((n_planes, a_pad + 2), np.int32)
        pi = 0
        for c in self.spec.components:
            if c.kind == "sumc":
                continue
            if lean and sa.lean_skippable(c):
                continue
            src = self._component_plane(c)[:used].reshape(-1)
            if c.kind == "sum":
                hi, lo = self._split_sum(src)
                packed[pi, :cells] = hi
                packed[pi + 1, :cells] = lo
                pi += 2
                continue
            packed[pi, :cells] = (
                np.ascontiguousarray(src, np.float64)
                .astype(np.float32)
                .view(np.int32)
            )
            if c.kind in NEUTRAL_BITS and cells < a_pad:
                packed[pi, cells:a_pad] = NEUTRAL_BITS[c.kind]
            pi += 1
        packed[0, a_pad] = self.u_base
        packed[0, a_pad + 1] = base_mod
        u_base = self._reset_after_take(used)
        return packed, a_pad, u_base, lean, True

    def _reset_after_take(self, used: int) -> int:
        """Shared post-pack stripe reset; returns the taken u_base."""
        u_base = self.u_base
        self.u_base = None
        self.u_hi = 0
        self.rows = 0
        # reset in place, touching only the unit rows this stripe used:
        # re-zeroing the full (V, U_MAX, SUB, G) planes costs ~100ms per
        # flush at 100K-key cardinality, while a stripe typically spans
        # 1-2 slide units
        self.row_cnt[:used] = 0
        self.cnt[:, :used] = 0
        self.sum[:, :used] = 0.0
        self.mn[:, :used] = np.inf
        self.mx[:, :used] = -np.inf
        self.nulls_seen = False
        return u_base
