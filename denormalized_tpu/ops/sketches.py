"""Mergeable sketch kernels — constant-state approximate aggregates.

One module owns every sketch in the engine (ISSUE 18 dedup): the
intern-time Space-Saving / HLL summaries the state observatory runs on
every stateful operator (moved here from obs/statewatch.py, re-exported
there), the UDAF-fallback HLL shim (api/builtin_accumulators.py), and
the slice-store **sketch planes** that make ``approx_distinct`` /
``approx_top_k`` / ``approx_percentile_cont`` first-class mergeable
window aggregates on :class:`~denormalized_tpu.ops.slice_store
.SliceStore`.

Design rules (docs/approx_aggregates.md):

- **Deterministic, stable, never salted.**  Hashes are splitmix64 over
  canonical 64-bit value patterns (numeric lanes) or 8-byte blake2b
  digests (object lanes) — process-independent, so kill/restore and
  shared-vs-independent runs produce byte-identical sketch state.
  Python's salted ``hash()`` never appears.
- **Mergeable by construction.**  Every per-(unit, gid) sketch folds
  across slice units with a bounded-error merge: HLL registers fold by
  elementwise max (associative + commutative — fold order free),
  Space-Saving summaries by the mergeable-summaries union (absent-key
  mass bounded by the other side's min slot count), KLL compactor
  levels by level-aligned re-insertion.  The slice store folds units in
  ascending order, so the fold tree is a pure function of the feed.
- **O(1) state per gid in value cardinality** — the whole point: an
  HLL plane row is ``2^p`` bytes no matter how many distinct values it
  absorbed; the exact accumulators grow without bound.

Import discipline: numpy / math / hashlib ONLY.  The soak harness's
jax-free parent process loads this file by path to recompute golden
sketch answers — a jax (or package-relative heavy) import here breaks
that and the doctor's early-import paths.
"""

from __future__ import annotations

import hashlib
import math

import numpy as np

__all__ = [
    "HLL_P",
    "KLL_K",
    "Hll",
    "HllSpec",
    "KllSpec",
    "SketchSpec",
    "SpaceSaving",
    "TopKSpec",
    "blake2b64",
    "hll_accumulate",
    "hll_estimate",
    "popcount64",
    "ss_admit",
    "stable_hash64",
    "topk_merge",
    "u64_bit_length",
]

#: default HLL precision for the approx_distinct slice lane: 2^12 = 4096
#: one-byte registers per (unit, gid) cell, ~1.6% standard error
HLL_P = 12

#: KLL/compactor level capacity: rank error after n inserts is bounded by
#: the sketch's own ``err`` accounting (one unit of level weight per
#: compaction), roughly ``log2(n / K) / K`` relative — ~2.1% at n = 1M
KLL_K = 512

_U64 = np.uint64
_M1 = np.uint64(0x5555555555555555)
_M2 = np.uint64(0x3333333333333333)
_M4 = np.uint64(0x0F0F0F0F0F0F0F0F)
_H01 = np.uint64(0x0101010101010101)
#: canonical quiet-NaN bit pattern (float64('nan') on every platform we
#: target) — all NaNs hash identically, mirroring the interner's NaN key
_NAN64 = np.float64("nan")


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer, vectorized (uint64 wraparound arithmetic)."""
    z = x + np.uint64(0x9E3779B97F4A7C15)
    z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
    z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
    return z ^ (z >> np.uint64(31))


def popcount64(x: np.ndarray) -> np.ndarray:
    """Vectorized 64-bit population count (SWAR) — exact for the full
    uint64 range, unlike any float round-trip."""
    x = x - ((x >> np.uint64(1)) & _M1)
    x = (x & _M2) + ((x >> np.uint64(2)) & _M2)
    x = (x + (x >> np.uint64(4))) & _M4
    return (x * _H01) >> np.uint64(56)


def u64_bit_length(x: np.ndarray) -> np.ndarray:
    """Exact vectorized ``int.bit_length`` for uint64 arrays (0 → 0).

    Bit-smear then popcount — no float64 log2, so ranks are exact for
    ANY register width (the float path restricted the statewatch HLL to
    p >= 12; this lifts it, and the p=11 accumulator shim rides it)."""
    x = x | (x >> np.uint64(1))
    x = x | (x >> np.uint64(2))
    x = x | (x >> np.uint64(4))
    x = x | (x >> np.uint64(8))
    x = x | (x >> np.uint64(16))
    x = x | (x >> np.uint64(32))
    return popcount64(x)


def blake2b64(v) -> int:
    """Stable 8-byte blake2b digest of one Python value — the object-lane
    hash, and byte-compatible with the historical
    ``ApproxDistinctAccumulator._hash64`` canonical encoding."""
    if isinstance(v, bytes):
        b = v
    elif isinstance(v, str):
        b = v.encode()
    else:
        b = repr(v).encode()
    return int.from_bytes(hashlib.blake2b(b, digest_size=8).digest(), "little")


def _hash_object64(arr, valid: np.ndarray | None = None) -> np.ndarray:
    """Per-UNIQUE-value blake2b over an object column (deliberately
    unpinned: it loops distinct values, never rows — the
    SliceStore.accumulate precedent; repeated values pay one digest)."""
    obj = np.asarray(arr, dtype=object)
    n = len(obj)
    out = np.zeros(n, dtype=np.uint64)
    if valid is None:
        idx = None
        sub = obj
    else:
        idx = np.flatnonzero(valid)
        sub = obj[idx]
    if not len(sub):
        return out
    # None entries can't sort against other objects (np.unique would
    # raise); peel them off and hash them like any value — blake2b of
    # repr(None) — matching the exact-accumulator fallback, which feeds
    # unmasked Nones straight into its own blake2b
    none_mask = np.equal(sub, None)
    if none_mask.any():
        none_idx = np.flatnonzero(none_mask)
        tgt = none_idx if idx is None else idx[none_idx]
        out[tgt] = np.uint64(blake2b64(None))
        keep = np.flatnonzero(~none_mask)
        idx = keep if idx is None else idx[keep]
        sub = sub[keep]
        if not len(sub):
            return out
    uniq, inv = np.unique(sub, return_inverse=True)
    uh = np.empty(len(uniq), dtype=np.uint64)
    for i, v in enumerate(uniq.tolist()):
        uh[i] = np.uint64(blake2b64(v))
    if idx is None:
        out[:] = uh[inv]
    else:
        out[idx] = uh[inv]
    return out


def stable_hash64(col, valid: np.ndarray | None = None) -> np.ndarray:
    """Process-independent uint64 hash of one column (never salted).

    Numeric lanes canonicalize to a 64-bit pattern (−0.0 → +0.0, one
    NaN pattern; ints through int64 bits — integers beyond 2^53 keep
    exact identity, unlike a float round-trip) and run splitmix64 in
    one vectorized pass.  Object lanes dispatch to the per-unique
    blake2b path.  Rows where ``valid`` is False hash to an arbitrary
    value the caller must mask — validity is the caller's mask, not
    ours."""
    arr = col if isinstance(col, np.ndarray) else np.asarray(col)
    kind = arr.dtype.kind
    if kind in "iub":
        bits = arr.astype(np.int64, copy=False).view(np.uint64)
    elif kind == "f":
        x = arr.astype(np.float64, copy=True)
        zero = x == 0.0
        x[zero] = 0.0
        x[np.isnan(x)] = _NAN64
        bits = x.view(np.uint64)
    elif kind in "Mm":
        bits = arr.view(np.int64).view(np.uint64)
    else:
        return _hash_object64(arr, valid)
    return _mix64(bits)


def _aggregate_gids(g: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """(unique gids, per-gid counts) of one batch.  Dense gid spaces
    (the normal case — interners hand out consecutive ids) take the
    O(n + max_gid) bincount path instead of the O(n log n) sort that
    ``np.unique`` costs; the sketch update must stay microseconds at
    8k-row batches (the run_obs_overhead gate covers it)."""
    mx = int(g.max())
    if mx < 4 * len(g) + 1024:
        bc = np.bincount(g)
        u = np.nonzero(bc)[0]
        return u, bc[u]
    u, c = np.unique(g.astype(np.int64, copy=False), return_counts=True)
    return u, c


# -- Space-Saving heavy hitters ------------------------------------------


def ss_admit(
    keys: np.ndarray, counts: np.ndarray, errs: np.ndarray,
    u: np.ndarray, c: np.ndarray,
) -> None:
    """Vectorized Space-Saving admission of pre-aggregated (key, count)
    pairs into one summary's slot arrays, in place.  Hits scatter-add;
    misses take the lowest-count victims, inheriting the evicted count
    as their error bound — ``count - err <= true <= count`` for every
    tracked key.  Shared by :class:`SpaceSaving` (statewatch's
    intern-time sketch) and the slice store's per-gid
    :class:`TopKSpec` planes."""
    k = keys
    order = np.argsort(k, kind="stable")
    ks = k[order]
    pos = np.minimum(np.searchsorted(ks, u), len(ks) - 1)
    hit = ks[pos] == u
    np.add.at(counts, order[pos[hit]], c[hit])
    miss = ~hit
    if miss.any():
        mu = u[miss]
        mc = c[miss]
        # largest newcomers first when more new keys than slots
        mo = np.argsort(-mc, kind="stable")
        take = min(len(mu), len(k))
        mu = mu[mo[:take]]
        mc = mc[mo[:take]]
        victims = np.argsort(counts, kind="stable")[:take]
        base = counts[victims]
        # admission guard: sequential Space-Saving only ever evicts
        # the MINIMUM slot, whose count stays near the smallest base
        # as it churns — so a newcomer may only take a victim whose
        # count is within its own batch mass of that minimum.
        # Without this, a batch with >= K new keys would pair its
        # smallest newcomer against the LARGEST victim and evict a
        # genuine heavy hitter (caught by the skew smoke test).
        ok = base <= base[0] + mc
        if not ok.all():
            victims = victims[ok]
            mu = mu[ok]
            mc = mc[ok]
            base = base[ok]
        keys[victims] = mu
        errs[victims] = base
        counts[victims] = base + mc


class SpaceSaving:
    """Vectorized Space-Saving (Metwally et al.) over dense int gids.

    K slots of (key, count, err).  ``update`` aggregates the batch with
    one ``np.unique`` and applies hits as a scatter-add; new keys
    replace the lowest-count slots, inheriting the evicted count as
    their error bound — ``count - err <= true count <= count`` for
    every tracked key.  All numpy, no per-row Python (pinned by
    DNZ-H001 via hotpaths.toml).

    With ``decay_every`` > 0 the sketch is WINDOWED: every
    ``decay_every`` rows fed, counts, error bounds, and the total are
    scaled by ``decay_factor`` — an exponential moving window with a
    half-life of ``decay_every / (1 - decay_factor) * ln2`` rows at the
    default factor ½.  Shares then track RECENT traffic: a retired
    celebrity's share decays geometrically instead of only as
    ``1/total`` growth, so the join adaptation policy's fold trigger
    fires promptly instead of holding stale heavy hitters for the rest
    of the run.  Default 0 (off) preserves the monotone sketch every
    other consumer (skew verdicts, hot-key gauges) was tuned against;
    the overestimate invariant ``count - err <= true(window)`` is
    preserved under decay because both sides of the bound scale
    together.
    """

    __slots__ = (
        "keys", "counts", "errs", "total", "decay_every", "decay_factor",
        "_since_decay",
    )

    def __init__(
        self,
        capacity: int = 64,
        *,
        decay_every: int = 0,
        decay_factor: float = 0.5,
    ) -> None:
        k = max(int(capacity), 8)
        self.keys = np.full(k, -1, dtype=np.int64)
        self.counts = np.zeros(k, dtype=np.int64)
        self.errs = np.zeros(k, dtype=np.int64)
        self.total = 0  # rows in the (possibly decayed) window
        self.decay_every = max(int(decay_every), 0)
        if not 0.0 < float(decay_factor) < 1.0:
            raise ValueError("decay_factor must be in (0, 1)")
        self.decay_factor = float(decay_factor)
        self._since_decay = 0

    def update(self, gids: np.ndarray) -> None:
        g = np.asarray(gids, dtype=np.int64)
        if len(g) == 0:
            return
        self.update_aggregated(*_aggregate_gids(g), len(g))

    def decay(self) -> None:
        """One decay step: scale counts, errors, and the total by
        ``decay_factor``; slots decayed to zero free up for new keys
        (their key stays until evicted — a zero-count slot is the first
        victim the admission pass picks)."""
        f = self.decay_factor
        self.counts = (self.counts * f).astype(np.int64)
        self.errs = (self.errs * f).astype(np.int64)
        self.total = int(self.total * f)
        self._since_decay = 0

    def update_aggregated(
        self, u: np.ndarray, c: np.ndarray, rows: int
    ) -> None:
        """Batch update from pre-aggregated (unique gids, counts) —
        the shape :func:`_aggregate_gids` produces once per batch so the
        HLL can share the same reduction."""
        if self.decay_every:
            self._since_decay += int(rows)
            if self._since_decay >= self.decay_every:
                self.decay()
        self.total += int(rows)
        ss_admit(self.keys, self.counts, self.errs, u, c)

    def top(self, k: int = 8) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """(gids, counts, errs) of the top-k tracked keys, count-desc."""
        live = np.nonzero(self.keys >= 0)[0]
        if len(live) == 0:
            e = np.empty(0, dtype=np.int64)
            return e, e.copy(), e.copy()
        order = live[np.argsort(-self.counts[live], kind="stable")][:k]
        return (
            self.keys[order].copy(),
            self.counts[order].copy(),
            self.errs[order].copy(),
        )

    def reset(self) -> None:
        """Drop all tracked keys (a re-intern invalidated the gid space);
        the sketch re-warms from subsequent traffic."""
        self.keys.fill(-1)
        self.counts.fill(0)
        self.errs.fill(0)
        self.total = 0
        self._since_decay = 0


def topk_merge(
    ka: np.ndarray, ca: np.ndarray, ea: np.ndarray,
    kb: np.ndarray, cb: np.ndarray, eb: np.ndarray,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Row-wise mergeable-summaries union of two ``(G, S)`` Space-Saving
    planes (Agarwal et al.): keys in both sum counts and error bounds;
    a key tracked on one side only adds the OTHER side's minimum slot
    count (its maximum possible untracked mass there — 0 while that
    side still has empty slots) to both count and err; the union keeps
    the top S by count.  ``count - err <= true <= count`` is preserved
    for every retained key.  Fully vectorized across gid rows (axis-1
    sorts); deterministic: ties in count keep key-ascending order."""
    g, s = ka.shape
    sent = np.int64(np.iinfo(np.int64).max)
    min_a = np.where((ka >= 0).all(axis=1), ca.min(axis=1), 0)
    min_b = np.where((kb >= 0).all(axis=1), cb.min(axis=1), 0)
    keys = np.concatenate((ka, kb), axis=1)
    cnts = np.concatenate((ca, cb), axis=1).astype(np.int64)
    errs = np.concatenate((ea, eb), axis=1).astype(np.int64)
    from_b = np.zeros((g, 2 * s), dtype=bool)
    from_b[:, s:] = True
    empty = keys < 0
    keys = np.where(empty, sent, keys)
    cnts = np.where(empty, 0, cnts)
    errs = np.where(empty, 0, errs)
    ordk = np.argsort(keys, axis=1, kind="stable")
    ks = np.take_along_axis(keys, ordk, axis=1)
    cs = np.take_along_axis(cnts, ordk, axis=1)
    es = np.take_along_axis(errs, ordk, axis=1)
    fb = np.take_along_axis(from_b, ordk, axis=1)
    # a key occurs at most twice (once per side): dup marks the second
    # occurrence, which folds into the first and is then blanked
    dup = np.zeros_like(ks, dtype=bool)
    dup[:, 1:] = (ks[:, 1:] == ks[:, :-1]) & (ks[:, 1:] != sent)
    cs2 = cs.copy()
    es2 = es.copy()
    cs2[:, :-1] += np.where(dup[:, 1:], cs[:, 1:], 0)
    es2[:, :-1] += np.where(dup[:, 1:], es[:, 1:], 0)
    pair_head = np.zeros_like(dup)
    pair_head[:, :-1] = dup[:, 1:]
    single = (~dup) & (~pair_head) & (ks != sent)
    other_min = np.where(fb, min_a[:, None], min_b[:, None])
    cs2 += np.where(single, other_min, 0)
    es2 += np.where(single, other_min, 0)
    ks2 = np.where(dup, sent, ks)
    dead = ks2 == sent
    cs2 = np.where(dead, 0, cs2)
    es2 = np.where(dead, 0, es2)
    # top-S by count desc; ks2 is key-ascending per row, so a stable
    # sort on -count breaks ties key-ascending — deterministic
    ords = np.argsort(-cs2, axis=1, kind="stable")[:, :s]
    ko = np.take_along_axis(ks2, ords, axis=1)
    co = np.take_along_axis(cs2, ords, axis=1)
    eo = np.take_along_axis(es2, ords, axis=1)
    gone = ko == sent
    ko = np.where(gone, np.int64(-1), ko)
    co = np.where(gone, 0, co)
    eo = np.where(gone, 0, eo)
    return ko, co, eo


# -- HyperLogLog cardinality ---------------------------------------------


def hll_accumulate(
    plane: np.ndarray, gids: np.ndarray, hashes: np.ndarray
) -> None:
    """Batch max-insert into a ``(cap, 2^p)`` register plane, in place.

    Register index = top p hash bits, rank = leading-zero count of the
    remaining ``64-p`` bits + 1 (exact via :func:`u64_bit_length`).
    One ``np.sort`` over packed ``(cell << 6) | rho`` keys turns the
    scatter-max into last-of-run picks + one bounded fancy-index max —
    no ``ufunc.at``.  Max is associative and commutative, so the result
    is independent of row order AND of how the batch was split across
    calls — the property the slice fold and the soak golden rely on."""
    cap, m = plane.shape
    p = int(m - 1).bit_length()
    width = np.uint64(64 - p)
    idx = (hashes >> width).astype(np.int64)
    w = hashes & ((np.uint64(1) << width) - np.uint64(1))
    rho = (width + np.uint64(1) - u64_bit_length(w)).astype(np.uint64)
    flat = (gids.astype(np.int64) * m + idx).astype(np.uint64)
    key = (flat << np.uint64(6)) | rho
    ks = np.sort(key)
    cells = (ks >> np.uint64(6)).astype(np.int64)
    pick = np.concatenate(
        (np.flatnonzero(cells[1:] != cells[:-1]),
         np.asarray([len(cells) - 1], dtype=np.int64))
    )
    cid = cells[pick]
    r = (ks[pick] & np.uint64(63)).astype(plane.dtype)
    pf = plane.reshape(-1)
    pf[cid] = np.maximum(pf[cid], r)


def hll_estimate(plane: np.ndarray) -> np.ndarray:
    """Per-gid cardinality estimates for a ``(G, 2^p)`` register plane:
    the standard HLL harmonic-mean estimator with the linear-counting
    small-range correction — the same formula (and therefore the same
    answer) as :meth:`Hll.estimate`, vectorized across rows."""
    g, m = plane.shape
    alpha = 0.7213 / (1.0 + 1.079 / m)
    regs = plane.astype(np.float64)
    est = alpha * m * m / np.sum(np.exp2(-regs), axis=1)
    zeros = np.count_nonzero(plane == 0, axis=1)
    lc = m * np.log(m / np.maximum(zeros, 1).astype(np.float64))
    out = np.where((est <= 2.5 * m) & (zeros > 0), lc, est)
    return np.rint(out).astype(np.int64)


class Hll:
    """HyperLogLog over dense int gids; standard error 1.04/sqrt(2**p).

    The register update is one vectorized hash + scatter-max via
    :func:`hll_accumulate` on a single-row plane view.  Ranks come from
    the exact bit-smear :func:`u64_bit_length` (identical to the former
    float64 ``floor(log2)`` for every width that was legal then), so
    any p in [4, 16] is exact — the p >= 12 float-mantissa restriction
    is gone.
    """

    __slots__ = ("p", "m", "registers", "_alpha")

    def __init__(self, p: int = 12) -> None:
        if not 4 <= p <= 16:
            raise ValueError("Hll precision p must be in [4, 16]")
        self.p = p
        self.m = 1 << p
        self.registers = np.zeros(self.m, dtype=np.uint8)
        self._alpha = 0.7213 / (1.0 + 1.079 / self.m)

    def update(self, gids: np.ndarray) -> None:
        g = np.asarray(gids)
        if len(g) == 0:
            return
        hll_accumulate(
            self.registers.reshape(1, -1),
            np.zeros(len(g), dtype=np.int64),
            _mix64(g.astype(np.uint64)),
        )

    def estimate(self) -> float:
        regs = self.registers.astype(np.float64)
        est = self._alpha * self.m * self.m / float(np.sum(np.exp2(-regs)))
        zeros = int(np.count_nonzero(self.registers == 0))
        if est <= 2.5 * self.m and zeros:
            # small-range (linear counting) correction
            return self.m * math.log(self.m / zeros)
        return est

    def reset(self) -> None:
        self.registers.fill(0)


# -- slice-store sketch planes -------------------------------------------


class SketchSpec:
    """Plane layout + kernels for one sketch family on the slice store.

    A spec is STATELESS — sketch state lives in each slice unit's label
    dict next to the scalar AggComponent arrays, under labels prefixed
    ``<sid>|``.  The spec declares the layout (:meth:`init_planes`,
    :meth:`alloc_label`, :meth:`fill_for`), the per-batch per-unit
    accumulate kernel, the cross-unit fold, and finalization; the store
    owns capacity growth, snapshot, restore, and byte accounting
    generically through those hooks.  ``uses`` names the per-row source
    lane the exec must feed: ``"hash"`` (stable uint64 value hashes),
    ``"vid"`` (dense value-interner ids), or ``"f64"`` (the shared
    float64 value matrix)."""

    kind = ""
    uses = "f64"

    def __init__(self, sid: str, vcol: int) -> None:
        self.sid = sid
        self.vcol = int(vcol)

    def key(self) -> tuple:
        """Dedup identity across subscribers (kind, value column, params)."""
        raise NotImplementedError

    def owns(self, label: str) -> bool:
        return label.startswith(self.sid + "|")

    def init_planes(self, cap: int) -> dict[str, np.ndarray]:
        raise NotImplementedError

    def alloc_label(self, label: str, cap: int) -> np.ndarray:
        """Fresh plane for ``label`` at capacity ``cap`` (restore of
        dynamically created labels)."""
        raise NotImplementedError

    def fill_for(self, label: str):
        """Neutral fill value for capacity growth of ``label``."""
        raise NotImplementedError

    def accumulate_unit(self, slot, cap, gids, col, valid) -> None:
        """Fold one unit's rows (gids ascending — the store's shared
        sort order) into the unit's planes."""
        raise NotImplementedError

    def fold(self, slots: list[dict], cap: int) -> dict[str, np.ndarray]:
        """Merge this spec's planes across ``slots`` (ascending unit
        order) into fresh arrays keyed by the same labels."""
        raise NotImplementedError


class HllSpec(SketchSpec):
    """``approx_distinct``: one ``(cap, 2^p)`` int8 register plane."""

    kind = "hll"
    uses = "hash"

    def __init__(self, sid: str, vcol: int, p: int = HLL_P) -> None:
        super().__init__(sid, vcol)
        self.p = int(p)
        self.m = 1 << self.p

    def key(self) -> tuple:
        return ("hll", self.vcol, self.p)

    @property
    def _label(self) -> str:
        return f"{self.sid}|regs"

    def init_planes(self, cap: int) -> dict[str, np.ndarray]:
        return {self._label: np.zeros((cap, self.m), dtype=np.int8)}

    def alloc_label(self, label: str, cap: int) -> np.ndarray:
        return np.zeros((cap, self.m), dtype=np.int8)

    def fill_for(self, label: str):
        return 0

    def accumulate_unit(self, slot, cap, gids, col, valid) -> None:
        if not valid.all():
            gids = gids[valid]
            col = col[valid]
        if not len(gids):
            return
        hll_accumulate(slot[self._label], gids, col)

    def fold(self, slots: list[dict], cap: int) -> dict[str, np.ndarray]:
        out = slots[0][self._label].copy()
        for s in slots[1:]:
            np.maximum(out, s[self._label], out=out)
        return {self._label: out}

    def finalize(self, rows: dict, gids: np.ndarray) -> np.ndarray:
        return hll_estimate(rows[self._label][gids])


class TopKSpec(SketchSpec):
    """``approx_top_k``: per-gid Space-Saving planes over dense value
    ids — ``(cap, S)`` keys/counts/errs with S = max(64, 8k) slots so
    the reported top k sit well inside the tracked set."""

    kind = "topk"
    uses = "vid"

    def __init__(self, sid: str, vcol: int, k: int) -> None:
        super().__init__(sid, vcol)
        self.k = int(k)
        if self.k <= 0:
            raise ValueError(f"approx_top_k needs k >= 1, got {k}")
        self.slots = max(64, 8 * self.k)

    def key(self) -> tuple:
        return ("topk", self.vcol, self.k)

    def init_planes(self, cap: int) -> dict[str, np.ndarray]:
        return {
            f"{self.sid}|k": np.full((cap, self.slots), -1, dtype=np.int64),
            f"{self.sid}|c": np.zeros((cap, self.slots), dtype=np.int64),
            f"{self.sid}|e": np.zeros((cap, self.slots), dtype=np.int64),
        }

    def alloc_label(self, label: str, cap: int) -> np.ndarray:
        fill = self.fill_for(label)
        return np.full((cap, self.slots), fill, dtype=np.int64)

    def fill_for(self, label: str):
        return -1 if label.endswith("|k") else 0

    def accumulate_unit(self, slot, cap, gids, col, valid) -> None:
        g = gids[valid].astype(np.int64)
        if not len(g):
            return
        v = col[valid].astype(np.int64)
        mult = np.int64(int(v.max()) + 1)
        ks = np.sort(g * mult + v)
        edges = np.flatnonzero(ks[1:] != ks[:-1]) + 1
        starts = np.concatenate((np.zeros(1, dtype=np.int64), edges))
        cnts = np.diff(np.append(starts, len(ks)))
        pk = ks[starts]
        pg = pk // mult
        pv = pk % mult
        ka = slot[f"{self.sid}|k"]
        ca = slot[f"{self.sid}|c"]
        ea = slot[f"{self.sid}|e"]
        ue = np.flatnonzero(pg[1:] != pg[:-1]) + 1
        us = np.concatenate((np.zeros(1, dtype=np.int64), ue))
        uend = np.append(ue, len(pg))
        # iterates distinct gids present in the unit, never rows — the
        # SliceStore.accumulate precedent; each admission is the
        # vectorized ss_admit kernel over that gid's slot row views
        for i, gg in enumerate(pg[us].tolist()):
            lo, hi = int(us[i]), int(uend[i])
            ss_admit(ka[gg], ca[gg], ea[gg], pv[lo:hi], cnts[lo:hi])

    def fold(self, slots: list[dict], cap: int) -> dict[str, np.ndarray]:
        ka = slots[0][f"{self.sid}|k"].copy()
        ca = slots[0][f"{self.sid}|c"].copy()
        ea = slots[0][f"{self.sid}|e"].copy()
        for s in slots[1:]:
            ka, ca, ea = topk_merge(
                ka, ca, ea,
                s[f"{self.sid}|k"], s[f"{self.sid}|c"], s[f"{self.sid}|e"],
            )
        return {f"{self.sid}|k": ka, f"{self.sid}|c": ca, f"{self.sid}|e": ea}

    def cell_top(
        self, keys_row: np.ndarray, counts_row: np.ndarray,
        errs_row: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Top-k (vids, counts, errs) of one gid's summary, count-desc;
        ties keep slot order, which the fold makes deterministic."""
        live = np.flatnonzero((keys_row >= 0) & (counts_row > 0))
        order = live[np.argsort(-counts_row[live], kind="stable")][: self.k]
        return keys_row[order], counts_row[order], errs_row[order]


class KllSpec(SketchSpec):
    """``approx_percentile_cont`` / ``approx_median``: a deterministic
    compactor (MRL/KLL-style) quantile sketch per gid.

    Level ℓ holds up to K values of weight ``2^ℓ`` in a lazily
    allocated ``(cap, K)`` plane.  Overflow compacts: sort the level,
    keep the odd-indexed half of the even-length prefix at doubled
    weight one level up (any odd leftover stays).  Each compaction of
    level ℓ shifts any rank estimate by at most ``2^ℓ``; the per-gid
    ``err`` plane accumulates exactly that, so the sketch SELF-REPORTS
    a worst-case rank-error bound the test suite asserts against.
    Folding re-inserts the source's levels at their own level (weight
    preserved) and adds the error accounts — mergeability by
    re-insertion.  With level capacity K the bound after n inserts is
    ~``n · log2(n/K) / K`` absolute rank, i.e. ``log2(n/K)/K``
    relative (~2.1% at n = 1M for K = 512).  Deterministic keep-odd
    compaction — no RNG — so shared/independent/restored runs agree
    byte-for-byte."""

    kind = "kll"
    uses = "f64"

    def __init__(self, sid: str, vcol: int, K: int = KLL_K) -> None:
        super().__init__(sid, vcol)
        self.K = int(K)

    def key(self) -> tuple:
        return ("kll", self.vcol, self.K)

    def init_planes(self, cap: int) -> dict[str, np.ndarray]:
        return {f"{self.sid}|err": np.zeros(cap, dtype=np.int64)}

    def alloc_label(self, label: str, cap: int) -> np.ndarray:
        tail = label[len(self.sid) + 1:]
        if tail.startswith("v"):
            return np.full((cap, self.K), np.nan, dtype=np.float64)
        return np.zeros(cap, dtype=np.int64)

    def fill_for(self, label: str):
        tail = label[len(self.sid) + 1:]
        return np.nan if tail.startswith("v") else 0

    def _level(self, slot, lv: int, cap: int):
        vl = f"{self.sid}|v{lv}"
        cl = f"{self.sid}|c{lv}"
        if vl not in slot:
            slot[vl] = np.full((cap, self.K), np.nan, dtype=np.float64)
            slot[cl] = np.zeros(cap, dtype=np.int64)
        return slot[vl], slot[cl]

    def _insert_cell(self, slot, cap, gi: int, vals: np.ndarray, lv: int):
        err = slot[f"{self.sid}|err"]
        pend = np.asarray(vals, dtype=np.float64)
        while len(pend):
            v_arr, c_arr = self._level(slot, lv, cap)
            cnt = int(c_arr[gi])
            buf = np.concatenate((v_arr[gi, :cnt], pend)) if cnt else pend
            if len(buf) <= self.K:
                v_arr[gi, : len(buf)] = buf
                c_arr[gi] = len(buf)
                return
            buf = np.sort(buf, kind="stable")
            m2 = len(buf) - (len(buf) & 1)
            keep = buf[m2:]
            v_arr[gi, :] = np.nan
            v_arr[gi, : len(keep)] = keep
            c_arr[gi] = len(keep)
            err[gi] += np.int64(1) << np.int64(lv)
            pend = buf[1:m2:2]
            lv += 1

    def accumulate_unit(self, slot, cap, gids, col, valid) -> None:
        g = gids[valid]
        if not len(g):
            return
        v = col[valid]
        edges = np.flatnonzero(g[1:] != g[:-1]) + 1
        starts = np.concatenate((np.zeros(1, dtype=np.int64), edges))
        ends = np.append(edges, len(g))
        # distinct gids per unit, never rows (accumulate precedent);
        # the inner work is one sort per compaction cascade
        for i, gg in enumerate(g[starts].tolist()):
            self._insert_cell(
                slot, cap, int(gg), v[int(starts[i]):int(ends[i])], 0
            )

    def _levels_of(self, rows: dict) -> list[tuple[np.ndarray, np.ndarray]]:
        out = []
        lv = 0
        while f"{self.sid}|v{lv}" in rows:
            out.append((rows[f"{self.sid}|v{lv}"], rows[f"{self.sid}|c{lv}"]))
            lv += 1
        return out

    def fold(self, slots: list[dict], cap: int) -> dict[str, np.ndarray]:
        out: dict[str, np.ndarray] = {
            f"{self.sid}|err": slots[0][f"{self.sid}|err"].copy()
        }
        for vl, cl in self._levels_of(slots[0]):
            lv = len([k for k in out if k.startswith(f"{self.sid}|v")])
            out[f"{self.sid}|v{lv}"] = vl.copy()
            out[f"{self.sid}|c{lv}"] = cl.copy()
        err_out = out[f"{self.sid}|err"]
        for s in slots[1:]:
            levels = self._levels_of(s)
            s_err = s[f"{self.sid}|err"]
            act = s_err > 0
            for _vl, cl in levels:
                act = act | (cl > 0)
            for gi in np.flatnonzero(act).tolist():
                for lv, (vl, cl) in enumerate(levels):
                    c = int(cl[gi])
                    if c:
                        self._insert_cell(out, cap, gi, vl[gi, :c], lv)
                err_out[gi] += s_err[gi]
        return out

    def finalize_quantile(
        self, rows: dict, gids: np.ndarray, q: float
    ) -> np.ndarray:
        """Per-gid nearest-lower-rank quantile from the folded levels:
        weighted rank target ``q * (W - 1)`` over the value-sorted
        (value, weight) items.  Exact (rank error 0) while no
        compaction ever fired; otherwise within the gid's self-reported
        ``err`` bound."""
        levels = self._levels_of(rows)
        out = np.full(len(gids), np.nan, dtype=np.float64)
        for i, gi in enumerate(np.asarray(gids).tolist()):
            vals, wts = [], []
            for lv, (vl, cl) in enumerate(levels):
                c = int(cl[gi])
                if c:
                    vals.append(vl[gi, :c])
                    wts.append(
                        np.full(c, np.int64(1) << np.int64(lv), np.int64)
                    )
            if not vals:
                continue
            v = np.concatenate(vals)
            w = np.concatenate(wts)
            o = np.argsort(v, kind="stable")
            v = v[o]
            cw = np.cumsum(w[o])
            t = q * float(cw[-1] - 1)
            idx = min(
                int(np.searchsorted(cw, t, side="right")), len(v) - 1
            )
            out[i] = v[idx]
        return out
