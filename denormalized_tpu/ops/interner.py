"""Host-side group-key interning: values → dense int32 group ids.

The TPU analog of DataFusion's ``GroupValues`` hash-interning table, which the
reference drives inside ``GroupedAggWindowFrame::group_aggregate_batch``
(grouped_window_agg_stream.rs:501-537): group keys are interned to dense
indices so accumulators can be flat vectors.  Here the dense id doubles as the
row index into the device-resident ``(windows, groups)`` state buffers, so
interning is the bridge between host strings and HBM tensors.

Vectorized via ``np.unique`` per batch: only first-seen values take the Python
dict path.  A C++ fast path can replace `_lookup_batch` without changing the
interface.
"""

from __future__ import annotations

import numpy as np


class ColumnInterner:
    """value -> id for one column (any hashable host values)."""

    def __init__(self) -> None:
        self._to_id: dict = {}
        self._values: list = []

    def __len__(self) -> int:
        return len(self._values)

    def intern_array(self, arr: np.ndarray) -> np.ndarray:
        if arr.dtype.kind in "ifb" or arr.dtype.kind == "M":
            # numeric key column: unique per batch, dict on uniques only
            uniq, inv = np.unique(arr, return_inverse=True)
        else:
            uniq, inv = np.unique(arr.astype(object), return_inverse=True)
        ids = np.empty(len(uniq), dtype=np.int32)
        to_id = self._to_id
        values = self._values
        for i, v in enumerate(uniq.tolist()):
            j = to_id.get(v)
            if j is None:
                j = len(values)
                to_id[v] = j
                values.append(v)
            ids[i] = j
        return ids[inv]

    def value_of(self, ids: np.ndarray) -> np.ndarray:
        out = np.empty(len(ids), dtype=object)
        for i, j in enumerate(ids.tolist()):
            out[i] = self._values[j]
        return out


class GroupInterner:
    """Composite (multi-column) key -> dense group id.

    Per-column ids are packed row-wise and the row-tuples interned, so the
    reverse map can reconstruct every key column for emission.
    """

    def __init__(self, num_columns: int) -> None:
        self.num_columns = num_columns
        self._col_interners = [ColumnInterner() for _ in range(num_columns)]
        self._tuple_to_gid: dict = {}
        # per group id, the tuple of per-column value ids
        self._gid_rows: list[tuple] = []

    def __len__(self) -> int:
        return len(self._gid_rows)

    def intern(self, key_columns: list[np.ndarray]) -> np.ndarray:
        assert len(key_columns) == self.num_columns
        per_col = [
            it.intern_array(c) for it, c in zip(self._col_interners, key_columns)
        ]
        if self.num_columns == 1:
            # single-column fast path: column id IS the group id candidate,
            # but keep the tuple table for a uniform reverse map
            stacked = per_col[0][:, None]
        else:
            stacked = np.stack(per_col, axis=1)
        uniq_rows, inv = np.unique(stacked, axis=0, return_inverse=True)
        gids_for_uniq = np.empty(len(uniq_rows), dtype=np.int32)
        for i, row in enumerate(map(tuple, uniq_rows.tolist())):
            g = self._tuple_to_gid.get(row)
            if g is None:
                g = len(self._gid_rows)
                self._tuple_to_gid[row] = g
                self._gid_rows.append(row)
            gids_for_uniq[i] = g
        return gids_for_uniq[inv]

    def keys_of(self, gids: np.ndarray) -> list[np.ndarray]:
        """Reconstruct each key column's values for the given group ids."""
        rows = np.array([self._gid_rows[g] for g in gids.tolist()], dtype=np.int64)
        if len(gids) == 0:
            rows = rows.reshape(0, self.num_columns)
        return [
            it.value_of(rows[:, c])
            for c, it in enumerate(self._col_interners)
        ]

    # -- checkpoint support ---------------------------------------------
    def snapshot(self) -> dict:
        return {
            "columns": [it._values for it in self._col_interners],
            "rows": self._gid_rows,
        }

    @classmethod
    def restore(cls, snap: dict) -> "GroupInterner":
        g = cls(len(snap["columns"]))
        for it, vals in zip(g._col_interners, snap["columns"]):
            it._values = list(vals)
            it._to_id = {v: i for i, v in enumerate(it._values)}
        g._gid_rows = [tuple(r) for r in snap["rows"]]
        g._tuple_to_gid = {r: i for i, r in enumerate(g._gid_rows)}
        return g
