"""Host-side group-key interning: values → dense int32 group ids.

The TPU analog of DataFusion's ``GroupValues`` hash-interning table, which the
reference drives inside ``GroupedAggWindowFrame::group_aggregate_batch``
(grouped_window_agg_stream.rs:501-537): group keys are interned to dense
indices so accumulators can be flat vectors.  Here the dense id doubles as the
row index into the device-resident ``(windows, groups)`` state buffers, so
interning is the bridge between host strings and HBM tensors.

Vectorized via ``np.unique`` per batch: only first-seen values take the Python
dict path.  A C++ fast path can replace `_lookup_batch` without changing the
interface.
"""

from __future__ import annotations

import numpy as np


def _load_native():
    lib = _load_native_lib()
    if lib is None:
        return None, None
    try:
        import ctypes

        from denormalized_tpu.native.build import _DIR

        if getattr(lib, "_intern_pyobjects", None) is None:
            # the PyObject fast path keeps the GIL → must go through PyDLL
            # (same .so, second handle)
            pylib = ctypes.PyDLL(str(_DIR / "interner.so"))
            pylib.intern_pyobjects.restype = ctypes.c_int
            pylib.intern_pyobjects.argtypes = [
                ctypes.c_void_p,
                ctypes.c_void_p,  # PyObject** (the object array's data)
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.c_int32),
            ]
            pylib.intern_py_release.argtypes = [ctypes.c_void_p]
            lib._intern_pyobjects = pylib.intern_pyobjects
            lib._intern_py_release = pylib.intern_py_release
        return lib, lib._intern_pyobjects
    except Exception:  # dnzlint: allow(broad-except) the PyObject fast path is optional (needs -DINTERN_HAVE_PYTHON + headers); the byte-key path below covers interning either way
        return lib, None


def _load_native_lib():
    try:
        import ctypes
        import sysconfig

        from denormalized_tpu.native.build import load

        try:
            inc = sysconfig.get_paths()["include"]
            lib = load(
                "interner", [f"-I{inc}", "-DINTERN_HAVE_PYTHON"]
            )
        except Exception:  # dnzlint: allow(broad-except) retried immediately as the plain (headerless) build — only THAT failure is terminal below
            # no Python headers: plain build without the PyObject path
            lib = load("interner")
        if not getattr(lib, "_in_configured", False):
            lib.intern_create.restype = ctypes.c_void_p
            lib.intern_destroy.argtypes = [ctypes.c_void_p]
            lib.intern_count.restype = ctypes.c_uint64
            lib.intern_count.argtypes = [ctypes.c_void_p]
            lib.intern_key.restype = ctypes.c_uint32
            lib.intern_key.argtypes = [
                ctypes.c_void_p,
                ctypes.c_uint64,
                ctypes.c_char_p,
                ctypes.c_uint32,
            ]
            lib.intern_keys_range.restype = ctypes.c_int64
            lib.intern_keys_range.argtypes = [
                ctypes.c_void_p,
                ctypes.c_uint64,
                ctypes.c_uint64,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
            ]
            # offsets+bytes lane (StringColumn) — probe for a stale .so
            # without the symbol; srchash rebuilds make this moot, but a
            # cheap guard beats an AttributeError mid-stream
            if hasattr(lib, "intern_offsets"):
                lib.intern_offsets.argtypes = [
                    ctypes.c_void_p,
                    ctypes.c_void_p,  # utf-8 byte buffer
                    ctypes.POINTER(ctypes.c_uint64),
                    ctypes.c_void_p,  # validity (u8) or NULL
                    ctypes.c_uint64,
                    ctypes.POINTER(ctypes.c_int32),
                ]
            lib.intern_free.argtypes = [ctypes.c_void_p]
            lib._in_configured = True
        return lib
    except Exception as e:  # dnzlint: allow(broad-except) dict-based interning is the designed fallback on no-compiler boxes; logged so the downgrade is visible, gated by test_native_build_gate where g++ exists
        from denormalized_tpu.runtime.tracing import logger

        logger.warning(
            "native interner unavailable (%s: %s) — dict-based interning "
            "takes over (slower at high key cardinality)",
            type(e).__name__, e,
        )
        return None


# canonical dict key for float NaN (nan != nan, so NaN itself can never be
# found again in a dict); all NaNs intern to one id — the SQL
# GROUP-BY-NULL convention, and what np.unique already does within a batch
_NAN_KEY = ("__nan__",)


class ColumnInterner:
    """value -> id for one column.

    String columns take the native path: the object column is converted to a
    fixed-width numpy ``S`` array (one vectorized pass) and the raw buffer is
    hashed by the C++ open-addressing interner — no per-object Python work at
    steady state.  Numeric columns and environments without a compiler use
    the np.unique+dict fallback.
    """

    def __init__(self) -> None:
        self._to_id: dict = {}
        self._values: list = []
        self._lib, self._py_intern = _load_native()
        self._h = self._lib.intern_create() if self._lib else None
        self._native_active = False
        self._values_arr: np.ndarray | None = None  # object-array mirror
        # numeric fast-path mirror: known keys sorted + their ids, valid
        # only while _num_mirror_n == len(_values) (any dict-path or
        # restore mutation invalidates it → lazily rebuilt)
        self._num_sorted: np.ndarray | None = None
        self._num_ids: np.ndarray | None = None
        self._num_by_id: np.ndarray | None = None  # dense id → key
        self._num_mirror_n = -1
        # the fast path syncs _to_id lazily (suffix-only, see
        # _sync_to_id) — the NaN id is tracked directly so the NaN tail
        # never forces a sync
        self._nan_id: int | None = None
        self._to_id_synced = 0  # dict-synced prefix of _values

    def __del__(self):
        if getattr(self, "_h", None) and self._lib:
            rel = getattr(self._lib, "_intern_py_release", None)
            if rel is not None:
                rel(self._h)  # drop the pointer cache's INCREF pins
            self._lib.intern_destroy(self._h)
            self._h = None

    def __len__(self) -> int:
        if self._native_active:
            # authoritative count straight from the native table — the
            # Python value mirror is synced LAZILY (only when emission or a
            # checkpoint needs the actual strings)
            return int(self._lib.intern_count(self._h))
        if self._num_by_id is not None:
            # numeric fast path: the dense key array is authoritative,
            # the Python list lags until _flush_values
            return max(len(self._values), len(self._num_by_id))
        return len(self._values)

    def _sync_native_values(self) -> None:
        """Extend the Python-side value mirror with newly interned keys —
        ONE bulk ctypes call per batch fetching every new key's bytes, so
        emission-time keys_of() is plain list indexing even at 100k+
        cardinality."""
        import ctypes

        n_now = int(self._lib.intern_count(self._h))
        values = self._values
        start = len(values)
        if n_now <= start:
            return
        bptr = ctypes.POINTER(ctypes.c_uint8)()
        optr = ctypes.POINTER(ctypes.c_uint64)()
        n = self._lib.intern_keys_range(
            self._h, start, n_now, ctypes.byref(bptr), ctypes.byref(optr)
        )
        try:
            offs = np.ctypeslib.as_array(optr, shape=(n + 1,))
            raw = ctypes.string_at(bptr, int(offs[-1])) if offs[-1] else b""
            for i in range(n):
                piece = raw[offs[i] : offs[i + 1]]
                # 0xFF is the dedicated NULL-key byte (see interner.cpp)
                values.append(
                    None
                    if piece == b"\xff"
                    else piece.decode("utf-8", errors="replace")
                )
        finally:
            self._lib.intern_free(bptr)
            self._lib.intern_free(optr)

    def intern_array(self, arr: np.ndarray) -> np.ndarray:
        """Key normalization note: fixed-width numpy string storage cannot
        represent trailing NUL characters, so keys differing only in
        trailing ``'\\x00'`` intern to one id — consistently in BOTH the
        native and fallback paths."""
        import ctypes

        from denormalized_tpu.common.columns import StringColumn

        if isinstance(arr, StringColumn):
            # columnar lane: intern straight off offsets+bytes — no
            # Python str is ever created for a key on this path.  Null
            # slots intern the 0xFF NULL key, the same id the PyObject
            # lane gives None, so a column mixing columnar and legacy
            # batches groups identically.
            fn = (
                getattr(self._lib, "intern_offsets", None)
                if self._h is not None else None
            )
            if fn is not None:
                return self._intern_string_column(arr, fn)
            arr = arr.as_object()  # no native lib: dict fallback below
        if arr.dtype.kind in "ifbM":
            # numeric key column: unique per batch, dict on uniques only
            uniq, inv = np.unique(arr, return_inverse=True)
            if arr.dtype.kind in "if":
                # int/float columns take the sorted-mirror fast path: one
                # searchsorted per batch, Python only for first-seen keys
                # (bulk).  At 1M-distinct approx_top_k cardinalities the
                # per-unique dict loop below was 70% of the sketch lane's
                # wall time (ISSUE 18 approx_scale profile).
                out = self._intern_numeric_uniques(uniq)
                if out is not None:
                    return out[inv]
            uniq = uniq.tolist()
        elif self._h is not None and self._py_intern is not None:
            # PyObject fast path: the C side reads each slot's CPython-cached
            # UTF-8 bytes directly — no fixed-width conversion, no new
            # Python objects, no per-batch value sync (lazy, at emission)
            obj = arr if arr.dtype == object else arr.astype(object)
            obj = np.ascontiguousarray(obj)
            n = len(obj)
            ids = np.empty(n, dtype=np.int32)
            rc = self._py_intern(
                self._h,
                obj.ctypes.data,
                n,
                ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            )
            if rc != 0:  # pragma: no cover - PyDLL re-raises pending errors
                raise RuntimeError("native interning failed")
            self._native_active = True
            return ids
        else:
            # fallback dict interning with the SAME value identity rules as
            # the native PyObject path, so results never depend on build
            # flavor: None is its own key, non-string objects normalize via
            # str(), trailing NULs strip like the native arena padding.
            # (There is deliberately NO third fixed-width-buffer path: a
            # str()-based one merged None with 'None'.)
            ids = np.empty(len(arr), dtype=np.int32)
            to_id = self._sync_to_id()
            values = self._values
            for i, v in enumerate(arr.tolist()):
                if v is None:
                    pass
                elif isinstance(v, str):
                    v = v.rstrip("\x00")
                else:
                    v = str(v)
                j = to_id.get(v)
                if j is None:
                    j = len(values)
                    to_id[v] = j
                    values.append(v)
                ids[i] = j
            self._to_id_synced = len(values)
            return ids
        ids = np.empty(len(uniq), dtype=np.int32)
        to_id = self._sync_to_id()
        values = self._values
        for i, v in enumerate(uniq):
            # NaN needs a canonical dict key: np.unique collapses NaNs
            # WITHIN a batch, but nan != nan so a plain dict lookup would
            # mint a fresh id every batch — grouping would then depend on
            # batch boundaries (review-found, pinned by
            # test_nan_group_keys_form_one_session cross-batch case)
            key = _NAN_KEY if isinstance(v, float) and v != v else v
            j = to_id.get(key)
            if j is None:
                j = len(values)
                to_id[key] = j
                values.append(v)
                if key is _NAN_KEY:
                    self._nan_id = j
            ids[i] = j
        self._to_id_synced = len(values)
        return ids[inv]

    def _rebuild_num_mirror(self, dtype) -> bool:
        """(Re)build the sorted numeric-key mirror from the value list —
        covers first use, checkpoint restore, and any dict-path mutation.
        Returns False (mirror stays invalid) when the stored values can't
        round-trip through ``dtype`` unambiguously: non-numeric entries,
        or cast collisions (two distinct dict keys landing on one
        ``dtype`` value — e.g. ints beyond 2**53 under float64); those
        columns keep the per-unique dict loop, which has no such limits."""
        vals = self._values
        try:
            karr = np.asarray(vals, dtype=dtype)
        except (ValueError, TypeError, OverflowError):
            return False
        ids = np.arange(len(vals), dtype=np.int32)
        if karr.dtype.kind == "f":
            ok = karr == karr  # NaN lives in the dict under _NAN_KEY
            karr, ids = karr[ok], ids[ok]
        order = np.argsort(karr, kind="stable")
        skarr, sids = karr[order], ids[order]
        if len(skarr) and bool(np.any(skarr[1:] == skarr[:-1])):
            return False  # cast collision → ambiguous lookup
        self._num_sorted = skarr
        self._num_ids = sids
        # dense id-ordered key array (NaN included): value_of gathers
        # straight from it, so streaming never materializes Python floats
        self._num_by_id = np.asarray(vals, dtype=dtype)
        self._num_mirror_n = len(vals)
        return True

    def _intern_numeric_uniques(self, uniq: np.ndarray) -> np.ndarray | None:
        """Vectorized id lookup for one batch's sorted unique numeric
        keys; assigns first-seen ids in ``uniq`` order — exactly the old
        per-unique loop's order, so interning is bit-identical either
        way.  New keys land ONLY in numpy structures (the sorted mirror
        + the dense id-ordered ``_num_by_id``); the Python value list
        and key dict lag behind and are suffix-synced lazily
        (``_flush_values`` / ``_sync_to_id``) the moment a checkpoint,
        restore, or dict-path batch needs them.  Returns None to fall
        back to the per-unique dict loop."""
        n = len(uniq)
        ids_u = np.empty(n, dtype=np.int32)
        # np.unique sorts NaN to the tail (and collapses it); it can't go
        # through searchsorted — resolve via the canonical sentinel
        nan_tail = 0
        if uniq.dtype.kind == "f" and n and uniq[-1] != uniq[-1]:
            # count, don't assume 1: np.unique only collapses NaNs on
            # numpy builds with equal_nan — all of them sort to the tail
            nan_tail = int(np.count_nonzero(np.isnan(uniq)))
        core = uniq[: n - nan_tail]
        nb = self._num_by_id
        total = len(nb) if nb is not None else len(self._values)
        if self._num_mirror_n != total or (
            self._num_sorted is not None
            and self._num_sorted.dtype != core.dtype
        ):
            self._flush_values()
            if not self._rebuild_num_mirror(core.dtype):
                return None
            nb = self._num_by_id
        skeys, sids = self._num_sorted, self._num_ids
        pos = np.searchsorted(skeys, core)
        safe = np.minimum(pos, max(len(skeys) - 1, 0))
        if len(skeys):
            found = (pos < len(skeys)) & (skeys[safe] == core)
        else:
            found = np.zeros(len(core), dtype=bool)
        ids_core = np.where(found, sids[safe] if len(skeys) else 0, -1)
        miss = np.flatnonzero(~found)
        if len(miss):
            new_keys = core[miss]
            start = len(nb)
            new_ids = np.arange(
                start, start + len(new_keys), dtype=np.int32
            )
            nb = np.concatenate([nb, new_keys])
            self._num_by_id = nb
            ids_core[miss] = new_ids
            # merge the (sorted) new keys into the sorted mirror with two
            # boolean scatters — one pass, vs np.insert's two generic
            # fancy-index passes (measurable at 100k+ new keys/run)
            ins = np.searchsorted(skeys, new_keys)
            m = len(skeys) + len(new_keys)
            pos_new = ins + np.arange(len(new_keys))
            old_mask = np.ones(m, dtype=bool)
            old_mask[pos_new] = False
            merged_k = np.empty(m, dtype=skeys.dtype)
            merged_i = np.empty(m, dtype=sids.dtype)
            merged_k[pos_new] = new_keys
            merged_k[old_mask] = skeys
            merged_i[pos_new] = new_ids
            merged_i[old_mask] = sids
            self._num_sorted = merged_k
            self._num_ids = merged_i
            self._num_mirror_n = len(nb)
        ids_u[: n - nan_tail] = ids_core
        if nan_tail:
            j = self._nan_id
            if j is None:
                # a dict-path batch may have minted the sentinel before
                # this column ever hit the fast path
                j = self._sync_to_id().get(_NAN_KEY)
            if j is None:
                j = len(nb)
                self._to_id[_NAN_KEY] = j
                self._num_by_id = np.concatenate(
                    [nb, np.asarray([uniq[-1]], dtype=nb.dtype)]
                )
                # NaN never enters the SORTED mirror (it can't be
                # searched) but it does hold an id slot
                self._num_mirror_n = len(self._num_by_id)
            self._nan_id = j
            ids_u[n - nan_tail :] = j
        return ids_u

    def _flush_values(self) -> None:
        """Materialize the Python value list from the dense numeric key
        array — called lazily at checkpoint / restore / dict-path
        boundaries, never per streaming batch."""
        nb = self._num_by_id
        if nb is not None and len(nb) > len(self._values):
            self._values.extend(nb[len(self._values) :].tolist())

    def _sync_to_id(self) -> dict:
        """Suffix-sync the key dict with the value list.  The numeric
        fast path appends values WITHOUT dict entries (the sorted mirror
        is its lookup structure); any path that still needs the dict
        calls this first.  The un-synced keys are exactly the suffix the
        fast path appended — O(new), not O(all); tracked by an explicit
        prefix counter (``len(to_id)`` can't serve: the fast path's NaN
        sentinel lands in the dict ahead of un-synced values)."""
        self._flush_values()
        to_id, values = self._to_id, self._values
        n = self._to_id_synced
        if n < len(values):
            for i in range(n, len(values)):
                v = values[i]
                to_id[
                    _NAN_KEY if isinstance(v, float) and v != v else v
                ] = i
            self._to_id_synced = len(values)
        return to_id

    def _intern_string_column(self, col, fn) -> np.ndarray:
        """offsets+bytes native intern (pinned hot path: one foreign call
        per batch, no per-row Python)."""
        import ctypes

        n = len(col)
        ids = np.empty(n, dtype=np.int32)
        if n == 0:
            return ids
        offsets = np.ascontiguousarray(col.offsets, dtype=np.uint64)
        data = np.ascontiguousarray(col.data)
        validity = col.validity
        vptr = (
            0 if validity is None
            else np.ascontiguousarray(validity).ctypes.data
        )
        fn(
            self._h,
            data.ctypes.data if data.size else 0,
            offsets.ctypes.data_as(ctypes.POINTER(ctypes.c_uint64)),
            vptr,
            n,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        self._native_active = True
        return ids

    def value_of(self, ids: np.ndarray) -> np.ndarray:
        if self._native_active:
            self._sync_native_values()
            # fancy-index the object-array mirror: C-speed gather even for
            # 100k-group emissions
            if self._values_arr is None or len(self._values_arr) != len(
                self._values
            ):
                self._values_arr = np.empty(len(self._values), dtype=object)
                self._values_arr[:] = self._values
            return self._values_arr[np.asarray(ids)]
        nb = self._num_by_id
        if nb is not None and len(nb) > len(self._values):
            # numeric fast path with an un-flushed suffix: gather from
            # the dense key array, then box ONLY the requested ids to
            # Python scalars (tolist) — emission asks for a handful of
            # ids, never the whole key space
            sel = nb[np.asarray(ids, dtype=np.int64)]
            out = np.empty(len(sel), dtype=object)
            out[:] = sel.tolist()
            return out
        values = self._values
        out = np.empty(len(ids), dtype=object)
        for i, j in enumerate(ids.tolist()):
            out[i] = values[j]
        return out

    # -- snapshot/restore support ---------------------------------------
    def all_values(self) -> list:
        if self._native_active:
            self._sync_native_values()
        self._flush_values()
        return list(self._values)

    def load_values(self, vals: list) -> None:
        """Re-seed with an ordered value list (ids must match positions)."""
        if (
            self._h is not None
            and vals
            and all(isinstance(v, str) or v is None for v in vals)
        ):
            # string column → native table re-seed (also re-syncs _values)
            ids = self.intern_array(np.array(vals, dtype=object))
            assert ids.tolist() == list(range(len(vals))), "restore order"
        else:
            # numeric (or no-native) columns live in the dict; NaN values
            # re-key through the canonical NaN sentinel exactly like
            # intern_array, or post-restore batches would re-mint NaN ids
            self._values = list(vals)
            self._to_id = {
                (_NAN_KEY if isinstance(v, float) and v != v else v): i
                for i, v in enumerate(self._values)
            }
            self._to_id_synced = len(self._values)
            self._nan_id = self._to_id.get(_NAN_KEY)
            self._num_mirror_n = -1  # mirror re-derives from the new list
            self._num_by_id = None


def format_key_tuple(vals) -> str:
    """Canonical display string for one composite key — the ONE
    formatting rule every hot-key label uses (engine interners and the
    reference oracle's seq-id map must render identically or
    differential hot-key comparisons break)."""
    return (
        str(vals[0]) if len(vals) == 1
        else "(" + ", ".join(str(v) for v in vals) + ")"
    )


def display_keys(interner, gids) -> list:
    """Best-effort display strings for dense gids, None for released or
    out-of-range ids — the state observatory's hot-key resolution (a
    heavy-hitter sketch can briefly hold a gid the recycling interner
    already released; that key's state is gone, so rendering the raw
    gid is the honest answer)."""
    gl = np.asarray(gids, dtype=np.int64)
    out: list = [None] * len(gl)
    rows = interner._gid_rows
    ok = [
        i for i, g in enumerate(gl.tolist())
        if 0 <= g < len(rows) and rows[g] is not None
    ]
    if not ok:
        return out
    cols = interner.keys_of(gl[ok])
    for j, i in enumerate(ok):
        out[i] = format_key_tuple([c[j] for c in cols])
    return out


def interner_accounting(interner) -> dict:
    """Free-list / id-space accounting shared by both interner classes
    (the state observatory's key-capacity view): live ids, total dense
    id space, and the recycling free-list depth (0 for the
    non-recycling :class:`GroupInterner`)."""
    free = len(getattr(interner, "_free", ()))
    return {
        "live_keys": len(interner),
        "key_capacity": getattr(
            interner, "capacity", len(interner._gid_rows)
        ),
        "free_gids": free,
    }


def _dedup_rows(per_col: list[np.ndarray]) -> tuple[list[tuple], np.ndarray]:
    """Shared composite-key dedup: per-column id arrays → (unique row
    tuples, inverse indices).  2 columns pack into one int64 for a 1-D
    unique (much faster than np.unique(axis=0)'s void-view row sort);
    single source of truth for GroupInterner AND RecyclingGroupInterner so
    the packing can never diverge between them."""
    if len(per_col) == 2:
        packed = (per_col[0].astype(np.int64) << 32) | per_col[1].astype(
            np.int64
        )
        uniq, inv = np.unique(packed, return_inverse=True)
        rows = [(int(p >> 32), int(p & 0xFFFFFFFF)) for p in uniq.tolist()]
    else:
        stacked = np.stack(per_col, axis=1)
        uniq_rows, inv = np.unique(stacked, axis=0, return_inverse=True)
        rows = list(map(tuple, uniq_rows.tolist()))
    return rows, inv


class GroupInterner:
    """Composite (multi-column) key -> dense group id.

    Per-column ids are packed row-wise and the row-tuples interned, so the
    reverse map can reconstruct every key column for emission.
    """

    def __init__(self, num_columns: int) -> None:
        self.num_columns = num_columns
        self._col_interners = [ColumnInterner() for _ in range(num_columns)]
        self._tuple_to_gid: dict = {}
        # per group id, the tuple of per-column value ids
        self._gid_rows: list[tuple] = []

    def __len__(self) -> int:
        return len(self._gid_rows)

    def intern(self, key_columns: list[np.ndarray]) -> np.ndarray:
        assert len(key_columns) == self.num_columns
        per_col = [
            it.intern_array(c) for it, c in zip(self._col_interners, key_columns)
        ]
        if self.num_columns == 1:
            # single-column fast path: the column interner assigns dense ids
            # in first-seen order, which is exactly the group-id order —
            # no row-dedup needed at all
            cids = per_col[0]
            n_known = len(self._gid_rows)
            n_now = len(self._col_interners[0])
            if n_now > n_known:
                # zip() of one range yields the (i,) 1-tuples at C speed —
                # the genexpr version was measurable at 100k+ new ids/batch
                # (the approx_top_k value-interning profile, ISSUE 18)
                self._gid_rows.extend(zip(range(n_known, n_now)))
            return cids
        rows, inv = _dedup_rows(per_col)
        gids_for_uniq = np.empty(len(rows), dtype=np.int32)
        for i, row in enumerate(rows):
            g = self._tuple_to_gid.get(row)
            if g is None:
                g = len(self._gid_rows)
                self._tuple_to_gid[row] = g
                self._gid_rows.append(row)
            gids_for_uniq[i] = g
        return gids_for_uniq[inv]

    def keys_of(self, gids: np.ndarray) -> list[np.ndarray]:
        """Reconstruct each key column's values for the given group ids."""
        if self.num_columns == 1:
            # group id == column id (see intern's single-column fast path)
            return [self._col_interners[0].value_of(gids)]
        rows = np.array([self._gid_rows[g] for g in gids.tolist()], dtype=np.int64)
        if len(gids) == 0:
            rows = rows.reshape(0, self.num_columns)
        return [
            it.value_of(rows[:, c])
            for c, it in enumerate(self._col_interners)
        ]

    # -- checkpoint support ---------------------------------------------
    def snapshot(self) -> dict:
        return {
            "columns": [it.all_values() for it in self._col_interners],
            "rows": self._gid_rows,
        }

    @classmethod
    def restore(cls, snap: dict) -> "GroupInterner":
        g = cls(len(snap["columns"]))
        for it, vals in zip(g._col_interners, snap["columns"]):
            it.load_values(list(vals))
        g._gid_rows = [tuple(r) for r in snap["rows"]]
        g._tuple_to_gid = {r: i for i, r in enumerate(g._gid_rows)}
        return g


class RecyclingGroupInterner:
    """Composite key -> dense group id WITH gid recycling.

    Same ``intern``/``keys_of`` contract as :class:`GroupInterner`, plus
    ``release(gids)``: a released gid goes onto a free list and is handed
    to the next first-seen key, so the dense-id space stays proportional
    to the number of LIVE keys rather than all keys ever seen.  Built for
    the session operator, whose key population churns (a key with no open
    session holds no state and its id can be reused); the window and join
    interners keep gids forever because their ids index device buffers.

    Two deliberate deviations from GroupInterner:

    - no single-column ``cid == gid`` fast path — recycling breaks that
      identity, so every shape goes through the packed-row dedup (still
      O(uniques-per-batch) Python, the same bound as the multi-column
      paths);
    - per-COLUMN value ids (inside ColumnInterner) are never recycled:
      they deduplicate values, and the composite-key cross product — the
      thing that actually explodes at high key churn — is what the free
      list caps.
    """

    def __init__(self, num_columns: int) -> None:
        self.num_columns = num_columns
        self._col_interners = [ColumnInterner() for _ in range(num_columns)]
        self._row_to_gid: dict = {}
        # per gid: tuple of per-column value ids, or None when freed
        self._gid_rows: list[tuple | None] = []
        self._free: list[int] = []

    def __len__(self) -> int:
        """Number of LIVE (unreleased) keys."""
        return len(self._gid_rows) - len(self._free)

    @property
    def capacity(self) -> int:
        """Dense-id space size (live + free) — sizes gid-indexed arrays."""
        return len(self._gid_rows)

    def intern(self, key_columns: list[np.ndarray]) -> np.ndarray:
        assert len(key_columns) == self.num_columns
        from denormalized_tpu.common.columns import as_key_column

        per_col = [
            it.intern_array(as_key_column(c))
            for it, c in zip(self._col_interners, key_columns)
        ]
        if self.num_columns == 1:
            # no cid==gid fast path here (recycling breaks the identity),
            # but the dedup is still a single 1-D unique
            uniq, inv = np.unique(per_col[0].astype(np.int64),
                                  return_inverse=True)
            rows = [(int(c),) for c in uniq.tolist()]
        else:
            rows, inv = _dedup_rows(per_col)
        gids_for_uniq = np.empty(len(rows), dtype=np.int32)
        row_to_gid = self._row_to_gid
        gid_rows = self._gid_rows
        free = self._free
        for i, row in enumerate(rows):
            g = row_to_gid.get(row)
            if g is None:
                if free:
                    g = free.pop()
                    gid_rows[g] = row
                else:
                    g = len(gid_rows)
                    gid_rows.append(row)
                row_to_gid[row] = g
            gids_for_uniq[i] = g
        return gids_for_uniq[inv]

    def release(self, gids) -> None:
        """Return gids to the free list (idempotent per gid).  The caller
        guarantees no state remains keyed by a released gid."""
        gid_rows = self._gid_rows
        for g in np.asarray(gids).tolist():
            row = gid_rows[g]
            if row is None:
                continue  # already free
            del self._row_to_gid[row]
            gid_rows[g] = None
            self._free.append(g)

    def keys_of(self, gids: np.ndarray) -> list[np.ndarray]:
        """Reconstruct each key column's values for the given LIVE gids."""
        rows = np.array(
            [self._gid_rows[g] for g in np.asarray(gids).tolist()],
            dtype=np.int64,
        )
        if len(rows) == 0:
            rows = rows.reshape(0, self.num_columns)
        return [
            it.value_of(rows[:, c])
            for c, it in enumerate(self._col_interners)
        ]
