"""Shared slice-level window aggregation — the multi-query kernel.

The Factor-Windows / shared-aggregation design (PAPERS.md): a sliding
window ``[j*S, j*S + L)`` is a union of NON-OVERLAPPING slices of width
``g = gcd(S, L)`` (for a set of concurrent window specs, ``g`` is the
gcd over every spec's slide AND length), so raw rows need to be
aggregated exactly once per slice — every window, of every concurrently
registered query on the same feed, then FOLDS its answer from ``L/g``
slice partials instead of re-scanning rows per overlap.  This is the
host analog of the device ring in :mod:`segment_agg`: where the device
kernel fans each row out to its ``k`` overlapping windows at scatter
time (O(k) device work per row), the slice store pays O(1) per row and
O(L/g) per *emitted window* — the winning trade whenever windows
overlap (k > 1) or several queries share one ingest.

Representation: one dense per-gid array per primitive
:class:`~denormalized_tpu.ops.segment_agg.AggComponent` per live slice
unit, fed by ``np.{add,minimum,maximum}.reduceat`` over one lexsort per
batch (the PR-3 segment kernels' idiom).  Sums — including the variance
family's pivot-shifted moment columns — fold across slices by exact
addition; under a shared constant pivot the Chan combine's delta terms
cancel identically, so the additive fold IS the exact Chan merge of the
per-slice moments.  min/max fold by elementwise min/max.  Everything is
float64 on host: two runs that accumulate the same batches in the same
order produce bit-identical folds, which is what makes shared-vs-
independent and kill/restore emission comparisons exact.
"""

from __future__ import annotations

from time import perf_counter

import numpy as np

from denormalized_tpu.ops.segment_agg import AggComponent

#: per-component fold-neutral init values (mirrors WindowKernelSpec
#: .init_value, in host f64/int64)
_F64 = np.float64
_I64 = np.int64


def _init_for(comp: AggComponent):
    if comp.kind == "count":
        return np.zeros(0, dtype=_I64)
    if comp.kind == "sum":
        return np.zeros(0, dtype=_F64)
    if comp.kind == "min":
        return np.full(0, np.inf, dtype=_F64)
    if comp.kind == "max":
        return np.full(0, -np.inf, dtype=_F64)
    raise ValueError(comp.kind)


def _fill_value(comp: AggComponent):
    if comp.kind == "count":
        return 0
    if comp.kind == "sum":
        return 0.0
    if comp.kind == "min":
        return np.inf
    if comp.kind == "max":
        return -np.inf
    raise ValueError(comp.kind)


def slice_segment_bounds(units, gids, capacity):
    """One lexsort + boundary scan for a whole batch: rows keyed by
    ``(slide_unit, gid)`` collapse to per-segment runs whose partials
    reduceat computes in one pass each.  Returns ``(order, starts,
    seg_units, seg_gids)`` where ``order`` sorts the batch, ``starts``
    are the segment start offsets into the sorted batch, and
    ``seg_units``/``seg_gids`` name each segment's slice cell."""
    key = units.astype(np.int64) * np.int64(capacity) + gids.astype(np.int64)
    order = np.argsort(key, kind="stable")
    ks = key[order]
    edges = np.flatnonzero(ks[1:] != ks[:-1]) + 1
    starts = np.concatenate((np.zeros(1, dtype=np.int64), edges))
    seg_key = ks[starts]
    # floor-div/mod recover (unit, gid) exactly for negative units too
    return order, starts, seg_key // capacity, seg_key % capacity


def fold_slices(kind: str, stack: np.ndarray) -> np.ndarray:
    """Combine a ``(n_units, G)`` stack of slice partials into one
    ``(G,)`` window partial — adds for counts/sums (exact Chan combine
    under the store's shared pivot), elementwise min/max for extrema.
    Deterministic: the same stack always folds to the same bits, the
    invariant the byte-identical emission guarantees ride on."""
    if kind in ("count", "sum"):
        return np.add.reduce(stack, axis=0)
    if kind == "min":
        return np.minimum.reduce(stack, axis=0)
    if kind == "max":
        return np.maximum.reduce(stack, axis=0)
    raise ValueError(kind)


class SliceStore:
    """Per-(slide-unit, gid) partial aggregates for one shared feed.

    ``components`` is the deduped union of primitive components every
    subscriber's aggregates decompose into
    (:func:`segment_agg.components_for`); gids come from the shared
    :class:`~denormalized_tpu.ops.interner.GroupInterner`, so one store
    serves every window spec folding from it."""

    def __init__(
        self,
        components,
        unit_ms: int,
        *,
        force_sort_lane: bool = False,
        sketches=(),
    ) -> None:
        if unit_ms <= 0:
            raise ValueError(f"slice unit must be positive, got {unit_ms}")
        self.components = tuple(components)
        #: SketchSpec layouts riding this store's slice units — frozen at
        #: construction so every unit (and every restore) carries the
        #: same planes; see ops/sketches.py
        self.sketches = tuple(sketches)
        self.unit_ms = int(unit_ms)
        # unit -> {component label -> (capacity,) array}
        self._units: dict[int, dict[str, np.ndarray]] = {}
        self._cap = 0
        self.rows_accumulated = 0
        self.sketch_rows = 0
        self.sketch_update_s = 0.0
        self._itemsize_total = 8 * len(self.components)
        self._comp_labels = frozenset(c.label for c in self.components)
        # add-only component sets (counts + sums, no extrema) take the
        # sort-free bincount lane in accumulate(); min/max need ordered
        # segments, so their presence keeps the lexsort lane.
        # ``force_sort_lane`` pins the lexsort lane regardless: a shared
        # group whose component UNION carries extrema always sorts, so
        # an add-only member's independent byte-identity oracle must be
        # able to match that lane (EngineConfig(slice_sort_lane=True)).
        # Sketch planes always sort: their per-cell update sequences
        # must be a pure function of the (unit, gid) segment order.
        self._add_only = (
            not force_sort_lane
            and not self.sketches
            and all(c.kind in ("count", "sum") for c in self.components)
        )

    # -- accounting ------------------------------------------------------
    def __len__(self) -> int:
        return len(self._units)

    @property
    def add_only(self) -> bool:
        """True when this store may take the sort-free bincount lane —
        callers precomputing a shared sort permutation must NOT hand it
        to an add-only store (the dense lane's bits differ)."""
        return self._add_only

    @property
    def capacity(self) -> int:
        return self._cap

    def nbytes(self) -> int:
        return (
            len(self._units) * self._cap * self._itemsize_total
            + self.sketch_nbytes()
        )

    def sketch_nbytes(self) -> int:
        """Exact bytes held by sketch planes across live units — O(1) in
        value cardinality by construction (the doctor reports this next
        to the unbounded exact-accumulator growth it replaces)."""
        if not self.sketches:
            return 0
        total = 0
        for slot in self._units.values():
            for label, arr in slot.items():
                if label not in self._comp_labels:
                    total += arr.nbytes
        return total

    def live_units(self) -> list[int]:
        return sorted(self._units)

    # -- capacity --------------------------------------------------------
    def _ensure_capacity(self, ngroups: int) -> None:
        if ngroups <= self._cap:
            return
        new_cap = 1 << max(4, (ngroups - 1).bit_length())
        for slot in self._units.values():
            for comp in self.components:
                old = slot[comp.label]
                arr = np.full(
                    new_cap, _fill_value(comp), dtype=old.dtype
                )
                arr[: len(old)] = old
                slot[comp.label] = arr
            for spec in self.sketches:
                for label in [k for k in slot if spec.owns(k)]:
                    old = slot[label]
                    arr = np.full(
                        (new_cap,) + old.shape[1:],
                        spec.fill_for(label),
                        dtype=old.dtype,
                    )
                    arr[: old.shape[0]] = old
                    slot[label] = arr
        self._cap = new_cap

    def _new_unit(self) -> dict[str, np.ndarray]:
        slot = {}
        for comp in self.components:
            init = _init_for(comp)
            slot[comp.label] = np.full(
                self._cap, _fill_value(comp), dtype=init.dtype
            )
        for spec in self.sketches:
            slot.update(spec.init_planes(self._cap))
        return slot

    # -- hot path: per-batch accumulation --------------------------------
    def accumulate(
        self,
        units: np.ndarray,
        gids: np.ndarray,
        values64: np.ndarray,
        colvalid: np.ndarray,
        ngroups: int,
        *,
        order: np.ndarray | None = None,
        aux: dict[int, np.ndarray] | None = None,
    ) -> int:
        """Fold one batch's rows into their slice partials.  ``units``
        are slide-unit indices (``ts // unit_ms``), ``gids`` dense group
        ids, ``values64`` the ``(n, V)`` f64 value matrix (variance
        columns already pivot-shifted by the caller — the same transform
        StreamingWindowExec applies), ``colvalid`` per-cell validity.

        ``order``, when given, is a precomputed stable ``(unit, gid)``
        sort permutation — the full batch's, or an order-preserving
        masked subset of it (row indices into the batch arrays).  The
        store then skips its own lexsort and folds exactly the rows
        ``order`` names, in that order.  A stable subset of a stable
        sort IS the subset's stable sort, so the per-segment row
        sequences (and hence the reduceat bits) are identical to
        sorting the subset directly — the shared pipeline exploits this
        to pay ONE sort per batch across every residual filter class.

        ``aux`` carries per-row sketch source lanes keyed by value
        column: uint64 stable hashes (HLL) or dense value-interner ids
        (top-K), indexed by the same batch row positions as
        ``values64``.  Required when the store carries a spec whose
        ``uses`` is not ``"f64"``.
        Returns the number of distinct slice segments touched."""
        n = len(units) if order is None else len(order)
        if n == 0:
            return 0
        self._ensure_capacity(max(ngroups, 1))
        cap = self._cap
        if order is None:
            if self._add_only:
                u_min = int(units.min())
                span = int(units.max()) - u_min + 1
                # dense-cell guard: a wildly out-of-order batch whose
                # unit span dwarfs its row count falls back to sorting
                if span * cap <= 4 * max(n, 1024):
                    return self._accumulate_dense(
                        units, gids, values64, colvalid, u_min, span
                    )
            order, starts, seg_u, seg_g = slice_segment_bounds(
                units, gids, cap
            )
        else:
            ks = units[order].astype(np.int64) * np.int64(
                cap
            ) + gids[order].astype(np.int64)
            edges = np.flatnonzero(ks[1:] != ks[:-1]) + 1
            starts = np.concatenate((np.zeros(1, dtype=np.int64), edges))
            seg_key = ks[starts]
            seg_u = seg_key // cap
            seg_g = seg_key % cap
        row_counts = np.diff(np.append(starts, n))
        # per-component segment partials (one reduceat per component);
        # gather-then-select equals select-then-gather elementwise, so
        # both order paths produce the same bits
        seg_vals: dict[str, np.ndarray] = {}
        for comp in self.components:
            if comp.kind == "count" and comp.col is None:
                seg_vals[comp.label] = row_counts.astype(_I64)
                continue
            if comp.kind == "count":
                v = colvalid[order, comp.col].astype(_I64)
                seg_vals[comp.label] = np.add.reduceat(v, starts)
                continue
            col = values64[order, comp.col]
            ok = colvalid[order, comp.col]
            if comp.kind == "sum":
                v = np.where(ok, col, 0.0)
                seg_vals[comp.label] = np.add.reduceat(v, starts)
            elif comp.kind == "min":
                v = np.where(ok, col, np.inf)
                seg_vals[comp.label] = np.minimum.reduceat(v, starts)
            elif comp.kind == "max":
                v = np.where(ok, col, -np.inf)
                seg_vals[comp.label] = np.maximum.reduceat(v, starts)
            else:  # pragma: no cover — components_for never emits others
                raise ValueError(comp.kind)
        # scatter segment partials into per-unit arrays: segments are
        # sorted by (unit, gid), so distinct units form contiguous runs;
        # within one unit the gids are unique → plain fancy indexing
        u_edges = np.flatnonzero(seg_u[1:] != seg_u[:-1]) + 1
        u_starts = np.concatenate((np.zeros(1, dtype=np.int64), u_edges))
        u_ends = np.append(u_edges, len(seg_u))
        units_list = seg_u[u_starts]
        for i, u in enumerate(units_list.tolist()):
            lo, hi = int(u_starts[i]), int(u_ends[i])
            g = seg_g[lo:hi]
            slot = self._units.get(u)
            if slot is None:
                slot = self._new_unit()
                self._units[u] = slot
            for comp in self.components:
                arr = slot[comp.label]
                seg = seg_vals[comp.label][lo:hi]
                if comp.kind in ("count", "sum"):
                    arr[g] += seg
                elif comp.kind == "min":
                    arr[g] = np.minimum(arr[g], seg)
                else:
                    arr[g] = np.maximum(arr[g], seg)
            if self.sketches:
                # rows of this unit, in segment (gid-ascending) order —
                # the per-cell sequences every sketch kernel requires
                ts = perf_counter()
                r0 = int(starts[lo])
                r1 = int(starts[hi]) if hi < len(starts) else n
                rows = order[r0:r1]
                g_rows = gids[rows]
                for spec in self.sketches:
                    if spec.uses == "f64":
                        col = values64[rows, spec.vcol]
                    else:
                        col = aux[spec.vcol][rows]
                    spec.accumulate_unit(
                        slot, cap, g_rows, col,
                        colvalid[rows, spec.vcol],
                    )
                self.sketch_update_s += perf_counter() - ts
        if self.sketches:
            self.sketch_rows += n
        self.rows_accumulated += n
        return len(seg_u)

    def _accumulate_dense(
        self, units, gids, values64, colvalid, u_min: int, span: int
    ) -> int:
        """Sort-free lane for add-only component sets: one ``bincount``
        per component over dense ``(unit, gid)`` cell indices.  NOT
        bit-identical to the lexsort lane (bincount adds strictly in
        row order; reduceat may fold a long segment pairwise), but the
        lane choice is a pure function of the component set and the
        batch's unit span — two runs over the same feed with the same
        aggregates always take the same lane, which is what the
        byte-identical emission guarantees actually require."""
        n = len(units)
        cap = self._cap
        rel = (units - u_min).astype(np.int64)
        idx = rel * cap + gids.astype(np.int64)
        ncells = span * cap
        per_comp: dict[str, np.ndarray] = {}
        for comp in self.components:
            if comp.kind == "count" and comp.col is None:
                per_comp[comp.label] = np.bincount(idx, minlength=ncells)
            elif comp.kind == "count":
                per_comp[comp.label] = np.bincount(
                    idx,
                    weights=colvalid[:, comp.col].astype(np.float64),
                    minlength=ncells,
                ).astype(_I64)
            else:  # sum
                per_comp[comp.label] = np.bincount(
                    idx,
                    weights=np.where(
                        colvalid[:, comp.col], values64[:, comp.col], 0.0
                    ),
                    minlength=ncells,
                )
        touched = np.flatnonzero(np.bincount(rel, minlength=span))
        for r in touched.tolist():
            u = u_min + r
            slot = self._units.get(u)
            if slot is None:
                slot = self._new_unit()
                self._units[u] = slot
            lo = r * cap
            for comp in self.components:
                slot[comp.label] += per_comp[comp.label][lo:lo + cap]
        self.rows_accumulated += n
        return int(len(touched))

    # -- fold: window emission -------------------------------------------
    def fold(self, u_start: int, u_end: int) -> dict[str, np.ndarray] | None:
        """Combine slice partials over units ``[u_start, u_end)`` into
        one window's component rows (the shape
        :func:`segment_agg.finalize` consumes).  None when no slice in
        the range holds data — the window is empty for every group."""
        present = [
            self._units[u] for u in range(u_start, u_end) if u in self._units
        ]
        if not present:
            return None
        out: dict[str, np.ndarray] = {}
        if len(present) == 1:
            slot = present[0]
            for comp in self.components:
                out[comp.label] = slot[comp.label].copy()
        else:
            for comp in self.components:
                stack = np.stack([slot[comp.label] for slot in present])
                out[comp.label] = fold_slices(comp.kind, stack)
        # sketch planes merge across units in ascending unit order — a
        # pure function of the feed, so shared / independent / restored
        # runs fold identical bits
        for spec in self.sketches:
            out.update(spec.fold(present, self._cap))
        return out

    # -- retention -------------------------------------------------------
    def prune(self, min_unit: int) -> int:
        """Drop every slice below ``min_unit`` — no subscriber's open or
        future window can reference them (the caller computes the floor
        over ALL subscribers' cursors and watermark floors)."""
        dead = [u for u in self._units if u < min_unit]
        for u in dead:
            del self._units[u]
        return len(dead)

    # -- checkpoint integration ------------------------------------------
    def snapshot_arrays(self, ngroups: int) -> dict[str, np.ndarray]:
        """Pack every live slice's arrays (trimmed to the live group
        prefix) under ``u<unit>|<label>`` keys — the epoch snapshot's
        array payload."""
        ngroups = max(1, min(ngroups, self._cap) if self._cap else 1)
        out = {}
        for u, slot in self._units.items():
            for comp in self.components:
                out[f"u{u}|{comp.label}"] = slot[comp.label][:ngroups]
            if self.sketches:
                # sketch planes (incl. dynamically allocated quantile
                # levels) trim to the live gid prefix on axis 0
                for label, arr in slot.items():
                    if label not in self._comp_labels:
                        out[f"u{u}|{label}"] = arr[:ngroups]
        return out

    def restore_arrays(
        self, arrays: dict[str, np.ndarray], ngroups: int
    ) -> None:
        """Rebuild the store from a snapshot's array payload (exact:
        the arrays are the f64/i64 partials as accumulated)."""
        self._units = {}
        self._cap = 0
        self.rows_accumulated = 0
        self._ensure_capacity(max(ngroups, 1))
        for key, arr in arrays.items():
            u_str, label = key.split("|", 1)
            u = int(u_str[1:])
            slot = self._units.get(u)
            if slot is None:
                slot = self._new_unit()
                self._units[u] = slot
            if label not in slot:
                # dynamically allocated sketch plane (quantile level):
                # ask the owning spec for a fresh full-capacity array
                for spec in self.sketches:
                    if spec.owns(label):
                        slot[label] = spec.alloc_label(label, self._cap)
                        break
            slot[label][: len(arr)] = arr
