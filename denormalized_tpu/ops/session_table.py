"""SoA open-session store — flat numpy state for the session operator.

The StreamBox-HBM-style structure-of-arrays replacement for the old
``dict[key_tuple, list[_Session]]`` store: every open session is one SLOT in
a set of parallel flat arrays (interval bounds + one column per running
aggregate component), sessions of the same group chain through
``head[gid] -> link[slot] -> ...`` exactly like the join's ``_SideState``
chained-array row store, and closed slots recycle through a free list.  All
bulk operations — gathering the open sessions of the gids a batch touches,
scattering merged sessions back, scanning for watermark-expired sessions —
are numpy gathers/scatters; no per-session Python objects exist at steady
state.

Aggregate layout per slot (V = number of float value columns):

- ``start``/``last``: session interval bounds (event-time ms)
- ``row_count``: rows in the session (count(*))
- ``counts``/``sums``/``mins``/``maxs``: per-column null-aware primitives
- ``means``/``m2s``: Welford/Chan moments for the variance family

UDAF/collection accumulators are inherently per-session Python objects;
they live OUTSIDE the arrays in a ``{slot: [Accumulator, ...]}`` dict that
follows slot alloc/free.
"""

from __future__ import annotations

import numpy as np


class SessionTable:
    """Slot-per-open-session SoA store with per-gid chains + free list."""

    __slots__ = (
        "num_value_cols",
        "start",
        "last",
        "row_count",
        "counts",
        "sums",
        "mins",
        "maxs",
        "means",
        "m2s",
        "gid",
        "link",
        "live",
        "head",
        "accs",
        "_free",
        "_hwm",
    )

    def __init__(self, num_value_cols: int, slot_capacity: int = 1024) -> None:
        self.num_value_cols = V = int(num_value_cols)
        cap = max(int(slot_capacity), 16)
        self.start = np.zeros(cap, dtype=np.int64)
        self.last = np.zeros(cap, dtype=np.int64)
        self.row_count = np.zeros(cap, dtype=np.int64)
        self.counts = np.zeros((cap, V), dtype=np.int64)
        self.sums = np.zeros((cap, V), dtype=np.float64)
        self.mins = np.zeros((cap, V), dtype=np.float64)
        self.maxs = np.zeros((cap, V), dtype=np.float64)
        self.means = np.zeros((cap, V), dtype=np.float64)
        self.m2s = np.zeros((cap, V), dtype=np.float64)
        self.gid = np.full(cap, -1, dtype=np.int32)
        self.link = np.full(cap, -1, dtype=np.int32)
        self.live = np.zeros(cap, dtype=bool)
        self.head = np.full(1024, -1, dtype=np.int32)
        self.accs: dict[int, list] = {}
        self._free: list[int] = []
        self._hwm = 0  # slots ever allocated (free-listed ones included)

    # -- capacity --------------------------------------------------------
    def __len__(self) -> int:
        return self._hwm - len(self._free)

    def ensure_gids(self, num_gids: int) -> None:
        cap = len(self.head)
        if num_gids <= cap:
            return
        while cap < num_gids:
            cap *= 2
        new = np.full(cap, -1, dtype=np.int32)
        new[: len(self.head)] = self.head
        self.head = new

    def _ensure_slots(self, need: int) -> None:
        cap = len(self.start)
        if need <= cap:
            return
        while cap < need:
            cap *= 2
        for name in (
            "start", "last", "row_count", "counts", "sums", "mins", "maxs",
            "means", "m2s", "gid", "link", "live",
        ):
            old = getattr(self, name)
            shape = (cap,) + old.shape[1:]
            if name == "gid" or name == "link":
                new = np.full(shape, -1, dtype=old.dtype)
            else:
                new = np.zeros(shape, dtype=old.dtype)
            new[: self._hwm] = old[: self._hwm]
            setattr(self, name, new)

    # -- slot lifecycle --------------------------------------------------
    def alloc(self, k: int) -> np.ndarray:
        """k fresh slot indices: free-listed slots first, then new ones."""
        reuse = min(k, len(self._free))
        out = np.empty(k, dtype=np.int64)
        if reuse:
            out[:reuse] = self._free[-reuse:]
            del self._free[-reuse:]
        fresh = k - reuse
        if fresh:
            self._ensure_slots(self._hwm + fresh)
            out[reuse:] = np.arange(self._hwm, self._hwm + fresh)
            self._hwm += fresh
        return out

    def free(self, slots: np.ndarray) -> None:
        """Release slots (the caller has already unlinked their chains)."""
        if len(slots) == 0:
            return
        self.live[slots] = False
        self.gid[slots] = -1
        self.link[slots] = -1
        if self.accs:
            for s in slots.tolist():
                self.accs.pop(s, None)
        self._free.extend(int(s) for s in slots.tolist())

    # -- chains ----------------------------------------------------------
    def chain(self, gids: np.ndarray, slots: np.ndarray) -> None:
        """Link ``slots`` into their per-gid chains (join _SideState trick:
        one stable sort; within a same-gid run each slot links to its
        predecessor, the first links to the gid's previous head, the last
        becomes the new head)."""
        n = len(gids)
        if n == 0:
            return
        order = np.argsort(gids, kind="stable")
        gs = np.asarray(gids)[order]
        ss = np.asarray(slots)[order].astype(np.int32)
        first = np.empty(n, dtype=bool)
        first[0] = True
        first[1:] = gs[1:] != gs[:-1]
        linkv = np.empty(n, dtype=np.int32)
        linkv[~first] = ss[:-1][~first[1:]]
        linkv[first] = self.head[gs[first]]
        self.link[ss] = linkv
        last = np.empty(n, dtype=bool)
        last[-1] = True
        last[:-1] = first[1:]
        self.head[gs[last]] = ss[last]

    def open_slots_of(self, gids: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """All open slots of the given gids: (slots, owner_pos) where
        ``owner_pos[i]`` indexes into ``gids``.  Vectorized chain walk —
        one hop per iteration across ALL queried gids simultaneously (the
        join-probe pattern); iterations = max open sessions per key,
        almost always 1."""
        k = len(gids)
        if k == 0:
            e = np.empty(0, dtype=np.int64)
            return e, e
        cur = self.head[np.asarray(gids)].astype(np.int64)
        pos = np.arange(k, dtype=np.int64)
        out_s: list[np.ndarray] = []
        out_p: list[np.ndarray] = []
        while True:
            m = cur >= 0
            if not m.any():
                break
            cur = cur[m]
            pos = pos[m]
            out_s.append(cur)
            out_p.append(pos)
            cur = self.link[cur].astype(np.int64)
        if not out_s:
            e = np.empty(0, dtype=np.int64)
            return e, e
        return np.concatenate(out_s), np.concatenate(out_p)

    def remove_slots(self, slots: np.ndarray) -> np.ndarray:
        """Unlink + free ``slots``; returns the gids left with NO open
        session (candidates for interner gid recycling).  Chains of the
        affected gids are rebuilt from their surviving slots."""
        if len(slots) == 0:
            return np.empty(0, dtype=np.int64)
        affected = np.unique(self.gid[slots]).astype(np.int64)
        all_slots, owner = self.open_slots_of(affected)
        rm = np.zeros(len(self.start), dtype=bool)
        rm[slots] = True
        keep = ~rm[all_slots]
        self.head[affected] = -1
        self.chain(affected[owner[keep]], all_slots[keep])
        self.free(np.asarray(slots))
        return affected[self.head[affected] == -1]

    # -- accounting (obs/statewatch.py) ----------------------------------
    def per_slot_nbytes(self) -> int:
        """Exact bytes one slot occupies across the parallel arrays —
        the restore-invariant unit of the session operator's live-state
        accounting (live bytes = live slots x this; allocated capacity
        is reported separately, it may differ across a restore)."""
        V = self.num_value_cols
        return int(
            self.start.itemsize
            + self.last.itemsize
            + self.row_count.itemsize
            + self.gid.itemsize
            + self.link.itemsize
            + self.live.itemsize
            + V
            * (
                self.counts.itemsize
                + self.sums.itemsize
                + self.mins.itemsize
                + self.maxs.itemsize
                + self.means.itemsize
                + self.m2s.itemsize
            )
        )

    def capacity_nbytes(self) -> int:
        """Actually-allocated storage (all slots, live or free, plus the
        per-gid head index)."""
        return sum(
            int(a.nbytes)
            for a in (
                self.start, self.last, self.row_count, self.counts,
                self.sums, self.mins, self.maxs, self.means, self.m2s,
                self.gid, self.link, self.live, self.head,
            )
        )

    # -- cold-tier eviction hooks (state/tiering.py) ---------------------
    #: the per-slot payload arrays a spill block carries (gid/link/live
    #: are structural and re-derived at reload; accs ride the block meta)
    SPILL_FIELDS = (
        "start", "last", "row_count", "counts", "sums", "mins", "maxs",
        "means", "m2s",
    )

    def extract_slots(self, slots: np.ndarray) -> dict[str, np.ndarray]:
        """Gather the payload arrays of ``slots`` (one vectorized take
        per field) for cold-tier serialization.  The caller follows up
        with :meth:`remove_slots` — extract is read-only."""
        return {
            name: getattr(self, name)[slots].copy()
            for name in self.SPILL_FIELDS
        }

    def inject_slots(
        self, gids: np.ndarray, fields: dict[str, np.ndarray]
    ) -> np.ndarray:
        """Re-admit previously extracted sessions: allocate slots,
        scatter every payload field, and chain them into their gids'
        lists.  Returns the slot indices (for accumulator re-attach)."""
        n = len(gids)
        slots = self.alloc(n)
        for name in self.SPILL_FIELDS:
            getattr(self, name)[slots] = fields[name]
        self.gid[slots] = gids
        self.live[slots] = True
        self.chain(np.asarray(gids, dtype=np.int64), slots)
        return slots

    # -- scans -----------------------------------------------------------
    def live_slots(self) -> np.ndarray:
        return np.nonzero(self.live[: self._hwm])[0]

    def expired_slots(self, gap_ms: int, watermark: int) -> np.ndarray:
        idx = self.live_slots()
        if len(idx) == 0:
            return idx
        return idx[self.last[idx] + gap_ms <= watermark]
