"""Pallas TPU kernel for the windowed-aggregation hot op (dense small-G
path).

The default device step (`segment_agg.update_state`) scatters rows into
``(W, G)`` HBM buffers — general, but scatter on TPU serializes through
sort-based lowering.  For LOW-cardinality aggregation (the emit_measurements
shape: ≤ ~2k groups), this kernel reformulates the scatter as dense
MXU/VPU work per TILE-row tile (TILE=256):

- count/sum become one-hot matmuls on the MXU
  (``one_hot(gid).T @ masked_values``);
- min/max become masked broadcast-reductions on the VPU;
- the few window slots a batch touches (``k_active``, static) are handled by
  masking rows per relative slot, so the kernel accumulates a
  ``(k_active, G)`` VMEM scratch and the caller adds/merges it into the HBM
  ring at ``[base : base+k_active]`` — one dynamic-slice update instead of a
  row scatter.

Selected via ``EngineConfig(device_strategy="pallas_dense")``; falls back to
the scatter path when G or the batch's window span exceeds the dense limits.
Runs under ``interpret=True`` on CPU so tests validate bit-parity with the
scatter path without TPU hardware.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from denormalized_tpu.ops import segment_agg as sa

# dense-path limits: G beyond this, or batches spanning more ring slots than
# K_ACTIVE, fall back to the scatter path
MAX_DENSE_GROUPS = 2048
K_ACTIVE = 8
TILE = 256


def _kernel(
    values_ref,  # (TILE, V) f32
    colvalid_ref,  # (TILE, V) f32 (1.0 valid)
    rel_ref,  # (TILE, KREL) int32 — slots relative to base, -1 = dropped.
    # One column per window the row fans out to (sliding: KREL =
    # length_units), so the whole fan-out costs ONE kernel launch.
    gid_ref,  # (TILE, 1) int32
    cnt_ref,  # (K, G*V) f32 out — valid-entry count per (slot, col, group)
    sum_ref,  # (K, G*V) f32 out
    min_ref,  # (K, G*V) f32 out
    max_ref,  # (K, G*V) f32 out
    rowcnt_ref,  # (K, G) f32 out — rows per (slot, group), for count(*)
    *,
    G: int,
    V: int,
):
    step = pl.program_id(0)
    values = values_ref[:]
    colvalid = colvalid_ref[:]
    rel = rel_ref[:]  # (TILE, KREL)
    gid = gid_ref[:]

    # one-hot over groups, (TILE, G)
    groups = jax.lax.broadcasted_iota(jnp.int32, (TILE, G), 1)
    onehot = (gid == groups).astype(jnp.float32)

    @pl.when(step == 0)
    def _init():
        cnt_ref[:] = jnp.zeros_like(cnt_ref)
        sum_ref[:] = jnp.zeros_like(sum_ref)
        min_ref[:] = jnp.full_like(min_ref, jnp.inf)
        max_ref[:] = jnp.full_like(max_ref, -jnp.inf)
        rowcnt_ref[:] = jnp.zeros_like(rowcnt_ref)

    for j in range(K_ACTIVE):
        # a row feeds slot j through at most one of its KREL fan-out
        # columns (windows are distinct), so the sum is 0/1
        in_slot = jnp.sum(
            (rel == j).astype(jnp.float32), axis=1, keepdims=True
        )  # (TILE, 1)
        oh = onehot * in_slot  # rows of this slot only
        # rows per (slot, group): MXU matmul with a ones vector
        rowcnt_ref[j, :] += jnp.sum(oh, axis=0)
        for v in range(V):
            col = values[:, v : v + 1]  # (TILE, 1)
            ok = colvalid[:, v : v + 1]
            sel = (oh * ok) > 0
            # count/sum via where-selection: masked-out lanes may hold NaN
            # (values behind an invalid mask are unspecified), and 0*NaN
            # would poison a multiplicative mask
            cnt_ref[j, v * G : (v + 1) * G] += jnp.sum(oh * ok, axis=0)
            sum_ref[j, v * G : (v + 1) * G] += jnp.sum(
                jnp.where(sel, col, 0.0), axis=0
            )
            # min/max via masked broadcast reduce on the VPU
            min_ref[j, v * G : (v + 1) * G] = jnp.minimum(
                min_ref[j, v * G : (v + 1) * G],
                jnp.min(jnp.where(sel, col, jnp.inf), axis=0),
            )
            max_ref[j, v * G : (v + 1) * G] = jnp.maximum(
                max_ref[j, v * G : (v + 1) * G],
                jnp.max(jnp.where(sel, col, -jnp.inf), axis=0),
            )


@functools.partial(
    jax.jit, static_argnames=("G", "V", "KREL", "interpret")
)
def _dense_partials(
    values, colvalid, rel, gid, *, G: int, V: int, KREL: int, interpret: bool
):
    """→ (rowcnt (K,G), cnt (K,G,V), sum (K,G,V), min (K,G,V), max (K,G,V))

    ``rel`` is (B, KREL): each row's target slots (rebased), -1 = dropped."""
    B = values.shape[0]
    assert B % TILE == 0
    grid = (B // TILE,)
    outs = pl.pallas_call(
        functools.partial(_kernel, G=G, V=V),
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE, V), lambda i: (i, 0)),
            pl.BlockSpec((TILE, V), lambda i: (i, 0)),
            pl.BlockSpec((TILE, KREL), lambda i: (i, 0)),
            pl.BlockSpec((TILE, 1), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((K_ACTIVE, G * V), lambda i: (0, 0)),
            pl.BlockSpec((K_ACTIVE, G * V), lambda i: (0, 0)),
            pl.BlockSpec((K_ACTIVE, G * V), lambda i: (0, 0)),
            pl.BlockSpec((K_ACTIVE, G * V), lambda i: (0, 0)),
            pl.BlockSpec((K_ACTIVE, G), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((K_ACTIVE, G * V), jnp.float32),
            jax.ShapeDtypeStruct((K_ACTIVE, G * V), jnp.float32),
            jax.ShapeDtypeStruct((K_ACTIVE, G * V), jnp.float32),
            jax.ShapeDtypeStruct((K_ACTIVE, G * V), jnp.float32),
            jax.ShapeDtypeStruct((K_ACTIVE, G), jnp.float32),
        ],
        interpret=interpret,
    )(
        values.astype(jnp.float32),
        colvalid.astype(jnp.float32),
        rel.reshape(-1, KREL),
        gid.reshape(-1, 1),
    )
    cnt, ssum, smin, smax, rowcnt = outs
    shp = (K_ACTIVE, V, G)
    return (
        rowcnt,
        cnt.reshape(shp),
        ssum.reshape(shp),
        smin.reshape(shp),
        smax.reshape(shp),
    )


def dense_supported(spec: sa.WindowKernelSpec) -> bool:
    return (
        spec.group_capacity <= MAX_DENSE_GROUPS
        # sliding fan-out rides the (TILE, k) rel matrix in ONE launch; the
        # batch's slot span must still fit the K_ACTIVE scratch rows (the
        # caller additionally checks the actual span per batch)
        and spec.length_units <= K_ACTIVE
        # the kernel accumulates in f32; honor an explicit f64 request by
        # staying on the scatter path
        and spec.accum_dtype == jnp.float32
        # compensated (hi, lo) sums need the scatter path's TwoSum fold
        and not spec.compensated
    )


@functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
def _merge_partials(spec, state, partials, base_mod):
    """Fold the (K, ...) dense partials into the HBM ring with ONE
    dynamic-window update per component (no row scatter)."""
    rowcnt, cnt, ssum, smin, smax = partials
    W = spec.window_slots
    G = spec.group_capacity
    # ring rows base_mod..base_mod+K (mod W): do it as a K-row scatter-free
    # update using modular row indices via take/set on a small index vector
    rows = (base_mod + jnp.arange(K_ACTIVE, dtype=jnp.int32)) % W
    for comp in spec.components:
        buf = state[comp.label]
        if comp.kind == "count":
            upd = (
                rowcnt if comp.col is None else cnt[:, comp.col, :]
            ).astype(buf.dtype)
            state[comp.label] = buf.at[rows].add(upd)
        elif comp.kind == "sum":
            state[comp.label] = buf.at[rows].add(
                ssum[:, comp.col, :].astype(buf.dtype)
            )
        elif comp.kind == "min":
            state[comp.label] = buf.at[rows].min(
                smin[:, comp.col, :].astype(buf.dtype)
            )
        else:
            state[comp.label] = buf.at[rows].max(
                smax[:, comp.col, :].astype(buf.dtype)
            )
    return state


def dense_update(
    spec: sa.WindowKernelSpec,
    state,
    values,
    colvalid,
    win_rel,
    rem,
    gid,
    row_valid,
    base_mod,
    *,
    min_win_rel: int,
    interpret: bool = False,
):
    """Dense-path equivalent of ``update_state``: compute per-slot partials
    with the pallas kernel, then fold them into the ring.

    ``min_win_rel`` is the smallest window index (relative to first_open) any
    row of this batch touches; the kernel works in ``rel - min_win_rel``
    space so K_ACTIVE covers the batch's span.  Caller guarantees the span
    fits (else it uses the scatter path).  The k-way sliding fan-out is one
    (B, k) rel matrix → ONE kernel launch regardless of k."""
    k = spec.length_units
    rel_cols = []
    for i in range(k):
        wr = win_rel - i
        ok = row_valid & (wr >= 0) & (wr < spec.window_slots)
        if spec.length_ms - i * spec.slide_ms < spec.slide_ms:
            ok = ok & (rem < spec.length_ms - i * spec.slide_ms)
        rel_cols.append(jnp.where(ok, wr - min_win_rel, -1).astype(jnp.int32))
    rel = jnp.stack(rel_cols, axis=1)  # (B, k)
    partials = _dense_partials(
        values,
        colvalid,
        rel,
        gid,
        G=spec.group_capacity,
        V=max(spec.num_value_cols, 1),
        KREL=k,
        interpret=interpret,
    )
    base = (base_mod + jnp.asarray(min_win_rel, jnp.int32)) % spec.window_slots
    return _merge_partials(spec, state, partials, base)
