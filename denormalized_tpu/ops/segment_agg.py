"""Device-resident windowed segment aggregation — THE hot path.

TPU re-design of the reference's ``GroupedWindowAggStream`` /
``GroupedAggWindowFrame`` (grouped_window_agg_stream.rs:501-605): where the
reference keeps one ``GroupValues`` table + boxed ``GroupsAccumulator`` per
open window frame and pushes 32-row batches through them on CPU, we keep ONE
set of ``(num_window_slots, group_capacity)`` accumulator buffers resident in
TPU HBM for *all* open windows and update them with a single ``jax.jit``
step per (large) batch:

- window slots form a ring over the window index (slide index), so sliding
  windows fan out on-device without duplicating row data (the reference
  re-filters the batch once per overlapping frame, streaming_window.rs
  :1063-1075 + :548-605 — O(frames x batch) CPU work);
- group keys arrive as dense int32 ids from the host interner
  (:mod:`denormalized_tpu.ops.interner`);
- nulls are neutralized on-device per aggregate kind (0 for sum, ±inf for
  min/max) so XLA fuses mask+scatter into one pass over the batch;
- all state buffers are donated, so the update is allocation-free at
  steady state;
- late rows (window < first_open) and padding rows are dropped by scatter
  ``mode='drop'`` — the device-side mirror of the reference's late-data drop
  (streaming_window.rs:982-991).

Shapes are static: batches are bucketed to powers of two and state is grown
by re-compilation when group cardinality or window skew exceeds capacity.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True)
class AggComponent:
    """One primitive accumulator buffer.  Composite aggregates decompose:
    avg = sum + count (exactly as DataFusion's AvgGroupsAccumulator does).
    Kind 'sumc' is the compensation (low-order) buffer paired with a 'sum'
    of the same column when the spec runs compensated summation."""

    kind: str  # 'count' | 'sum' | 'min' | 'max' | 'sumc'
    col: int | None  # value-column index; None = row count (count(*))

    @property
    def label(self) -> str:
        return f"{self.kind}_{'star' if self.col is None else self.col}"


# presence counter: always first so emission knows which groups are active
ROW_COUNT = AggComponent("count", None)


from denormalized_tpu.logical.expr import VAR_KINDS  # noqa: E402


def variance_result(
    kind: str, c: np.ndarray, s: np.ndarray, s2: np.ndarray
) -> np.ndarray:
    """Shared variance finalize: ``s``/``s2`` are Σ(x−K) and Σ(x−K)² for any
    constant shift K (callers pick K near the data's magnitude so the
    ``s2 − s²/c`` subtraction doesn't catastrophically cancel — with K=0 and
    epoch-scale values the two terms agree to ~24 digits and f32/f64 both
    return garbage).  The shift cancels exactly in the algebra."""
    c = np.asarray(c, np.float64)
    s = np.asarray(s, np.float64)
    s2 = np.asarray(s2, np.float64)
    with np.errstate(invalid="ignore", divide="ignore"):
        m2 = np.maximum(s2 - s * s / np.maximum(c, 1), 0.0)
    return variance_from_m2(kind, c, m2)


def variance_from_m2(kind: str, c, m2):
    """Variance finalize from Welford/Chan moments (count, M2) — the host
    accumulators' representation."""
    c = np.asarray(c, np.float64)
    m2 = np.asarray(m2, np.float64)
    with np.errstate(invalid="ignore", divide="ignore"):
        if kind.endswith("_pop"):
            v = np.where(c > 0, m2 / np.maximum(c, 1), np.nan)
        else:
            v = np.where(c > 1, m2 / np.maximum(c - 1, 1), np.nan)
    return np.sqrt(v) if kind.startswith("stddev") else v


def chan_merge(n1, mean1, m21, n2, mean2, m22):
    """Chan et al. parallel combine of (count, mean, M2) moment pairs —
    numerically stable for any magnitude, exact merge algebra."""
    n = n1 + n2
    if n == 0:
        return 0.0, 0.0, 0.0
    delta = mean2 - mean1
    mean = mean1 + delta * n2 / n
    m2 = m21 + m22 + delta * delta * n1 * n2 / n
    return n, mean, m2


def components_for(aggs: list[tuple]) -> list[AggComponent]:
    """Decompose aggregate specs into deduped primitive components.

    Spec entries are ``(kind, value_col)`` — or, for the variance family,
    ``(kind, shifted_col, shifted_sq_col)``: the caller registers two
    DEDICATED value columns holding (x−K) and (x−K)² for a pivot K it picks
    from the first data it sees (see ``variance_result``).  ``avg`` → sum +
    count; variance → sum + count + sum of squares over the shifted
    columns (the running-moments decomposition DataFusion's
    VarianceGroupsAccumulator keeps, made cancellation-safe)."""
    comps: list[AggComponent] = [ROW_COUNT]
    for spec in aggs:
        kind, col = spec[0], spec[1]
        if kind == "count":
            wanted = [AggComponent("count", col)]
        elif kind == "avg":
            wanted = [AggComponent("sum", col), AggComponent("count", col)]
        elif kind in VAR_KINDS:
            sq = spec[2]
            wanted = [
                AggComponent("sum", col),
                AggComponent("count", col),
                AggComponent("sum", sq),
            ]
        elif kind in ("sum", "min", "max"):
            wanted = [AggComponent(kind, col)]
        elif kind == "sketch":
            # sketch aggregates carry their own slice-store planes
            # (ops/sketches.py SketchSpec) — no scalar components
            wanted = []
        else:
            raise ValueError(f"unknown aggregate kind {kind!r}")
        for c in wanted:
            if c not in comps:
                comps.append(c)
    return comps


def with_compensation(comps: list[AggComponent]) -> list[AggComponent]:
    """Add a low-order ('sumc') companion for every 'sum' component —
    storage for Kahan-style compensated accumulation (see
    ``update_state_impl``)."""
    out = list(comps)
    for c in comps:
        if c.kind == "sum":
            out.append(AggComponent("sumc", c.col))
    return out


def read_sum(rows: dict[str, np.ndarray], col: int) -> np.ndarray:
    """A column's total from an emitted row set: hi + lo when compensated
    (lo absent → plain)."""
    hi = rows[AggComponent("sum", col).label].astype(np.float64)
    lo = rows.get(AggComponent("sumc", col).label)
    return hi if lo is None else hi + lo.astype(np.float64)


@dataclass(frozen=True)
class WindowKernelSpec:
    """Static configuration of one compiled window-aggregation kernel.

    Window indexing: windows are identified by their *slide index* ``j``,
    covering ``[j*slide_ms, j*slide_ms + length_ms)`` in epoch milliseconds
    (tumbling ⇒ slide == length, epoch-aligned snapping like the reference's
    ``snap_to_window_start``, streaming_window.rs:1088).  The host rebases
    indices to ``win_rel = j - first_open`` so the device works in small
    int32s; ring slots use the *absolute* index mod W via ``base_mod``."""

    components: tuple[AggComponent, ...]
    num_value_cols: int
    window_slots: int  # W — ring size over open window indices
    group_capacity: int  # G — padded group-id capacity (multiple of 128)
    length_ms: int
    slide_ms: int
    accum_dtype: Any = jnp.float32
    # compensated (Kahan-style) summation: each batch's contribution is
    # scattered into a fresh per-batch partial, then folded into the
    # running (hi, lo) pair with an exact TwoSum — cross-batch rounding
    # vanishes, leaving only intra-batch scatter rounding.  Error bound for
    # a group receiving n values per batch over B batches (f32):
    # |err|/|sum| ≲ sqrt(n)·2^-24 per batch partial, combining across
    # batches as a random walk of batch-sized contributions — ~1e-6
    # relative at 1M values/group vs ~1e-4 for plain f32 accumulation.
    compensated: bool = False

    @property
    def length_units(self) -> int:
        """k = number of windows each row fans out to."""
        return -(-self.length_ms // self.slide_ms)

    def init_value(self, comp: AggComponent):
        if comp.kind == "count":
            return jnp.zeros((), jnp.int32)
        if comp.kind in ("sum", "sumc"):
            return jnp.zeros((), self.accum_dtype)
        if comp.kind == "min":
            return jnp.array(jnp.inf, self.accum_dtype)
        if comp.kind == "max":
            return jnp.array(-jnp.inf, self.accum_dtype)
        raise ValueError(comp.kind)


def init_state(spec: WindowKernelSpec) -> dict[str, jax.Array]:
    """Allocate the HBM-resident accumulator buffers: one (W, G) array per
    primitive component."""
    shape = (spec.window_slots, spec.group_capacity)
    return {
        c.label: jnp.full(shape, spec.init_value(c))
        for c in spec.components
    }


def _apply_component(
    spec: WindowKernelSpec,
    comp: AggComponent,
    buf: jax.Array,
    slot: jax.Array,  # (B,) int32, out-of-range => dropped
    gid: jax.Array,  # (B,) int32
    values: jax.Array,  # (B, V) accum_dtype
    colvalid: jax.Array,  # (B, V) bool
) -> jax.Array:
    at = buf.at[slot, gid]
    if comp.kind == "count":
        if comp.col is None:
            inc = jnp.ones(slot.shape, jnp.int32)
        else:
            inc = colvalid[:, comp.col].astype(jnp.int32)
        return at.add(inc, mode="drop")
    v = values[:, comp.col]
    ok = colvalid[:, comp.col]
    if comp.kind == "sum":
        return at.add(jnp.where(ok, v, 0), mode="drop")
    if comp.kind == "min":
        return at.min(jnp.where(ok, v, jnp.inf), mode="drop")
    if comp.kind == "max":
        return at.max(jnp.where(ok, v, -jnp.inf), mode="drop")
    raise ValueError(comp.kind)


def update_state_impl(
    spec: WindowKernelSpec,
    state: dict[str, jax.Array],
    values: jax.Array,  # (B, V)
    colvalid: jax.Array,  # (B, V) bool
    win_rel: jax.Array,  # (B,) int32: slide-index of row minus first_open
    rem_ms: jax.Array,  # (B,) int32: ts - slide_index*slide (in [0, S))
    gid: jax.Array,  # (B,) int32 dense group ids from the host interner
    row_valid: jax.Array,  # (B,) bool (padding rows false)
    base_mod: jax.Array,  # () int32: first_open % W (ring phase)
) -> dict[str, jax.Array]:
    """One device step: scatter the batch into every window frame it belongs
    to.  A row with slide-index ``t`` belongs to windows ``t-k+1 .. t``
    (k = length_units); the fan-out is a static unrolled loop of k scatters —
    XLA fuses the mask/neutralize work, and row data crosses host→HBM once
    regardless of k (tumbling: k=1).  The reference instead re-filters the
    batch once per overlapping frame on CPU (streaming_window.rs:1063-1075)."""
    W = spec.window_slots
    values = values.astype(spec.accum_dtype)
    # compensated mode: scatter 'sum' components into fresh per-batch
    # partials, folded into (hi, lo) once at the end via exact TwoSum
    partials = {}
    if spec.compensated:
        for comp in spec.components:
            if comp.kind == "sum":
                partials[comp.label] = jnp.zeros_like(state[comp.label])
    for i in range(spec.length_units):
        wr = win_rel - i  # rebased index of the i-th window this row feeds
        # membership: window covers the row iff i*S + rem < L (exactly k
        # windows when L % S == 0); late rows (wr < 0 — window already
        # emitted; the reference logs-and-drops at streaming_window.rs:982)
        # and skew overflow (wr >= W, guarded host-side) are masked out.
        ok = row_valid & (wr >= 0) & (wr < W)
        if spec.length_ms - i * spec.slide_ms < spec.slide_ms:
            ok = ok & (rem_ms < spec.length_ms - i * spec.slide_ms)
        # ring slot of the *absolute* window index; invalid rows pushed out of
        # range so mode='drop' skips them
        slot = jnp.where(ok, (wr + base_mod) % W, W).astype(jnp.int32)
        for comp in spec.components:
            if comp.kind == "sumc":
                continue  # written only by the TwoSum fold below
            if comp.kind == "sum" and spec.compensated:
                partials[comp.label] = _apply_component(
                    spec, comp, partials[comp.label], slot, gid, values,
                    colvalid,
                )
                continue
            state[comp.label] = _apply_component(
                spec, comp, state[comp.label], slot, gid, values, colvalid
            )
    if spec.compensated:
        for comp in spec.components:
            if comp.kind != "sum":
                continue
            hi = state[comp.label]
            lo = state[AggComponent("sumc", comp.col).label]
            p = partials[comp.label]
            # Knuth TwoSum: s + e == hi + p exactly
            s = hi + p
            t = s - hi
            e = (hi - (s - t)) + (p - t)
            state[comp.label] = s
            state[AggComponent("sumc", comp.col).label] = lo + e
    return state


# jitted single-device entry; the sharded variants wrap update_state_impl in
# shard_map (see denormalized_tpu.parallel.sharded_state)
update_state = functools.partial(jax.jit, static_argnums=0, donate_argnums=1)(
    update_state_impl
)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4), donate_argnums=5)
def merge_partials(
    spec: WindowKernelSpec,
    SUB: int,
    a_pad: int,
    lean: bool,
    dense: bool,
    state: dict[str, jax.Array],
    packed: jax.Array,  # int32, (P+1, a_pad+2) compact / (P, a_pad+2) dense
) -> dict[str, jax.Array]:
    """Fold host-side partial aggregates into the window ring — the device
    half of the ``partial_merge`` strategy (host edge-reduction +
    accelerator merge; see ops/host_partial.py).

    ``packed`` is an **int32 carrier** (immune to x64-off canonicalization):
    row 0 holds flat cell indices ``((u*SUB)+s)*G + g`` (−1 = padding) plus
    ``u_base_rel`` (stripe unit 0 relative to first_open) and ``base_mod``
    (first_open % W) in its tail slots; value planes are f32 (or f64-pair)
    bitcasts — sums arrive as (hi, lo) so the host's f64 accumulation
    survives transit.  The k-way sliding fan-out happens HERE: unit u's
    partial feeds windows u-k+1..u, with sub-bucket 1 (rows past the
    L-(k-1)S edge) excluded from the oldest window.  Compensated mode
    routes lo into the 'sumc' buffer — one rounding per merge per cell
    instead of one per row.

    ``dense`` selects the index-free layout (host_partial.take_packed
    dense branch): cell i IS flat index i, the index plane is omitted
    (plane p sits at row p, header ints still in row 0's tail slots), and
    padding carries fold-neutral values — the high-density win (≥~75%
    of cells active, e.g. 100K live keys in a 131K ring)."""
    return merge_partials_body(
        spec, SUB, a_pad, state, packed, spec.group_capacity,
        jnp.asarray(0, jnp.int32), lean, dense,
    )


def lean_skippable(c: AggComponent) -> bool:
    """Whether ``c``'s plane is omitted from the LEAN packed/gather layouts
    and aliased to plane 1 (row count).  Single source of truth: the host
    packing (host_partial.take_packed), the device merge unpack, the
    emission gather, and the prewarm plane count must all agree on this
    predicate or plane indices silently shift."""
    return c.kind == "count" and c.col is not None


def lean_possible(spec: WindowKernelSpec) -> bool:
    """Whether the lean layout differs from the full one for this spec."""
    return any(lean_skippable(c) for c in spec.components)


def merge_partials_body(
    spec: WindowKernelSpec,
    SUB: int,
    a_pad: int,
    state: dict[str, jax.Array],
    packed: jax.Array,
    G_total: int,
    g_shift,
    lean: bool = False,
    dense: bool = False,
) -> dict[str, jax.Array]:
    """Shared fold: ``state`` holds the contiguous group slice
    ``[g_shift, g_shift + cap)`` of a ``G_total``-wide group space (single
    device: the whole space, shift 0; key-sharded mesh: one shard per
    device, shift = axis_index * G_local).

    ``lean`` selects the null-free packed layout: per-column count planes
    are omitted from ``packed`` and aliased to the row-count plane — a
    null-free stripe's per-column counts equal its row counts
    cell-for-cell (host_partial.take_packed).

    ``dense`` selects the index-free layout: no index plane (value plane p
    is row p, header stays in row 0's tail slots), cell i is flat index i,
    and pad cells beyond the stripe's span hold fold-neutral values (count
    0, sum 0, min +inf, max −inf) so no validity mask is needed for them."""
    W = spec.window_slots
    u_base_rel = packed[0, a_pad]
    base_mod = packed[0, a_pad + 1]
    if dense:
        safe = jnp.arange(a_pad, dtype=jnp.int32)
        valid = jnp.ones((a_pad,), bool)
    else:
        idx = packed[0, :a_pad]
        valid = idx >= 0
        safe = jnp.maximum(idx, 0)
    g_glob = safe % G_total
    us = safe // G_total
    s = us % SUB
    u = us // SUB
    cap = next(iter(state.values())).shape[1]
    g = g_glob - g_shift
    valid = valid & (g >= 0) & (g < cap)
    g = jnp.clip(g, 0, cap - 1)
    plane0 = 0 if dense else 1

    def f32_plane(pi):
        return jax.lax.bitcast_convert_type(
            packed[plane0 + pi, :a_pad], jnp.float32
        )

    for i in range(spec.length_units):
        ok = valid
        if SUB == 2 and i == spec.length_units - 1:
            ok = ok & (s == 0)
        w_rel = u_base_rel + u - i
        ok = ok & (w_rel >= 0) & (w_rel < W)
        slot = jnp.where(ok, (base_mod + w_rel) % W, W).astype(jnp.int32)
        pi = 0
        for comp in spec.components:
            if comp.kind == "sumc":
                continue
            buf = state[comp.label]
            at = buf.at[slot, g]
            if comp.kind == "sum":
                hi = f32_plane(pi).astype(buf.dtype)
                lo = f32_plane(pi + 1).astype(buf.dtype)
                if spec.compensated:
                    state[comp.label] = at.add(hi, mode="drop")
                    lo_label = AggComponent("sumc", comp.col).label
                    state[lo_label] = state[lo_label].at[slot, g].add(
                        lo, mode="drop"
                    )
                else:
                    # two adds keep most of the host f64 precision even in
                    # a plain f32 buffer
                    state[comp.label] = at.add(hi, mode="drop").at[
                        slot, g
                    ].add(lo, mode="drop")
                pi += 2
                continue
            if lean and lean_skippable(comp):
                pv = f32_plane(0)  # alias the row-count plane
            else:
                pv = f32_plane(pi)
                pi += 1
            if comp.kind == "count":
                state[comp.label] = at.add(pv.astype(buf.dtype), mode="drop")
            elif comp.kind == "min":
                state[comp.label] = at.min(pv.astype(buf.dtype), mode="drop")
            else:
                state[comp.label] = at.max(pv.astype(buf.dtype), mode="drop")
    return state


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 5), donate_argnums=3)
def _gather_and_reset(
    spec: WindowKernelSpec,
    n: int,
    g_bucket: int,
    state: dict[str, jax.Array],
    first_slot,
    lean: bool = False,
):
    """Read ``n`` consecutive ring slots AND reset them in one program —
    one device round-trip per emission cycle instead of two per window.

    ``g_bucket`` is the transferred group width — the GLOBAL capacity for
    sharded layouts (whose static spec carries only the per-device
    shard), the spec capacity on a single device.  ``lean`` omits
    per-column count planes from the transfer (they equal the row-count
    plane when the stream has never carried a null; the host aliases
    them back)."""
    state, comp = _read_and_reset_slots(spec, n, g_bucket, state, first_slot)
    out = {
        c.label: comp[c.label]
        for c in spec.components
        if not (lean and lean_skippable(c))
    }
    return state, out




def _read_and_reset_slots(
    spec: WindowKernelSpec, n: int, g_bucket: int, state, first_slot
):
    """Traced slice of ``n`` consecutive ring slots (``:g_bucket`` group
    prefix) of EVERY component, and re-initialization of those slots in
    the (donated) state — the shared read+reset core of both emission
    paths (_gather_and_reset and _finals_and_reset), so the ':g_bucket
    prefix only' reset invariant cannot diverge between them."""
    W = spec.window_slots
    slots = (first_slot + jnp.arange(n, dtype=jnp.int32)) % W
    comp = {
        c.label: state[c.label][slots, :g_bucket] for c in spec.components
    }
    for c in spec.components:
        # only the transferred prefix needs resetting: cells beyond the
        # live-group prefix were never written
        init = jnp.full((n, g_bucket), spec.init_value(c))
        state[c.label] = state[c.label].at[slots, :g_bucket].set(
            init.astype(state[c.label].dtype)
        )
    return state, comp


# aggregate kinds whose final value is cheap elementwise math over the
# component planes — eligible for on-device finalization at emission
BASIC_FINAL_KINDS = ("count", "sum", "min", "max", "avg")

# key of the packed active-group bitmask in a finals emission block
ACTIVE_BITS = "__active_bits__"


def finals_possible(agg_specs: tuple) -> bool:
    """True when every output aggregate can be finalized on device (the
    variance family needs the host's pivot-shifted f64 algebra)."""
    return all(s[0] in BASIC_FINAL_KINDS for s in agg_specs)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3), donate_argnums=4)
def _finals_and_reset(
    spec: WindowKernelSpec,
    agg_specs: tuple,
    n: int,
    g_bucket: int,
    state: dict[str, jax.Array],
    first_slot,
):
    """Emission with on-device finalization: read ``n`` ring slots, compute
    the FINAL output columns (count/sum/min/max/avg) and an active-group
    bitmask on device, reset the slots, and return only the finals.

    Versus the component gather this ships one ``accum_dtype`` plane per
    OUTPUT aggregate plus ``g_bucket/8`` mask bytes — instead of one plane
    per primitive component (row count, per-column counts, Kahan hi+lo sum
    pairs).  On a narrow host↔device link emission traffic drops by the
    component/output ratio (e.g. 12→8.5 bytes per group for sum+avg,
    12→4.5 for a single avg).  Precision: a compensated sum is emitted as
    fl(hi+lo) — the correctly-rounded ``accum_dtype`` value of the
    maintained sum (≤1 ulp), vs the host's f64 hi+lo add; checkpoints and
    state export still carry full components, so this rounding affects
    emitted values only.  Mirrors ``GroupsAccumulator::evaluate``
    (grouped_window_agg_stream.rs:609-629) run device-side."""
    state, comp = _read_and_reset_slots(spec, n, g_bucket, state, first_slot)
    rc = comp[ROW_COUNT.label]
    out = {ACTIVE_BITS: jnp.packbits(rc > 0, axis=1)}

    def cnt_of(col):
        lbl = AggComponent("count", col).label
        return comp[lbl] if lbl in comp else rc

    def sum_of(col):
        hi = comp[AggComponent("sum", col).label]
        lo = comp.get(AggComponent("sumc", col).label)
        return hi if lo is None else hi + lo

    nan = jnp.asarray(jnp.nan, spec.accum_dtype)
    for i, s in enumerate(agg_specs):
        kind, col = s[0], s[1]
        if kind == "count":
            f = cnt_of(col)
        elif kind == "sum":
            f = sum_of(col)
        elif kind == "avg":
            c = cnt_of(col)
            f = jnp.where(c > 0, sum_of(col) / jnp.maximum(c, 1), nan)
        elif kind == "min":
            v = comp[AggComponent("min", col).label]
            f = jnp.where(jnp.isposinf(v), nan, v)
        elif kind == "max":
            v = comp[AggComponent("max", col).label]
            f = jnp.where(jnp.isneginf(v), nan, v)
        else:  # pragma: no cover — guarded by finals_possible
            raise ValueError(kind)
        out[f"__final_{i}__"] = f
    return state, out


@functools.partial(jax.jit, static_argnums=0, donate_argnums=1)
def reset_slot(
    spec: WindowKernelSpec, state: dict[str, jax.Array], slot: jax.Array
) -> dict[str, jax.Array]:
    """Re-initialize one ring slot after its window was emitted, freeing it
    for reuse (the reference instead drops the whole frame from its BTreeMap,
    streaming_window.rs:703-730; our buffers are preallocated)."""
    for comp in spec.components:
        buf = state[comp.label]
        state[comp.label] = buf.at[slot].set(
            jnp.full((spec.group_capacity,), spec.init_value(comp))
        )
    return state


@functools.partial(jax.jit, static_argnums=0)
def _gather_slot(spec: WindowKernelSpec, state, slot):
    # slot is TRACED: one compiled program serves every ring slot.  Indexing
    # with a Python int instead would compile a fresh gather per distinct
    # slot — ruinous on a remote-compile TPU backend (seconds per window).
    return {
        c.label: jax.lax.dynamic_index_in_dim(
            state[c.label], slot, axis=0, keepdims=False
        )
        for c in spec.components
    }


def read_slot(
    spec: WindowKernelSpec, state: dict[str, jax.Array], slot: int
) -> dict[str, np.ndarray]:
    """Fetch one window's accumulator rows to host (device→host crossing of
    G-sized vectors only — results, never raw rows)."""
    return jax.device_get(
        _gather_slot(spec, state, jnp.asarray(slot, jnp.int32))
    )


@functools.partial(jax.jit, static_argnums=0)
def _compact_slot(spec: WindowKernelSpec, state, slot):
    """Device-side emission compaction: permute one window row so ACTIVE
    groups come first, returning (active_count, permuted gids, permuted
    component rows).  The host then transfers only a power-of-two bucket
    covering the active prefix instead of all G entries — the win when
    emitted windows are sparse relative to the padded group capacity."""
    counts = jax.lax.dynamic_index_in_dim(
        state[ROW_COUNT.label], slot, axis=0, keepdims=False
    )
    active = counts > 0
    n_active = jnp.sum(active.astype(jnp.int32))
    # stable argsort of ~active floats active gids to the front in order
    perm = jnp.argsort(~active, stable=True)
    out = {"__gids__": perm.astype(jnp.int32), "__count__": n_active}
    for c in spec.components:
        row = jax.lax.dynamic_index_in_dim(
            state[c.label], slot, axis=0, keepdims=False
        )
        out[c.label] = row[perm]
    return out


def read_slot_compact(
    spec: WindowKernelSpec, state: dict[str, jax.Array], slot,
    capacity: int | None = None,
) -> tuple[np.ndarray, dict[str, np.ndarray]]:
    """→ (active gids ascending, component rows aligned to them).

    Two-phase transfer: the scalar active count crosses first, then a
    pow2-bucketed prefix of the compacted buffers — one compiled program
    per bucket size, ≤ log2(G) programs total.  ``capacity`` overrides the
    spec's group width for sharded layouts whose state is globally shaped
    while the spec carries the per-device shard."""
    compacted = _compact_slot(spec, state, jnp.asarray(slot, jnp.int32))
    k = int(jax.device_get(compacted["__count__"]))
    if k == 0:
        return np.empty(0, dtype=np.int32), {
            c.label: np.empty(
                0, dtype=np.asarray(jax.device_get(spec.init_value(c))).dtype
            )
            for c in spec.components
        }
    bucket = min(
        1 << (k - 1).bit_length(), capacity or spec.group_capacity
    )
    host = jax.device_get(
        {
            name: jax.lax.slice_in_dim(arr, 0, bucket)
            for name, arr in compacted.items()
            if name != "__count__"
        }
    )
    gids = host.pop("__gids__")[:k]
    rows = {label: arr[:k] for label, arr in host.items()}
    # ascending gid order (argsort floated actives in gid order already,
    # but make the contract explicit for callers)
    return gids, rows


def export_state(state: dict[str, jax.Array]) -> dict[str, np.ndarray]:
    """Full device→host snapshot (checkpointing / capacity growth)."""
    return jax.device_get(state)


@jax.jit
def clone_state(state: dict[str, jax.Array]) -> dict[str, jax.Array]:
    """On-device copy of the window ring — an immutable snapshot source
    that later (donated) update programs cannot touch, so its
    device→host transfer can run asynchronously under ingest (the
    drain-free analog of the reference's state()-then-reseed trick,
    grouped_window_agg_stream.rs:379-394)."""
    return {k: jnp.copy(v) for k, v in state.items()}


def import_state(
    spec: WindowKernelSpec, host_state: dict[str, np.ndarray]
) -> dict[str, jax.Array]:
    """Rebuild device state from a host snapshot, padding up to the spec's
    (possibly larger) capacity — used on restore and on G/W growth."""
    state = init_state(spec)
    out = {}
    for comp in spec.components:
        # np.array copies: device_get may hand back read-only views
        buf = np.array(jax.device_get(state[comp.label]))
        src = host_state.get(comp.label)
        if src is not None:
            w = min(src.shape[0], buf.shape[0])
            g = min(src.shape[1], buf.shape[1])
            buf[:w, :g] = src[:w, :g]
        out[comp.label] = jnp.asarray(buf)
    return out


def finalize(
    agg_specs: list[tuple],
    rows: dict[str, np.ndarray],
    active: np.ndarray,
) -> list[np.ndarray]:
    """Host-side final evaluation of one emitted window from its primitive
    component rows (the mirror of ``Accumulator::evaluate`` /
    ``GroupsAccumulator::evaluate`` at grouped_window_agg_stream.rs:609-629).

    ``active`` is the boolean mask of live group slots in this window."""
    outs: list[np.ndarray] = []
    for spec in agg_specs:
        kind, col = spec[0], spec[1]
        if kind in VAR_KINDS:
            sq = spec[2]
            outs.append(
                variance_result(
                    kind,
                    rows[AggComponent("count", col).label][active],
                    read_sum(rows, col)[active],
                    read_sum(rows, sq)[active],
                )
            )
            continue
        if kind == "count":
            label = AggComponent("count", col).label
            outs.append(rows[label][active].astype(np.int64))
        elif kind == "sum":
            outs.append(read_sum(rows, col)[active])
        elif kind == "avg":
            s = read_sum(rows, col)[active]
            c = rows[AggComponent("count", col).label][active].astype(np.float64)
            with np.errstate(invalid="ignore", divide="ignore"):
                outs.append(np.where(c > 0, s / np.maximum(c, 1), np.nan))
        elif kind == "min":
            v = rows[AggComponent("min", col).label][active].astype(np.float64)
            outs.append(np.where(np.isposinf(v), np.nan, v))
        elif kind == "max":
            v = rows[AggComponent("max", col).label][active].astype(np.float64)
            outs.append(np.where(np.isneginf(v), np.nan, v))
        else:
            raise ValueError(kind)
    return outs
