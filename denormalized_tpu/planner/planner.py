"""Physical planner: logical plan → executable operator tree.

Counterpart of the reference's ``StreamingQueryPlanner`` +
``StreamingWindowPlanner`` extension (query_planner.rs:11-30,
planner/streaming_window.rs:71-172).  Where the reference decides
Partial+Final vs Single aggregation by input partitioning and injects a hash
``RepartitionExec`` via a physical optimizer rule
(coalesce_before_streaming_window_aggregate.rs:32-95), the TPU build has no
cross-thread exchange to plan: partition-parallelism maps to device sharding
inside the window operator (see :mod:`denormalized_tpu.parallel`), so the
planner decides *which window operator variant* to instantiate (dense device
kernel / UDAF host loop / session) and threads sharding config through.
"""

from __future__ import annotations

from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.logical import plan as lp
from denormalized_tpu.physical.base import ExecOperator
from denormalized_tpu.physical.simple_execs import (
    FilterExec,
    ProjectExec,
    SinkExec,
    SourceExec,
)
from denormalized_tpu.physical.window_exec import StreamingWindowExec


class Planner:
    def __init__(self, config=None) -> None:
        # config: api.context.EngineConfig
        self.config = config

    def _route_approx(self, node) -> list:
        """Route approximate aggregates: on the slice path they stay
        first-class sketch kinds (constant-state mergeable planes,
        ops/sketches.py); everywhere else — sessions, the device ring,
        default config, plans mixing true UDAFs, or
        ``approx_native=False`` — each lowers to the exact accumulator
        UDAF it historically was, preserving every prior behavior."""
        from denormalized_tpu.logical.expr import (
            SKETCH_AGG_KINDS,
            AggregateExpr,
        )

        aggs = node.aggr_exprs
        if not any(a.kind in SKETCH_AGG_KINDS for a in aggs):
            return aggs
        native = (
            node.window_type is not lp.WindowType.SESSION
            and self.config is not None
            and getattr(self.config, "slice_windows", False)
            and getattr(self.config, "approx_native", True)
            and not getattr(self.config, "mesh_devices", None)
            and not any(a.kind == "udaf" for a in aggs)
        )
        if native:
            return aggs
        lowered = []
        for a in aggs:
            if a.kind in SKETCH_AGG_KINDS:
                if a.udaf is None:
                    raise PlanError(
                        f"approximate aggregate {a.name!r} has no "
                        "accumulator fallback and the plan cannot take "
                        "the slice path (sketch aggregates need "
                        "EngineConfig(slice_windows=True) here)"
                    )
                lowered.append(
                    AggregateExpr("udaf", a.arg, a._alias, a.udaf)
                )
            else:
                lowered.append(a)
        return lowered

    def create_physical_plan(self, node: lp.LogicalPlan) -> ExecOperator:
        # extension point: a logical node that knows how to build its own
        # exec (the cluster runtime's ExchangeScan leaf) builds it here —
        # the planner stays ignorant of subsystem-specific operators
        hook = getattr(node, "create_exec", None)
        if hook is not None:
            return hook(self)
        if isinstance(node, lp.Scan):
            return SourceExec(
                node.source,
                idle_timeout_ms=getattr(
                    self.config, "source_idle_timeout_ms", None
                )
                if self.config is not None
                else None,
                partition_watermarks=getattr(
                    self.config, "partition_watermarks", "auto"
                )
                if self.config is not None
                else "auto",
            )
        if isinstance(node, lp.Project):
            child = self.create_physical_plan(node.input)
            return ProjectExec(child, node.exprs, node.schema)
        if isinstance(node, lp.Filter):
            child = self.create_physical_plan(node.input)
            return FilterExec(child, node.predicate)
        if isinstance(node, lp.StreamingWindow):
            child = self.create_physical_plan(node.input)
            aggr_exprs = self._route_approx(node)
            kwargs = {}
            if self.config is not None:
                mesh = None
                if getattr(self.config, "mesh_slices", None) and not (
                    self.config.mesh_devices
                ):
                    raise ValueError(
                        "mesh_slices requires mesh_devices (the 2-D "
                        "layout needs the total device count) — the job "
                        "would otherwise silently run single-device"
                    )
                if self.config.mesh_devices:
                    from denormalized_tpu.parallel.mesh import (
                        make_mesh,
                        make_mesh_2d,
                    )

                    if getattr(self.config, "mesh_slices", None):
                        import jax as _jax

                        n_dev = self.config.mesh_devices
                        n_sl = self.config.mesh_slices
                        if n_sl > n_dev or n_dev % n_sl:
                            raise ValueError(
                                f"mesh_devices={n_dev} must be a multiple "
                                f"of mesh_slices={n_sl} (each slice gets "
                                f"mesh_devices/mesh_slices key shards)"
                            )
                        if n_sl & (n_sl - 1):
                            # batches bucket to powers of two and rows
                            # shard P(slices): a non-pow2 slice count
                            # would die on the first batch mid-stream
                            # with a cryptic divisibility error
                            raise ValueError(
                                f"mesh_slices={n_sl} must be a power of "
                                f"two (batches are pow2-bucketed and rows "
                                f"split across slices)"
                            )
                        mesh = make_mesh_2d(
                            n_sl,
                            n_dev // n_sl,
                            devices=_jax.devices()[:n_dev],
                        )
                    else:
                        mesh = make_mesh(self.config.mesh_devices)
                kwargs.update(
                    accum_dtype=self.config.accum_dtype,
                    compensated_sums=self.config.compensated_sums,
                    min_group_capacity=self.config.min_group_capacity,
                    min_window_slots=self.config.min_window_slots,
                    min_batch_bucket=self.config.min_batch_bucket,
                    emit_on_close=self.config.emit_on_close,
                    emission_compaction=self.config.emission_compaction,
                    device_finalize=self.config.device_finalize,
                    mesh=mesh,
                    shard_strategy=self.config.shard_strategy,
                    device_strategy=self.config.device_strategy,
                    partial_merge_rows=self.config.partial_merge_rows,
                    emit_lag_ms=self.config.emit_lag_ms,
                    host_pipeline=self.config.host_pipeline,
                )
            if node.window_type is lp.WindowType.SESSION:
                # sessions handle builtin AND accumulator (UDAF/collection)
                # aggregates in one operator
                import os

                if os.environ.get("DENORMALIZED_SESSION_REFERENCE") == "1":
                    # escape hatch + differential-oracle path: the
                    # pre-vectorization operator, kept verbatim
                    from denormalized_tpu.physical.session_reference import (
                        ReferenceSessionWindowExec as SessionWindowExec,
                    )
                else:
                    from denormalized_tpu.physical.session_exec import (
                        SessionWindowExec,
                    )

                return SessionWindowExec(
                    child,
                    node.group_exprs,
                    aggr_exprs,
                    gap_ms=node.length_ms,
                    emit_on_close=kwargs.get("emit_on_close", True),
                )
            if any(a.kind == "udaf" for a in aggr_exprs):
                from denormalized_tpu.physical.udaf_exec import UdafWindowExec

                return UdafWindowExec(
                    child,
                    node.group_exprs,
                    aggr_exprs,
                    node.window_type,
                    node.length_ms,
                    node.slide_ms,
                    emit_on_close=kwargs.get("emit_on_close", True),
                )
            if (
                self.config is not None
                and getattr(self.config, "slice_windows", False)
                and not self.config.mesh_devices
            ):
                # slice-fold fast path (docs/multi_query.md): every
                # builtin aggregate folds from slice partials, so a
                # sliding window pays O(1) per row + O(L/slide) per
                # emitted window instead of the k-way scatter fan-out.
                # Host kernel — a device mesh keeps the ring operator.
                from denormalized_tpu.physical.slice_exec import (
                    SliceSubscriber,
                    SliceWindowExec,
                )

                return SliceWindowExec(
                    child,
                    node.group_exprs,
                    [
                        SliceSubscriber(
                            aggr_exprs,
                            node.length_ms,
                            node.slide_ms or node.length_ms,
                        )
                    ],
                    emit_on_close=kwargs.get("emit_on_close", True),
                    unit_ms=getattr(self.config, "slice_unit_ms", None),
                    sort_lane=getattr(
                        self.config, "slice_sort_lane", False
                    ),
                )
            return StreamingWindowExec(
                child,
                node.group_exprs,
                aggr_exprs,
                node.window_type,
                node.length_ms,
                node.slide_ms,
                **kwargs,
            )
        if isinstance(node, lp.Join):
            from denormalized_tpu.physical.join_exec import StreamingJoinExec

            left = self.create_physical_plan(node.left)
            right = self.create_physical_plan(node.right)
            jkw = {}
            if self.config is not None:
                jkw["retention_ms"] = self.config.join_retention_ms
                jkw["adaptive"] = bool(self.config.join_adaptive)
                jkw["adapt_interval_s"] = (
                    self.config.join_adapt_interval_s
                )
                jkw["band_slack_ms"] = self.config.join_band_slack_ms
            return StreamingJoinExec(
                left,
                right,
                node.kind,
                node.left_keys,
                node.right_keys,
                node.filter,
                node.schema,
                band=node.band,
                **jkw,
            )
        if isinstance(node, lp.Sink):
            child = self.create_physical_plan(node.input)
            return SinkExec(child, node.sink)
        raise PlanError(f"no physical rule for {type(node).__name__}")
