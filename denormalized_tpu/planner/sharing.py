"""Cross-query sharing pass: which concurrently registered window
queries can fold from ONE shared slice store.

The Factor-Windows rewrite rules (PAPERS.md), applied conservatively:
a set of queries shares one ingest + slice store iff

1. they read the SAME upstream subtree below their filters — same
   source object, same projections (structural signature, source
   compared by identity: two scans of one registered Source are one
   feed, two different Source objects are two feeds even if their
   contents agree), and for stream-stream joins the same join
   signature (kind, equi keys, band predicate, join filter, both side
   subtrees — ONE ``StreamingJoinExec`` then feeds the whole group) —
   and their filter predicates either match exactly
   or nest under predicate subsumption: a query whose filter provably
   IMPLIES another member's filter (planner/predicates.py) joins that
   member's group, which then ingests+interns once under the WEAKEST
   member predicate while the slice operator re-applies each stronger
   member's own full predicate as a vectorized residual mask;
2. they group by the SAME key expressions (the slice store is keyed by
   the shared interner's dense gids);
3. every aggregate folds from slice partials (builtin count / sum /
   min / max / avg / variance family — UDAFs hold opaque per-window
   accumulator state and cannot fold);
4. the common slice width ``g = gcd over members of (length, slide)``
   keeps every member's fold fan-in ``length/g`` under a cost bound —
   the cost-based half of the rewrite: two queries at 60s/7ms and
   60s/1000ms would share a 1ms slice and pay a 60000-way fold per
   window, slower than running them independently.

Filters only participate in subsumption when they sit directly under
the window (``Filter* → (Project|Scan)…``) — a filter buried below a
projection keeps exact-signature matching, because its predicate reads
pre-projection columns the residual mask could no longer see.

Queries that fail any rule fall back to independent plans (the
negative-path contract tests pin this).
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass, field

from denormalized_tpu.logical import plan as lp
from denormalized_tpu.physical.slice_exec import FOLDABLE_KINDS
from denormalized_tpu.planner import predicates as pr

#: cost guard: maximum slice partials one window fold may combine.
#: Past this, the fold itself dominates and independent plans win.
MAX_SLICES_PER_WINDOW = 4096


_OPAQUE = itertools.count()


def input_signature(node: lp.LogicalPlan) -> str:
    """Structural signature of a window's upstream subtree.  Scans key
    on SOURCE IDENTITY; filters/projections on expression reprs; joins
    key on (kind, equi-key pairs, band, join filter) plus BOTH side
    signatures recursively — two windows over structurally identical
    joins of the same sources run ONE ``StreamingJoinExec`` whose
    output fans into the shared slice store.  Any other shape (nested
    windows, UDFs) is opaque — NEVER shared, so the opaque token is
    unique per call (two windows over the same unreviewed subtree must
    not silently share a pipeline)."""
    if isinstance(node, lp.Scan):
        return f"scan#{id(node.source)}"
    if isinstance(node, lp.Filter):
        return f"filter[{node.predicate!r}]({input_signature(node.input)})"
    if isinstance(node, lp.Project):
        exprs = ",".join(repr(e) for e in node.exprs)
        return f"project[{exprs}]({input_signature(node.input)})"
    if isinstance(node, lp.Join):
        keys = ",".join(
            f"{l}={r}" for l, r in zip(node.left_keys, node.right_keys)
        )
        parts = [node.kind.value, keys]
        if node.band is not None:
            b = node.band
            parts.append(
                f"band[{b.left_expr!r};{b.right_expr!r};"
                f"{b.lower_ms};{b.upper_ms}]"
            )
        if node.filter is not None:
            parts.append(f"filter[{node.filter!r}]")
        return (
            f"join[{';'.join(parts)}]"
            f"({input_signature(node.left)})({input_signature(node.right)})"
        )
    return f"opaque#{next(_OPAQUE)}"


def split_filter_chain(node: lp.LogicalPlan):
    """Peel the ``Filter*`` prefix directly under a window → (predicate
    list, remaining skeleton node)."""
    preds = []
    while isinstance(node, lp.Filter):
        preds.append(node.predicate)
        node = node.input
    return preds, node


@dataclass
class _Entry:
    """One shareable window query's planning facts."""

    window: lp.LogicalPlan
    preds: list  # lifted filter predicates (conjunctive)
    cons: pr.Constraints
    filter_sig: str


def classify(plan: lp.LogicalPlan):
    """→ ``(bucket_key, _Entry)`` when ``plan`` is a shareable window
    query, else ``(None, reason)``.  The bucket key carries the
    filter-free skeleton — members of one bucket may still split into
    several groups by predicate implication."""
    if not isinstance(plan, lp.StreamingWindow):
        return None, f"top node is {type(plan).__name__}, not a window"
    if plan.window_type is lp.WindowType.SESSION:
        return None, "session windows hold per-key gap state (no slices)"
    bad = [a.kind for a in plan.aggr_exprs if a.kind not in FOLDABLE_KINDS]
    if bad:
        return None, f"aggregate kind(s) {bad} do not fold from slices"
    group_sig = tuple(repr(g) for g in plan.group_exprs)
    preds, skeleton = split_filter_chain(plan.input)
    entry = _Entry(
        window=plan,
        preds=preds,
        cons=pr.analyze(preds),
        filter_sig=pr.predicate_signature(preds),
    )
    return (input_signature(skeleton), group_sig), entry


@dataclass
class ShareGroup:
    """One planning decision: either a shared slice plan over
    ``members`` (≥ 2 queries, ``shared=True``) or an independent
    fallback (singleton, or a documented rejection ``reason``).

    For a shared group, ``input_plan`` is the BASE member's full input
    (its filter chain included — the weakest predicate in the group),
    ``filters[k]`` is member k's residual predicate the slice operator
    re-applies per row (None when the member's predicate is already
    the base predicate — no re-filter), and ``filter_sigs[k]`` the
    member's full-predicate signature (checkpoint identity)."""

    members: list[int]
    shared: bool
    windows: list = field(default_factory=list)
    input_plan: lp.LogicalPlan | None = None
    unit_ms: int | None = None
    reason: str | None = None
    filters: list = field(default_factory=list)
    filter_sigs: list = field(default_factory=list)
    base_sig: str | None = None


@dataclass
class _Proto:
    """Greedy group under construction: ``base`` is the weakest member
    seen so far (every member's predicate implies it — base-widening
    preserves the invariant by transitivity)."""

    base: _Entry
    members: list  # [(index, _Entry)]


def detect_sharing(
    plans: list[lp.LogicalPlan],
    max_slices_per_window: int = MAX_SLICES_PER_WINDOW,
    subsumption: bool = True,
) -> list[ShareGroup]:
    """Partition query plans into shared groups + independent
    fallbacks.  Order inside a group follows registration order, and
    every input index appears in exactly one group.  With
    ``subsumption=False`` only textually identical predicates share
    (the pre-subsumption behavior — the A/B control)."""
    buckets: dict = {}
    singles: list[ShareGroup] = []
    for i, plan in enumerate(plans):
        key, entry_or_reason = classify(plan)
        if key is None:
            singles.append(
                ShareGroup([i], shared=False, reason=entry_or_reason)
            )
            continue
        buckets.setdefault(key, []).append((i, entry_or_reason))
    groups: list[ShareGroup] = []
    for _key, members in buckets.items():
        protos: list[_Proto] = []
        for i, e in members:
            placed = False
            for pg in protos:
                if e.filter_sig == pg.base.filter_sig:
                    pg.members.append((i, e))
                    placed = True
                    break
                if not subsumption:
                    continue
                if pr.implies(e.cons, pg.base.cons):
                    # e is at least as strong as the base: its rows are
                    # a subset of what the group already ingests
                    pg.members.append((i, e))
                    placed = True
                    break
                if pr.implies(pg.base.cons, e.cons):
                    # e is strictly weaker: widen the group's ingest to
                    # e's predicate — every existing member implies the
                    # old base, which implies e (transitivity)
                    pg.base = e
                    pg.members.append((i, e))
                    placed = True
                    break
            if not placed:
                protos.append(_Proto(base=e, members=[(i, e)]))
        for pg in protos:
            if len(pg.members) == 1:
                i, _e = pg.members[0]
                groups.append(
                    ShareGroup([i], shared=False, reason="no co-registered "
                               "query shares this source+filter+keys")
                )
                continue
            g = 0
            for _i, e in pg.members:
                w = e.window
                slide = int(w.slide_ms) if w.slide_ms else int(w.length_ms)
                g = math.gcd(g, math.gcd(int(w.length_ms), slide))
            worst = max(
                int(e.window.length_ms) // g for _i, e in pg.members
            )
            if worst > max_slices_per_window:
                # cost-based rejection: the gcd slice is so fine that
                # folds dominate — run the members independently
                for i, _e in pg.members:
                    groups.append(
                        ShareGroup(
                            [i], shared=False,
                            reason=(
                                f"gcd slice {g}ms gives a {worst}-way fold "
                                f"(> {max_slices_per_window}) — independent "
                                "plans are cheaper"
                            ),
                        )
                    )
                continue
            base = pg.base
            groups.append(
                ShareGroup(
                    [i for i, _e in pg.members],
                    shared=True,
                    windows=[e.window for _i, e in pg.members],
                    input_plan=base.window.input,
                    unit_ms=g,
                    filters=[
                        None if e.filter_sig == base.filter_sig
                        else pr.conjoin(e.preds)
                        for _i, e in pg.members
                    ],
                    filter_sigs=[e.filter_sig for _i, e in pg.members],
                    base_sig=base.filter_sig,
                )
            )
    # deterministic output order: by first member index
    out = groups + singles
    out.sort(key=lambda grp: grp.members[0])
    return out
