"""Cross-query sharing pass: which concurrently registered window
queries can fold from ONE shared slice store.

The Factor-Windows rewrite rules (PAPERS.md), applied conservatively:
a set of queries shares one ingest + slice store iff

1. they read the SAME upstream subtree — same source object, same
   filter predicates, same projections (structural signature, source
   compared by identity: two scans of one registered Source are one
   feed, two different Source objects are two feeds even if their
   contents agree);
2. they group by the SAME key expressions (the slice store is keyed by
   the shared interner's dense gids);
3. every aggregate folds from slice partials (builtin count / sum /
   min / max / avg / variance family — UDAFs hold opaque per-window
   accumulator state and cannot fold);
4. the common slice width ``g = gcd over members of (length, slide)``
   keeps every member's fold fan-in ``length/g`` under a cost bound —
   the cost-based half of the rewrite: two queries at 60s/7ms and
   60s/1000ms would share a 1ms slice and pay a 60000-way fold per
   window, slower than running them independently.

Queries that fail any rule fall back to independent plans (the
negative-path contract tests pin this).
"""

from __future__ import annotations

import itertools
import math
from dataclasses import dataclass, field

from denormalized_tpu.logical import plan as lp
from denormalized_tpu.physical.slice_exec import FOLDABLE_KINDS

#: cost guard: maximum slice partials one window fold may combine.
#: Past this, the fold itself dominates and independent plans win.
MAX_SLICES_PER_WINDOW = 4096


_OPAQUE = itertools.count()


def input_signature(node: lp.LogicalPlan) -> str:
    """Structural signature of a window's upstream subtree.  Scans key
    on SOURCE IDENTITY; filters/projections on expression reprs; any
    other shape (joins, nested windows) is opaque — NEVER shared, so
    the opaque token is unique per call (two windows over the same
    join node must not silently share an unreviewed pipeline; sharing
    joins' windowed inputs is ROADMAP item-2 residue)."""
    if isinstance(node, lp.Scan):
        return f"scan#{id(node.source)}"
    if isinstance(node, lp.Filter):
        return f"filter[{node.predicate!r}]({input_signature(node.input)})"
    if isinstance(node, lp.Project):
        exprs = ",".join(repr(e) for e in node.exprs)
        return f"project[{exprs}]({input_signature(node.input)})"
    return f"opaque#{next(_OPAQUE)}"


def classify(plan: lp.LogicalPlan):
    """→ ``(share_key, window_node)`` when ``plan`` is a shareable
    window query, else ``(None, reason)``."""
    if not isinstance(plan, lp.StreamingWindow):
        return None, f"top node is {type(plan).__name__}, not a window"
    if plan.window_type is lp.WindowType.SESSION:
        return None, "session windows hold per-key gap state (no slices)"
    bad = [a.kind for a in plan.aggr_exprs if a.kind not in FOLDABLE_KINDS]
    if bad:
        return None, f"aggregate kind(s) {bad} do not fold from slices"
    group_sig = tuple(repr(g) for g in plan.group_exprs)
    return (input_signature(plan.input), group_sig), plan


@dataclass
class ShareGroup:
    """One planning decision: either a shared slice plan over
    ``members`` (≥ 2 queries, ``shared=True``) or an independent
    fallback (singleton, or a documented rejection ``reason``)."""

    members: list[int]
    shared: bool
    windows: list = field(default_factory=list)
    input_plan: lp.LogicalPlan | None = None
    unit_ms: int | None = None
    reason: str | None = None


def detect_sharing(
    plans: list[lp.LogicalPlan],
    max_slices_per_window: int = MAX_SLICES_PER_WINDOW,
) -> list[ShareGroup]:
    """Partition query plans into shared groups + independent
    fallbacks.  Order inside a group follows registration order, and
    every input index appears in exactly one group."""
    buckets: dict = {}
    singles: list[ShareGroup] = []
    for i, plan in enumerate(plans):
        key, node_or_reason = classify(plan)
        if key is None:
            singles.append(
                ShareGroup([i], shared=False, reason=node_or_reason)
            )
            continue
        buckets.setdefault(key, []).append((i, node_or_reason))
    groups: list[ShareGroup] = []
    for key, members in buckets.items():
        if len(members) == 1:
            i, _w = members[0]
            groups.append(
                ShareGroup([i], shared=False, reason="no co-registered "
                           "query shares this source+filter+keys")
            )
            continue
        g = 0
        for _i, w in members:
            slide = int(w.slide_ms) if w.slide_ms else int(w.length_ms)
            g = math.gcd(g, math.gcd(int(w.length_ms), slide))
        worst = max(int(w.length_ms) // g for _i, w in members)
        if worst > max_slices_per_window:
            # cost-based rejection: the gcd slice is so fine that folds
            # dominate — run the members independently
            for i, _w in members:
                groups.append(
                    ShareGroup(
                        [i], shared=False,
                        reason=(
                            f"gcd slice {g}ms gives a {worst}-way fold "
                            f"(> {max_slices_per_window}) — independent "
                            "plans are cheaper"
                        ),
                    )
                )
            continue
        groups.append(
            ShareGroup(
                [i for i, _w in members],
                shared=True,
                windows=[w for _i, w in members],
                input_plan=members[0][1].input,
                unit_ms=g,
            )
        )
    # deterministic output order: by first member index
    out = groups + singles
    out.sort(key=lambda grp: grp.members[0])
    return out
