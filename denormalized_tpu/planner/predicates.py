"""Conservative predicate-implication checker for subsumption sharing.

The sharing pass (planner/sharing.py) groups queries whose filters are
*not* textually identical when one filter provably implies another: a
query filtering ``v > 1`` can fold from a group ingesting under
``v > 0`` because every row it wants survives the weaker predicate —
the group ingests+interns ONCE under the weakest member predicate and
the slice operator re-applies each member's own full predicate as a
vectorized residual mask (physical/slice_exec.py).

Implication here is deliberately syntactic and conservative — the
classic conjunct-containment fragment, not a theorem prover:

- a predicate is split on ``and`` into conjuncts;
- conjuncts of shape ``col <op> literal`` (op ∈ ==, <, <=, >, >=) and
  ``in_list(col, lit, ...)`` are *constrained*: per-column interval
  and/or finite value-set bounds;
- every other conjunct (``or``, ``!=``, arithmetic, scalar functions,
  is_null, cross-column compares) is *opaque* and must match by exact
  repr on both sides;
- ``implies(P, Q)`` holds iff Q's opaque conjuncts are a subset of
  P's, and per column Q's bounds contain P's (interval containment,
  value-set containment, or P's finite set inside Q's interval).

NaN/null semantics make containment safe without special cases: a
comparison against NaN or a null cell evaluates false (numpy
elementwise semantics, identical to FilterExec), so a constrained
conjunct rejects NaN/null rows on BOTH sides of an implication — the
row sets still nest.  A NaN *literal* bound never constrains anything
(``v > nan`` is empty) and is kept opaque instead.  Anything the
checker cannot see through falls back to exact-match sharing, pinned
by the negative tests in tests/test_subsumption.py.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from denormalized_tpu.logical.expr import (
    BinaryExpr,
    Column,
    Expr,
    Literal,
    ScalarFunctionExpr,
)

_NEG_INF = object()  # below every value, any type
_POS_INF = object()  # above every value, any type

_FLIP = {"<": ">", "<=": ">=", ">": "<", ">=": "<="}


@dataclass(frozen=True)
class Interval:
    """One column's range bound: (lo, hi) with per-end strictness.
    Ends are literal values of whatever ordered type the column holds
    (numbers, strings) or the +/-inf sentinels."""

    lo: object = _NEG_INF
    lo_strict: bool = False
    hi: object = _POS_INF
    hi_strict: bool = False


def _lt(a, b) -> bool | None:
    """a < b, or None when the values are not comparable (mixed types,
    NaN) — callers treat None as 'cannot prove'."""
    if a is _NEG_INF or b is _POS_INF:
        return not (a is _NEG_INF and b is _NEG_INF) and not (
            a is _POS_INF and b is _POS_INF
        )
    if a is _POS_INF or b is _NEG_INF:
        return False
    try:
        return bool(a < b)
    except TypeError:
        return None


def _interval_contains(outer: Interval, inner: Interval) -> bool:
    """Every value satisfying ``inner`` also satisfies ``outer``."""
    # lower end: outer.lo must be <= inner.lo (strictness-aware)
    if outer.lo is not _NEG_INF:
        c = _lt(outer.lo, inner.lo)
        if c is None:
            return False
        if not c:  # outer.lo >= inner.lo
            eq = (
                inner.lo is not _NEG_INF
                and _lt(inner.lo, outer.lo) is False
            )
            if not eq:
                return False
            if outer.lo_strict and not inner.lo_strict:
                return False
    if outer.hi is not _POS_INF:
        c = _lt(inner.hi, outer.hi)
        if c is None:
            return False
        if not c:  # inner.hi >= outer.hi
            eq = (
                inner.hi is not _POS_INF
                and _lt(outer.hi, inner.hi) is False
            )
            if not eq:
                return False
            if outer.hi_strict and not inner.hi_strict:
                return False
    return True


def _value_in(v, iv: Interval) -> bool:
    """Literal ``v`` provably inside interval ``iv``."""
    if iv.lo is not _NEG_INF:
        c = _lt(iv.lo, v)
        if c is None:
            return False
        if not c and (iv.lo_strict or _lt(v, iv.lo) is not False):
            return False
    if iv.hi is not _POS_INF:
        c = _lt(v, iv.hi)
        if c is None:
            return False
        if not c and (iv.hi_strict or _lt(iv.hi, v) is not False):
            return False
    return True


def _intersect(a: Interval, b: Interval) -> Interval:
    lo, los = a.lo, a.lo_strict
    if b.lo is not _NEG_INF and (
        lo is _NEG_INF or _lt(lo, b.lo) or (
            _lt(b.lo, lo) is False and b.lo_strict
        )
    ):
        lo, los = b.lo, b.lo_strict
    hi, his = a.hi, a.hi_strict
    if b.hi is not _POS_INF and (
        hi is _POS_INF or _lt(b.hi, hi) or (
            _lt(hi, b.hi) is False and b.hi_strict
        )
    ):
        hi, his = b.hi, b.hi_strict
    return Interval(lo, los, hi, his)


@dataclass
class Constraints:
    """The analyzable content of one conjunctive predicate."""

    intervals: dict[str, Interval] = field(default_factory=dict)
    sets: dict[str, frozenset] = field(default_factory=dict)
    opaque: frozenset = frozenset()

    @property
    def constrained_columns(self) -> set[str]:
        return set(self.intervals) | set(self.sets)


def split_conjuncts(pred: Expr | None) -> list[Expr]:
    """Flatten nested ``and`` nodes into a conjunct list."""
    if pred is None:
        return []
    if isinstance(pred, BinaryExpr) and pred.op == "and":
        return split_conjuncts(pred.left) + split_conjuncts(pred.right)
    return [pred]


def _is_bad_literal(v) -> bool:
    try:
        return isinstance(v, float) and math.isnan(v)
    except TypeError:  # pragma: no cover
        return True


def analyze(preds: list[Expr]) -> Constraints:
    """Classify every conjunct of the given predicate list (an implicit
    AND) into interval / set / opaque constraints."""
    cons = Constraints()
    opaque: set[str] = set()
    for pred in preds:
        for c in split_conjuncts(pred):
            if not _absorb(c, cons):
                opaque.add(repr(c))
    cons.opaque = frozenset(opaque)
    return cons


def _absorb(conj: Expr, cons: Constraints) -> bool:
    """Try to fold one conjunct into ``cons``; False → opaque."""
    if isinstance(conj, BinaryExpr) and conj.op in ("==", "<", "<=", ">", ">="):
        op = conj.op
        left, right = conj.left, conj.right
        if isinstance(left, Literal) and isinstance(right, Column):
            left, right = right, left
            op = _FLIP.get(op, op)
        if not (isinstance(left, Column) and isinstance(right, Literal)):
            return False
        v = right.value
        if _is_bad_literal(v):
            return False
        name = left.name
        if op == "==":
            s = cons.sets.get(name, frozenset({v}))
            cons.sets[name] = s & {v} if name in cons.sets else frozenset({v})
            return True
        iv = {
            "<": Interval(hi=v, hi_strict=True),
            "<=": Interval(hi=v),
            ">": Interval(lo=v, lo_strict=True),
            ">=": Interval(lo=v),
        }[op]
        prev = cons.intervals.get(name)
        cons.intervals[name] = iv if prev is None else _intersect(prev, iv)
        return True
    if (
        isinstance(conj, ScalarFunctionExpr)
        and conj.fname == "in_list"
        and len(conj.args) >= 2
        and isinstance(conj.args[0], Column)
        and all(isinstance(a, Literal) for a in conj.args[1:])
    ):
        vals = [a.value for a in conj.args[1:]]
        if any(_is_bad_literal(v) for v in vals):
            return False
        name = conj.args[0].name
        s = frozenset(vals)
        cons.sets[name] = (
            cons.sets[name] & s if name in cons.sets else s
        )
        return True
    return False


def implies(p: Constraints, q: Constraints) -> bool:
    """Every row satisfying ``p`` provably satisfies ``q``."""
    if not q.opaque <= p.opaque:
        return False
    for name, q_set in q.sets.items():
        p_set = p.sets.get(name)
        if p_set is None or not p_set <= q_set:
            return False
    for name, q_iv in q.intervals.items():
        p_iv = p.intervals.get(name)
        if p_iv is not None and _interval_contains(q_iv, p_iv):
            continue
        p_set = p.sets.get(name)
        if p_set is not None and all(_value_in(v, q_iv) for v in p_set):
            continue
        return False
    return True


def weakest(cands: list[Constraints]) -> int | None:
    """Index of the member every OTHER member provably implies — the
    subsumption-lattice bottom of the given set — or None when no
    single member is weakest (incomparable survivors).  Used to
    re-derive the shared ingest predicate after the base member of a
    live group deregisters (runtime/multi_query.py): the survivors'
    weakest predicate becomes the new ingest filter, and rows only the
    departed base could reach stop being ingested.  First match wins
    for determinism when several members tie."""
    for i, c in enumerate(cands):
        if all(implies(o, c) for j, o in enumerate(cands) if j != i):
            return i
    return None


def predicate_signature(preds: list[Expr]) -> str:
    """Stable textual identity of a full (conjunctive) predicate list —
    the per-subscriber filter signature checkpoints carry."""
    return "&".join(sorted(repr(c) for p in preds for c in split_conjuncts(p)))


def conjoin(preds: list[Expr]) -> Expr | None:
    """Re-assemble a filter-node chain's predicates into one AND
    expression (None for an empty chain)."""
    if not preds:
        return None
    out = preds[0]
    for p in preds[1:]:
        out = BinaryExpr("and", out, p)
    return out
