"""Build and run the native C++ test binary under sanitizers — coverage
the reference lacks entirely (SURVEY.md §5):

- AddressSanitizer + UndefinedBehaviorSanitizer: memory safety over the
  parser/LSM/codec surfaces (untrusted broker bytes included);
- ThreadSanitizer: the threaded hammers in native_test.cpp (concurrent
  kafka_client produce/fetch against a loopback mini-broker, lsmkv
  put/get/flush from 4 threads, concurrent TLS-API init) — the engine
  calls these components from prefetch worker threads with the GIL
  released, so races here are real races;
- a plain optimized build, because the hammers are also ordinary
  correctness tests.

Each flavor skips cleanly — with the toolchain's own error recorded in
the skip reason — when this g++ can't produce a working binary for it
(e.g. no libtsan on the image).
"""

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

NATIVE = Path(__file__).resolve().parent.parent / "denormalized_tpu" / "native"

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None,
    reason="no compiler — the pure-Python fallbacks cover this environment",
)

FLAVORS = {
    "asan": ["-fsanitize=address,undefined", "-fno-sanitize-recover=all"],
    "tsan": ["-fsanitize=thread"],
    "plain": ["-O2"],
}


def _probe_sanitizer(tmp_path: Path, flags: list[str]) -> str | None:
    """Can this toolchain build AND run a trivial binary with ``flags``?
    Returns the failure detail (recorded in the skip reason) or None.
    Runtime is probed too: some images ship the compiler support but not
    the sanitizer runtime libraries."""
    src = tmp_path / "probe.cpp"
    src.write_text("int main() { return 0; }\n")
    exe = tmp_path / "probe"
    build = subprocess.run(
        ["g++", "-std=c++17", *flags, str(src), "-o", str(exe)],
        capture_output=True, text=True, timeout=120,
    )
    if build.returncode != 0:
        return f"probe build failed: {build.stderr[-300:]}"
    run = subprocess.run(
        [str(exe)], capture_output=True, text=True, timeout=60
    )
    if run.returncode != 0:
        return f"probe run failed: {run.stderr[-300:]}"
    return None


@pytest.mark.parametrize("flavor", sorted(FLAVORS))
def test_native_components(tmp_path, flavor):
    flags = FLAVORS[flavor]
    if flavor != "plain":
        why = _probe_sanitizer(tmp_path, flags)
        if why is not None:
            pytest.skip(f"toolchain lacks {flavor}: {why}")
    exe = tmp_path / "native_test"
    build = subprocess.run(
        # -ldl: the kafka client dlopens OpenSSL; glibc < 2.34 keeps
        # dlopen/dlsym in libdl (newer glibc folded them into libc, where
        # the flag is a harmless no-op).  -lpthread likewise for the
        # hammer threads on older glibc.
        ["g++", "-std=c++17", "-g", *flags,
         str(NATIVE / "native_test.cpp"), "-o", str(exe),
         "-lz", "-ldl", "-lpthread"],
        capture_output=True,
        text=True,
        cwd=NATIVE,
    )
    assert build.returncode == 0, build.stderr[-2000:]
    run = subprocess.run(
        [str(exe), str(tmp_path / "lsm")],
        capture_output=True,
        text=True,
        timeout=280,
    )
    sys.stderr.write(run.stderr[-1000:])
    assert run.returncode == 0, (run.stdout[-500:], run.stderr[-2000:])
    assert "ALL NATIVE TESTS PASSED" in run.stdout
    # the hammers must actually have run in every flavor — a refactor
    # that drops them from main() would silently gut the TSan coverage
    for marker in ("lsm hammer ok", "kafka hammer ok",
                   "interner hammer ok"):
        assert marker in run.stdout, run.stdout[-500:]


def test_tsan_build_flavor(tmp_path):
    """The ``sanitize="thread"`` flavor in native/build.py produces a
    distinctly-named, distinctly-stamped artifact (lsmkv.tsan.so) beside
    the production lsmkv.so, and the artifact is genuinely dlopen-able
    with the TSan runtime preloaded (the harness usage it exists for)."""
    why = _probe_sanitizer(tmp_path, ["-fsanitize=thread"])
    if why is not None:
        pytest.skip(f"toolchain lacks tsan: {why}")
    from denormalized_tpu.native import build

    with pytest.raises(ValueError, match="unknown sanitize kind"):
        build.compile("lsmkv", sanitize="bogus")
    so = build.compile("lsmkv", sanitize="thread")
    assert so == NATIVE / "lsmkv.tsan.so"
    assert so.exists() and so.stat().st_size > 0
    stamp = NATIVE / "lsmkv.tsan.so.srchash"
    assert stamp.exists()
    # flavored stamp differs from the plain one (different flags hash)
    plain_stamp = NATIVE / "lsmkv.so.srchash"
    if plain_stamp.exists():
        assert stamp.read_text() != plain_stamp.read_text()
    # second call is a cache hit (stamp matches — no recompile)
    assert build.compile("lsmkv", sanitize="thread") == so

    libtsan = subprocess.run(
        ["g++", "-print-file-name=libtsan.so"],
        capture_output=True, text=True,
    ).stdout.strip()
    if not libtsan or "/" not in libtsan:
        pytest.skip("g++ cannot locate libtsan.so for preload")
    snippet = (
        "import ctypes\n"
        f"lib = ctypes.CDLL({str(so)!r})\n"
        "lib.lsm_open.restype = ctypes.c_void_p\n"
        "lib.lsm_open.argtypes = [ctypes.c_char_p]\n"
        "lib.lsm_close.argtypes = [ctypes.c_void_p]\n"
        f"h = lib.lsm_open({str(tmp_path / 'flv').encode()!r})\n"
        "assert h\n"
        "lib.lsm_close(h)\n"
        "print('FLAVOR_OK')\n"
    )
    run = subprocess.run(
        [sys.executable, "-c", snippet],
        capture_output=True, text=True,
        env={"LD_PRELOAD": libtsan, "PATH": "/usr/bin:/bin",
             "TSAN_OPTIONS": "report_bugs=0:exitcode=0"},
        timeout=120,
    )
    assert "FLAVOR_OK" in run.stdout, (run.stdout, run.stderr[-1500:])
