"""Build and run the native C++ test binary under AddressSanitizer +
UndefinedBehaviorSanitizer — sanitizer coverage the reference lacks
entirely (SURVEY.md §5)."""

import shutil
import subprocess
import sys
from pathlib import Path

import pytest

NATIVE = Path(__file__).resolve().parent.parent / "denormalized_tpu" / "native"

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None,
    reason="no compiler — the pure-Python fallbacks cover this environment",
)


@pytest.mark.parametrize("flags", [
    ["-fsanitize=address,undefined", "-fno-sanitize-recover=all"],
    ["-O2"],  # plain optimized build must also pass
])
def test_native_components(tmp_path, flags):
    exe = tmp_path / "native_test"
    build = subprocess.run(
        # -ldl: the kafka client dlopens OpenSSL; glibc < 2.34 keeps
        # dlopen/dlsym in libdl (newer glibc folded them into libc, where
        # the flag is a harmless no-op)
        ["g++", "-std=c++17", "-g", *flags,
         str(NATIVE / "native_test.cpp"), "-o", str(exe), "-lz", "-ldl"],
        capture_output=True,
        text=True,
        cwd=NATIVE,
    )
    assert build.returncode == 0, build.stderr[-2000:]
    run = subprocess.run(
        [str(exe), str(tmp_path / "lsm")],
        capture_output=True,
        text=True,
        timeout=120,
    )
    sys.stderr.write(run.stderr[-1000:])
    assert run.returncode == 0, (run.stdout[-500:], run.stderr[-2000:])
    assert "ALL NATIVE TESTS PASSED" in run.stdout
