"""Multi-process ``jax.distributed`` tests (VERDICT r1 smoke; extended in
r5 per VERDICT r4 #7: every layout dryrun_multichip validates in-process
gets a CROSS-PROCESS twin, plus a kill/restore across process boundaries).

Each test spawns real OS processes that join one JAX job over a local
coordinator and build a global mesh spanning all processes' virtual CPU
devices.  Children validate the state a process can address against host
oracles (sharded layouts), or the replicated collective-merge output
(partial layouts)."""

import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest


def _free_addr() -> str:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    return addr


def _spawn_job(tmp_path, child_src, n_procs, devices_per_proc, extra_args=(),
               name="child"):
    addr = _free_addr()
    script = tmp_path / f"{name}.py"
    script.write_text(child_src)
    env = dict(os.environ)
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={devices_per_proc}"
    )
    env["PYTHONPATH"] = (
        str(Path(__file__).resolve().parent.parent)
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    return [
        subprocess.Popen(
            [sys.executable, str(script), addr, str(i), str(n_procs),
             *map(str, extra_args)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(n_procs)
    ]


def _collect(procs, timeout=240):
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return outs

_CHILD = r"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

coordinator, pid = sys.argv[1], int(sys.argv[2])

from denormalized_tpu.parallel.distributed import (
    global_mesh,
    init_distributed,
    local_device_count,
)

init_distributed(
    coordinator_address=coordinator, num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert local_device_count() == 4, local_device_count()
assert len(jax.devices()) == 8, jax.devices()

mesh = global_mesh()
assert mesh.devices.size == 8

from denormalized_tpu.ops import segment_agg as sa
from denormalized_tpu.parallel.sharded_state import KeyShardedWindowState

spec = sa.WindowKernelSpec(
    components=tuple(sa.components_for([("count", 0), ("sum", 0)])),
    num_value_cols=1,
    window_slots=8,
    group_capacity=256,  # 32 per device
    length_ms=1000,
    slide_ms=1000,
)
state = KeyShardedWindowState(spec, mesh)

# deterministic batch, identical on both processes (inputs are replicated)
rng = np.random.default_rng(0)
B = 512
gid = rng.integers(0, 256, B).astype(np.int32)
vals = rng.normal(10.0, 1.0, (B, 1)).astype(np.float32)
win_rel = rng.integers(0, 4, B).astype(np.int32)
state.update(
    vals,
    np.ones((B, 1), dtype=bool),
    win_rel,
    np.zeros(B, dtype=np.int32),
    gid,
    np.ones(B, dtype=bool),
    np.int32(0),
)

# oracle over the full (W, G) space
expect = np.zeros((8, 256), np.int64)
np.add.at(expect, (win_rel, gid), 1)

# validate every shard THIS process can address
buf = state._state["count_0"]
checked = 0
for shard in buf.addressable_shards:
    got = np.asarray(shard.data)
    w_sl, g_sl = shard.index
    np.testing.assert_array_equal(got, expect[w_sl, g_sl])
    checked += 1
assert checked > 0
print(f"DISTRIBUTED-OK pid={pid} shards={checked}", flush=True)
"""


@pytest.mark.slow
def test_two_process_distributed_window_step(tmp_path):
    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{port.getsockname()[1]}"
    port.close()

    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = (
        str(Path(__file__).resolve().parent.parent)
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), addr, str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
        assert f"DISTRIBUTED-OK pid={i}" in out, out[-2000:]


_LAYOUT_CHILD = r"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

coordinator, pid, nprocs, layout = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4]
)

from denormalized_tpu.parallel.distributed import global_mesh, init_distributed

init_distributed(
    coordinator_address=coordinator, num_processes=nprocs, process_id=pid
)
assert jax.process_count() == nprocs, jax.process_count()
mesh = global_mesh()
N = mesh.devices.size

from denormalized_tpu.ops import segment_agg as sa
from denormalized_tpu.parallel import sharded_state as ss
from denormalized_tpu.parallel.mesh import make_mesh_2d

W, G = 8, 256
spec = sa.WindowKernelSpec(
    components=tuple(sa.components_for([("count", 0), ("sum", 0)])),
    num_value_cols=1,
    window_slots=W,
    group_capacity=G,
    length_ms=1000,
    slide_ms=1000,
)
rng = np.random.default_rng(7)
B = 512
gid = rng.integers(0, G, B).astype(np.int32)
vals = rng.normal(10.0, 1.0, (B, 1)).astype(np.float32)
win_rel = rng.integers(0, 4, B).astype(np.int32)
rem = np.zeros(B, np.int32)
colvalid = np.ones((B, 1), bool)
row_valid = np.ones(B, bool)

cnt_oracle = np.zeros((W, G), np.int64)
np.add.at(cnt_oracle, (win_rel, gid), 1)

checked = 0
if layout == "key_sharded":
    st = ss.KeyShardedWindowState(spec, mesh)
    st.update(vals, colvalid, win_rel, rem, gid, row_valid, np.int32(0))
    for shard in st._state["count_0"].addressable_shards:
        w_sl, g_sl = shard.index
        np.testing.assert_array_equal(
            np.asarray(shard.data), cnt_oracle[w_sl, g_sl]
        )
        checked += 1
elif layout == "partial_merge":
    # cross-process equivalence twin: the identical accumulate stream
    # into the single-device partial_merge backend (property-tested
    # against the f64 oracle elsewhere) must produce the same state the
    # mesh layout's addressable shards hold
    st = ss.KeyShardedPartialMergeWindowState(spec, mesh)
    single = ss.PartialMergeWindowState(spec)
    for backend in (st, single):
        backend.accumulate(
            win_rel.astype(np.int64), rem, gid,
            vals.astype(np.float64), colvalid, None, 0,
        )
        backend.flush_pending()
    ref = {k: np.asarray(jax.device_get(v)) for k, v in single._state.items()}
    for label, buf in st._state.items():
        for shard in buf.addressable_shards:
            np.testing.assert_allclose(
                np.asarray(shard.data), ref[label][shard.index],
                rtol=1e-6, atol=1e-6,
            )
            checked += 1
elif layout == "partial_final":
    st = ss.PartialFinalWindowState(spec, mesh)
    st.update(vals, colvalid, win_rel, rem, gid, row_valid, np.int32(0))
    per = B // N  # shard_map splits rows over the mesh in order
    for shard in st._state["count_0"].addressable_shards:
        d_sl, w_sl, g_sl = shard.index
        d = d_sl.start
        exp = np.zeros((W, G), np.int64)
        sel = slice(d * per, (d + 1) * per)
        np.add.at(exp, (win_rel[sel], gid[sel]), 1)
        np.testing.assert_array_equal(
            np.asarray(shard.data)[0], exp[w_sl, g_sl]
        )
        checked += 1
    # the layout's only collective: the replicated emission merge must
    # equal the global oracle on EVERY process
    merged = st.read_slot(2)
    np.testing.assert_array_equal(merged["count_0"], cnt_oracle[2])
elif layout == "two_level":
    mesh2 = make_mesh_2d(2, N // 2)
    st = ss.TwoLevelWindowState(spec, mesh2)
    st.update(vals, colvalid, win_rel, rem, gid, row_valid, np.int32(0))
    per = B // 2  # rows split across the slice axis in order
    for shard in st._state["count_0"].addressable_shards:
        s_sl, w_sl, g_sl = shard.index
        s = s_sl.start
        exp = np.zeros((W, G), np.int64)
        sel = slice(s * per, (s + 1) * per)
        np.add.at(exp, (win_rel[sel], gid[sel]), 1)
        np.testing.assert_array_equal(
            np.asarray(shard.data)[0], exp[w_sl, g_sl]
        )
        checked += 1
else:
    raise SystemExit(f"unknown layout {layout}")

assert checked > 0
print(f"LAYOUT-OK layout={layout} pid={pid} shards={checked}", flush=True)
"""


@pytest.mark.slow
@pytest.mark.parametrize(
    "layout", ["key_sharded", "partial_merge", "partial_final", "two_level"]
)
def test_four_process_layouts(tmp_path, layout):
    """Every sharding layout dryrun_multichip validates in-process gets a
    cross-process twin: 4 processes x 2 virtual devices = 8 global."""
    procs = _spawn_job(_free := tmp_path, _LAYOUT_CHILD, 4, 2, (layout,),
                       name=f"layout_{layout}")
    outs = _collect(procs)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"{layout} process {i} failed:\n{out[-3000:]}"
        assert f"LAYOUT-OK layout={layout} pid={i}" in out, out[-2000:]


_KILL_RESTORE_CHILD = r"""
import os
import signal
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

coordinator, pid, nprocs, phase, snapdir = (
    sys.argv[1], int(sys.argv[2]), int(sys.argv[3]), sys.argv[4], sys.argv[5]
)

from denormalized_tpu.parallel.distributed import global_mesh, init_distributed

init_distributed(
    coordinator_address=coordinator, num_processes=nprocs, process_id=pid
)
mesh = global_mesh()

from denormalized_tpu.ops import segment_agg as sa
from denormalized_tpu.parallel import sharded_state as ss

W, G = 8, 256
spec = sa.WindowKernelSpec(
    components=tuple(sa.components_for([("count", 0), ("sum", 0)])),
    num_value_cols=1,
    window_slots=W,
    group_capacity=G,
    length_ms=1000,
    slide_ms=1000,
)


def batch(b):
    rng = np.random.default_rng(100 + b)  # identical across phases/procs
    B = 256
    return (
        rng.normal(10.0, 1.0, (B, 1)).astype(np.float32),
        np.ones((B, 1), bool),
        rng.integers(0, 4, B).astype(np.int32),
        np.zeros(B, np.int32),
        rng.integers(0, G, B).astype(np.int32),
        np.ones(B, bool),
        np.int32(0),
    )


st = ss.KeyShardedWindowState(spec, mesh)

if phase == "A":
    for b in range(3):
        st.update(*batch(b))
    # bank THIS process's addressable shards — the per-host snapshot files
    # a real multi-host aligned barrier would write
    payload = {}
    for label, buf in st._state.items():
        for shard in buf.addressable_shards:
            w_sl, g_sl = shard.index
            payload[f"{label}|{g_sl.start}|{g_sl.stop}"] = np.asarray(
                shard.data
            )
    path = os.path.join(snapdir, f"snap_p{pid}.npz")
    with open(path + ".tmp", "wb") as f:
        np.savez(f, **payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(path + ".tmp", path)
    print(f"SNAP-BANKED pid={pid}", flush=True)
    if pid == nprocs - 1:
        # crash only after EVERY host banked its snapshot (the aligned
        # barrier completed) — the point under test is restore-from-a-
        # committed-cut, not a torn barrier; files appear atomically via
        # os.replace, so presence implies completeness
        import time as _time

        deadline = _time.time() + 60
        while _time.time() < deadline:
            if all(
                os.path.exists(os.path.join(snapdir, f"snap_p{p}.npz"))
                for p in range(nprocs)
            ):
                break
            _time.sleep(0.05)
        os.kill(os.getpid(), signal.SIGKILL)  # crash mid-stream
    # survivors keep streaming past the snapshot (their post-snapshot work
    # is legitimately discarded by the restore) — key_sharded updates have
    # no collectives, so a dead peer does not wedge them
    for b in range(3, 6):
        st.update(*batch(b))
    jax.block_until_ready(list(st._state.values()))
    print(f"SURVIVOR-DONE pid={pid}", flush=True)
    # hold until the parent confirms the killer died (tombstone file):
    # exiting first would race the failure detector into tearing the
    # killer down before ITS SIGKILL, making the crash nondeterministic
    import time as _time

    deadline = _time.time() + 60
    while _time.time() < deadline and not os.path.exists(
        os.path.join(snapdir, "killer_dead")
    ):
        _time.sleep(0.05)
    os._exit(0)  # skip the distributed-shutdown barrier (peer is dead)

# phase B: fresh job, assemble the global snapshot from every host's
# file, restore, replay the post-snapshot stream, validate vs oracle
host_state = {}
for p in range(nprocs):
    with np.load(os.path.join(snapdir, f"snap_p{p}.npz")) as z:
        for key in z.files:
            label, g0, g1 = key.split("|")
            buf = host_state.setdefault(
                label,
                np.zeros(
                    (W, G),
                    z[key].dtype,
                ),
            )
            buf[:, int(g0):int(g1)] = z[key]
st.import_(host_state)
for b in range(3, 6):
    st.update(*batch(b))

expect = np.zeros((W, G), np.int64)
for b in range(6):
    _, _, win_rel, _, gid, _, _ = batch(b)
    np.add.at(expect, (win_rel, gid), 1)
checked = 0
for shard in st._state["count_0"].addressable_shards:
    w_sl, g_sl = shard.index
    np.testing.assert_array_equal(np.asarray(shard.data), expect[w_sl, g_sl])
    checked += 1
assert checked > 0
print(f"RESTORED-OK pid={pid} shards={checked}", flush=True)
# normal exit: every phase-B peer is alive, so jax.distributed's graceful
# shutdown barrier synchronizes the teardown (an os._exit here would look
# like a task death and tear slower peers down mid-validation)
"""


@pytest.mark.slow
def test_kill_restore_across_process_boundaries(tmp_path):
    """Kill/restore across process boundaries (VERDICT r4 #7): a 4-process
    key-sharded job banks per-host shard snapshots, one process SIGKILLs
    itself mid-stream, survivors stream on; a FRESH 4-process job
    assembles the global state from the per-host files, restores, replays
    the remainder, and every process's addressable shards match the
    full-stream oracle."""
    snapdir = tmp_path / "snaps"
    snapdir.mkdir()
    procs = _spawn_job(
        tmp_path, _KILL_RESTORE_CHILD, 4, 2, ("A", str(snapdir)),
        name="kill_a",
    )
    # the designated killer must die by ITS OWN SIGKILL (after the
    # snapshot barrier); survivors hold their exit until the parent banks
    # this tombstone so the failure detector cannot fire first
    killed = procs[-1]
    deadline = time.time() + 120
    while killed.poll() is None and time.time() < deadline:
        time.sleep(0.1)
    (snapdir / "killer_dead").write_text("dead")
    outs = _collect(procs)
    assert killed.returncode == -signal.SIGKILL, (
        killed.returncode, outs[-1][-2000:])
    for i, (p, out) in enumerate(zip(procs[:-1], outs[:-1])):
        # a survivor either streams to completion (key_sharded updates
        # need no collectives) or is torn down by jax.distributed's
        # coordination-service failure detector noticing the dead peer —
        # BOTH are correct failure-detection outcomes; what must never
        # happen is a silent wedge (communicate() timeout) or a crash for
        # any other reason
        detected = (
            "JAX distributed service detected fatal errors" in out
            or "coordination service" in out.lower()
        )
        assert p.returncode == 0 or detected, (
            f"survivor {i} failed for a non-peer-death reason:\n"
            f"{out[-3000:]}"
        )
        if p.returncode == 0:
            assert f"SURVIVOR-DONE pid={i}" in out, out[-2000:]
    for i, out in enumerate(outs):
        assert f"SNAP-BANKED pid={i}" in out, out[-2000:]
    assert len(list(snapdir.glob("snap_p*.npz"))) == 4

    procs_b = _spawn_job(
        tmp_path, _KILL_RESTORE_CHILD, 4, 2, ("B", str(snapdir)),
        name="kill_b",
    )
    outs_b = _collect(procs_b)
    for i, (p, out) in enumerate(zip(procs_b, outs_b)):
        assert p.returncode == 0, f"restore process {i} failed:\n{out[-3000:]}"
        assert f"RESTORED-OK pid={i}" in out, out[-2000:]
