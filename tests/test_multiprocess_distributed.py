"""2-process ``jax.distributed`` smoke test (VERDICT round-1 item: prove
``init_distributed`` + ``global_mesh`` are more than documentation).

Spawns two real OS processes that join one JAX job over a local
coordinator, build the global key-axis mesh spanning both processes'
devices (4 virtual CPU devices each → 8 global), and run one key-sharded
window-kernel update through ``shard_map``.  Each process validates the
accumulator shards it can address against a host oracle."""

import os
import socket
import subprocess
import sys
from pathlib import Path

import pytest

_CHILD = r"""
import sys

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np

coordinator, pid = sys.argv[1], int(sys.argv[2])

from denormalized_tpu.parallel.distributed import (
    global_mesh,
    init_distributed,
    local_device_count,
)

init_distributed(
    coordinator_address=coordinator, num_processes=2, process_id=pid
)
assert jax.process_count() == 2, jax.process_count()
assert local_device_count() == 4, local_device_count()
assert len(jax.devices()) == 8, jax.devices()

mesh = global_mesh()
assert mesh.devices.size == 8

from denormalized_tpu.ops import segment_agg as sa
from denormalized_tpu.parallel.sharded_state import KeyShardedWindowState

spec = sa.WindowKernelSpec(
    components=tuple(sa.components_for([("count", 0), ("sum", 0)])),
    num_value_cols=1,
    window_slots=8,
    group_capacity=256,  # 32 per device
    length_ms=1000,
    slide_ms=1000,
)
state = KeyShardedWindowState(spec, mesh)

# deterministic batch, identical on both processes (inputs are replicated)
rng = np.random.default_rng(0)
B = 512
gid = rng.integers(0, 256, B).astype(np.int32)
vals = rng.normal(10.0, 1.0, (B, 1)).astype(np.float32)
win_rel = rng.integers(0, 4, B).astype(np.int32)
state.update(
    vals,
    np.ones((B, 1), dtype=bool),
    win_rel,
    np.zeros(B, dtype=np.int32),
    gid,
    np.ones(B, dtype=bool),
    np.int32(0),
)

# oracle over the full (W, G) space
expect = np.zeros((8, 256), np.int64)
np.add.at(expect, (win_rel, gid), 1)

# validate every shard THIS process can address
buf = state._state["count_0"]
checked = 0
for shard in buf.addressable_shards:
    got = np.asarray(shard.data)
    w_sl, g_sl = shard.index
    np.testing.assert_array_equal(got, expect[w_sl, g_sl])
    checked += 1
assert checked > 0
print(f"DISTRIBUTED-OK pid={pid} shards={checked}", flush=True)
"""


@pytest.mark.slow
def test_two_process_distributed_window_step(tmp_path):
    port = socket.socket()
    port.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{port.getsockname()[1]}"
    port.close()

    script = tmp_path / "child.py"
    script.write_text(_CHILD)
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    env["PYTHONPATH"] = (
        str(Path(__file__).resolve().parent.parent)
        + os.pathsep
        + env.get("PYTHONPATH", "")
    )
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), addr, str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} failed:\n{out[-3000:]}"
        assert f"DISTRIBUTED-OK pid={i}" in out, out[-2000:]
