"""Unit tests for the exchange building blocks: framing integrity,
stable hashing, plan splitting, and edge-merger semantics (watermark
min-merge, barrier alignment, EOS collapse) — no worker processes."""

import os
import queue

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.common.errors import PlanError, SourceError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.cluster import framing, hashing
from denormalized_tpu.cluster.exchange import EdgeMerger, EdgeState
from denormalized_tpu.cluster.split import split_keyed
from denormalized_tpu.logical import plan as lp
from denormalized_tpu.logical.optimizer import optimize
from denormalized_tpu.sources.memory import MemorySource


def _batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return RecordBatch.from_pydict({
        "k": np.array([f"s{i % 3}" for i in range(n)], dtype=object),
        "v": rng.normal(size=n),
        "ts": np.arange(n, dtype=np.int64),
    })


# -- hashing ---------------------------------------------------------------

def test_hash_rows_stable_and_key_consistent():
    a = hashing.hash_rows([np.array([5, 6, 5], dtype=np.int64)])
    assert a[0] == a[2] and a[0] != a[1]
    # int32 and int64 spellings of the same key agree (canonical int64)
    b = hashing.hash_rows([np.array([5, 6, 5], dtype=np.int32)])
    assert (a == b).all()
    # string keys: object-column lane, deterministic across calls
    s1 = hashing.hash_rows([np.array(["x", "y"], dtype=object)])
    s2 = hashing.hash_rows([np.array(["x", "y"], dtype=object)])
    assert (s1 == s2).all() and s1[0] != s1[1]
    # multi-column: order matters
    two = hashing.hash_rows([
        np.array([1, 2], dtype=np.int64),
        np.array([2, 1], dtype=np.int64),
    ])
    assert two[0] != two[1]


def test_bucket_rows_covers_all_buckets():
    keys = np.arange(1000, dtype=np.int64)
    b = hashing.bucket_rows([keys], 4)
    assert set(np.unique(b)) == {0, 1, 2, 3}
    # roughly uniform (hash quality smoke, not a distribution proof)
    counts = np.bincount(b, minlength=4)
    assert counts.min() > 150


def test_partitions_for_disjoint_cover():
    for n in (1, 2, 3, 4, 8):
        seen = []
        for w in range(n):
            seen += hashing.partitions_for(w, n, 13)
        assert sorted(seen) == list(range(13))


# -- framing ---------------------------------------------------------------

def _roundtrip(frame: bytes, schema):
    # strip the 12-byte wire header; CRC integrity is read_frame's job
    return framing.decode_frame(frame[12:], schema)


def test_data_frame_roundtrip_with_masks():
    b = _batch()
    mask = np.array([True] * 7 + [False], dtype=bool)
    b = RecordBatch(b.schema, b.columns, [None, mask, None])
    kind, got, wm, part = _roundtrip(framing.encode_data(b, 777), b.schema)
    assert kind == "data" and wm == 777 and part is None
    # provenance-stamped frames round-trip the global partition id
    _, _, _, p2 = _roundtrip(
        framing.encode_data(b, 777, part=5), b.schema
    )
    assert p2 == 5
    assert got.to_pydict() == b.to_pydict()
    assert got.masks[1].tolist() == mask.tolist()
    assert got.masks[0] is None


def test_torn_frame_detected_at_receiver():
    import socket as socketlib

    b = _batch()
    frame = framing.encode_data(b, None)
    a, c = socketlib.socketpair()
    try:
        a.sendall(frame[: len(frame) - 3])  # torn mid-payload
        a.close()
        with pytest.raises(SourceError, match="torn"):
            framing.read_frame(c)
    finally:
        c.close()


def test_corrupt_crc_detected():
    import socket as socketlib

    frame = bytearray(framing.encode_barrier(5))
    frame[-1] ^= 0xFF
    a, c = socketlib.socketpair()
    try:
        a.sendall(bytes(frame))
        a.close()
        with pytest.raises(SourceError, match="CRC"):
            framing.read_frame(c)
    finally:
        c.close()


# -- plan split ------------------------------------------------------------

def _plan(ds):
    return optimize(lp.Sink(ds.logical_plan(), None), True)


def _mem_ds(ctx):
    b = _batch()
    return ctx.from_source(
        MemorySource.from_batches([b], timestamp_column="ts")
    )


def test_split_keyed_basic():
    ctx = Context()
    ds = _mem_ds(ctx).window(
        [col("k")], [F.count(col("v")).alias("c")], 1000
    )
    sq = split_keyed(_plan(ds))
    assert sq.key_columns == ["k"]
    assert sq.exchange_schema.has("k")


def test_split_rejects_stateless_and_computed_keys():
    ctx = Context()
    with pytest.raises(PlanError, match="keyed operator"):
        split_keyed(_plan(_mem_ds(ctx).filter(col("v") > 0)))
    ds = _mem_ds(ctx).window(
        [col("v") + col("v")], [F.count(col("v")).alias("c")], 1000
    )
    with pytest.raises(PlanError, match="column group keys"):
        split_keyed(_plan(ds))


def test_split_rejects_joins():
    ctx = Context()
    left = _mem_ds(ctx)
    right = (
        ctx.from_source(
            MemorySource.from_batches(
                [_batch(seed=1)], timestamp_column="ts"
            ),
            name="right",
        )
        .with_column_renamed("v", "v2")
        .with_column_renamed("ts", "ts2")
    )
    joined = left.join(right, "inner", ["k"], ["k"]).window(
        [col("k")], [F.count(col("v")).alias("c")], 1000
    )
    with pytest.raises(PlanError, match="non-join"):
        split_keyed(_plan(joined))


# -- edge merger -----------------------------------------------------------

class _FakeServer:
    def __init__(self, n):
        import threading

        class _G:
            def set(self, v):
                pass

        self.edges = {i: EdgeState(i, _G()) for i in range(n)}
        self.wake = threading.Event()


def _drain(merger, limit=100):
    out = []
    it = iter(merger)
    for _ in range(limit):
        try:
            out.append(next(it))
        except StopIteration:
            break
    return out


def test_merger_watermark_is_min_over_edges():
    srv = _FakeServer(2)
    m = EdgeMerger(srv)
    b = _batch()
    srv.edges[0].queue.put(("data", b, 100))
    srv.edges[1].queue.put(("wm", 50))
    srv.edges[0].queue.put(("eos",))
    srv.edges[1].queue.put(("eos",))
    items = _drain(m)
    wms = [i[1] for i in items if i[0] == "wm"]
    assert wms == [50]  # min(100, 50); never the fast edge's 100


def test_merger_aligns_barriers_and_blocks_edges():
    srv = _FakeServer(2)
    m = EdgeMerger(srv)
    early, late = _batch(seed=1), _batch(seed=2)
    # edge0: barrier first, then post-barrier data; edge1: data then barrier
    srv.edges[0].queue.put(("barrier", 9))
    srv.edges[0].queue.put(("data", early, None))
    srv.edges[1].queue.put(("data", late, None))
    srv.edges[1].queue.put(("barrier", 9))
    srv.edges[0].queue.put(("eos",))
    srv.edges[1].queue.put(("eos",))
    items = _drain(m)
    kinds = [i[0] for i in items]
    barrier_at = kinds.index("barrier")
    # edge0's post-barrier batch must come AFTER the aligned barrier
    datas = [i for i, k in enumerate(kinds) if k == "data"]
    pre = [i for i in datas if i < barrier_at]
    post = [i for i in datas if i > barrier_at]
    assert len(pre) == 1 and len(post) == 1
    assert items[pre[0]][1] is late  # pre-barrier data from edge1
    assert items[post[0]][1] is early


def test_merger_eos_satisfies_barrier():
    srv = _FakeServer(2)
    m = EdgeMerger(srv)
    srv.edges[0].queue.put(("barrier", 4))
    srv.edges[0].queue.put(("eos",))
    srv.edges[1].queue.put(("eos",))  # finished before the barrier
    items = _drain(m)
    assert ("barrier", 4) in items


def test_merger_raises_in_band_errors():
    srv = _FakeServer(1)
    m = EdgeMerger(srv)
    srv.edges[0].queue.put(("err", SourceError("boom")))
    with pytest.raises(SourceError, match="boom"):
        _drain(m)


def test_edge_queue_is_bounded():
    st = EdgeState(0, type("G", (), {"set": lambda self, v: None})())
    assert st.queue.maxsize > 0
    with pytest.raises(queue.Full):
        for _ in range(st.queue.maxsize + 1):
            st.queue.put_nowait(("wm", 1))


# -- obs merge CLI ---------------------------------------------------------

def test_obs_readers_merge_cli(tmp_path):
    """``python -m denormalized_tpu.obs.readers merge`` combines N
    workers' JSONL snapshot streams into one registry view: counters
    sum, histograms merge bucket-wise with re-derived percentiles."""
    import json as jsonlib
    import subprocess
    import sys as syslib

    def snap(counter, hist_counts, t):
        return jsonlib.dumps({
            "event": "obs", "t": t,
            "metrics": {
                "dnz_op_rows_in_total{op=window}": counter,
                "dnz_op_batch_ms{op=window}": {
                    "count": sum(hist_counts), "sum": 10.0,
                    "min": 0.5, "max": 4.0,
                    "bounds": [1.0, 2.0, 4.0],
                    "bucket_counts": hist_counts + [0],
                },
            },
        })

    a, b = tmp_path / "w0.jsonl", tmp_path / "w1.jsonl"
    a.write_text(snap(100, [1, 2, 3], 1.0) + "\n"
                 + snap(250, [2, 4, 6], 2.0) + "\n")
    b.write_text(snap(50, [5, 0, 1], 1.5) + "\n")
    proc = subprocess.run(
        [syslib.executable, "-m", "denormalized_tpu.obs.readers",
         "merge", str(a), str(b)],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr
    out = jsonlib.loads(proc.stdout)
    assert out["files"] == 2
    assert out["series"]["dnz_op_rows_in_total{op=window}"] == 300
    h = out["series"]["dnz_op_batch_ms{op=window}"]
    assert h["count"] == 18  # final-per-file: 12 + 6
    assert h["min"] == 0.5 and h["max"] == 4.0
    assert h["p50"] is not None and h["p50"] <= h["p99"]
