"""Stream-stream join tests — the stream_join example pattern: two windowed
streams joined on (sensor, window bounds) (reference
examples/examples/stream_join.rs:15-85)."""

import numpy as np

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.common.constants import WINDOW_START_COLUMN
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.sources.memory import MemorySource


def _make_sources(rng, t0, n_batches=8, rows=200):
    schema = Schema(
        [
            Field("occurred_at_ms", DataType.INT64, nullable=False),
            Field("sensor_name", DataType.STRING, nullable=False),
            Field("reading", DataType.FLOAT64),
        ]
    )
    def batches(seed_shift):
        out = []
        for b in range(n_batches):
            ts = np.sort(t0 + b * 500 + rng.integers(0, 500, rows))
            names = rng.choice(["s0", "s1", "s2"], size=rows)
            vals = rng.normal(50, 5, rows) + seed_shift
            out.append(
                RecordBatch(
                    schema,
                    [ts, names.astype(object), vals],
                )
            )
        return out

    return schema, batches(0), batches(100)


def test_windowed_stream_join():
    rng = np.random.default_rng(3)
    t0 = 1_700_000_000_000
    _, temp_batches, hum_batches = _make_sources(rng, t0)

    ctx = Context()
    temperature = ctx.from_source(
        MemorySource.from_batches(temp_batches, timestamp_column="occurred_at_ms"),
        name="temperature",
    ).window(
        ["sensor_name"], [F.avg(col("reading")).alias("avg_temperature")], 1000
    )
    humidity = (
        ctx.from_source(
            MemorySource.from_batches(hum_batches, timestamp_column="occurred_at_ms"),
            name="humidity",
        )
        .window(["sensor_name"], [F.avg(col("reading")).alias("avg_humidity")], 1000)
        .with_column_renamed("sensor_name", "humidity_sensor")
        .with_column_renamed("window_start_time", "humidity_window_start_time")
        .with_column_renamed("window_end_time", "humidity_window_end_time")
    )
    joined = temperature.join(
        humidity,
        "inner",
        ["sensor_name", "window_start_time"],
        ["humidity_sensor", "humidity_window_start_time"],
    )
    res = joined.collect()
    assert res.num_rows > 0
    # every joined row agrees on key + window
    assert (
        res.column("sensor_name") == res.column("humidity_sensor")
    ).all()
    assert (
        res.column(WINDOW_START_COLUMN) == res.column("humidity_window_start_time")
    ).all()
    # both aggregates present and separated by the +100 shift
    assert (
        res.column("avg_humidity") - res.column("avg_temperature")
    ).mean() > 90


def test_left_join_emits_unmatched():
    schema = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("k", DataType.STRING, nullable=False),
            Field("v", DataType.FLOAT64),
        ]
    )
    t0 = 1_700_000_000_000

    def mk(ts, ks, vs):
        return RecordBatch(
            schema,
            [np.asarray(ts, np.int64), np.asarray(ks, object), np.asarray(vs)],
        )

    ctx = Context()
    left = ctx.from_source(
        MemorySource.from_batches(
            [mk([t0, t0 + 10], ["a", "b"], [1.0, 2.0])], timestamp_column="ts"
        ),
        name="left",
    )
    right = (
        ctx.from_source(
            MemorySource.from_batches(
                [mk([t0 + 5], ["a"], [9.0])], timestamp_column="ts"
            ),
            name="right",
        )
        .with_column_renamed("k", "rk")
        .with_column_renamed("ts", "rts")
        .with_column_renamed("v", "rv")
    )
    res = left.join(right, "left", ["k"], ["rk"]).collect()
    rows = {res.column("k")[i]: i for i in range(res.num_rows)}
    assert set(rows) == {"a", "b"}
    # matched row has right value; unmatched row has null mask on right cols
    ia, ib = rows["a"], rows["b"]
    assert float(res.column("rv")[ia]) == 9.0
    rv_mask = res.mask("rv")
    assert rv_mask is not None and not rv_mask[ib]


def _raw_sources(L_rows, R_rows):
    """Two raw (unwindowed) sources from (ts, key, value) row tuples."""
    SL = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("k", DataType.STRING, nullable=False),
            Field("v", DataType.FLOAT64),
        ]
    )
    SR = Schema(
        [
            Field("ts2", DataType.INT64, nullable=False),
            Field("k2", DataType.STRING, nullable=False),
            Field("w", DataType.FLOAT64),
        ]
    )

    def rb(schema, names, rows):
        cols = list(zip(*rows))
        return RecordBatch(
            schema,
            [
                np.asarray(cols[0], np.int64),
                np.asarray(cols[1], object),
                np.asarray(cols[2], np.float64),
            ],
        )

    L = [rb(SL, None, batch) for batch in L_rows]
    R = [rb(SR, None, batch) for batch in R_rows]
    ctx = Context()
    left = ctx.from_source(
        MemorySource.from_batches(L, timestamp_column="ts"), name="jl"
    )
    right = ctx.from_source(
        MemorySource.from_batches(R, timestamp_column="ts2"), name="jr"
    )
    return left, right


def test_raw_join_duplicate_key_chains():
    """Duplicate keys within AND across batches: the chained-array probe
    must produce the full cross product per key, matching a brute-force
    oracle."""
    t0 = 1_700_000_000_000
    L_rows = [
        [(t0 + 1, "a", 1.0), (t0 + 2, "a", 2.0), (t0 + 3, "b", 3.0)],
        [(t0 + 10, "a", 4.0), (t0 + 11, "c", 5.0)],
    ]
    R_rows = [
        [(t0 + 1, "a", 10.0), (t0 + 2, "b", 20.0)],
        [(t0 + 12, "a", 30.0), (t0 + 13, "a", 40.0), (t0 + 14, "z", 50.0)],
    ]
    left, right = _raw_sources(L_rows, R_rows)
    res = left.join(right, "inner", ["k"], ["k2"]).collect()
    got = sorted(
        (res.column("k")[i], float(res.column("v")[i]), float(res.column("w")[i]))
        for i in range(res.num_rows)
    )
    lflat = [r for b in L_rows for r in b]
    rflat = [r for b in R_rows for r in b]
    want = sorted(
        (lk, lv, rw)
        for (_, lk, lv) in lflat
        for (_, rk, rw) in rflat
        if lk == rk
    )
    assert got == want, (got, want)


def test_raw_join_eviction_rebuild_keeps_matching():
    """After watermark eviction drops old batches, the rebuilt chain arrays
    must still match retained rows correctly (and never resurrect evicted
    ones)."""
    t0 = 1_700_000_000_000
    gap = 400_000  # > default 300s retention → forces eviction
    L_rows = [
        [(t0 + 1, "old", 1.0)],
        [(t0 + gap, "new", 2.0), (t0 + gap + 1, "new", 3.0)],
        [(t0 + gap + 1000, "new", 4.0)],
    ]
    R_rows = [
        [(t0 + 2, "none", 0.0)],
        [(t0 + gap + 5, "new", 10.0)],
        # 'old' arrives after eviction: must NOT match the evicted left row
        [(t0 + gap + 1001, "old", 20.0), (t0 + gap + 1002, "new", 30.0)],
    ]
    left, right = _raw_sources(L_rows, R_rows)
    res = left.join(right, "inner", ["k"], ["k2"]).collect()
    got = sorted(
        (res.column("k")[i], float(res.column("v")[i]), float(res.column("w")[i]))
        for i in range(res.num_rows)
    )
    want = sorted(
        [("new", 2.0, 10.0), ("new", 3.0, 10.0), ("new", 4.0, 10.0),
         ("new", 2.0, 30.0), ("new", 3.0, 30.0), ("new", 4.0, 30.0)]
    )
    # the evicted left 'old' row must never match the late right 'old' probe
    assert got == want, (got, want)


def test_raw_join_key_dtype_mismatch_rejected():
    import pytest

    from denormalized_tpu.common.errors import PlanError

    t0 = 1_700_000_000_000
    left, right = _raw_sources(
        [[(t0, "a", 1.0)]], [[(t0, "a", 2.0)]]
    )
    with pytest.raises(PlanError, match="dtype mismatch"):
        # string key joined against a numeric column
        left.join(right, "inner", ["k"], ["ts2"]).collect()


def test_raw_join_reinterning_bounds_key_state():
    """UUID-style keys: every row a new key.  After eviction, the join must
    re-key so interner state is bounded by retention, not stream lifetime —
    and results must stay correct across the rebuild."""
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor

    t0 = 1_700_000_000_000
    step = 100_000
    L_rows, R_rows = [], []
    uid = 0
    for b in range(40):
        lb, rb_ = [], []
        for i in range(50):
            lb.append((t0 + b * step + i, f"u{uid}", float(uid)))
            rb_.append((t0 + b * step + i, f"u{uid}", float(uid) * 10))
            uid += 1
        L_rows.append(lb)
        R_rows.append(rb_)
    left, right = _raw_sources(L_rows, R_rows)
    ds = left.join(right, "inner", ["k"], ["k2"])
    ctx = ds._ctx
    root = executor.build_physical(lp.Sink(ds._plan, CollectSink()), ctx)
    # find the join exec and force aggressive re-keying
    from denormalized_tpu.physical.join_exec import StreamingJoinExec

    def find(op):
        if isinstance(op, StreamingJoinExec):
            return op
        for c in op.children:
            r = find(c)
            if r is not None:
                return r
        return None

    j = find(root)
    j._reintern_min = 64
    rows = []
    from denormalized_tpu.physical.base import EndOfStream

    sink = root.sink if hasattr(root, "sink") else None
    got = {}
    for item in root.run():
        if isinstance(item, EndOfStream):
            break
        if isinstance(item, RecordBatch):
            for i in range(item.num_rows):
                got[item.column("k")[i]] = (
                    float(item.column("v")[i]),
                    float(item.column("w")[i]),
                )
    assert len(got) == 2000, len(got)
    for k, (v, w) in got.items():
        assert w == v * 10, (k, v, w)
    # the interner was actually re-keyed down: without re-keying it would
    # hold all 2000 distinct keys; retention (~300s = 4 batches of 50 keys)
    # keeps it far smaller
    assert len(j._interner) < 1000, len(j._interner)


def test_join_on_expression_keys_and_residual():
    """join_on with arbitrary binary expressions (round-3 VERDICT item 8,
    datastream.rs:126-177): an equi conjunct over EXPRESSIONS
    (upper(sensor_name) == hs_up) becomes a hidden hash key, and a
    non-equi conjunct (range predicate over both sides) becomes a
    residual filter evaluated on matched pairs."""
    rng = np.random.default_rng(9)
    t0 = 1_700_000_000_000
    _, temp_batches, hum_batches = _make_sources(rng, t0)

    ctx = Context()
    left = ctx.from_source(
        MemorySource.from_batches(temp_batches, timestamp_column="occurred_at_ms"),
        name="t2",
    ).window(["sensor_name"], [F.avg(col("reading")).alias("avg_t")], 1000)
    right = (
        ctx.from_source(
            MemorySource.from_batches(hum_batches, timestamp_column="occurred_at_ms"),
            name="h2",
        )
        .window(["sensor_name"], [F.avg(col("reading")).alias("avg_h")], 1000)
        .with_column("hs_up", F.upper(col("sensor_name")))
        .with_column_renamed("sensor_name", "hs")
        .with_column_renamed("window_start_time", "hws")
        .with_column_renamed("window_end_time", "hwe")
    )
    joined = left.join_on(
        right,
        "inner",
        [
            F.upper(col("sensor_name")) == col("hs_up"),  # expression key
            col("window_start_time") == col("hws"),       # plain column key
            col("avg_h") > col("avg_t"),                  # residual (always
            # true here: humidity readings are shifted +100)
            col("avg_h") - col("avg_t") < F.lit(200.0),   # residual range
        ],
    )
    result = joined.collect()
    assert result.num_rows > 0
    names = result.schema.names
    # hidden expression-key columns must not leak into the output
    assert not [n for n in names if n.startswith("__join_")]
    # every surviving pair satisfies the residuals and the equi keys
    for i in range(result.num_rows):
        assert str(result.column("sensor_name")[i]).upper() == str(
            result.column("hs_up")[i]
        )
        assert int(result.column(WINDOW_START_COLUMN)[i]) == int(
            result.column("hws")[i]
        )
        assert float(result.column("avg_h")[i]) > float(result.column("avg_t")[i])

    # compare pair-count against the plain column join (equi semantics
    # unchanged by the expression lowering; residuals always true here)
    base = left.join(
        right, "inner",
        ["sensor_name", "window_start_time"], ["hs", "hws"],
    ).collect()
    assert result.num_rows == base.num_rows


def test_join_on_rejects_pure_theta():
    rng = np.random.default_rng(10)
    t0 = 1_700_000_000_000
    _, temp_batches, hum_batches = _make_sources(rng, t0, n_batches=2)
    ctx = Context()
    left = ctx.from_source(
        MemorySource.from_batches(temp_batches, timestamp_column="occurred_at_ms"),
        name="t3",
    ).window(["sensor_name"], [F.avg(col("reading")).alias("a")], 1000)
    right = (
        ctx.from_source(
            MemorySource.from_batches(hum_batches, timestamp_column="occurred_at_ms"),
            name="h3",
        )
        .window(["sensor_name"], [F.avg(col("reading")).alias("b")], 1000)
        .with_column_renamed("sensor_name", "hs")
    )
    import pytest as _pytest

    from denormalized_tpu.common.errors import PlanError

    with _pytest.raises(PlanError, match="equi conjunct"):
        left.join_on(right, "inner", [col("a") < col("b")])


def test_join_on_shared_name_columns():
    """col('k') == col('k') where both inputs carry 'k': the verbatim
    column fast path must keep treating it as a shared equi-key (Join
    emits the shared column once), not demote it to a residual."""
    rng = np.random.default_rng(11)
    t0 = 1_700_000_000_000
    _, temp_batches, hum_batches = _make_sources(rng, t0, n_batches=4)
    ctx = Context()
    left = ctx.from_source(
        MemorySource.from_batches(temp_batches, timestamp_column="occurred_at_ms"),
        name="t4",
    ).window(["sensor_name"], [F.avg(col("reading")).alias("avg_t")], 1000)
    right = (
        ctx.from_source(
            MemorySource.from_batches(hum_batches, timestamp_column="occurred_at_ms"),
            name="h4",
        )
        .window(["sensor_name"], [F.avg(col("reading")).alias("avg_h")], 1000)
        # non-key shared names still need a rename (pre-existing rule);
        # the KEY columns stay shared-name on purpose
        .with_column_renamed("window_end_time", "hwe")
    )
    joined = left.join_on(
        right,
        "inner",
        [
            col("sensor_name") == col("sensor_name"),
            col("window_start_time") == col("window_start_time"),
        ],
    )
    result = joined.collect()
    assert result.num_rows > 0
    assert result.schema.names.count("sensor_name") == 1  # shared key once


# -- property test: windowed join vs brute-force oracle ------------------

try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ModuleNotFoundError:  # image without hypothesis: keep the
    # concrete join tests collectable, skip only the property test
    HAVE_HYPOTHESIS = False

if HAVE_HYPOTHESIS:


    # pytest inserts tests/ itself on sys.path (no __init__.py here), so the
    # sibling module imports under its own name — the same module object the
    # suite already created, not a 'tests.' package double-import
    from test_window_properties import oracle_values


    @st.composite
    def _join_case(draw):
        """Two random streams with disorder and late rows; tumbling 1s join."""
        t0 = 1_700_000_000_000
        streams = []
        for side in range(2):
            n_batches = draw(st.integers(2, 5))
            batches = []
            base = 0
            for _ in range(n_batches):
                n = draw(st.integers(1, 20))
                base += draw(st.integers(0, 800))
                offs = draw(
                    st.lists(st.integers(-500, 900), min_size=n, max_size=n)
                )
                ts = sorted(max(0, base + o) + t0 for o in offs)
                ks = draw(
                    st.lists(
                        st.sampled_from(["a", "b", "c"]), min_size=n, max_size=n
                    )
                )
                vs = [float((i * 7 + side) % 11) for i in range(n)]
                batches.append((ts, ks, vs))
            streams.append(batches)
        return streams


    @settings(max_examples=25, deadline=None)
    @given(_join_case())
    def test_windowed_join_matches_oracle(case):
        """The inner windowed stream join must equal the brute-force join of
        the two per-stream window oracles (each with its own watermark and
        late-row drops) on (window_start, key) — the stream_join example
        semantics (reference examples/examples/stream_join.rs:61-80) under
        random disorder."""
        L = 1000
        raw_l, raw_r = case
        schema = Schema(
            [
                Field("occurred_at_ms", DataType.INT64, nullable=False),
                Field("sensor_name", DataType.STRING, nullable=False),
                Field("reading", DataType.FLOAT64),
            ]
        )

        def to_batches(raw):
            return [
                RecordBatch(
                    schema,
                    [
                        np.asarray(ts, np.int64),
                        np.asarray(ks, object),
                        np.asarray(vs),
                    ],
                )
                for ts, ks, vs in raw
            ]

        ctx = Context()
        left = ctx.from_source(
            MemorySource.from_batches(
                to_batches(raw_l), timestamp_column="occurred_at_ms"
            ),
            name="pj_l",
        ).window(["sensor_name"], [F.avg(col("reading")).alias("avg_l")], L)
        right = (
            ctx.from_source(
                MemorySource.from_batches(
                    to_batches(raw_r), timestamp_column="occurred_at_ms"
                ),
                name="pj_r",
            )
            .window(["sensor_name"], [F.avg(col("reading")).alias("avg_r")], L)
            .with_column_renamed("sensor_name", "rs")
            .with_column_renamed("window_start_time", "rws")
            .with_column_renamed("window_end_time", "rwe")
        )
        res = left.join(
            right,
            "inner",
            ["sensor_name", "window_start_time"],
            ["rs", "rws"],
        ).collect()

        want_l = oracle_values(raw_l, L, L)
        want_r = oracle_values(raw_r, L, L)
        want = {
            k: (np.mean(want_l[k]), np.mean(want_r[k]))
            for k in set(want_l) & set(want_r)
        }
        got = {}
        for i in range(res.num_rows):
            key = (
                int(res.column(WINDOW_START_COLUMN)[i]),
                res.column("sensor_name")[i],
            )
            assert key not in got, f"duplicate joined row {key}"
            got[key] = (
                float(res.column("avg_l")[i]),
                float(res.column("avg_r")[i]),
            )
        assert set(got) == set(want), sorted(set(got) ^ set(want))[:5]
        for k, (al, ar) in want.items():
            np.testing.assert_allclose(got[k][0], al, rtol=1e-5, atol=1e-5)
            np.testing.assert_allclose(got[k][1], ar, rtol=1e-5, atol=1e-5)


else:
    import pytest

    @pytest.mark.skip(reason="hypothesis not installed in this image")
    def test_windowed_join_matches_oracle():
        pass


# -- existence joins (LeftSemi / LeftAnti, datastream.rs:129) ------------


def _rows(res):
    """Materialize a left-schema result as a set-with-counts of row tuples."""
    from collections import Counter

    return Counter(
        (int(res.column("ts")[i]), res.column("k")[i],
         float(res.column("v")[i]))
        for i in range(res.num_rows)
    )


def test_left_semi_join_emits_matching_left_rows_once():
    """Semi: every left row with >=1 right key match emits exactly once,
    with the LEFT schema only — regardless of how many right rows match
    or which side arrives first."""
    t0 = 1_700_000_000_000
    L_rows = [
        [(t0 + 1, "a", 1.0), (t0 + 2, "b", 2.0)],
        [(t0 + 500, "a", 3.0), (t0 + 501, "c", 4.0)],
        [(t0 + 1000, "d", 5.0)],
    ]
    R_rows = [
        [(t0 + 3, "a", 10.0), (t0 + 4, "a", 11.0)],  # dup matches: still 1 emit
        [(t0 + 600, "c", 12.0)],
        [(t0 + 1100, "zz", 13.0)],
    ]
    left, right = _raw_sources(L_rows, R_rows)
    res = left.join(right, "semi", ["k"], ["k2"]).collect()
    # left-only schema: no right columns surface
    assert "w" not in res.schema.names and "k2" not in res.schema.names
    got = _rows(res)
    want = {(t0 + 1, "a", 1.0): 1, (t0 + 500, "a", 3.0): 1,
            (t0 + 501, "c", 4.0): 1}
    assert dict(got) == want, (dict(got), want)


def test_left_anti_join_emits_matchless_left_rows():
    """Anti: left rows with NO right key match emit (at EOS for a bounded
    stream), each exactly once, left schema only."""
    t0 = 1_700_000_000_000
    L_rows = [
        [(t0 + 1, "a", 1.0), (t0 + 2, "b", 2.0)],
        [(t0 + 500, "c", 3.0), (t0 + 501, "b", 4.0)],
    ]
    R_rows = [
        [(t0 + 3, "a", 10.0)],
        [(t0 + 600, "c", 12.0), (t0 + 601, "c", 13.0)],
    ]
    left, right = _raw_sources(L_rows, R_rows)
    res = left.join(right, "anti", ["k"], ["k2"]).collect()
    assert "w" not in res.schema.names
    got = _rows(res)
    want = {(t0 + 2, "b", 2.0): 1, (t0 + 501, "b", 4.0): 1}
    assert dict(got) == want, (dict(got), want)


def test_semi_join_filter_gates_existence():
    """The join filter participates in the existence check: a key-equal
    pair rejected by the filter does not count as a match (for semi OR
    anti), exactly like DataFusion's filtered semi join."""
    t0 = 1_700_000_000_000
    L_rows = [[(t0 + 1, "a", 1.0), (t0 + 2, "b", 50.0)]]
    R_rows = [[(t0 + 3, "a", 10.0), (t0 + 4, "b", 10.0)]]
    left, right = _raw_sources(L_rows, R_rows)
    # match requires w > v: a (10 > 1) passes, b (10 > 50) fails
    res = left.join(right, "semi", ["k"], ["k2"],
                    filter=col("w") > col("v")).collect()
    assert dict(_rows(res)) == {(t0 + 1, "a", 1.0): 1}
    left2, right2 = _raw_sources(L_rows, R_rows)
    res2 = left2.join(right2, "anti", ["k"], ["k2"],
                      filter=col("w") > col("v")).collect()
    assert dict(_rows(res2)) == {(t0 + 2, "b", 50.0): 1}


def test_right_semi_anti_normalize_by_swapping():
    """RightSemi(a,b) == LeftSemi(b,a): the API normalizes, the output is
    RIGHT-side rows."""
    t0 = 1_700_000_000_000
    L_rows = [[(t0 + 1, "a", 1.0), (t0 + 2, "b", 2.0)]]
    R_rows = [[(t0 + 3, "a", 10.0), (t0 + 4, "x", 11.0)]]
    left, right = _raw_sources(L_rows, R_rows)
    res = left.join(right, "right_semi", ["k"], ["k2"]).collect()
    assert "v" not in res.schema.names  # left columns don't surface
    assert [(int(res.column("ts2")[i]), res.column("k2")[i])
            for i in range(res.num_rows)] == [(t0 + 3, "a")]
    left2, right2 = _raw_sources(L_rows, R_rows)
    res2 = left2.join(right2, "RightAnti", ["k"], ["k2"]).collect()
    assert [(int(res2.column("ts2")[i]), res2.column("k2")[i])
            for i in range(res2.num_rows)] == [(t0 + 4, "x")]


def test_anti_join_watermark_eviction_is_final():
    """Watermark-eviction interaction: a left row that ages past the
    retention horizon unmatched emits as anti THEN — a matching right row
    arriving later must neither retract the anti emission nor match the
    evicted row (same finality contract as the inner join's eviction)."""
    t0 = 1_700_000_000_000
    gap = 400_000  # > default 300s retention → forces eviction
    L_rows = [
        [(t0 + 1, "old", 1.0)],
        [(t0 + gap, "new", 2.0)],
        [(t0 + gap + 1000, "new", 3.0)],
    ]
    R_rows = [
        [(t0 + 2, "none", 0.0)],
        [(t0 + gap + 5, "new", 10.0)],
        # 'old' arrives only after the left 'old' row evicted
        [(t0 + gap + 1001, "old", 20.0)],
    ]
    left, right = _raw_sources(L_rows, R_rows)
    res = left.join(right, "anti", ["k"], ["k2"]).collect()
    got = dict(_rows(res))
    # 'old' evicted unmatched → anti; 'new' rows matched → absent
    assert got == {(t0 + 1, "old", 1.0): 1}, got


def test_semi_join_filter_ambiguous_shared_name_rejected():
    """A semi/anti join FILTER referencing a column both sides carry must
    raise (it would silently bind left); shared equi-keys and untouched
    shared names stay fine."""
    import pytest

    from denormalized_tpu.common.errors import PlanError
    from denormalized_tpu.common.record_batch import RecordBatch
    from denormalized_tpu.common.schema import DataType, Field, Schema

    t0 = 1_700_000_000_000
    S = Schema([Field("ts", DataType.INT64, nullable=False),
                Field("k", DataType.STRING, nullable=False),
                Field("v", DataType.FLOAT64)])

    def src(name):
        rb = RecordBatch(S, [np.asarray([t0], np.int64),
                             np.asarray(["a"], object),
                             np.asarray([1.0])])
        return Context().from_source(
            MemorySource.from_batches([rb], timestamp_column="ts"),
            name=name)

    ctx = Context()
    l_, r_ = src("l"), src("r")
    with pytest.raises(PlanError, match="ambiguous"):
        l_.join(r_, "semi", ["k"], ["k"], filter=col("v") > 0.5)
    # same-named columns WITHOUT a filter referencing them are fine
    res = l_.join(r_, "semi", ["k"], ["k"]).collect()
    assert res.num_rows == 1
    # and the shared equi-key itself is referenceable (equal on a pair)
    res2 = src("l2").join(src("r2"), "semi", ["k"], ["k"],
                          filter=col("k") == "a").collect()
    assert res2.num_rows == 1
