"""Stream-stream join tests — the stream_join example pattern: two windowed
streams joined on (sensor, window bounds) (reference
examples/examples/stream_join.rs:15-85)."""

import numpy as np

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.common.constants import WINDOW_START_COLUMN
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.sources.memory import MemorySource


def _make_sources(rng, t0, n_batches=8, rows=200):
    schema = Schema(
        [
            Field("occurred_at_ms", DataType.INT64, nullable=False),
            Field("sensor_name", DataType.STRING, nullable=False),
            Field("reading", DataType.FLOAT64),
        ]
    )
    def batches(seed_shift):
        out = []
        for b in range(n_batches):
            ts = np.sort(t0 + b * 500 + rng.integers(0, 500, rows))
            names = rng.choice(["s0", "s1", "s2"], size=rows)
            vals = rng.normal(50, 5, rows) + seed_shift
            out.append(
                RecordBatch(
                    schema,
                    [ts, names.astype(object), vals],
                )
            )
        return out

    return schema, batches(0), batches(100)


def test_windowed_stream_join():
    rng = np.random.default_rng(3)
    t0 = 1_700_000_000_000
    _, temp_batches, hum_batches = _make_sources(rng, t0)

    ctx = Context()
    temperature = ctx.from_source(
        MemorySource.from_batches(temp_batches, timestamp_column="occurred_at_ms"),
        name="temperature",
    ).window(
        ["sensor_name"], [F.avg(col("reading")).alias("avg_temperature")], 1000
    )
    humidity = (
        ctx.from_source(
            MemorySource.from_batches(hum_batches, timestamp_column="occurred_at_ms"),
            name="humidity",
        )
        .window(["sensor_name"], [F.avg(col("reading")).alias("avg_humidity")], 1000)
        .with_column_renamed("sensor_name", "humidity_sensor")
        .with_column_renamed("window_start_time", "humidity_window_start_time")
        .with_column_renamed("window_end_time", "humidity_window_end_time")
    )
    joined = temperature.join(
        humidity,
        "inner",
        ["sensor_name", "window_start_time"],
        ["humidity_sensor", "humidity_window_start_time"],
    )
    res = joined.collect()
    assert res.num_rows > 0
    # every joined row agrees on key + window
    assert (
        res.column("sensor_name") == res.column("humidity_sensor")
    ).all()
    assert (
        res.column(WINDOW_START_COLUMN) == res.column("humidity_window_start_time")
    ).all()
    # both aggregates present and separated by the +100 shift
    assert (
        res.column("avg_humidity") - res.column("avg_temperature")
    ).mean() > 90


def test_left_join_emits_unmatched():
    schema = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("k", DataType.STRING, nullable=False),
            Field("v", DataType.FLOAT64),
        ]
    )
    t0 = 1_700_000_000_000

    def mk(ts, ks, vs):
        return RecordBatch(
            schema,
            [np.asarray(ts, np.int64), np.asarray(ks, object), np.asarray(vs)],
        )

    ctx = Context()
    left = ctx.from_source(
        MemorySource.from_batches(
            [mk([t0, t0 + 10], ["a", "b"], [1.0, 2.0])], timestamp_column="ts"
        ),
        name="left",
    )
    right = (
        ctx.from_source(
            MemorySource.from_batches(
                [mk([t0 + 5], ["a"], [9.0])], timestamp_column="ts"
            ),
            name="right",
        )
        .with_column_renamed("k", "rk")
        .with_column_renamed("ts", "rts")
        .with_column_renamed("v", "rv")
    )
    res = left.join(right, "left", ["k"], ["rk"]).collect()
    rows = {res.column("k")[i]: i for i in range(res.num_rows)}
    assert set(rows) == {"a", "b"}
    # matched row has right value; unmatched row has null mask on right cols
    ia, ib = rows["a"], rows["b"]
    assert float(res.column("rv")[ia]) == 9.0
    rv_mask = res.mask("rv")
    assert rv_mask is not None and not rv_mask[ib]
