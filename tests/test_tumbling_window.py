"""Golden-window integration tests: replay source → tumbling windowed
aggregation → collected results vs a numpy oracle.

This is the integration layer the reference never had (SURVEY.md §4): its
de-facto test was running examples against live Kafka."""

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.common.constants import WINDOW_END_COLUMN, WINDOW_START_COLUMN
from denormalized_tpu.sources.memory import MemorySource


def window_oracle(ts, keys, vals, length_ms):
    """Reference semantics: tumbling windows epoch-aligned; watermark is the
    monotonic max of batch min-ts; with in-order batches every window emits."""
    out = {}
    for t, k, v in zip(ts, keys, vals):
        w = (t // length_ms) * length_ms
        out.setdefault((w, k), []).append(v)
    return out


@pytest.mark.parametrize("num_partitions", [1])
def test_simple_aggregation_end_to_end(sensor_schema, make_batch, num_partitions):
    """The simple_aggregation example config: 1s tumbling
    count/min/max/avg over sensor_name (reference
    examples/examples/simple_aggregation.rs:15-60)."""
    rng = np.random.default_rng(0)
    n_batches, rows = 20, 500
    batches, all_ts, all_keys, all_vals = [], [], [], []
    t0 = 1_700_000_000_000
    for b in range(n_batches):
        # each batch spans ~250ms, advancing in time (in-order stream)
        ts = t0 + b * 250 + rng.integers(0, 250, size=rows)
        ts.sort()
        names = rng.choice(["sensor_%d" % i for i in range(10)], size=rows)
        vals = rng.normal(50.0, 10.0, size=rows)
        batches.append(make_batch(ts, names, vals))
        all_ts += ts.tolist()
        all_keys += names.tolist()
        all_vals += vals.tolist()

    ctx = Context()
    ds = (
        ctx.from_source(
            MemorySource.from_batches(
                batches, timestamp_column="occurred_at_ms", num_partitions=num_partitions
            )
        )
        .window(
            [col("sensor_name")],
            [
                F.count(col("reading")).alias("count"),
                F.min(col("reading")).alias("min"),
                F.max(col("reading")).alias("max"),
                F.avg(col("reading")).alias("average"),
            ],
            1000,
        )
    )
    result = ds.collect()

    oracle = window_oracle(all_ts, all_keys, all_vals, 1000)
    got = {}
    for i in range(result.num_rows):
        key = (
            int(result.column(WINDOW_START_COLUMN)[i]),
            result.column("sensor_name")[i],
        )
        assert key not in got, f"duplicate window emission for {key}"
        got[key] = {
            "count": int(result.column("count")[i]),
            "min": float(result.column("min")[i]),
            "max": float(result.column("max")[i]),
            "avg": float(result.column("average")[i]),
            "end": int(result.column(WINDOW_END_COLUMN)[i]),
        }

    assert set(got) == set(oracle)
    for key, vals in oracle.items():
        g = got[key]
        assert g["count"] == len(vals)
        assert g["end"] == key[0] + 1000
        np.testing.assert_allclose(g["min"], np.min(vals), rtol=1e-6)
        np.testing.assert_allclose(g["max"], np.max(vals), rtol=1e-6)
        np.testing.assert_allclose(g["avg"], np.mean(vals), rtol=1e-4)


def test_ungrouped_window(sensor_schema, make_batch):
    """Ungrouped windows — the reference's WindowAggStream/Partial+Final path
    (streaming_window.rs:421-482) — degenerate G=1 case here."""
    t0 = 1_700_000_000_000
    b1 = make_batch([t0 + 100, t0 + 200, t0 + 900], ["a", "b", "a"], [1.0, 2.0, 3.0])
    b2 = make_batch([t0 + 1100, t0 + 1500], ["b", "c"], [10.0, 20.0])
    b3 = make_batch([t0 + 2600], ["c"], [30.0])

    ctx = Context()
    result = (
        ctx.from_source(
            MemorySource.from_batches([b1, b2, b3], timestamp_column="occurred_at_ms")
        )
        .window([], [F.count(col("reading")).alias("cnt"), F.sum(col("reading")).alias("total")], 1000)
        .collect()
    )
    rows = {
        int(result.column(WINDOW_START_COLUMN)[i]): (
            int(result.column("cnt")[i]),
            float(result.column("total")[i]),
        )
        for i in range(result.num_rows)
    }
    assert rows == {
        t0: (3, 6.0),
        t0 + 1000: (2, 30.0),
        t0 + 2000: (1, 30.0),
    }


def test_incremental_emission_before_close(sensor_schema, make_batch):
    """Windows must emit as the watermark passes them, not only at EOS."""
    t0 = 1_700_000_000_000
    batches = [
        make_batch([t0 + i * 300 + j for j in range(3)], ["x"] * 3, [1.0] * 3)
        for i in range(12)  # spans ~3.6s
    ]
    from denormalized_tpu.sources.memory import GeneratorSource

    fed = []

    def gen():
        for b in batches:
            fed.append(1)
            yield b

    ctx = Context()
    src = GeneratorSource(
        sensor_schema,
        [gen],
        timestamp_column="occurred_at_ms",
        unbounded=False,
    )
    ds = ctx.from_source(src).window(
        ["sensor_name"], [F.count(col("reading")).alias("cnt")], 1000
    )
    emitted_at = []  # how many source batches had been fed when each window arrived
    rows = 0
    for batch in ds.stream():
        emitted_at.append(len(fed))
        rows += batch.num_rows
    assert rows == 4
    # windows 0..2 close mid-stream as the watermark passes them; only the
    # last window may rely on the EOS flush
    assert emitted_at[0] < len(batches), "first window only emitted at EOS"
    assert sum(1 for e in emitted_at if e < len(batches)) >= 3


def test_late_data_dropped(sensor_schema, make_batch):
    """Late rows (window already emitted) are dropped, mirroring
    streaming_window.rs:982-991."""
    t0 = 1_700_000_000_000
    batches = [
        make_batch([t0 + 100], ["a"], [1.0]),
        make_batch([t0 + 2500], ["a"], [2.0]),  # watermark → t0+2500, emits w0,w1
        make_batch([t0 + 300], ["a"], [99.0]),  # late into w0 — dropped
        make_batch([t0 + 3600], ["a"], [3.0]),
    ]
    ctx = Context()
    result = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
        )
        .window(["sensor_name"], [F.count(col("reading")).alias("cnt")], 1000)
        .collect()
    )
    counts = {
        int(result.column(WINDOW_START_COLUMN)[i]): int(result.column("cnt")[i])
        for i in range(result.num_rows)
    }
    assert counts[t0] == 1  # late row not counted
