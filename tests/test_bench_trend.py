"""bench_trend --gate: the perf trajectory as a CI gate, not just a log.

Stdlib-only surface (tools/bench_trend.py runs in jax-free driver
environments); these tests pin the gate semantics: regression beyond
the threshold exits 2, improvement and single-record histories pass,
cross-device records never compare against each other, and the
committed BENCH_HISTORY.jsonl itself passes the wired lint.sh gate.
"""

import json
from pathlib import Path

from tools.bench_trend import by_config, gate, load_history, main

REPO = Path(__file__).resolve().parent.parent


def _hist(tmp_path, entries):
    p = tmp_path / "hist.jsonl"
    p.write_text("\n".join(json.dumps(e) for e in entries) + "\n")
    return p


def _e(round_, value, device="cpu", config="simple"):
    return {
        "round": round_, "config": config, "value": value,
        "device": device, "unit": "rows/s", "metric": "m",
    }


def test_gate_passes_on_improvement(tmp_path):
    p = _hist(tmp_path, [_e("r1", 100), _e("r2", 150)])
    assert main(["--path", str(p), "--gate", "--config", "simple"]) == 0


def test_gate_fails_on_regression_beyond_threshold(tmp_path):
    p = _hist(tmp_path, [_e("r1", 100), _e("r2", 80)])
    rc = main([
        "--path", str(p), "--gate", "--config", "simple",
        "--max-regress-pct", "10",
    ])
    assert rc == 2


def test_gate_tolerates_regression_within_threshold(tmp_path):
    p = _hist(tmp_path, [_e("r1", 100), _e("r2", 95)])
    rc = main([
        "--path", str(p), "--gate", "--config", "simple",
        "--max-regress-pct", "10",
    ])
    assert rc == 0


def test_gate_single_record_passes(tmp_path):
    p = _hist(tmp_path, [_e("r1", 100)])
    assert main(["--path", str(p), "--gate", "--config", "simple"]) == 0


def test_gate_never_compares_across_devices(tmp_path):
    # a TPU point followed by a (much slower) CPU point is not a
    # regression: the CPU point compares against the last CPU point
    p = _hist(tmp_path, [
        _e("r1", 90, device="cpu"),
        _e("r2", 1000, device="tpu"),
        _e("r3", 95, device="cpu"),
    ])
    assert main(["--path", str(p), "--gate", "--config", "simple"]) == 0


def test_gate_unknown_config_errors(tmp_path):
    p = _hist(tmp_path, [_e("r1", 100)])
    assert main(["--path", str(p), "--gate", "--config", "nope"]) == 1
    # --gate without --config is a usage error, not a silent pass
    assert main(["--path", str(p), "--gate"]) == 1


def test_gate_unit_contract():
    rc, msg = gate([_e("r1", 100), _e("r2", 50)], 10.0, "simple")
    assert rc == 2 and "REGRESSION" in msg
    rc, msg = gate([], 10.0, "missing")
    assert rc == 1


def test_committed_history_passes_wired_gate():
    """The exact invocation tools/lint.sh wires must pass on the
    committed artifact — otherwise lint.sh would be red at HEAD."""
    entries = load_history(REPO / "BENCH_HISTORY.jsonl")
    assert entries, "committed BENCH_HISTORY.jsonl missing or empty"
    groups = by_config(entries)
    rc, msg = gate(groups["simple"], 25.0, "simple")
    assert rc == 0, msg
