"""Fast end-to-end runs of the soak harness (tools/soak.py).

The real soaks are minutes long (committed artifacts SOAK.json /
SOAK_JOIN.json / SOAK_SESSION.json); this keeps the harness itself
CI-validated: a ~20s run with one mid-stream SIGKILL must lose zero
windows, match the golden, and see EOS — for the simple windowed
pipeline, the stream-join pipeline (join state is the hardest
checkpoint-restore path), session windows (exact bounds checked), and
the sketch-native approx pipeline (HLL estimates held to exact integer
equality against a golden folded with the engine's own kernels).
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


@pytest.mark.parametrize(
    "pipeline", ["simple", "sliding", "join", "session", "udaf", "kafka",
                 "approx"]
)
def test_soak_smoke(tmp_path, pipeline):
    out = tmp_path / "soak.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "tools" / "soak.py"),
            "--pipeline", pipeline,
            "--minutes", "0.35", "--kill-every", "8",
            "--pace", "150000", "--out", str(out),
        ],
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    r = json.loads(out.read_text())
    if r.get("aborted") and "relay active" in r["aborted"]:
        pytest.skip("soak yielded to an active TPU relay")
    assert r["aborted"] is None, r
    assert r["eos_done_seen"], r
    assert r["kills"] >= 1, r
    assert r["windows_lost"] == 0, r
    assert r["windows_spurious"] == 0, r
    assert r["windows_mismatched"] == 0, r
    assert r["emitted_windows"] == r["golden_windows"] > 0, r
    # recovery after SIGKILL banks its first emission promptly
    for t in r["recovery_first_emit_s"]:
        assert t < 30, r


def test_soak_smoke_join_dense(tmp_path):
    """Shared-join multi-query registry under SIGKILL: 10 staggered
    queries windowing over ONE fact×dim interval join, every emission
    checked byte-identical to its independent join+window oracle,
    warm backfills exact, one pipeline build per segment."""
    out = tmp_path / "soak.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "tools" / "soak.py"),
            "--pipeline", "join_dense",
            "--minutes", "0.5", "--kill-every", "8",
            "--pace", "40000", "--batch-rows", "2048",
            "--out", str(out),
        ],
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    r = json.loads(out.read_text())
    if r.get("aborted") and "relay active" in r["aborted"]:
        pytest.skip("soak yielded to an active TPU relay")
    assert r["aborted"] is None, r
    assert r["eos_done_seen"], r
    assert r["kills"] >= 1, r
    jd = r["join_dense"]
    assert jd["oracle_rc"] == 0, jd
    assert jd["oracle_windows"] > 0, jd
    assert jd["failures"] == 0, jd
    assert jd["queries_silent"] == [], jd
    assert jd["backfill_missing"] == [], jd
    assert jd["backfilled_joiners"] >= 3, jd
    assert jd["max_builds_per_segment"] == 1, jd


def test_soak_smoke_query_dense(tmp_path):
    """Live multi-query registry under one SIGKILL: 50 staggered
    queries, every emission checked byte-identical to its independent
    oracle, backfills exact, one pipeline build per segment."""
    out = tmp_path / "soak.json"
    proc = subprocess.run(
        [
            sys.executable, str(REPO / "tools" / "soak.py"),
            "--pipeline", "query_dense",
            "--minutes", "0.5", "--kill-every", "8",
            "--pace", "40000", "--batch-rows", "2048",
            "--out", str(out),
        ],
        capture_output=True, text=True, timeout=240,
    )
    assert proc.returncode == 0, proc.stderr[-800:]
    r = json.loads(out.read_text())
    if r.get("aborted") and "relay active" in r["aborted"]:
        pytest.skip("soak yielded to an active TPU relay")
    assert r["aborted"] is None, r
    assert r["eos_done_seen"], r
    assert r["kills"] >= 1, r
    qd = r["query_dense"]
    assert qd["oracle_rc"] == 0, qd
    assert qd["oracle_windows"] > 0, qd
    assert qd["failures"] == 0, qd
    assert qd["queries_silent"] == [], qd
    assert qd["backfill_missing"] == [], qd
    assert qd["backfilled_joiners"] >= 10, qd
    assert qd["max_builds_per_segment"] == 1, qd
