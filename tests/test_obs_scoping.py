"""Per-query registry binding (the PR-6 documented limitation, fixed):
exporter lifecycle and metrics enablement are scoped to each execution,
so two concurrent queries with different ``metrics_enabled`` settings in
one process no longer fight over a process-global flag."""

import threading

import numpy as np
import pytest

from denormalized_tpu import Context, col, obs
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.obs.registry import NULL, MetricsRegistry
from denormalized_tpu.sources.memory import MemorySource


@pytest.fixture
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = obs.use_registry(reg)
    yield reg
    obs.use_registry(prev)


T0 = 1_700_000_000_000


def _batches(make_batch, n_batches=8, rows=200, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = np.sort(T0 + b * 400 + rng.integers(0, 400, size=rows))
        names = rng.choice([f"sensor_{i}" for i in range(5)], size=rows)
        vals = rng.normal(50.0, 10.0, size=rows)
        out.append(make_batch(ts, names, vals))
    return out


def _run_query(make_batch, enabled, rows=200, n_batches=8, seed=0):
    ctx = Context(EngineConfig(
        min_batch_bucket=256, metrics_enabled=enabled,
    ))
    src = MemorySource.from_batches(
        _batches(make_batch, n_batches=n_batches, rows=rows, seed=seed),
        timestamp_column="occurred_at_ms",
    )
    ds = ctx.from_source(src).window(
        [col("sensor_name")],
        [F.count(col("reading")).alias("count")],
        1000,
    )
    ds.collect()
    return ctx


def _window_op(ctx):
    from denormalized_tpu.physical.window_exec import StreamingWindowExec
    from denormalized_tpu.state.checkpoint import walk

    for op in walk(ctx._last_physical):
        if isinstance(op, StreamingWindowExec):
            return op
    raise AssertionError("no window operator in the plan")


def test_concurrent_queries_with_mixed_enablement_do_not_fight(
    make_batch, registry
):
    """The regression the satellite demands: query A (metrics on) and
    query B (metrics off) EXECUTING CONCURRENTLY in one process.  A's
    operators must bind live instruments, B's must bind nulls, and the
    shared registry must see exactly A's rows — regardless of
    interleaving."""
    results: dict = {}
    barrier = threading.Barrier(2, timeout=30)

    def run(key, enabled, seed):
        barrier.wait()  # maximize overlap of the two builds + runs
        results[key] = _run_query(
            make_batch, enabled, n_batches=12, seed=seed
        )

    ta = threading.Thread(target=run, args=("a", True, 1))
    tb = threading.Thread(target=run, args=("b", False, 2))
    ta.start()
    tb.start()
    ta.join(timeout=60)
    tb.join(timeout=60)
    assert "a" in results and "b" in results

    win_a = _window_op(results["a"])
    win_b = _window_op(results["b"])
    # A bound live handles; B bound the shared falsy null
    assert win_a._obs_rows_in is not NULL
    assert win_a._obs_rows_in.value == 12 * 200
    assert win_b._obs_rows_in is NULL
    assert win_b._obs_batch_ms is NULL
    # the registry's series carry ONLY A's counts: B contributed nothing
    c = registry.counter("dnz_op_rows_in_total", op="window")
    assert c.value == 12 * 200
    # both queries still produced correct output-side dict metrics
    for key in ("a", "b"):
        m = _window_op(results[key]).metrics()
        assert m["rows_in"] == 12 * 200


def test_disabled_query_binds_nothing_enabled_query_unaffected(
    make_batch, registry
):
    """Sequential form of the same contract (deterministic ordering):
    a disabled run leaves the registry untouched; a following enabled
    run binds normally."""
    _run_query(make_batch, enabled=False)
    assert registry.instruments() == []
    _run_query(make_batch, enabled=True)
    c = registry.counter("dnz_op_rows_in_total", op="window")
    assert c.value == 8 * 200


def test_bound_registry_nesting_and_out_of_order_exit():
    """The thread-local binding stack: nesting resolves innermost, and
    an out-of-order exit (interleaved generators) removes the right
    entry, not whatever is on top."""
    default = obs.current_registry()
    r1 = MetricsRegistry(enabled=True)
    r2 = MetricsRegistry(enabled=True)
    cm1 = obs.bound_registry(r1)
    cm1.__enter__()
    assert obs.current_registry() is r1
    cm2 = obs.bound_registry(r2)
    cm2.__enter__()
    assert obs.current_registry() is r2
    # r1's context exits FIRST (its generator finished while r2's is
    # still live): r2 must stay the current binding
    cm1.__exit__(None, None, None)
    assert obs.current_registry() is r2
    cm2.__exit__(None, None, None)
    assert obs.current_registry() is default


def test_worker_thread_binds_into_captured_registry(make_batch, registry):
    """An instrument bound FROM another thread inside bound_registry's
    capture (the prefetch-worker re-entry pattern) lands in the captured
    registry, not the thread's default."""
    captured = MetricsRegistry(enabled=True)
    bound = {}

    def worker(reg):
        with obs.bound_registry(reg):
            bound["c"] = obs.counter("dnz_op_rows_in_total", op="capture")

    t = threading.Thread(target=worker, args=(captured,))
    t.start()
    t.join(timeout=10)
    assert bound["c"] is captured.counter(
        "dnz_op_rows_in_total", op="capture"
    )
    assert registry.instruments() == []


def test_exporters_scope_to_query_registry(make_batch, registry, tmp_path):
    """A query's JSONL exporter snapshots the registry THAT query
    resolved — a disabled query with an exporter writes empty metric
    snapshots instead of leaking whatever the process default holds."""
    registry.counter("dnz_op_rows_in_total", op="preexisting").add(7)
    jsonl = tmp_path / "obs.jsonl"
    ctx = Context(EngineConfig(
        min_batch_bucket=256,
        metrics_enabled=False,
        metrics_jsonl_path=str(jsonl),
        metrics_jsonl_interval_s=0.05,
    ))
    src = MemorySource.from_batches(
        _batches(make_batch), timestamp_column="occurred_at_ms"
    )
    ctx.from_source(src).window(
        [col("sensor_name")], [F.count(col("reading")).alias("c")], 1000
    ).collect()
    from denormalized_tpu.obs.jsonl import read_stream

    snaps = read_stream(jsonl)
    assert snaps  # the exporter ran (final snapshot on clean stop)
    assert all(s["metrics"] == {} for s in snaps), (
        "disabled query's exporter leaked another registry's series"
    )
