"""Parity suite for the ``partial_merge`` device strategy: host edge
reduction (native C++ / numpy fallback) + device merge must produce the
same results as the per-row ``scatter`` path across window shapes, nulls,
variance aggregates, late data, capacity growth, and checkpoint export."""

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.constants import WINDOW_START_COLUMN
from denormalized_tpu.sources.memory import MemorySource


def _run(batches, aggs, length_ms, slide_ms=None, *, strategy, groups=None,
         cfg_extra=None):
    cfg = EngineConfig(device_strategy=strategy, **(cfg_extra or {}))
    ctx = Context(cfg)
    ds = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
    ).window(
        [col(g) for g in (groups if groups is not None else ["sensor_name"])],
        aggs(),
        length_ms,
        slide_ms,
    )
    result = ds.collect()
    keyed = {}
    group_cols = groups if groups is not None else ["sensor_name"]
    for i in range(result.num_rows):
        key = (int(result.column(WINDOW_START_COLUMN)[i]),) + tuple(
            result.column(g)[i] for g in group_cols
        )
        assert key not in keyed, f"duplicate emission {key}"
        keyed[key] = {
            n: result.column(n)[i]
            for n in result.schema.names
            if n not in group_cols
        }
    return keyed


def _assert_parity(a, b, rtol=1e-6):
    assert set(a) == set(b), (
        f"window/key sets differ: only-scatter={set(a) - set(b)} "
        f"only-partial={set(b) - set(a)}"
    )
    for k in a:
        for name, va in a[k].items():
            vb = b[k][name]
            if isinstance(va, (float, np.floating)):
                if np.isnan(va) and np.isnan(vb):
                    continue
                assert vb == pytest.approx(va, rel=rtol, abs=1e-9), (
                    k, name, va, vb
                )
            else:
                assert va == vb, (k, name, va, vb)


def _sensor_batches(make_batch, n_batches=24, rows=400, keys=10, span=250,
                    seed=0, nulls=False):
    from denormalized_tpu.common.record_batch import RecordBatch

    rng = np.random.default_rng(seed)
    t0 = 1_700_000_000_000
    batches = []
    for b in range(n_batches):
        ts = np.sort(t0 + b * span + rng.integers(0, span, rows))
        names = rng.choice([f"s{i}" for i in range(keys)], size=rows)
        vals = rng.normal(50.0, 10.0, rows)
        batch = make_batch(ts, names, vals)
        if nulls:
            mask = rng.random(rows) > 0.15
            batch = RecordBatch(
                batch.schema, batch.columns, [None, None, mask]
            )
        batches.append(batch)
    return batches


def _std_aggs():
    return [
        F.count(col("reading")).alias("cnt"),
        F.min(col("reading")).alias("mn"),
        F.max(col("reading")).alias("mx"),
        F.avg(col("reading")).alias("av"),
        F.sum(col("reading")).alias("sm"),
    ]


@pytest.mark.parametrize(
    "length,slide",
    [(1000, None), (1000, 250), (500, 200)],  # tumbling; k=4; k=3 with sub
    ids=["tumbling", "sliding_divisible", "sliding_ragged"],
)
def test_partial_matches_scatter(make_batch, length, slide):
    batches = _sensor_batches(make_batch)
    a = _run(batches, _std_aggs, length, slide, strategy="scatter")
    b = _run(batches, _std_aggs, length, slide, strategy="partial_merge")
    assert len(a) > 10
    _assert_parity(a, b)


def test_partial_with_nulls(make_batch):
    batches = _sensor_batches(make_batch, nulls=True)
    a = _run(batches, _std_aggs, 1000, strategy="scatter")
    b = _run(batches, _std_aggs, 1000, strategy="partial_merge")
    _assert_parity(a, b)


def test_partial_lean_to_full_transition(make_batch):
    """Null-free stripes ship the lean packed layout (per-column count
    planes aliased to the row-count plane); the first null switches the
    stripe to the full layout.  A stream whose nulls start mid-way must
    exercise both layouts and still match scatter exactly — counts in the
    null windows must reflect only valid rows."""
    from denormalized_tpu.common.record_batch import RecordBatch

    rng = np.random.default_rng(3)
    clean = _sensor_batches(make_batch, n_batches=12, seed=3)
    dirty = []
    for b in _sensor_batches(make_batch, n_batches=12, seed=4):
        # shift dirty batches after the clean ones in event time
        ts = np.asarray(b.column("occurred_at_ms")) + 12 * 250
        mask = rng.random(b.num_rows) > 0.2
        dirty.append(
            RecordBatch(b.schema, [ts, b.columns[1], b.columns[2]],
                        [None, None, mask])
        )
    batches = clean + dirty
    # oracle row counts per (window_start, key) INCLUDING null readings:
    # proves the dirty half really carried nulls (cnt < rows somewhere)
    rows_per_window: dict = {}
    for bt in batches:
        ts = np.asarray(bt.column("occurred_at_ms"))
        names = np.asarray(bt.column("sensor_name"))
        for t, nm in zip(ts, names):
            rows_per_window[(int(t) // 1000 * 1000, nm)] = (
                rows_per_window.get((int(t) // 1000 * 1000, nm), 0) + 1
            )
    a = _run(batches, _std_aggs, 1000, strategy="scatter")
    b = _run(batches, _std_aggs, 1000, strategy="partial_merge")
    _assert_parity(a, b)
    assert any(
        v["cnt"] < rows_per_window[k[0], k[1]] for k, v in a.items()
    ), "no window lost rows to nulls — the full layout was never exercised"


def test_partial_host_pipeline_parity(make_batch):
    """host_pipeline=True moves backend.accumulate onto a worker thread;
    results must be identical to the synchronous path (same stream, same
    windows), including across growth and null batches."""
    batches = _sensor_batches(make_batch, keys=200, nulls=True)
    a = _run(batches, _std_aggs, 1000, 250, strategy="partial_merge")
    b = _run(batches, _std_aggs, 1000, 250, strategy="partial_merge",
             cfg_extra={"host_pipeline": True})
    _assert_parity(a, b)


def test_partial_host_pipeline_error_propagates(make_batch):
    """A failure inside the worker-threaded accumulate must surface on the
    stream thread (not vanish into the pool)."""
    from denormalized_tpu.parallel import sharded_state as ss

    batches = _sensor_batches(make_batch, n_batches=8)
    orig = ss._HostPartialMixin.accumulate
    calls = {"n": 0}

    def boom(self, *a, **k):
        calls["n"] += 1
        if calls["n"] == 3:
            raise RuntimeError("injected stripe failure")
        return orig(self, *a, **k)

    ss._HostPartialMixin.accumulate = boom
    try:
        with pytest.raises(RuntimeError, match="injected stripe failure"):
            _run(batches, _std_aggs, 1000, strategy="partial_merge",
                 cfg_extra={"host_pipeline": True})
    finally:
        ss._HostPartialMixin.accumulate = orig


def test_partial_ungrouped(make_batch):
    batches = _sensor_batches(make_batch)
    a = _run(batches, _std_aggs, 1000, strategy="scatter", groups=[])
    b = _run(batches, _std_aggs, 1000, strategy="partial_merge", groups=[])
    assert len(a) > 3
    _assert_parity(a, b)


def test_partial_variance_family(make_batch):
    batches = _sensor_batches(make_batch)

    def aggs():
        return [
            F.stddev(col("reading")).alias("sd"),
            F.var(col("reading")).alias("vr"),
            F.avg(col("reading")).alias("av"),
        ]

    a = _run(batches, aggs, 1000, strategy="scatter")
    b = _run(batches, aggs, 1000, strategy="partial_merge")
    _assert_parity(a, b, rtol=1e-5)


def test_partial_late_rows_dropped(make_batch):
    """A batch far behind the watermark must be dropped identically."""
    batches = _sensor_batches(make_batch, n_batches=12)
    # splice in a late batch (timestamps from 3 windows earlier)
    rng = np.random.default_rng(9)
    t0 = 1_700_000_000_000
    late = make_batch(
        np.sort(t0 + rng.integers(0, 200, 100)),
        rng.choice(["s0", "s1"], 100),
        rng.normal(0, 1, 100),
    )
    seq = batches[:8] + [late] + batches[8:]
    a = _run(seq, _std_aggs, 1000, strategy="scatter")
    b = _run(seq, _std_aggs, 1000, strategy="partial_merge")
    _assert_parity(a, b)


def test_partial_growth(make_batch):
    """Group capacity and window-slot growth mid-stream (stripe must be
    flushed across the recompilation boundary)."""
    rng = np.random.default_rng(3)
    t0 = 1_700_000_000_000
    batches = []
    for b in range(30):
        rows = 300
        ts = np.sort(t0 + b * 200 + rng.integers(0, 200, rows))
        # cardinality ramps past the 128 default capacity
        hi = 20 + b * 12
        names = rng.choice([f"k{i}" for i in range(hi)], size=rows)
        vals = rng.normal(10.0, 3.0, rows)
        batches.append(make_batch(ts, names, vals))
    a = _run(batches, _std_aggs, 1000, strategy="scatter")
    b = _run(batches, _std_aggs, 1000, strategy="partial_merge")
    assert len({k[1] for k in a}) > 128
    _assert_parity(a, b)


def test_partial_compensated(make_batch):
    batches = _sensor_batches(make_batch)
    a = _run(
        batches, _std_aggs, 1000, strategy="scatter",
        cfg_extra={"compensated_sums": True},
    )
    b = _run(
        batches, _std_aggs, 1000, strategy="partial_merge",
        cfg_extra={"compensated_sums": True},
    )
    _assert_parity(a, b)


def test_partial_giant_span_batch(make_batch):
    """One catch-up batch spanning far more slide units than a stripe can
    hold (> U_MAX=16) must be chunk-folded, not silently truncated."""
    rng = np.random.default_rng(13)
    t0 = 1_700_000_000_000
    n = 40_000
    ts = np.sort(t0 + rng.integers(0, 40_000, n))  # 40 one-second units
    names = rng.choice([f"s{i}" for i in range(6)], size=n)
    vals = rng.normal(1.0, 0.1, n)
    batches = [make_batch(ts, names, vals)]
    a = _run(batches, _std_aggs, 1000, strategy="scatter")
    b = _run(batches, _std_aggs, 1000, strategy="partial_merge")
    assert len({k[0] for k in a}) >= 39  # windows across the whole span
    _assert_parity(a, b)


def test_partial_f32_overflow_parity(make_batch):
    """Sums overflowing f32 range: both strategies end at ±inf (the f32
    accumulator's honest answer), never NaN."""
    t0 = 1_700_000_000_000
    n = 64
    ts = np.arange(t0, t0 + n, dtype=np.int64)
    names = np.array(["a"] * n, dtype=object)
    vals = np.full(n, 1e38)
    tail = make_batch(
        np.arange(t0 + 2000, t0 + 2064, dtype=np.int64),
        np.array(["a"] * 64, dtype=object),
        np.ones(64),
    )
    batches = [make_batch(ts, names, vals), tail]
    a = _run(batches, _std_aggs, 1000, strategy="scatter")
    b = _run(batches, _std_aggs, 1000, strategy="partial_merge")
    key = (t0 // 1000 * 1000, "a")
    assert np.isinf(a[key]["sm"]) and a[key]["sm"] > 0
    assert np.isinf(b[key]["sm"]) and b[key]["sm"] > 0


def test_partial_inf_values_propagate(make_batch):
    """Genuine ±inf inputs: sum must stay ±inf (as scatter yields), not
    NaN from the (hi, lo) split's inf - inf residual."""
    t0 = 1_700_000_000_000
    n = 32
    ts = np.arange(t0, t0 + n, dtype=np.int64)
    names = np.array(["a"] * n, dtype=object)
    vals = np.ones(n)
    vals[3] = np.inf
    tail = make_batch(
        np.arange(t0 + 2000, t0 + 2032, dtype=np.int64),
        np.array(["a"] * 32, dtype=object),
        np.ones(32),
    )
    batches = [make_batch(ts, names, vals), tail]
    a = _run(batches, _std_aggs, 1000, strategy="scatter")
    b = _run(batches, _std_aggs, 1000, strategy="partial_merge")
    key = (t0 // 1000 * 1000, "a")
    assert np.isinf(a[key]["sm"]) and a[key]["sm"] > 0
    assert np.isinf(b[key]["sm"]) and b[key]["sm"] > 0


def test_partial_nan_values_propagate(make_batch):
    """NaN VALUES (valid, not null) must poison min/max identically on
    every strategy — a plain `x < mn` in the native reducer would skip
    them."""
    t0 = 1_700_000_000_000
    ts = np.arange(t0, t0 + 400, dtype=np.int64)
    names = np.array(["a", "b"] * 200, dtype=object)
    vals = np.ones(400)
    vals[7] = np.nan  # lands in key 'b'
    tail = make_batch(
        np.arange(t0 + 2000, t0 + 2100, dtype=np.int64),
        np.array(["a"] * 100, dtype=object),
        np.ones(100),
    )
    batches = [make_batch(ts, names, vals), tail]
    a = _run(batches, _std_aggs, 1000, strategy="scatter")
    b = _run(batches, _std_aggs, 1000, strategy="partial_merge")
    key = (t0 // 1000 * 1000, "b")
    assert np.isnan(a[key]["mn"]) and np.isnan(a[key]["mx"])
    assert np.isnan(b[key]["mn"]) and np.isnan(b[key]["mx"])


def test_partial_numpy_fallback_matches_native(make_batch, monkeypatch):
    from denormalized_tpu.ops import host_partial

    batches = _sensor_batches(make_batch, nulls=True)
    a = _run(batches, _std_aggs, 500, 200, strategy="partial_merge")
    monkeypatch.setattr(host_partial, "_LIB", None)
    monkeypatch.setattr(host_partial, "_LIB_TRIED", True)
    b = _run(batches, _std_aggs, 500, 200, strategy="partial_merge")
    _assert_parity(a, b, rtol=1e-12)


def test_partial_merge_key_sharded_mesh(make_batch):
    """partial_merge over an 8-device mesh (G-sharded merge under
    shard_map) must match the single-device scatter path exactly in
    shape and near-exactly in values."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device platform")
    rng = np.random.default_rng(23)
    t0 = 1_700_000_000_000
    batches = []
    for b in range(20):
        n = 768
        ts = np.sort(t0 + b * 300 + rng.integers(0, 300, n))
        # cardinality ramps past the 8-device initial capacity (1024) so
        # growth re-lays the sharded state mid-stream
        hi = 100 + b * 80
        keys = np.array(
            [f"s{i}" for i in rng.integers(0, hi, n)], dtype=object
        )
        batches.append(make_batch(ts, keys, rng.normal(50, 5, n)))
    a = _run(batches, _std_aggs, 1000, strategy="scatter")
    b = _run(
        batches, _std_aggs, 1000, strategy="partial_merge",
        cfg_extra={"mesh_devices": 8},
    )
    assert len({k[1] for k in a}) > 1024  # grew past the initial capacity
    _assert_parity(a, b)


def test_partial_merge_key_sharded_sliding(make_batch):
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device platform")
    batches = _sensor_batches(make_batch, n_batches=20)
    a = _run(batches, _std_aggs, 500, 200, strategy="scatter")
    b = _run(
        batches, _std_aggs, 500, 200, strategy="partial_merge",
        cfg_extra={"mesh_devices": 8},
    )
    _assert_parity(a, b)


def test_partial_checkpoint_kill_restore(make_batch, tmp_path):
    """Kill/restore through the shared protocol driver with the
    partial_merge backend: the barrier snapshot must include host-striped
    rows (flush-before-snapshot), and run B resumes to golden."""
    from test_checkpoint import _kill_restore_roundtrip

    rng = np.random.default_rng(77)
    t0 = 1_700_000_000_000
    batches = []
    for b in range(12):
        n = 200
        ts = np.sort(t0 + b * 400 + rng.integers(0, 400, n))
        keys = np.array([f"s{i}" for i in rng.integers(0, 7, n)], dtype=object)
        batches.append(make_batch(ts, keys, rng.normal(50, 5, n)))

    def make_cfg(path):
        return EngineConfig(
            checkpoint=path is not None,
            checkpoint_interval_s=9999,
            state_backend_path=path,
            device_strategy="partial_merge",
            emit_lag_ms=0,  # prompt emission: the driver commits a barrier
            # between mid-stream emissions
        )

    golden, a, b = _kill_restore_roundtrip(
        batches, make_cfg, str(tmp_path / "state_pm")
    )
    combined = dict(a)
    combined.update(b)
    assert set(combined) == set(golden)
    # stripe boundaries differ across the restore, so f32 merge order (and
    # the last rounded digit of sums) may differ — counts stay exact
    for k, (cnt, sm, av) in golden.items():
        gc, gs, ga = combined[k]
        assert gc == cnt, (k, gc, cnt)
        assert gs == pytest.approx(sm, rel=1e-5)
        assert ga == pytest.approx(av, rel=1e-5)
    assert len(b) < len(golden) or len(a) == 0


def test_partial_device_finalize_parity(make_batch):
    """On-device finalization (finals planes + active bitmask,
    segment_agg._finals_and_reset) must match the component-transfer path
    (device_finalize=False) on the same feed — including nulls, where
    per-column counts diverge from row counts."""
    for nulls in (False, True):
        batches = _sensor_batches(make_batch, nulls=nulls, seed=11)
        a = _run(
            batches, _std_aggs, 1000, strategy="partial_merge",
            cfg_extra={"device_finalize": False},
        )
        b = _run(
            batches, _std_aggs, 1000, strategy="partial_merge",
            cfg_extra={"device_finalize": True},
        )
        assert len(a) > 10
        # finals emit fl(hi+lo) in f32 — up to 1 ulp from the host's
        # f64 hi+lo add
        _assert_parity(a, b, rtol=1e-5)


def test_partial_device_finalize_sharded(make_batch):
    """Finals emission over the 8-device mesh (borrowed single-device
    machinery, GSPMD-partitioned) matches scatter."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device platform")
    batches = _sensor_batches(make_batch, n_batches=20)
    a = _run(batches, _std_aggs, 1000, strategy="scatter")
    b = _run(
        batches, _std_aggs, 1000, strategy="partial_merge",
        cfg_extra={"mesh_devices": 8, "device_finalize": True},
    )
    _assert_parity(a, b, rtol=1e-5)


def test_partial_emission_compaction_sharded(make_batch):
    """Device-side emission compaction now works over
    KeyShardedPartialMergeWindowState (round-3 VERDICT item 2): active
    groups permuted to the front on device, bucketed prefix transfer."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device platform")
    batches = _sensor_batches(make_batch, n_batches=20)
    a = _run(batches, _std_aggs, 1000, strategy="scatter")
    b = _run(
        batches, _std_aggs, 1000, strategy="partial_merge",
        cfg_extra={"mesh_devices": 8, "emission_compaction": True},
    )
    _assert_parity(a, b, rtol=1e-5)


def test_partial_dense_upload_layout(make_batch):
    """High-density stripes take the index-free dense pack (fewer bytes
    than compact incl. the index row) and still match scatter; the layout
    decision is exercised both ways by spying take_packed."""
    from denormalized_tpu.ops.host_partial import HostPartialStripe

    layouts = []
    orig = HostPartialStripe.take_packed

    def spy(self, base_mod):
        r = orig(self, base_mod)
        if r is not None:
            layouts.append(r[4])
        return r

    HostPartialStripe.take_packed = spy
    try:
        batches = _sensor_batches(make_batch, keys=10)
        a = _run(batches, _std_aggs, 1000, strategy="scatter")
        b = _run(batches, _std_aggs, 1000, strategy="partial_merge")
    finally:
        HostPartialStripe.take_packed = orig
    # small G (128) in a 1024 bucket: dense (3-5 planes x 1024) always
    # beats compact ((P+1) x 1024) — every flush should have gone dense
    assert layouts and all(layouts), layouts
    _assert_parity(a, b)


def test_partial_compact_upload_layout(make_batch):
    """Sparse stripes (few active cells in a grown ring) keep the compact
    indexed pack."""
    from denormalized_tpu.ops.host_partial import HostPartialStripe

    layouts = []
    orig = HostPartialStripe.take_packed

    def spy(self, base_mod):
        r = orig(self, base_mod)
        if r is not None:
            layouts.append(r[4])
        return r

    HostPartialStripe.take_packed = spy
    try:
        batches = _sensor_batches(make_batch, keys=5, n_batches=12)
        b = _run(
            batches, _std_aggs, 1000, strategy="partial_merge",
            cfg_extra={"min_group_capacity": 16384},
        )
        a = _run(batches, _std_aggs, 1000, strategy="scatter")
    finally:
        HostPartialStripe.take_packed = orig
    # G=16384 forces cells_d >= 16384 -> its bucket dwarfs the ~5-cell
    # compact bucket (1024): compact must win every flush
    assert layouts and not any(layouts), layouts
    _assert_parity(a, b)


def test_auto_strategy_never_row_ships_on_tpu(monkeypatch):
    """Round-3 VERDICT weak-7: 'auto' must PROVABLY never pick the
    row-shipping strategies on a narrow-link TPU backend.  With the
    backend reporting tpu, auto resolves to host edge-reduction
    (PartialMergeWindowState) whose strategy_name labels the bench."""
    import denormalized_tpu.parallel.sharded_state as ss
    from denormalized_tpu.ops import segment_agg as sa

    # the backend reports tpu for routing AND construction — the
    # prewarm ladders compile against the CPU platform here, which is
    # exactly what a restored-on-CPU state would do; the routing
    # decision is what this test pins
    monkeypatch.setattr(ss.jax, "default_backend", lambda: "tpu")
    spec = sa.WindowKernelSpec(
        components=tuple(sa.components_for([("count", 0)])),
        num_value_cols=1,
        window_slots=4,
        group_capacity=128,
        length_ms=1000,
        slide_ms=1000,
    )
    backend = ss.make_sharded_state(spec, None, "auto", "auto")
    assert isinstance(backend, ss.PartialMergeWindowState)
    assert backend.strategy_name == "partial_merge"


def test_auto_strategy_on_cpu_partial_merge_except_f64(monkeypatch):
    """'auto' on CPU picks host edge-reduction too (the native reducer
    beats XLA scatter adds), EXCEPT for f64 accumulators: the stripe's
    f32 hi/lo transport refuses finite f64 sums beyond f32 range
    (ops/host_partial.py), while CPU XLA scatter keeps f64 end-to-end —
    routing must not turn a working default-config f64 workload into a
    runtime OverflowError."""
    import jax.numpy as jnp

    import denormalized_tpu.parallel.sharded_state as ss
    from denormalized_tpu.ops import segment_agg as sa

    def spec_for(dtype):
        return sa.WindowKernelSpec(
            components=tuple(sa.components_for([("sum", 0)])),
            num_value_cols=1,
            window_slots=4,
            group_capacity=128,
            length_ms=1000,
            slide_ms=1000,
            accum_dtype=dtype,
        )

    monkeypatch.setattr(ss.jax, "default_backend", lambda: "cpu")
    assert isinstance(
        ss.make_sharded_state(spec_for(jnp.float32), None, "auto", "auto"),
        ss.PartialMergeWindowState,
    )
    f64 = ss.make_sharded_state(spec_for(jnp.float64), None, "auto", "auto")
    assert isinstance(f64, ss.SingleDeviceWindowState)
    assert "scatter" in f64.strategy_name
    # explicit partial_merge is still honored (the transport raises its
    # own actionable OverflowError only if an out-of-range sum occurs)
    assert isinstance(
        ss.make_sharded_state(spec_for(jnp.float64), None, "auto",
                              "partial_merge"),
        ss.PartialMergeWindowState,
    )


@pytest.mark.parametrize(
    "backend,expected_lag_s",
    [("cpu", 0.0), ("tpu", 0.2), ("gpu", 0.2)],
)
def test_emit_lag_backend_default(monkeypatch, make_batch, backend,
                                  expected_lag_s):
    """emit_lag_ms=None resolves per backend: 0 only on CPU (merges are
    memcpy-cheap and deferral would hold a paused stream's output); every
    accelerator — including GPU, which the routing measurements don't
    cover — keeps the 200ms round-trip amortization."""
    import denormalized_tpu.physical.window_exec as we

    monkeypatch.setattr(we.jax, "default_backend", lambda: backend)

    from denormalized_tpu import Context, col
    from denormalized_tpu.api import functions as F
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime.executor import build_physical
    from denormalized_tpu.sources.memory import MemorySource

    t0 = 1_700_000_000_000
    ctx = Context()
    ds = ctx.from_source(
        MemorySource.from_batches(
            [make_batch([t0], ["a"], [1.0])],
            timestamp_column="occurred_at_ms",
        )
    ).window(["sensor_name"], [F.count(col("reading")).alias("c")], 1000)
    root = build_physical(lp.Sink(ds._plan, CollectSink()), ctx)
    op, found = root, None
    while op is not None:
        if isinstance(op, we.StreamingWindowExec):
            found = op
            break
        op = getattr(op, "input_op", None)
    assert found is not None
    assert found._emit_lag_s == expected_lag_s
