"""Seeded-bug end-to-end gates for the dnzlint v2 passes.

Each test copies the REAL engine tree, plants exactly one bug of the
class its pass exists to catch, and runs the FULL gate (``run_all``
with the committed registries, baseline, and pragmas) — proving the
pass catches its target class at tree scale AND that no suppression
channel (pragma, baseline, guards.toml, replaypaths.toml) can mask a
fresh instance.  The committed tree itself must stay clean, so the
seeded finding is asserted to be the ONLY new one.

These are the acceptance tests for the v2 tentpole: an unguarded
coordinator counter (DNZ-G), a wall-clock read smuggled into the
snapshot encoder (DNZ-D), and a snapshot field dropped from the
restore path (DNZ-S).
"""

import shutil
import sys
import textwrap
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.dnzlint import run_all  # noqa: E402

ENGINE = REPO / "denormalized_tpu"


def _copy_engine(tmp_path: Path) -> Path:
    """The copy keeps the package name — baseline and registry keys are
    ``denormalized_tpu/...`` paths, so the full gate applies unchanged."""
    dst = tmp_path / "denormalized_tpu"
    shutil.copytree(
        ENGINE, dst, ignore=shutil.ignore_patterns("__pycache__")
    )
    return dst


def _seeded_new(root: Path) -> list:
    new, _suppressed, stale = run_all(root)
    assert stale == [], f"seed invalidated baseline entries: {stale}"
    return new


def _patch(path: Path, old: str, new: str) -> None:
    """Anchored one-occurrence patch: drift in the anchored source line
    fails here, loudly, instead of silently seeding nothing."""
    text = path.read_text()
    assert text.count(old) == 1, (
        f"seed anchor {old!r} occurs {text.count(old)}x in {path.name} — "
        f"update the seeded-bug test to the moved/renamed code"
    )
    path.write_text(text.replace(old, new))


def test_seeded_unguarded_coordinator_counter_is_caught(tmp_path):
    """DNZ-G e2e: a coordinator whose counter is written under its lock
    on one path and bare on another — the exact shape of the races
    fixed in the v2 triage (exchange replay flag, shared-pipeline
    membership, doctor profiler counter)."""
    root = _copy_engine(tmp_path)
    (root / "runtime" / "seeded_coord.py").write_text(textwrap.dedent("""\
        import threading


        class SeededCoordinator:
            def __init__(self):
                self._lock = threading.Lock()
                self._inflight = 0

            def start(self):
                with self._lock:
                    self._inflight += 1

            def finish(self):
                self._inflight -= 1
        """))
    new = _seeded_new(root)
    assert [
        (f.rule, f.symbol) for f in new
    ] == [("DNZ-G001", "SeededCoordinator.finish")], \
        [f.render() for f in new]
    (f,) = new
    assert "write of self._inflight" in f.message
    assert "SeededCoordinator._lock" in f.message


def test_seeded_clock_read_in_snapshot_encoder_is_caught(tmp_path):
    """DNZ-D e2e: ``time.time()`` smuggled into ``pack_snapshot`` — the
    codec every operator snapshot funnels through, registered directly
    in replaypaths.toml, so the impurity scan hits it as a root."""
    root = _copy_engine(tmp_path)
    ser = root / "state" / "serialization.py"
    _patch(ser, "import struct", "import struct\nimport time")
    _patch(
        ser,
        "    entries = []",
        "    meta = dict(meta, packed_at=time.time())\n    entries = []",
    )
    new = _seeded_new(root)
    assert [(f.rule, f.symbol) for f in new] == \
        [("DNZ-D001", "pack_snapshot")], [f.render() for f in new]
    (f,) = new
    assert "time.time" in f.message
    assert f.path == "denormalized_tpu/state/serialization.py"


def test_seeded_snapshot_restore_asymmetry_is_caught(tmp_path):
    """DNZ-S e2e, both drift directions on the real window operator: a
    payload field the restore never reads (state silently dropped), and
    a restore read renamed away from what any snapshot writes (KeyError
    on every real snapshot)."""
    root = _copy_engine(tmp_path)
    we = root / "physical" / "window_exec.py"
    # direction 1: write a field no restore path reads
    _patch(
        we,
        '"max_win_seen": self._max_win_seen,',
        '"max_win_seen": self._max_win_seen,\n            "resume_salt": 0,',
    )
    # direction 2: strict-read a key no snapshot path writes
    _patch(
        we,
        'self._first_open = meta["first_open"]',
        'self._first_open = meta["first_open_v2"]',
    )
    new = _seeded_new(root)
    got = sorted((f.rule, f.symbol) for f in new)
    assert got == [
        ("DNZ-S001", "StreamingWindowExec._restore"),
        ("DNZ-S001", "StreamingWindowExec._snapshot"),
    ], [f.render() for f in new]
    by_symbol = {f.symbol: f.message for f in new}
    assert "'resume_salt'" in by_symbol["StreamingWindowExec._snapshot"]
    assert "no restore path reads it" \
        in by_symbol["StreamingWindowExec._snapshot"]
    assert "'first_open_v2'" in by_symbol["StreamingWindowExec._restore"]
    assert "KeyError" in by_symbol["StreamingWindowExec._restore"]
