"""Job factories for the cluster runtime tests (and the cluster soak).

Imported BY WORKER PROCESSES via ClusterSpec.job ("cluster_jobs:<fn>"
with sys_path pointing at tests/), so everything here must be
module-level and deterministic from job_args alone — the N workers and
the single-process oracle all rebuild the identical source.

Values are small integers (stored in float64 columns) so every
aggregate (count/sum/min/max/avg) is EXACT in the engine's f32
accumulators regardless of exchange arrival order — the property the
byte-identical cluster-vs-oracle comparisons lean on (docs/cluster.md
#determinism)."""

from __future__ import annotations

import time

import numpy as np

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.sources.base import (
    PartitionReader,
    Source,
    attach_canonical_timestamp,
    canonicalize_schema,
)

T0 = 1_700_000_000_000

SCHEMA = Schema([
    Field("k", DataType.STRING, nullable=False),
    Field("v", DataType.FLOAT64, nullable=False),
    Field("ts", DataType.TIMESTAMP_MS, nullable=False),
])


def partition_arrays(part: int, args: dict):
    """Deterministic batches for one partition: in-order timestamps,
    string keys spread over the key space, integer-valued readings.

    With ``skew_divisor`` set, partition 0's event time advances that
    many times slower — its early windows stay open (the min-watermark
    stalls on it), so a small ``state_budget_bytes`` forces the window
    tier to spill the deferred prefix (the PR-9 skew-span case), which
    is how the spilled-rescale test gets spilled state AT the cut."""
    n_batches = int(args.get("batches", 12))
    rows = int(args.get("rows", 64))
    keys = int(args.get("keys", 13))
    span_ms = int(args.get("batch_span_ms", 250))
    skew_div = int(args.get("skew_divisor", 1) or 1)
    out = []
    for b in range(n_batches):
        base = T0 + b * span_ms
        if part == 0 and skew_div > 1:
            base = T0 + (b * span_ms) // skew_div
        i = np.arange(rows, dtype=np.int64)
        ts = base + (i * span_ms) // rows
        kid = (i * 7 + part * 3 + b) % keys
        k = np.array([f"s{x:04d}" for x in kid], dtype=object)
        v = ((i + part + b) % 16).astype(np.float64)
        out.append((ts, k, v))
    return out


class _PacedReader(PartitionReader):
    def __init__(self, part: int, args: dict) -> None:
        self._arrays = partition_arrays(part, args)
        self._pos = 0
        self._pace_s = float(args.get("pace_s", 0.0))
        if part == 0 and args.get("pace_skew_s") is not None:
            self._pace_s = float(args["pace_skew_s"])
        # optional mid-stream silence for partition 0: batches keep
        # NOT arriving while its watermark contribution pins the min —
        # the spill test's way of holding a deferred window prefix cold
        # (and untouched) across several barriers
        self._pause_after = (
            int(args["p0_pause_after"])
            if part == 0 and args.get("p0_pause_after") is not None
            else None
        )
        self._pause_s = float(args.get("p0_pause_s", 0.0))

    def read(self, timeout_s=None):
        if self._pos >= len(self._arrays):
            return None
        if self._pause_after is not None and self._pos == self._pause_after:
            self._pause_after = None  # once, not on replay re-reads
            time.sleep(self._pause_s)
        if self._pace_s:
            time.sleep(self._pace_s)
        ts, k, v = self._arrays[self._pos]
        self._pos += 1
        batch = RecordBatch(SCHEMA, [k, v, ts.astype(np.int64)])
        return attach_canonical_timestamp(batch, "ts", fallback_ms=0)

    def offset_snapshot(self) -> dict:
        return {"pos": self._pos}

    def offset_restore(self, snap: dict) -> None:
        self._pos = int(snap.get("pos", 0))


class PacedMemorySource(Source):
    """Replayable, seekable, optionally paced synthetic source."""

    def __init__(self, args: dict) -> None:
        self._args = dict(args)
        self.name = "cluster_synth"
        self._schema = canonicalize_schema(SCHEMA)

    @property
    def schema(self) -> Schema:
        return self._schema

    @property
    def unbounded(self) -> bool:
        # "unbounded" routes multi-partition workers through the
        # threaded prefetch pump (barrier polls stay responsive while a
        # slow reader sleeps); the readers still finish, and the pump
        # converts all-readers-done into EOS
        return bool(self._args.get("unbounded", False))

    def partitions(self) -> list[PartitionReader]:
        return [
            _PacedReader(p, self._args)
            for p in range(int(self._args.get("partitions", 4)))
        ]


def make_source(args: dict) -> PacedMemorySource:
    return PacedMemorySource(args)


def apply_pipeline(ds, args: dict):
    from denormalized_tpu import col
    from denormalized_tpu.api import functions as F

    return ds.window(
        [col("k")],
        [
            F.count(col("v")).alias("count"),
            F.sum(col("v")).alias("total"),
            F.min(col("v")).alias("lo"),
            F.max(col("v")).alias("hi"),
            F.avg(col("v")).alias("mean"),
        ],
        int(args.get("window_ms", 1000)),
    )


def windowed_job(args: dict) -> dict:
    return {
        "source": make_source(args),
        "pipeline": lambda ds: apply_pipeline(ds, args),
        "engine": args.get("engine") or {},
    }


def oracle_rows(args: dict) -> list[tuple]:
    """Single-process oracle: run the identical query in-process and
    return canonical row tuples (sorted)."""
    from denormalized_tpu.api.context import Context, EngineConfig
    from denormalized_tpu.common.constants import (
        WINDOW_END_COLUMN,
        WINDOW_START_COLUMN,
    )

    config = EngineConfig()
    for k, v in (args.get("engine") or {}).items():
        # oracle ignores cluster-only knobs that need a store
        if k in ("state_budget_bytes",):
            continue
        config.set(k, v)
    config.partition_watermarks = True
    ctx = Context(config)
    ds = apply_pipeline(ctx.from_source(make_source(args)), args)
    got = ds.collect()
    rows = []
    for i in range(got.num_rows):
        rows.append(canonical_row({
            "k": str(got.column("k")[i]),
            "count": int(got.column("count")[i]),
            "total": float(got.column("total")[i]),
            "lo": float(got.column("lo")[i]),
            "hi": float(got.column("hi")[i]),
            "mean": float(got.column("mean")[i]),
            WINDOW_START_COLUMN: int(got.column(WINDOW_START_COLUMN)[i]),
            WINDOW_END_COLUMN: int(got.column(WINDOW_END_COLUMN)[i]),
        }))
    return sorted(rows)


def canonical_row(rec: dict) -> tuple:
    """One emission row → canonical comparable tuple (drops the epoch
    tag; field order fixed)."""
    from denormalized_tpu.common.constants import (
        WINDOW_END_COLUMN,
        WINDOW_START_COLUMN,
    )

    return (
        int(rec[WINDOW_START_COLUMN]),
        int(rec[WINDOW_END_COLUMN]),
        str(rec["k"]),
        int(rec["count"]),
        float(rec["total"]),
        float(rec["lo"]),
        float(rec["hi"]),
        float(rec["mean"]),
    )
