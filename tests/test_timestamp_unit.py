"""timestamp_unit on sources (VERDICT-r4 missing #3).

The reference's source config declares the event-time column's unit
(kafka_config.rs:42); without it a seconds- or microseconds-resolution
topic silently mis-windows by 1000x.  All sources normalize to the
canonical epoch-ms column at ingest.
"""

import json
import threading
import time

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.errors import SourceError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.sources.base import normalize_ts_to_ms, validate_ts_unit
from denormalized_tpu.sources.kafka import KafkaTopicBuilder
from denormalized_tpu.sources.memory import MemorySource
from denormalized_tpu.testing.mock_kafka import MockKafkaBroker


# -- unit conversion ------------------------------------------------------


def test_normalize_units():
    ts = np.array([1_700_000_000, 1_700_000_001], np.int64)
    np.testing.assert_array_equal(
        normalize_ts_to_ms(ts, "s"), ts * 1000)
    np.testing.assert_array_equal(
        normalize_ts_to_ms(ts * 1000, "ms"), ts * 1000)
    np.testing.assert_array_equal(
        normalize_ts_to_ms(ts * 1_000_000, "us"), ts * 1000)
    np.testing.assert_array_equal(
        normalize_ts_to_ms(ts * 1_000_000_000, "ns"), ts * 1000)
    # spelling variants
    assert validate_ts_unit("Seconds") == "s"
    assert validate_ts_unit("microseconds") == "us"
    assert validate_ts_unit(None) == "ms"


def test_float_seconds_keep_subsecond_part():
    # a float-seconds column (time.time() style) must not truncate to
    # whole seconds before scaling
    ts = np.array([1_700_000_000.25, 1_700_000_000.75])
    np.testing.assert_array_equal(
        normalize_ts_to_ms(ts, "s"),
        np.array([1_700_000_000_250, 1_700_000_000_750], np.int64),
    )


def test_unknown_unit_raises_at_build_time():
    with pytest.raises(SourceError, match="timestamp_unit"):
        validate_ts_unit("fortnights")
    with pytest.raises(SourceError, match="timestamp_unit"):
        MemorySource.from_batches(
            [_batch_s([1.0], ["a"], [1.0])],
            timestamp_column="ts",
            timestamp_unit="fortnights",
        )
    with pytest.raises(SourceError, match="timestamp_unit"):
        KafkaTopicBuilder("localhost:9092").with_option(
            "timestamp_unit", "fortnights")


# -- windowing on a seconds-unit source ----------------------------------

SCHEMA_S = Schema([
    Field("ts", DataType.FLOAT64, nullable=False),
    Field("k", DataType.STRING, nullable=False),
    Field("v", DataType.FLOAT64),
])
T0_S = 1_700_000_000  # epoch seconds


def _batch_s(ts, ks, vs):
    return RecordBatch(
        SCHEMA_S,
        [np.asarray(ts, np.float64), np.asarray(ks, object),
         np.asarray(vs, np.float64)],
    )


def test_memory_source_seconds_unit_windows():
    """1s tumbling windows over a seconds-resolution source: each whole
    second's events land in exactly one window keyed at second*1000 ms."""
    batches = [
        _batch_s([T0_S + 0.1, T0_S + 0.6, T0_S + 1.2], ["a", "a", "a"],
                 [1.0, 2.0, 3.0]),
        _batch_s([T0_S + 2.4, T0_S + 3.5], ["a", "a"], [4.0, 5.0]),
        _batch_s([T0_S + 6.0], ["a"], [6.0]),
    ]
    out = (
        Context()
        .from_source(MemorySource.from_batches(
            batches, timestamp_column="ts", timestamp_unit="s"))
        .window(["k"], [F.count(col("v")).alias("n"),
                        F.sum(col("v")).alias("s")], 1000)
        .collect()
    )
    got = {}
    for i in range(out.num_rows):
        got[int(out.column("window_start_time")[i])] = (
            int(out.column("n")[i]), float(out.column("s")[i]))
    base = T0_S * 1000
    assert got[base] == (2, 3.0)          # +0.1s, +0.6s
    assert got[base + 1000] == (1, 3.0)   # +1.2s
    assert got[base + 2000] == (1, 4.0)
    assert got[base + 3000] == (1, 5.0)
    assert got[base + 6000] == (1, 6.0)
    # WITHOUT the unit the same feed mis-windows: seconds read as ms all
    # collapse near epoch-0 — guard that the fix is actually load-bearing
    out2 = (
        Context()
        .from_source(MemorySource.from_batches(
            batches, timestamp_column="ts"))
        .window(["k"], [F.count(col("v")).alias("n")], 1000)
        .collect()
    )
    starts = {int(out2.column("window_start_time")[i])
              for i in range(out2.num_rows)}
    assert not (starts & set(got)), (starts, set(got))


def test_kafka_topic_seconds_unit_windows():
    """End-to-end: a topic whose payload carries float epoch-SECONDS event
    time windows correctly under with_option('timestamp_unit', 's')
    (the reference inherits this via config passthrough)."""
    b = MockKafkaBroker().start()
    try:
        b.create_topic("secs", partitions=1)

        def feed():
            for chunk in range(5):
                msgs = [
                    json.dumps({
                        "occurred_at": T0_S + chunk + i / 50.0,
                        "sensor": "s0",
                        "reading": 1.0,
                    }).encode()
                    for i in range(50)
                ]
                b.produce("secs", 0, msgs, ts_ms=T0_S * 1000)
                time.sleep(0.15)

        threading.Thread(target=feed, daemon=True).start()
        ctx = Context(EngineConfig(source_idle_timeout_ms=400))
        sample = json.dumps(
            {"occurred_at": 1.5, "sensor": "a", "reading": 1.0})
        ds = ctx.from_topic(
            "secs",
            sample_json=sample,
            bootstrap_servers=b.bootstrap,
            timestamp_column="occurred_at",
            timestamp_unit="s",
        ).window(["sensor"], [F.count(col("reading")).alias("n")], 1000)
        got = {}
        stop_at = time.time() + 20
        for batch in ds.stream():
            for i in range(batch.num_rows):
                got[int(batch.column("window_start_time")[i])] = int(
                    batch.column("n")[i])
            if len(got) >= 3 or time.time() > stop_at:
                break
        base = T0_S * 1000
        assert len(got) >= 3
        for w, n in got.items():
            assert (w - base) % 1000 == 0 and 0 <= (w - base) < 5000, w
            assert n == 50, (w, n)  # each second carries exactly 50 events
    finally:
        b.stop()
