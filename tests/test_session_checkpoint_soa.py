"""Checkpoint round-trip for the SoA session store: snapshot mid-stream,
restore in a fresh process-equivalent context, and require the union of
pre-kill and post-restore emissions to be BYTE-IDENTICAL (exact float
equality, not approx) to an uninterrupted run.

Workload: the tools/soak.py session pipeline config (sensor keys,
count/min/max/avg, 300ms gap, 600ms-burst/400ms-silence event time) at a
higher rate — ~10x the soak smoke's rows per burst — so the snapshot lands
mid-session with real open state: multiple keys, Chan moment columns, and
an interner worth of gids to rebuild.
"""

import numpy as np

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.logical import plan as lp
from denormalized_tpu.physical.base import EndOfStream, Marker
from denormalized_tpu.physical.simple_execs import CollectSink
from denormalized_tpu.runtime import executor
from denormalized_tpu.sources.memory import MemorySource
from denormalized_tpu.state.checkpoint import wire_checkpointing
from denormalized_tpu.state.lsm import close_global_state_backend
from denormalized_tpu.state.orchestrator import Orchestrator

SESSION_GAP_MS = 300
T0 = 1_700_000_000_000

SCHEMA = Schema(
    [
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ]
)


def _burst_ts(ts):
    """tools/soak.py burst_ts: squeeze each second's events into its first
    600ms — the 400ms silence (> gap) closes one session per key/second."""
    sec = (ts // 1000) * 1000
    return sec + ((ts - sec) * 3) // 5


def _batches(n_batches=14, rows=400, n_keys=7, seed=11):
    rng = np.random.default_rng(seed)
    out = []
    ms_per_batch = 250
    for b in range(n_batches):
        base = T0 + b * ms_per_batch
        ts = np.sort(_burst_ts(base + rng.integers(0, ms_per_batch, rows)))
        ks = np.asarray(
            [f"sensor_{i}" for i in rng.integers(0, n_keys, rows)], object
        )
        vs = rng.normal(50.0, 10.0, rows)
        out.append(RecordBatch(SCHEMA, [ts, ks, vs]))
    return out


def _pipeline(ctx, batches):
    return ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts"),
        name="soa_ckpt",
    ).session_window(
        ["k"],
        [
            F.count(col("v")).alias("count"),
            F.min(col("v")).alias("min"),
            F.max(col("v")).alias("max"),
            F.avg(col("v")).alias("average"),
            F.stddev(col("v")).alias("sd"),
        ],
        SESSION_GAP_MS,
    )


def _rows_of(batch):
    out = {}
    for i in range(batch.num_rows):
        key = (
            batch.column("k")[i],
            int(batch.column("window_start_time")[i]),
            int(batch.column("window_end_time")[i]),
        )
        out[key] = (
            int(batch.column("count")[i]),
            float(batch.column("min")[i]),
            float(batch.column("max")[i]),
            float(batch.column("average")[i]),
            float(batch.column("sd")[i]),
        )
    return out


def test_soa_session_store_kill_restore_byte_identical(tmp_path):
    batches = _batches()

    golden = {}
    for item in _pipeline(Context(), batches).stream():
        golden.update(_rows_of(item))

    def make_cfg(path):
        return EngineConfig(
            checkpoint=True, checkpoint_interval_s=9999, state_backend_path=path
        )

    state_dir = str(tmp_path / "state")
    try:
        # run A: process a few emissions, snapshot MID-SESSION, stop hard
        ctx_a = Context(make_cfg(state_dir))
        root_a = executor.build_physical(
            lp.Sink(_pipeline(ctx_a, batches)._plan, CollectSink()), ctx_a
        )
        orch_a = Orchestrator(interval_s=9999)
        coord_a = wire_checkpointing(root_a, ctx_a, orch_a)
        emitted_a = {}
        items_seen = 0
        it = root_a.run()
        for item in it:
            if isinstance(item, RecordBatch):
                emitted_a.update(_rows_of(item))
            if items_seen == 2:
                orch_a.trigger_now()
            if isinstance(item, Marker):
                coord_a.commit(item.epoch)
                break
            items_seen += 1
        it.close()
        close_global_state_backend()

        # run B: restore from the snapshot, run to completion
        ctx_b = Context(make_cfg(state_dir))
        root_b = executor.build_physical(
            lp.Sink(_pipeline(ctx_b, batches)._plan, CollectSink()), ctx_b
        )
        orch_b = Orchestrator(interval_s=9999)
        coord_b = wire_checkpointing(root_b, ctx_b, orch_b)
        assert coord_b.committed_epoch is not None
        emitted_b = {}
        for item in root_b.run():
            if isinstance(item, RecordBatch):
                emitted_b.update(_rows_of(item))
            if isinstance(item, EndOfStream):
                break
    finally:
        close_global_state_backend()

    combined = dict(emitted_a)
    combined.update(emitted_b)
    assert set(combined) == set(golden), {
        "extra": sorted(set(combined) - set(golden))[:4],
        "missing": sorted(set(golden) - set(combined))[:4],
    }
    for key in golden:
        # byte-identical: the snapshot stores exact f64 components (JSON
        # repr round-trips doubles exactly), the merge order after restore
        # matches the uninterrupted run, so every float must be EQUAL
        assert combined[key] == golden[key], (key, combined[key], golden[key])


def test_soa_snapshot_interoperates_with_reference(tmp_path, monkeypatch):
    """The SoA store writes the SAME JSON snapshot schema the dict-era
    operator wrote: a snapshot taken by the vectorized operator restores
    into the reference operator (and vice versa) with identical emissions.
    Pins the format so checkpoints survive engine upgrades in both
    directions."""
    batches = _batches(n_batches=14, rows=120, n_keys=4, seed=3)

    golden = {}
    for item in _pipeline(Context(), batches).stream():
        golden.update(_rows_of(item))

    def run_with(env_for_a, env_for_b, path):
        def make_cfg():
            return EngineConfig(
                checkpoint=True,
                checkpoint_interval_s=9999,
                state_backend_path=path,
            )

        if env_for_a:
            monkeypatch.setenv("DENORMALIZED_SESSION_REFERENCE", "1")
        else:
            monkeypatch.delenv("DENORMALIZED_SESSION_REFERENCE", raising=False)
        ctx_a = Context(make_cfg())
        root_a = executor.build_physical(
            lp.Sink(_pipeline(ctx_a, batches)._plan, CollectSink()), ctx_a
        )
        orch_a = Orchestrator(interval_s=9999)
        coord_a = wire_checkpointing(root_a, ctx_a, orch_a)
        emitted = {}
        items_seen = 0
        it = root_a.run()
        for item in it:
            if isinstance(item, RecordBatch):
                emitted.update(_rows_of(item))
            if items_seen == 0:
                orch_a.trigger_now()
            if isinstance(item, Marker):
                coord_a.commit(item.epoch)
                break
            items_seen += 1
        it.close()
        close_global_state_backend()

        if env_for_b:
            monkeypatch.setenv("DENORMALIZED_SESSION_REFERENCE", "1")
        else:
            monkeypatch.delenv("DENORMALIZED_SESSION_REFERENCE", raising=False)
        ctx_b = Context(make_cfg())
        root_b = executor.build_physical(
            lp.Sink(_pipeline(ctx_b, batches)._plan, CollectSink()), ctx_b
        )
        orch_b = Orchestrator(interval_s=9999)
        coord_b = wire_checkpointing(root_b, ctx_b, orch_b)
        assert coord_b.committed_epoch is not None
        for item in root_b.run():
            if isinstance(item, RecordBatch):
                emitted.update(_rows_of(item))
            if isinstance(item, EndOfStream):
                break
        close_global_state_backend()
        return emitted

    def check(got):
        # cross-OPERATOR resume cannot be bit-exact (the two engines fold
        # floats in different orders); the format-compat bar is: same
        # sessions, exact count/min/max/bounds, avg/sd to 1e-12 relative
        assert set(got) == set(golden)
        for k in golden:
            gc, gmn, gmx, gav, gsd = got[k]
            wc, wmn, wmx, wav, wsd = golden[k]
            assert (gc, gmn, gmx) == (wc, wmn, wmx), k
            assert abs(gav - wav) <= 1e-12 * max(1.0, abs(wav)), k
            assert abs(gsd - wsd) <= 1e-9 * max(1.0, abs(wsd)), k

    try:
        # vectorized writes → reference restores
        check(run_with(False, True, str(tmp_path / "s1")))
        # reference writes → vectorized restores
        check(run_with(True, False, str(tmp_path / "s2")))
    finally:
        close_global_state_backend()
