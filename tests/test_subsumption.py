"""Predicate-subsumption edge cases (planner/predicates.py + sharing).

The conservative implication checker decides which query joins a
shared ingest with a residual re-filter — a FALSE positive here is a
correctness bug (rows the joiner wants would be missing from the
shared ingest), so every edge lives under test:

- boundary-touching ranges and strictness (``v >= 5 ⇒ v > 4`` but
  NOT ``v > 5``... and so on);
- IN-lists vs equality vs intervals (finite sets nest into intervals);
- NaN literals are opaque (``v > nan`` constrains nothing and must
  never share structurally);
- NaN/null DATA rows: a constrained conjunct rejects them on both
  sides, so a shared run with residual re-filters stays differentially
  identical to independent oracles even with nulls in the filter
  column;
- the negative pin: non-implied predicates never share.
"""

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.planner import predicates as pr
from denormalized_tpu.planner.sharing import detect_sharing
from denormalized_tpu.runtime.multi_query import run_queries
from denormalized_tpu.sources.memory import MemorySource

SCHEMA = Schema(
    [
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ]
)
T0 = 1_700_000_000_000


def _implies(p_expr, q_expr) -> bool:
    return pr.implies(pr.analyze([p_expr]), pr.analyze([q_expr]))


# -- interval boundaries -------------------------------------------------


def test_range_strictness_boundaries():
    v = col("v")
    assert _implies(v > 5.0, v > 4.0)
    assert _implies(v >= 5.0, v > 4.0)
    assert _implies(v > 5.0, v >= 5.0)
    assert _implies(v >= 5.0, v >= 5.0)
    # the boundary row v == 5 satisfies >= 5 but not > 5
    assert not _implies(v >= 5.0, v > 5.0)
    assert not _implies(v > 4.0, v > 5.0)
    assert not _implies(v > 4.0, v >= 5.0)
    # upper bounds, mirrored
    assert _implies(v < 4.0, v < 5.0)
    assert _implies(v < 5.0, v <= 5.0)
    assert not _implies(v <= 5.0, v < 5.0)
    # two-sided nesting
    both_tight = (v > 2.0) & (v < 3.0)
    both_loose = (v > 1.0) & (v < 4.0)
    assert _implies(both_tight, both_loose)
    assert not _implies(both_loose, both_tight)
    # conjunct ordering is irrelevant
    assert _implies((v < 3.0) & (v > 2.0), both_loose)


def test_equality_and_in_list_nesting():
    k, v = col("k"), col("v")
    assert _implies(k == "a", F.in_list(k, ["a", "b"]))
    assert not _implies(F.in_list(k, ["a", "b"]), k == "a")
    assert _implies(
        F.in_list(k, ["a"]),
        F.in_list(k, ["a", "b"]),
    )
    # a finite numeric set nests into a covering interval...
    assert _implies(F.in_list(v, [2.0, 3.0]), v > 1.0)
    assert _implies(v == 2.0, v >= 2.0)
    # ...but not when one member leaks out (boundary: 1.0 fails > 1.0)
    assert not _implies(F.in_list(v, [1.0, 2.0]), v > 1.0)
    # an interval never implies a finite set
    assert not _implies(v > 1.0, F.in_list(v, [2.0, 3.0]))


def test_unconstrained_and_unrelated_columns():
    k, v = col("k"), col("v")
    # anything implies the empty predicate; the converse does not hold
    assert pr.implies(pr.analyze([v > 0.0]), pr.analyze([]))
    assert not pr.implies(pr.analyze([]), pr.analyze([v > 0.0]))
    # a bound on one column says nothing about another
    assert not _implies(v > 5.0, k == "a")
    # extra constrained columns on the stronger side are fine
    assert _implies((v > 5.0) & (k == "a"), v > 0.0)


def test_nan_literal_is_opaque():
    v = col("v")
    nan = float("nan")
    # v > nan is the empty predicate; treating it as an interval would
    # "prove" it implies anything — it must stay opaque instead
    cons = pr.analyze([v > nan])
    assert "v" not in cons.intervals and cons.opaque
    assert not _implies(v > nan, v > 0.0)
    assert not _implies(v > 0.0, v > nan)
    # identical opaque conjuncts still match by repr
    assert _implies(v > nan, v > nan)
    cons_in = pr.analyze([F.in_list(v, [nan, 1.0])])
    assert "v" not in cons_in.sets and cons_in.opaque


def test_opaque_conjuncts_match_by_repr_only():
    k, v = col("k"), col("v")
    disj = (v > 5.0) | (k == "a")
    assert _implies(disj, disj)
    assert not _implies(disj, (v > 5.0) | (k == "b"))
    # opaque+constrained mix: P needs Q's opaque verbatim
    assert _implies(pr.conjoin([disj, v > 5.0]), disj)
    assert not _implies(v > 5.0, disj)  # would need OR reasoning


# -- sharing-pass integration -------------------------------------------


AGGS = [F.count(col("v")).alias("c"), F.sum(col("v")).alias("s")]


def _plans(batches, filters, L=3000, S=1000):
    ctx = Context(EngineConfig())
    base = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts"),
        name="feed",
    )
    out = []
    for flt in filters:
        ds = base if flt is None else base.filter(flt)
        out.append(ds.window(["k"], AGGS, L, S)._plan)
    return out


def _batches(seed=41, n_batches=12, rows=300, null_frac=0.0, nan_frac=0.0):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = np.sort(T0 + b * 1000 + rng.integers(0, 1000, rows))
        ks = np.asarray([f"s{i}" for i in rng.integers(0, 5, rows)], object)
        vs = rng.normal(10.0, 4.0, rows)
        if nan_frac:
            vs[rng.random(rows) < nan_frac] = np.nan
        if null_frac:
            vs = vs.astype(object)
            vs[rng.random(rows) < null_frac] = None
            vs = np.asarray(vs, object)
        out.append(RecordBatch(SCHEMA, [ts, ks, vs]))
    return out


def test_sharing_pass_boundary_negative_pin():
    """v > 5 and v >= 5 share — but only by REBASING onto the weaker
    >= 5 side (ingesting under > 5 would drop the boundary rows);
    incomparable ranges never share."""
    batches = _batches(n_batches=4)
    v = col("v")
    groups = detect_sharing(_plans(batches, [v > 5.0, v >= 5.0]))
    shared = [g for g in groups if g.shared]
    assert len(shared) == 1 and shared[0].members == [0, 1]
    # the strict > 5 member re-filters; the >= 5 member IS the base
    assert shared[0].filters[0] is not None
    assert shared[0].filters[1] is None
    # disjoint ranges: neither implies the other, no group
    groups = detect_sharing(_plans(batches, [v > 5.0, v < 5.0]))
    assert all(len(g.members) == 1 for g in groups)
    groups = detect_sharing(_plans(batches, [v > 4.0, v >= 5.0]))
    assert [g.members for g in groups if g.shared] == [[0, 1]]


def test_sharing_pass_widens_base_to_weakest_member():
    """Arrival order must not matter: when the weaker predicate shows
    up AFTER a stronger one, the group re-bases onto it."""
    batches = _batches(n_batches=4)
    v = col("v")
    groups = detect_sharing(_plans(batches, [v > 5.0, v > 1.0, v > 3.0]))
    shared = [g for g in groups if g.shared]
    assert len(shared) == 1 and shared[0].members == [0, 1, 2]
    g = shared[0]
    # base = the v > 1 member: its residual is None, the others re-filter
    assert g.filters[1] is None
    assert g.filters[0] is not None and g.filters[2] is not None


@pytest.mark.parametrize("null_frac,nan_frac", [(0.0, 0.0), (0.15, 0.1)])
def test_shared_residuals_differential_vs_oracles(null_frac, nan_frac):
    """The end-to-end differential: a subsumption group with residual
    re-filters emits byte-identically to per-query independent oracles
    — including NaN and null rows in the filter column, which every
    constrained predicate rejects on both sides."""
    batches = _batches(
        seed=43, n_batches=14, null_frac=null_frac, nan_frac=nan_frac
    )
    v, k = col("v"), col("k")
    filters = [
        v > 6.0,
        (v > 8.0) & (v < 14.0),
        F.in_list(k, ["s0", "s1"]) & (v > 9.0),
    ]
    # every member implies the weakest (v > 6) predicate — including
    # the k-in-list member, whose extra key constraint only narrows —
    # so all three ride one ingest with per-member residuals
    plans = _plans(batches, filters)
    groups = detect_sharing(plans)
    shared = [g for g in groups if g.shared]
    assert len(shared) == 1 and shared[0].members == [0, 1, 2]
    assert shared[0].filters[0] is None  # v > 6 IS the base

    ctx = Context(EngineConfig())
    base = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts"),
        name="feed",
    )
    outs = [dict() for _ in filters]

    def rows_of(b, acc):
        for i in range(b.num_rows):
            key = (
                b.column("k")[i],
                int(b.column("window_start_time")[i]),
            )
            acc[key] = (
                float(b.column("c")[i]),
                float(b.column("s")[i]),
            )

    queries = [
        (
            base.filter(flt).window(["k"], AGGS, 3000, 1000),
            (lambda acc: (lambda b: rows_of(b, acc)))(outs[i]),
        )
        for i, flt in enumerate(filters)
    ]
    report = run_queries(ctx, queries)
    assert report["shared_queries"] == 3

    for i, flt in enumerate(filters):
        # oracle pins the shared group's slice unit AND, for RESIDUAL
        # members only, the lexsort fold lane their class store forces
        # (the base member folds through the default dense lane)
        octx = Context(
            EngineConfig(
                slice_windows=True,
                slice_unit_ms=1000,
                slice_sort_lane=(i != 0),
            )
        )
        ods = octx.from_source(
            MemorySource.from_batches(batches, timestamp_column="ts"),
            name="feed",
        ).filter(flt).window(["k"], AGGS, 3000, 1000)
        oracle = {}
        for b in ods.stream():
            rows_of(b, oracle)
        assert outs[i] == oracle, f"query {i} diverged from its oracle"


def test_subsumption_off_config_restores_exact_match_sharing():
    batches = _batches(n_batches=4)
    v = col("v")
    plans = _plans(batches, [v > 0.0, v > 1.0])
    assert [g.members for g in detect_sharing(plans) if g.shared] == [[0, 1]]
    off = detect_sharing(plans, subsumption=False)
    assert all(not g.shared for g in off)
    # identical predicates still share with subsumption off
    same = _plans(batches, [v > 1.0, v > 1.0])
    assert [
        g.members for g in detect_sharing(same, subsumption=False) if g.shared
    ] == [[0, 1]]
