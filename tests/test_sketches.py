"""Sketch-native approximate aggregates (ops/sketches.py).

Property tests for the mergeable sketch kernels — HLL error bounds
across many seeds, Space-Saving count bounds on zipf traffic, quantile
rank error against the sketch's self-reported bound, merge
associativity / fold-order invariance, stable-hash canonicalization —
plus engine differentials of the slice-native path against the exact
accumulator path and byte-identical store snapshot/restore."""

import math

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.ops import sketches as skx
from denormalized_tpu.sources.memory import MemorySource

# -- stable hashing ------------------------------------------------------


def test_stable_hash_canonicalizes_floats():
    a = skx.stable_hash64(np.asarray([0.0, np.nan, 1.5]))
    b = skx.stable_hash64(np.asarray([-0.0, np.float64("nan"), 1.5]))
    assert np.array_equal(a, b)
    assert len(set(a.tolist())) == 3  # distinct values stay distinct


def test_stable_hash_int_identity_beyond_f53():
    # 2^53 and 2^53+1 collapse under a float64 round-trip; the int lane
    # must keep them distinct
    big = np.asarray([2**53, 2**53 + 1], dtype=np.int64)
    h = skx.stable_hash64(big)
    assert h[0] != h[1]
    # int dtypes of the same value hash identically
    assert skx.stable_hash64(np.asarray([7], dtype=np.int32))[0] == (
        skx.stable_hash64(np.asarray([7], dtype=np.int64))[0]
    )


def test_stable_hash_objects_blake2b_and_validity():
    vals = np.asarray(["a", "b", "a", None], dtype=object)
    valid = np.asarray([True, True, True, False])
    h = skx.stable_hash64(vals, valid)
    assert h[0] == h[2] != h[1]
    assert h[3] == 0  # invalid rows hash to the masked placeholder
    assert h[0] == np.uint64(skx.blake2b64("a"))


def test_bit_length_exact_full_range():
    xs = np.asarray(
        [0, 1, 2, 3, 2**31, 2**52 - 1, 2**53 + 1, 2**63, 2**64 - 1],
        dtype=np.uint64,
    )
    got = skx.u64_bit_length(xs).astype(np.int64)
    want = np.asarray([int(x).bit_length() for x in xs.tolist()])
    assert np.array_equal(got, want)


# -- HLL -----------------------------------------------------------------


def _hll_estimate_for(values, p=skx.HLL_P):
    plane = np.zeros((1, 1 << p), dtype=np.int8)
    skx.hll_accumulate(
        plane,
        np.zeros(len(values), dtype=np.int64),
        skx.stable_hash64(values),
    )
    return int(skx.hll_estimate(plane)[0]), plane


def test_hll_error_bound_across_seeds():
    # documented bound: standard error 1.04/sqrt(2^p) ≈ 1.63% at p=12;
    # assert 4 sigma on every committed seed (deterministic: the hash
    # is never salted, so these can never flake)
    bound = 4 * 1.04 / math.sqrt(1 << skx.HLL_P)
    for seed in range(12):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(200, 60_000))
        vals = rng.choice(n * 13, size=n, replace=False).astype(np.int64)
        est, _ = _hll_estimate_for(vals)
        assert abs(est - n) <= max(3, bound * n), (seed, n, est)


def test_hll_fold_order_and_split_invariance():
    rng = np.random.default_rng(42)
    vals = rng.integers(0, 10_000, 30_000).astype(np.int64)
    whole, plane_all = _hll_estimate_for(vals)
    parts = []
    for chunk in np.array_split(vals, 3):
        _, p = _hll_estimate_for(chunk)
        parts.append(p)
    ab_c = np.maximum(np.maximum(parts[0], parts[1]), parts[2])
    c_ba = np.maximum(parts[2], np.maximum(parts[1], parts[0]))
    assert np.array_equal(ab_c, c_ba)  # fold-order invariant
    assert np.array_equal(ab_c, plane_all)  # split invariant
    assert int(skx.hll_estimate(ab_c.reshape(1, -1))[0]) == whole


def test_hll_class_matches_plane_kernel():
    rng = np.random.default_rng(3)
    g = rng.integers(0, 5000, 20_000)
    h = skx.Hll(p=12)
    h.update(g)
    est = h.estimate()
    assert abs(est - 5000) <= 0.07 * 5000
    # p below 12 is now legal (exact bit_length lifted the float limit)
    h2 = skx.Hll(p=8)
    h2.update(g)
    assert abs(h2.estimate() - 5000) <= 0.35 * 5000


# -- Space-Saving / top-k ------------------------------------------------


def _zipf_gids(rng, n, nkeys, a=1.3):
    g = rng.zipf(a, n)
    return np.minimum(g, nkeys) - 1


def test_space_saving_bounds_on_zipf():
    rng = np.random.default_rng(17)
    g = _zipf_gids(rng, 50_000, 500)
    true = np.bincount(g, minlength=500)
    ss = skx.SpaceSaving(64)
    for chunk in np.array_split(g, 20):
        ss.update(chunk)
    keys, counts, errs = ss.top(64)
    assert len(keys)
    for k, c, e in zip(keys.tolist(), counts.tolist(), errs.tolist()):
        assert c - e <= true[k] <= c, (k, c, e, true[k])


def test_topk_merge_preserves_bounds():
    rng = np.random.default_rng(23)
    spec = skx.TopKSpec("sk0", 0, k=8)
    cap = 4
    slots = []
    g_all = np.zeros(0, dtype=np.int64)
    v_all = np.zeros(0, dtype=np.int64)
    for _u in range(3):
        g = np.sort(rng.integers(0, cap, 9000))
        v = _zipf_gids(rng, 9000, 800)
        slot = spec.init_planes(cap)
        spec.accumulate_unit(
            slot, cap, g, v, np.ones(len(g), dtype=bool)
        )
        slots.append(slot)
        g_all = np.concatenate((g_all, g))
        v_all = np.concatenate((v_all, v))
    folded = spec.fold(slots, cap)
    ka = folded["sk0|k"]
    ca = folded["sk0|c"]
    ea = folded["sk0|e"]
    for gi in range(cap):
        mask = g_all == gi
        true = np.bincount(v_all[mask], minlength=800)
        vids, cnts, errs = spec.cell_top(ka[gi], ca[gi], ea[gi])
        assert len(vids)
        for v, c, e in zip(vids.tolist(), cnts.tolist(), errs.tolist()):
            assert c - e <= true[v] <= c, (gi, v, c, e, true[v])
        # the genuinely heaviest key must be reported first: its true
        # count exceeds every bound-adjusted competitor at this skew
        assert true[vids[0]] == true.max()


def test_topk_merge_with_empty_side_is_identity():
    spec = skx.TopKSpec("sk0", 0, k=4)
    a = spec.init_planes(2)
    g = np.asarray([0, 0, 0, 1, 1], dtype=np.int64)
    v = np.asarray([5, 5, 9, 7, 7], dtype=np.int64)
    spec.accumulate_unit(a, 2, g, v, np.ones(5, dtype=bool))
    empty = spec.init_planes(2)
    ko, co, eo = skx.topk_merge(
        a["sk0|k"], a["sk0|c"], a["sk0|e"],
        empty["sk0|k"], empty["sk0|c"], empty["sk0|e"],
    )
    vids, cnts, errs = spec.cell_top(ko[0], co[0], eo[0])
    assert vids.tolist() == [5, 9] and cnts.tolist() == [2, 1]
    assert errs.tolist() == [0, 0]
    vids, cnts, _ = spec.cell_top(ko[1], co[1], eo[1])
    assert vids.tolist() == [7] and cnts.tolist() == [2]


# -- KLL quantiles -------------------------------------------------------


def test_kll_exact_below_level_capacity():
    rng = np.random.default_rng(5)
    vals = rng.normal(0, 100, skx.KLL_K - 3)
    spec = skx.KllSpec("sk0", 0)
    slot = spec.init_planes(1)
    spec.accumulate_unit(
        slot, 1, np.zeros(len(vals), dtype=np.int64), vals,
        np.ones(len(vals), dtype=bool),
    )
    assert int(slot["sk0|err"][0]) == 0  # no compaction fired
    for q in (0.0, 0.1, 0.5, 0.9, 1.0):
        got = spec.finalize_quantile(slot, np.asarray([0]), q)[0]
        want = np.percentile(vals, q * 100, method="lower")
        assert got == want, (q, got, want)


def test_kll_rank_error_within_self_reported_bound():
    rng = np.random.default_rng(11)
    n = 60_000
    vals = rng.normal(50, 20, n)
    spec = skx.KllSpec("sk0", 0)
    slots = []
    for chunk in np.array_split(vals, 7):
        slot = spec.init_planes(1)
        spec.accumulate_unit(
            slot, 1, np.zeros(len(chunk), dtype=np.int64), chunk,
            np.ones(len(chunk), dtype=bool),
        )
        slots.append(slot)
    folded = spec.fold(slots, 1)
    err = int(folded["sk0|err"][0])
    assert 0 < err <= n * math.log2(n / skx.KLL_K) / skx.KLL_K * 2
    s = np.sort(vals)
    for q in (0.05, 0.25, 0.5, 0.75, 0.95):
        got = spec.finalize_quantile(folded, np.asarray([0]), q)[0]
        # rank error: where the reported value actually sits vs target
        rank = int(np.searchsorted(s, got, side="left"))
        target = q * (n - 1)
        assert abs(rank - target) <= err + 1, (q, rank, target, err)


def test_kll_fold_deterministic():
    rng = np.random.default_rng(29)
    vals = rng.normal(0, 1, 5000)
    spec = skx.KllSpec("sk0", 0)

    def build():
        slots = []
        for chunk in np.array_split(vals, 4):
            slot = spec.init_planes(1)
            spec.accumulate_unit(
                slot, 1, np.zeros(len(chunk), dtype=np.int64), chunk,
                np.ones(len(chunk), dtype=bool),
            )
            slots.append(slot)
        return spec.fold(slots, 1)

    a, b = build(), build()
    assert set(a) == set(b)
    for k in a:
        assert np.array_equal(a[k], b[k], equal_nan=True), k


# -- slice store: snapshot/restore byte identity -------------------------


def test_store_sketch_snapshot_restore_byte_identical():
    from denormalized_tpu.ops.segment_agg import components_for
    from denormalized_tpu.ops.slice_store import SliceStore

    rng = np.random.default_rng(37)
    specs = [("sum", 0), ("sketch", 1, None)]
    hll = skx.HllSpec("sk0", 1)
    kll = skx.KllSpec("sk1", 0)
    comps = components_for(specs)

    def feed(store, rounds):
        for r in range(rounds):
            n = 800
            units = np.sort(rng.integers(r, r + 3, n))
            gids = rng.integers(0, 6, n).astype(np.int64)
            values = rng.normal(10, 3, (n, 2))
            valid = np.ones((n, 2), dtype=bool)
            hashes = skx.stable_hash64(
                rng.integers(0, 4000, n).astype(np.int64)
            )
            key = units.astype(np.int64) * 16 + gids
            order = np.argsort(key, kind="stable")
            store.accumulate(
                units, gids, values, valid, 6,
                order=order, aux={1: hashes},
            )

    rng_state = rng.bit_generator.state
    a = SliceStore(comps, 1000, sketches=(hll, kll))
    feed(a, 4)
    snap = a.snapshot_arrays(6)
    b = SliceStore(comps, 1000, sketches=(hll, kll))
    b.restore_arrays(
        {k: v.copy() for k, v in snap.items()}, 6
    )
    # keep feeding BOTH the same stream — restored state must be
    # byte-equivalent, including dynamically allocated quantile levels
    rng.bit_generator.state = rng_state
    feed(a, 2)
    rng.bit_generator.state = rng_state
    feed(b, 2)
    fa = a.fold(0, 10)
    fb = b.fold(0, 10)
    assert set(fa) == set(fb)
    for k in fa:
        assert np.array_equal(fa[k], fb[k], equal_nan=True), k
    assert a.sketch_nbytes() == b.sketch_nbytes()


# -- engine differentials ------------------------------------------------

SCHEMA = Schema(
    [
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ]
)
T0 = 1_700_000_000_000


def _batches(seed=7, n_batches=12, rows=500, n_vals=400, null_frac=0.0):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = np.sort(T0 + b * 1000 + rng.integers(0, 1000, rows))
        ks = np.asarray(
            [f"s{i}" for i in rng.integers(0, 2, rows)], object
        )
        vs = rng.integers(0, n_vals, rows).astype(np.float64)
        if null_frac:
            vs = vs.astype(object)
            vs[rng.random(rows) < null_frac] = None
            vs = np.asarray(vs, object)
        out.append(RecordBatch(SCHEMA, [ts, ks, vs]))
    return out


APPROX_AGGS = [
    F.approx_distinct(col("v")).alias("nd"),
    F.approx_median(col("v")).alias("med"),
    F.approx_percentile_cont(col("v"), 0.9).alias("p90"),
    F.approx_top_k(col("v"), 3).alias("top"),
    F.sum(col("v")).alias("s"),
]


def _run(batches, cfg, aggs=APPROX_AGGS, L=2000, S=1000):
    ctx = Context(cfg)
    ds = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts"),
        name="feed",
    ).window(["k"], aggs, L, S)
    out = {}
    for b in ds.stream():
        for i in range(b.num_rows):
            key = (
                b.column("k")[i],
                int(b.column("window_start_time")[i]),
            )
            row = []
            for a in aggs:
                c = b.column(a.name)[i]
                row.append(
                    tuple(tuple(p) for p in c)
                    if isinstance(c, list)
                    else float(c)
                )
            out[key] = tuple(row)
    return out


def test_native_path_tracks_exact_path_within_bounds():
    batches = _batches()
    native = _run(
        batches, EngineConfig(slice_windows=True, slice_unit_ms=1000)
    )
    exact = _run(batches, EngineConfig())
    assert set(native) == set(exact)
    for key in native:
        nd_n, med_n, p90_n, top_n, s_n = native[key]
        nd_e, med_e, p90_e, top_e, s_e = exact[key]
        assert abs(nd_n - nd_e) <= max(4, 0.066 * nd_e), (key, nd_n, nd_e)
        assert abs(med_n - med_e) <= 0.05 * 400, key
        assert abs(p90_n - p90_e) <= 0.05 * 400, key
        assert 0 < len(top_n) <= 3
        assert s_n == s_e  # exact aggregate rides along untouched


def test_native_path_handles_nulls():
    # unmasked None values (object-dtype float column) must not crash
    # the hash lane, and must hash like the exact accumulator does
    # (blake2b of the None value itself)
    batches = _batches(seed=9, null_frac=0.25)
    native = _run(
        batches, EngineConfig(slice_windows=True, slice_unit_ms=1000),
        aggs=APPROX_AGGS[:1],
    )
    exact = _run(batches, EngineConfig(), aggs=APPROX_AGGS[:1])
    assert set(native) == set(exact)
    for key in native:
        (nd_n,) = native[key]
        (nd_e,) = exact[key]
        assert abs(nd_n - nd_e) <= max(4, 0.066 * nd_e)


def test_native_path_deterministic_bit_exact():
    batches = _batches(seed=13)
    cfg = lambda: EngineConfig(slice_windows=True, slice_unit_ms=1000)  # noqa: E731
    a = _run(batches, cfg())
    b = _run(batches, cfg())
    assert a == b  # exact equality including sketch estimates


def test_approx_native_false_lowers_to_accumulators():
    # the A/B control: same config except approx_native — the lowered
    # path must agree exactly with the default (UDAF) path
    batches = _batches(seed=15)
    lowered = _run(
        batches,
        EngineConfig(
            slice_windows=True, slice_unit_ms=1000, approx_native=False
        ),
    )
    exact = _run(batches, EngineConfig())
    assert lowered == exact


def test_approx_on_strings_native():
    rng = np.random.default_rng(21)
    batches = []
    for b in range(8):
        rows = 400
        ts = np.sort(T0 + b * 1000 + rng.integers(0, 1000, rows))
        ks = np.asarray(
            [f"s{i}" for i in rng.integers(0, 2, rows)], object
        )
        vs = np.asarray(
            [f"u{i}" for i in rng.integers(0, 300, rows)], object
        )
        batches.append(
            RecordBatch(
                Schema(
                    [
                        Field("ts", DataType.INT64, nullable=False),
                        Field("k", DataType.STRING, nullable=False),
                        Field("v", DataType.STRING),
                    ]
                ),
                [ts, ks, vs],
            )
        )
    aggs = [
        F.approx_distinct(col("v")).alias("nd"),
        F.approx_top_k(col("v"), 2).alias("top"),
    ]
    ctx = Context(EngineConfig(slice_windows=True, slice_unit_ms=1000))
    ds = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts"),
        name="feed",
    ).window(["k"], aggs, 2000, 1000)
    seen = 0
    for b in ds.stream():
        for i in range(b.num_rows):
            seen += 1
            nd = int(b.column("nd")[i])
            top = b.column("top")[i]
            assert 0 < nd <= 330
            assert all(
                isinstance(v, str) and v.startswith("u") for v, _c in top
            )
    assert seen


def test_sketch_state_constant_in_cardinality():
    # the tentpole property: sketch planes do not grow with distinct
    # values — same group count, 100x cardinality, same sketch bytes
    from denormalized_tpu.physical.slice_exec import (
        SliceSubscriber,
        SliceWindowExec,
    )
    from denormalized_tpu.physical.simple_execs import SourceExec

    def bytes_for(n_vals):
        batches = _batches(seed=3, n_vals=n_vals)
        src = SourceExec(
            MemorySource.from_batches(batches, timestamp_column="ts")
        )
        op = SliceWindowExec(
            src,
            [col("k")],
            [SliceSubscriber(list(APPROX_AGGS), 2000, 1000)],
            unit_ms=1000,
        )
        for _ in op.run():
            pass
        return op.state_info()["sketch_bytes"]

    assert bytes_for(40) == bytes_for(4000)
