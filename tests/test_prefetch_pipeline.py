"""Pipelined multi-core ingest: prefetch-path correctness and the
GIL-release property it depends on.

The prefetch engine (``denormalized_tpu/runtime/prefetch.py``) gives
every partition a worker thread that owns its own ``KafkaClient`` and
runs fetch → native decode → assembly off the consumer thread.  That
only scales because the ctypes foreign calls drop the GIL for their
native portion — pinned here — and it is only CORRECT if batches,
offsets, and watermarks come out equivalent to a serial drive of the
same readers, and if a restore discards in-flight prefetched batches
instead of replaying them.
"""

import ctypes
import json
import threading
import time

import numpy as np
import pytest

from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.physical.base import Marker, WatermarkHint
from denormalized_tpu.physical.simple_execs import SourceExec
from denormalized_tpu.sources.kafka import KafkaClient, KafkaTopicBuilder
from denormalized_tpu.testing.mock_kafka import MockKafkaBroker

T0 = 1_700_000_000_000
SAMPLE = '{"ts": 1, "p": 1, "i": 1, "v": 1.0}'


@pytest.fixture
def broker():
    b = MockKafkaBroker().start()
    try:
        yield b
    finally:
        b.stop()


def _produce_chunk(broker, topic, part, chunk_idx, rows, n_parts):
    payloads = []
    for r in range(rows):
        i = chunk_idx * rows + r
        ts = T0 + (chunk_idx * rows + r) * 7
        payloads.append(
            json.dumps(
                {"ts": ts, "p": part, "i": i, "v": float(i % 13)}
            ).encode()
        )
    broker.produce_batched(topic, part, payloads, ts_ms=T0)


def _source(broker, topic, **opts):
    b = (
        KafkaTopicBuilder(broker.bootstrap)
        .with_topic(topic)
        .infer_schema_from_json(SAMPLE)
        .with_timestamp_column("ts")
    )
    for k, v in opts.items():
        b = b.with_option(k, v)
    return b.build_reader()


# -- GIL audit --------------------------------------------------------------


def test_native_libs_loaded_gil_releasing():
    """The whole pipelining premise: every native library is loaded via
    ``ctypes.CDLL`` (releases the GIL around each foreign call), never
    ``ctypes.PyDLL`` (holds it).  A regression here would silently
    serialize every worker again."""
    from denormalized_tpu.native.build import load

    lib = load("kafka_client", ["-lz"])
    assert isinstance(lib, ctypes.CDLL)
    assert not isinstance(lib, ctypes.PyDLL)
    for name in ("json_parser", "interner"):
        lib = load(name)
        assert isinstance(lib, ctypes.CDLL) and not isinstance(
            lib, ctypes.PyDLL
        ), name


def test_blocking_fetch_releases_gil(broker):
    """Two clients long-poll an EMPTY topic concurrently.  The broker
    honors max_wait before answering an empty fetch, so each call blocks
    ~0.5s inside the native client; if ctypes held the GIL the two calls
    would serialize to ~1.0s+.  Concurrent wall time must stay well
    under the serial sum — even on one core, because the block is a
    socket wait, not CPU."""
    broker.create_topic("gil", partitions=2)
    clients = [KafkaClient(broker.bootstrap) for _ in range(2)]
    try:
        # warm up connections/metadata outside the timed section
        for p, c in enumerate(clients):
            c.fetch("gil", p, 0, max_wait_ms=1)

        def one(p):
            clients[p].fetch("gil", p, 0, max_wait_ms=500)

        t0 = time.perf_counter()
        threads = [
            threading.Thread(target=one, args=(p,)) for p in range(2)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        assert wall < 0.85, (
            f"two concurrent 0.5s blocking fetches took {wall:.2f}s — "
            "the native fetch is not releasing the GIL"
        )
    finally:
        for c in clients:
            c.close()


# -- equivalence with the serial path ---------------------------------------


N_PARTS = 3
CHUNK_ROWS = 200
N_CHUNKS = 12
TOTAL = N_PARTS * CHUNK_ROWS * N_CHUNKS


def _feed(broker, topic, delay_s=0.015):
    for j in range(N_CHUNKS):
        for p in range(N_PARTS):
            _produce_chunk(broker, topic, p, j, CHUNK_ROWS, N_PARTS)
        time.sleep(delay_s)


def _drain_serial(src):
    """Ground truth: drive fresh readers one at a time on this thread."""
    per_part = {p: [] for p in range(N_PARTS)}
    readers = src.partitions()
    for r in readers:
        while sum(len(v) for v in per_part.values()) < TOTAL:
            b = r.read(timeout_s=0.05)
            if b is None or not b.num_rows:
                if b is not None and not b.num_rows and r.caught_up():
                    break
                continue
            p = int(np.asarray(b.column("p"))[0])
            per_part[p].extend(np.asarray(b.column("i")).tolist())
    snaps = [r.offset_snapshot() for r in readers]
    return per_part, snaps


def test_staggered_prefetch_matches_serial(broker):
    """N partitions with staggered per-fetch broker latency through the
    full prefetch path: rows, per-partition order, final offsets, and
    partition-watermark monotonicity must match a serial drive of the
    same topic."""
    topic = "stag"
    broker.create_topic(topic, partitions=N_PARTS)
    for p in range(N_PARTS):
        # stagger service times so partitions genuinely interleave
        broker.fetch_delay_s[(topic, p)] = 0.005 * (p + 1)
    feeder = threading.Thread(
        target=_feed, args=(broker, topic), daemon=True
    )
    feeder.start()

    src = _source(broker, topic)
    exec_ = SourceExec(src, idle_timeout_ms=400, partition_watermarks=True)
    per_part = {p: [] for p in range(N_PARTS)}
    hint_max = None
    violations = []
    gen = exec_.run()
    deadline = time.monotonic() + 60
    for item in gen:
        if time.monotonic() > deadline:
            pytest.fail(
                f"prefetch drain stalled: "
                f"{sum(len(v) for v in per_part.values())}/{TOTAL} rows"
            )
        if isinstance(item, WatermarkHint):
            if item.kind == "partition" and not item.is_announcement:
                hint_max = max(hint_max or 0, item.ts_ms)
            continue
        if isinstance(item, RecordBatch) and item.num_rows:
            ts = np.asarray(
                item.column(CANONICAL_TIMESTAMP_COLUMN), dtype=np.int64
            )
            if hint_max is not None and int(ts.min()) < hint_max:
                violations.append((int(ts.min()), hint_max))
            p = int(np.asarray(item.column("p"))[0])
            per_part[p].extend(np.asarray(item.column("i")).tolist())
            if sum(len(v) for v in per_part.values()) >= TOTAL:
                # one more step so the generator runs the post-yield
                # bookkeeping (offset snapshot) for the final batch
                next(gen)
                break
    yielded = [dict(s) for s in exec_._yielded_offsets]
    gen.close()
    feeder.join()

    serial_parts, serial_snaps = _drain_serial(_source(broker, topic))
    n_rows = CHUNK_ROWS * N_CHUNKS
    for p in range(N_PARTS):
        assert per_part[p] == list(range(n_rows)), (
            f"partition {p}: prefetch rows diverge "
            f"(got {len(per_part[p])}, dupes="
            f"{len(per_part[p]) - len(set(per_part[p]))})"
        )
        assert serial_parts[p] == per_part[p]
    # offsets the barrier would persist == the serial cursors
    assert sorted(yielded, key=lambda s: s["partition"]) == sorted(
        serial_snaps, key=lambda s: s["partition"]
    )
    # a partition hint must never run ahead of rows still being yielded
    assert not violations, f"watermark ran ahead of data: {violations[:3]}"


# -- restore vs in-flight prefetch ------------------------------------------


def test_restore_mid_prefetch_replays_no_row_twice(broker):
    """Kill/restore semantics at the exact hazard the prefetch engine
    introduces: batches fetched and buffered PAST the last barrier's
    offsets are in flight when the stream dies.  A restore from that
    barrier must yield exactly the complement of what was consumed
    before it — nothing lost, nothing twice — because restore happens
    before workers spawn and the restored reader discards pending
    slices."""
    topic = "restore"
    broker.create_topic(topic, partitions=2)
    n_rows = 4000
    for p in range(2):
        _produce_chunk(broker, topic, p, 0, n_rows, 2)
        broker.fetch_delay_s[(topic, p)] = 0.002 * (p + 1)

    # small decode units force many in-flight batches around the barrier
    src = _source(broker, topic, **{"max.batch.rows": "256",
                                    "fetch.coalesce.rows": "0"})
    exec_ = SourceExec(src, idle_timeout_ms=None,
                       partition_watermarks=False)
    marker_every = [0]

    def barrier_poll():
        marker_every[0] += 1
        if marker_every[0] % 5 == 0:
            return marker_every[0] // 5
        return None

    exec_.set_barrier_source(barrier_poll)
    seen_pre = {0: [], 1: []}
    snap_at_marker = None
    seen_at_marker = {0: 0, 1: 0}
    gen = exec_.run()
    deadline = time.monotonic() + 60
    for item in gen:
        assert time.monotonic() < deadline, "pre-restore drive stalled"
        if isinstance(item, Marker):
            snap_at_marker = [dict(s) for s in exec_._yielded_offsets]
            seen_at_marker = {p: len(v) for p, v in seen_pre.items()}
            continue
        if isinstance(item, RecordBatch) and item.num_rows:
            p = int(np.asarray(item.column("p"))[0])
            seen_pre[p].extend(np.asarray(item.column("i")).tolist())
            total = sum(len(v) for v in seen_pre.values())
            if snap_at_marker is not None and total >= 5000:
                break  # die mid-stream, prefetch buffers non-empty
    gen.close()
    assert snap_at_marker is not None, "no barrier landed before the kill"
    # roll the consumed-set back to the barrier cut: everything after the
    # marker is "lost output" the restore must regenerate
    pre_marker = {
        p: seen_pre[p][: seen_at_marker[p]] for p in (0, 1)
    }

    # restored process: fresh readers, seek to the barrier's offsets —
    # this is what SourceExec._restore_offsets does before spawning
    # prefetch workers
    readers = src.partitions()
    by_part = {r._partition: r for r in readers}
    for s in snap_at_marker:
        by_part[s["partition"]].offset_restore(s)
    post = {0: [], 1: []}
    for p, r in by_part.items():
        deadline = time.monotonic() + 30
        while len(post[p]) < n_rows - len(pre_marker[p]):
            assert time.monotonic() < deadline, "post-restore read stalled"
            b = r.read(timeout_s=0.05)
            if b is not None and b.num_rows:
                post[p].extend(np.asarray(b.column("i")).tolist())

    for p in (0, 1):
        got = pre_marker[p] + post[p]
        assert got == list(range(n_rows)), (
            f"partition {p}: restore replayed or lost rows "
            f"(pre={len(pre_marker[p])}, post={len(post[p])}, "
            f"dupes={len(got) - len(set(got))})"
        )


# -- coalescing -------------------------------------------------------------


def _drain_counting(reader, n):
    rows = []
    batches = 0
    deadline = time.monotonic() + 30
    while len(rows) < n:
        assert time.monotonic() < deadline, "read stalled"
        b = reader.read(timeout_s=0.05)
        if b is not None and b.num_rows:
            rows.extend(np.asarray(b.column("i")).tolist())
            batches += 1
    return rows, batches


def test_fetch_coalescing_combines_small_fetches(broker):
    """Small fetches (clamped broker serve size) with backlog at the
    broker must coalesce into larger decode units — identical rows, same
    final offset, several-fold fewer rowful batches than the uncoalesced
    read of the same topic."""
    topic = "coal"
    broker.create_topic(topic, partitions=1)
    n = 600
    payloads = [
        json.dumps({"ts": T0 + i, "p": 0, "i": i, "v": 1.0}).encode()
        for i in range(n)
    ]
    broker.produce_batched(topic, 0, payloads, ts_ms=T0,
                           records_per_batch=4)
    # ~4 records per fetch: the small-arena shape of a slow link or a
    # tiny-batch producer
    broker.fetch_max_bytes_clamp = 256

    plain = _source(broker, topic, **{"fetch.coalesce.rows": "0"})
    (reader0,) = plain.partitions()
    rows0, batches0 = _drain_counting(reader0, n)
    assert rows0 == list(range(n))

    src = _source(broker, topic, **{"fetch.coalesce.rows": "512"})
    (reader,) = src.partitions()
    rows, batches = _drain_counting(reader, n)
    assert rows == list(range(n))
    assert reader.offset_snapshot()["offset"] == n
    assert reader.caught_up() is True
    assert batches * 3 <= batches0, (
        f"coalescing produced {batches} decode units vs {batches0} "
        "uncoalesced — expected a several-fold reduction"
    )


def test_coalescing_preserves_split_offsets(broker):
    """Coalesced decode units still split at max.batch.rows with EXACT
    per-record kafka offsets: a barrier between slices checkpoints a
    cursor that a restore can seek to without loss or replay."""
    topic = "coalsplit"
    broker.create_topic(topic, partitions=1)
    n = 900
    payloads = [
        json.dumps({"ts": T0 + i, "p": 0, "i": i, "v": 1.0}).encode()
        for i in range(n)
    ]
    broker.produce_batched(topic, 0, payloads, ts_ms=T0,
                           records_per_batch=64)
    # ~64 records per fetch, so the 900-row decode unit is stitched from
    # many fetches — the combined per-record offsets must stay exact
    broker.fetch_max_bytes_clamp = 3000
    src = _source(broker, topic, **{
        "fetch.coalesce.rows": "4096", "max.batch.rows": "128",
    })
    (reader,) = src.partitions()
    rows = []
    deadline = time.monotonic() + 30
    while len(rows) < n:
        assert time.monotonic() < deadline, "split read stalled"
        b = reader.read(timeout_s=0.05)
        if b is None or not b.num_rows:
            continue
        assert b.num_rows <= 128
        rows.extend(np.asarray(b.column("i")).tolist())
        # the snapshot after each slice must equal the count of rows
        # yielded so far — the exact offset a restore would seek to
        assert reader.offset_snapshot()["offset"] == len(rows)
    assert rows == list(range(n))
