"""Slice-store checkpoint/restore: one epoch snapshot, per-query
emission cursors.

The acceptance scenario: a shared pipeline serving 3 subscriber
queries at different fold cadences is killed MID-EPOCH (progress past
the last committed cut is lost), restored, and driven to completion —
the union of pre-kill and post-restore emissions must be
BYTE-IDENTICAL per query to 3 independent, uninterrupted pipelines.
Plus the negative pin: an unshareable query in the batch falls back to
an independent plan and still completes."""

import numpy as np

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.physical.base import EndOfStream, Marker
from denormalized_tpu.physical.slice_exec import SubscriberBatch
from denormalized_tpu.planner.sharing import detect_sharing
from denormalized_tpu.runtime.multi_query import build_shared_root, run_queries
from denormalized_tpu.sources.memory import MemorySource
from denormalized_tpu.state.checkpoint import wire_checkpointing
from denormalized_tpu.state.lsm import close_global_state_backend
from denormalized_tpu.state.orchestrator import Orchestrator

SCHEMA = Schema(
    [
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ]
)
T0 = 1_700_000_000_000

AGGS = [
    F.count(col("v")).alias("c"),
    F.sum(col("v")).alias("s"),
    F.min(col("v")).alias("mn"),
    F.max(col("v")).alias("mx"),
    F.avg(col("v")).alias("av"),
    F.stddev(col("v")).alias("sd"),
]
AGG_COLS = ("c", "s", "mn", "mx", "av", "sd")
#: three different fold cadences over one gcd slice (500ms)
SPECS = [(3000, 1000), (4000, 2000), (1000, 500)]


def _batches(seed=5, n_batches=24, rows=300, n_keys=5):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = np.sort(T0 + b * 500 + rng.integers(0, 500, rows))
        ks = np.asarray(
            [f"s{i}" for i in rng.integers(0, n_keys, rows)], object
        )
        vs = rng.normal(10.0, 3.0, rows)
        out.append(RecordBatch(SCHEMA, [ts, ks, vs]))
    return out


def _rows_of(batch, acc):
    for i in range(batch.num_rows):
        key = (
            batch.column("k")[i],
            int(batch.column("window_start_time")[i]),
            int(batch.column("window_end_time")[i]),
        )
        acc[key] = tuple(float(batch.column(c)[i]) for c in AGG_COLS)


def _shared_root(ctx, batches):
    base = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts"),
        name="feed",
    )
    plans = [base.window(["k"], AGGS, L, S)._plan for (L, S) in SPECS]
    groups = detect_sharing(plans)
    assert len(groups) == 1 and groups[0].shared
    return build_shared_root(ctx, groups[0])


def test_shared_kill_restore_byte_identical_to_independent(tmp_path):
    batches = _batches()

    # 3 independent, uninterrupted oracle pipelines — same slice kernel,
    # pinned to the SHARED group's gcd slice (500ms): the fold grouping
    # is part of the numeric contract, and byte-identity is only defined
    # against an oracle folding the same slices (docs/multi_query.md)
    oracles = []
    for L, S in SPECS:
        ctx = Context(EngineConfig(slice_windows=True, slice_unit_ms=500))
        ds = ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="ts"),
            name="feed",
        ).window(["k"], AGGS, L, S)
        out = {}
        for b in ds.stream():
            _rows_of(b, out)
        oracles.append(out)
    assert all(len(o) for o in oracles)

    state_dir = str(tmp_path / "state")

    def make_cfg():
        return EngineConfig(
            checkpoint=True,
            checkpoint_interval_s=9999,
            state_backend_path=state_dir,
        )

    got = [dict() for _ in SPECS]
    try:
        # run A: commit ONE epoch, keep emitting past it (mid-epoch
        # progress the kill loses), then stop hard
        ctx_a = Context(make_cfg())
        root_a = _shared_root(ctx_a, batches)
        orch_a = Orchestrator(interval_s=9999)
        coord_a = wire_checkpointing(root_a, ctx_a, orch_a)
        emissions = 0
        committed = False
        post_commit = 0
        it = root_a.run()
        for item in it:
            if isinstance(item, SubscriberBatch):
                _rows_of(item.batch, got[item.tag])
                emissions += 1
                if committed:
                    post_commit += 1
                    if post_commit >= 9:
                        break  # hard kill mid-epoch: progress uncommitted
            if emissions == 8 and not committed:
                orch_a.trigger_now()
            if isinstance(item, Marker):
                coord_a.commit(item.epoch)
                committed = True
        it.close()
        assert committed and post_commit >= 9
        close_global_state_backend()

        # run B: restore from the committed cut, drive to completion —
        # windows emitted between the cut and the kill re-emit with
        # byte-identical values (the dict union dedupes them)
        ctx_b = Context(make_cfg())
        root_b = _shared_root(ctx_b, batches)
        orch_b = Orchestrator(interval_s=9999)
        coord_b = wire_checkpointing(root_b, ctx_b, orch_b)
        assert coord_b.committed_epoch is not None
        for item in root_b.run():
            if isinstance(item, SubscriberBatch):
                _rows_of(item.batch, got[item.tag])
            if isinstance(item, EndOfStream):
                break
    finally:
        close_global_state_backend()

    for q in range(len(SPECS)):
        assert set(got[q]) == set(oracles[q]), {
            "query": q,
            "missing": sorted(set(oracles[q]) - set(got[q]))[:4],
            "extra": sorted(set(got[q]) - set(oracles[q]))[:4],
        }
        for k in oracles[q]:
            # byte-identical: exact float equality, not approx — the
            # snapshot stores the exact f64 slice partials and the fold
            # order after restore matches the uninterrupted run
            assert got[q][k] == oracles[q][k], (q, k)


def test_snapshot_carries_per_query_cursors(tmp_path):
    """One snapshot, N emission cursors: after a restore each
    subscriber resumes at ITS OWN next window, not a shared one."""
    batches = _batches(seed=9, n_batches=16)
    state_dir = str(tmp_path / "state")

    def make_cfg():
        return EngineConfig(
            checkpoint=True,
            checkpoint_interval_s=9999,
            state_backend_path=state_dir,
        )

    try:
        ctx_a = Context(make_cfg())
        root_a = _shared_root(ctx_a, batches)
        orch_a = Orchestrator(interval_s=9999)
        coord_a = wire_checkpointing(root_a, ctx_a, orch_a)
        emissions = 0
        it = root_a.run()
        for item in it:
            if isinstance(item, SubscriberBatch):
                emissions += 1
            if emissions == 10:
                orch_a.trigger_now()
                emissions += 1
            if isinstance(item, Marker):
                coord_a.commit(item.epoch)
                break
        cursors_a = list(root_a._next_win)
        it.close()
        close_global_state_backend()

        ctx_b = Context(make_cfg())
        root_b = _shared_root(ctx_b, batches)
        orch_b = Orchestrator(interval_s=9999)
        wire_checkpointing(root_b, ctx_b, orch_b)
        assert root_b._next_win == cursors_a
        # three cadences → three DIFFERENT cursor positions in ms
        starts = [
            nw * SPECS[q][1] for q, nw in enumerate(root_b._next_win)
        ]
        assert len(set(starts)) > 1
    finally:
        close_global_state_backend()


def test_unshareable_query_negative_falls_back(tmp_path):
    """The planner-fallback pin: a session query co-registered with two
    shareable window queries runs independently (the report says so)
    and every query still completes."""
    batches = _batches(seed=12, n_batches=12)
    ctx = Context(EngineConfig())
    base = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts"),
        name="feed",
    )
    a, b, c = {}, {}, []
    queries = [
        (base.window(["k"], AGGS, 3000, 1000), lambda x: _rows_of(x, a)),
        (base.window(["k"], AGGS, 2000, 1000), lambda x: _rows_of(x, b)),
        (
            base.session_window(["k"], [F.count(col("v")).alias("c")], 400),
            lambda x: c.append(x.num_rows),
        ),
    ]
    report = run_queries(ctx, queries)
    shared_groups = [g for g in report["groups"] if g["shared"]]
    fallback = [g for g in report["groups"] if not g["shared"]]
    assert len(shared_groups) == 1
    assert shared_groups[0]["members"] == [0, 1]
    assert len(fallback) == 1 and fallback[0]["members"] == [2]
    assert "session" in fallback[0]["reason"]
    assert a and b and sum(c) > 0


# -- approximate aggregates across kill/restore (ISSUE 18) ----------------

APPROX_AGGS = [
    F.approx_distinct(col("v")).alias("nd"),
    F.approx_median(col("v")).alias("med"),
    F.approx_top_k(col("v"), 3).alias("top"),
    F.sum(col("v")).alias("s"),
]
APPROX_COLS = ("nd", "med", "top", "s")


def _approx_batches(seed=7, n_batches=24, rows=300, n_keys=4):
    # integer-valued v so approx_top_k sees real repeats
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = np.sort(T0 + b * 500 + rng.integers(0, 500, rows))
        ks = np.asarray(
            [f"s{i}" for i in rng.integers(0, n_keys, rows)], object
        )
        vs = rng.integers(0, 50, rows).astype(np.float64)
        out.append(RecordBatch(SCHEMA, [ts, ks, vs]))
    return out


def _rows_of_approx(batch, acc):
    for i in range(batch.num_rows):
        key = (
            batch.column("k")[i],
            int(batch.column("window_start_time")[i]),
            int(batch.column("window_end_time")[i]),
        )
        row = []
        for c in APPROX_COLS:
            v = batch.column(c)[i]
            row.append(
                tuple(tuple(p) for p in v)
                if isinstance(v, list)
                else float(v)
            )
        acc[key] = tuple(row)


def _approx_shared_root(ctx, batches):
    base = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts"),
        name="feed",
    )
    plans = [
        base.window(["k"], APPROX_AGGS, L, S)._plan for (L, S) in SPECS
    ]
    groups = detect_sharing(plans)
    assert len(groups) == 1 and groups[0].shared
    return build_shared_root(ctx, groups[0])


def test_approx_kill_restore_byte_identical(tmp_path):
    """Sketch planes across a mid-window kill: HLL registers, KLL
    compactor levels (dynamically allocated labels), Space-Saving
    planes AND the value-id interner all ride the epoch snapshot, so
    the union of pre-kill and post-restore emissions is byte-identical
    per query to uninterrupted oracles — sketch estimates included."""
    batches = _approx_batches()

    oracles = []
    for L, S in SPECS:
        ctx = Context(EngineConfig(slice_windows=True, slice_unit_ms=500))
        ds = ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="ts"),
            name="feed",
        ).window(["k"], APPROX_AGGS, L, S)
        out = {}
        for b in ds.stream():
            _rows_of_approx(b, out)
        oracles.append(out)
    assert all(len(o) for o in oracles)

    state_dir = str(tmp_path / "state")

    def make_cfg():
        return EngineConfig(
            checkpoint=True,
            checkpoint_interval_s=9999,
            state_backend_path=state_dir,
        )

    got = [dict() for _ in SPECS]
    try:
        ctx_a = Context(make_cfg())
        root_a = _approx_shared_root(ctx_a, batches)
        orch_a = Orchestrator(interval_s=9999)
        coord_a = wire_checkpointing(root_a, ctx_a, orch_a)
        emissions = 0
        committed = False
        post_commit = 0
        it = root_a.run()
        for item in it:
            if isinstance(item, SubscriberBatch):
                _rows_of_approx(item.batch, got[item.tag])
                emissions += 1
                if committed:
                    post_commit += 1
                    if post_commit >= 9:
                        break  # hard kill: mid-epoch progress lost
            if emissions == 8 and not committed:
                orch_a.trigger_now()
            if isinstance(item, Marker):
                coord_a.commit(item.epoch)
                committed = True
        it.close()
        assert committed and post_commit >= 9
        close_global_state_backend()

        ctx_b = Context(make_cfg())
        root_b = _approx_shared_root(ctx_b, batches)
        orch_b = Orchestrator(interval_s=9999)
        coord_b = wire_checkpointing(root_b, ctx_b, orch_b)
        assert coord_b.committed_epoch is not None
        for item in root_b.run():
            if isinstance(item, SubscriberBatch):
                _rows_of_approx(item.batch, got[item.tag])
            if isinstance(item, EndOfStream):
                break
    finally:
        close_global_state_backend()

    for q in range(len(SPECS)):
        assert set(got[q]) == set(oracles[q]), {
            "query": q,
            "missing": sorted(set(oracles[q]) - set(got[q]))[:4],
            "extra": sorted(set(got[q]) - set(oracles[q]))[:4],
        }
        for k in oracles[q]:
            assert got[q][k] == oracles[q][k], (q, k)
