"""Idle-source watermark hints: windows/sessions over a quiet topic close
after ``source_idle_timeout_ms`` instead of waiting for more data forever
(the reference never closes them — this is the Flink-style idleness
escape hatch, default OFF)."""

import json
import threading
import time

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.testing.mock_kafka import MockKafkaBroker


@pytest.fixture
def broker():
    b = MockKafkaBroker().start()
    yield b
    b.stop()


def _produce_then_quiet(broker, topic, parts, t0, rows_per_part=600):
    """Rows spanning ~2.4s of event time, produced progressively, then
    silence."""

    def feed():
        for chunk in range(4):
            for p in range(parts):
                msgs = [
                    json.dumps(
                        {
                            "occurred_at_ms": t0
                            + chunk * 600
                            + i * (600 // (rows_per_part // 4)),
                            "sensor_name": f"s{i % 3}",
                            "reading": 1.0,
                        }
                    ).encode()
                    for i in range(rows_per_part // 4)
                ]
                broker.produce(topic, p, msgs)
            time.sleep(0.15)

    th = threading.Thread(target=feed, daemon=True)
    th.start()
    return th


@pytest.mark.parametrize("parts", [1, 2])
def test_idle_timeout_closes_final_windows(broker, parts):
    """Without the timeout the windows covering the tail of a quiet topic
    never emit; with it they close at the max timestamp seen.  parts=1
    exercises the round-robin source path, parts=2 the threaded one."""
    topic = f"quiet{parts}"
    broker.create_topic(topic, partitions=parts)
    t0 = 1_700_000_000_000
    _produce_then_quiet(broker, topic, parts, t0)

    sample = json.dumps(
        {"occurred_at_ms": 1, "sensor_name": "a", "reading": 0.5}
    )
    ctx = Context(EngineConfig(source_idle_timeout_ms=400))
    ds = ctx.from_topic(
        topic, sample, broker.bootstrap, "occurred_at_ms"
    ).window(["sensor_name"], [F.count(col("reading")).alias("c")], 1000)

    got = {}
    it = ds.stream()
    deadline = time.time() + 25
    for batch in it:
        for i in range(batch.num_rows):
            got[
                (
                    int(batch.column("window_start_time")[i]),
                    str(batch.column("sensor_name")[i]),
                )
            ] = int(batch.column("c")[i])
        # the LAST fully-covered window starts at t0+1000 (event time tops
        # out just under t0+2400, so [1000,2000) is complete; [2000,3000)
        # is partial and must stay open)
        if any(ws == t0 + 1000 for ws, _ in got) or time.time() > deadline:
            it.close()
            break
    starts = {ws for ws, _ in got}
    assert t0 in starts, starts
    assert t0 + 1000 in starts, (
        "idle hint did not close the final complete window"
    )
    assert t0 + 2000 not in starts, (
        "window beyond the max seen timestamp must NOT close"
    )


def test_idle_timeout_closes_sessions(broker):
    """Session windows: the gap can only expire via new data — or via the
    idle hint."""
    topic = "quiet_sess"
    broker.create_topic(topic, partitions=2)
    t0 = 1_700_000_000_000

    def feed():
        for chunk in range(3):
            for p in range(2):
                msgs = [
                    json.dumps(
                        {
                            "occurred_at_ms": t0 + chunk * 300 + i * 2,
                            "sensor_name": "a",
                            "reading": 1.0,
                        }
                    ).encode()
                    for i in range(100)
                ]
                broker.produce(topic, p, msgs)
            time.sleep(0.15)

    threading.Thread(target=feed, daemon=True).start()
    sample = json.dumps(
        {"occurred_at_ms": 1, "sensor_name": "a", "reading": 0.5}
    )
    ctx = Context(EngineConfig(source_idle_timeout_ms=400))
    ds = ctx.from_topic(
        topic, sample, broker.bootstrap, "occurred_at_ms"
    ).session_window(
        ["sensor_name"], [F.count(col("reading")).alias("c")], 5_000
    )

    # all 600 rows form ONE session (gaps are tiny); the hint advances
    # the watermark only to the max SEEN timestamp, which is inside the
    # session's gap horizon — so nothing may close.  Pull items at the
    # operator level: the hint reaching the sink is the deterministic
    # "idleness fired" sync point, making the no-emission assert bounded.
    from denormalized_tpu.common.record_batch import RecordBatch
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import WM_ANNOUNCE, WatermarkHint
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor

    root = executor.build_physical(
        lp.Sink(ds._plan, CollectSink()), ds._ctx
    )
    gen = root.run()
    saw_hint = False
    emitted = 0
    for item in gen:
        if isinstance(item, RecordBatch):
            emitted += item.num_rows
        if isinstance(item, WatermarkHint):
            saw_hint = True
            break
    gen.close()
    assert saw_hint, "idle hint never reached the sink"
    assert emitted == 0, (
        "session closed although its gap extends beyond the max seen "
        "timestamp"
    )


def test_idle_timeout_session_gap_expired(broker):
    """A session whose gap HAS expired relative to the max seen timestamp
    closes on the idle hint."""
    topic = "quiet_sess2"
    broker.create_topic(topic, partitions=2)
    t0 = 1_700_000_000_000

    def feed():
        # burst 1 at t0, burst 2 at t0+10_000 (gap 5s long expired)
        for burst_t in (t0, t0 + 10_000):
            for p in range(2):
                msgs = [
                    json.dumps(
                        {
                            "occurred_at_ms": burst_t + i,
                            "sensor_name": "a",
                            "reading": 1.0,
                        }
                    ).encode()
                    for i in range(50)
                ]
                broker.produce(topic, p, msgs)
            time.sleep(0.15)

    threading.Thread(target=feed, daemon=True).start()
    sample = json.dumps(
        {"occurred_at_ms": 1, "sensor_name": "a", "reading": 0.5}
    )
    ctx = Context(EngineConfig(source_idle_timeout_ms=400))
    ds = ctx.from_topic(
        topic, sample, broker.bootstrap, "occurred_at_ms"
    ).session_window(
        ["sensor_name"], [F.count(col("reading")).alias("c")], 5_000
    )
    counts = []
    it = ds.stream()
    deadline = time.time() + 25
    for batch in it:
        counts += [int(v) for v in batch.column("c")]
        if counts or time.time() > deadline:
            it.close()
            break
    # the FIRST burst's session (100 rows across 2 partitions) closes via
    # the hint: max_ts ~= t0+10_049 > t0+49+5000
    assert counts and counts[0] == 100, counts


def test_idle_timeout_evicts_join_state(broker):
    """A left-outer join's unmatched rows can only evict (and emit
    null-padded) once BOTH sides' watermarks pass them; a quiet side's
    watermark advances via the idle hint."""
    t0 = 1_700_000_000_000
    broker.create_topic("jl", partitions=2)
    broker.create_topic("jr", partitions=2)

    def feed(topic, key):
        def run():
            for chunk in range(3):
                for p in range(2):
                    msgs = [
                        json.dumps(
                            {
                                "occurred_at_ms": t0 + chunk * 400 + i * 4,
                                "sensor_name": f"{key}{i % 4}",
                                "reading": 1.0,
                            }
                        ).encode()
                        for i in range(100)
                    ]
                    broker.produce(topic, p, msgs)
                time.sleep(0.12)

        th = threading.Thread(target=run, daemon=True)
        th.start()
        return th

    feed("jl", "L")  # keys L0..L3 never match R0..R3: all left rows unmatched
    feed("jr", "R")

    sample = json.dumps(
        {"occurred_at_ms": 1, "sensor_name": "a", "reading": 0.5}
    )
    ctx = Context(
        EngineConfig(source_idle_timeout_ms=400, join_retention_ms=500)
    )
    left = ctx.from_topic("jl", sample, broker.bootstrap, "occurred_at_ms")
    right = (
        ctx.from_topic("jr", sample, broker.bootstrap, "occurred_at_ms")
        .with_column_renamed("occurred_at_ms", "r_at_ms")
        .with_column_renamed("sensor_name", "rname")
        .with_column_renamed("reading", "rreading")
    )
    ds = left.join(right, "left", ["sensor_name"], ["rname"])

    unmatched = 0
    it = ds.stream()
    deadline = time.time() + 25
    for batch in it:
        m = batch.mask("rname")
        if m is not None:
            unmatched += int((~m).sum())
        elif batch.num_rows and batch.column("rname")[0] is None:
            unmatched += batch.num_rows
        if unmatched > 0 or time.time() > deadline:
            # only the rows older than the hint-driven horizon evict
            # (~200 of 600); one emitted eviction proves the path
            it.close()
            break
    # both sides go quiet after ~1.2s; hints advance both watermarks to
    # their max seen (~t0+1196), horizon = that - 500 > t0+696... at least
    # the early unmatched left rows MUST have evicted and emitted
    assert unmatched > 0, "no unmatched rows evicted via idle hints"


def test_forwarded_hint_clamped_below_open_windows(broker):
    """Operators forward hints clamped below their lowest possible future
    emission (emissions stamp canonical ts = window start) — a downstream
    stateful operator must NOT late-drop a later-closing window."""
    topic = "quiet_clamp"
    broker.create_topic(topic, partitions=2)
    t0 = 1_700_000_000_000
    _produce_then_quiet(broker, topic, 2, t0)

    from denormalized_tpu.common.record_batch import RecordBatch
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import WM_ANNOUNCE, WatermarkHint
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor

    sample = json.dumps(
        {"occurred_at_ms": 1, "sensor_name": "a", "reading": 0.5}
    )
    ctx = Context(EngineConfig(source_idle_timeout_ms=400))
    ds = ctx.from_topic(
        topic, sample, broker.bootstrap, "occurred_at_ms"
    ).window(["sensor_name"], [F.count(col("reading")).alias("c")], 1000)
    root = executor.build_physical(
        lp.Sink(ds._plan, CollectSink()), ds._ctx
    )
    gen = root.run()
    hint_ts = None
    max_emitted_start = None
    deadline = time.time() + 20
    for item in gen:
        if isinstance(item, RecordBatch) and item.num_rows:
            s = int(np.max(item.column("window_start_time")))
            if max_emitted_start is None or s > max_emitted_start:
                max_emitted_start = s
        if isinstance(item, WatermarkHint) and item.ts_ms > WM_ANNOUNCE:
            # skip the partition-mode announcement: the clamp property
            # applies to every REAL forwarded hint, idle or partition
            hint_ts = item.ts_ms
            break
        if time.time() > deadline:
            break
    gen.close()
    assert hint_ts is not None, "no forwarded hint observed"
    # event time tops out just under t0+2400: window [2000,3000) stays
    # OPEN, so the forwarded hint must be clamped below its start
    assert hint_ts < t0 + 2000, (hint_ts - t0, "hint not clamped")
    # and everything emitted so far is at or below the forwarded hint
    if max_emitted_start is not None:
        assert max_emitted_start <= hint_ts


def test_idle_hint_forces_deferred_emission(broker):
    """The partial_merge strategy defers emission up to emit_lag_ms
    expecting another item to follow; the single idle hint must FORCE the
    emission and drain the async pipeline — otherwise closable windows
    sit unemitted forever."""
    from denormalized_tpu.common.record_batch import RecordBatch
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import WM_ANNOUNCE, WatermarkHint
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor

    topic = "quiet_defer"
    broker.create_topic(topic, partitions=2)
    t0 = 1_700_000_000_000
    _produce_then_quiet(broker, topic, 2, t0)
    sample = json.dumps(
        {"occurred_at_ms": 1, "sensor_name": "a", "reading": 0.5}
    )
    ctx = Context(
        EngineConfig(
            source_idle_timeout_ms=400,
            device_strategy="partial_merge",
            emit_lag_ms=10_000,  # far beyond the test horizon
        )
    )
    ds = ctx.from_topic(
        topic, sample, broker.bootstrap, "occurred_at_ms"
    ).window(["sensor_name"], [F.count(col("reading")).alias("c")], 1000)
    root = executor.build_physical(
        lp.Sink(ds._plan, CollectSink()), ds._ctx
    )
    gen = root.run()
    starts = set()
    hint_ts = None
    deadline = time.time() + 20
    for item in gen:
        if isinstance(item, RecordBatch) and item.num_rows:
            starts |= {
                int(v) - t0 for v in item.column("window_start_time")
            }
        if isinstance(item, WatermarkHint) and item.kind == "idle":
            # partition-watermark hints flow continuously (and do NOT
            # force); the IDLE hint is the one that must force the
            # deferred emission
            hint_ts = item.ts_ms
            break
        if time.time() > deadline:
            break
    gen.close()
    assert 0 in starts and 1000 in starts, starts
    assert hint_ts is not None and hint_ts < t0 + 2000
