"""State & skew observatory (obs/statewatch.py + obs/doctor/statedoc.py).

Covers the ISSUE-8 acceptance surface:

- sketch correctness: Space-Saving overestimate bounds, hot-key
  survival under key churn, HLL accuracy, block-sampling scale-back;
- exact state accounting identical before a kill and after restore for
  BOTH session operators, the join, and the udaf operator (sketches
  deliberately re-warm — the documented trade);
- the integration acceptance: a deliberately skewed join feed yields a
  ``skewed-join-side`` verdict at ``GET /queries/<id>/state`` naming
  the correct node id and the hot key's state-mass share within sketch
  error bounds, and a budgeted session workload produces a finite
  time-to-budget forecast that tightens as snapshots accrue;
- the registry/doctor surfaces: per-node dnz_state_* gauges, hot-key
  share series, per-key checkpoint snapshot-size gauges,
  explain_analyze state columns, and the soak telemetry derivation.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from denormalized_tpu import Context, col, obs
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.api.udaf import Accumulator
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.obs import statewatch
from denormalized_tpu.obs.doctor import statedoc
from denormalized_tpu.obs.readers import gauge_series, linear_forecast
from denormalized_tpu.obs.registry import MetricsRegistry
from denormalized_tpu.obs.statewatch import Hll, SpaceSaving, StateWatch
from denormalized_tpu.sources.memory import MemorySource
from denormalized_tpu.state.lsm import close_global_state_backend


@pytest.fixture
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = obs.use_registry(reg)
    yield reg
    obs.use_registry(prev)


@pytest.fixture(autouse=True)
def _clean_global_backend():
    yield
    close_global_state_backend()


T0 = 1_700_000_000_000


# -- sketches ---------------------------------------------------------------


def test_space_saving_overestimate_bound():
    """count - err <= true <= count for every tracked key (the classic
    Space-Saving guarantee, preserved by the batch variant)."""
    rng = np.random.default_rng(7)
    true: dict[int, int] = {}
    ss = SpaceSaving(32)
    for _ in range(50):
        batch = rng.zipf(1.5, size=500) % 200
        for g in batch.tolist():
            true[g] = true.get(g, 0) + 1
        ss.update(batch.astype(np.int64))
    gids, counts, errs = ss.top(32)
    assert ss.total == 50 * 500
    for g, c, e in zip(gids.tolist(), counts.tolist(), errs.tolist()):
        t = true.get(g, 0)
        assert t <= c, (g, t, c)
        assert c - e <= t, (g, t, c, e)


def test_space_saving_hot_key_survives_churn():
    """A celebrity key must survive batches that bring more NEW keys
    than the sketch has slots (the admission-guard regression)."""
    for trial in range(4):
        rng = np.random.default_rng(trial)
        ss = SpaceSaving(64)
        for b in range(40):
            churn = rng.integers(b * 1000, b * 1000 + 900, 400)
            g = np.concatenate([churn, np.full(600, 999_999)])
            rng.shuffle(g)
            ss.update(g)
        gids, counts, _ = ss.top(1)
        assert gids[0] == 999_999
        share = counts[0] / ss.total
        assert 0.55 <= share <= 0.65, share


def test_space_saving_decay_halves_counts_and_total():
    """One decay step scales counts, errs, and total by decay_factor —
    the windowed-sketch contract (shares stay comparable because both
    numerator and denominator scale together)."""
    ss = SpaceSaving(16, decay_every=1000, decay_factor=0.5)
    ss.update(np.full(600, 7, dtype=np.int64))
    ss.update(np.full(300, 9, dtype=np.int64))
    assert ss.total == 900  # below the horizon: no decay yet
    gids, counts, _ = ss.top(2)
    before = dict(zip(gids.tolist(), counts.tolist()))
    assert before == {7: 600, 9: 300}
    # crossing the horizon decays the WINDOW first, then adds the batch
    ss.update(np.full(100, 7, dtype=np.int64))
    assert ss.total == 450 + 100
    gids, counts, _ = ss.top(2)
    after = dict(zip(gids.tolist(), counts.tolist()))
    assert after == {7: 300 + 100, 9: 150}


def test_space_saving_decay_retires_stale_celebrity():
    """A celebrity that stops appearing must lose its top share within
    a bounded number of decay horizons — the monotone sketch keeps it
    near-forever (share only falls as 1/total), the windowed one
    halves it per horizon.  This is what lets the join adaptation
    policy FOLD a retired hot key promptly."""
    monotone = SpaceSaving(64)
    windowed = SpaceSaving(64, decay_every=10_000, decay_factor=0.5)
    rng = np.random.default_rng(3)
    hot_phase = np.concatenate(
        [np.full(700, 42), rng.integers(0, 50, 300)]
    ).astype(np.int64)
    for _ in range(10):
        monotone.update(hot_phase)
        windowed.update(hot_phase)
    for ss in (monotone, windowed):
        g, c, _ = ss.top(1)
        assert g[0] == 42 and c[0] / ss.total > 0.6
    cold_phase = rng.integers(100, 150, 1000).astype(np.int64)
    for _ in range(30):
        monotone.update(cold_phase)
        windowed.update(cold_phase)

    def share(ss, key):
        gids, counts, _ = ss.top(64)
        m = dict(zip(gids.tolist(), counts.tolist()))
        return m.get(key, 0) / ss.total

    # monotone: still >17% after 3x cold traffic (7000/40000)
    assert share(monotone, 42) > 0.15
    # windowed: decayed well below the fold threshold regime
    assert share(windowed, 42) < 0.05


def test_fold_trigger_fires_on_decayed_share():
    """The policy's fold condition (share < fold_share for hold_ticks
    consecutive ticks) must become reachable through sketch decay alone
    — pin it directly against the windowed sketch's share sequence."""
    from denormalized_tpu.obs.doctor.actions import JoinAdaptationPolicy

    pol = JoinAdaptationPolicy()
    ss = SpaceSaving(64, decay_every=2_000, decay_factor=0.5)
    ss.update(np.full(10_000, 42, dtype=np.int64))  # all-hot warmup
    ticks_below = 0
    rng = np.random.default_rng(5)
    for _ in range(40):
        ss.update(rng.integers(100, 150, 1000).astype(np.int64))
        gids, counts, _ = ss.top(64)
        m = dict(zip(gids.tolist(), counts.tolist()))
        if m.get(42, 0) / ss.total < pol.fold_share:
            ticks_below += 1
            if ticks_below >= pol.hold_ticks:
                break
        else:
            ticks_below = 0
    assert ticks_below >= pol.hold_ticks, (
        "decayed share never stayed below fold_share long enough"
    )


def test_space_saving_reset():
    ss = SpaceSaving(16)
    ss.update(np.arange(100))
    ss.reset()
    assert ss.total == 0
    g, c, e = ss.top(5)
    assert len(g) == 0


def test_hll_accuracy():
    h = Hll()
    h.update(np.arange(100_000))
    est = h.estimate()
    assert abs(est - 100_000) / 100_000 < 0.05  # 1.04/sqrt(4096) ~ 1.6%
    h2 = Hll()
    h2.update(np.arange(40))
    assert abs(h2.estimate() - 40) <= 3  # linear-counting regime
    h2.reset()
    assert h2.estimate() == 0 or h2.estimate() < 1


def test_block_sampling_scales_counts_back_to_row_units():
    """Batches beyond SKETCH_ROW_CAP sample a rotating contiguous block;
    shares and totals must still be in true-row units."""
    sw = StateWatch("t")
    n = statewatch.SKETCH_ROW_CAP * 6
    rng = np.random.default_rng(3)
    g = rng.integers(0, 2, size=n).astype(np.int64)  # two keys, 50/50
    sw.update(g)
    assert sw.sketch.total == n
    _gids, counts, _errs = sw.sketch.top(2)
    assert counts.sum() == pytest.approx(n, rel=0.25)
    for c in counts:
        assert c / n == pytest.approx(0.5, abs=0.1)


def test_block_sampling_just_over_cap_keeps_shares_bounded():
    """Regression: a batch just over SKETCH_ROW_CAP must rescale by the
    TRUE sampling ratio (~1.04), not an integer ceiling (2x) — the
    ceiling inflated every share ~2x and could fabricate skew verdicts
    (a single-key batch read share 1.93)."""
    sw = StateWatch("t")
    n = statewatch.SKETCH_ROW_CAP + 600
    sw.update(np.zeros(n, dtype=np.int64))  # one key, 100% of rows
    _g, counts, _e = sw.sketch.top(1)
    share = counts[0] / sw.sketch.total
    assert 0.95 <= share <= 1.05, share


def test_block_sampling_rotation_covers_batch_tail():
    """Regression: with constant-size batches the sample phase must wrap
    over the valid start range, not reset to 0 — a key living only in
    the tail rows past the last full block was permanently invisible."""
    sw = StateWatch("t")
    n = statewatch.SKETCH_ROW_CAP + 4000
    g = np.zeros(n, dtype=np.int64)
    g[-4000:] = 7  # the celebrity lives ONLY in the batch tail
    for _ in range(20):
        sw.update(g)
    gids, counts, _ = sw.sketch.top(2)
    assert 7 in gids.tolist(), gids
    i = gids.tolist().index(7)
    share = counts[i] / sw.sketch.total
    assert share == pytest.approx(4000 / n, rel=0.5), share


def test_query_level_budget_pressure_verdict():
    """The budget bounds TOTAL state: four growers each ~4400s from the
    budget alone, jointly 600s, must raise a QUERY-level
    state-budget-pressure verdict (node_id None) while every per-node
    check stays silent."""
    now = time.time()

    class _FakeOp:
        def __init__(self, nid, cur, slope):
            self.nid = nid
            self._info = {
                "op": "window", "state_bytes": cur, "live_keys": 1,
            }
            self._sw = StateWatch("f")
            for k in range(4, 0, -1):
                self._sw.record_sample(cur - slope * k, t=now - k)

        def state_info(self):
            return self._info

        def _state_watch_views(self):
            return []

    ops = [_FakeOp(f"{i}_W", 10_000, 15.0) for i in range(4)]

    class _H:
        query_id = "qx"
        running = True
        # total 40k, joint slope 60 B/s -> joint tt = 600s (fires);
        # per node: (76k - 10k) / 15 = 4400s (silent)
        config = EngineConfig(state_budget_bytes=76_000)

        def _walk(self):
            return iter((op, op.nid, None) for op in ops)

    snap = statedoc.state_snapshot(_H())
    assert snap["forecast"]["slope_bytes_per_s"] == pytest.approx(
        60.0, rel=0.05
    )
    pressure = [v for v in snap["verdicts"]
                if v["kind"] == "state-budget-pressure"]
    assert pressure and pressure[0]["node_id"] is None, snap["verdicts"]
    assert pressure[0]["time_to_budget_s"] <= statedoc.BUDGET_PRESSURE_S
    assert len(pressure) == 1  # no per-node verdict joined it


def test_join_skew_gauge_uses_per_side_live_keys():
    """Regression: the skew gauge fed a per-side sketch the COMBINED
    both-sides key count, reading ~2 on a perfectly uniform join."""
    info = {
        "live_keys": 200,
        "sides": {"left": {"live_keys": 100}, "right": {"live_keys": 100}},
    }
    assert statewatch.side_live_keys(info, "left") == 100
    assert statewatch.side_live_keys(info, None) == 200
    sw = StateWatch("t")
    sw.update(np.arange(100).repeat(10))  # uniform: 100 keys x 10 rows
    assert sw.skew_factor(
        statewatch.side_live_keys(info, "left")
    ) == pytest.approx(1.0, rel=0.05)


def test_skew_factor_and_hot_keys():
    sw = StateWatch("t")
    g = np.concatenate([np.full(500, 3), np.arange(4, 54).repeat(10)])
    sw.update(g)
    hot = sw.hot_keys(3, resolve=lambda gids: [f"k{int(x)}" for x in gids])
    assert hot[0]["key"] == "k3"
    assert hot[0]["share"] == pytest.approx(0.5, abs=0.02)
    sk = sw.skew_factor(live_keys=51)
    assert sk == pytest.approx(25.5, rel=0.1)  # 0.5 share x 51 keys


def test_null_watch_is_inert_and_falsy():
    nw = statewatch.NULL_WATCH
    assert not nw
    nw.update(np.arange(10))
    nw.record_sample(100)
    assert nw.forecast(10) is None
    assert nw.summary()["enabled"] is False


def test_make_watch_follows_registry_enablement(registry):
    assert isinstance(statewatch.make_watch("x"), StateWatch)
    with obs.bound_registry(obs.disabled_registry()):
        assert statewatch.make_watch("x") is statewatch.NULL_WATCH


# -- growth forecasting -----------------------------------------------------


def test_linear_forecast_contract():
    # exact line: 100 B/s from 1000
    pts = [(10.0 + i, 1000.0 + 100 * i) for i in range(5)]
    fc = linear_forecast(pts, budget=11_400)
    assert fc["slope_bytes_per_s"] == pytest.approx(100.0)
    assert fc["r2"] == pytest.approx(1.0)
    assert fc["time_to_budget_s"] == pytest.approx(100.0, rel=0.01)
    # flat: never reaches the budget
    flat = linear_forecast([(0, 5), (1, 5), (2, 5)], budget=100)
    assert flat["slope_bytes_per_s"] == 0
    assert flat["time_to_budget_s"] is None
    # at/over budget: 0
    over = linear_forecast([(0, 100), (1, 200)], budget=150)
    assert over["time_to_budget_s"] == 0.0
    # under two points: None
    assert linear_forecast([(0, 1)]) is None
    assert linear_forecast([]) is None


def test_gauge_series_reader():
    snaps = [
        {"event": "obs", "t": 1.0, "metrics": {"dnz_state_bytes{node=\"x\"}": 10}},
        {"event": "obs", "t": 2.0, "metrics": {"dnz_state_bytes{node=\"x\"}": 20}},
        {"event": "obs", "t": 3.0, "metrics": {}},
    ]
    pts = gauge_series(snaps, 'dnz_state_bytes{node="x"}')
    assert pts == [(1.0, 10), (2.0, 20)]
    assert linear_forecast(pts)["slope_bytes_per_s"] == pytest.approx(10.0)


# -- verdict rules (unit) ---------------------------------------------------


def _join_node(share, live_keys, skew):
    return {
        "node_id": "2_StreamingJoinExec", "op": "join",
        "sides": {"left": {"live_keys": live_keys}, "right": {"live_keys": 3}},
        "sketches": {
            "left": {
                "hot_keys": [
                    {"key": "celebrity", "rows": 100, "err_rows": 1,
                     "share": share},
                ],
                "skew_factor": skew,
            },
        },
    }


def test_verdict_skewed_join_side_fires_and_names_side():
    v = statedoc.verdicts([_join_node(0.5, 40, 20.0)])
    assert v and v[0]["kind"] == "skewed-join-side"
    assert v[0]["node_id"] == "2_StreamingJoinExec"
    assert v[0]["side"] == "left"
    assert v[0]["key"] == "celebrity"
    # below either threshold: silent
    assert not statedoc.verdicts([_join_node(0.1, 40, 20.0)])
    assert not statedoc.verdicts([_join_node(0.5, 4, 2.0)])


def test_verdict_retention_leak_and_ranking():
    nodes = [
        {"node_id": "1_S", "op": "session", "retention_unit_ms": 1000,
         "oldest_event_lag_ms": 50_000},
        _join_node(0.3, 40, 12.0),
    ]
    v = statedoc.verdicts(nodes)
    kinds = [x["kind"] for x in v]
    assert "retention-leak" in kinds and "skewed-join-side" in kinds
    # ranked by severity desc
    sevs = [x["severity"] for x in v]
    assert sevs == sorted(sevs, reverse=True)
    # lag below N units: silent
    ok = {"node_id": "1_S", "op": "session", "retention_unit_ms": 1000,
          "oldest_event_lag_ms": 2_000}
    assert not statedoc.verdicts([ok])


def test_verdict_growth_and_budget_pressure():
    grow = {
        "node_id": "1_S", "op": "session", "state_bytes": 1000,
        "forecast": {"slope_bytes_per_s": 50.0, "r2": 0.9, "samples": 5,
                     "window_s": 10.0},
    }
    v = statedoc.verdicts([grow], budget=2000)
    kinds = {x["kind"] for x in v}
    assert "unbounded-session-growth" in kinds
    assert "state-budget-pressure" in kinds
    tt = [x for x in v if x["kind"] == "state-budget-pressure"][0]
    assert tt["time_to_budget_s"] == pytest.approx(20.0, rel=0.01)
    # poor fit: no growth verdict
    grow2 = dict(grow, forecast=dict(grow["forecast"], r2=0.1))
    assert "unbounded-session-growth" not in {
        x["kind"] for x in statedoc.verdicts([grow2])
    }


# -- accounting across checkpoint/restore (the satellite core) --------------


_SENSOR_SCHEMA = Schema([
    Field("occurred_at_ms", DataType.INT64, nullable=False),
    Field("sensor_name", DataType.STRING, nullable=False),
    Field("reading", DataType.FLOAT64),
])


def _sensor_batches(n_batches=12, rows=200, seed=21, keys=7):
    """Bursty feed: batch b is a 300ms burst at T0 + b*1000 — the 700ms
    silences exceed the 300ms session gap, so each burst's sessions
    CLOSE when the next burst advances the watermark (emissions flow
    mid-stream, giving the checkpoint barrier an injection point)."""
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = np.sort(T0 + b * 1000 + rng.integers(0, 300, rows))
        names = np.array(
            [f"s{i}" for i in rng.integers(0, keys, rows)], dtype=object
        )
        out.append(RecordBatch(
            _SENSOR_SCHEMA, [ts, names, rng.normal(50, 5, rows)]
        ))
    return out


def _cfg(path):
    return EngineConfig(
        checkpoint=path is not None,
        checkpoint_interval_s=9999,
        state_backend_path=path,
        emit_lag_ms=0,
    )


def _find_op(root, cls_name):
    from denormalized_tpu.state.checkpoint import walk

    for op in walk(root):
        if type(op).__name__ == cls_name:
            return op
    raise AssertionError(f"no {cls_name} in plan")


def _run_to_marker(plan, ctx):
    """Build + wire + drive until the first committed barrier, then
    crash (generator close).  Returns the physical root, frozen."""
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import Marker
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.orchestrator import Orchestrator

    root = executor.build_physical(lp.Sink(plan, CollectSink()), ctx)
    orch = Orchestrator(interval_s=9999)
    coord = wire_checkpointing(root, ctx, orch)
    items_seen = 0
    it = root.run()
    for item in it:
        if items_seen == 1:
            orch.trigger_now()
        if isinstance(item, Marker):
            coord.commit(item.epoch)
            break
        items_seen += 1
    it.close()  # crash
    close_global_state_backend()
    return root


def _wire_restore(plan, ctx):
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.orchestrator import Orchestrator

    root = executor.build_physical(lp.Sink(plan, CollectSink()), ctx)
    coord = wire_checkpointing(root, ctx, Orchestrator(interval_s=9999))
    assert coord.committed_epoch is not None
    return root


def _invariant(info, keys):
    return {k: info.get(k) for k in keys}


_SESSION_KEYS = (
    "op", "state_bytes", "live_keys", "slot_live", "acc_objects",
    "oldest_event_ms", "watermark_ms", "oldest_event_lag_ms",
)


def _session_restore_roundtrip(tmp_path, registry, op_cls):
    state = str(tmp_path / "state")
    batches = _sensor_batches()

    def build(ctx):
        return ctx.from_source(
            MemorySource.from_batches(
                batches, timestamp_column="occurred_at_ms"
            ),
            name="sw_src",
        ).session_window(
            ["sensor_name"],
            [F.count(col("reading")).alias("cnt"),
             F.avg(col("reading")).alias("a")],
            300,
        )._plan

    ctx_a = Context(_cfg(state))
    root_a = _run_to_marker(build(ctx_a), ctx_a)
    op_a = _find_op(root_a, op_cls)
    info_a = op_a.state_info()
    assert info_a["live_keys"] > 0 and info_a["state_bytes"] > 0

    ctx_b = Context(_cfg(state))
    root_b = _wire_restore(build(ctx_b), ctx_b)
    op_b = _find_op(root_b, op_cls)
    info_b = op_b.state_info()
    assert _invariant(info_a, _SESSION_KEYS) == _invariant(
        info_b, _SESSION_KEYS
    )
    return op_a, op_b


def test_session_accounting_survives_restore(tmp_path, registry):
    op_a, op_b = _session_restore_roundtrip(
        tmp_path, registry, "SessionWindowExec"
    )
    # sketches do NOT ride the snapshot: they re-warm (documented)
    assert op_a._sw.sketch.total > 0
    assert op_b._sw.sketch.total == 0


def test_reference_session_accounting_survives_restore(
    tmp_path, registry, monkeypatch
):
    monkeypatch.setenv("DENORMALIZED_SESSION_REFERENCE", "1")
    _session_restore_roundtrip(
        tmp_path, registry, "ReferenceSessionWindowExec"
    )


def test_join_accounting_survives_restore(tmp_path, registry):
    from denormalized_tpu.physical import join_exec as JE

    state = str(tmp_path / "state")
    rng = np.random.default_rng(5)
    lb, rb = [], []
    # enough batches that the bounded sources cannot fully drain into
    # the join's pumps before the barrier is triggered mid-stream
    for b in range(24):
        rows = 150
        ts = np.sort(T0 + b * 400 + rng.integers(0, 400, rows))
        ks = np.array(
            [f"k{i}" for i in rng.integers(0, 9, rows)], dtype=object
        )
        lb.append(RecordBatch(
            Schema([Field("ts", DataType.INT64, nullable=False),
                    Field("k", DataType.STRING, nullable=False),
                    Field("v", DataType.FLOAT64)]),
            [ts, ks, rng.normal(0, 1, rows)],
        ))
        rb.append(RecordBatch(
            Schema([Field("rts", DataType.INT64, nullable=False),
                    Field("rk", DataType.STRING, nullable=False),
                    Field("rv", DataType.FLOAT64)]),
            [ts.copy(), ks.copy(), rng.normal(0, 1, rows)],
        ))

    def build(ctx):
        left = ctx.from_source(MemorySource.from_batches(
            lb, timestamp_column="ts"), name="L")
        right = ctx.from_source(MemorySource.from_batches(
            rb, timestamp_column="rts"), name="R")
        return left.join(right, "inner", ["k"], ["rk"])._plan

    ctx_a = Context(_cfg(state))
    root_a = _run_to_marker(build(ctx_a), ctx_a)
    join_a = _find_op(root_a, "StreamingJoinExec")
    info_a = join_a.state_info()
    assert info_a["slot_live"] > 0 and info_a["state_bytes"] > 0

    ctx_b = Context(_cfg(state))
    root_b = _wire_restore(build(ctx_b), ctx_b)
    join_b = _find_op(root_b, "StreamingJoinExec")
    sides = (JE._SideState(), JE._SideState())
    join_b._sides = sides
    join_b._restore(sides)
    info_b = join_b.state_info()

    keys = ("op", "state_bytes", "live_keys", "slot_live")
    assert _invariant(info_a, keys) == _invariant(info_b, keys)
    for side in ("left", "right"):
        sa, sb = info_a["sides"][side], info_b["sides"][side]
        assert sa == sb, (side, sa, sb)


def test_udaf_accounting_survives_restore(tmp_path, registry):
    class Spread(Accumulator):
        def __init__(self):
            self.lo, self.hi = float("inf"), float("-inf")

        def update(self, values):
            if len(values):
                self.lo = min(self.lo, float(values.min()))
                self.hi = max(self.hi, float(values.max()))

        def merge(self, states):
            self.lo = min(self.lo, states[0])
            self.hi = max(self.hi, states[1])

        def state(self):
            return [self.lo, self.hi]

        def evaluate(self):
            return self.hi - self.lo if self.hi >= self.lo else 0.0

    spread = F.udaf(Spread, DataType.FLOAT64, "spread")
    state = str(tmp_path / "state")
    batches = _sensor_batches()

    def build(ctx):
        return ctx.from_source(
            MemorySource.from_batches(
                batches, timestamp_column="occurred_at_ms"
            ),
            name="u_src",
        ).window(
            ["sensor_name"], [spread(col("reading")).alias("sp")], 1000
        )._plan

    ctx_a = Context(_cfg(state))
    root_a = _run_to_marker(build(ctx_a), ctx_a)
    op_a = _find_op(root_a, "UdafWindowExec")
    info_a = op_a.state_info()
    assert info_a["acc_objects"] > 0

    ctx_b = Context(_cfg(state))
    root_b = _wire_restore(build(ctx_b), ctx_b)
    op_b = _find_op(root_b, "UdafWindowExec")
    info_b = op_b.state_info()
    keys = ("op", "state_bytes", "live_keys", "slot_live", "open_windows",
            "acc_objects", "oldest_event_ms", "watermark_ms")
    assert _invariant(info_a, keys) == _invariant(info_b, keys)


def test_checkpoint_last_snapshot_bytes_gauge(tmp_path, registry):
    """Satellite 1: every persisted state key gets a labeled
    last-snapshot-bytes gauge, so a restore-size regression names its
    operator."""
    state = str(tmp_path / "state")
    batches = _sensor_batches()
    ctx = Context(_cfg(state))
    plan = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="occurred_at_ms"),
        name="g_src",
    ).session_window(
        ["sensor_name"], [F.count(col("reading")).alias("c")], 300
    )._plan
    _run_to_marker(plan, ctx)
    snap = registry.snapshot()
    series = [
        k for k in snap
        if k.startswith("dnz_checkpoint_last_snapshot_bytes")
    ]
    assert any("session_" in s for s in series), series
    assert any("offsets_" in s for s in series), series
    for s in series:
        assert snap[s] > 0


# -- live surfaces ----------------------------------------------------------


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.read()


def test_skewed_join_yields_verdict_at_state_endpoint(registry):
    """ISSUE-8 integration acceptance: a join feed where one celebrity
    key holds >= 50% of the left side's rows produces a
    ``skewed-join-side`` verdict at GET /queries/<id>/state naming the
    join's node id, the left side, and the key's state-mass share
    within sketch error bounds."""
    rng = np.random.default_rng(11)
    lschema = Schema([Field("ts", DataType.INT64, nullable=False),
                      Field("k", DataType.STRING, nullable=False),
                      Field("v", DataType.FLOAT64)])
    rschema = Schema([Field("rts", DataType.INT64, nullable=False),
                      Field("rk", DataType.STRING, nullable=False),
                      Field("rv", DataType.FLOAT64)])
    lb, rb = [], []
    for b in range(8):
        rows = 400
        ts = np.sort(T0 + b * 400 + rng.integers(0, 400, rows))
        lk = np.array(
            [f"u{i}" for i in rng.integers(0, 60, rows)], dtype=object
        )
        lk[: rows // 2] = "celebrity"  # >= 50% of the left side
        rk = np.array(
            [f"u{i}" for i in rng.integers(0, 60, rows)], dtype=object
        )
        lb.append(RecordBatch(lschema, [ts, lk, rng.normal(0, 1, rows)]))
        rb.append(RecordBatch(
            rschema, [ts.copy(), rk, rng.normal(0, 1, rows)]
        ))

    ctx = Context(EngineConfig(prometheus_port=0))
    left = ctx.from_source(
        MemorySource.from_batches(lb, timestamp_column="ts"), name="L"
    )
    right = ctx.from_source(
        MemorySource.from_batches(rb, timestamp_column="rts"), name="R"
    )
    ds = left.join(right, "inner", ["k"], ["rk"])
    it = ds.stream()
    try:
        for _ in range(4):
            next(it, None)
        port = ctx._last_exporters.prometheus.port
        base = f"http://127.0.0.1:{port}"
        qid = json.loads(_get(f"{base}/queries")[1])["queries"][0][
            "query_id"
        ]
        status, body = _get(f"{base}/queries/{qid}/state")
        assert status == 200
        payload = json.loads(body)
        assert payload["total_state_bytes"] > 0
        node_ids = {n["node_id"] for n in payload["nodes"]}
        sk = [v for v in payload["verdicts"]
              if v["kind"] == "skewed-join-side"]
        assert sk, payload["verdicts"]
        v = sk[0]
        assert "StreamingJoinExec" in v["node_id"]
        assert v["node_id"] in node_ids
        assert v["side"] == "left"
        assert v["key"] == "celebrity"
        # true share is 0.5; sketch overestimate bounded by err
        assert 0.4 <= v["share"] <= 0.62, v
        # the rule text ships with the payload
        assert "skewed-join-side" in payload["rules"]
    finally:
        for _ in it:
            pass


def test_budgeted_session_forecast_tightens(registry, monkeypatch):
    """ISSUE-8 integration acceptance, second half: a session workload
    with a state budget produces a FINITE time-to-budget forecast that
    tightens (more samples, shrinking projection) as snapshots accrue.

    Driven at the operator level: an ever-growing key population (no
    session ever closes) yields no emissions for a stream loop to pace
    on, so the test feeds batches directly and polls the registered
    query's /state view between feeds — exactly what a monitoring loop
    scraping a long-running query does."""
    from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
    from denormalized_tpu.obs import doctor
    from denormalized_tpu.physical.base import ExecOperator
    from denormalized_tpu.physical.session_exec import SessionWindowExec

    monkeypatch.setattr(statewatch, "_SAMPLE_MIN_INTERVAL_S", 0.0)
    in_schema = Schema([
        Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS,
              nullable=False),
        Field("sensor_name", DataType.STRING, nullable=False),
        Field("reading", DataType.FLOAT64),
    ])

    class _Leaf(ExecOperator):
        schema = in_schema

        def run(self):
            return iter(())

    op = SessionWindowExec(
        _Leaf(), [col("sensor_name")],
        [F.count(col("reading")).alias("c")], 60_000,
    )
    handle = doctor.register_query(
        op, config=EngineConfig(state_budget_bytes=30_000_000),
        registry=registry,
    )
    try:
        rng = np.random.default_rng(2)
        samples_seen, tts = [], []
        uid = 0
        for b in range(10):
            rows = 300
            ts = np.sort(T0 + b * 400 + rng.integers(0, 400, rows))
            names = np.array(
                [f"u{uid + i}" for i in range(rows)], dtype=object
            )
            uid += rows
            batch = RecordBatch(
                in_schema, [ts, names, rng.normal(0, 1, rows)]
            )
            list(op._process_batch(batch))
            time.sleep(0.05)
            snap = handle.state_snapshot()
            fc = snap.get("forecast")
            if fc:
                samples_seen.append(fc["samples"])
                if fc.get("time_to_budget_s") is not None:
                    tts.append(fc["time_to_budget_s"])
        assert snap["budget_bytes"] == 30_000_000
        node = [n for n in snap["nodes"] if n["op"] == "session"][0]
        assert node["live_keys"] == uid  # nothing ever closed
    finally:
        handle.finish()
    assert samples_seen and samples_seen[-1] > samples_seen[0]
    assert samples_seen == sorted(samples_seen)  # accruing, never lost
    assert tts, "no finite time-to-budget despite budget + growth"
    assert all(t > 0 for t in tts)
    assert tts[-1] <= tts[0] * 1.5  # projection tightens, not wanders


def test_state_gauges_and_hot_key_series_bound_per_node(
    make_batch, registry
):
    """The registry view: per-node dnz_state_* gauge_fns and the
    1 Hz-refreshed hot-key share series appear under the plan node id
    and read real values."""
    rng = np.random.default_rng(0)
    batches = []
    for b in range(8):
        rows = 200
        ts = np.sort(T0 + b * 400 + rng.integers(0, 400, rows))
        names = rng.choice(
            [f"s{i}" for i in range(5)], size=rows
        ).astype(object)
        names[: rows // 2] = "hot"
        batches.append(make_batch(ts, names, rng.normal(50, 10, rows)))
    ctx = Context(EngineConfig(min_batch_bucket=256))
    ds = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
    ).window(
        [col("sensor_name")], [F.count(col("reading")).alias("c")], 1000
    )
    ds.collect()
    snap = registry.snapshot()
    win_bytes = [
        k for k in snap
        if k.startswith("dnz_state_bytes") and "WindowExec" in k
    ]
    assert win_bytes and snap[win_bytes[0]] > 0
    assert any(k.startswith("dnz_state_live_keys") for k in snap)
    assert any(
        k.startswith("dnz_state_slots") and 'kind="capacity"' in k
        for k in snap
    )
    hot = {
        k: v for k, v in snap.items()
        if k.startswith("dnz_state_hot_key_share") and 'key="hot"' in k
    }
    assert hot, [k for k in snap if k.startswith("dnz_state_hot")]
    assert max(hot.values()) == pytest.approx(0.5, abs=0.1)
    assert any(k.startswith("dnz_state_skew_factor") for k in snap)


def test_explain_analyze_carries_state_columns(make_batch, registry, capsys):
    ctx = Context(EngineConfig(min_batch_bucket=256))
    rng = np.random.default_rng(0)
    batches = []
    for b in range(8):
        ts = np.sort(T0 + b * 400 + rng.integers(0, 400, 200))
        names = rng.choice([f"s{i}" for i in range(5)], size=200)
        batches.append(make_batch(ts, names, rng.normal(50, 10, 200)))
    text = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
    ).window(
        [col("sensor_name")], [F.count(col("reading")).alias("c")], 1000
    ).explain_analyze()
    assert "state=" in text
    assert "keys" in text


def test_state_snapshot_frozen_after_finish(make_batch, registry):
    ctx = Context(EngineConfig(min_batch_bucket=256))
    rng = np.random.default_rng(0)
    batches = []
    for b in range(6):
        ts = np.sort(T0 + b * 400 + rng.integers(0, 400, 100))
        names = rng.choice(["a", "b"], size=100)
        batches.append(make_batch(ts, names, rng.normal(0, 1, 100)))
    ds = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
    ).window([col("sensor_name")], [F.count(col("reading")).alias("c")], 1000)
    ds.collect()
    handle = ctx._last_doctor
    assert not handle.running
    snap = handle.state_snapshot()
    assert snap["state"] == "finished"
    assert snap["nodes"], snap
    # frozen: identical object on re-read, survives root drop
    assert handle.state_snapshot() is snap


# -- soak telemetry derivation ---------------------------------------------


def test_soak_telemetry_reports_peak_state_and_hot_keys(tmp_path):
    import importlib.util
    from pathlib import Path

    spec = importlib.util.spec_from_file_location(
        "_t_soak", Path(__file__).resolve().parent.parent / "tools" / "soak.py"
    )
    soak = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(soak)

    p = tmp_path / "obs_seg0.jsonl"
    lines = []
    for i in range(4):
        lines.append(json.dumps({
            "event": "obs", "t": 100.0 + i,
            "metrics": {
                'dnz_state_bytes{node="3_SessionWindowExec"}': 1000 * (i + 1),
                'dnz_state_bytes{node="state_backend"}': 500,
                'dnz_state_hot_key_share{key="celebrity",node="3_SessionWindowExec"}': 0.5,
                'dnz_state_hot_key_share{key="minor",node="3_SessionWindowExec"}': 0.01,
            },
        }))
    p.write_text("\n".join(lines) + "\n")
    tele = soak.derive_telemetry([str(p)])
    assert tele["peak_state_bytes"] == 4500
    hot = tele["state_hot_keys"][0]
    assert hot["segment"] == 0
    assert "celebrity" in hot["top_keys"][0]["series"]
    assert hot["top_keys"][0]["share"] == pytest.approx(0.5)


def test_budget_pressure_verdict_on_exact_median_workload(
    registry, monkeypatch
):
    """Satellite acceptance (ISSUE 18): an exact-median workload over a
    FIXED group population grows without bound in values, not keys —
    the old flat per-accumulator estimate was constant there, blinding
    the doctor.  Real ``state_nbytes`` accounting must (a) report
    growing state_bytes while live_keys stays fixed and (b) raise a
    ``state-budget-pressure`` verdict against the udaf node once the
    ring forecast projects budget exhaustion."""
    from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
    from denormalized_tpu.logical.plan import WindowType
    from denormalized_tpu.obs import doctor
    from denormalized_tpu.physical.base import ExecOperator
    from denormalized_tpu.physical.udaf_exec import UdafWindowExec

    monkeypatch.setattr(statewatch, "_SAMPLE_MIN_INTERVAL_S", 0.0)
    in_schema = Schema([
        Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS,
              nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ])

    class _Leaf(ExecOperator):
        schema = in_schema

        def run(self):
            return iter(())

    op = UdafWindowExec(
        _Leaf(), [col("k")], [F.median(col("v")).alias("m")],
        WindowType.TUMBLING, 3_600_000, None,
    )
    handle = doctor.register_query(
        op, config=EngineConfig(state_budget_bytes=2_000_000),
        registry=registry,
    )
    try:
        rng = np.random.default_rng(7)
        bytes_seen, rows_total = [], 0
        snap = None
        for b in range(8):
            rows = 4000
            ts = np.sort(T0 + b * 400 + rng.integers(0, 400, rows))
            ks = np.asarray(
                [f"g{i}" for i in rng.integers(0, 8, rows)], object
            )
            batch = RecordBatch(
                in_schema, [ts, ks, rng.normal(0, 1, rows)]
            )
            list(op._process_batch(batch))
            rows_total += rows
            time.sleep(0.05)
            snap = handle.state_snapshot()
            node = [n for n in snap["nodes"] if n["op"] == "udaf"][0]
            bytes_seen.append(node["state_bytes"])
            assert node["live_keys"] == 8  # fixed groups throughout
        # real accounting: bytes grow with the VALUE population (the
        # flat estimate was constant at fixed groups x aggs)
        assert bytes_seen[-1] > bytes_seen[0]
        assert bytes_seen == sorted(bytes_seen)
        assert bytes_seen[-1] >= 8 * rows_total  # >= raw f64 payload
        kinds = [v["kind"] for v in snap["verdicts"]]
        assert "state-budget-pressure" in kinds, snap["verdicts"]
        # one stateful node: the query-TOTAL projection (node_id None)
        # and the per-node projection cover the same state, and they
        # rank by measured severity — accept whichever fired, preferring
        # the node-attributed one when both did
        v = max(
            (x for x in snap["verdicts"]
             if x["kind"] == "state-budget-pressure"),
            key=lambda x: x.get("node_id") is not None,
        )
        assert v["node_id"] is None or "udaf" in v["node_id"].lower(), v
        assert v["time_to_budget_s"] >= 0.0
    finally:
        handle.finish()
