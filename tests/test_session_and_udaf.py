"""Session windows (reference leaves these todo!()) and Python UDAFs
(reference python/examples/udaf_example.py pattern)."""

import numpy as np

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.udaf import Accumulator
from denormalized_tpu.common.constants import (
    WINDOW_END_COLUMN,
    WINDOW_START_COLUMN,
)
from denormalized_tpu.common.schema import DataType
from denormalized_tpu.sources.memory import MemorySource


def test_session_window_gap_split(make_batch):
    t0 = 1_700_000_000_000
    # key "a": bursts at [0..300] and [2000..2100] (gap 500 splits them)
    # key "b": single burst [100..900] (within-gap steps)
    batches = [
        make_batch(
            [t0, t0 + 150, t0 + 300, t0 + 100, t0 + 500],
            ["a", "a", "a", "b", "b"],
            [1.0, 2.0, 3.0, 10.0, 20.0],
        ),
        make_batch(
            [t0 + 900, t0 + 2000, t0 + 2100, t0 + 9000],
            ["b", "a", "a", "z"],
            [30.0, 4.0, 5.0, 0.0],
        ),
    ]
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
        )
        .session_window(
            ["sensor_name"],
            [F.count(col("reading")).alias("cnt"), F.sum(col("reading")).alias("s")],
            gap_ms=500,
        )
        .collect()
    )
    got = {}
    for i in range(res.num_rows):
        got[
            (res.column("sensor_name")[i], int(res.column(WINDOW_START_COLUMN)[i]))
        ] = (
            int(res.column("cnt")[i]),
            float(res.column("s")[i]),
            int(res.column(WINDOW_END_COLUMN)[i]),
        )
    assert got[("a", t0)] == (3, 6.0, t0 + 300 + 500)
    assert got[("a", t0 + 2000)] == (2, 9.0, t0 + 2100 + 500)
    assert got[("b", t0 + 100)] == (3, 60.0, t0 + 900 + 500)
    assert ("z", t0 + 9000) in got


class WeightedObservation(Accumulator):
    """Stateful UDAF: value weighted by recency rank (order-sensitive state,
    modeled on the reference's udaf_example.py running-sum accumulator)."""

    def __init__(self):
        self.total = 0.0
        self.n = 0

    def update(self, values: np.ndarray):
        self.total += float(values.sum())
        self.n += len(values)

    def merge(self, states):
        self.total += states[0]
        self.n += states[1]

    def state(self):
        return [self.total, self.n]

    def evaluate(self):
        return self.total / self.n if self.n else 0.0


def test_udaf_window(make_batch):
    t0 = 1_700_000_000_000
    batches = [
        make_batch([t0 + 10, t0 + 20], ["a", "b"], [1.0, 10.0]),
        make_batch([t0 + 600, t0 + 2500], ["a", "a"], [3.0, 0.0]),
    ]
    my_mean = F.udaf(WeightedObservation, DataType.FLOAT64, "my_mean")
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
        )
        .window(
            ["sensor_name"],
            [my_mean(col("reading")).alias("m"), F.count(col("reading")).alias("c")],
            1000,
        )
        .collect()
    )
    got = {
        (res.column("sensor_name")[i], int(res.column(WINDOW_START_COLUMN)[i])): (
            float(res.column("m")[i]),
            int(res.column("c")[i]),
        )
        for i in range(res.num_rows)
    }
    assert got[("a", t0)] == (2.0, 2)  # mean(1, 3)
    assert got[("b", t0)] == (10.0, 1)
    assert got[("a", t0 + 2000)] == (0.0, 1)


def test_session_window_with_collection_aggregates():
    """Sessions now carry accumulator aggregates (median/array_agg/user
    UDAFs) alongside the builtin kinds — merging across segments and
    out-of-order bridges included."""
    import numpy as np

    from denormalized_tpu import Context, col
    from denormalized_tpu.api import functions as F
    from denormalized_tpu.common.record_batch import RecordBatch
    from denormalized_tpu.common.schema import DataType, Field, Schema
    from denormalized_tpu.sources.memory import MemorySource

    S = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("k", DataType.STRING, nullable=False),
            Field("v", DataType.FLOAT64),
        ]
    )
    t0 = 1_700_000_000_000

    def kv(ts, ks, vs):
        return RecordBatch(
            S,
            [np.asarray(ts, np.int64), np.asarray(ks, object), np.asarray(vs)],
        )

    batches = [
        kv([t0 + 0, t0 + 100], ["a", "a"], [5.0, 1.0]),
        # out-of-order bridge: arrives later, merges the session downward
        kv([t0 + 50, t0 + 20_000], ["a", "w"], [3.0, 0.0]),
        kv([t0 + 40_000], ["w"], [0.0]),
    ]
    ctx = Context()
    res = (
        ctx.from_source(MemorySource.from_batches(batches, timestamp_column="ts"))
        .session_window(
            ["k"],
            [
                F.median(col("v")).alias("med"),
                F.array_agg(col("v")).alias("arr"),
                F.count(col("v")).alias("c"),
            ],
            5_000,
        )
        .collect()
    )
    rows = {res.column("k")[i]: i for i in range(res.num_rows)}
    i = rows["a"]
    assert int(res.column("c")[i]) == 3
    assert float(res.column("med")[i]) == 3.0
    assert sorted(res.column("arr")[i]) == [1.0, 3.0, 5.0]


def test_session_collection_aggregates_survive_kill_restore(tmp_path):
    """Session accumulator state (array_agg) checkpoints and restores."""
    import numpy as np

    from denormalized_tpu import Context, col
    from denormalized_tpu.api import functions as F
    from denormalized_tpu.api.context import EngineConfig
    from denormalized_tpu.common.record_batch import RecordBatch
    from denormalized_tpu.common.schema import DataType, Field, Schema
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import EndOfStream, Marker
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.sources.memory import MemorySource
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.lsm import close_global_state_backend
    from denormalized_tpu.state.orchestrator import Orchestrator

    S = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("k", DataType.STRING, nullable=False),
            Field("v", DataType.FLOAT64),
        ]
    )
    t0 = 1_700_000_000_000

    def kv(ts, ks, vs):
        return RecordBatch(
            S,
            [np.asarray(ts, np.int64), np.asarray(ks, object), np.asarray(vs)],
        )

    # bursts every 800ms spanning 200ms, gap 300 → sessions close per burst
    rng = np.random.default_rng(9)
    batches = []
    for b in range(10):
        n = 20
        ts = np.sort(t0 + b * 800 + rng.integers(0, 200, n))
        ks = np.asarray([f"s{i % 3}" for i in range(n)], dtype=object)
        batches.append(kv(ts, ks, rng.integers(0, 50, n).astype(np.float64)))

    def pipeline(ctx):
        return ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="ts"),
            name="sacc",
        ).session_window(
            ["k"], [F.array_agg(col("v")).alias("arr")], 300
        )

    def windows(result):
        return {
            (result.column("k")[i], int(result.column("window_start_time")[i])):
            sorted(result.column("arr")[i])
            for i in range(result.num_rows)
        }

    golden = windows(pipeline(Context()).collect())

    def make_cfg(path):
        return EngineConfig(
            checkpoint=path is not None,
            checkpoint_interval_s=9999,
            state_backend_path=path,
        )

    state_dir = str(tmp_path / "state")
    try:
        ctx_a = Context(make_cfg(state_dir))
        root_a = executor.build_physical(
            lp.Sink(pipeline(ctx_a)._plan, CollectSink()), ctx_a
        )
        orch_a = Orchestrator(interval_s=9999)
        coord_a = wire_checkpointing(root_a, ctx_a, orch_a)
        emitted_a = {}
        items_seen = 0
        it = root_a.run()
        for item in it:
            if isinstance(item, RecordBatch):
                emitted_a.update(windows(item))
            if items_seen == 1:
                orch_a.trigger_now()
            if isinstance(item, Marker):
                coord_a.commit(item.epoch)
                break
            items_seen += 1
        it.close()
        close_global_state_backend()

        ctx_b = Context(make_cfg(state_dir))
        root_b = executor.build_physical(
            lp.Sink(pipeline(ctx_b)._plan, CollectSink()), ctx_b
        )
        orch_b = Orchestrator(interval_s=9999)
        coord_b = wire_checkpointing(root_b, ctx_b, orch_b)
        assert coord_b.committed_epoch is not None
        emitted_b = {}
        for item in root_b.run():
            if isinstance(item, RecordBatch):
                emitted_b.update(windows(item))
            if isinstance(item, EndOfStream):
                break
    finally:
        close_global_state_backend()

    combined = dict(emitted_a)
    combined.update(emitted_b)
    assert set(combined) == set(golden)
    for k in golden:
        assert combined[k] == golden[k], (k, combined[k], golden[k])


def test_session_order_sensitive_accumulators_keep_arrival_order():
    """first_value/last_value through session merges must reflect arrival
    order (review repro: the new batch partial was the merge base, flipping
    first and last)."""
    import numpy as np

    from denormalized_tpu import Context, col
    from denormalized_tpu.api import functions as F
    from denormalized_tpu.common.record_batch import RecordBatch
    from denormalized_tpu.common.schema import DataType, Field, Schema
    from denormalized_tpu.sources.memory import MemorySource

    S = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("k", DataType.STRING, nullable=False),
            Field("v", DataType.FLOAT64),
        ]
    )
    t0 = 1_700_000_000_000

    def kv(ts, ks, vs):
        return RecordBatch(
            S,
            [np.asarray(ts, np.int64), np.asarray(ks, object), np.asarray(vs)],
        )

    batches = [
        kv([t0], ["a"], [1.0]),
        kv([t0 + 100], ["a"], [2.0]),
        kv([t0 + 200], ["a"], [3.0]),
        kv([t0 + 20_000], ["w"], [0.0]),
    ]
    ctx = Context()
    res = (
        ctx.from_source(MemorySource.from_batches(batches, timestamp_column="ts"))
        .session_window(
            ["k"],
            [
                F.first_value(col("v")).alias("fv"),
                F.last_value(col("v")).alias("lv"),
                F.array_agg(col("v")).alias("arr"),
            ],
            5_000,
        )
        .collect()
    )
    rows = {res.column("k")[i]: i for i in range(res.num_rows)}
    i = rows["a"]
    assert float(res.column("fv")[i]) == 1.0
    assert float(res.column("lv")[i]) == 3.0
    assert list(res.column("arr")[i]) == [1.0, 2.0, 3.0]
