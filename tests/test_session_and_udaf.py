"""Session windows (reference leaves these todo!()) and Python UDAFs
(reference python/examples/udaf_example.py pattern)."""

import numpy as np

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.udaf import Accumulator
from denormalized_tpu.common.constants import (
    WINDOW_END_COLUMN,
    WINDOW_START_COLUMN,
)
from denormalized_tpu.common.schema import DataType
from denormalized_tpu.sources.memory import MemorySource


def test_session_window_gap_split(make_batch):
    t0 = 1_700_000_000_000
    # key "a": bursts at [0..300] and [2000..2100] (gap 500 splits them)
    # key "b": single burst [100..900] (within-gap steps)
    batches = [
        make_batch(
            [t0, t0 + 150, t0 + 300, t0 + 100, t0 + 500],
            ["a", "a", "a", "b", "b"],
            [1.0, 2.0, 3.0, 10.0, 20.0],
        ),
        make_batch(
            [t0 + 900, t0 + 2000, t0 + 2100, t0 + 9000],
            ["b", "a", "a", "z"],
            [30.0, 4.0, 5.0, 0.0],
        ),
    ]
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
        )
        .session_window(
            ["sensor_name"],
            [F.count(col("reading")).alias("cnt"), F.sum(col("reading")).alias("s")],
            gap_ms=500,
        )
        .collect()
    )
    got = {}
    for i in range(res.num_rows):
        got[
            (res.column("sensor_name")[i], int(res.column(WINDOW_START_COLUMN)[i]))
        ] = (
            int(res.column("cnt")[i]),
            float(res.column("s")[i]),
            int(res.column(WINDOW_END_COLUMN)[i]),
        )
    assert got[("a", t0)] == (3, 6.0, t0 + 300 + 500)
    assert got[("a", t0 + 2000)] == (2, 9.0, t0 + 2100 + 500)
    assert got[("b", t0 + 100)] == (3, 60.0, t0 + 900 + 500)
    assert ("z", t0 + 9000) in got


class WeightedObservation(Accumulator):
    """Stateful UDAF: value weighted by recency rank (order-sensitive state,
    modeled on the reference's udaf_example.py running-sum accumulator)."""

    def __init__(self):
        self.total = 0.0
        self.n = 0

    def update(self, values: np.ndarray):
        self.total += float(values.sum())
        self.n += len(values)

    def merge(self, states):
        self.total += states[0]
        self.n += states[1]

    def state(self):
        return [self.total, self.n]

    def evaluate(self):
        return self.total / self.n if self.n else 0.0


def test_udaf_window(make_batch):
    t0 = 1_700_000_000_000
    batches = [
        make_batch([t0 + 10, t0 + 20], ["a", "b"], [1.0, 10.0]),
        make_batch([t0 + 600, t0 + 2500], ["a", "a"], [3.0, 0.0]),
    ]
    my_mean = F.udaf(WeightedObservation, DataType.FLOAT64, "my_mean")
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
        )
        .window(
            ["sensor_name"],
            [my_mean(col("reading")).alias("m"), F.count(col("reading")).alias("c")],
            1000,
        )
        .collect()
    )
    got = {
        (res.column("sensor_name")[i], int(res.column(WINDOW_START_COLUMN)[i])): (
            float(res.column("m")[i]),
            int(res.column("c")[i]),
        )
        for i in range(res.num_rows)
    }
    assert got[("a", t0)] == (2.0, 2)  # mean(1, 3)
    assert got[("b", t0)] == (10.0, 1)
    assert got[("a", t0 + 2000)] == (0.0, 1)
