"""Live query registration on the shared slice pipeline.

The query-dense serving surface (docs/multi_query.md): queries join and
leave a running :class:`SharedPipeline` MID-STREAM without restarting
the shared operator.  Pins the acceptance contracts:

- a mid-stream joiner WARMS from the slice store's retained partials:
  windows from its first exact window ``j*`` on (including the
  immediately backfilled ones) are byte-identical to an independent
  from-start pipeline folding the same slices;
- a joiner whose residual predicate opens a NEW filter class has no
  retained partials, so its exactness starts past the max ingested
  event time — and is byte-identical to its filtered oracle from there;
- deregistration detaches one cursor and leaves every survivor's
  emissions byte-identical to an undisturbed run;
- unshareable registrations are rejected AT register() with PlanError,
  not on the operator thread;
- kill/restore of a pipeline with a mid-stream joiner AND an already
  departed short-lived query: replaying the same event-time-scheduled
  registration sequence yields a per-query emission union byte-identical
  to an uninterrupted run (cursor adoption by tag, departed-tag
  idempotence).
"""

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.errors import PlanError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.physical.base import Marker
from denormalized_tpu.physical.slice_exec import SubscriberBatch
from denormalized_tpu.runtime.multi_query import SharedPipeline
from denormalized_tpu.sources.memory import MemorySource
from denormalized_tpu.state.checkpoint import wire_checkpointing
from denormalized_tpu.state.lsm import close_global_state_backend
from denormalized_tpu.state.orchestrator import Orchestrator

SCHEMA = Schema(
    [
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ]
)
T0 = 1_700_000_000_000

# no stddev here: a residual member's variance pivot is chosen from the
# SHARED ingest's first batch, its independent oracle's from the
# filtered first batch — numerically equal only to ~1e-12, not byte-
# identical (the documented exclusion; sums/extrema/counts fold exactly)
AGGS = [
    F.count(col("v")).alias("c"),
    F.sum(col("v")).alias("s"),
    F.min(col("v")).alias("mn"),
    F.max(col("v")).alias("mx"),
    F.avg(col("v")).alias("av"),
]
AGG_COLS = ("c", "s", "mn", "mx", "av")


def _batches(seed=31, n_batches=20, rows=300, n_keys=6):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = np.sort(T0 + b * 1000 + rng.integers(0, 1000, rows))
        ks = np.asarray(
            [f"s{i}" for i in rng.integers(0, n_keys, rows)], object
        )
        vs = rng.normal(10.0, 3.0, rows)
        out.append(RecordBatch(SCHEMA, [ts, ks, vs]))
    return out


def _rows_of(batch, acc):
    for i in range(batch.num_rows):
        key = (
            batch.column("k")[i],
            int(batch.column("window_start_time")[i]),
            int(batch.column("window_end_time")[i]),
        )
        acc[key] = tuple(float(batch.column(c)[i]) for c in AGG_COLS)


def _sink(acc):
    return lambda b: _rows_of(b, acc)


def _base(ctx, batches):
    return ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts"),
        name="feed",
    )


def _oracle(batches, L, S, *, flt=None, sort_lane=False):
    """Independent from-start pipeline pinned to the shared group's
    1000ms slice (and, for residual members, its lexsort fold lane)."""
    ctx = Context(
        EngineConfig(
            slice_windows=True,
            slice_unit_ms=1000,
            slice_sort_lane=sort_lane,
        )
    )
    ds = _base(ctx, batches)
    if flt is not None:
        ds = ds.filter(flt)
    out = {}
    for b in ds.window(["k"], AGGS, L, S).stream():
        _rows_of(b, out)
    return out


def _first_exact_start(sp, tag):
    """window-start ms of the joiner's first exact window."""
    root = sp.root
    for q, sub in enumerate(root._subs):
        if sub.tag == tag:
            fe = root._first_exact[q]
            assert fe is not None
            return fe * sub.slide_ms
    raise AssertionError(f"tag {tag} not attached")


# -- live attach ---------------------------------------------------------


def test_live_attach_backfills_exact_windows():
    """A same-filter joiner at T0+8s backfills retained-slice windows
    immediately and every window from its first exact one is
    byte-identical to a from-start oracle."""
    batches = _batches(seed=31)
    got0, got1 = {}, {}
    ctx = Context(EngineConfig())
    base = _base(ctx, batches)
    sp = SharedPipeline(ctx, [(base.window(["k"], AGGS, 3000, 1000), _sink(got0))])
    when = T0 + 8_000
    tag = sp.register(
        base.window(["k"], AGGS, 2000, 1000),
        _sink(got1),
        label="joiner",
        when_ts=when,
    )
    assert tag == 1
    sp.run()

    j_start = _first_exact_start(sp, tag)
    oracle1 = _oracle(batches, 2000, 1000)
    expect1 = {k: v for k, v in oracle1.items() if k[1] >= j_start}
    assert got1 == expect1  # EXACT equality, every float
    # the warm-up actually reached back: some exact windows CLOSED
    # before the join point (served from retained slices, not live feed)
    assert any(k[2] <= when for k in got1)
    # the seed query is byte-identical to its own from-start oracle
    assert got0 == _oracle(batches, 3000, 1000)
    assert sp.root.metrics()["subscribers"] == 2


def test_live_attach_residual_filter_exact_from_attach():
    """A joiner with a strictly stronger predicate opens a fresh filter
    class: no retained partials to warm from, so exactness starts past
    the already-ingested max event time — and from there it is
    byte-identical to its independent filtered oracle (which pins the
    lexsort fold lane, the residual class's store lane)."""
    batches = _batches(seed=32)
    got0, got1 = {}, {}
    ctx = Context(EngineConfig())
    base = _base(ctx, batches)
    sp = SharedPipeline(ctx, [(base.window(["k"], AGGS, 3000, 1000), _sink(got0))])
    when = T0 + 9_000
    tag = sp.register(
        base.filter(col("v") > 12.0).window(["k"], AGGS, 2000, 1000),
        _sink(got1),
        when_ts=when,
    )
    sp.run()

    j_start = _first_exact_start(sp, tag)
    # fresh class: nothing before the attach point can be exact
    assert j_start >= when - 2000
    oracle1 = _oracle(batches, 2000, 1000, flt=col("v") > 12.0, sort_lane=True)
    expect1 = {k: v for k, v in oracle1.items() if k[1] >= j_start}
    assert expect1  # the window after the clamp still has content
    assert got1 == expect1
    assert sp.root.metrics()["filter_classes"] == 2


def test_live_detach_survivor_unaffected():
    batches = _batches(seed=33)
    got0, got1 = {}, {}
    ctx = Context(EngineConfig())
    base = _base(ctx, batches)
    sp = SharedPipeline(
        ctx,
        [
            (base.window(["k"], AGGS, 3000, 1000), _sink(got0)),
            (base.window(["k"], AGGS, 2000, 1000), _sink(got1)),
        ],
    )
    when = T0 + 10_000
    sp.deregister(1, when_ts=when)
    sp.run()

    # survivor: byte-identical to an undisturbed from-start oracle
    assert got0 == _oracle(batches, 3000, 1000)
    # the departed query emitted ONLY up to the leave point
    oracle1 = _oracle(batches, 2000, 1000)
    assert got1
    assert set(got1) < set(oracle1)
    assert all(got1[k] == oracle1[k] for k in got1)
    assert max(k[2] for k in got1) <= when + 2000
    m = sp.root.metrics()
    assert m["subscribers"] == 1


def test_detach_of_base_member_narrows_shared_ingest():
    """ISSUE 17 satellite: when the weakest-predicate (base) member
    deregisters, the shared ingest predicate re-derives from the
    survivors at the next slice boundary — rows only the departed base
    needed stop being ingested, the base filter class's partials are
    pruned — and the survivor stays byte-identical to its from-start
    filtered oracle."""
    batches = _batches(seed=36)

    def run(deregister_base):
        got0, got1 = {}, {}
        ctx = Context(EngineConfig())
        base = _base(ctx, batches)
        sp = SharedPipeline(
            ctx,
            [
                (
                    base.filter(col("v") > 5.0)
                    .window(["k"], AGGS, 3000, 1000),
                    _sink(got0),
                ),
                (
                    base.filter(col("v") > 12.0)
                    .window(["k"], AGGS, 2000, 1000),
                    _sink(got1),
                ),
            ],
        )
        if deregister_base:
            sp.deregister(0, when_ts=T0 + 10_000)
        sp.run()
        return got0, got1, sp.root.metrics()

    got0_c, got1_c, m_c = run(False)  # control: base member stays
    got0, got1, m = run(True)         # base member leaves at +10s

    # the shared subtree's planned FilterExec (v > 5, the base pred)
    # feeds both runs identically; without narrowing every arriving row
    # is ingested, with it the post-departure ingest drops v ∈ (5, 12]
    assert m["rows_in"] == m_c["rows_in"] > 0
    assert m_c["rows_ingested"] == m_c["rows_in"]
    assert m["rows_ingested"] < m_c["rows_ingested"]
    # the base filter class no survivor owns was pruned with its partials
    assert m_c["filter_classes"] == 2
    assert m["filter_classes"] == 1

    # survivor: byte-identical to its from-start filtered oracle in both
    # runs (narrowing never drops a row the survivor's class would keep)
    oracle1 = _oracle(batches, 2000, 1000, flt=col("v") > 12.0, sort_lane=True)
    assert got1 == oracle1
    assert got1_c == oracle1
    # the departed base emitted only up to the leave point, all exact
    oracle0 = _oracle(batches, 3000, 1000, flt=col("v") > 5.0, sort_lane=True)
    assert got0 and set(got0) < set(oracle0)
    assert all(got0[k] == oracle0[k] for k in got0)
    assert max(k[2] for k in got0) <= T0 + 10_000 + 3000
    assert got0_c == oracle0


def test_register_rejects_unshareable():
    batches = _batches(seed=34, n_batches=4)
    ctx = Context(EngineConfig())
    base = _base(ctx, batches)
    seed = base.filter(col("v") > 10.0).window(["k"], AGGS, 3000, 1000)
    sp = SharedPipeline(ctx, [(seed, _sink({}))])
    # different group keys
    with pytest.raises(PlanError, match="source, projection and group"):
        sp.register(base.window([], AGGS, 3000, 1000), _sink({}))
    # WEAKER predicate: the shared (v > 10) ingest cannot widen
    with pytest.raises(PlanError, match="cannot widen"):
        sp.register(
            base.filter(col("v") > 5.0).window(["k"], AGGS, 2000, 1000),
            _sink({}),
        )
    # window that does not tile the group's gcd slice
    with pytest.raises(PlanError, match="tile"):
        sp.register(
            base.filter(col("v") > 10.0).window(["k"], AGGS, 1500, 500),
            _sink({}),
        )
    # a STRONGER implied predicate is accepted
    tag = sp.register(
        base.filter(col("v") > 15.0).window(["k"], AGGS, 2000, 1000),
        _sink({}),
    )
    assert tag >= 1


# -- kill/restore with a live registration schedule ----------------------


def _drive_with_schedule(sp, outs, *, kill_after_committed=None, orch=None,
                         coord=None):
    """Pump sp.root, routing tagged emissions; with a kill budget set,
    trigger ONE epoch once the late joiner (tag 2) starts emitting,
    commit it, keep going for the budget, then stop hard."""
    committed = False
    post_commit = 0
    it = sp.root.run()
    for item in it:
        if isinstance(item, SubscriberBatch):
            acc = outs.get(item.tag)
            if acc is not None:
                _rows_of(item.batch, acc)
            if kill_after_committed is None:
                continue
            if item.tag == 2 and not committed and orch is not None:
                orch.trigger_now()
            if committed:
                post_commit += 1
                if post_commit >= kill_after_committed:
                    it.close()
                    return True
        elif isinstance(item, Marker) and coord is not None:
            coord.commit(item.epoch)
            committed = True
    return committed


def _schedule(sp, base, outs):
    """The replayable registration schedule: a short-lived query that
    joins at +4s and leaves at +9s, and a joiner at +11s that outlives
    the run.  Event-time thresholds make the schedule land at the same
    stream positions on every (re)play."""
    t1 = sp.register(
        base.window(["k"], AGGS, 2000, 2000),
        _sink(outs.setdefault(1, {})),
        when_ts=T0 + 4_000,
    )
    sp.deregister(t1, when_ts=T0 + 9_000)
    t2 = sp.register(
        base.filter(col("v") > 12.0).window(["k"], AGGS, 2000, 1000),
        _sink(outs.setdefault(2, {})),
        when_ts=T0 + 11_000,
    )
    assert (t1, t2) == (1, 2)


def test_kill_restore_with_live_joins_byte_identical(tmp_path):
    """The acceptance scenario: SIGKILL-equivalent mid-epoch stop of a
    shared pipeline AFTER a live join and a completed join+leave, then
    restore + replay of the same registration schedule.  Per query, the
    union of pre-kill and post-restore emissions must be byte-identical
    to an uninterrupted run — the joiner adopts its checkpointed cursor
    by TAG (no spurious backfill), the departed tag replays as a no-op."""
    batches = _batches(seed=35, n_batches=24)
    state_dir = str(tmp_path / "state")

    def make_cfg(**kw):
        return EngineConfig(**kw)

    # golden: the SAME schedule, uninterrupted, no checkpointing
    golden: dict[int, dict] = {0: {}}
    ctx_g = Context(make_cfg())
    base_g = _base(ctx_g, batches)
    sp_g = SharedPipeline(
        ctx_g,
        [(base_g.window(["k"], AGGS, 3000, 1000), _sink(golden[0]))],
    )
    _schedule(sp_g, base_g, golden)
    _drive_with_schedule(sp_g, golden)
    assert golden[1] and golden[2]

    got: dict[int, dict] = {0: {}}
    try:
        # run A: commit one epoch after the late joiner attached, keep
        # emitting past it, then stop hard (mid-epoch progress lost)
        ctx_a = Context(
            make_cfg(
                checkpoint=True,
                checkpoint_interval_s=9999,
                state_backend_path=state_dir,
            )
        )
        base_a = _base(ctx_a, batches)
        sp_a = SharedPipeline(
            ctx_a,
            [(base_a.window(["k"], AGGS, 3000, 1000), _sink(got[0]))],
        )
        _schedule(sp_a, base_a, got)
        orch_a = Orchestrator(interval_s=9999)
        coord_a = wire_checkpointing(sp_a.root, ctx_a, orch_a)
        killed = _drive_with_schedule(
            sp_a, got, kill_after_committed=6, orch=orch_a, coord=coord_a
        )
        assert killed
        # the snapshot recorded the joiner's cursor and the departure
        close_global_state_backend()

        # run B: restore, REPLAY the schedule, drive to completion
        ctx_b = Context(
            make_cfg(
                checkpoint=True,
                checkpoint_interval_s=9999,
                state_backend_path=state_dir,
            )
        )
        base_b = _base(ctx_b, batches)
        sp_b = SharedPipeline(
            ctx_b,
            [(base_b.window(["k"], AGGS, 3000, 1000), _sink(got[0]))],
        )
        _schedule(sp_b, base_b, got)
        orch_b = Orchestrator(interval_s=9999)
        coord_b = wire_checkpointing(sp_b.root, ctx_b, orch_b)
        assert coord_b.committed_epoch is not None
        # the joiner's checkpointed cursor is retained for tag adoption
        assert 2 in sp_b.root._orphans
        assert 1 in sp_b.root._departed
        _drive_with_schedule(sp_b, got)
        # replayed join adopted the cursor — it is attached, no orphan
        assert 2 in {s.tag for s in sp_b.root._subs}
        assert not sp_b.root._orphans
    finally:
        close_global_state_backend()

    for tag in (0, 1, 2):
        assert set(got[tag]) == set(golden[tag]), {
            "tag": tag,
            "missing": sorted(set(golden[tag]) - set(got[tag]))[:4],
            "extra": sorted(set(got[tag]) - set(golden[tag]))[:4],
        }
        for k in golden[tag]:
            assert got[tag][k] == golden[tag][k], (tag, k)
