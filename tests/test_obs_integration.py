"""Engine-level observability integration: per-operator collect_metrics
key sets (stable, documented in docs/observability.md), node-id keying
across checkpoint/restore, the Prometheus endpoint scraped during a
running query, JSONL + Perfetto exporters through EngineConfig, and the
metrics-disabled engine path."""

import json
import urllib.request

import numpy as np
import pytest

from denormalized_tpu import Context, col, obs
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.api.udaf import Accumulator
from denormalized_tpu.common.schema import DataType
from denormalized_tpu.obs.registry import MetricsRegistry
from denormalized_tpu.runtime.tracing import collect_metrics
from denormalized_tpu.sources.memory import MemorySource


@pytest.fixture
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = obs.use_registry(reg)
    yield reg
    obs.use_registry(prev)


T0 = 1_700_000_000_000


def _batches(make_batch, n_batches=8, rows=200, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = np.sort(T0 + b * 400 + rng.integers(0, 400, size=rows))
        names = rng.choice([f"sensor_{i}" for i in range(5)], size=rows)
        vals = rng.normal(50.0, 10.0, size=rows)
        out.append(make_batch(ts, names, vals))
    return out


def _mem(batches):
    return MemorySource.from_batches(
        batches, timestamp_column="occurred_at_ms"
    )


def _by_class(metrics_by_node):
    out = {}
    for node_id, m in metrics_by_node.items():
        cls = node_id.split("_", 1)[1]
        out.setdefault(cls, {}).update(m)
    return out


#: the documented per-operator metric key sets (docs/observability.md
#: compatibility-view section) — changing one is an API break for every
#: consumer of collect_metrics (bench, soak, dashboards), so it must be
#: a conscious diff here
SOURCE_KEYS = {
    "rows_out", "batches_out", "decode_fallback_rows", "salvaged_rows",
}
WINDOW_KEYS = {
    "rows_in", "batches_in", "late_rows", "windows_emitted",
    "device_steps", "partial_merges", "grow_events", "host_prep_s",
    "bytes_h2d", "bytes_d2h", "strategy_resolved",
}
SESSION_KEYS = {
    "rows_in", "sessions_emitted", "late_rows", "salvage_rows_scanned",
}
UDAF_KEYS = {"rows_in", "windows_emitted", "late_rows"}
JOIN_KEYS = {"rows_out", "evicted", "hot_keys", "adaptations"}


def test_collect_metrics_window_pipeline_keys(make_batch, registry):
    ctx = Context(EngineConfig(min_batch_bucket=256))
    ds = ctx.from_source(_mem(_batches(make_batch))).window(
        [col("sensor_name")],
        [F.count(col("reading")).alias("count")],
        1000,
    )
    ds.collect()
    per_class = _by_class(collect_metrics(ctx._last_physical))
    assert set(per_class["SourceExec"]) == SOURCE_KEYS
    assert set(per_class["StreamingWindowExec"]) == WINDOW_KEYS
    assert per_class["StreamingWindowExec"]["rows_in"] == 8 * 200
    # the registry sees the same counts the dict view reports
    c = registry.counter("dnz_op_rows_in_total", op="window")
    assert c.value == 8 * 200


def test_collect_metrics_session_pipeline_keys(make_batch, registry):
    ctx = Context(EngineConfig(min_batch_bucket=256))
    ds = ctx.from_source(_mem(_batches(make_batch))).session_window(
        [col("sensor_name")],
        [F.count(col("reading")).alias("count")],
        300,
    )
    ds.collect()
    per_class = _by_class(collect_metrics(ctx._last_physical))
    assert set(per_class["SessionWindowExec"]) == SESSION_KEYS


def test_collect_metrics_udaf_pipeline_keys(make_batch, registry):
    class Spread(Accumulator):
        def __init__(self):
            self.lo, self.hi = float("inf"), float("-inf")

        def update(self, values):
            if len(values):
                self.lo = min(self.lo, float(values.min()))
                self.hi = max(self.hi, float(values.max()))

        def merge(self, states):
            self.lo = min(self.lo, states[0])
            self.hi = max(self.hi, states[1])

        def state(self):
            return [self.lo, self.hi]

        def evaluate(self):
            return self.hi - self.lo if self.hi >= self.lo else 0.0

    spread = F.udaf(Spread, DataType.FLOAT64, "spread")
    ctx = Context(EngineConfig(min_batch_bucket=256))
    ds = ctx.from_source(_mem(_batches(make_batch))).window(
        [col("sensor_name")],
        [spread(col("reading")).alias("spread")],
        1000,
    )
    ds.collect()
    per_class = _by_class(collect_metrics(ctx._last_physical))
    assert set(per_class["UdafWindowExec"]) == UDAF_KEYS


def test_collect_metrics_join_pipeline_keys(make_batch, registry):
    ctx = Context(EngineConfig(min_batch_bucket=256))
    left = ctx.from_source(
        _mem(_batches(make_batch, seed=1)), name="l"
    ).window(
        [col("sensor_name")], [F.avg(col("reading")).alias("a")], 1000
    )
    right = (
        ctx.from_source(_mem(_batches(make_batch, seed=2)), name="r")
        .window([col("sensor_name")], [F.avg(col("reading")).alias("b")], 1000)
        .with_column_renamed("sensor_name", "rs")
        .with_column_renamed("window_start_time", "rws")
        .with_column_renamed("window_end_time", "rwe")
    )
    ds = left.join(
        right, "inner", ["sensor_name", "window_start_time"], ["rs", "rws"]
    )
    ds.collect()
    per_class = _by_class(collect_metrics(ctx._last_physical))
    assert set(per_class["StreamingJoinExec"]) == JOIN_KEYS
    assert per_class["StreamingJoinExec"]["rows_out"] > 0


def test_node_id_keying_survives_checkpoint_restore(make_batch, tmp_path):
    """collect_metrics keys by the same DFS node ids checkpoints use —
    the keying must come out identical in a restored incarnation of the
    same query, or dashboards lose series continuity across restarts."""
    from denormalized_tpu.state.lsm import close_global_state_backend

    def run_once():
        cfg = EngineConfig(
            min_batch_bucket=256,
            checkpoint=True,
            checkpoint_interval_s=0.05,
            state_backend_path=str(tmp_path / "state"),
        )
        ctx = Context(cfg)
        ds = ctx.from_source(_mem(_batches(make_batch))).window(
            [col("sensor_name")],
            [F.count(col("reading")).alias("count")],
            1000,
        )
        ds.collect()
        keys = set(collect_metrics(ctx._last_physical))
        close_global_state_backend()
        return keys

    keys1 = run_once()
    keys2 = run_once()  # restores from the first run's checkpoint
    assert keys1 == keys2
    assert any("StreamingWindowExec" in k for k in keys1)
    assert any("SourceExec" in k for k in keys1)


def test_prometheus_endpoint_during_running_query(make_batch, registry):
    """Acceptance: a scrape against the opt-in endpoint DURING a running
    query returns every registered instrument in valid exposition
    format."""
    from denormalized_tpu.obs.catalog import INSTRUMENTS

    ctx = Context(EngineConfig(min_batch_bucket=256, prometheus_port=0))
    ds = ctx.from_source(_mem(_batches(make_batch, n_batches=12))).window(
        [col("sensor_name")],
        [F.count(col("reading")).alias("count")],
        1000,
    )
    it = ds.stream()
    got_rows = 0
    try:
        first = next(it)  # query is now mid-stream, exporters live
        got_rows += first.num_rows
        port = ctx._last_exporters.prometheus.port
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=5
        )
        assert body.headers["Content-Type"].startswith("text/plain")
        text = body.read().decode()
    finally:
        for b in it:
            got_rows += b.num_rows
    # all registered instruments present, each with HELP + TYPE
    for name, (kind, _help, *_r) in INSTRUMENTS.items():
        assert f"# HELP {name} " in text, name
        assert f"# TYPE {name} {kind}" in text, name
    # live series from this very query
    assert 'dnz_op_rows_in_total{op="window"}' in text
    assert "dnz_op_batch_ms_bucket" in text
    assert got_rows > 0
    # endpoint is down after the stream finishes (exporters stopped)
    with pytest.raises(Exception):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=1
        )


def test_jsonl_and_perfetto_exporters_via_config(
    make_batch, tmp_path, registry
):
    jsonl_path = tmp_path / "telemetry.jsonl"
    trace_path = tmp_path / "trace.json"
    ctx = Context(EngineConfig(
        min_batch_bucket=256,
        metrics_jsonl_path=str(jsonl_path),
        metrics_jsonl_interval_s=0.05,
        trace_path=str(trace_path),
    ))
    ds = ctx.from_source(_mem(_batches(make_batch))).window(
        [col("sensor_name")],
        [F.count(col("reading")).alias("count")],
        1000,
    )
    try:
        ds.collect()
    finally:
        from denormalized_tpu.obs import spans as obs_spans

        obs_spans.disable_span_recording()
    from denormalized_tpu.obs.jsonl import last_stats, read_stream

    snaps = read_stream(jsonl_path)
    assert snaps, "no telemetry snapshots written"
    rows_in = last_stats(snaps, 'dnz_op_rows_in_total{op="window"}')
    assert rows_in == 8 * 200
    batch_stats = last_stats(snaps, 'dnz_op_batch_ms{op="window"}')
    assert batch_stats["count"] == 8
    # Perfetto trace: valid chrome trace JSON with the engine's spans
    trace = json.loads(trace_path.read_text())
    names = {e["name"] for e in trace["traceEvents"]}
    assert "window.process_batch" in names
    assert all("ts" in e and "ph" in e for e in trace["traceEvents"])


def test_metrics_disabled_engine_runs_clean(make_batch, registry):
    ctx = Context(EngineConfig(min_batch_bucket=256, metrics_enabled=False))
    ds = ctx.from_source(_mem(_batches(make_batch))).window(
        [col("sensor_name")],
        [F.count(col("reading")).alias("count")],
        1000,
    )
    out = ds.collect()
    assert out.num_rows > 0
    # nothing bound: the registry stayed empty, the dict view still works
    assert registry.instruments() == []
    per_class = _by_class(collect_metrics(ctx._last_physical))
    assert per_class["StreamingWindowExec"]["rows_in"] == 8 * 200
    obs.set_enabled(True)


@pytest.mark.slow
def test_metrics_overhead_within_noise(make_batch):
    """Overhead guard (unit-scale twin of bench.py run_obs_overhead):
    default-level metrics must not measurably slow the windowed
    pipeline.  Threshold is deliberately loose — the authoritative gate
    is the bench-scale run against the r5 baseline."""
    import time as _time

    batches = _batches(make_batch, n_batches=40, rows=2000)

    def once(enabled):
        reg = MetricsRegistry(enabled=enabled)
        prev = obs.use_registry(reg)
        try:
            ctx = Context(EngineConfig(
                min_batch_bucket=2048, metrics_enabled=enabled
            ))
            ds = ctx.from_source(_mem(batches)).window(
                [col("sensor_name")],
                [F.count(col("reading")).alias("count")],
                1000,
            )
            t0 = _time.perf_counter()
            ds.collect()
            return _time.perf_counter() - t0
        finally:
            obs.use_registry(prev)

    once(True)  # warm compile caches
    best = {True: float("inf"), False: float("inf")}
    for _ in range(3):
        for enabled in (True, False):
            best[enabled] = min(best[enabled], once(enabled))
    assert best[True] <= best[False] * 1.25, best
