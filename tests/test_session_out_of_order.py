"""Session windows under out-of-order arrival: sessions must stay open until
the watermark passes last+gap, and a bridging segment must merge open
sessions (the review-found defect class)."""

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.sources.memory import MemorySource

SCHEMA = Schema(
    [
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ]
)


def kv(ts, ks, vs):
    return RecordBatch(
        SCHEMA,
        [np.asarray(ts, np.int64), np.asarray(ks, object), np.asarray(vs)],
    )


def run_session(batches, gap_ms):
    ctx = Context()
    return (
        ctx.from_source(MemorySource.from_batches(batches, timestamp_column="ts"))
        .session_window(
            ["k"],
            [F.count(col("v")).alias("cnt"), F.sum(col("v")).alias("s")],
            gap_ms,
        )
        .collect()
    )


def test_out_of_order_does_not_split_session():
    """k@1000 then k@20000 (watermark stays low), then k@5000 arrives: with
    gap 10s all of 1000/5000 belong to one session and 5000 bridges NOTHING
    prematurely — no session may close before the watermark allows."""
    t0 = 1_700_000_000_000
    batches = [
        kv([t0 + 1000, t0 + 2000], ["a", "w"], [1.0, 0.0]),
        kv([t0 + 20_000, t0 + 2100], ["a", "w"], [2.0, 0.0]),  # wm stays 2100
        kv([t0 + 5000, t0 + 2200], ["a", "w"], [4.0, 0.0]),  # out-of-order for a
    ]
    res = run_session(batches, gap_ms=10_000)
    got = {}
    for i in range(res.num_rows):
        k = res.column("k")[i]
        start = int(res.column("window_start_time")[i])
        got.setdefault(k, []).append(
            (start - t0, int(res.column("cnt")[i]), float(res.column("s")[i]))
        )
    # a: [1000, 5000] merge (within 10s); 20000 is beyond 5000+10000? exactly
    # 20000 - 5000 = 15000 > 10000 → separate session
    a = sorted(got["a"])
    assert a == [(1000, 2, 5.0), (20_000, 1, 2.0)]


def test_bridging_segment_merges_open_sessions():
    """Two open sessions [1000] and [4000] (gap 2000 keeps them apart); a
    late-but-not-dropped row at 2500 bridges them into ONE session."""
    t0 = 1_700_000_000_000
    batches = [
        kv([t0 + 1000, t0 + 4000], ["a", "a"], [1.0, 4.0]),
        kv([t0 + 2500], ["a"], [2.5]),
    ]
    res = run_session(batches, gap_ms=2000)
    assert res.num_rows == 1
    assert int(res.column("cnt")[0]) == 3
    assert float(res.column("s")[0]) == 7.5
    assert int(res.column("window_start_time")[0]) == t0 + 1000
    assert int(res.column("window_end_time")[0]) == t0 + 4000 + 2000


def test_session_late_rows_dropped_and_counted():
    t0 = 1_700_000_000_000
    batches = [
        kv([t0 + 100], ["a"], [1.0]),
        kv([t0 + 10_000], ["b"], [1.0]),  # wm → t0+10000, a's session closes
        kv([t0 + 200], ["a"], [99.0]),  # ts+gap=1200 <= wm → late, dropped
    ]
    ctx = Context()
    ds = ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts")
    ).session_window(["k"], [F.sum(col("v")).alias("s")], 1000)
    res = ds.collect()
    by_key = {
        res.column("k")[i]: float(res.column("s")[i]) for i in range(res.num_rows)
    }
    assert by_key["a"] == 1.0  # late 99.0 not included


def test_session_late_row_merging_open_session_is_kept():
    """Flink event-time semantics: gap=10s, open session for `a` with
    last=100s, watermark=105s — a row at ts=90s has ts+gap <= wm but lies
    within gap of the open session, so it merges (the merged session closes
    at 110s) instead of being dropped as a closed singleton."""
    t0 = 1_700_000_000_000
    batches = [
        kv([t0 + 100_000], ["a"], [1.0]),  # open session last=100s
        kv([t0 + 105_000], ["w"], [0.0]),  # wm → 105s (a still open)
        kv([t0 + 90_000, t0 + 106_000], ["a", "w"], [5.0, 0.0]),  # 90s late
        kv([t0 + 125_000], ["w"], [0.0]),  # wm → 125s, a closes
    ]
    res = run_session(batches, gap_ms=10_000)
    by_key = {
        res.column("k")[i]: (
            int(res.column("cnt")[i]),
            float(res.column("s")[i]),
            int(res.column("window_start_time")[i]) - t0,
            int(res.column("window_end_time")[i]) - t0,
        )
        for i in range(res.num_rows)
        if res.column("k")[i] == "a"
    }
    assert by_key["a"] == (2, 6.0, 90_000, 110_000), by_key


def test_session_late_chain_to_open_session_is_kept():
    """A late row that reaches the open session only THROUGH another
    salvaged late row arriving earlier in the same batch is also kept
    (matches row-at-a-time processing in arrival order)."""
    t0 = 1_700_000_000_000
    batches = [
        kv([t0 + 100_000], ["a"], [1.0]),
        kv([t0 + 105_000], ["w"], [0.0]),  # wm → 105s
        # 82s is NOT within 10s of [100s, 100s], but 91s (arriving first)
        # is — after 91s merges, the session spans [91s, 100s] and 82s is
        # within gap of it
        kv([t0 + 91_000, t0 + 82_000, t0 + 106_000], ["a", "a", "w"],
           [5.0, 3.0, 0.0]),
        kv([t0 + 125_000], ["w"], [0.0]),
    ]
    res = run_session(batches, gap_ms=10_000)
    by_key = {
        res.column("k")[i]: (
            int(res.column("cnt")[i]),
            float(res.column("s")[i]),
            int(res.column("window_start_time")[i]) - t0,
        )
        for i in range(res.num_rows)
        if res.column("k")[i] == "a"
    }
    assert by_key["a"] == (3, 9.0, 82_000), by_key


def test_partial_final_non_pow2_mesh(make_batch):
    import jax

    if len(jax.devices()) < 3:
        pytest.skip("needs multi-device CPU platform")
    rng = np.random.default_rng(3)
    t0 = 1_700_000_000_000
    batches = [
        make_batch(
            np.sort(t0 + b * 500 + rng.integers(0, 500, 100)),
            ["x"] * 100,
            rng.normal(0, 1, 100),
        )
        for b in range(5)
    ]
    ctx = Context(EngineConfig(mesh_devices=3, shard_strategy="partial_final"))
    res = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
        )
        .window(["sensor_name"], [F.count(col("reading")).alias("c")], 1000)
        .collect()
    )
    assert sum(int(c) for c in res.column("c")) == 500
