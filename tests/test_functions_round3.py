"""Round-3 function-surface parity: the timestamp family, hashes and
encodings, edit-distance string functions, in_list, the LIST/array
function family over first-class LIST columns, STRUCT constructors,
regexp_match, ranking/offset window functions, and the bivariate
aggregate family (corr/covar/regr_*).

Reference surface: py-denormalized/python/denormalized/datafusion/
functions.py (229 exported names) — the parity test at the bottom pins
the missing-name count to ZERO.
"""

import ast
import math
import re
from pathlib import Path

import numpy as np
import pytest

from denormalized_tpu import Context, col, lit
from denormalized_tpu.api import functions as F
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.sources.memory import MemorySource

S = Schema(
    [
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
        Field("w", DataType.FLOAT64),
    ]
)


def rb(ts, ks, vs, ws=None):
    return RecordBatch(
        S,
        [
            np.asarray(ts, np.int64),
            np.asarray(ks, object),
            np.asarray(vs, np.float64),
            np.asarray(ws if ws is not None else vs, np.float64),
        ],
    )


BATCH = rb(
    [1_700_000_000_000, 1_700_000_061_500, 1_700_003_600_000],
    ["kitten", "flaw", "abc"],
    [1.0, 2.0, 3.0],
    [2.0, 4.0, 7.0],
)

LS = Schema(
    [
        Field("l", DataType.LIST, children=(Field("item", DataType.INT64),)),
        Field("x", DataType.INT64),
    ]
)
LBATCH = RecordBatch(
    LS,
    [
        np.array([[1, 2, 2, 3], [], None], object),
        np.array([10, 20, 30], np.int64),
    ],
)


def ev(expr, batch=BATCH):
    return expr.eval(batch)


# -- string additions ----------------------------------------------------


def test_levenshtein():
    out = ev(F.levenshtein(col("k"), lit("sitting")))
    assert out.tolist() == [3, 7, 7]


def test_find_in_set_overlay_substr_index():
    assert ev(F.find_in_set(col("k"), lit("flaw,abc"))).tolist() == [0, 1, 2]
    assert ev(
        F.overlay(lit("Txxxxas"), lit("hom"), lit(2), lit(4))
    )[0] == "Thomas"
    assert ev(
        F.substr_index(lit("www.apache.org"), lit("."), lit(2))
    )[0] == "www.apache"
    assert ev(
        F.substr_index(lit("www.apache.org"), lit("."), lit(-2))
    )[0] == "apache.org"


def test_bit_length():
    assert ev(F.bit_length(col("k"))).tolist() == [48, 32, 24]


def test_hashes_encode_decode_digest():
    import hashlib

    got = ev(F.sha256(col("k")))[2]
    assert got == hashlib.sha256(b"abc").hexdigest()
    for name in ("sha224", "sha384", "sha512"):
        fn = getattr(F, name)
        assert ev(fn(col("k")))[2] == getattr(hashlib, name)(b"abc").hexdigest()
    assert ev(F.digest(col("k"), lit("md5")))[2] == hashlib.md5(b"abc").hexdigest()
    assert ev(F.encode(col("k"), lit("hex")))[2] == "616263"
    assert ev(F.decode(lit("616263"), lit("hex")))[0] == "abc"
    assert ev(F.decode(F.encode(col("k"), lit("base64")), lit("base64")))[2] == "abc"


def test_uuid_random_rowwise():
    u = ev(F.uuid())
    assert len(set(u)) == 3  # one draw per row, not a broadcast scalar
    r = ev(F.random())
    assert len(set(r.tolist())) == 3
    assert all(0.0 <= x < 1.0 for x in r.tolist())


def test_arrow_typeof():
    assert ev(F.arrow_typeof(col("v")))[0] == "Float64"
    assert F.arrow_typeof(col("l")).eval(LBATCH)[0] == "List"


def test_in_list():
    out = ev(F.in_list(col("k"), ["abc", "zzz"]))
    assert out.tolist() == [False, False, True]
    neg = ev(F.in_list(col("k"), ["abc"], negated=True))
    assert neg.tolist() == [True, True, False]


# -- math additions ------------------------------------------------------


def test_math_additions():
    assert ev(F.cot(lit(1.0)))[0] == pytest.approx(1 / math.tan(1.0))
    assert ev(F.acosh(lit(2.0)))[0] == pytest.approx(math.acosh(2.0))
    assert ev(F.asinh(lit(2.0)))[0] == pytest.approx(math.asinh(2.0))
    assert ev(F.atanh(lit(0.5)))[0] == pytest.approx(math.atanh(0.5))
    assert ev(F.factorial(lit(6)))[0] == 720
    assert ev(F.gcd(lit(12), lit(18)))[0] == 6
    assert ev(F.lcm(lit(4), lit(6)))[0] == 12
    assert ev(F.iszero(col("v"))).tolist() == [False, False, False]


# -- timestamp family ----------------------------------------------------


def test_timestamp_family():
    # numeric to_timestamp interprets seconds (datafusion semantics)
    assert ev(F.to_timestamp(lit(1_700_000_000)))[0] == 1_700_000_000_000
    assert ev(F.to_timestamp_seconds(lit(1_700_000_000)))[0] == 1_700_000_000_000
    assert ev(F.to_timestamp_micros(lit(1_700_000_000_123_456)))[0] == (
        1_700_000_000_123
    )
    assert ev(F.to_timestamp_nanos(lit(1.7e18)))[0] == 1_700_000_000_000
    # strings parse ISO or via chrono-style formatters
    assert ev(F.to_timestamp(lit("2023-11-14T22:13:20")))[0] == 1_700_000_000_000
    assert ev(
        F.to_timestamp(lit("14/11/2023 22:13:20"), lit("%d/%m/%Y %H:%M:%S"))
    )[0] == 1_700_000_000_000
    # ts column (epoch ms) -> unix seconds
    assert ev(F.to_unixtime(col("ts"))).tolist() == [
        1_700_000_000, 1_700_000_061, 1_700_003_600,
    ]
    assert ev(F.from_unixtime(lit(1_700_000_000)))[0] == 1_700_000_000_000
    assert ev(F.make_date(lit(2023), lit(11), lit(14)))[0] == 1_699_920_000_000
    # datepart/datetrunc aliases agree with date_part/date_trunc
    assert (
        ev(F.datepart("minute", col("ts"))).tolist()
        == ev(F.date_part("minute", col("ts"))).tolist()
    )
    assert (
        ev(F.datetrunc("hour", col("ts"))).tolist()
        == ev(F.date_trunc("hour", col("ts"))).tolist()
    )
    today = ev(F.current_date())[0]
    assert today % 86_400_000 == 0
    assert 0 <= ev(F.current_time())[0] < 86_400_000


# -- LIST family ---------------------------------------------------------


def le(expr):
    return expr.eval(LBATCH)


def test_array_basics():
    assert le(F.array_length(col("l"))).tolist() == [4, 0, None]
    assert le(F.array_element(col("l"), lit(2))).tolist() == [2, None, None]
    assert le(F.array_element(col("l"), lit(-1))).tolist() == [3, None, None]
    assert le(F.array_ndims(col("l"))).tolist() == [1, 1, None]
    assert le(F.array_dims(col("l"))).tolist() == [[4], [0], None]


def test_array_mutators():
    assert le(F.array_append(col("l"), lit(9))).tolist() == [
        [1, 2, 2, 3, 9], [9], None,
    ]
    assert le(F.array_prepend(lit(0), col("l"))).tolist() == [
        [0, 1, 2, 2, 3], [0], None,
    ]
    assert le(F.array_pop_back(col("l"))).tolist() == [[1, 2, 2], [], None]
    assert le(F.array_pop_front(col("l"))).tolist() == [[2, 2, 3], [], None]
    assert le(F.array_remove(col("l"), lit(2))).tolist() == [[1, 2, 3], [], None]
    assert le(F.array_remove_all(col("l"), lit(2))).tolist() == [[1, 3], [], None]
    assert le(F.array_remove_n(col("l"), lit(2), lit(1))).tolist() == [
        [1, 2, 3], [], None,
    ]
    assert le(F.array_replace(col("l"), lit(2), lit(9))).tolist() == [
        [1, 9, 2, 3], [], None,
    ]
    assert le(F.array_replace_all(col("l"), lit(2), lit(9))).tolist() == [
        [1, 9, 9, 3], [], None,
    ]
    assert le(F.array_resize(col("l"), lit(2))).tolist() == [[1, 2], [None, None], None]
    assert le(F.array_repeat(col("x"), lit(2))).tolist() == [
        [10, 10], [20, 20], [30, 30],
    ]


def test_array_search_sets():
    assert le(F.array_has(col("l"), lit(2))).tolist() == [True, False, None]
    assert le(F.array_position(col("l"), lit(2))).tolist() == [2, None, None]
    assert le(F.array_position(col("l"), lit(2), 3)).tolist() == [3, None, None]
    assert le(F.array_positions(col("l"), lit(2))).tolist() == [[2, 3], [], None]
    two = F.make_array(lit(2), lit(9))
    assert le(F.array_has_any(col("l"), two)).tolist() == [True, False, None]
    assert le(F.array_has_all(col("l"), two)).tolist() == [False, False, None]
    assert le(F.array_intersect(col("l"), two)).tolist() == [[2], [], None]
    assert le(F.array_union(col("l"), two)).tolist() == [
        [1, 2, 3, 9], [2, 9], None,
    ]
    assert le(F.array_except(col("l"), two)).tolist() == [[1, 3], [], None]
    assert le(F.array_distinct(col("l"))).tolist() == [[1, 2, 3], [], None]


def test_array_slice_sort_join():
    assert le(F.array_slice(col("l"), lit(2), lit(3))).tolist() == [
        [2, 2], [], None,
    ]
    assert le(F.array_slice(col("l"), lit(-2), lit(-1))).tolist() == [
        [2, 3], [], None,
    ]
    assert le(F.array_sort(col("l"), descending=True)).tolist() == [
        [3, 2, 2, 1], [], None,
    ]
    assert le(F.array_to_string(col("l"), lit("-"))).tolist() == [
        "1-2-2-3", "", None,
    ]
    assert le(F.array_join(col("l"), lit(","))).tolist() == [
        "1,2,2,3", "", None,
    ]


def test_array_constructors():
    assert le(F.make_array(col("x"), lit(1))).tolist() == [
        [10, 1], [20, 1], [30, 1],
    ]
    assert le(F.range(lit(1), lit(7), lit(2)))[0] == [1, 3, 5]
    assert le(F.array_concat(col("l"), col("l"))).tolist() == [
        [1, 2, 2, 3, 1, 2, 2, 3], [], None,
    ]
    nested = F.make_array(col("l"), col("l"))
    assert le(F.flatten(nested))[0] == [1, 2, 2, 3, 1, 2, 2, 3]
    # row 3's inner list is NULL -> [None, None] is 1-dimensional
    assert le(F.array_ndims(nested)).tolist() == [2, 2, 1]


def test_list_aliases_are_same():
    assert le(F.list_length(col("l"))).tolist() == [4, 0, None]
    assert le(F.list_element(col("l"), lit(1))).tolist() == [1, None, None]
    assert le(F.list_sort(col("l"))).tolist() == [[1, 2, 2, 3], [], None]
    assert le(F.list_to_string(col("l"), lit("."))).tolist() == [
        "1.2.2.3", "", None,
    ]


def test_list_out_field_tracks_element_type():
    f = F.array_distinct(col("l")).out_field(LS)
    assert f.dtype is DataType.LIST
    assert f.children[0].dtype is DataType.INT64
    assert F.array_element(col("l"), lit(1)).out_field(LS).dtype is DataType.INT64
    assert F.array_length(col("l")).out_field(LS).dtype is DataType.INT64


def test_regexp_match():
    sch = Schema([Field("s", DataType.STRING)])
    b = RecordBatch(sch, [np.array(["kitten", "dog", None], object)])
    out = F.regexp_match(col("s"), lit("k(.t)t")).eval(b)
    assert out.tolist() == [["it"], None, None]
    whole = F.regexp_match(col("s"), lit("d.g")).eval(b)
    assert whole.tolist() == [None, ["dog"], None]


def test_struct_constructors():
    s = ev(F.struct(col("v"), col("k")))
    assert s[0] == {"c0": 1.0, "c1": "kitten"}
    ns = ev(F.named_struct("a", col("v"), "b", col("k")))
    assert ns[1] == {"a": 2.0, "b": "flaw"}
    pairs = ev(F.named_struct([("a", col("v")), ("b", col("k"))]))
    assert pairs[2] == {"a": 3.0, "b": "abc"}
    f = F.struct(col("v"), col("k")).out_field(S)
    assert f.dtype is DataType.STRUCT
    assert [c.dtype for c in f.children] == [DataType.FLOAT64, DataType.STRING]


# -- ranking / offset window functions ------------------------------------


def test_window_functions_ranking():
    sch = Schema([Field("g", DataType.STRING), Field("x", DataType.FLOAT64)])
    b = RecordBatch(
        sch,
        [
            np.array(["a", "a", "a", "b", "b", "a"], object),
            np.array([3.0, 1.0, 2.0, 5.0, 5.0, 2.0]),
        ],
    )
    pb, ob = [col("g")], [F.order_by(col("x"))]
    assert F.row_number(pb, ob).eval(b).tolist() == [4, 1, 2, 1, 2, 3]
    assert F.rank(pb, ob).eval(b).tolist() == [4, 1, 2, 1, 1, 2]
    assert F.dense_rank(pb, ob).eval(b).tolist() == [3, 1, 2, 1, 1, 2]
    pr = F.percent_rank(pb, ob).eval(b)
    assert pr.tolist() == pytest.approx([1.0, 0.0, 1 / 3, 0.0, 0.0, 1 / 3])
    cd = F.cume_dist(pb, ob).eval(b)
    assert cd.tolist() == pytest.approx([1.0, 0.25, 0.75, 1.0, 1.0, 0.75])
    assert F.ntile(2, pb, ob).eval(b).tolist() == [2, 1, 1, 1, 2, 2]
    # descending order flips rank 1 to the max
    desc = F.rank(pb, [F.order_by(col("x"), ascending=False)]).eval(b)
    assert desc.tolist() == [1, 4, 2, 1, 1, 2]
    # window() by-name constructor matches the direct form
    assert F.window("rank", [], pb, ob).eval(b).tolist() == [4, 1, 2, 1, 1, 2]


def test_window_functions_offsets():
    sch = Schema([Field("x", DataType.FLOAT64)])
    b = RecordBatch(sch, [np.array([10.0, 20.0, 30.0])])
    assert F.lag(col("x"), 1, -1.0).eval(b).tolist() == [-1.0, 10.0, 20.0]
    assert F.lead(col("x"), 1).eval(b).tolist() == [20.0, 30.0, None]
    assert F.lead(col("x"), 2, 0.0).eval(b).tolist() == [30.0, 0.0, 0.0]


# -- aggregate additions (through a real windowed stream) -----------------


def window_once(aggs, rows=200, seed=3):
    rng = np.random.default_rng(seed)
    ts = 1_700_000_000_000 + np.sort(rng.integers(0, 3000, rows))
    ks = np.array(["a", "b"], object)[rng.integers(0, 2, rows)]
    x = rng.normal(10, 3, rows)
    y = 2.0 * x + rng.normal(0, 1, rows)
    sch = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("k", DataType.STRING, nullable=False),
            Field("v", DataType.FLOAT64),
            Field("w", DataType.FLOAT64),
        ]
    )
    batches = [RecordBatch(sch, [ts, ks, y, x])]
    ctx = Context()
    src = MemorySource.from_batches(batches, timestamp_column="ts")
    out = ctx.from_source(src).window(["k"], aggs, 1000).collect()
    rowmap = {}
    for i in range(out.num_rows):
        key = (
            int(np.asarray(out.column("window_start_time"))[i]),
            str(np.asarray(out.column("k"))[i]),
        )
        rowmap[key] = {
            f.name: np.asarray(out.column(f.name))[i] for f in out.schema.fields
        }
    return (ts, ks, y, x), rowmap


def test_bivariate_aggregates_vs_numpy():
    (ts, ks, y, x), rows = window_once(
        [
            F.corr(col("v"), col("w")).alias("corr"),
            F.covar_samp(col("v"), col("w")).alias("cov"),
            F.covar_pop(col("v"), col("w")).alias("covp"),
            F.regr_slope(col("v"), col("w")).alias("slope"),
            F.regr_intercept(col("v"), col("w")).alias("icept"),
            F.regr_r2(col("v"), col("w")).alias("r2"),
            F.regr_count(col("v"), col("w")).alias("n"),
        ]
    )
    for (ws, key), got in rows.items():
        m = (ts // 1000 * 1000 == ws) & (ks == key)
        yy, xx = y[m], x[m]
        if len(xx) < 3:
            continue
        assert got["n"] == len(xx)
        assert got["corr"] == pytest.approx(np.corrcoef(xx, yy)[0, 1], rel=1e-9)
        assert got["cov"] == pytest.approx(np.cov(xx, yy, ddof=1)[0, 1], rel=1e-9)
        assert got["covp"] == pytest.approx(np.cov(xx, yy, ddof=0)[0, 1], rel=1e-9)
        slope, icept = np.polyfit(xx, yy, 1)
        assert got["slope"] == pytest.approx(slope, rel=1e-6)
        assert got["icept"] == pytest.approx(icept, rel=1e-6)
        assert got["r2"] == pytest.approx(
            np.corrcoef(xx, yy)[0, 1] ** 2, rel=1e-9
        )


def test_bit_bool_string_nth_aggregates():
    sch = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("k", DataType.STRING, nullable=False),
            Field("i", DataType.INT64),
            Field("b", DataType.BOOL),
        ]
    )
    ts = np.array([1_700_000_000_000 + i for i in range(6)], np.int64)
    batches = [
        RecordBatch(
            sch,
            [
                ts,
                np.array(["a"] * 6, object),
                np.array([12, 10, 7, 5, 3, 9], np.int64),
                np.array([True, True, False, True, True, True]),
            ],
        )
    ]
    ctx = Context()
    out = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="ts")
        )
        .window(
            ["k"],
            [
                F.bit_and(col("i")).alias("band"),
                F.bit_or(col("i")).alias("bor"),
                F.bit_xor(col("i")).alias("bxor"),
                F.bool_and(col("b")).alias("ball"),
                F.bool_or(col("b")).alias("bany"),
                F.string_agg(col("k"), "|").alias("sagg"),
                F.nth_value(col("i"), 3).alias("third"),
                F.count_star().alias("n"),
                F.mean(col("i")).alias("m"),
                F.var_sample(col("i")).alias("vs"),
            ],
            1000,
        )
        .collect()
    )
    assert out.num_rows == 1
    row = {f.name: np.asarray(out.column(f.name))[0] for f in out.schema.fields}
    vals = [12, 10, 7, 5, 3, 9]
    band = bor = bxor = None
    for v in vals:
        band = v if band is None else band & v
        bor = v if bor is None else bor | v
        bxor = v if bxor is None else bxor ^ v
    assert row["band"] == band and row["bor"] == bor and row["bxor"] == bxor
    assert not row["ball"] and row["bany"]
    assert row["sagg"] == "|".join(["a"] * 6)
    assert row["third"] == 7
    assert row["n"] == 6
    assert row["m"] == pytest.approx(np.mean(vals))
    assert row["vs"] == pytest.approx(np.var(vals, ddof=1))


def test_weighted_percentile():
    sch = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("k", DataType.STRING, nullable=False),
            Field("v", DataType.FLOAT64),
            Field("w", DataType.FLOAT64),
        ]
    )
    ts = np.array([1_700_000_000_000 + i for i in range(4)], np.int64)
    batches = [
        RecordBatch(
            sch,
            [
                ts,
                np.array(["a"] * 4, object),
                np.array([1.0, 2.0, 3.0, 4.0]),
                np.array([1.0, 1.0, 1.0, 100.0]),
            ],
        )
    ]
    ctx = Context()
    out = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="ts")
        )
        .window(
            ["k"],
            [
                F.approx_percentile_cont_with_weight(
                    col("v"), col("w"), 0.5
                ).alias("wp")
            ],
            1000,
        )
        .collect()
    )
    # weight mass concentrates on 4.0 -> weighted median pulls to 4
    assert np.asarray(out.column("wp"))[0] == pytest.approx(4.0, abs=0.1)


def test_list_column_through_pipeline():
    """array_agg emits a LIST column; array functions project over it and
    a filter consumes a derived INT64 — LIST as a first-class citizen."""
    sch = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("k", DataType.STRING, nullable=False),
            Field("v", DataType.FLOAT64),
        ]
    )
    rng = np.random.default_rng(0)
    ts = 1_700_000_000_000 + np.sort(rng.integers(0, 2000, 60))
    ks = np.array(["a", "b"], object)[rng.integers(0, 2, 60)]
    vs = rng.integers(0, 5, 60).astype(np.float64)
    batches = [RecordBatch(sch, [ts, ks, vs])]
    ctx = Context()
    ds = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="ts")
        )
        .window(["k"], [F.array_agg(col("v")).alias("vals")], 1000)
        .with_column("n", F.array_length(col("vals")))
        .with_column("uniq", F.array_distinct(col("vals")))
        .with_column("n_uniq", F.array_length(col("uniq")))
        .with_column("txt", F.array_to_string(col("uniq"), lit(",")))
        .filter(col("n") > 0)
    )
    out = ds.collect()
    assert out.num_rows >= 2
    n = np.asarray(out.column("n"))
    nu = np.asarray(out.column("n_uniq"))
    vals = np.asarray(out.column("vals"), dtype=object)
    txt = np.asarray(out.column("txt"), dtype=object)
    for i in range(out.num_rows):
        assert n[i] == len(vals[i])
        assert nu[i] == len(set(vals[i]))
        assert txt[i].count(",") == nu[i] - 1
    # schema carries LIST through the projections
    assert out.schema.field("uniq").dtype is DataType.LIST


# -- full-surface parity --------------------------------------------------


def test_reference_export_parity_zero_missing():
    ref = Path(
        "/root/reference/py-denormalized/python/denormalized/datafusion/"
        "functions.py"
    )
    if not ref.exists():
        pytest.skip("reference not available")
    src = ref.read_text()
    allist = ast.literal_eval(
        "[" + re.findall(r"^__all__\s*=\s*\[(.*?)\]", src, re.S | re.M)[0] + "]"
    )
    missing = [n for n in allist if not hasattr(F, n)]
    assert missing == [], f"missing {len(missing)} reference exports"
