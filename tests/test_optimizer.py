"""Logical optimizer: projection pruning, project merging, filter pushdown —
plan-shape assertions plus end-to-end equivalence with the optimizer off."""

import numpy as np

from denormalized_tpu import Context, col, lit
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.logical import plan as lp
from denormalized_tpu.logical.optimizer import optimize
from denormalized_tpu.sources.memory import MemorySource

WIDE = Schema(
    [
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("a", DataType.FLOAT64),
        Field("b", DataType.FLOAT64),
        Field("c", DataType.FLOAT64),
        Field("unused1", DataType.STRING),
        Field("unused2", DataType.FLOAT64),
    ]
)


def _batches(n_batches=4, rows=256):
    rng = np.random.default_rng(0)
    t0 = 1_700_000_000_000
    out = []
    for b in range(n_batches):
        ts = np.sort(t0 + b * 400 + rng.integers(0, 400, rows))
        out.append(
            RecordBatch(
                WIDE,
                [
                    ts,
                    np.asarray([f"g{i % 5}" for i in range(rows)], object),
                    rng.normal(10, 2, rows),
                    rng.normal(0, 1, rows),
                    rng.normal(5, 1, rows),
                    np.asarray(["pad"] * rows, object),
                    rng.normal(0, 1, rows),
                ],
            )
        )
    return out


def _ds(ctx):
    return ctx.from_source(
        MemorySource.from_batches(_batches(), timestamp_column="ts"),
        name="wide",
    )


def _find(plan, cls):
    found = []

    def walk(n):
        if isinstance(n, cls):
            found.append(n)
        for c in n.children:
            walk(c)

    walk(plan)
    return found


def test_projection_pruning_narrows_scan():
    ctx = Context()
    ds = _ds(ctx).window(["k"], [F.avg(col("a")).alias("m")], 1000)
    opt = optimize(ds._plan)
    # a pruning Project sits directly above the Scan with only ts/k/a (+ts)
    scans = _find(opt, lp.Scan)
    assert len(scans) == 1
    projects = [
        p for p in _find(opt, lp.Project) if isinstance(p.input, lp.Scan)
    ]
    assert projects, opt.display()
    names = set(projects[0].schema.names)
    assert "unused1" not in names and "unused2" not in names
    assert {"k", "a"} <= names


def test_merge_projects_collapses_with_column_chain():
    ctx = Context()
    ds = (
        _ds(ctx)
        .with_column("x", col("a") + 1.0)
        .with_column("y", col("x") * 2.0)
        .with_column("z", col("y") - col("b"))
    )
    opt = optimize(ds._plan)
    projs = _find(opt, lp.Project)
    # the three stacked with_column projections merge into one
    stacked = [p for p in projs if isinstance(p.input, lp.Project)]
    assert not stacked, opt.display()


def test_filter_pushdown_below_projection():
    ctx = Context()
    ds = (
        _ds(ctx)
        .with_column("x", col("a") * 2.0)
        .filter(col("x") > 20.0)
    )
    opt = optimize(ds._plan)
    # the filter now sits beneath the projection (predicate rewritten)
    filts = _find(opt, lp.Filter)
    assert len(filts) == 1
    projs = _find(opt, lp.Project)
    assert any(isinstance(p.input, lp.Filter) for p in projs), opt.display()
    # adjacent filters fuse
    ds2 = _ds(ctx).filter(col("a") > 0).filter(col("b") < 1)
    opt2 = optimize(ds2._plan)
    assert len(_find(opt2, lp.Filter)) == 1, opt2.display()


def test_projection_narrowing_through_with_column_chain():
    """Columns nobody above reads are dropped from intermediate
    projections, not carried to the top of the plan."""
    ctx = Context()
    ds = (
        _ds(ctx)
        .with_column("x", col("a") * 2.0)
        .window(["k"], [F.avg(col("x")).alias("m")], 1000)
    )
    opt = optimize(ds._plan)
    win = _find(opt, lp.StreamingWindow)[0]
    names = set(win.input.schema.names)
    assert "unused1" not in names and "unused2" not in names, opt.display()
    assert "b" not in names and "c" not in names, opt.display()
    # results unchanged
    res_on = ds.collect()
    ctx_off = Context(EngineConfig(optimizer=False))
    ds_off = (
        _ds(ctx_off)
        .with_column("x", col("a") * 2.0)
        .window(["k"], [F.avg(col("x")).alias("m")], 1000)
    )
    res_off = ds_off.collect()

    def key(r):
        return {
            (r.column("k")[i], int(r.column("window_start_time")[i])): round(
                float(r.column("m")[i]), 6
            )
            for i in range(r.num_rows)
        }

    assert key(res_on) == key(res_off) and res_on.num_rows > 0


def test_is_null_filter_not_pushed_through_projection():
    """IsNull on a projected column checks the validity MASK; pushing the
    substituted predicate would turn it into a value/NaN check (review
    repro: mask-null row with fill value 0.0 vanished from results)."""
    batch = RecordBatch(
        WIDE,
        [
            np.array([1_700_000_000_000 + i for i in range(4)], np.int64),
            np.asarray(list("abcd"), object),
            np.array([1.0, 0.0, 3.0, 4.0]),
            np.zeros(4),
            np.zeros(4),
            np.asarray(["p"] * 4, object),
            np.zeros(4),
        ],
        masks=[None, None, np.array([True, False, True, True]), None, None,
               None, None],
    )
    for on in (True, False):
        ctx = Context(EngineConfig(optimizer=on))
        res = (
            ctx.from_source(
                MemorySource.from_batches([batch], timestamp_column="ts"),
                name="m",
            )
            .with_column("x", col("a"))
            .filter(col("x").is_null())
            .collect()
        )
        assert res.num_rows == 1, (on, res.num_rows)
        assert res.column("k")[0] == "b"


def test_udf_never_duplicated_by_optimizer():
    """A projected UDF column referenced by a filter must be evaluated
    exactly once per input batch — pushing or inlining it would re-run it."""
    calls = {"n": 0}

    def expensive(a):
        calls["n"] += 1
        return a * 2.0

    myudf = F.udf(expensive, DataType.FLOAT64, "expensive")
    ctx = Context()
    res = (
        _ds(ctx)
        .with_column("x", myudf(col("a")))
        .filter(col("x") > 0.0)
        .select("k", "x")
        .collect()
    )
    assert res.num_rows > 0
    # one call per input batch (4 batches), not two
    assert calls["n"] == 4, calls


def _run(optimizer_on: bool):
    ctx = Context(EngineConfig(optimizer=optimizer_on))
    ds = (
        _ds(ctx)
        .with_column("x", col("a") * 2.0)
        .with_column("y", F.round(col("x") + col("c"), lit(2)))
        .filter(col("y") > 20.0)
        .window(
            ["k"],
            [
                F.count(col("y")).alias("n"),
                F.sum(col("y")).alias("s"),
                F.min(col("b")).alias("mb"),
            ],
            1000,
        )
        .filter(col("n") > 0)
        .select("k", "n", "s", "mb", "window_start_time")
    )
    res = ds.collect()
    return {
        (res.column("k")[i], int(res.column("window_start_time")[i])): (
            int(res.column("n")[i]),
            round(float(res.column("s")[i]), 4),
            round(float(res.column("mb")[i]), 6),
        )
        for i in range(res.num_rows)
    }


def test_optimized_matches_unoptimized_end_to_end():
    on = _run(True)
    off = _run(False)
    assert on == off and len(on) > 0


def test_join_plans_survive_optimization():
    ctx = Context()
    left = _ds(ctx).window(["k"], [F.avg(col("a")).alias("la")], 1000)
    right = (
        ctx.from_source(
            MemorySource.from_batches(_batches(), timestamp_column="ts"),
            name="wide2",
        )
        .window(["k"], [F.avg(col("b")).alias("rb")], 1000)
        .with_column_renamed("k", "rk")
        .with_column_renamed("window_start_time", "rws")
        .with_column_renamed("window_end_time", "rwe")
    )
    joined = left.join(right, "inner", ["k", "window_start_time"], ["rk", "rws"])
    ctx_off = Context(EngineConfig(optimizer=False))
    res_on = joined.collect()

    # rebuild the identical pipeline with the optimizer off
    left2 = _ds(ctx_off).window(["k"], [F.avg(col("a")).alias("la")], 1000)
    right2 = (
        ctx_off.from_source(
            MemorySource.from_batches(_batches(), timestamp_column="ts"),
            name="wide2",
        )
        .window(["k"], [F.avg(col("b")).alias("rb")], 1000)
        .with_column_renamed("k", "rk")
        .with_column_renamed("window_start_time", "rws")
        .with_column_renamed("window_end_time", "rwe")
    )
    res_off = left2.join(
        right2, "inner", ["k", "window_start_time"], ["rk", "rws"]
    ).collect()

    def keyset(r):
        return {
            (r.column("k")[i], int(r.column("window_start_time")[i]),
             round(float(r.column("la")[i]), 4), round(float(r.column("rb")[i]), 4))
            for i in range(r.num_rows)
        }

    assert keyset(res_on) == keyset(res_off) and res_on.num_rows > 0
