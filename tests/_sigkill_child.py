"""Child entry point for the true process-level SIGKILL kill/restore test
(tests/test_checkpoint.py::test_sigkill_process_kill_and_restore).

Runs a CHECKPOINTED Kafka pipeline (from_topic → 500ms tumbling count/sum
by key) against the parent's mock broker and appends one flushed JSON line
per emitted window row — so a SIGKILL loses at most one torn line.  The
parent kills this process mid-stream with a real ``os.kill(pid, SIGKILL)``
(no ``finally`` blocks, no generator close — unlike the in-process
variants above it in the test file), then starts a second instance on the
same state path to exercise the restore path the reference implements at
kafka_stream_read.rs:110-140 (offset restore-by-seek) and
grouped_window_agg_stream.rs:160-211 (frame restore).

Config via env: KR_BROKER, KR_TOPIC, KR_STATE, KR_OUT, KR_INTERVAL, and
optionally KR_MAX_BATCH_ROWS — when set, the source is built through
``KafkaTopicBuilder.with_option("max.batch.rows", …)`` instead of
``from_topic``, so oversized fetches are sliced and checkpoint barriers
can land between slices (the mid-split kill/restore test).
"""

import json
import os


def main() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    from denormalized_tpu import Context, col
    from denormalized_tpu.api import functions as F
    from denormalized_tpu.api.context import EngineConfig
    from denormalized_tpu.common.constants import WINDOW_START_COLUMN

    cfg = EngineConfig(
        checkpoint=True,
        checkpoint_interval_s=float(os.environ["KR_INTERVAL"]),
        state_backend_path=os.environ["KR_STATE"],
        min_batch_bucket=1024,
        emit_on_close=False,
    )
    ctx = Context(cfg)
    mbr = os.environ.get("KR_MAX_BATCH_ROWS")
    if mbr:
        # builder path: the mid-split variant bounds fetch slices so
        # checkpoint barriers land BETWEEN slices of one fetch
        from denormalized_tpu.sources.kafka import KafkaTopicBuilder

        stream = ctx.from_source(
            KafkaTopicBuilder(os.environ["KR_BROKER"])
            .with_topic(os.environ["KR_TOPIC"])
            .infer_schema_from_json('{"ts": 1, "k": "a", "v": 1.0}')
            .with_timestamp_column("ts")
            .with_option("max.batch.rows", mbr)
            .build_reader()
        )
    else:
        stream = ctx.from_topic(
            os.environ["KR_TOPIC"],
            sample_json='{"ts": 1, "k": "a", "v": 1.0}',
            bootstrap_servers=os.environ["KR_BROKER"],
            timestamp_column="ts",
        )
    ds = stream.window(
        ["k"],
        [F.count(col("v")).alias("c"), F.sum(col("v")).alias("s")],
        500,
    )
    with open(os.environ["KR_OUT"], "a", buffering=1) as out:
        out.write(json.dumps({"event": "ready"}) + "\n")
        for b in ds.stream():
            if not b.schema.has(WINDOW_START_COLUMN):
                continue
            ws = b.column(WINDOW_START_COLUMN)
            for i in range(b.num_rows):
                out.write(
                    json.dumps(
                        {
                            "ws": int(ws[i]),
                            "k": str(b.column("k")[i]),
                            "c": int(b.column("c")[i]),
                            "s": float(b.column("s")[i]),
                        }
                    )
                    + "\n"
                )


if __name__ == "__main__":
    main()
