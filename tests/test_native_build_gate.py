"""Native-build smoke gate: every C++ component must COMPILE on this
image, loudly.

PR 1 found the JSON parser had never compiled here (a gcc-10 libstdc++
gap) while every caller silently caught the build failure and ran the
~30x-slower pure-Python fallback — for five rounds.  This gate makes
that failure mode structurally impossible: it compiles every
``denormalized_tpu/native/*.cpp`` from source with the same flags the
production loader uses, into a scratch directory, and fails the suite
with the compiler's stderr on any error.  A second check drives the real
``build.load()`` path so the ctypes modules are known loadable, not just
compilable."""

import shutil
import subprocess
import sysconfig
from pathlib import Path

import pytest

NATIVE = Path(__file__).resolve().parent.parent / "denormalized_tpu" / "native"

pytestmark = pytest.mark.skipif(
    shutil.which("g++") is None,
    reason="no compiler — the pure-Python fallbacks cover this environment",
)

_PY_INC = sysconfig.get_paths()["include"]

# every ctypes-loaded module and its production extra flags (mirrors the
# call sites: sources/kafka.py loads kafka_client with -lz; state/lsm.py
# builds lsmkv with the base flags; pyassemble needs the Python headers —
# the interner's optional -DINTERN_HAVE_PYTHON build is exercised by its
# own loader check below)
_MODULES = {
    "json_parser": [],
    "avro_parser": [],
    "interner": [],
    "partial_agg": [],
    "kafka_client": ["-lz"],
    "lsmkv": [],
    "pyassemble": [f"-I{_PY_INC}"],
}

# the production loader's warning surface, made FATAL here: the gate is
# where warning-cleanliness is enforced (build.py keeps warnings
# non-fatal so a future compiler's new diagnostics can't brick first-use
# builds in production — the gate catches them in CI instead)
from denormalized_tpu.native.build import WARN_FLAGS

_BASE_FLAGS = ["-O2", "-shared", "-fPIC", "-std=c++17", *WARN_FLAGS,
               "-Werror"]


def test_all_native_sources_enumerated():
    """A new .cpp dropped into native/ must be added to the gate (or the
    gate is silently incomplete) — native_test.cpp is the standalone test
    binary, compiled end-to-end by test_native_sanitizers."""
    on_disk = {p.stem for p in NATIVE.glob("*.cpp")} - {"native_test"}
    assert on_disk == set(_MODULES), (
        f"native modules on disk {sorted(on_disk)} != gated "
        f"{sorted(_MODULES)} — extend _MODULES in this test"
    )


@pytest.mark.parametrize("name", sorted(_MODULES))
def test_native_module_compiles(tmp_path, name):
    src = NATIVE / f"{name}.cpp"
    out = tmp_path / f"{name}.so"
    proc = subprocess.run(
        ["g++", *_BASE_FLAGS, str(src), "-o", str(out), *_MODULES[name]],
        capture_output=True,
        text=True,
        cwd=NATIVE,
        timeout=300,
    )
    assert proc.returncode == 0, (
        f"{name}.cpp does not compile on this image — every caller would "
        f"silently run its Python fallback:\n{proc.stderr[-3000:]}"
    )
    assert out.exists() and out.stat().st_size > 0


def test_native_parsers_load_through_production_path():
    """The real build-on-first-use loaders must return a usable library —
    compilation alone doesn't prove the srchash/stamp machinery and the
    ctypes signature setup work."""
    from denormalized_tpu.formats._native_parser_base import _pyassemble
    from denormalized_tpu.formats.native_avro import _lib as avro_lib
    from denormalized_tpu.formats.native_json import _lib as json_lib

    jl = json_lib()
    assert hasattr(jl, "jp_create_tree")
    al = avro_lib()
    assert hasattr(al, "ap_create_tree")
    # this image has Python headers, so the C row assembler must engage
    # (elsewhere it may legitimately be None — the wrapper then uses the
    # generated-comprehension reassembly)
    assert _pyassemble() is not None
