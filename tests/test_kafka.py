"""Kafka stack tests: native wire client ⇄ in-process mock broker, then the
full pipeline (from_topic → window → sink_kafka → read back) — the
integration coverage the reference only had via live docker Kafka."""

import json
import threading
import time

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.api import functions as F
from denormalized_tpu.sources.kafka import KafkaClient, KafkaTopicBuilder
from denormalized_tpu.testing.mock_kafka import (
    MockKafkaBroker,
    build_record_batch,
    parse_record_batches,
)


@pytest.fixture
def broker():
    b = MockKafkaBroker().start()
    yield b
    b.stop()


def test_record_batch_codec_roundtrip():
    records = [(1000, b"hello"), (1001, b""), (1002, "日本".encode())]
    blob = build_record_batch(7, records)
    assert parse_record_batches(blob) == records


def test_native_client_metadata_offsets_produce_fetch(broker):
    broker.create_topic("t1", partitions=3)
    c = KafkaClient(broker.bootstrap)
    assert c.partition_count("t1") == 3
    assert c.list_offset("t1", 0, -2) == 0
    assert c.list_offset("t1", 0, -1) == 0

    payloads = [json.dumps({"i": i}).encode() for i in range(100)]
    c.produce("t1", 0, payloads[:60])
    c.produce("t1", 0, payloads[60:])
    assert c.list_offset("t1", 0, -1) == 100

    got, ts, next_off = c.fetch("t1", 0, 0, max_wait_ms=10)
    assert got == payloads
    assert next_off == 100
    assert len(ts) == 100

    # fetch from the middle
    got2, _, next2 = c.fetch("t1", 0, 42, max_wait_ms=10)
    assert got2 == payloads[42:]
    assert next2 == 100

    # fetch beyond the end waits then returns nothing
    t0 = time.time()
    got3, _, _ = c.fetch("t1", 0, 100, max_wait_ms=80)
    assert got3 == [] and time.time() - t0 >= 0.05
    c.close()


def test_kafka_source_to_window_pipeline(broker):
    broker.create_topic("temperature", partitions=2)
    t0 = 1_700_000_000_000
    rng = np.random.default_rng(5)

    def feed():
        # progressive production: the engine's watermark is the monotonic
        # max of batch min-timestamps, so windows only close as newer data
        # arrives — exactly like a live stream
        for chunk in range(6):
            for p in range(2):
                msgs = []
                for i in range(chunk * 50, (chunk + 1) * 50):
                    msgs.append(
                        json.dumps(
                            {
                                "occurred_at_ms": int(t0 + i * 10),
                                "sensor_name": f"s{rng.integers(0, 3)}",
                                "reading": float(rng.normal(50, 5)),
                            }
                        ).encode()
                    )
                broker.produce("temperature", p, msgs, ts_ms=t0)
            time.sleep(0.25)

    threading.Thread(target=feed, daemon=True).start()

    ctx = Context(
        # the feed goes quiet once produced; without an idle hint the
        # tail windows close only if the LAST fetch happens to carry a
        # high min-ts batch (watermark = max of batch min-ts), so the
        # consume loop can starve on fetch-coalescing timing
        EngineConfig(source_idle_timeout_ms=400)
    )
    sample = json.dumps(
        {"occurred_at_ms": 1, "sensor_name": "a", "reading": 1.0}
    )
    ds = ctx.from_topic(
        "temperature",
        sample_json=sample,
        bootstrap_servers=broker.bootstrap,
        timestamp_column="occurred_at_ms",
    ).window(
        ["sensor_name"],
        [F.count(col("reading")).alias("cnt")],
        1000,
    )

    # unbounded source: consume until both windows appeared, then stop
    got = {}
    it = ds.stream()
    deadline = time.time() + 20
    for batch in it:
        for i in range(batch.num_rows):
            got[
                (
                    int(batch.column("window_start_time")[i]),
                    batch.column("sensor_name")[i],
                )
            ] = int(batch.column("cnt")[i])
        # 600 rows over [t0, t0+3000): windows 0,1 close once watermark
        # passes; the final partial window needs more data, so stop at ≥2
        if len({w for w, _ in got}) >= 2 or time.time() > deadline:
            it.close()
            break
    # the two closed windows cover rows in [t0, t0+2000): 100 rows per
    # window per partition × 2 partitions × 2 windows
    closed = sum(v for (w, k), v in got.items() if w < t0 + 2000)
    assert closed == 400


def test_sink_kafka_roundtrip(broker):
    broker.create_topic("in", partitions=1)
    broker.create_topic("out", partitions=1)
    t0 = 1_700_000_000_000
    def feed():
        for chunk in range(10):
            msgs = [
                json.dumps(
                    {
                        "occurred_at_ms": t0 + i * 100,
                        "sensor_name": "a",
                        "reading": float(i),
                    }
                ).encode()
                for i in range(chunk * 5, (chunk + 1) * 5)
            ]
            broker.produce("in", 0, msgs, ts_ms=t0)
            time.sleep(0.2)

    threading.Thread(target=feed, daemon=True).start()

    ctx = Context(
        # the feed goes quiet once produced; without an idle hint the
        # tail windows close only if the LAST fetch happens to carry a
        # high min-ts batch (watermark = max of batch min-ts), so the
        # consume loop can starve on fetch-coalescing timing
        EngineConfig(source_idle_timeout_ms=400)
    )
    sample = json.dumps({"occurred_at_ms": 1, "sensor_name": "a", "reading": 1.0})
    ds = ctx.from_topic(
        "in",
        sample_json=sample,
        bootstrap_servers=broker.bootstrap,
        timestamp_column="occurred_at_ms",
    ).window(["sensor_name"], [F.sum(col("reading")).alias("s")], 1000)

    stop = threading.Event()

    def run_sink():
        # sink_kafka runs an unbounded pipeline; drive it in a thread and
        # stop once the expected output shows up
        try:
            ds.sink_kafka(broker.bootstrap, "out")
        except Exception:
            pass

    th = threading.Thread(target=run_sink, daemon=True)
    th.start()
    deadline = time.time() + 20
    rows = []
    while time.time() < deadline:
        rows = [json.loads(pl) for _, _, pl in broker.log("out", 0)]
        if len(rows) >= 4:
            break
        time.sleep(0.1)
    assert len(rows) >= 4
    by_window = {r["window_start_time"]: r["s"] for r in rows}
    assert by_window[t0] == sum(range(10))
    assert by_window[t0 + 1000] == sum(range(10, 20))


def test_poison_message_does_not_livelock(broker):
    """A malformed payload raises once; the reader advances past it and the
    stream continues (review regression: offset commits before decode)."""
    broker.create_topic("poison", partitions=1)
    t0 = 1_700_000_000_000

    def feed():
        broker.produce(
            "poison",
            0,
            [
                json.dumps({"occurred_at_ms": t0, "sensor_name": "a", "reading": 1.0}).encode(),
                b'{"occurred_at_ms": oops}',
            ],
            ts_ms=t0,
        )
        time.sleep(0.3)
        for c in range(4):
            broker.produce(
                "poison",
                0,
                [
                    json.dumps(
                        {"occurred_at_ms": t0 + 500 + c * 500, "sensor_name": "a", "reading": 2.0}
                    ).encode()
                ],
                ts_ms=t0,
            )
            time.sleep(0.2)

    threading.Thread(target=feed, daemon=True).start()
    sample = json.dumps({"occurred_at_ms": 1, "sensor_name": "a", "reading": 1.0})
    src = (
        KafkaTopicBuilder(broker.bootstrap)
        .with_topic("poison")
        .infer_schema_from_json(sample)
        .with_timestamp_column("occurred_at_ms")
        .build_reader()
    )
    reader = src.partitions()[0]
    # the poison record is skipped in-place: the good record co-fetched in
    # the same fetch arrives (no 4MB-fetch drop), no exception propagates
    # (an engine-driven pipeline would otherwise abort before the advanced
    # offset is ever checkpointed → crash loop on restart), and later
    # records keep flowing
    rows = 0
    readings = []
    deadline = time.time() + 15
    while time.time() < deadline and rows < 5:
        b = reader.read(timeout_s=0.2)
        rows += b.num_rows
        if b.num_rows:
            readings.extend(np.asarray(b.column("reading")).tolist())
    assert rows == 5, f"expected all 5 good records, got {rows}"
    assert readings[0] == 1.0, "good record co-fetched with poison was lost"


def test_gzip_compressed_batches(broker):
    """The native client inflates gzip record batches (Kafka codec 1)."""
    broker.create_topic("gz", partitions=1)
    payloads = [json.dumps({"i": i, "pad": "x" * 100}).encode() for i in range(50)]
    broker.produce("gz", 0, payloads, ts_ms=123, gzip_codec=True)
    c = KafkaClient(broker.bootstrap)
    got, ts, next_off = c.fetch("gz", 0, 0, max_wait_ms=10)
    assert got == payloads
    assert next_off == 50
    assert list(ts) == [123] * 50
    # fetch from the middle of compressed batches
    got2, _, _ = c.fetch("gz", 0, 30, max_wait_ms=10)
    assert got2 == payloads[30:]
    c.close()


def test_snappy_compressed_batches(broker):
    """The native client decodes raw-snappy record batches (Kafka codec 2),
    the magic-2 framing modern producers use."""
    broker.create_topic("sn", partitions=1)
    payloads = [json.dumps({"i": i, "pad": "y" * 80}).encode() for i in range(40)]
    broker.produce("sn", 0, payloads, ts_ms=77, codec=2)
    c = KafkaClient(broker.bootstrap)
    got, ts, next_off = c.fetch("sn", 0, 0, max_wait_ms=10)
    assert got == payloads
    assert next_off == 40
    assert list(ts) == [77] * 40
    got2, _, _ = c.fetch("sn", 0, 25, max_wait_ms=10)
    assert got2 == payloads[25:]
    c.close()


def test_snappy_xerial_framing(broker):
    """Legacy Java-producer snappy framing (\\x82SNAPPY\\x00 header) is
    auto-detected, mirroring librdkafka."""
    from denormalized_tpu.testing.mock_kafka import (
        encode_records,
        xerial_snappy_compress,
    )

    broker.create_topic("snx", partitions=1)
    payload = json.dumps({"k": "xerial"}).encode()
    crafted = xerial_snappy_compress(encode_records([(5, payload)]))
    broker.produce("snx", 0, [payload], ts_ms=5, codec=2,
                   compressed_records=crafted)
    c = KafkaClient(broker.bootstrap)
    got, ts, _ = c.fetch("snx", 0, 0, max_wait_ms=10)
    assert got == [payload] and list(ts) == [5]
    c.close()


def test_snappy_copy_elements(broker):
    """Hand-crafted snappy stream with copy (back-reference) elements —
    the part a literal-only encoder never exercises, including
    overlapping RLE copies."""
    from denormalized_tpu.testing.mock_kafka import encode_records

    broker.create_topic("snc", partitions=1)
    payload = b'{"s": "' + b"A" * 200 + b'"}'
    raw = encode_records([(9, payload)])
    run = raw.index(b"AAAA")

    out = bytearray()
    n = len(raw)
    while True:  # uvarint
        b = n & 0x7F
        n >>= 7
        out.append(b | (0x80 if n else 0))
        if not n:
            break

    def lit(chunk):
        for i in range(0, len(chunk), 60):
            c = chunk[i : i + 60]
            out.append((len(c) - 1) << 2)
            out.extend(c)

    lit(raw[: run + 1])  # literals up to and incl. one 'A'
    remaining = 199  # the other A's via copies
    # type-1 copy: offset 1, len 4..11 (overlapping → RLE)
    out.append(((4 - 4) << 2) | 1 | (0 << 5))
    out.append(1)
    remaining -= 4
    # type-2 copies: offset LE16, len ≤ 64
    while remaining > 0:
        ln = min(remaining, 60)
        out.append(((ln - 1) << 2) | 2)
        out.extend((1).to_bytes(2, "little"))
        remaining -= ln
    lit(raw[run + 200 :])

    broker.produce("snc", 0, [payload], ts_ms=9, codec=2,
                   compressed_records=bytes(out))
    c = KafkaClient(broker.bootstrap)
    got, _, _ = c.fetch("snc", 0, 0, max_wait_ms=10)
    assert got == [payload]
    c.close()


def test_lz4_compressed_batches(broker):
    """The native client decodes LZ4-frame record batches (Kafka codec 3)."""
    broker.create_topic("l4", partitions=1)
    payloads = [json.dumps({"i": i, "pad": "z" * 90}).encode() for i in range(30)]
    broker.produce("l4", 0, payloads, ts_ms=42, codec=3)
    c = KafkaClient(broker.bootstrap)
    got, ts, next_off = c.fetch("l4", 0, 0, max_wait_ms=10)
    assert got == payloads
    assert next_off == 30
    assert list(ts) == [42] * 30
    c.close()


def test_lz4_match_sequences(broker):
    """Hand-crafted LZ4 block with literal+match sequences (offset-1 RLE
    overlap) inside a frame."""
    import struct as _s

    from denormalized_tpu.testing.mock_kafka import encode_records

    broker.create_topic("l4m", partitions=1)
    payload = b'{"s": "' + b"B" * 150 + b'"}'
    raw = encode_records([(3, payload)])
    run = raw.index(b"BBBB")

    block = bytearray()
    head = raw[: run + 1]  # literals through one 'B'
    # sequence 1: literals + match(offset=1, len=149)
    litlen = len(head)
    token_lit = min(litlen, 15)
    mlen = 149 - 4  # stored match length (actual − 4)
    token_match = min(mlen, 15)
    block.append((token_lit << 4) | token_match)
    if token_lit == 15:
        rest = litlen - 15
        while rest >= 255:
            block.append(255)
            rest -= 255
        block.append(rest)
    block += head
    block += (1).to_bytes(2, "little")  # match offset
    if token_match == 15:
        rest = mlen - 15
        while rest >= 255:
            block.append(255)
            rest -= 255
        block.append(rest)
    # sequence 2 (last): remaining literals only
    tail = raw[run + 150 :]
    token_lit = min(len(tail), 15)
    block.append(token_lit << 4)
    if token_lit == 15:
        rest = len(tail) - 15
        while rest >= 255:
            block.append(255)
            rest -= 255
        block.append(rest)
    block += tail

    frame = bytearray()
    frame += _s.pack("<I", 0x184D2204)
    frame += bytes([0x40, 0x40, 0x00])
    frame += _s.pack("<I", len(block))
    frame += block
    frame += _s.pack("<I", 0)  # EndMark

    broker.produce("l4m", 0, [payload], ts_ms=3, codec=3,
                   compressed_records=bytes(frame))
    c = KafkaClient(broker.bootstrap)
    got, _, _ = c.fetch("l4m", 0, 0, max_wait_ms=10)
    assert got == [payload]
    c.close()


def test_zstd_round_trip(broker):
    """zstd batches decode via the hybrid path: C++ stashes the compressed
    section, Python zstandard decompresses, the C++ record parser
    re-ingests — full codec parity with librdkafka."""
    pytest.importorskip("zstandard")
    broker.create_topic("zs", partitions=1)
    payloads = [json.dumps({"i": i, "pad": "z" * 70}).encode() for i in range(25)]
    broker.produce("zs", 0, payloads, ts_ms=55, codec=4)
    c = KafkaClient(broker.bootstrap)
    got, ts, next_off = c.fetch("zs", 0, 0, max_wait_ms=10)
    assert got == payloads
    assert next_off == 25
    assert list(ts) == [55] * 25
    got2, _, _ = c.fetch("zs", 0, 10, max_wait_ms=10)
    assert got2 == payloads[10:]
    c.close()


def test_zstd_without_decompressor_surfaces_named_error(broker):
    """Without an external decompressor registered, zstd batches keep the
    error-loudly behavior — never a silent skip (that would be silent data
    loss; the reference supports all codecs via librdkafka)."""
    from denormalized_tpu.common.errors import SourceError

    broker.create_topic("zs2", partitions=1)
    broker.produce("zs2", 0, [b'{"i": 1}'], ts_ms=1, codec=4,
                   compressed_records=b"\x28\xb5\x2f\xfd")
    c = KafkaClient(broker.bootstrap, external_codecs=False)
    with pytest.raises(SourceError, match="zstd"):
        c.fetch("zs2", 0, 0, max_wait_ms=10)
    c.close()


def test_zstd_corrupt_payload_errors(broker):
    """A zstd batch whose payload fails decompression raises loudly."""
    pytest.importorskip("zstandard")
    from denormalized_tpu.common.errors import SourceError

    broker.create_topic("zs3", partitions=1)
    broker.produce("zs3", 0, [b'{"i": 1}'], ts_ms=1, codec=4,
                   compressed_records=b"\x28\xb5\x2f\xfd\xff\xff\xff")
    c = KafkaClient(broker.bootstrap)
    with pytest.raises(SourceError, match="zstd decompression failed"):
        c.fetch("zs3", 0, 0, max_wait_ms=10)
    c.close()


def test_corrupt_compressed_batch_errors(broker):
    """A corrupt compressed records section errors instead of silently
    dropping the batch's records."""
    from denormalized_tpu.common.errors import SourceError

    broker.create_topic("cor", partitions=1)
    broker.produce("cor", 0, [b'{"i": 1}'], ts_ms=1, codec=2,
                   compressed_records=b"\xff\xff\xff\xff\xff")
    c = KafkaClient(broker.bootstrap)
    with pytest.raises(SourceError, match="snappy decompression failed"):
        c.fetch("cor", 0, 0, max_wait_ms=10)
    c.close()


def test_projection_pushdown_into_json_reader(broker):
    """Reader-level pushdown: a wide JSON topic feeding a 2-column window
    only DECODES the needed columns (the decoder's schema narrows), and
    results are unchanged."""
    broker.create_topic("wide", partitions=1)
    t0 = 1_700_000_000_000

    def feed():
        # progressive: separate fetches so the watermark (monotonic max of
        # batch MIN timestamps) actually advances and closes windows
        for chunk in range(4):
            msgs = [
                json.dumps(
                    {
                        "occurred_at_ms": t0 + i * 20,
                        "sensor_name": f"s{i % 3}",
                        "reading": float(i),
                        **{f"extra{j}": j * 1.5 for j in range(10)},
                    }
                ).encode()
                for i in range(chunk * 50, (chunk + 1) * 50)
            ]
            broker.produce("wide", 0, msgs, ts_ms=t0)
            time.sleep(0.25)
        broker.produce(
            "wide", 0,
            [json.dumps({"occurred_at_ms": t0 + 10_000, "sensor_name": "s0",
                         "reading": 0.0,
                         **{f"extra{j}": 0.0 for j in range(10)}}).encode()],
            ts_ms=t0,
        )

    threading.Thread(target=feed, daemon=True).start()
    sample = json.dumps(
        {"occurred_at_ms": 1, "sensor_name": "a", "reading": 1.0,
         **{f"extra{j}": 1.0 for j in range(10)}}
    )
    ctx = Context(
        # the feed goes quiet once produced; without an idle hint the
        # tail windows close only if the LAST fetch happens to carry a
        # high min-ts batch (watermark = max of batch min-ts), so the
        # consume loop can starve on fetch-coalescing timing
        EngineConfig(source_idle_timeout_ms=400)
    )
    ds = ctx.from_topic(
        "wide",
        sample_json=sample,
        bootstrap_servers=broker.bootstrap,
        timestamp_column="occurred_at_ms",
    ).window(
        ["sensor_name"], [F.sum(col("reading")).alias("s")], 1000
    )

    # the OPTIMIZED plan's scan decodes only 3 columns (+ canonical ts)
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.logical.optimizer import optimize

    opt = optimize(lp.Sink(ds._plan, None))

    def find_scan(n):
        if isinstance(n, lp.Scan):
            return n
        for c in n.children:
            r = find_scan(c)
            if r is not None:
                return r
        return None

    scan = find_scan(opt)
    names = set(scan.source.schema.names)
    assert "extra0" not in names and "extra9" not in names, names
    assert {"sensor_name", "reading", "occurred_at_ms"} <= names

    total = 0.0
    expected = sum(float(i) for i in range(200))
    it = ds.stream()
    deadline = time.time() + 20
    for b in it:
        for i in range(b.num_rows):
            total += float(b.column("s")[i])
        # rows 0..199 land in closed windows once the t0+10s row arrives
        if abs(total - expected) < 1e-6 or time.time() > deadline:
            it.close()
            break
    assert abs(total - expected) < 1e-6, total


def test_avro_from_topic_pipeline(broker):
    """Broker-backed Avro source: from_topic(encoding='avro') decodes
    through the native C++ parser straight off the fetch arena and feeds
    the windowed aggregation (VERDICT round-1 item)."""
    from denormalized_tpu.formats.avro_codec import (
        encode_record,
        parse_avro_schema,
    )

    decl = {
        "type": "record",
        "name": "Measurement",
        "fields": [
            {"name": "occurred_at_ms",
             "type": {"type": "long", "logicalType": "timestamp-millis"}},
            {"name": "sensor_name", "type": "string"},
            {"name": "reading", "type": ["null", "double"]},
        ],
    }
    schema = parse_avro_schema(decl)
    broker.create_topic("avro_t", partitions=1)
    t0 = 1_700_000_000_000
    total = 0

    def feed():
        nonlocal total
        for chunk in range(5):
            msgs = []
            for i in range(chunk * 40, (chunk + 1) * 40):
                msgs.append(
                    encode_record(
                        schema,
                        {
                            "occurred_at_ms": t0 + i * 25,
                            "sensor_name": f"s{i % 3}",
                            "reading": None if i % 10 == 0 else float(i),
                        },
                    )
                )
            broker.produce("avro_t", 0, msgs, ts_ms=t0 + chunk)
            total += len(msgs)
            time.sleep(0.15)

    threading.Thread(target=feed, daemon=True).start()
    ctx = Context(
        # the feed goes quiet once produced; without an idle hint the
        # tail windows close only if the LAST fetch happens to carry a
        # high min-ts batch (watermark = max of batch min-ts), so the
        # consume loop can starve on fetch-coalescing timing
        EngineConfig(source_idle_timeout_ms=400)
    )
    src = ctx.from_topic(
        "avro_t",
        bootstrap_servers=broker.bootstrap,
        timestamp_column="occurred_at_ms",
        encoding="avro",
        avro_schema=decl,
    )
    reader_src = ctx.table("avro_t")
    from denormalized_tpu.formats.avro_codec import AvroDecoder

    probe = reader_src.partitions()[0]
    assert isinstance(probe._decoder, AvroDecoder)
    assert probe._decoder._native is not None, "native Avro did not engage"

    ds = src.window(
        ["sensor_name"],
        [F.count(col("reading")).alias("cnt"), F.sum(col("reading")).alias("s")],
        1000,
    )
    counts: dict = {}
    deadline = time.time() + 20
    for batch in ds.stream():
        for i in range(batch.num_rows):
            key = (
                int(batch.column("window_start_time")[i]),
                batch.column("sensor_name")[i],
            )
            counts[key] = counts.get(key, 0) + int(batch.column("cnt")[i])
        # rows 0..159 span 4s; the last full second closes once chunk 5 lands
        if sum(counts.values()) >= 120 or time.time() > deadline:
            break
    # count() counts NON-NULL readings only; windows 0..2 closed ⇒ rows
    # 0..119 with i%10==0 excluded (12 nulls)
    assert sum(counts.values()) >= 108, counts


def test_nested_avro_from_topic_pipeline(broker):
    """Rideshare-shape NESTED Avro payload (record-in-record + array +
    enum) through from_topic(encoding='avro'), struct field accessors, and
    a windowed aggregation (VERDICT round-3 item 7; reference decodes
    arbitrary Avro via DataFusion's recursive reader,
    formats/decoders/utils.rs:14, decoders/avro.rs:11-54)."""
    from denormalized_tpu.formats.avro_codec import (
        AvroDecoder,
        encode_record,
        parse_avro_schema,
    )

    decl = {
        "type": "record",
        "name": "Trip",
        "fields": [
            {"name": "occurred_at_ms",
             "type": {"type": "long", "logicalType": "timestamp-millis"}},
            {"name": "driver", "type": {
                "type": "record", "name": "Driver",
                "fields": [
                    {"name": "id", "type": "string"},
                    {"name": "gps", "type": {
                        "type": "record", "name": "Gps",
                        "fields": [
                            {"name": "speed", "type": "double"},
                            {"name": "lat", "type": "double"},
                        ]}},
                ]}},
            {"name": "tags", "type": {"type": "array", "items": "string"}},
            {"name": "status", "type": {
                "type": "enum", "name": "Status",
                "symbols": ["REQUESTED", "ACTIVE", "DONE"]}},
        ],
    }
    schema = parse_avro_schema(decl)
    broker.create_topic("trips_avro", partitions=1)
    t0 = 1_700_000_000_000

    def feed():
        for chunk in range(5):
            msgs = []
            for i in range(chunk * 40, (chunk + 1) * 40):
                msgs.append(
                    encode_record(
                        schema,
                        {
                            "occurred_at_ms": t0 + i * 25,
                            "driver": {
                                "id": f"d{i % 3}",
                                "gps": {"speed": float(i % 7), "lat": 37.0},
                            },
                            "tags": ["x"] * (i % 3),
                            "status": "ACTIVE" if i % 2 else "DONE",
                        },
                    )
                )
            broker.produce("trips_avro", 0, msgs, ts_ms=t0 + chunk)
            time.sleep(0.15)

    threading.Thread(target=feed, daemon=True).start()
    ctx = Context(
        # the feed goes quiet once produced; without an idle hint the
        # tail windows close only if the LAST fetch happens to carry a
        # high min-ts batch (watermark = max of batch min-ts), so the
        # consume loop can starve on fetch-coalescing timing
        EngineConfig(source_idle_timeout_ms=400)
    )
    src = ctx.from_topic(
        "trips_avro",
        bootstrap_servers=broker.bootstrap,
        timestamp_column="occurred_at_ms",
        encoding="avro",
        avro_schema=decl,
    )
    probe = ctx.table("trips_avro").partitions()[0]
    assert isinstance(probe._decoder, AvroDecoder)
    assert probe._decoder._native is None, (
        "nested Avro must route to the recursive Python decoder"
    )

    ds = (
        src.with_column("speed", col("driver").field("gps").field("speed"))
        .with_column("driver_id", col("driver").field("id"))
        .window(
            ["driver_id"],
            [
                F.count(col("speed")).alias("cnt"),
                F.max(col("speed")).alias("top_speed"),
            ],
            1000,
        )
    )
    counts: dict = {}
    top: dict = {}
    deadline = time.time() + 20
    for batch in ds.stream():
        for i in range(batch.num_rows):
            key = batch.column("driver_id")[i]
            counts[key] = counts.get(key, 0) + int(batch.column("cnt")[i])
            top[key] = max(top.get(key, 0.0), float(batch.column("top_speed")[i]))
        if sum(counts.values()) >= 120 or time.time() > deadline:
            break
    # 3 windows close (rows 0..119), keys d0/d1/d2 each get 40 rows
    assert sum(counts.values()) >= 120, counts
    assert set(counts) == {"d0", "d1", "d2"}
    assert max(top.values()) == 6.0, top


def test_broker_outage_recovery():
    """A broker outage yields empty batches with reconnect attempts (the
    reference's log-and-retry on recv errors, kafka_stream_read.rs:210-218);
    when the broker returns on the same port, consumption resumes."""
    b1 = MockKafkaBroker().start()
    port = b1.port
    b1.create_topic("r", 1)
    t0 = 1_700_000_000_000
    b1.produce("r", 0, [json.dumps({"occurred_at_ms": t0, "sensor_name": "a", "reading": 1.0}).encode()], ts_ms=t0)

    sample = json.dumps({"occurred_at_ms": 1, "sensor_name": "a", "reading": 1.0})
    src = (
        KafkaTopicBuilder(b1.bootstrap)
        .with_topic("r")
        .infer_schema_from_json(sample)
        .with_timestamp_column("occurred_at_ms")
        .build_reader()
    )
    reader = src.partitions()[0]
    first = reader.read(timeout_s=0.1)
    assert first.num_rows == 1

    # outage
    b1.stop()
    time.sleep(0.1)
    outage_reads = [reader.read(timeout_s=0.05) for _ in range(3)]
    assert all(r.num_rows == 0 for r in outage_reads)  # alive, no data

    # broker returns on the same port with more data
    b2 = MockKafkaBroker(port=port).start()
    b2.create_topic("r", 1)
    b2.produce("r", 0, [
        json.dumps({"occurred_at_ms": t0 + 100, "sensor_name": "a", "reading": 2.0}).encode(),
        json.dumps({"occurred_at_ms": t0 + 200, "sensor_name": "a", "reading": 3.0}).encode(),
    ], ts_ms=t0)
    # the restarted broker's log begins at offset 0; the reader seeks from
    # its committed offset (1) and picks up the second record onward
    got = 0
    deadline = time.time() + 10
    while time.time() < deadline and got == 0:
        batch = reader.read(timeout_s=0.2)
        got += batch.num_rows
    assert got >= 1
    b2.stop()


def test_mixed_codec_fetch_preserves_offset_order(broker):
    """A fetch spanning a zstd batch followed by a plain batch must deliver
    records in partition-offset order: the client stops the fetch at the
    boundary and the trailing batches arrive on the NEXT fetch."""
    pytest.importorskip("zstandard")
    broker.create_topic("mix", partitions=1)
    broker.produce("mix", 0, [b'{"i": 0}', b'{"i": 1}'], ts_ms=1, codec=4)
    broker.produce("mix", 0, [b'{"i": 2}', b'{"i": 3}'], ts_ms=2)  # plain
    broker.produce("mix", 0, [b'{"i": 4}'], ts_ms=3, codec=4)
    c = KafkaClient(broker.bootstrap)
    seen = []
    off = 0
    for _ in range(6):
        got, _, off = c.fetch("mix", 0, off, max_wait_ms=10)
        seen.extend(got)
        if len(seen) >= 5:
            break
    assert seen == [b'{"i": 0}', b'{"i": 1}', b'{"i": 2}', b'{"i": 3}',
                    b'{"i": 4}'], seen
    assert off == 5
    c.close()


def test_fetch_splitting_bounded_batches_exact_offsets(broker):
    """A fetch larger than max.batch.rows yields bounded batches whose
    offset snapshots land EXACTLY on slice boundaries: a checkpoint taken
    between slices must neither lose nor duplicate rows on restore.  The
    split also keeps watermark granularity tight — one oversized batch
    would otherwise hold every window close behind it for the whole
    fetch's event-time span (watermark = batch min-ts)."""
    broker.create_topic("split", partitions=1)
    total = 1000
    msgs = [
        b'{"occurred_at_ms": %d, "sensor_name": "s", "reading": %d}'
        % (1_700_000_000_000 + i, i)
        for i in range(total)
    ]
    broker.produce_batched("split", 0, msgs)
    sample = json.dumps({"occurred_at_ms": 1, "sensor_name": "a", "reading": 1.0})
    src = (
        KafkaTopicBuilder(broker.bootstrap)
        .with_topic("split")
        .infer_schema_from_json(sample)
        .with_timestamp_column("occurred_at_ms")
        .with_option("max.batch.rows", "256")
        .build_reader()
    )
    reader = src.partitions()[0]
    sizes, snaps, readings = [], [], []
    deadline = time.time() + 15
    while sum(sizes) < total and time.time() < deadline:
        b = reader.read(timeout_s=0.1)
        if b is None or b.num_rows == 0:
            continue
        sizes.append(b.num_rows)
        snaps.append(reader.offset_snapshot()["offset"])
        readings.extend(int(v) for v in b.column("reading"))
    assert sum(sizes) == total
    assert max(sizes) <= 256, sizes
    # snapshots advance by exactly the yielded rows (cumulative row count)
    assert snaps == list(np.cumsum(sizes)), (snaps, sizes)
    assert readings == list(range(total))
    # restore onto a mid-fetch snapshot: replay starts at the NEXT row
    reader2 = src.partitions()[0]
    reader2.offset_restore({"offset": snaps[1]})
    b = reader2.read(timeout_s=0.5)
    while b is not None and b.num_rows == 0:
        b = reader2.read(timeout_s=0.5)
    assert int(b.column("reading")[0]) == sum(sizes[:2])


def test_fetch_splitting_non_native_decode_path(broker):
    """Schemas the native parser declines to shred (here: a dynamic-map
    struct with no declared children — the ONE remaining fallback shape
    now that lists of structs/lists shred natively) decode through the
    Python decoder, but the fetch still runs through the native client —
    so max.batch.rows splitting and its exact slice-boundary offsets
    apply on this path too."""
    broker.create_topic("splitnest", partitions=1)
    total = 600
    msgs = [
        b'{"occurred_at_ms": %d, "meta": {"k%d": %d}}'
        % (1_700_000_000_000 + i, i, i)
        for i in range(total)
    ]
    broker.produce_batched("splitnest", 0, msgs)
    sample = json.dumps({"occurred_at_ms": 1, "meta": {}})
    src = (
        KafkaTopicBuilder(broker.bootstrap)
        .with_topic("splitnest")
        .infer_schema_from_json(sample)
        .with_timestamp_column("occurred_at_ms")
        .with_option("max.batch.rows", "128")
        .build_reader()
    )
    reader = src.partitions()[0]
    assert getattr(reader._decoder, "_native", None) is None
    sizes, snaps = [], []
    deadline = time.time() + 15
    while sum(sizes) < total and time.time() < deadline:
        b = reader.read(timeout_s=0.1)
        if b is None or b.num_rows == 0:
            continue
        sizes.append(b.num_rows)
        snaps.append(reader.offset_snapshot()["offset"])
    assert sum(sizes) == total
    assert max(sizes) <= 128, sizes
    assert snaps == list(np.cumsum(sizes)), (snaps, sizes)


def test_from_topic_positional_order_matches_reference(broker):
    """The reference wrapper's positional order is (topic, sample_json,
    bootstrap_servers, timestamp_column, group_id)
    (py-denormalized/python/denormalized/context.py:32-39).  A migrating
    user's positional call must bind the timestamp column — binding
    group_id there instead would silently demote event-time processing
    to broker arrival time."""
    broker.create_topic("postest", partitions=1)
    t0 = 1_700_000_000_000

    def feed():
        # progressive production: the watermark is the monotonic max of
        # batch min-timestamps, so windows only close as newer fetches
        # arrive — all-at-once production would pin it at t0 forever
        for chunk in range(5):
            msgs = [
                json.dumps(
                    {
                        "occurred_at_ms": t0 + chunk * 500 + i,
                        "sensor_name": "a",
                        "reading": 1.0,
                    }
                ).encode()
                for i in range(500)
            ]
            # no ts_ms: broker stamps wall clock, so if the regression
            # under test reappears (timestamp column not bound), windows
            # anchor at wall time and close — the assert fails cleanly
            # instead of the stream hanging with a frozen watermark
            broker.produce("postest", 0, msgs)
            time.sleep(0.25)

    threading.Thread(target=feed, daemon=True).start()
    sample = json.dumps(
        {"occurred_at_ms": 1, "sensor_name": "a", "reading": 0.5}
    )
    ctx = Context(
        # the feed goes quiet once produced; without an idle hint the
        # tail windows close only if the LAST fetch happens to carry a
        # high min-ts batch (watermark = max of batch min-ts), so the
        # consume loop can starve on fetch-coalescing timing
        EngineConfig(source_idle_timeout_ms=400)
    )
    # POSITIONAL call in the reference's order
    ds = ctx.from_topic(
        "postest", sample, broker.bootstrap, "occurred_at_ms"
    ).window(["sensor_name"], [F.count(col("reading")).alias("c")], 1000)
    starts = []
    it = ds.stream()
    deadline = time.time() + 20
    for batch in it:
        for i in range(batch.num_rows):
            starts.append(int(batch.column("window_start_time")[i]))
        if starts or time.time() > deadline:
            it.close()
            break
    # event-time windows anchor at t0 — broker arrival time (wall clock)
    # would put the first window decades later
    assert t0 in starts, starts
