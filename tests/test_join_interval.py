"""Interval/range (banded) join predicates (ISSUE 15).

Semantics under test (docs/joins.md): a pair joins iff the equi keys
match AND ``left_expr - right_expr`` lands in ``[lower_ms, upper_ms]``
inclusive (None = open bound), evaluated per side BEFORE pair
materialization; null band values match nothing; ``lower > upper`` is a
legal empty band; matches only exist while both rows are co-retained
(the retention clip).  The differential oracle is a brute-force
nested-loop join — including a deterministic-drive case at the
band == retention edge and late (out-of-order) rows on both sides.
"""

from __future__ import annotations

import numpy as np
import pytest

from denormalized_tpu.api.context import Context, EngineConfig
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.logical.expr import col
from denormalized_tpu.sources.memory import MemorySource

T0 = 1_700_000_000_000

L_SCHEMA = Schema([
    Field("ts", DataType.TIMESTAMP_MS, nullable=False),
    Field("k", DataType.STRING, nullable=False),
    Field("lv", DataType.INT64),
])
R_SCHEMA = Schema([
    Field("ts2", DataType.TIMESTAMP_MS, nullable=False),
    Field("k2", DataType.STRING, nullable=False),
    Field("rv", DataType.INT64),
])


def _ctx(**kw):
    kw.setdefault("join_retention_ms", 10**9)
    return Context(EngineConfig(
        join_adaptive=True, join_adapt_interval_s=0.0, **kw
    ))


def _streams(ctx, L, R):
    left = ctx.from_source(
        MemorySource.from_batches(L, timestamp_column="ts"), name="il"
    )
    right = ctx.from_source(
        MemorySource.from_batches(R, timestamp_column="ts2"), name="ir"
    )
    return left, right


def _mk(schema, rows, masks=None):
    cols = list(zip(*rows)) if rows else [[], [], []]
    arrs = [
        np.asarray(cols[0], dtype=np.int64),
        np.asarray(cols[1], dtype=object),
        np.asarray(cols[2], dtype=np.int64),
    ]
    return RecordBatch(schema, arrs, masks)


def _got(res):
    return sorted(zip(
        np.asarray(res.column("ts")).tolist(),
        [str(x) for x in np.asarray(res.column("k"), dtype=object)],
        np.asarray(res.column("lv")).tolist(),
        np.asarray(res.column("ts2")).tolist(),
        np.asarray(res.column("rv")).tolist(),
    ))


def _nested_loop(L_rows, R_rows, lo, hi, l_band=None, r_band=None):
    """Brute-force oracle: all key-equal pairs whose band difference
    lands inclusively in [lo, hi]; None band value matches nothing."""
    out = []
    for (lts, lk, lv) in L_rows:
        for (rts, rk, rv) in R_rows:
            if lk != rk:
                continue
            bl = lts if l_band is None else l_band((lts, lk, lv))
            br = rts if r_band is None else r_band((rts, rk, rv))
            if bl is None or br is None:
                continue
            d = bl - br
            if lo is not None and d < lo:
                continue
            if hi is not None and d > hi:
                continue
            out.append((lts, lk, lv, rts, rv))
    return sorted(out)


def test_band_inclusive_bounds_and_one_sided():
    L = [[(T0 + 0, "a", 1), (T0 + 10, "a", 2), (T0 + 20, "b", 3)]]
    R = [[(T0 + 5, "a", 10), (T0 + 10, "a", 20), (T0 + 25, "b", 30)]]
    Lr = [r for b in L for r in b]
    Rr = [r for b in R for r in b]
    for lo, hi in [(-5, 5), (0, 0), (None, 0), (0, None), (-100, 100)]:
        ctx = _ctx()
        left, right = _streams(
            ctx, [_mk(L_SCHEMA, b) for b in L], [_mk(R_SCHEMA, b) for b in R]
        )
        res = left.join(
            right, "inner", ["k"], ["k2"], band=("ts", "ts2", lo, hi)
        ).collect()
        assert _got(res) == _nested_loop(Lr, Rr, lo, hi), (lo, hi)


def test_empty_band_matches_nothing():
    L = [[(T0, "a", 1)]]
    R = [[(T0, "a", 2)]]
    ctx = _ctx()
    left, right = _streams(
        ctx, [_mk(L_SCHEMA, b) for b in L], [_mk(R_SCHEMA, b) for b in R]
    )
    res = left.join(
        right, "inner", ["k"], ["k2"], band=("ts", "ts2", 10, -10)
    ).collect()
    assert res.num_rows == 0


def test_band_needs_a_bound():
    from denormalized_tpu.common.errors import PlanError

    ctx = _ctx()
    left, right = _streams(
        ctx, [_mk(L_SCHEMA, [(T0, "a", 1)])],
        [_mk(R_SCHEMA, [(T0, "a", 2)])],
    )
    with pytest.raises(PlanError, match="at least one bound"):
        left.join(
            right, "inner", ["k"], ["k2"],
            band=("ts", "ts2", None, None),
        ).collect()


def test_null_band_values_never_match():
    """Null band-column cells (validity mask) match nothing, on either
    side and under one-sided bounds."""
    L_rows = [(T0, "a", 1), (T0 + 1, "a", 2)]
    R_rows = [(T0, "a", 10), (T0 + 1, "a", 20)]
    lmask = [None, None, np.array([True, False])]   # lv null in row 1
    rmask = [None, None, np.array([False, True])]   # rv null in row 0
    for lo, hi in [(-10**6, 10**6), (None, 10**6)]:
        ctx = _ctx()
        left, right = _streams(
            ctx,
            [_mk(L_SCHEMA, L_rows, lmask)],
            [_mk(R_SCHEMA, R_rows, rmask)],
        )
        res = left.join(
            right, "inner", ["k"], ["k2"],
            band=(col("lv"), col("rv"), lo, hi),
        ).collect()
        want = _nested_loop(
            L_rows, R_rows, lo, hi,
            l_band=lambda r: r[2] if r[2] != 2 else None,
            r_band=lambda r: r[2] if r[2] != 10 else None,
        )
        assert _got(res) == want


def test_join_on_lowers_between_to_band():
    """``l.ts >= r.ts - a  AND  l.ts <= r.ts + b`` conjuncts in join_on
    lower to ONE JoinBand (visible in the plan) and produce exactly the
    explicit band API's result."""
    rng = np.random.default_rng(3)
    L = [[(T0 + int(t), f"k{rng.integers(4)}", int(v))
          for t, v in zip(rng.integers(0, 500, 40), range(40))]]
    R = [[(T0 + int(t), f"k{rng.integers(4)}", int(v))
          for t, v in zip(rng.integers(0, 500, 40), range(40))]]

    ctx = _ctx()
    left, right = _streams(
        ctx, [_mk(L_SCHEMA, b) for b in L], [_mk(R_SCHEMA, b) for b in R]
    )
    ds = left.join_on(right, "inner", [
        col("k") == col("k2"),
        col("ts") >= col("ts2") - 50,
        col("ts") <= col("ts2") + 30,
    ])
    band = ds._plan.band
    assert band is not None
    assert band.lower_ms == -50 and band.upper_ms == 30
    assert ds.optimized_plan().band is not None  # survives the optimizer
    got = _got(ds.collect())

    ctx2 = _ctx()
    left2, right2 = _streams(
        ctx2, [_mk(L_SCHEMA, b) for b in L], [_mk(R_SCHEMA, b) for b in R]
    )
    want = _got(left2.join(
        right2, "inner", ["k"], ["k2"], band=("ts", "ts2", -50, 30)
    ).collect())
    assert got == want
    Lr = [r for b in L for r in b]
    Rr = [r for b in R for r in b]
    assert got == _nested_loop(Lr, Rr, -50, 30)


def test_band_differential_seeded_nested_loop():
    """Seeded random feeds with LATE (out-of-order) rows on both sides:
    with retention effectively infinite, the operator must equal the
    pure nested-loop oracle for every band shape."""
    rng = np.random.default_rng(11)
    cases = [(-40, 40), (0, 120), (None, 0), (-7, None), (60, 10)]
    for seed, (lo, hi) in enumerate(cases):
        r = np.random.default_rng(seed)

        def feed(sd):
            rr = np.random.default_rng(sd)
            batches = []
            for b in range(5):
                n = 60
                # deliberately unsorted within AND across batches: both
                # sides late relative to each other
                ts = T0 + rr.integers(0, 2_000, n)
                ks = np.array(
                    [f"k{i}" for i in rr.integers(0, 6, n)], dtype=object
                )
                vs = rr.integers(0, 1000, n)
                batches.append([
                    (int(t), str(k), int(v))
                    for t, k, v in zip(ts, ks, vs)
                ])
            return batches

        Lb, Rb = feed(seed * 2 + 1), feed(seed * 2 + 2)
        ctx = _ctx()
        left, right = _streams(
            ctx,
            [_mk(L_SCHEMA, b) for b in Lb],
            [_mk(R_SCHEMA, b) for b in Rb],
        )
        res = left.join(
            right, "inner", ["k"], ["k2"], band=("ts", "ts2", lo, hi)
        ).collect()
        Lr = [x for b in Lb for x in b]
        Rr = [x for b in Rb for x in b]
        assert _got(res) == _nested_loop(Lr, Rr, lo, hi), (seed, lo, hi)


def _sequential_pump(monkeypatch):
    """Deterministic drive: pump threads enqueue strictly in spawn
    order (all of the left source, then all of the right), so eviction
    timing — and therefore retention-edge matches — is reproducible."""
    import threading

    from denormalized_tpu.runtime import pump as pump_mod

    real_put = pump_mod.checked_put
    threads: list[threading.Thread] = []

    def fake_spawn(q, done, items, sentinel, wrap=lambda x: x):
        idx = len(threads)

        def run():
            if idx:
                threads[idx - 1].join()
            try:
                for item in items():
                    if not real_put(q, done, wrap(item)):
                        return
            finally:
                real_put(q, done, sentinel)

        th = threading.Thread(target=run, daemon=True)
        threads.append(th)
        th.start()
        return th

    monkeypatch.setattr(pump_mod, "spawn_pump", fake_spawn)


def test_band_at_retention_edge_deterministic(monkeypatch):
    """band width == retention: matches at exactly the retention
    horizon are clipped by whole-batch eviction.  Under the sequential
    drive the eviction schedule is reproducible, so the oracle models
    it exactly: when a right batch probes, the horizon is
    min(final-left-watermark, right-watermark-so-far) - retention and
    left batches wholly below it are gone."""
    _sequential_pump(monkeypatch)
    retention = 400
    rng = np.random.default_rng(5)

    def feed(sd, nb=6, n=50):
        rr = np.random.default_rng(sd)
        t = T0
        out = []
        for _ in range(nb):
            ts = np.sort(t + rr.integers(0, 200, n))
            t += 200
            ks = np.array(
                [f"k{i}" for i in rr.integers(0, 4, n)], dtype=object
            )
            out.append([
                (int(a), str(k), int(v))
                for a, k, v in zip(ts, ks, rr.integers(0, 100, n))
            ])
        return out

    Lb, Rb = feed(1), feed(2)
    ctx = _ctx(join_retention_ms=retention, partition_watermarks=False)
    left, right = _streams(
        ctx, [_mk(L_SCHEMA, b) for b in Lb], [_mk(R_SCHEMA, b) for b in Rb]
    )
    res = left.join(
        right, "inner", ["k"], ["k2"],
        band=("ts", "ts2", -retention, retention),
    ).collect()

    # oracle: left fully ingested first (no eviction: right watermark is
    # unset), then each right batch probes retained left batches before
    # its own eviction sweep
    wmL = max(min(r[0] for r in b) for b in Lb)
    retained = [(b, max(r[0] for r in b)) for b in Lb]
    wmR = None
    want = []
    for rb in Rb:
        for (rts, rk, rv) in rb:
            for lb, _mx in retained:
                for (lts, lk, lv) in lb:
                    d = lts - rts
                    if lk == rk and -retention <= d <= retention:
                        want.append((lts, lk, lv, rts, rv))
        bmin = min(r[0] for r in rb)
        wmR = bmin if wmR is None or bmin > wmR else wmR
        horizon = min(wmL, wmR) - retention
        retained = [(lb, mx) for lb, mx in retained if mx >= horizon]
    assert _got(res) == sorted(want)
    assert len(want) > 50


def test_outer_join_band_rejected_pairs_emit_unmatched():
    """LEFT join: an equi-hit rejected by the band must still surface
    as an unmatched (null-padded) left row at EOS."""
    L = [[(T0, "a", 1), (T0 + 500, "a", 2)]]
    R = [[(T0 + 2, "a", 10)]]
    ctx = _ctx()
    left, right = _streams(
        ctx, [_mk(L_SCHEMA, b) for b in L], [_mk(R_SCHEMA, b) for b in R]
    )
    res = left.join(
        right, "left", ["k"], ["k2"], band=("ts", "ts2", -10, 10)
    ).collect()
    rows = {}
    for i in range(res.num_rows):
        lv = int(res.column("lv")[i])
        rv_mask = res.mask("rv")
        matched = bool(rv_mask[i]) if rv_mask is not None else True
        rows[lv] = matched
    # row lv=1 in band -> matched pair; lv=2 out of band -> unmatched
    assert rows == {1: True, 2: False}


def test_banded_join_kill_restore_byte_identical(tmp_path):
    """Band values ride the snapshot: a restored banded join continues
    exactly (no re-derivation drift, spilled-row-safe layout)."""
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import Marker
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.lsm import close_global_state_backend
    from denormalized_tpu.state.orchestrator import Orchestrator

    rng = np.random.default_rng(9)

    def feed(sd, nb=24, n=80):
        rr = np.random.default_rng(sd)
        t = T0
        out = []
        for _ in range(nb):
            ts = np.sort(t + rr.integers(0, 300, n))
            t += 300
            ks = np.array(
                [f"k{i}" for i in rr.integers(0, 5, n)], dtype=object
            )
            out.append([
                (int(a), str(k), int(v))
                for a, k, v in zip(ts, ks, rr.integers(0, 100, n))
            ])
        return out

    Lb, Rb = feed(1), feed(2)

    def mk(path):
        ctx = Context(EngineConfig(
            checkpoint=path is not None,
            checkpoint_interval_s=9999,
            state_backend_path=path,
            join_adaptive=True,
            join_adapt_interval_s=0.0,
        ))
        left, right = _streams(
            ctx,
            [_mk(L_SCHEMA, b) for b in Lb],
            [_mk(R_SCHEMA, b) for b in Rb],
        )
        return ctx, left.join(
            right, "inner", ["k"], ["k2"], band=("ts", "ts2", -50, 50)
        )

    _ctx_g, ds_g = mk(None)
    golden = set(_got(ds_g.collect()))

    state_dir = str(tmp_path / "state")
    ctx_a, ds_a = mk(state_dir)
    sink_a = CollectSink()
    root_a = executor.build_physical(lp.Sink(ds_a._plan, sink_a), ctx_a)
    orch = Orchestrator(interval_s=9999)
    coord = wire_checkpointing(root_a, ctx_a, orch)
    it = root_a.run()
    seen = 0
    committed = False
    for item in it:
        if isinstance(item, RecordBatch):
            seen += 1
        if seen == 1:
            orch.trigger_now()
            seen += 1
        if isinstance(item, Marker):
            coord.commit(item.epoch)
            committed = True
            break
    assert committed, "sources drained before the checkpoint trigger"
    it.close()
    close_global_state_backend()
    emitted_a = [
        r for b in sink_a.batches for r in _got(b)
    ]

    ctx_b, ds_b = mk(state_dir)
    sink_b = CollectSink()
    root_b = executor.build_physical(lp.Sink(ds_b._plan, sink_b), ctx_b)
    orch_b = Orchestrator(interval_s=9999)
    coord_b = wire_checkpointing(root_b, ctx_b, orch_b)
    assert coord_b.committed_epoch is not None
    join_b = root_b.input_op
    for _ in root_b.run():
        pass
    # band values restored from the snapshot arrays (not re-derived)
    assert all(
        s.row_band is not None for s in join_b._sides
    )
    emitted_b = [r for b in sink_b.batches for r in _got(b)]
    combined = set(emitted_a) | set(emitted_b)
    assert combined == golden
    close_global_state_backend()


def _find_join(op):
    from denormalized_tpu.physical.join_exec import StreamingJoinExec

    stack = [op]
    while stack:
        cur = stack.pop()
        if isinstance(cur, StreamingJoinExec):
            return cur
        stack.extend(cur.children)
    raise AssertionError("no StreamingJoinExec in plan")


def test_band_eviction_bounds_state_matches_oracle(monkeypatch):
    """Band-aware eviction pin (ISSUE 17 satellite): at band ≪ retention
    the SAME in-order feed run with ``join_band_slack_ms=0`` vs ``None``
    (off) produces identical output — equal to the nested-loop oracle —
    while the band-evicting run retains a small fraction of the state
    bytes the retention-only run holds at EOS."""
    _sequential_pump(monkeypatch)
    band = 300

    def feed(sd, nb=30, n=24):
        rr = np.random.default_rng(sd)
        t = T0
        out = []
        for _ in range(nb):
            ts = np.sort(t + rr.integers(0, 500, n))
            t += 500
            ks = np.array(
                [f"k{i}" for i in rr.integers(0, 4, n)], dtype=object
            )
            out.append([
                (int(a), str(k), int(v))
                for a, k, v in zip(ts, ks, rr.integers(0, 100, n))
            ])
        return out

    Lb, Rb = feed(21), feed(22)

    def run(slack):
        ctx = _ctx(join_band_slack_ms=slack, partition_watermarks=False)
        left, right = _streams(
            ctx,
            [_mk(L_SCHEMA, b) for b in Lb],
            [_mk(R_SCHEMA, b) for b in Rb],
        )
        res = left.join(
            right, "inner", ["k"], ["k2"], band=("ts", "ts2", -band, band)
        ).collect()
        return _got(res), _find_join(ctx._last_physical)

    got_evict, j_evict = run(0)
    got_off, j_off = run(None)
    Lr = [x for b in Lb for x in b]
    Rr = [x for b in Rb for x in b]
    want = _nested_loop(Lr, Rr, -band, band)
    assert got_evict == want
    assert got_off == want
    # retention is effectively infinite: every evicted row is the band
    # horizon's doing, and the off run must not evict at all
    assert j_off._metrics["evicted"] == 0
    assert j_evict._metrics["evicted"] > 0
    b_evict = j_evict.state_info()["state_bytes"]
    b_off = j_off.state_info()["state_bytes"]
    assert b_off > 0 and b_evict < 0.3 * b_off, (b_evict, b_off)


def test_band_eviction_slack_absorbs_late_rows():
    """Late (bounded out-of-order) band values: with slack ≥ the feed's
    lateness, band eviction loses no matches under ANY thread
    interleaving — exact vs the nested-loop oracle — while still
    evicting (band ≪ retention).  The final sweep runs with both sides'
    final band watermarks, so the eviction count is deterministic."""
    band, late = 150, 400

    def feed(sd, nb=30, n=24):
        rr = np.random.default_rng(sd)
        out = []
        for b in range(nb):
            base = T0 + b * 500
            ts = base + rr.integers(-late, 500, n)
            ts[0] = base  # on-time anchor: batch min stays ≤ base
            ks = np.array(
                [f"k{i}" for i in rr.integers(0, 4, n)], dtype=object
            )
            out.append([
                (int(a), str(k), int(v))
                for a, k, v in zip(ts, ks, rr.integers(0, 100, n))
            ])
        return out

    Lb, Rb = feed(31), feed(32)
    ctx = _ctx(join_band_slack_ms=late)
    left, right = _streams(
        ctx, [_mk(L_SCHEMA, b) for b in Lb], [_mk(R_SCHEMA, b) for b in Rb]
    )
    res = left.join(
        right, "inner", ["k"], ["k2"], band=("ts", "ts2", -band, band)
    ).collect()
    Lr = [x for b in Lb for x in b]
    Rr = [x for b in Rb for x in b]
    assert _got(res) == _nested_loop(Lr, Rr, -band, band)
    assert _find_join(ctx._last_physical)._metrics["evicted"] > 0


# -- hypothesis property (clean skip when the dep is absent) --------------

try:
    from hypothesis import given, settings
    from hypothesis import strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - env without hypothesis
    HAVE_HYPOTHESIS = False


if HAVE_HYPOTHESIS:

    @st.composite
    def _band_case(draw):
        nkeys = draw(st.integers(1, 5))
        span = draw(st.integers(1, 1500))

        def rows(n):
            return [
                (
                    T0 + draw(st.integers(0, span)),
                    f"k{draw(st.integers(0, nkeys - 1))}",
                    draw(st.integers(0, 50)),
                )
                for _ in range(n)
            ]

        L = [rows(draw(st.integers(0, 25))) for _ in range(draw(st.integers(1, 3)))]
        R = [rows(draw(st.integers(0, 25))) for _ in range(draw(st.integers(1, 3)))]
        lo = draw(st.one_of(st.none(), st.integers(-span, span)))
        hi = draw(st.one_of(st.none(), st.integers(-span, span)))
        if lo is None and hi is None:
            hi = 0
        return L, R, lo, hi

    @settings(max_examples=25, deadline=None)
    @given(_band_case())
    def test_band_property_matches_nested_loop(case):
        L, R, lo, hi = case
        if not any(b for b in L) and not any(b for b in R):
            return
        ctx = _ctx()
        left, right = _streams(
            ctx,
            [_mk(L_SCHEMA, b) for b in L],
            [_mk(R_SCHEMA, b) for b in R],
        )
        res = left.join(
            right, "inner", ["k"], ["k2"], band=("ts", "ts2", lo, hi)
        ).collect()
        Lr = [x for b in L for x in b]
        Rr = [x for b in R for x in b]
        assert _got(res) == _nested_loop(Lr, Rr, lo, hi)

else:

    @pytest.mark.skip(reason="hypothesis not installed in this environment")
    def test_band_property_matches_nested_loop():
        pass
