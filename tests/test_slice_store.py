"""SliceStore kernel unit tests: per-(gid, slide-unit) partials from
reduceat accumulation must agree with brute-force per-cell aggregation,
window folds must agree with direct aggregation over the folded range,
and the snapshot round-trip must be bit-exact (the property the
multi-query engine's byte-identical emission guarantees ride on)."""

import numpy as np
import pytest

from denormalized_tpu.ops.segment_agg import AggComponent, components_for
from denormalized_tpu.ops.slice_store import (
    SliceStore,
    fold_slices,
    slice_segment_bounds,
)

COMPONENTS = tuple(
    components_for([("count", 0), ("sum", 0), ("min", 0), ("max", 0)])
)


def _brute_cells(units, gids, vals, valid):
    cells = {}
    for u, g, v, ok in zip(
        units.tolist(), gids.tolist(), vals.tolist(), valid.tolist()
    ):
        c = cells.setdefault(
            (u, g),
            {"rows": 0, "n": 0, "s": 0.0, "mn": np.inf, "mx": -np.inf},
        )
        c["rows"] += 1
        if ok:
            c["n"] += 1
            c["s"] += v
            c["mn"] = min(c["mn"], v)
            c["mx"] = max(c["mx"], v)
    return cells


def _feed(seed=0, n=5000, n_units=7, n_gids=23, null_frac=0.1):
    rng = np.random.default_rng(seed)
    units = rng.integers(0, n_units, n).astype(np.int64)
    gids = rng.integers(0, n_gids, n).astype(np.int32)
    vals = rng.normal(100.0, 30.0, n)
    valid = rng.random(n) >= null_frac
    return units, gids, vals, valid


def _accumulate(store, units, gids, vals, valid, ngroups, chunks=4):
    edges = np.linspace(0, len(units), chunks + 1).astype(int)
    for a, b in zip(edges[:-1], edges[1:]):
        store.accumulate(
            units[a:b],
            gids[a:b],
            vals[a:b].reshape(-1, 1),
            valid[a:b].reshape(-1, 1),
            ngroups,
        )


def test_segment_bounds_partition_batch_exactly():
    units, gids, _v, _ok = _feed(seed=3, n=1000)
    order, starts, seg_u, seg_g = slice_segment_bounds(units, gids, 32)
    # every row lands in exactly one segment, and segment cells are unique
    total = 0
    ends = np.append(starts[1:], len(units))
    seen = set()
    for i in range(len(starts)):
        lo, hi = int(starts[i]), int(ends[i])
        total += hi - lo
        cell = (int(seg_u[i]), int(seg_g[i]))
        assert cell not in seen
        seen.add(cell)
        assert (units[order[lo:hi]] == cell[0]).all()
        assert (gids[order[lo:hi]] == cell[1]).all()
    assert total == len(units)


def test_segment_bounds_negative_units():
    units = np.array([-3, -3, -1, 0, 2], dtype=np.int64)
    gids = np.array([1, 2, 1, 0, 1], dtype=np.int32)
    _order, _starts, seg_u, seg_g = slice_segment_bounds(units, gids, 16)
    assert seg_u.tolist() == [-3, -3, -1, 0, 2]
    assert seg_g.tolist() == [1, 2, 1, 0, 1]


def test_accumulate_matches_brute_force_with_nulls():
    units, gids, vals, valid = _feed()
    store = SliceStore(COMPONENTS, unit_ms=1000)
    _accumulate(store, units, gids, vals, valid, ngroups=23)
    cells = _brute_cells(units, gids, vals, valid)
    for (u, g), c in cells.items():
        slot = store._units[u]
        assert slot["count_star"][g] == c["rows"]
        assert slot["count_0"][g] == c["n"]
        assert slot["sum_0"][g] == pytest.approx(c["s"], rel=1e-12)
        if c["n"]:
            assert slot["min_0"][g] == c["mn"]
            assert slot["max_0"][g] == c["mx"]
        else:
            assert np.isposinf(slot["min_0"][g])
            assert np.isneginf(slot["max_0"][g])


def test_fold_matches_direct_aggregation_over_range():
    units, gids, vals, valid = _feed(seed=9)
    store = SliceStore(COMPONENTS, unit_ms=1000)
    _accumulate(store, units, gids, vals, valid, ngroups=23)
    rows = store.fold(2, 6)  # units [2, 6)
    sel = (units >= 2) & (units < 6)
    cells = _brute_cells(
        units[sel], np.zeros(sel.sum(), np.int32) + gids[sel], vals[sel],
        valid[sel],
    )
    per_g = {}
    for (_u, g), c in cells.items():
        t = per_g.setdefault(
            g, {"rows": 0, "n": 0, "s": 0.0, "mn": np.inf, "mx": -np.inf}
        )
        t["rows"] += c["rows"]
        t["n"] += c["n"]
        t["s"] += c["s"]
        t["mn"] = min(t["mn"], c["mn"])
        t["mx"] = max(t["mx"], c["mx"])
    for g, t in per_g.items():
        assert rows["count_star"][g] == t["rows"]
        assert rows["count_0"][g] == t["n"]
        assert rows["sum_0"][g] == pytest.approx(t["s"], rel=1e-12)
        if t["n"]:
            assert rows["min_0"][g] == t["mn"]
            assert rows["max_0"][g] == t["mx"]


def test_fold_empty_range_returns_none():
    store = SliceStore(COMPONENTS, unit_ms=1000)
    units, gids, vals, valid = _feed(n=100, n_units=3)
    _accumulate(store, units, gids, vals, valid, ngroups=23, chunks=1)
    assert store.fold(50, 60) is None


def test_fold_single_unit_copies():
    """A one-unit fold must hand back a COPY — emission finalize mutates
    nothing, but a caller holding the rows across a later accumulate
    must not see them change underneath."""
    store = SliceStore(COMPONENTS, unit_ms=1000)
    u = np.zeros(4, np.int64)
    g = np.zeros(4, np.int32)
    v = np.ones((4, 1))
    ok = np.ones((4, 1), bool)
    store.accumulate(u, g, v, ok, 1)
    rows = store.fold(0, 1)
    store.accumulate(u, g, v, ok, 1)
    assert rows["count_star"][0] == 4
    assert store.fold(0, 1)["count_star"][0] == 8


def test_capacity_growth_preserves_partials():
    store = SliceStore(COMPONENTS, unit_ms=1000)
    units, gids, vals, valid = _feed(seed=1, n=500, n_gids=10)
    _accumulate(store, units, gids, vals, valid, ngroups=10, chunks=1)
    before = store.fold(0, 7)
    cap0 = store.capacity
    # a second batch with 10x the gid space forces growth
    units2, gids2, vals2, valid2 = _feed(seed=2, n=500, n_gids=300)
    _accumulate(store, units2, gids2, vals2, valid2, ngroups=300, chunks=1)
    assert store.capacity > cap0
    after = store.fold(0, 7)
    # the original gids' contributions survived the growth
    cells1 = _brute_cells(units, gids, vals, valid)
    cells2 = _brute_cells(units2, gids2, vals2, valid2)
    for g in range(10):
        rows = sum(c["rows"] for (u, gg), c in cells1.items() if gg == g)
        rows += sum(c["rows"] for (u, gg), c in cells2.items() if gg == g)
        assert after["count_star"][g] == rows
    assert before["count_star"][:10].sum() == sum(
        c["rows"] for c in cells1.values()
    )


def test_prune_drops_only_below_floor():
    store = SliceStore(COMPONENTS, unit_ms=1000)
    units, gids, vals, valid = _feed(n=200, n_units=10)
    _accumulate(store, units, gids, vals, valid, ngroups=23, chunks=1)
    assert store.prune(4) == 4
    assert store.live_units() == [4, 5, 6, 7, 8, 9]
    assert store.fold(0, 4) is None


def test_snapshot_restore_bit_exact():
    store = SliceStore(COMPONENTS, unit_ms=1000)
    units, gids, vals, valid = _feed(seed=7)
    _accumulate(store, units, gids, vals, valid, ngroups=23)
    arrays = store.snapshot_arrays(23)
    other = SliceStore(COMPONENTS, unit_ms=1000)
    other.restore_arrays(
        {k: v.copy() for k, v in arrays.items()}, 23
    )
    assert other.live_units() == store.live_units()
    a = store.fold(0, 7)
    b = other.fold(0, 7)
    for label in a:
        np.testing.assert_array_equal(a[label][:23], b[label][:23])
    # continued accumulation after restore stays bit-identical
    u2, g2, v2, ok2 = _feed(seed=8, n=1000)
    _accumulate(store, u2, g2, v2, ok2, ngroups=23, chunks=1)
    _accumulate(other, u2, g2, v2, ok2, ngroups=23, chunks=1)
    a = store.fold(0, 7)
    b = other.fold(0, 7)
    for label in a:
        np.testing.assert_array_equal(a[label][:23], b[label][:23])


def test_dense_and_sort_lanes_agree():
    """Add-only component sets take the bincount lane; forcing the sort
    lane over the same rows must agree to float64 rounding (the lanes
    may associate long-segment adds differently — lane CHOICE is a pure
    function of components + batch shape, so identical runs always take
    identical lanes; cross-lane identity is not part of the contract)."""
    comps = tuple(components_for([("count", 0), ("sum", 0), ("avg", 0)]))
    units, gids, vals, valid = _feed(seed=17, n=4000)
    dense = SliceStore(comps, unit_ms=1000)
    assert dense._add_only
    _accumulate(dense, units, gids, vals, valid, ngroups=23)
    sortl = SliceStore(comps, unit_ms=1000)
    sortl._add_only = False
    _accumulate(sortl, units, gids, vals, valid, ngroups=23)
    assert dense.live_units() == sortl.live_units()
    for u in dense.live_units():
        for comp in comps:
            a = dense._units[u][comp.label]
            b = sortl._units[u][comp.label]
            if comp.kind == "count":
                np.testing.assert_array_equal(a, b)
            else:
                np.testing.assert_allclose(a, b, rtol=1e-12)


def test_dense_lane_guard_falls_back_on_sparse_span():
    """A batch whose unit span dwarfs its rows must not allocate a
    span*cap bincount — the sort lane takes it instead, with identical
    results."""
    comps = tuple(components_for([("count", 0), ("sum", 0)]))
    store = SliceStore(comps, unit_ms=1000)
    units = np.array([0, 10_000_000], dtype=np.int64)
    gids = np.zeros(2, np.int32)
    store.accumulate(
        units, gids, np.ones((2, 1)), np.ones((2, 1), bool), 1
    )
    assert store.live_units() == [0, 10_000_000]
    assert store._units[0]["sum_0"][0] == 1.0


def test_fold_slices_deterministic():
    rng = np.random.default_rng(0)
    stack = rng.normal(0, 1, (9, 64))
    assert (
        fold_slices("sum", stack) == fold_slices("sum", stack.copy())
    ).all()
    assert (
        fold_slices("min", stack) == np.minimum.reduce(stack, axis=0)
    ).all()


def test_variance_components_fold_additively():
    """The variance family rides shifted-moment components: folding
    per-slice (count, Σ(x−K), Σ(x−K)²) by addition is the exact
    constant-pivot Chan combine, so a fold over two slices must equal
    accumulating all rows into one slice."""
    comps = tuple(components_for([("var", 0, 1)]))
    rng = np.random.default_rng(4)
    x = rng.normal(1e6, 1.0, 2000)  # large magnitude: pivot matters
    K = x[0]
    shifted = np.stack([x - K, (x - K) ** 2], axis=1)
    ok = np.ones((2000, 2), bool)
    g = np.zeros(2000, np.int32)
    split = SliceStore(comps, unit_ms=1000)
    split.accumulate(
        np.repeat(np.array([0, 1], np.int64), 1000), g, shifted, ok, 1
    )
    one = SliceStore(comps, unit_ms=1000)
    one.accumulate(np.zeros(2000, np.int64), g, shifted, ok, 1)
    a = split.fold(0, 2)
    b = one.fold(0, 1)
    for label in a:
        np.testing.assert_allclose(
            a[label][:1], b[label][:1], rtol=1e-12
        )
