"""Prefetch supervisor: a worker crash mid-stream must heal — restart with
backoff, rebuild the reader at the last ENQUEUED offset snapshot — without
replaying rows the consumer already saw and without losing any; past the
restart budget it must fail structurally, not hang."""

import json
import threading
import time

import pytest

from denormalized_tpu.common.errors import SourceError
from denormalized_tpu.runtime import faults
from denormalized_tpu.runtime.prefetch import (
    PrefetchPump,
    PrefetchRestartExhausted,
)
from denormalized_tpu.sources.kafka import KafkaTopicBuilder
from denormalized_tpu.testing.mock_kafka import MockKafkaBroker

T0 = 1_700_000_000_000
SAMPLE = '{"ts": 1, "p": 1, "i": 1}'


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faults.disarm()


@pytest.fixture
def broker():
    b = MockKafkaBroker().start()
    try:
        yield b
    finally:
        b.stop()


def _source(broker, topic, **opts):
    b = (
        KafkaTopicBuilder(broker.bootstrap)
        .with_topic(topic)
        .infer_schema_from_json(SAMPLE)
        .with_timestamp_column("ts")
    )
    for k, v in opts.items():
        b = b.with_option(k, v)
    return b.build_reader()


def _fill(broker, topic, parts, rows_per_part, chunk=64):
    broker.create_topic(topic, partitions=parts)
    for p in range(parts):
        for base in range(0, rows_per_part, chunk):
            payloads = [
                json.dumps({"ts": T0 + i * 3, "p": p, "i": i}).encode()
                for i in range(base, min(base + chunk, rows_per_part))
            ]
            broker.produce_batched(topic, p, payloads, ts_ms=T0)


def _drain_rows(pump, total_rows, deadline_s=30.0):
    """→ {partition: [i...]} in consumption order."""
    seen = {}
    deadline = time.monotonic() + deadline_s
    for idx, _snap, batch in pump.drain(
        total_rows=total_rows, deadline=deadline
    ):
        part = int(batch.column("p")[0])
        seen.setdefault(part, []).extend(int(v) for v in batch.column("i"))
    return seen


def test_worker_crash_recovers_no_lost_no_replayed_rows(broker):
    """Injected crashes mid-stream (non-transport errors escape the
    reader) recover via restart+reseek: each partition's row ids come out
    exactly once, in order — the offset-snapshot restart contract."""
    parts, rows = 2, 1500
    _fill(broker, "sup", parts, rows)
    src = _source(broker, "sup", **{"max.batch.rows": 128,
                                    "fetch.coalesce.rows": 0})
    faults.arm({"seed": 2, "rules": [
        # first crash on the very first fetch anywhere (that partition
        # cannot deliver a row without a successful restart), second a
        # couple of fetches later — possibly mid-catch-up on the rebuilt
        # reader
        {"site": "kafka.fetch", "kind": "error", "times": 1,
         "message": "injected worker crash A"},
        {"site": "kafka.fetch", "kind": "error", "after": 2, "times": 1,
         "message": "injected worker crash B"},
    ]})
    pump = PrefetchPump(
        src.partitions(),
        reader_factories=src.partition_factories(),
        restart_budget=5,
    ).start()
    try:
        seen = _drain_rows(pump, parts * rows)
    finally:
        stragglers = pump.stop(join_timeout_s=5.0)
    assert stragglers == []
    for p in range(parts):
        assert seen[p] == list(range(rows)), (
            f"partition {p}: dup or lost rows after supervised restart"
        )
    stats = pump.restart_stats()
    # crash A's restart is guaranteed (its partition delivered nothing
    # before the crash, and every row came out); crash B may land after
    # the consumer already finished — racing the shutdown is fine, LOSING
    # rows is not
    assert 1 <= stats["restarts"] <= 2, stats
    assert stats["restarted_partitions"] >= 1, stats
    assert stats["last_errors"], stats


def test_restart_budget_exhausted_escalates_structured_failure(broker):
    """A permanently-failing partition surfaces PrefetchRestartExhausted
    (partition + attempts + last error), not a hang and not a bare
    reader exception."""
    _fill(broker, "dead", 1, 200)
    src = _source(broker, "dead")
    faults.arm({"seed": 2, "rules": [
        {"site": "kafka.fetch", "kind": "error",
         "message": "injected permanent failure"},  # unlimited
    ]})
    pump = PrefetchPump(
        src.partitions(),
        reader_factories=src.partition_factories(),
        restart_budget=2,
    ).start()
    try:
        with pytest.raises(PrefetchRestartExhausted) as ei:
            for _ in pump.drain(total_rows=200,
                                deadline=time.monotonic() + 20):
                pass
        assert ei.value.partition == 0
        assert ei.value.attempts == 2
        assert "injected permanent failure" in str(ei.value.last_error)
    finally:
        pump.stop(join_timeout_s=5.0)


def test_without_factories_crash_surfaces_verbatim(broker):
    """No factories (sources that opt out) = the pre-supervisor contract:
    the first worker exception reaches the consumer."""
    _fill(broker, "nofac", 1, 100)
    src = _source(broker, "nofac")
    faults.arm({"seed": 2, "rules": [
        {"site": "kafka.fetch", "kind": "error", "times": 1,
         "message": "injected crash (unsupervised)"},
    ]})
    pump = PrefetchPump(src.partitions()).start()
    try:
        with pytest.raises(SourceError, match="unsupervised"):
            for _ in pump.drain(total_rows=100,
                                deadline=time.monotonic() + 20):
                pass
    finally:
        pump.stop(join_timeout_s=5.0)


def test_empty_factory_list_hits_length_guard(broker):
    """Review-found hole: `reader_factories or ...` treated an empty
    LIST like the None sentinel, silently disabling supervision for
    every partition instead of raising the length-mismatch error."""
    _fill(broker, "emptyfac", 1, 10)
    src = _source(broker, "emptyfac")
    with pytest.raises(ValueError, match="0 reader factories"):
        PrefetchPump(src.partitions(), reader_factories=[])


def test_restart_budget_heals_after_crash_free_interval(broker):
    """Review-found design flaw: lifetime budgets guaranteed death for
    any long-lived stream with occasional healed hiccups.  The streak
    must reset (and global tokens refund) after a crash-free interval,
    so two well-separated transient crashes survive a budget of 1."""
    _fill(broker, "heal", 1, 400)
    src = _source(broker, "heal")
    faults.arm({"seed": 2, "rules": [
        {"site": "kafka.fetch", "kind": "error", "times": 1,
         "message": "injected hiccup one"},
        # ~15 post-restart reads later (0.1s timeout each): well past the
        # 0.3s heal interval below
        {"site": "kafka.fetch", "kind": "error", "after": 15, "times": 1,
         "message": "injected hiccup two"},
    ]})
    pump = PrefetchPump(
        src.partitions(),
        reader_factories=src.partition_factories(),
        restart_budget=1,          # one restart per streak ONLY
        global_restart_budget=1,   # and one global token
        restart_heal_s=0.3,
    ).start()
    try:
        seen = _drain_rows(pump, 400)
        assert seen[0] == list(range(400))
        deadline = time.monotonic() + 10
        while pump.workers[0].restarts < 2:
            assert time.monotonic() < deadline, pump.restart_stats()
            time.sleep(0.05)
        assert pump.workers[0].restarts == 2  # both hiccups healed
    finally:
        faults.disarm()
        pump.stop(join_timeout_s=5.0)


def test_restarting_partition_never_judged_idle(broker):
    """Review-found bug: during backoff/rebuild a crashed partition used
    to look idle (pending=False, stale first_read_done, caught_up=None),
    so the watermark could advance over the rows the restart re-reads —
    late-dropping them.  The crash must pin the partition as
    known-backlog until the rebuilt reader's first fetch reports."""
    _fill(broker, "idlepin", 1, 500)
    src = _source(broker, "idlepin")
    faults.arm({"seed": 2, "rules": [
        # crash every fetch: the worker stays in backoff/rebuild loops
        {"site": "kafka.fetch", "kind": "error",
         "message": "injected permanent-ish failure"},
    ]})
    pump = PrefetchPump(
        src.partitions(),
        reader_factories=src.partition_factories(),
        restart_budget=50,
        global_restart_budget=50,
    ).start()
    try:
        deadline = time.monotonic() + 5
        saw_restart = False
        while time.monotonic() < deadline:
            w = pump.workers[0]
            if w.restarts >= 1:
                saw_restart = True
                # in or between restarts: may_judge_idle must be False
                # and the reader side must not be quiet
                assert w.activity()[3] is False, w.activity()
                assert not w.reader_quiet()
                assert not pump.quiet()
                if w.restarts >= 3:
                    break
            time.sleep(0.02)
        assert saw_restart
    finally:
        faults.disarm()
        pump.stop(join_timeout_s=5.0)


def test_stop_joins_workers_and_drains_queue(broker):
    """stop() must leave NO worker thread behind (live readers block-poll
    an idle topic forever otherwise) and release queued batches."""
    _fill(broker, "stopt", 2, 300)
    src = _source(broker, "stopt")
    before = {t.name for t in threading.enumerate()}
    pump = PrefetchPump(src.partitions()).start()
    # let workers enqueue up to their buffer depth, consumer never reads
    time.sleep(0.5)
    stragglers = pump.stop(join_timeout_s=5.0)
    assert stragglers == []
    after = {t.name for t in threading.enumerate()}
    leaked = {n for n in after - before if n.startswith("prefetch-")}
    assert not leaked, f"leaked worker threads: {leaked}"
    assert pump._q.qsize() == 0  # drained: no batch refs outlive the query


def test_supervisor_metrics_visible_in_source_exec(broker):
    """SourceExec.metrics() must expose restart counts on the production
    path (the acceptance-criteria observability hook)."""
    from denormalized_tpu.physical.simple_execs import SourceExec
    from denormalized_tpu.common.record_batch import RecordBatch

    parts, rows = 2, 600
    _fill(broker, "supm", parts, rows)
    src = _source(broker, "supm", **{"max.batch.rows": 64,
                                     "fetch.coalesce.rows": 0})
    faults.arm({"seed": 2, "rules": [
        # fires on the second fetch overall: a partition that still owes
        # rows, so the restart always lands before the stream completes
        {"site": "kafka.fetch", "kind": "error", "after": 1, "times": 1,
         "message": "injected worker crash"},
    ]})
    exec_ = SourceExec(src, idle_timeout_ms=200)
    n = 0
    it = exec_.run()
    deadline = time.monotonic() + 30
    for item in it:
        assert time.monotonic() < deadline, "stalled"
        if isinstance(item, RecordBatch):
            n += item.num_rows
        if n >= parts * rows:
            break
    it.close()
    m = exec_.metrics()
    assert m["rows_out"] == parts * rows
    assert m["prefetch_restarts"] == 1
    assert m["prefetch_restarted_partitions"] == 1
    assert m["prefetch_last_errors"], m


def test_get_live_raises_on_sentinelless_dead_worker():
    """Liveness backstop (PR-7): a worker thread that died WITHOUT its
    end-of-stream sentinel must surface as a structured SourceError from
    the consumer's queue wait, never an unbounded block — every live
    worker guarantees an item at least per read-timeout, so a starved
    queue plus a dead sentinel-less thread can never heal."""
    from denormalized_tpu.runtime.prefetch import PrefetchPump

    pump = PrefetchPump([object()], queue_budget=4)
    w = pump.workers[0]
    # simulate the lost-sentinel failure: a thread object that ran and
    # died without w.finished / the sentinel ever being set
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()
    w._thread = t
    assert not w.finished
    with pytest.raises(SourceError, match="without an end-of-stream"):
        pump.get_live(timeout_s=0.2)


def test_get_live_keeps_waiting_while_workers_alive():
    """Alive-but-slow workers (a long native recv) must NOT trip the
    backstop: get_live only raises for dead sentinel-less threads."""
    from denormalized_tpu.runtime.prefetch import PrefetchPump

    pump = PrefetchPump([object()], queue_budget=4)
    w = pump.workers[0]
    stop = threading.Event()
    t = threading.Thread(target=stop.wait, daemon=True)
    t.start()
    w._thread = t
    try:
        # starved queue + live worker: one timeout cycle logs and waits;
        # an item arriving on the next cycle is returned normally
        def feed():
            time.sleep(0.35)
            pump._q.put((0, {"pos": 1}, None, 0.0))

        threading.Thread(target=feed, daemon=True).start()
        idx, snap, b = pump.get_live(timeout_s=0.15)
        assert idx == 0 and snap == {"pos": 1} and b is None
    finally:
        stop.set()
