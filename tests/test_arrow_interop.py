"""pyarrow interop: the reference's Python surface hands user callbacks
pyarrow RecordBatches (py-denormalized/src/datastream.rs:244-252) and its
vendored layer is pyarrow-based throughout — these tests pin the
conversion bridge a migrating user relies on."""

import numpy as np
import pytest

pa = pytest.importorskip("pyarrow")

from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema


def _flat_batch():
    schema = Schema(
        [
            Field("ts", DataType.TIMESTAMP_MS, nullable=False),
            Field("name", DataType.STRING, nullable=False),
            Field("reading", DataType.FLOAT64),
            Field("n", DataType.INT64),
            Field("ok", DataType.BOOL),
        ]
    )
    return RecordBatch(
        schema,
        [
            np.array([1000, 2000, 3000], dtype=np.int64),
            np.array(["a", "béta", "c"], dtype=object),
            np.array([0.5, 0.0, -2.5]),
            np.array([7, 0, 9], dtype=np.int64),
            np.array([True, False, True]),
        ],
        masks=[
            None,
            None,
            np.array([True, False, True]),
            np.array([True, False, True]),
            None,
        ],
    )


def test_to_pyarrow_types_and_nulls():
    rb = _flat_batch().to_pyarrow()
    assert rb.num_rows == 3
    assert rb.schema.field("ts").type == pa.timestamp("ms")
    assert rb.schema.field("name").type == pa.string()
    assert rb.schema.field("reading").type == pa.float64()
    assert rb.schema.field("n").type == pa.int64()
    assert rb.schema.field("ok").type == pa.bool_()
    assert rb.column("reading").null_count == 1
    assert rb.column("reading").to_pylist() == [0.5, None, -2.5]
    assert rb.column("n").to_pylist() == [7, None, 9]
    assert rb.column("name").to_pylist() == ["a", "béta", "c"]


def test_pyarrow_roundtrip():
    b = _flat_batch()
    back = RecordBatch.from_pyarrow(b.to_pyarrow())
    assert [f.dtype for f in back.schema] == [f.dtype for f in b.schema]
    for name in b.schema.names:
        ma, mb = b.mask(name), back.mask(name)
        assert (ma is None) == (mb is None), name
        if ma is not None:
            np.testing.assert_array_equal(ma, mb)
        va, vb = b.column(name), back.column(name)
        if va.dtype == object:
            assert va.tolist() == vb.tolist()
        else:
            keep = np.ones(len(va), bool) if ma is None else ma
            np.testing.assert_array_equal(va[keep], vb[keep])


def test_from_pyarrow_external_batch():
    """A batch built by pyarrow directly (a migrating user's data)."""
    rb = pa.RecordBatch.from_pydict(
        {
            "k": pa.array(["x", None, "z"]),
            "v": pa.array([1.5, 2.5, None]),
            "t": pa.array([1, 2, 3], type=pa.timestamp("ms")),
        }
    )
    b = RecordBatch.from_pyarrow(rb)
    assert b.schema.field("k").dtype is DataType.STRING
    assert b.schema.field("v").dtype is DataType.FLOAT64
    assert b.schema.field("t").dtype is DataType.TIMESTAMP_MS
    assert b.column("k").tolist() == ["x", None, "z"]
    assert b.mask("v").tolist() == [True, True, False]
    assert b.column("t").tolist() == [1, 2, 3]


def test_nested_struct_list_to_pyarrow():
    schema = Schema(
        [
            Field("id", DataType.INT64, nullable=False),
            Field(
                "gps",
                DataType.STRUCT,
                children=(
                    Field("lat", DataType.FLOAT64),
                    Field("lon", DataType.FLOAT64),
                ),
            ),
            Field("tags", DataType.LIST),
        ]
    )
    gps = np.empty(2, dtype=object)
    gps[:] = [{"lat": 1.0, "lon": 2.0}, {"lat": 3.0, "lon": 4.0}]
    tags = np.empty(2, dtype=object)
    tags[:] = [["a", "b"], []]
    b = RecordBatch(
        schema, [np.array([1, 2], dtype=np.int64), gps, tags]
    )
    rb = b.to_pyarrow()
    assert pa.types.is_struct(rb.schema.field("gps").type)
    assert pa.types.is_list(rb.schema.field("tags").type)
    assert rb.column("gps").to_pylist()[1] == {"lat": 3.0, "lon": 4.0}
    back = RecordBatch.from_pyarrow(rb)
    assert back.column("tags").tolist() == [["a", "b"], []]


def test_sink_as_pyarrow_end_to_end():
    """ds.sink(fn, as_pyarrow=True): the callback sees pyarrow batches
    with internal columns stripped, through a real windowed pipeline."""
    from denormalized_tpu.api import functions as F
    from denormalized_tpu.api.context import Context
    from denormalized_tpu.api.functions import col
    from denormalized_tpu.sources.memory import MemorySource

    schema = Schema(
        [
            Field("occurred_at_ms", DataType.INT64, nullable=False),
            Field("sensor_name", DataType.STRING, nullable=False),
            Field("reading", DataType.FLOAT64),
        ]
    )
    t0 = 1_700_000_000_000
    rng = np.random.default_rng(3)
    batches = []
    for i in range(8):
        ts = np.sort(t0 + i * 500 + rng.integers(0, 500, 256))
        names = np.array(
            [f"s{k}" for k in rng.integers(0, 4, 256)], dtype=object
        )
        batches.append(
            RecordBatch(schema, [ts, names, rng.normal(10, 2, 256)])
        )
    got = []
    ctx = Context()
    (
        ctx.from_source(
            MemorySource.from_batches(
                batches, timestamp_column="occurred_at_ms"
            )
        )
        .window(
            [col("sensor_name")],
            [F.count(col("reading")).alias("count")],
            1000,
        )
        .sink(got.append, as_pyarrow=True)
    )
    assert got, "no batches delivered"
    for rb in got:
        assert isinstance(rb, pa.RecordBatch)
        names = rb.schema.names
        assert "window_start_time" in names and "count" in names
        assert not any(n.startswith("_") for n in names)


def test_from_pyarrow_normalizes_us_ns_timestamps():
    """us/ns timestamps (pandas default is ns) must land as millisecond
    values, not raw unit counts mislabeled TIMESTAMP_MS."""
    rb = pa.RecordBatch.from_pydict(
        {
            "us": pa.array([1_700_000_000_000_000], type=pa.timestamp("us")),
            "ns": pa.array(
                [1_700_000_000_000_000_000], type=pa.timestamp("ns")
            ),
        }
    )
    b = RecordBatch.from_pyarrow(rb)
    assert b.column("us").tolist() == [1_700_000_000_000]
    assert b.column("ns").tolist() == [1_700_000_000_000]


def test_empty_struct_list_batches_keep_schema():
    """A zero-row batch must produce the SAME arrow schema as a populated
    one (a windowed stream interleaves empty emissions; consumers concat
    by schema)."""
    schema = Schema(
        [
            Field(
                "gps",
                DataType.STRUCT,
                children=(
                    Field("lat", DataType.FLOAT64),
                    Field("lon", DataType.FLOAT64),
                ),
            ),
            Field("tags", DataType.LIST, children=(Field("", DataType.STRING),)),
        ]
    )
    empty = RecordBatch.empty(schema).to_pyarrow()
    gps = np.empty(1, dtype=object)
    gps[:] = [{"lat": 1.0, "lon": 2.0}]
    tags = np.empty(1, dtype=object)
    tags[:] = [["a"]]
    full = RecordBatch(schema, [gps, tags]).to_pyarrow()
    assert empty.schema.field("gps").type == full.schema.field("gps").type
    assert empty.schema.field("tags").type == full.schema.field("tags").type
    back = RecordBatch.from_pyarrow(empty)  # must not raise
    assert back.num_rows == 0


def test_from_pyarrow_rejects_uint64():
    from denormalized_tpu.common.errors import SchemaError

    rb = pa.RecordBatch.from_pydict(
        {"u": pa.array([2**63 + 5], type=pa.uint64())}
    )
    with pytest.raises(SchemaError):
        RecordBatch.from_pyarrow(rb)
