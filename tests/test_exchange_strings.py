"""Exchange framing round-trips of adversarial string columns — the raw
offsets+bytes lane AND the legacy JSON lane, cross-checked identical
(ISSUE 12 satellite).  Covers empty strings, multi-byte UTF-8,
null-heavy masks, and 0-row batches."""

import numpy as np
import pytest

from denormalized_tpu.cluster import framing
from denormalized_tpu.common.columns import (
    NestedColumn,
    PrimitiveColumn,
    StringColumn,
)
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema

F, S, D = Field, Schema, DataType

SCHEMA = S([F("k", D.STRING), F("v", D.INT64)])


def _roundtrip(batch, schema):
    frame = framing.encode_data(batch, 777)
    payload = frame[framing._HDR.size:]
    # the frame itself must verify (CRC over the raw sub-buffers)
    import io

    class _Sock:
        def __init__(self, b):
            self._b = io.BytesIO(b)

        def recv(self, n):
            return self._b.read(n)

    got = framing.read_frame(_Sock(frame))
    assert got == payload
    t, decoded, wm, _part = framing.decode_frame(payload, schema)
    assert t == "data" and wm == 777
    return decoded


def _cases():
    rng = np.random.default_rng(5)
    empty_heavy = ["" if i % 3 else f"v{i}" for i in range(64)]
    multibyte = ["日本語テキスト", "éàü", "😀😀", "mixédバイト", ""] * 10
    null_heavy = [
        None if rng.random() < 0.7 else f"k{i}" for i in range(128)
    ]
    return {
        "empty_strings": empty_heavy,
        "multibyte_utf8": multibyte,
        "null_heavy": null_heavy,
        "zero_rows": [],
    }


@pytest.mark.parametrize("name,vals", sorted(_cases().items()))
def test_raw_and_legacy_lanes_identical(name, vals, monkeypatch):
    obj = np.empty(len(vals), dtype=object)
    obj[:] = vals
    col = StringColumn.from_objects(obj)
    mask = col.validity
    v = np.arange(len(vals), dtype=np.int64)
    b_col = RecordBatch(SCHEMA, [col, v], [mask, None])
    b_obj = RecordBatch(SCHEMA, [obj, v], [mask, None])

    got_raw = _roundtrip(b_col, SCHEMA)
    assert isinstance(got_raw.columns[0], StringColumn) or not vals
    monkeypatch.setenv("DENORMALIZED_EXCHANGE_JSON", "1")
    got_legacy = _roundtrip(b_obj, SCHEMA)
    monkeypatch.delenv("DENORMALIZED_EXCHANGE_JSON")

    # the two lanes decode to IDENTICAL logical batches...
    assert got_raw.to_pydict() == got_legacy.to_pydict() == b_obj.to_pydict()
    # ...and the raw lane's re-encoded emission bytes are identical to
    # the legacy lane's (byte-identical cross-check at the row encoder)
    from denormalized_tpu.formats.json_codec import JsonRowEncoder

    enc = JsonRowEncoder()
    assert enc.encode(got_raw) == enc.encode(got_legacy)


def test_raw_lane_elides_duplicate_validity():
    """A columnar column's validity rides its own sub-frames; the batch
    mask (the same array) must not be shipped a second time — and the
    decode side must still surface it as the batch mask."""
    vals = [None if i % 3 else f"k{i}" for i in range(512)]
    obj = np.empty(len(vals), dtype=object)
    obj[:] = vals
    col = StringColumn.from_objects(obj)
    v = np.arange(len(vals), dtype=np.int64)
    b = RecordBatch(SCHEMA, [col, v], [col.validity, None])
    frame = framing.encode_data(b, None)
    # a frame shipping validity twice would be >= len(vals) bytes larger
    detached = RecordBatch(SCHEMA, [col, v], [col.validity.copy(), None])
    frame_dup = framing.encode_data(detached, None)
    # ~1 byte per row saved (modulo a few header chars)
    assert len(frame_dup) - len(frame) >= len(vals) - 16
    _t, got, _wm, _part = framing.decode_frame(
        frame[framing._HDR.size:], SCHEMA
    )
    np.testing.assert_array_equal(
        np.asarray(got.mask("k"), dtype=bool), col.validity
    )
    assert got.to_pydict() == b.to_pydict()


def test_raw_lane_nested_column_roundtrip():
    sch = S([F("st", D.STRUCT, children=(F("x", D.INT64),
                                         F("s", D.STRING)))])
    prim = PrimitiveColumn(
        "i64", np.arange(5), np.array([True, True, False, True, True])
    )
    ss = StringColumn.from_objects(
        np.array(["", "日本", None, "d", "e"], dtype=object)
    )
    st = NestedColumn(
        sch.field("st"), "struct", 5, [prim, ss],
        validity=np.array([True, False, True, True, True]),
    )
    b = RecordBatch(sch, [st], [st.validity])
    got = _roundtrip(b, sch)
    assert isinstance(got.columns[0], NestedColumn)
    assert got.to_pydict() == b.to_pydict()


def test_torn_columnar_frame_detected():
    col = StringColumn.from_objects(
        np.array(["abc"] * 50, dtype=object)
    )
    b = RecordBatch(SCHEMA, [col, np.arange(50)], [None, None])
    frame = bytearray(framing.encode_data(b, None))
    frame[-3] ^= 0xFF  # flip a byte inside the string data buffer
    import io

    from denormalized_tpu.common.errors import SourceError

    class _Sock:
        def __init__(self, bb):
            self._b = io.BytesIO(bytes(bb))

        def recv(self, n):
            return self._b.read(n)

    with pytest.raises(SourceError, match="CRC"):
        framing.read_frame(_Sock(frame))


def test_router_buckets_identical_across_lanes():
    """hash routing of a StringColumn bucketizes exactly like the same
    keys as an object column — rescale/bucket compat across lanes."""
    from denormalized_tpu.cluster.hashing import bucket_rows

    vals = ["a", "", "日本語", None, "key-123"] * 20
    obj = np.empty(len(vals), dtype=object)
    obj[:] = vals
    col = StringColumn.from_objects(obj)
    np.testing.assert_array_equal(
        bucket_rows([col], 4), bucket_rows([obj], 4)
    )
