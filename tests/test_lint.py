"""dnzlint gate: the committed tree must be clean, and every pass must
demonstrably FIRE on a purpose-built bad fixture — a lint suite that
never fails is indistinguishable from one that never runs.

Modeled on test_native_build_gate.py: this is a tier-1 test, so a
regression (new swallowed except, lock inversion, renamed fault site,
per-row loop in a pinned kernel) fails the suite with file:line and
rule id.
"""

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from tools.dnzlint import Finding, load_baseline, run_all  # noqa: E402
from tools.dnzlint.faultsites import fault_site_table, site_inventory  # noqa: E402
from tools.dnzlint.metricsreg import (  # noqa: E402
    load_catalog,
    metric_catalog_table,
    usage_inventory,
)

ENGINE = REPO / "denormalized_tpu"
BASELINE = REPO / "tools" / "dnzlint" / "baseline.toml"


# -- the gate --------------------------------------------------------------

def test_committed_tree_is_clean():
    new, suppressed, stale = run_all(ENGINE)
    assert new == [], "\n" + "\n".join(f.render() for f in new)
    # the suppression story must be real: findings exist and are absorbed
    # by reasoned pragmas/baseline — not "the passes found nothing"
    assert len(suppressed) >= 10
    assert stale == [], f"stale baseline entries: {stale}"


def test_baseline_is_nonempty_and_reasoned():
    baseline = load_baseline(BASELINE)
    assert len(baseline) >= 2
    for key, reason in baseline.items():
        assert len(reason) > 20, f"throwaway reason for {key}: {reason!r}"


def test_cli_exits_zero_on_committed_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dnzlint", "denormalized_tpu"],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_fault_site_docs_table_cannot_drift():
    """docs/fault_tolerance.md embeds the table generated from the
    verified site inventory (python -m tools.dnzlint --fault-site-table);
    regenerate the docs block when sites change."""
    table = fault_site_table(ENGINE)
    docs = (REPO / "docs" / "fault_tolerance.md").read_text()
    assert table in docs, (
        "docs/fault_tolerance.md fault-site table is stale — regenerate "
        "with: python -m tools.dnzlint --fault-site-table\n\n" + table
    )


def test_metric_catalog_docs_table_cannot_drift():
    """docs/observability.md embeds the table generated from the obs
    catalog + verified binder sites (python -m tools.dnzlint
    --metric-catalog); regenerate the docs block when instruments
    change."""
    table = metric_catalog_table(ENGINE)
    docs = (REPO / "docs" / "observability.md").read_text()
    assert table in docs, (
        "docs/observability.md metric-catalog table is stale — "
        "regenerate with: python -m tools.dnzlint --metric-catalog\n\n"
        + table
    )


def test_metric_usage_inventory_is_complete():
    catalog, _ = load_catalog(ENGINE)
    uses = usage_inventory(ENGINE)
    assert len(catalog) >= 15  # the engine-wide instrument surface
    for name in catalog:
        assert uses[name], f"instrument {name} has no binder call"
    # the layers the tentpole wires: physical, runtime, sources, state
    modules = {m for calls in uses.values() for m, _l in calls}
    for layer in ("physical/", "runtime/", "sources/", "state/"):
        assert any(layer in m for m in modules), layer


def test_site_inventory_is_complete():
    inv = site_inventory(ENGINE)
    assert set(inv) == {
        "kafka.fetch", "kafka.produce", "decode", "sink.write",
        "lsm.put", "lsm.get", "lsm.flush", "checkpoint.commit",
        "lsm.spill_put", "lsm.spill_get", "spill.manifest",
        "exchange.connect", "exchange.send", "exchange.recv",
        "exchange.reconnect", "cluster.rejoin", "cluster.replay",
    }
    for site, meta in inv.items():
        assert meta["calls"], f"site {site} has no inject call"
        assert meta["module"], f"site {site} has no declared module"


# -- bad fixtures: every pass must fire ------------------------------------

def _write_pkg(tmp_path: Path, files: dict[str, str]) -> Path:
    root = tmp_path / "badpkg"
    for rel, content in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(content))
    return root


def _rules(findings: list[Finding]) -> set[str]:
    return {f.rule for f in findings}


def test_lock_cycle_fires(tmp_path):
    root = _write_pkg(tmp_path, {"cyc.py": """\
        import threading


        class A:
            def __init__(self):
                self._la = threading.Lock()
                self._b = B()

            def go(self):
                with self._la:
                    self._b.poke()


        class B:
            def __init__(self):
                self._lb = threading.Lock()
                self._a = A()

            def poke(self):
                with self._lb:
                    pass

            def back(self):
                with self._lb:
                    self._a.go()
        """})
    new, _, _ = run_all(root, baseline_path=tmp_path / "nb.toml",
                        hotpaths_path=tmp_path / "nh.toml")
    cyc = [f for f in new if f.rule == "DNZ-L001"]
    assert len(cyc) == 1, [f.render() for f in new]
    assert "A._la" in cyc[0].symbol and "B._lb" in cyc[0].symbol
    # the report names both edges with their locations
    assert "cyc.py" in cyc[0].message and "->" in cyc[0].message


def test_direct_nested_inversion_fires(tmp_path):
    root = _write_pkg(tmp_path, {"inv.py": """\
        import threading

        L1 = threading.Lock()
        L2 = threading.Lock()


        def path_a():
            with L1:
                with L2:
                    pass


        def path_b():
            with L2:
                with L1:
                    pass
        """})
    new, _, _ = run_all(root, baseline_path=tmp_path / "nb.toml",
                        hotpaths_path=tmp_path / "nh.toml")
    assert "DNZ-L001" in _rules(new), [f.render() for f in new]


def test_blocking_under_lock_fires(tmp_path):
    root = _write_pkg(tmp_path, {"blk.py": """\
        import subprocess
        import threading
        import time


        class W:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = None

            def slow(self):
                with self._lock:
                    time.sleep(1.0)

            def drain(self):
                with self._lock:
                    return self._q.get(timeout=1.0)

            def build(self):
                with self._lock:
                    subprocess.run(["true"])
        """})
    new, _, _ = run_all(root, baseline_path=tmp_path / "nb.toml",
                        hotpaths_path=tmp_path / "nh.toml")
    blocking = [f for f in new if f.rule == "DNZ-L002"]
    msgs = " | ".join(f.message for f in blocking)
    assert "time.sleep" in msgs
    assert "_q.get" in msgs
    assert "subprocess.run" in msgs


def test_blocking_in_match_case_body_fires(tmp_path):
    """3.10 match statements: case bodies inside a held region are
    ordinary critical-section code and must not be a blind spot."""
    root = _write_pkg(tmp_path, {"mt.py": """\
        import threading
        import time


        class W:
            def __init__(self):
                self._lock = threading.Lock()

            def dispatch(self, kind):
                with self._lock:
                    match kind:
                        case "slow":
                            time.sleep(1.0)
                        case _:
                            pass
        """})
    new, _, _ = run_all(root, baseline_path=tmp_path / "nb.toml",
                        hotpaths_path=tmp_path / "nh.toml")
    blocking = [f for f in new if f.rule == "DNZ-L002"]
    assert any("time.sleep" in f.message for f in blocking), \
        [f.render() for f in new]


def test_swallowed_except_fires_and_pragma_suppresses(tmp_path):
    root = _write_pkg(tmp_path, {"sw.py": """\
        def bad():
            try:
                return 1
            except Exception:
                return None


        def bare():
            try:
                return 1
            except:
                pass


        def reraises():
            try:
                return 1
            except Exception as e:
                raise RuntimeError("wrapped") from e


        def allowed():
            try:
                return 1
            except Exception:  # dnzlint: allow(broad-except) fixture: deliberate
                return None


        def reasonless():
            try:
                return 1
            except Exception:  # dnzlint: allow(broad-except)
                return None
        """})
    new, suppressed, _ = run_all(root, baseline_path=tmp_path / "nb.toml",
                                 hotpaths_path=tmp_path / "nh.toml")
    e = [f for f in new if f.rule == "DNZ-E001"]
    symbols = {f.symbol for f in e}
    assert "bad" in symbols and "bare" in symbols
    assert "reraises" not in symbols  # converting + raising satisfies
    assert "allowed" not in symbols  # reasoned pragma suppresses
    assert any(f.symbol == "allowed" for f in suppressed)
    # a reasonless pragma does NOT suppress, and is itself reported
    assert "reasonless" in symbols
    assert any("no reason" in f.message for f in e)


def test_unknown_and_missing_fault_sites_fire(tmp_path):
    root = _write_pkg(tmp_path, {
        "runtime/faults.py": """\
            SITES = {
                "a.x": SourceError,
                "a.y": SourceError,
            }

            SITE_MODULES = {
                "a.x": ("mod.py", "x boundary"),
                "a.y": ("mod.py", "y boundary"),
            }


            def inject(site, key=None, payload=None):
                return payload
            """,
        "mod.py": """\
            from badpkg.runtime import faults


            def f():
                faults.inject("a.x")
                faults.inject("nope")
                faults.inject("a.x" + "")
            """,
    })
    new, _, _ = run_all(root, baseline_path=tmp_path / "nb.toml",
                        hotpaths_path=tmp_path / "nh.toml")
    f001 = [f for f in new if f.rule == "DNZ-F001"]
    f002 = [f for f in new if f.rule == "DNZ-F002"]
    assert any(f.symbol == "nope" for f in f001), [f.render() for f in new]
    assert any(f.symbol == "<dynamic>" for f in f001)
    # a.y is registered but never injected anywhere
    assert any(f.symbol == "a.y" for f in f002)


def test_metric_registry_pass_fires(tmp_path):
    """DNZ-M001 must fire in both directions plus the naming/kind
    checks, like DNZ-F001/F002 for fault sites."""
    root = _write_pkg(tmp_path, {
        "obs/catalog.py": """\
            INSTRUMENTS = {
                "dnz_good_total": ("counter", "a perfectly fine counter"),
                "dnz_unused_total": ("counter", "declared but never bound"),
                "dnz_bad_suffix": ("counter", "counter without _total"),
                "dnz_hist_nosuffix": ("histogram", "histogram sans unit"),
                "dnz_helpless_total": ("counter", ""),
                "badprefix_total": ("counter", "name without dnz_ prefix"),
                "dnz_kind_mismatch_ms": ("histogram", "bound as counter"),
            }
            """,
        "mod.py": """\
            from denormalized_tpu import obs


            def f(name):
                obs.counter("dnz_good_total")
                obs.counter("dnz_never_declared_total")
                obs.counter(name)
                obs.counter("dnz_kind_mismatch_ms")
            """,
    })
    new, _, _ = run_all(root, baseline_path=tmp_path / "nb.toml",
                        hotpaths_path=tmp_path / "nh.toml")
    m = [f for f in new if f.rule == "DNZ-M001"]
    symbols = {f.symbol for f in m}
    # direction 1: undeclared / dynamic / kind-mismatched binder calls
    assert "dnz_never_declared_total" in symbols
    assert "<dynamic>" in symbols
    assert any(
        f.symbol == "dnz_kind_mismatch_ms" and "binds a counter" in f.message
        for f in m
    )
    # direction 2: declared but never bound
    assert any(
        f.symbol == "dnz_unused_total" and "no engine module binds" in f.message
        for f in m
    )
    # naming + help discipline
    assert any(f.symbol == "dnz_bad_suffix" and "_total" in f.message
               for f in m)
    assert any(f.symbol == "dnz_hist_nosuffix" and "unit suffix" in f.message
               for f in m)
    assert any(f.symbol == "dnz_helpless_total" and "help" in f.message
               for f in m)
    assert any(f.symbol == "badprefix_total" for f in m)
    # the clean instrument raises nothing
    assert not any(
        f.symbol == "dnz_good_total" for f in m
    )


def test_handoff_instrument_pass_fires(tmp_path):
    """DNZ-M002 must fire in both directions: an operator overriding the
    batch-processing path without the doctor's handoff hooks, a new
    operator missing from operators.toml, and a stale registration."""
    root = _write_pkg(tmp_path, {
        "physical/ops.py": """\
            class GoodOp:
                def __init__(self, input_op):
                    self.input_op = input_op
                    self.bind_obs("good")

                def run(self):
                    for item in self._doctor_input():
                        t0 = 0.0
                        self._note_batch(t0, item.num_rows)
                        yield item


            class BadOp:
                def __init__(self, input_op):
                    self.input_op = input_op

                def run(self):
                    for item in self.input_op.run():
                        yield item


            class UnregisteredOp:
                def __init__(self, input_op):
                    self.input_op = input_op
                    self.bind_obs("unreg")

                def run(self):
                    for item in self._doctor_input():
                        self._note_batch(0.0, item.num_rows)
                        yield item


            class LeafOp:
                # no upstream input: exempt by shape (SourceExec analog)
                def run(self):
                    yield None
            """,
    })
    ops_toml = tmp_path / "ops.toml"
    ops_toml.write_text(textwrap.dedent("""\
        [[operator]]
        class = "GoodOp"
        file = "badpkg/physical/ops.py"

        [[operator]]
        class = "BadOp"
        file = "badpkg/physical/ops.py"

        [[operator]]
        class = "GoneOp"
        file = "badpkg/physical/gone.py"
        """))
    new, _, _ = run_all(root, baseline_path=tmp_path / "nb.toml",
                        hotpaths_path=tmp_path / "nh.toml",
                        operators_path=ops_toml)
    m2 = [f for f in new if f.rule == "DNZ-M002"]
    msgs = {f.symbol: [g.message for g in m2 if g.symbol == f.symbol]
            for f in m2}
    # BadOp: all three hooks missing (registered, so no registry finding)
    assert "BadOp" in msgs
    joined = " | ".join(msgs["BadOp"])
    assert "bind_obs" in joined
    assert "_doctor_input" in joined
    assert "_note_batch" in joined
    # a complete-but-unregistered operator fires the registry direction
    assert any("not registered" in m for m in msgs.get("UnregisteredOp", []))
    # a stale registration fires the reverse direction
    assert any("stale" in m for m in msgs.get("GoneOp", []))
    # the clean registered operator and the input-less leaf stay silent
    assert "GoodOp" not in msgs
    assert "LeafOp" not in msgs


def test_keyed_state_pass_fires_both_directions(tmp_path):
    """State-observatory drift pin (DNZ-M002 keyed-state extension):
    a keyed_state=true registration without state_info()/make_watch
    fires, and an operator that DEFINES state_info without the flag
    fires the reverse direction; a compliant operator stays silent."""
    root = _write_pkg(tmp_path, {
        "physical/sops.py": """\
            from denormalized_tpu.obs import statewatch


            class KeyedGood:
                def __init__(self, input_op):
                    self.input_op = input_op
                    self.bind_obs("kg")
                    self._sw = statewatch.make_watch("kg")

                def state_info(self):
                    return {"state_bytes": 0}

                def run(self):
                    for item in self._doctor_input():
                        self._note_batch(0.0, item.num_rows)
                        yield item


            class KeyedBare:
                # registered keyed_state=true but binds NEITHER
                # state-accounting instrument
                def __init__(self, input_op):
                    self.input_op = input_op
                    self.bind_obs("kb")

                def run(self):
                    for item in self._doctor_input():
                        self._note_batch(0.0, item.num_rows)
                        yield item


            class UnflaggedStateful:
                # defines state_info but is NOT flagged keyed_state
                def __init__(self, input_op):
                    self.input_op = input_op
                    self.bind_obs("uf")

                def state_info(self):
                    return {"state_bytes": 0}

                def run(self):
                    for item in self._doctor_input():
                        self._note_batch(0.0, item.num_rows)
                        yield item
            """,
    })
    ops_toml = tmp_path / "sops.toml"
    ops_toml.write_text(textwrap.dedent("""\
        [[operator]]
        class = "KeyedGood"
        file = "badpkg/physical/sops.py"
        keyed_state = true

        [[operator]]
        class = "KeyedBare"
        file = "badpkg/physical/sops.py"
        keyed_state = true

        [[operator]]
        class = "UnflaggedStateful"
        file = "badpkg/physical/sops.py"
        """))
    new, _, _ = run_all(root, baseline_path=tmp_path / "nb.toml",
                        hotpaths_path=tmp_path / "nh.toml",
                        operators_path=ops_toml)
    m2 = [f for f in new if f.rule == "DNZ-M002"]
    msgs = {f.symbol: [g.message for g in m2 if g.symbol == f.symbol]
            for f in m2}
    bare = " | ".join(msgs.get("KeyedBare", []))
    assert "state_info" in bare
    assert "make_watch" in bare or "sketch watch" in bare
    assert any(
        "keyed_state" in m for m in msgs.get("UnflaggedStateful", [])
    )
    assert "KeyedGood" not in msgs


def test_hotpath_loop_tolist_and_hash_fire(tmp_path):
    root = _write_pkg(tmp_path, {"hot.py": """\
        def kernel(rows):
            out = []
            for r in rows:
                out.append(r * 2)
            return out


        def hasher(cols):
            return hash(tuple(cols))


        def lister(arr):
            return sum(arr.tolist())


        def clean(arr):
            return arr * 2
        """})
    hp = tmp_path / "hp.toml"
    hp.write_text(textwrap.dedent("""\
        [[hotpath]]
        file = "badpkg/hot.py"
        qualname = "kernel"

        [[hotpath]]
        file = "badpkg/hot.py"
        qualname = "hasher"

        [[hotpath]]
        file = "badpkg/hot.py"
        qualname = "lister"

        [[hotpath]]
        file = "badpkg/hot.py"
        qualname = "clean"

        [[hotpath]]
        file = "badpkg/hot.py"
        qualname = "renamed_away"
        """))
    new, _, _ = run_all(root, baseline_path=tmp_path / "nb.toml",
                        hotpaths_path=hp)
    h1 = [f for f in new if f.rule == "DNZ-H001"]
    h2 = [f for f in new if f.rule == "DNZ-H002"]
    assert any(f.symbol == "kernel" and "`for` loop" in f.message
               for f in h1), [f.render() for f in new]
    assert any(f.symbol == "lister" and ".tolist()" in f.message
               for f in h1)
    assert any(f.symbol == "hasher" for f in h2)
    assert not any(f.symbol == "clean" for f in h1 + h2)
    # registering a function the tree doesn't define is itself a finding
    assert any(f.symbol == "renamed_away" for f in h1)


def test_baseline_suppresses_and_reports_stale(tmp_path):
    root = _write_pkg(tmp_path, {"sw.py": """\
        def bad():
            try:
                return 1
            except Exception:
                return None
        """})
    bl = tmp_path / "bl.toml"
    bl.write_text(textwrap.dedent("""\
        [[suppress]]
        rule = "DNZ-E001"
        file = "badpkg/sw.py"
        symbol = "bad"
        reason = "fixture: accepted for the baseline-mechanics test"

        [[suppress]]
        rule = "DNZ-E001"
        file = "badpkg/gone.py"
        symbol = "ghost"
        reason = "fixture: matches nothing, must be reported stale"
        """))
    new, suppressed, stale = run_all(root, baseline_path=bl,
                                     hotpaths_path=tmp_path / "nh.toml")
    assert not any(f.rule == "DNZ-E001" for f in new)
    assert any(f.symbol == "bad" for f in suppressed)
    assert ("DNZ-E001", "badpkg/gone.py", "ghost") in stale


def test_baseline_requires_reasons(tmp_path):
    bl = tmp_path / "bl.toml"
    bl.write_text(textwrap.dedent("""\
        [[suppress]]
        rule = "DNZ-E001"
        file = "x.py"
        symbol = "f"
        reason = ""
        """))
    with pytest.raises(ValueError, match="no reason"):
        load_baseline(bl)


# -- dnzlint v2: guarded-by / replay-purity / snapshot-symmetry ------------

def _v2_paths(tmp_path, **overrides):
    """Registry paths for fixture runs: nonexistent by default so the
    real tree's registries never leak into a fixture package."""
    none = tmp_path / "no-such-registry.toml"
    kw = dict(
        baseline_path=none, hotpaths_path=none, operators_path=none,
        guards_path=none, replaypaths_path=none,
    )
    kw.update(overrides)
    return kw


def test_guard_inference_fires_both_directions(tmp_path):
    """DNZ-G001: an attribute written under a lock anywhere in the class
    is claimed by it — unguarded reads AND writes fire; a reasoned
    pragma suppresses; a helper only ever called with the lock held is
    clean (transitive held-set resolution); a guards.toml exemption
    absorbs its attribute, and a stale exemption is itself a finding
    (DNZ-G002)."""
    root = _write_pkg(tmp_path, {"coord.py": """\
        import threading


        class Coordinator:
            def __init__(self):
                self._lock = threading.Lock()
                self._count = 0
                self._peers = {}

            def bump(self):
                with self._lock:
                    self._count += 1
                    self._peers["x"] = 1

            def racy_read(self):
                return self._count

            def racy_write(self):
                self._count = 0

            def peeked(self):
                return self._count  # dnzlint: allow(unguarded) monitoring peek, staleness tolerated by the dashboard

            def exempt_peek(self):
                return self._peers

            def locked_caller(self):
                with self._lock:
                    return self._helper()

            def _helper(self):
                return self._count
        """})
    gt = tmp_path / "guards.toml"
    gt.write_text(textwrap.dedent("""\
        [[unguarded]]
        class = "Coordinator"
        attr = "_peers"
        reason = "fixture: read-only dashboard tolerates stale membership"

        [[unguarded]]
        class = "Coordinator"
        attr = "_gone"
        reason = "fixture: stale entry must be reported"
        """))
    new, suppressed, _ = run_all(root, **_v2_paths(tmp_path, guards_path=gt))
    g1 = [f for f in new if f.rule == "DNZ-G001"]
    assert any(f.symbol == "Coordinator.racy_read"
               and "read of self._count" in f.message for f in g1), \
        [f.render() for f in new]
    assert any(f.symbol == "Coordinator.racy_write"
               and "write of self._count" in f.message for f in g1)
    # the claim names the lock and the claiming write site
    assert all("Coordinator._lock" in f.message for f in g1)
    # transitive resolution: the helper is only entered lock-held
    assert not any("_helper" in f.symbol or "locked_caller" in f.symbol
                   for f in g1)
    # guards.toml exemption absorbs _peers entirely
    assert not any("_peers" in f.message for f in g1)
    # reasoned pragma suppresses rather than fires
    assert any(f.rule == "DNZ-G001" and f.symbol == "Coordinator.peeked"
               for f in suppressed)
    # reverse drift: the _gone exemption matches nothing
    assert any(f.rule == "DNZ-G002" and f.symbol == "Coordinator._gone"
               for f in new)


def test_guard_registry_requires_reasons(tmp_path):
    from tools.dnzlint.guards import load_guards

    gt = tmp_path / "guards.toml"
    gt.write_text(textwrap.dedent("""\
        [[unguarded]]
        class = "C"
        attr = "_x"
        reason = ""
        """))
    with pytest.raises(ValueError, match="reason"):
        load_guards(gt)


def test_replay_purity_fires_both_directions(tmp_path):
    """DNZ-D001: an impurity fires transitively (attributed to the
    reached helper, naming the registered root) and on the registered
    kernel itself; a pure registered kernel is silent.  DNZ-D002 fires
    both ways: a registered symbol the tree no longer defines, and a
    snapshot-codec caller outside the registry closure."""
    root = _write_pkg(tmp_path, {"enc.py": """\
        import time


        def encode(meta):
            return _pack(meta)


        def _pack(meta):
            meta["at"] = time.time()
            return repr(meta).encode()


        def decode(blob):
            seen = set(blob)
            out = []
            for b in seen:
                out.append(b)
            return out


        def stray_codec(meta):
            return pack_snapshot(meta, {})


        def clean_kernel(rows):
            return sorted(rows)
        """})
    rp = tmp_path / "paths.toml"
    rp.write_text(textwrap.dedent("""\
        [[path]]
        file = "badpkg/enc.py"
        qualname = "encode"
        note = "fixture: frame encoder"

        [[path]]
        file = "badpkg/enc.py"
        qualname = "decode"
        note = "fixture: frame decoder"

        [[path]]
        file = "badpkg/enc.py"
        qualname = "clean_kernel"
        note = "fixture: pure kernel stays silent"

        [[path]]
        file = "badpkg/enc.py"
        qualname = "vanished"
        note = "fixture: registered symbol the tree no longer defines"
        """))
    new, _, _ = run_all(root, **_v2_paths(tmp_path, replaypaths_path=rp))
    d1 = [f for f in new if f.rule == "DNZ-D001"]
    d2 = [f for f in new if f.rule == "DNZ-D002"]
    # transitive: the clock read is in the helper, attributed to it,
    # naming the registered entry point it was reached from
    assert any(f.symbol == "_pack" and "time.time" in f.message
               and "reached from registered encode" in f.message
               for f in d1), [f.render() for f in new]
    # direct: unordered set iteration feeding the decoder's output
    assert any(f.symbol == "decode" and "unordered set" in f.message
               for f in d1)
    assert not any(f.symbol in ("encode", "clean_kernel") for f in d1)
    # registry drift, both directions
    assert any("vanished" in f.symbol for f in d2)
    assert any(f.symbol == "stray_codec"
               and "pack_snapshot" in f.message for f in d2)


def test_replaypaths_registry_requires_notes(tmp_path):
    from tools.dnzlint.replay import load_paths

    rp = tmp_path / "paths.toml"
    rp.write_text(textwrap.dedent("""\
        [[path]]
        file = "x.py"
        qualname = "f"
        note = ""
        """))
    with pytest.raises(ValueError, match="note"):
        load_paths(rp)


def test_snapshot_symmetry_fires_both_directions(tmp_path):
    """DNZ-S001: written-never-read, strict-read-never-written (tolerant
    .get(k, default) reads are the sanctioned legacy idiom and stay
    silent), and a version literal bumped on one side only.  DNZ-S002:
    codec flows without a keyed_state registration, and a keyed_state
    registration whose class lost its codec flow."""
    root = _write_pkg(tmp_path, {"physical/snapop.py": """\
        class WinOp:
            def _snapshot(self, coord):
                meta = {
                    "version": 2,
                    "rows": self._rows,
                    "orphaned": self._orphaned,
                }
                coord.put_snapshot("w", pack_snapshot(meta, {}))

            def _restore(self, coord):
                meta, _ = unpack_snapshot(coord.get_snapshot("w"))
                if meta["version"] != 1:
                    return
                self._rows = meta["rows"]
                self._missing = meta["ghost"]
                self._opt = meta.get("legacy", 0)


        class CleanOp:
            def _snapshot(self, coord):
                coord.put_snapshot("c", pack_snapshot({"rows": self._rows}, {}))

            def _restore(self, coord):
                meta, _ = unpack_snapshot(coord.get_snapshot("c"))
                self._rows = meta["rows"]


        class UnregisteredSnap:
            def _snapshot(self, coord):
                coord.put_snapshot("u", pack_snapshot({"x": 1}, {}))


        class StaleKeyed:
            def run(self):
                pass
        """})
    ops = tmp_path / "ops.toml"
    ops.write_text(textwrap.dedent("""\
        [[operator]]
        class = "WinOp"
        file = "badpkg/physical/snapop.py"
        keyed_state = true

        [[operator]]
        class = "CleanOp"
        file = "badpkg/physical/snapop.py"
        keyed_state = true

        [[operator]]
        class = "UnregisteredSnap"
        file = "badpkg/physical/snapop.py"

        [[operator]]
        class = "StaleKeyed"
        file = "badpkg/physical/snapop.py"
        keyed_state = true
        """))
    from tools.dnzlint import snapshots

    findings = snapshots.run(root, ops)
    s1 = [f for f in findings if f.rule == "DNZ-S001"]
    s2 = [f for f in findings if f.rule == "DNZ-S002"]
    assert any(f.symbol == "WinOp._snapshot" and "'orphaned'" in f.message
               and "no restore path reads it" in f.message
               for f in s1), [f.render() for f in findings]
    assert any(f.symbol == "WinOp._restore" and "'ghost'" in f.message
               and "KeyError" in f.message for f in s1)
    assert any(f.symbol == "WinOp" and "version literals" in f.message
               for f in s1)
    # tolerant legacy read and the symmetric operator stay silent
    assert not any("'legacy'" in f.message for f in s1)
    assert not any("CleanOp" in f.symbol for f in s1 + s2)
    # registry drift, both directions
    assert any(f.symbol == "UnregisteredSnap"
               and "keyed_state" in f.message for f in s2)
    assert any(f.symbol == "StaleKeyed"
               and "no snapshot codec flow" in f.message for f in s2)


def test_replay_path_docs_table_cannot_drift():
    """docs/static_analysis.md embeds the registry table generated from
    replaypaths.toml (python -m tools.dnzlint --replay-path-table);
    regenerate the docs block when the registry changes."""
    from tools.dnzlint.replay import replay_path_table

    table = replay_path_table()
    docs = (REPO / "docs" / "static_analysis.md").read_text()
    assert table in docs, (
        "docs/static_analysis.md replay-path table is stale — regenerate "
        "with: python -m tools.dnzlint --replay-path-table"
    )


def test_replaypaths_registry_covers_core_kernels():
    """The determinism pin is only as good as its roots: the codec,
    hashing, and operator snapshot surfaces must stay registered."""
    from tools.dnzlint.replay import load_paths

    entries = load_paths(REPO / "tools" / "dnzlint" / "replaypaths.toml")
    by_file = {}
    for e in entries:
        by_file.setdefault(e["file"], set()).add(e["qualname"])
    assert len(entries) >= 60
    core = {
        "denormalized_tpu/cluster/framing.py": {"encode_data", "decode_frame"},
        "denormalized_tpu/cluster/hashing.py": {"hash_rows", "bucket_rows"},
        "denormalized_tpu/cluster/rescale.py": {"rescale_cluster"},
        "denormalized_tpu/state/serialization.py": {
            "pack_snapshot", "unpack_snapshot",
        },
        "denormalized_tpu/state/checkpoint.py": {
            "CheckpointCoordinator.put_snapshot",
            "CheckpointCoordinator.get_snapshot",
        },
        "denormalized_tpu/ops/sketches.py": {"stable_hash64"},
        "denormalized_tpu/ops/slice_store.py": {"fold_slices"},
    }
    for file, quals in core.items():
        assert quals <= by_file.get(file, set()), (file, quals)


def test_cli_json_report_carries_reason_and_wall_clock(tmp_path):
    """--format=json / --report emit {rule, file, line, symbol, reason}
    per finding plus wall_clock_s (tools/lint.sh budget-gates on it)."""
    import json

    report_path = tmp_path / "report.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.dnzlint", "denormalized_tpu",
         "--format=json", "--report", str(report_path)],
        capture_output=True, text=True, cwd=REPO, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    report = json.loads(proc.stdout)
    on_disk = json.loads(report_path.read_text())
    assert report == on_disk
    assert report["counts"]["new"] == 0
    assert report["counts"]["suppressed"] >= 10
    # the lint.sh wall-clock budget, with headroom for slow CI boxes
    assert 0 < report["wall_clock_s"] < 60
    for f in report["suppressed"]:
        assert set(f) == {"rule", "file", "line", "symbol", "reason"}
        assert f["reason"]


def test_exchange_redial_blocking_forms_fire_under_lock(tmp_path):
    """DNZ-L002 blocking-list extension for the cluster exchange
    surface: the module-level socket dial helpers, selector polls, and
    the redial backoff sleep must all fire when reached under a held
    engine lock — and the same redial loop run WITHOUT the lock held
    stays silent."""
    root = _write_pkg(tmp_path, {"redial.py": """\
        import socket
        import threading
        import time


        class Exchange:
            def __init__(self):
                self._lock = threading.Lock()
                self._sel = None
                self._sock = None

            def bad_redial(self):
                with self._lock:
                    s = socket.create_connection(("peer", 1))
                    s.connect("/tmp/peer.sock")
                    self._sel.select(0.5)
                    time.sleep(0.2)

            def good_redial(self):
                s = socket.create_connection(("peer", 1))
                s.connect("/tmp/peer.sock")
                self._sel.select(0.5)
                time.sleep(0.2)
                with self._lock:
                    self._sock = s
        """})
    new, _, _ = run_all(root, **_v2_paths(tmp_path))
    l2 = [f for f in new if f.rule == "DNZ-L002"]
    msgs = [f.message for f in l2 if f.symbol == "Exchange.bad_redial"]
    joined = " | ".join(msgs)
    assert "socket.create_connection" in joined, \
        [f.render() for f in new]
    assert ".connect" in joined
    assert "select" in joined
    assert "time.sleep" in joined
    assert not any(f.symbol == "Exchange.good_redial" for f in l2)
