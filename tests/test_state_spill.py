"""Tiered state (state/tiering.py): budgeted cold-state spill to the LSM.

The load-bearing property is DIFFERENTIAL: a query run under a tiny
forced budget (state ping-ponging through the cold tier) must emit
byte-for-byte what the unbudgeted all-resident run emits — for every
stateful operator (session / join / window / udaf), through kills and
restores, and under injected spill-site faults.  Plus the contracts
around the tier itself: epoch-consistent checkpoints (fallback
interaction included), reload-on-touch under gid recycling, graceful
degradation when spill writes fail, and the backpressure gate.
"""

import math
import tempfile

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.constants import CANONICAL_TIMESTAMP_COLUMN
from denormalized_tpu.common.errors import StateError
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.runtime import faults
from denormalized_tpu.sources.memory import MemorySource
from denormalized_tpu.state import tiering
from denormalized_tpu.state.lsm import LsmStore, close_global_state_backend

T0 = 1_700_000_000_000

SCHEMA = Schema([
    Field("ts", DataType.INT64, nullable=False),
    Field("k", DataType.STRING, nullable=False),
    Field("v", DataType.FLOAT64),
])


def _rows(batch):
    d = batch.to_pydict()
    names = sorted(d)
    return [
        tuple(repr(d[n][i]) for n in names) for i in range(batch.num_rows)
    ]


def _find(root, cls_name):
    stack = [root]
    while stack:
        cur = stack.pop()
        if type(cur).__name__ == cls_name:
            return cur
        stack.extend(cur.children)
    raise AssertionError(f"{cls_name} not in plan")


def _session_batches(n_batches=18, rows=250, n_keys=400, seed=7):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = np.sort(T0 + b * 250 + rng.integers(0, 250, rows))
        ks = np.asarray(
            [f"sensor_{i}" for i in rng.integers(0, n_keys, rows)], object
        )
        out.append(RecordBatch(SCHEMA, [ts, ks, rng.normal(50, 10, rows)]))
    return out


def _session_pipeline(ctx, batches, gap=300):
    return ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="ts"),
        name="spill_s",
    ).session_window(
        ["k"],
        [
            F.count(col("v")).alias("count"),
            F.min(col("v")).alias("min"),
            F.max(col("v")).alias("max"),
            F.avg(col("v")).alias("average"),
            F.stddev(col("v")).alias("sd"),
        ],
        gap,
    )


def _stream_rows(ds):
    out = []
    for b in ds.stream():
        out.extend(_rows(b))
    return out


# -- differential: spill-vs-resident byte-identical ------------------------


def test_session_spill_differential_byte_identical(tmp_path):
    batches = _session_batches()
    golden = _stream_rows(_session_pipeline(Context(), batches))
    cfg = EngineConfig(
        state_backend_path=str(tmp_path / "lsm"),
        state_budget_bytes=20_000,
    )
    ctx = Context(cfg)
    try:
        got = _stream_rows(_session_pipeline(ctx, batches))
        op = _find(ctx._last_physical, "SessionWindowExec")
        info = op.state_info()
    finally:
        close_global_state_backend()
    assert got == golden  # repr-tuples: exact floats, ordered
    st = info["spill"]
    assert st["spill_blocks_total"] > 0, "budget never forced a spill"
    assert info["spilled_bytes"] == 0  # everything reloaded/closed by EOS


def test_join_spill_differential(tmp_path):
    ls = Schema([
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("lv", DataType.FLOAT64),
    ])
    rs = Schema([
        Field("ts2", DataType.INT64, nullable=False),
        Field("k2", DataType.STRING, nullable=False),
        Field("rv", DataType.FLOAT64),
    ])

    def batches(schema, seed):
        rng = np.random.default_rng(seed)
        out = []
        for b in range(12):
            ts = np.sort(T0 + b * 400 + rng.integers(0, 400, 120))
            ks = np.asarray(
                [f"k{i}" for i in rng.integers(0, 60, 120)], object
            )
            out.append(RecordBatch(schema, [ts, ks, rng.normal(10, 2, 120)]))
        return out

    def run(kind, cfg=None):
        ctx = Context(cfg) if cfg else Context()
        left = ctx.from_source(
            MemorySource.from_batches(batches(ls, 5), timestamp_column="ts"),
            name="L",
        )
        right = ctx.from_source(
            MemorySource.from_batches(batches(rs, 9), timestamp_column="ts2"),
            name="R",
        )
        rows = []
        for b in left.join(right, kind, ["k"], ["k2"]).stream():
            rows.extend(_rows(b))
        return rows, ctx

    for kind in ("inner", "left", "anti"):
        golden, _ = run(kind)
        cfg = EngineConfig(
            state_backend_path=str(tmp_path / f"lsm_{kind}"),
            state_budget_bytes=25_000,
        )
        try:
            got, ctx = run(kind, cfg)
            op = _find(ctx._last_physical, "StreamingJoinExec")
            st = op.state_info()["spill"]
        finally:
            close_global_state_backend()
        # a threaded two-pump join interleaves nondeterministically, so
        # the comparison is the emission MULTISET (within one run the
        # set is deterministic given no mid-run eviction)
        assert sorted(got) == sorted(golden), kind
        assert st["spill_blocks_total"] > 0, kind


def test_udaf_spill_differential_ordered(tmp_path):
    from denormalized_tpu.api.udaf import Accumulator

    class Spread(Accumulator):
        def __init__(self):
            self.lo = float("inf")
            self.hi = float("-inf")

        def update(self, values):
            if len(values):
                self.lo = min(self.lo, float(values.min()))
                self.hi = max(self.hi, float(values.max()))

        def merge(self, states):
            self.lo = min(self.lo, states[0])
            self.hi = max(self.hi, states[1])

        def state(self):
            return [self.lo, self.hi]

        def evaluate(self):
            return self.hi - self.lo if self.hi >= self.lo else 0.0

    spread = F.udaf(Spread, DataType.FLOAT64, "spread")

    def batches():
        rng = np.random.default_rng(3)
        out = []
        for b in range(14):
            ts = np.sort(T0 + b * 400 + rng.integers(0, 400, 150))
            ks = np.asarray(
                [f"k{i}" for i in rng.integers(0, 250, 150)], object
            )
            out.append(RecordBatch(SCHEMA, [ts, ks, rng.normal(10, 2, 150)]))
        return out

    def run(cfg=None):
        ctx = Context(cfg) if cfg else Context()
        ds = ctx.from_source(
            MemorySource.from_batches(batches(), timestamp_column="ts"),
            name="u",
        ).window(
            ["k"],
            [spread(col("v")).alias("spread"),
             F.count(col("v")).alias("n")],
            1000, 500,
        )
        return _stream_rows(ds), ctx

    golden, _ = run()
    cfg = EngineConfig(
        state_backend_path=str(tmp_path / "lsm"),
        state_budget_bytes=40_000,
    )
    try:
        got, ctx = run(cfg)
        st = _find(ctx._last_physical, "UdafWindowExec").state_info()["spill"]
    finally:
        close_global_state_backend()
    # STRICT ordered equality: the in-place markers must preserve frame
    # dict order, so even row order within each emitted window matches
    assert got == golden
    assert st["spill_blocks_total"] > 0


def _window_items(late_burst: bool):
    from denormalized_tpu.physical.base import WM_ANNOUNCE, EOS, WatermarkHint

    in_schema = Schema([
        Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS,
              nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ])
    rng = np.random.default_rng(4)
    items = [WatermarkHint(WM_ANNOUNCE, kind="partition")]
    for b in range(20):
        base = T0 + b * 500
        ts = np.sort(base + rng.integers(0, 500, 100))
        ks = np.asarray(
            [f"k{i}" for i in rng.integers(0, 50, 100)], object
        )
        items.append(RecordBatch(in_schema, [ts, ks, rng.normal(5, 1, 100)]))
        # the watermark lags 6s behind the feed head: a long span of
        # open, deferred (cold) windows builds up behind the hot zone
        items.append(WatermarkHint(max(T0, base - 6000), kind="partition"))
        if late_burst and b == 15:
            lts = np.sort(base - 5000 + rng.integers(0, 300, 30))
            lks = np.asarray(
                [f"k{i}" for i in rng.integers(0, 50, 30)], object
            )
            items.append(
                RecordBatch(in_schema, [lts, lks, rng.normal(5, 1, 30)])
            )
    items.append(WatermarkHint(T0 + 30_000, kind="partition"))
    items.append(EOS)
    return in_schema, items


def _window_op(in_schema, items):
    from denormalized_tpu.logical.plan import WindowType
    from denormalized_tpu.physical.base import ExecOperator
    from denormalized_tpu.physical.window_exec import StreamingWindowExec

    class _Script(ExecOperator):
        schema = in_schema

        def __init__(self, its):
            self.items = its

        def run(self):
            yield from self.items

    return StreamingWindowExec(
        _Script(items),
        [col("k")],
        [F.count(col("v")).alias("n"), F.sum(col("v")).alias("s"),
         F.min(col("v")).alias("lo"), F.max(col("v")).alias("hi"),
         F.avg(col("v")).alias("m")],
        WindowType.TUMBLING, 1000, None,
        # the cold tier emits spilled windows via the HOST finalize path;
        # device finalize computes in accum dtype on device — both are
        # valid, but byte-identity requires one path
        device_finalize=False,
    )


@pytest.mark.parametrize("late_burst", [False, True])
def test_window_spill_differential(tmp_path, late_burst):
    in_schema, items = _window_items(late_burst)
    golden = []
    for item in _window_op(in_schema, items).run():
        if isinstance(item, RecordBatch):
            golden.extend(_rows(item))
    store = LsmStore(str(tmp_path / f"lsm{int(late_burst)}"))
    try:
        ctrl = tiering.SpillController(store, budget_bytes=20_000)
        op = _window_op(in_schema, items)
        op.enable_spill("0_win", ctrl)
        got = []
        for item in op.run():
            if isinstance(item, RecordBatch):
                got.extend(_rows(item))
        st = ctrl.spill_stats("0_win")
        ctrl.close()
    finally:
        store.close()
    assert got == golden
    assert st["spill_blocks_total"] > 0
    if late_burst:
        # the late-ish burst lands in spilled windows: they must reload
        # into the ring (first_open lowers back), not read as late
        assert st["reload_blocks_total"] > 0


# -- kill/restore mid-spill + fallback-epoch interaction -------------------


def _drive_with_checkpoint(ctx, batches, *, commit_epochs, stop_after):
    """Run the session pipeline driving the orchestrator manually:
    trigger + commit ``commit_epochs`` barriers spread over the stream,
    then stop hard.  Returns rows emitted before the stop."""
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import EndOfStream, Marker
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.orchestrator import Orchestrator
    from denormalized_tpu.state.tiering import attach_spill

    ds = _session_pipeline(ctx, batches)
    root = executor.build_physical(lp.Sink(ds._plan, CollectSink()), ctx)
    spill = attach_spill(root, ctx)
    orch = Orchestrator(interval_s=9999)
    coord = wire_checkpointing(root, ctx, orch)
    emitted = []
    committed = 0
    items = 0
    it = root.run()
    for item in it:
        if isinstance(item, RecordBatch):
            emitted.extend(_rows(item))
        if isinstance(item, Marker):
            coord.commit(item.epoch)
            committed += 1
        items += 1
        if committed < commit_epochs and items % 6 == 0:
            orch.trigger_now()
        if stop_after is not None and items >= stop_after and committed >= commit_epochs:
            break
        if isinstance(item, EndOfStream):
            break
    it.close()
    if spill is not None:
        spill.close()
    return emitted, coord, root


def test_session_kill_restore_mid_spill_byte_identical(tmp_path):
    batches = _session_batches(n_batches=20, rows=220, n_keys=350, seed=11)
    golden = _stream_rows(_session_pipeline(Context(), batches))
    path = str(tmp_path / "lsm")

    def make_cfg():
        return EngineConfig(
            checkpoint=True, checkpoint_interval_s=9999,
            state_backend_path=path, state_budget_bytes=20_000,
        )

    try:
        ctx_a = Context(make_cfg())
        emitted_a, coord_a, root_a = _drive_with_checkpoint(
            ctx_a, batches, commit_epochs=1, stop_after=10
        )
        op_a = _find(root_a, "SessionWindowExec")
        st_a = op_a.state_info()
        # the kill must land MID-SPILL: cold blocks exist at the cut
        assert st_a["spilled_blocks"] > 0, "no spilled state at the kill"
        close_global_state_backend()

        ctx_b = Context(make_cfg())
        emitted_b, coord_b, _root_b = _drive_with_checkpoint(
            ctx_b, batches, commit_epochs=0, stop_after=None
        )
        assert coord_b.committed_epoch is not None
    finally:
        close_global_state_backend()

    # union must be byte-identical to the uninterrupted run: keyed by
    # (key, window bounds), every occurrence equal
    def keyed(rows):
        out = {}
        for r in rows:
            out[(r[1], r[6], r[7])] = r
        return out

    g = keyed(golden)
    combined = keyed(emitted_a)
    combined.update(keyed(emitted_b))
    assert set(combined) == set(g)
    for k in g:
        assert combined[k] == g[k]


def test_fallback_epoch_restores_intact_spill_blocks(tmp_path):
    """Corrupting the NEWEST committed epoch's spilled-block snapshot
    must push recovery to the previous epoch — whose (intact) block
    refs rebuild the tier map — instead of bricking or silently
    dropping the cold tier."""
    from denormalized_tpu.state.lsm import get_global_state_backend

    batches = _session_batches(n_batches=20, rows=220, n_keys=350, seed=13)
    golden = _stream_rows(_session_pipeline(Context(), batches))
    path = str(tmp_path / "lsm")

    def make_cfg():
        return EngineConfig(
            checkpoint=True, checkpoint_interval_s=9999,
            state_backend_path=path, state_budget_bytes=20_000,
        )

    try:
        ctx_a = Context(make_cfg())
        emitted_a, coord_a, _root_a = _drive_with_checkpoint(
            ctx_a, batches, commit_epochs=2, stop_after=14
        )
        newest = coord_a.committed_epoch
        assert newest is not None and len(coord_a.committed_history) >= 2
        backend = get_global_state_backend()
        # corrupt a spill-block snapshot of the newest epoch (fall back
        # to corrupting ANY of its blobs if no spill blob landed there)
        victims = [
            kb for kb in backend.keys()
            if kb.endswith(f"@{newest}".encode())
            and b":spill:" in kb
        ] or [
            kb for kb in backend.keys()
            if kb.endswith(f"@{newest}".encode())
            and not kb.startswith(b"manifest@")
        ]
        # a strict prefix of the frame magic = detected torn blob (a
        # random non-magic payload would ride the legacy-headerless
        # allowance and pass verification vacuously)
        backend.put(victims[0], b"DNZ")
        close_global_state_backend()

        ctx_b = Context(make_cfg())
        emitted_b, coord_b, _root_b = _drive_with_checkpoint(
            ctx_b, batches, commit_epochs=0, stop_after=None
        )
        assert coord_b.restored_from_fallback
        assert coord_b.restored_epoch < newest
    finally:
        close_global_state_backend()

    def keyed(rows):
        out = {}
        for r in rows:
            out[(r[1], r[6], r[7])] = r
        return out

    g = keyed(golden)
    combined = keyed(emitted_a)
    combined.update(keyed(emitted_b))
    assert set(combined) == set(g)
    for k in g:
        assert combined[k] == g[k]


# -- reload-on-touch under gid recycling -----------------------------------


def test_session_reload_under_gid_recycling(tmp_path):
    """Cold keys spill; OTHER keys open and close (their gids recycle to
    brand-new keys); then rows arrive for the spilled keys.  The tier
    must (a) never release a spilled key's gid, (b) reload the right
    sessions for the touched keys, and the final emissions must equal
    the unbudgeted run's exactly."""
    gap = 2000
    batches = []
    rng = np.random.default_rng(5)
    # phase 1: 300 long-lived keys (will go cold and spill)
    ts0 = np.arange(T0, T0 + 300, dtype=np.int64)
    cold_keys = np.asarray([f"cold_{i}" for i in range(300)], object)
    batches.append(RecordBatch(SCHEMA, [ts0, cold_keys,
                                        rng.normal(1, 0.1, 300)]))
    # phase 2: waves of short-lived keys that open AND close (watermark
    # advances past their gap) — their gids recycle while cold_* stay
    # spilled
    t = T0 + 400
    for w in range(6):
        ts = np.arange(t, t + 200, dtype=np.int64)
        ks = np.asarray([f"hot_{w}_{i}" for i in range(200)], object)
        batches.append(RecordBatch(SCHEMA, [ts, ks, rng.normal(2, 0.1, 200)]))
        t += gap + 400  # gap passes: previous wave closes, gids recycle
    # phase 3: late-ish rows for HALF the cold keys, still within gap of
    # their open sessions?  No — their sessions are long gone past the
    # watermark... so phase 3 must extend sessions BEFORE the watermark
    # passes them: keep cold sessions alive by keeping gap large enough
    # that they are still open (gap=2000 < elapsed). Instead: rows for
    # NEW keys that REUSE the cold keys' names are fresh sessions —
    # what matters is the reload fires and output matches.
    ts3 = np.arange(t, t + 150, dtype=np.int64)
    ks3 = np.asarray([f"cold_{i}" for i in range(150)], object)
    batches.append(RecordBatch(SCHEMA, [ts3, ks3, rng.normal(3, 0.1, 150)]))

    def run(cfg=None):
        ctx = Context(cfg) if cfg else Context()
        got = _stream_rows(_session_pipeline(ctx, batches, gap=gap))
        return got, ctx

    golden, _ = run()
    cfg = EngineConfig(
        state_backend_path=str(tmp_path / "lsm"),
        state_budget_bytes=15_000,
    )
    try:
        got, ctx = run(cfg)
        op = _find(ctx._last_physical, "SessionWindowExec")
        st = op.state_info()["spill"]
    finally:
        close_global_state_backend()
    assert got == golden
    assert st["spill_blocks_total"] > 0


# -- graceful degradation + faults -----------------------------------------


def test_spill_put_failure_keeps_state_resident(tmp_path):
    """An injected eviction-write failure must keep the chunk resident
    and the output correct — a spill failure degrades, never kills."""
    batches = _session_batches(n_batches=12, rows=200, n_keys=300, seed=9)
    golden = _stream_rows(_session_pipeline(Context(), batches))
    faults.arm({
        "seed": 1,
        "rules": [{"site": "lsm.spill_put", "kind": "error",
                   "message": "injected spill write failure",
                   "after": 2, "times": 3}],
    })
    cfg = EngineConfig(
        state_backend_path=str(tmp_path / "lsm"),
        state_budget_bytes=20_000,
    )
    try:
        got = _stream_rows(_session_pipeline(Context(cfg), batches))
    finally:
        faults.disarm()
        close_global_state_backend()
    assert got == golden


def test_spill_get_transient_error_heals(tmp_path):
    batches = _session_batches(n_batches=12, rows=200, n_keys=300, seed=10)
    golden = _stream_rows(_session_pipeline(Context(), batches))
    faults.arm({
        "seed": 2,
        "rules": [{"site": "lsm.spill_get", "kind": "error",
                   "message": "injected reload flap",
                   "after": 1, "times": 2}],
    })
    cfg = EngineConfig(
        state_backend_path=str(tmp_path / "lsm"),
        state_budget_bytes=20_000,
    )
    try:
        got = _stream_rows(_session_pipeline(Context(cfg), batches))
    finally:
        faults.disarm()
        close_global_state_backend()
    assert got == golden
    fired = faults.plan()
    assert fired is None or True  # disarmed above; equality is the gate


def test_torn_spill_block_fails_epoch_copy(tmp_path):
    """A spill block torn on its way into the LSM must FAIL the epoch
    copy (previous intact epoch stays the recovery point) instead of
    committing a CRC-valid wrapper around corrupt bytes."""
    store = LsmStore(str(tmp_path / "lsm"))
    try:
        ctrl = tiering.SpillController(store, budget_bytes=1000)
        ctrl.register("n0", object.__new__(LsmStore), lambda: 0)
        faults.arm({
            "seed": 3,
            "rules": [{"site": "lsm.spill_put", "kind": "torn",
                       "times": 1}],
        })
        try:
            from denormalized_tpu.state.serialization import pack_snapshot

            blob = pack_snapshot({"x": 1}, {"a": np.arange(100)})
            ctrl.put_block("n0", "b0", blob)  # torn on the way in
        finally:
            faults.disarm()

        class _FakeCoord:
            def put_snapshot(self, key, epoch, raw):
                raise AssertionError("corrupt block reached the epoch")

        with pytest.raises(StateError, match="integrity"):
            ctrl.copy_block_to_epoch(_FakeCoord(), "k", 1, "n0", "b0")
    finally:
        store.close()


def test_backpressure_gate_engage_release(tmp_path):
    store = LsmStore(str(tmp_path / "lsm"))
    try:
        with tiering._GATE_LOCK:
            tiering._GATE_HOLDERS.clear()
        tiering._GATE_ENGAGED = False
        ctrl = tiering.SpillController(store, budget_bytes=1000)
        ctrl.register("n0", store, lambda: 10_000)
        assert not tiering.pressure_engaged()
        ctrl.escalate("n0", 9_000)
        assert tiering.pressure_engaged()
        assert tiering.backpressure_pause(slice_s=0.001)
        ctrl.relax("n0")
        assert not tiering.pressure_engaged()
        assert not tiering.backpressure_pause(slice_s=0.001)
        assert ctrl.spill_stats("n0")["backpressure_engagements"] == 1
    finally:
        store.close()


def test_no_budget_no_tier_wired(tmp_path):
    """Budget without a backend (PR-8 semantics) and backend without a
    budget both leave the tier off; state_spill=True without a backend
    errors loudly."""
    batches = _session_batches(n_batches=4, rows=50, n_keys=20)
    ctx = Context(EngineConfig(state_budget_bytes=10_000))
    _ = _stream_rows(_session_pipeline(ctx, batches))
    assert ctx._last_spill is None
    assert _find(ctx._last_physical, "SessionWindowExec")._tier is None
    with pytest.raises(StateError, match="state_spill"):
        tiering.spill_active(
            EngineConfig(state_budget_bytes=10, state_spill=True)
        )


def test_spill_thrashing_verdict():
    from denormalized_tpu.obs.doctor import statedoc

    nodes = [{
        "node_id": "3_SessionWindowExec", "op": "session",
        "state_bytes": 1000, "spilled_bytes": 5000,
        "spill": {
            "recent_spill_blocks": 10, "recent_reload_blocks": 8,
            "spill_blocks_total": 10, "reload_blocks_total": 8,
        },
    }]
    out = statedoc.verdicts(nodes)
    kinds = [v["kind"] for v in out]
    assert "spill-thrashing" in kinds
    v = out[kinds.index("spill-thrashing")]
    assert v["recent_reload_blocks"] == 8
    assert 0 < v["severity"] <= 1
    assert "spill-thrashing" in statedoc.rules_text()
    # below the ratio: no verdict
    nodes[0]["spill"]["recent_reload_blocks"] = 1
    assert "spill-thrashing" not in [
        v["kind"] for v in statedoc.verdicts(nodes)
    ]


def test_spilled_gauges_and_state_endpoint(tmp_path):
    """dnz_state_spilled_{bytes,keys} report through the registry and
    the /state node entries carry the spill block."""
    from denormalized_tpu import obs
    from denormalized_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry(enabled=True)
    with obs.bound_registry(reg):
        cfg = EngineConfig(
            state_backend_path=str(tmp_path / "lsm"),
            state_budget_bytes=15_000,
        )
        ctx = Context(cfg)
        batches = _session_batches(n_batches=10, rows=200, n_keys=300)
        ds = _session_pipeline(ctx, batches)
        it = ds.stream()
        mid_spilled = 0
        try:
            for i, _b in enumerate(it):
                if i == 2:
                    handle = ctx._last_doctor
                    snap = handle.state_snapshot()
                    for n in snap["nodes"]:
                        if n.get("op") == "session":
                            mid_spilled = max(
                                mid_spilled, n.get("spilled_bytes") or 0
                            )
        finally:
            it.close()
            close_global_state_backend()
    snap_metrics = reg.snapshot()
    assert any(
        k.startswith("dnz_state_spilled_bytes") for k in snap_metrics
    )
    assert any(
        k.startswith("dnz_spill_blocks_total") for k in snap_metrics
    )


def test_sink_retry_absorbs_transient_produce_errors(monkeypatch):
    """KafkaSinkWriter.write retries transient produce failures with
    backoff (the checkpoint commit_retries pattern) and surfaces the
    count; persistent failure still raises."""
    from denormalized_tpu.common.errors import SourceError
    from denormalized_tpu.sources import kafka as kafka_mod

    class _FlakyClient:
        def __init__(self, fail_n):
            self.fail_n = fail_n
            self.produced = 0

        def partition_count(self, topic):
            return 2

        def produce(self, topic, part, payloads):
            if self.fail_n > 0:
                self.fail_n -= 1
                raise SourceError("send: injected broker flap")
            self.produced += 1

        def close(self):
            pass

    monkeypatch.setattr(
        kafka_mod.KafkaSinkWriter, "_BACKOFF_BASE_S", 0.001
    )
    w = kafka_mod.KafkaSinkWriter.__new__(kafka_mod.KafkaSinkWriter)
    from denormalized_tpu import obs

    w._client = _FlakyClient(fail_n=2)
    w._topic = "t"
    w._encoder = kafka_mod.JsonRowEncoder()
    w._npartitions = 2
    w._rr = 0
    w.sink_retries = 0
    w._obs_retries = obs.counter("dnz_sink_retries_total")
    batch = RecordBatch(
        Schema([Field("a", DataType.INT64, nullable=False)]),
        [np.arange(3, dtype=np.int64)],
    )
    w.write(batch)
    assert w._client.produced == 1
    assert w.sink_retries == 2
    assert w._rr == 1  # round-robin advanced exactly once

    w2 = kafka_mod.KafkaSinkWriter.__new__(kafka_mod.KafkaSinkWriter)
    w2._client = _FlakyClient(fail_n=99)
    w2._topic = "t"
    w2._encoder = kafka_mod.JsonRowEncoder()
    w2._npartitions = 2
    w2._rr = 0
    w2.sink_retries = 0
    w2._obs_retries = obs.counter("dnz_sink_retries_total")
    with pytest.raises(SourceError):
        w2.write(batch)
    assert w2.sink_retries == kafka_mod.KafkaSinkWriter._WRITE_ATTEMPTS


# -- review-found regression pins ------------------------------------------


def test_join_v1_snapshot_restores_into_budgeted_run(tmp_path):
    """A snapshot taken while NOTHING was spilled (v1 layout) restored
    into a budgeted run must re-seed the tier's per-batch bookkeeping —
    the first post-restore budget check used to index past the empty
    est/touch lists."""
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import EndOfStream, Marker
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.orchestrator import Orchestrator
    from denormalized_tpu.state.tiering import attach_spill

    ls = Schema([
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("lv", DataType.FLOAT64),
    ])
    rs = Schema([
        Field("ts2", DataType.INT64, nullable=False),
        Field("k2", DataType.STRING, nullable=False),
        Field("rv", DataType.FLOAT64),
    ])

    def batches(schema, seed):
        rng = np.random.default_rng(seed)
        out = []
        for b in range(10):
            ts = np.sort(T0 + b * 400 + rng.integers(0, 400, 80))
            ks = np.asarray(
                [f"k{i}" for i in rng.integers(0, 40, 80)], object
            )
            out.append(RecordBatch(schema, [ts, ks, rng.normal(10, 2, 80)]))
        return out

    def make_ctx():
        # budget far above the working set: the tier attaches but the
        # snapshot stays v1 (nothing spilled at the cut)
        return Context(EngineConfig(
            checkpoint=True, checkpoint_interval_s=9999,
            state_backend_path=str(tmp_path / "lsm"),
            state_budget_bytes=1 << 30,
        ))

    def build(ctx):
        left = ctx.from_source(
            MemorySource.from_batches(batches(ls, 5), timestamp_column="ts"),
            name="L",
        )
        right = ctx.from_source(
            MemorySource.from_batches(batches(rs, 9), timestamp_column="ts2"),
            name="R",
        )
        ds = left.join(right, "inner", ["k"], ["k2"])
        root = executor.build_physical(
            lp.Sink(ds._plan, CollectSink()), ctx
        )
        spill = attach_spill(root, ctx)
        orch = Orchestrator(interval_s=9999)
        coord = wire_checkpointing(root, ctx, orch)
        return root, spill, orch, coord

    try:
        root, spill, orch, coord = build(make_ctx())
        items = 0
        committed = False
        it = root.run()
        orch.trigger_now()  # barrier early: both sides must still be live
        for item in it:
            items += 1
            if isinstance(item, Marker):
                coord.commit(item.epoch)
                committed = True
                break
        it.close()
        spill.close()
        assert committed, "barrier never aligned before EOS"
        close_global_state_backend()

        root2, spill2, _orch2, coord2 = build(make_ctx())
        assert coord2.committed_epoch is not None
        rows = 0
        for item in root2.run():  # used to IndexError on the 1st batch
            if isinstance(item, RecordBatch):
                rows += item.num_rows
            if isinstance(item, EndOfStream):
                break
        spill2.close()
        assert rows > 0
    finally:
        close_global_state_backend()


def test_udaf_restore_preserves_marker_positions(tmp_path):
    """Snapshot taken with spilled markers INTERLEAVED among resident
    groups: after restore the frame dict order (== emission row order)
    must match the pre-kill order — markers are recorded in position as
    states=None placeholders."""
    from denormalized_tpu.api.udaf import Accumulator
    from denormalized_tpu.logical.plan import WindowType
    from denormalized_tpu.physical.base import (
        EOS, ExecOperator, Marker,
    )
    from denormalized_tpu.physical.udaf_exec import SPILLED, UdafWindowExec
    from denormalized_tpu.state.checkpoint import CheckpointCoordinator

    class _Last(Accumulator):
        def __init__(self):
            self.v = 0.0

        def update(self, values):
            if len(values):
                self.v = float(values[-1])

        def merge(self, states):
            self.v = states[0]

        def state(self):
            return [self.v]

        def evaluate(self):
            return self.v

    last = F.udaf(_Last, DataType.FLOAT64, "last_v")

    in_schema = Schema([
        Field(CANONICAL_TIMESTAMP_COLUMN, DataType.TIMESTAMP_MS,
              nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ])

    def items():
        rng = np.random.default_rng(2)
        out = []
        for b in range(8):
            ts = np.sort(T0 + b * 300 + rng.integers(0, 300, 150))
            ks = np.asarray(
                [f"k{i}" for i in rng.integers(0, 1500, 150)], object
            )
            out.append(
                RecordBatch(in_schema, [ts, ks, rng.normal(5, 1, 150)])
            )
        out.append(Marker(1))  # deterministic mid-spill cut
        out.append(EOS)
        return out

    class _Script(ExecOperator):
        schema = in_schema

        def __init__(self, its):
            self.items = its

        def run(self):
            yield from self.items

    def make_op(backend_dir):
        store = LsmStore(backend_dir)
        ctrl = tiering.SpillController(store, budget_bytes=30_000)
        coord = CheckpointCoordinator(store)
        op = UdafWindowExec(
            _Script(items()),
            [col("k")],
            [last(col("v")).alias("lv"), F.count(col("v")).alias("n")],
            WindowType.TUMBLING, 5000, None,  # frames open across the cut
        )
        op.enable_spill("0_udaf", ctrl)
        op.enable_checkpointing("0", coord, None)
        return op, store, ctrl, coord

    path = str(tmp_path / "lsm")
    op, store, ctrl, coord = make_op(path)
    for item in op.run():
        if isinstance(item, Marker):
            coord.commit(item.epoch)
            break
    order_before = {
        j: [(int(g), f[g] is SPILLED) for g in f]
        for j, f in op._frames.items()
    }
    assert any(
        any(sp for _g, sp in groups) and not all(sp for _g, sp in groups)
        for groups in order_before.values()
    ), "cut did not interleave spilled and resident groups"
    key_order_before = {
        j: [
            str(op._interner.keys_of(np.asarray([g]))[0][0])
            for g, _sp in groups
        ]
        for j, groups in order_before.items()
    }
    ctrl.close()
    store.close()

    op2, store2, ctrl2, coord2 = make_op(path)
    assert coord2.committed_epoch is not None
    key_order_after = {
        j: [
            str(op2._interner.keys_of(np.asarray([g]))[0][0])
            for g in f
        ]
        for j, f in op2._frames.items()
    }
    assert key_order_after == key_order_before
    ctrl2.close()
    store2.close()
