"""Regression tests for defects found in code review: join-filter vs outer
matching, null propagation through joins/UDAF/session paths, upstream error
propagation, marker alignment after one-sided EOS."""

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.udaf import Accumulator
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.sources.memory import GeneratorSource, MemorySource

KV_SCHEMA = Schema(
    [
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ]
)


def kv(ts, ks, vs, masks=None):
    return RecordBatch(
        KV_SCHEMA,
        [np.asarray(ts, np.int64), np.asarray(ks, object), np.asarray(vs)],
        masks=[None, None, masks] if masks is not None else None,
    )


def test_left_join_filter_rejected_rows_are_unmatched():
    """A LEFT-join row whose only equi-match fails the join filter must
    appear null-padded, not vanish."""
    t0 = 1_700_000_000_000
    ctx = Context()
    left = ctx.from_source(
        MemorySource.from_batches([kv([t0], ["a"], [1.0])], timestamp_column="ts"),
        name="l",
    )
    right = (
        ctx.from_source(
            MemorySource.from_batches([kv([t0], ["a"], [9.0])], timestamp_column="ts"),
            name="r",
        )
        .with_column_renamed("k", "rk")
        .with_column_renamed("ts", "rts")
        .with_column_renamed("v", "rv")
    )
    res = left.join(right, "left", ["k"], ["rk"], filter=col("rv") > 100.0).collect()
    assert res.num_rows == 1
    m = res.mask("rv")
    assert m is not None and not m[0]


def test_join_propagates_null_masks():
    """Null values on matched rows keep their validity mask through the
    join output."""
    t0 = 1_700_000_000_000
    ctx = Context()
    left = ctx.from_source(
        MemorySource.from_batches(
            [kv([t0], ["a"], [0.0], masks=np.array([False]))], timestamp_column="ts"
        ),
        name="l",
    )
    right = (
        ctx.from_source(
            MemorySource.from_batches([kv([t0], ["a"], [9.0])], timestamp_column="ts"),
            name="r",
        )
        .with_column_renamed("k", "rk")
        .with_column_renamed("ts", "rts")
        .with_column_renamed("v", "rv")
    )
    res = left.join(right, "inner", ["k"], ["rk"]).collect()
    assert res.num_rows == 1
    m = res.mask("v")
    assert m is not None and not m[0]


def test_source_error_propagates():
    """A connector failure mid-stream must raise, not truncate silently."""

    def boom():
        yield kv([1_700_000_000_000], ["a"], [1.0])
        raise RuntimeError("broker gone")

    def ok():
        t0 = 1_700_000_000_000
        for i in range(50):
            yield kv([t0 + i], ["b"], [1.0])

    ctx = Context()
    src = GeneratorSource(
        KV_SCHEMA, [boom, ok], timestamp_column="ts", unbounded=True
    )
    with pytest.raises(RuntimeError, match="broker gone"):
        ctx.from_source(src).collect()


def test_udaf_window_respects_null_masks(make_batch, sensor_schema):
    """Builtins sharing a window() with a UDAF must still exclude nulls."""

    class Noop(Accumulator):
        def __init__(self):
            self.n = 0

        def update(self, v):
            self.n += len(v)

        def merge(self, s):
            self.n += s[0]

        def state(self):
            return [self.n]

        def evaluate(self):
            return self.n

    t0 = 1_700_000_000_000
    batch = RecordBatch(
        sensor_schema,
        [
            np.array([t0 + 10, t0 + 20, t0 + 30, t0 + 1500], dtype=np.int64),
            np.array(["a"] * 4, dtype=object),
            np.array([1.0, 99.0, 3.0, 0.0]),
        ],
        masks=[None, None, np.array([True, False, True, True])],
    )
    noop = F.udaf(Noop, DataType.INT64, "noop")
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches([batch], timestamp_column="occurred_at_ms")
        )
        .window(
            ["sensor_name"],
            [
                noop(col("reading")).alias("u"),
                F.count(col("reading")).alias("cnt"),
                F.sum(col("reading")).alias("s"),
            ],
            1000,
        )
        .collect()
    )
    i = list(res.column("window_start_time")).index(t0)
    assert int(res.column("cnt")[i]) == 2
    assert float(res.column("s")[i]) == 4.0


def test_session_window_respects_null_masks():
    t0 = 1_700_000_000_000
    batch = kv(
        [t0, t0 + 100, t0 + 200],
        ["a", "a", "a"],
        [1.0, 99.0, 3.0],
        masks=np.array([True, False, True]),
    )
    ctx = Context()
    res = (
        ctx.from_source(MemorySource.from_batches([batch], timestamp_column="ts"))
        .session_window(
            ["k"],
            [
                F.count(col("v")).alias("cnt"),
                F.sum(col("v")).alias("s"),
                F.max(col("v")).alias("mx"),
            ],
            gap_ms=500,
        )
        .collect()
    )
    assert res.num_rows == 1
    assert int(res.column("cnt")[0]) == 2
    assert float(res.column("s")[0]) == 4.0
    assert float(res.column("mx")[0]) == 3.0


def test_session_udaf_supported():
    """Sessions carry user UDAFs (formerly a PlanError)."""

    class Total(Accumulator):
        def __init__(self):
            self.t = 0.0

        def update(self, col):
            self.t += float(col.sum())

        def merge(self, state):
            self.t += state[0]

        def state(self):
            return [self.t]

        def evaluate(self):
            return self.t

    u = F.udaf(Total, DataType.FLOAT64, "total")
    t0 = 1_700_000_000_000
    ctx = Context()
    res = ctx.from_source(
        MemorySource.from_batches(
            [kv([t0, t0 + 10, t0 + 9000], ["a", "a", "w"], [1.5, 2.5, 0.0])],
            timestamp_column="ts",
        )
    ).session_window(["k"], [u(col("v")).alias("t")], 1000).collect()
    rows = {res.column("k")[i]: float(res.column("t")[i]) for i in range(res.num_rows)}
    assert rows["a"] == 4.0
