"""Rescale-on-restore: checkpoint a 4-worker cluster mid-stream, SIGKILL
it, restore at N=2 and N=8 — emissions must be byte-identical to the
uninterrupted single-process oracle (accumulators move whole under the
new hash map; nothing is re-aggregated).  The spilled variant runs a
skewed feed under a tiny state budget so part of the keyed state sits
in PR-9 spill blocks AT the cut, and re-buckets through the
merge-resident path."""

import json
import os
import shutil
import sys

import pytest

from denormalized_tpu.cluster import ClusterSpec, run_cluster
from denormalized_tpu.cluster.reader import read_cluster

TESTS_DIR = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, TESTS_DIR)

import cluster_jobs  # noqa: E402


def _spec(workdir, n, job_args) -> ClusterSpec:
    return ClusterSpec(
        workdir=str(workdir),
        n_workers=n,
        job="cluster_jobs:windowed_job",
        job_args=job_args,
        sys_path=[TESTS_DIR],
        liveness_timeout_s=240.0,
        max_restarts=0,
        checkpoint_interval_s=0.3,
    )


def _canonical(rows):
    return sorted(cluster_jobs.canonical_row(r) for r in rows)


def _fork_workdir(src, dst):
    shutil.copytree(src, dst, ignore=shutil.ignore_patterns("*.sock"))


def _keyed_snapshot_meta(workdir, version, n_workers, epoch):
    """Raw (non-mutating) read of each worker's keyed snapshot meta at
    ``epoch`` — no CheckpointCoordinator, which would GC/rewrite."""
    from denormalized_tpu.state.checkpoint import unframe_snapshot
    from denormalized_tpu.state.lsm import LsmStore
    from denormalized_tpu.state.serialization import unpack_snapshot

    manifest = json.load(
        open(os.path.join(workdir, "meta", "manifest.json"))
    )
    key = manifest["state_keys"]["keyed"]
    metas = []
    for w in range(n_workers):
        store = LsmStore(
            os.path.join(workdir, "state", f"v{version}", f"worker_{w}")
        )
        try:
            raw = store.get(f"{key}@{epoch}")
            if raw is None:
                metas.append(None)
                continue
            ok, payload = unframe_snapshot(raw)
            assert ok
            meta, _arrays = unpack_snapshot(payload)
            metas.append(meta)
        finally:
            store.close()
    return metas


def _run_rescale(tmp_path, job_args, new_counts, kill_after=1):
    oracle = cluster_jobs.oracle_rows(job_args)
    assert oracle
    wd = str(tmp_path / "base")
    phase1 = run_cluster(
        _spec(wd, 4, job_args), kill_after_commits=kill_after
    )
    assert phase1["status"] == "killed"
    assert phase1["commits"]
    results = {}
    for new_n in new_counts:
        wd2 = str(tmp_path / f"n{new_n}")
        _fork_workdir(wd, wd2)
        p2 = run_cluster(_spec(wd2, new_n, job_args))
        assert p2["status"] == "done"
        got = read_cluster(p2["segments"])
        rows = _canonical(got["rows"])
        assert len(got["rows"]) == len(oracle), (
            f"N=4->{new_n}: kept {len(got['rows'])} rows vs oracle "
            f"{len(oracle)} (clipped {got['clipped']}) — lost or "
            "duplicate emissions across the rescale"
        )
        assert rows == oracle, f"N=4->{new_n}: emissions diverge"
        results[new_n] = (phase1, p2)
    return phase1, results


JOB_ARGS = {
    "partitions": 4,
    "batches": 10,
    "rows": 48,
    "keys": 11,
    "batch_span_ms": 250,
    "window_ms": 1000,
    "pace_s": 0.2,
}


def test_rescale_down_and_up_byte_identical(tmp_path):
    """N=4 checkpoint → restore at N=2 (merging worker state) and N=8
    (splitting it), both byte-identical to the oracle."""
    phase1, results = _run_rescale(tmp_path, JOB_ARGS, (2, 8))
    # the cut landed mid-stream (otherwise this test degenerates to
    # replaying output files): the restored runs re-emitted windows
    for new_n, (_p1, p2) in results.items():
        assert p2["rows_total"] > 0, (
            f"N=4->{new_n} re-emitted nothing: the phase-1 kill landed "
            "post-EOS; slow the pace so the cut is mid-stream"
        )


SPILL_ARGS = {
    # 8 partitions over 4 workers → 2 readers per worker → the THREADED
    # ingest path, whose barrier polls stay responsive while partition
    # 0 sleeps (the bounded round-robin path would hold every barrier
    # hostage to the pause, and the cut could never land mid-silence)
    "partitions": 8,
    "unbounded": True,
    "batches": 8,
    "rows": 48,
    "keys": 11,
    "batch_span_ms": 250,
    "window_ms": 250,
    "pace_s": 0.12,
    # partition 0: event time 4x slower (its open windows pin
    # first_open) AND a mid-stream pause — while it is silent, nothing
    # touches/reloads the spilled prefix, so the barrier cut carries it
    "skew_divisor": 4,
    "p0_pause_after": 2,
    "p0_pause_s": 2.0,
    "engine": {
        # tiny budget: the skew-deferred window prefix spills to the
        # LSM tier, so the cut carries PR-9 spill-block refs
        "state_budget_bytes": 4096,
        # spilled windows finalize on host; keep the ring path on host
        # finalize too so every emission (oracle included) shares one
        # finalize dtype path — byte-identity needs ONE path, not two
        "device_finalize": False,
    },
}


def test_rescale_with_spilled_state(tmp_path):
    """Part of the keyed state sits in spill blocks at the cut; rescale
    merges it resident, re-buckets, and the restored run (tier map
    rebuilt under its own budget) still matches the oracle exactly.

    Whether the CUT carries spill refs is timing-dependent (a trailing
    partition's batch rebases first_open to the watermark floor and
    reloads the spilled prefix — by design), so the kill phase retries
    a few times until a cut with spilled state is secured; the restore
    comparison then runs against that cut."""
    oracle = cluster_jobs.oracle_rows(SPILL_ARGS)
    spilled_cut = None
    for attempt in range(3):
        wd = str(tmp_path / f"base{attempt}")
        phase1 = run_cluster(
            _spec(wd, 4, SPILL_ARGS), kill_after_commits=1
        )
        assert phase1["status"] == "killed" and phase1["commits"]
        metas = _keyed_snapshot_meta(wd, 0, 4, phase1["commits"][-1])
        if any(m is not None and m.get("spill_windows") for m in metas):
            spilled_cut = wd
            break
    assert spilled_cut is not None, (
        "no attempt produced a cut with spilled windows — the "
        "spilled-rescale path was not exercised"
    )
    wd2 = str(tmp_path / "n2")
    _fork_workdir(spilled_cut, wd2)
    p2 = run_cluster(_spec(wd2, 2, SPILL_ARGS))
    assert p2["status"] == "done"
    got = read_cluster(p2["segments"])
    rows = _canonical(got["rows"])
    assert len(got["rows"]) == len(oracle)
    assert rows == oracle, "spilled rescale: emissions diverge"
