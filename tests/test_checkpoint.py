"""Checkpoint/restore tests: LSM store roundtrip, snapshot serialization,
and the kill→recover integration the reference never had (SURVEY.md §4:
'checkpoint-kill-restore tests')."""

import numpy as np
import pytest

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.constants import WINDOW_START_COLUMN
from denormalized_tpu.sources.memory import MemorySource
from denormalized_tpu.state import channel_manager as cm
from denormalized_tpu.state.lsm import LsmStore, close_global_state_backend
from denormalized_tpu.state.serialization import pack_snapshot, unpack_snapshot


def test_lsm_roundtrip_and_recovery(tmp_path):
    s = LsmStore(str(tmp_path / "kv"))
    s.put("a", b"1")
    s.put("b", b"22")
    s.put("a", b"111")
    s.delete("b")
    assert s.get("a") == b"111" and s.get("b") is None
    s.close()
    s2 = LsmStore(str(tmp_path / "kv"))
    assert s2.get("a") == b"111" and len(s2) == 1
    for i in range(100):
        s2.put(f"k{i}", bytes([i]))
    s2.compact()
    assert s2.get("k42") == bytes([42]) and s2.get("a") == b"111"
    s2.close()
    s3 = LsmStore(str(tmp_path / "kv"))
    assert len(s3) == 101
    s3.close()


def test_lsm_torn_tail_recovery(tmp_path):
    s = LsmStore(str(tmp_path / "kv"))
    s.put("good", b"value")
    s.flush()
    s.close()
    # corrupt: append garbage (torn write)
    segs = sorted((tmp_path / "kv").glob("seg-*.log"))
    with open(segs[-1], "ab") as f:
        f.write(b"\x01\x02\x03garbage")
    s2 = LsmStore(str(tmp_path / "kv"))
    assert s2.get("good") == b"value"
    s2.put("after", b"x")
    assert s2.get("after") == b"x"
    s2.close()


def test_snapshot_pack_roundtrip():
    meta = {"watermark": 123, "nested": {"a": [1, 2]}}
    arrays = {
        "sums": np.arange(12, dtype=np.float32).reshape(3, 4),
        "counts": np.ones((2, 2), dtype=np.int32),
    }
    blob = pack_snapshot(meta, arrays)
    m2, a2 = unpack_snapshot(blob)
    assert m2 == meta
    np.testing.assert_array_equal(a2["sums"], arrays["sums"])
    np.testing.assert_array_equal(a2["counts"], arrays["counts"])


def _pipeline(ctx, batches):
    return ctx.from_source(
        MemorySource.from_batches(batches, timestamp_column="occurred_at_ms"),
        name="ckpt_src",
    ).window(
        ["sensor_name"],
        [
            F.count(col("reading")).alias("cnt"),
            F.sum(col("reading")).alias("s"),
            F.min(col("reading")).alias("mn"),
        ],
        1000,
    )


def _collect_windows(result):
    # values stay UNROUNDED: kill/restore comparisons are tolerance-based
    # (f32 merge order differs between a restored and an uninterrupted
    # run); rounding first would re-introduce boundary coin flips
    return {
        (int(result.column(WINDOW_START_COLUMN)[i]), result.column("sensor_name")[i]): (
            int(result.column("cnt")[i]),
            float(result.column("s")[i]),
            float(result.column("mn")[i]),
        )
        for i in range(result.num_rows)
    }


@pytest.fixture(autouse=True)
def _clean_global_backend():
    yield
    close_global_state_backend()


def _kill_restore_roundtrip(batches, make_cfg, state_dir):
    """Shared kill→restore protocol driver: run A crashes right after one
    committed barrier; run B restores from the same backend path.  Returns
    (golden, emitted_a, emitted_b)."""
    from denormalized_tpu.common.record_batch import RecordBatch as RB
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import Marker
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.orchestrator import Orchestrator

    golden = _collect_windows(_pipeline(Context(make_cfg(None)), batches).collect())

    ctx_a = Context(make_cfg(state_dir))
    root_a = executor.build_physical(
        lp.Sink(_pipeline(ctx_a, batches)._plan, CollectSink()), ctx_a
    )
    orch_a = Orchestrator(interval_s=9999)
    coord_a = wire_checkpointing(root_a, ctx_a, orch_a)
    emitted_a = {}
    items_seen = 0
    it = root_a.run()
    for item in it:
        if isinstance(item, RB):
            emitted_a.update(_collect_windows(item))
        # one barrier after the first mid-stream emission, then crash right
        # after the marker clears the pipeline (root commit = durable epoch)
        if items_seen == 1:
            orch_a.trigger_now()
        if isinstance(item, Marker):
            coord_a.commit(item.epoch)
            break
        items_seen += 1
    it.close()  # crash
    close_global_state_backend()

    ctx_b = Context(make_cfg(state_dir))
    root_b = executor.build_physical(
        lp.Sink(_pipeline(ctx_b, batches)._plan, CollectSink()), ctx_b
    )
    orch_b = Orchestrator(interval_s=9999)
    coord_b = wire_checkpointing(root_b, ctx_b, orch_b)
    assert coord_b.committed_epoch is not None  # run A's barrier is durable
    emitted_b = {}
    for item in root_b.run():
        if isinstance(item, RB):
            emitted_b.update(_collect_windows(item))
    return golden, emitted_a, emitted_b


def _assert_kill_restore(golden, emitted_a, emitted_b):
    combined = dict(emitted_a)
    combined.update(emitted_b)
    assert set(combined) == set(golden)
    for k in golden:
        got, want = combined[k], golden[k]
        assert got[0] == want[0], (k, got, want)  # counts: exact
        # f32 sums: a restored run merges the snapshot in a different
        # order than the uninterrupted run accumulated, so rounded-equal
        # is a coin flip at the rounding boundary — compare by tolerance
        np.testing.assert_allclose(
            got[1:], want[1:], rtol=1e-4, atol=1e-6, err_msg=str(k)
        )
    # the restored run must NOT have reprocessed from scratch (unless the
    # barrier landed before anything emitted at all)
    assert len(emitted_b) < len(golden) or len(emitted_a) == 0


def test_kill_and_restore(tmp_path, make_batch):
    """Crash mid-stream after a checkpoint; a fresh process-equivalent run
    resumes from the barrier and the union of emissions covers every golden
    window with identical values (at-least-once on the sink, exactly-once on
    engine state)."""
    rng = np.random.default_rng(21)
    t0 = 1_700_000_000_000
    batches = []
    for b in range(12):
        n = 200
        ts = np.sort(t0 + b * 400 + rng.integers(0, 400, n))
        keys = np.array([f"s{i}" for i in rng.integers(0, 7, n)], dtype=object)
        batches.append(make_batch(ts, keys, rng.normal(50, 5, n)))

    def make_cfg(path):
        return EngineConfig(
            checkpoint=path is not None,
            checkpoint_interval_s=9999,
            state_backend_path=path,
            # prompt emission: the trigger in these tests is keyed to
            # consumer-visible items, and the partial_merge deferral
            # (the 'auto' default) would otherwise let the bounded
            # source drain before the barrier has an injection point
            emit_lag_ms=0,
        )

    golden, a, b = _kill_restore_roundtrip(
        batches, make_cfg, str(tmp_path / "state")
    )
    _assert_kill_restore(golden, a, b)


def test_channel_manager_semantics():
    ch = cm.create_channel("t1")
    assert cm.create_channel("t1") is ch
    assert cm.get_sender("t1") is ch
    r = cm.take_receiver("t1")
    assert r is ch
    assert cm.take_receiver("t1") is None  # take-once
    cm.remove_channel("t1")
    assert cm.get_sender("t1") is None


@pytest.mark.parametrize(
    "strategy", ["key_sharded", "partial_final", "two_level"]
)
def test_kill_and_restore_sharded(tmp_path, make_batch, strategy):
    """Checkpoint/restore must also work when window state is sharded over
    the mesh (export → epoch snapshot → import into the sharded layout)."""
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the virtual 8-device platform")
    rng = np.random.default_rng(31)
    t0 = 1_700_000_000_000
    batches = []
    for b in range(10):
        n = 256
        ts = np.sort(t0 + b * 400 + rng.integers(0, 400, n))
        keys = np.array([f"s{i}" for i in rng.integers(0, 40, n)], dtype=object)
        batches.append(make_batch(ts, keys, rng.normal(50, 5, n)))

    def make_cfg(path):
        return EngineConfig(
            checkpoint=path is not None,
            checkpoint_interval_s=9999,
            state_backend_path=path,
            mesh_devices=8,
            shard_strategy=strategy,
            mesh_slices=2 if strategy == "two_level" else None,
        )

    golden, a, b = _kill_restore_roundtrip(
        batches, make_cfg, str(tmp_path / f"state_{strategy}")
    )
    _assert_kill_restore(golden, a, b)


def test_session_window_kill_and_restore(tmp_path, make_batch):
    """Session-window state (open sessions incl. Welford moments) must
    survive a kill→restore: run A crashes after one committed barrier, run
    B restores and the union of emissions matches an uninterrupted run."""
    from denormalized_tpu.common.record_batch import RecordBatch as RB
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import Marker
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.orchestrator import Orchestrator

    rng = np.random.default_rng(5)
    t0 = 1_700_000_000_000
    batches = []
    for b in range(12):
        n = 60
        # bursts of 200ms every 800ms with a 300ms gap: each burst's
        # sessions CLOSE when the next burst advances the watermark, so
        # emissions (and barriers) flow throughout the stream
        ts = np.sort(t0 + b * 800 + rng.integers(0, 200, n))
        keys = np.array([f"s{i}" for i in rng.integers(0, 4, n)], dtype=object)
        batches.append(make_batch(ts, keys, rng.normal(10, 2, n)))

    def pipeline(ctx):
        return ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms"),
            name="sess_src",
        ).session_window(
            ["sensor_name"],
            [
                F.count(col("reading")).alias("c"),
                F.sum(col("reading")).alias("s"),
                F.stddev(col("reading")).alias("sd"),
            ],
            gap_ms=300,
        )

    def windows(result):
        out = {}
        for i in range(result.num_rows):
            key = (
                result.column("sensor_name")[i],
                int(result.column(WINDOW_START_COLUMN)[i]),
            )
            sd = float(result.column("sd")[i])
            out[key] = (
                int(result.column("c")[i]),
                round(float(result.column("s")[i]), 3),
                round(sd, 4) if np.isfinite(sd) else None,
            )
        return out

    golden = windows(pipeline(Context()).collect())

    def make_cfg(path):
        # no emit_lag_ms here: session windows run in SessionWindowExec,
        # which has no partial_merge emission deferral
        return EngineConfig(
            checkpoint=path is not None,
            checkpoint_interval_s=9999,
            state_backend_path=path,
        )

    state_dir = str(tmp_path / "state")
    ctx_a = Context(make_cfg(state_dir))
    root_a = executor.build_physical(
        lp.Sink(pipeline(ctx_a)._plan, CollectSink()), ctx_a
    )
    orch_a = Orchestrator(interval_s=9999)
    coord_a = wire_checkpointing(root_a, ctx_a, orch_a)
    emitted_a = {}
    items_seen = 0
    it = root_a.run()
    for item in it:
        if isinstance(item, RB):
            emitted_a.update(windows(item))
        if items_seen == 1:
            orch_a.trigger_now()
        if isinstance(item, Marker):
            coord_a.commit(item.epoch)
            break
        items_seen += 1
    it.close()  # crash
    close_global_state_backend()

    ctx_b = Context(make_cfg(state_dir))
    root_b = executor.build_physical(
        lp.Sink(pipeline(ctx_b)._plan, CollectSink()), ctx_b
    )
    orch_b = Orchestrator(interval_s=9999)
    coord_b = wire_checkpointing(root_b, ctx_b, orch_b)
    assert coord_b.committed_epoch is not None
    emitted_b = {}
    for item in root_b.run():
        if isinstance(item, RB):
            emitted_b.update(windows(item))

    combined = dict(emitted_a)
    combined.update(emitted_b)
    assert set(combined) == set(golden)
    for k in golden:
        assert combined[k] == golden[k], (k, combined[k], golden[k])


# -- shared scaffolding for the process-level SIGKILL tests ---------------


def _sigkill_read_out(path):
    """Parse the child's JSONL emissions → {(ws, k): (count, sum)}."""
    import json as _json

    wins = {}
    try:
        with open(path) as f:
            for line in f:
                try:
                    o = _json.loads(line)
                except _json.JSONDecodeError:
                    continue  # torn tail from the SIGKILL
                if "ws" in o:
                    wins[(o["ws"], o["k"])] = (o["c"], o["s"])
    except FileNotFoundError:
        pass
    return wins


def _sigkill_child_err(out_path, n=800):
    try:
        return open(out_path + ".err").read()[-n:]
    except OSError:
        return "<no stderr>"


def _sigkill_env(broker, topic, state_path, interval, **extra):
    import os

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.update(
        JAX_PLATFORMS="cpu",
        # prepend the repo root but keep the rest (e.g. the TPU plugin's
        # site dir) — overwriting PYTHONPATH breaks other environments
        PYTHONPATH=os.pathsep.join(
            [repo_root] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        ),
        KR_BROKER=broker.bootstrap,
        KR_TOPIC=topic,
        KR_STATE=state_path,
        KR_INTERVAL=interval,
        **extra,
    )
    return env


def _sigkill_spawn(env, out_path):
    import os
    import subprocess
    import sys

    e = dict(env)
    e["KR_OUT"] = out_path
    with open(out_path + ".err", "w") as errf:
        return subprocess.Popen(
            [sys.executable, os.path.join(os.path.dirname(__file__),
                                          "_sigkill_child.py")],
            env=e, stderr=errf,
        )


def test_sigkill_process_kill_and_restore(tmp_path, make_batch):
    """TRUE process-level kill/restore (round-3 VERDICT item 6): a child
    process runs a checkpointed Kafka pipeline against the mock broker;
    the parent SIGKILLs it mid-stream after at least one committed epoch
    — a real ``os.kill`` that skips every ``finally`` block an in-process
    ``it.close()`` would run — restarts it on the same state path, and
    asserts golden-window equality plus no full reprocess.  This is what
    makes PARITY.md's "SIGKILL-tested" claim literal.

    Reference paths exercised: offset restore-by-seek
    (kafka_stream_read.rs:110-140), frame restore
    (grouped_window_agg_stream.rs:160-211)."""
    import json as _json
    import os
    import signal
    import subprocess
    import sys
    import threading
    import time

    from denormalized_tpu.testing.mock_kafka import MockKafkaBroker

    broker = MockKafkaBroker().start()
    t0 = 1_700_000_000_000
    keys = [f"k{i}" for i in range(5)]
    golden: dict = {}

    def produce_span(ms_lo, ms_hi, rows_per_ms=4):
        """Rows over [ms_lo, ms_hi) event time, round-robin over both
        partitions; updates the golden (count, sum) oracle."""
        payloads = [[], []]
        for ms in range(ms_lo, ms_hi):
            for r in range(rows_per_ms):
                ts = t0 + ms
                k = keys[(ms + r) % len(keys)]
                v = float((ms + r) % 97) / 7.0
                payloads[(ms + r) % 2].append(
                    _json.dumps({"ts": ts, "k": k, "v": v}).encode()
                )
                w = (ts // 500) * 500
                c, s = golden.get((w, k), (0, 0.0))
                golden[(w, k)] = (c + 1, s + v)
        for p in (0, 1):
            broker.produce("kr", p, payloads[p], ts_ms=t0 + ms_lo)

    read_out = _sigkill_read_out
    child_err = _sigkill_child_err
    out_a = str(tmp_path / "emit_a.jsonl")
    out_b = str(tmp_path / "emit_b.jsonl")
    env = _sigkill_env(broker, "kr", str(tmp_path / "state"), "0.3")

    def spawn(out_path):
        return _sigkill_spawn(env, out_path)

    stop_closers = threading.Event()

    def trickle(ms_lo, ms_hi, step=150, delay=0.25):
        """Continuous small-chunk production: the watermark is the batch's
        MIN timestamp (reference parity, RecordBatchWatermark), so a
        pre-produced topic fetched as one giant batch would never close a
        window — real streams arrive incrementally."""
        for lo in range(ms_lo, ms_hi, step):
            produce_span(lo, min(lo + step, ms_hi))
            time.sleep(delay)

    def wait_ready(out_path, proc, timeout=60):
        """Block until the child wrote its 'ready' line — producing before
        the consumer is up would land everything in its first fetch."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            try:
                if open(out_path).readline():
                    return
            except FileNotFoundError:
                pass
            assert proc.poll() is None, (
                "child exited before ready: " + child_err(out_path)
            )
            time.sleep(0.05)
        raise AssertionError("child never became ready")

    def closer_trickle():
        """Far-future rows, repeated: once a consumer drains the backlog,
        its next fetch holds only these (batch min ts = 5000+) and the
        watermark jumps past every real window."""
        ms = 5000
        while not stop_closers.is_set():
            produce_span(ms, ms + 1, rows_per_ms=1)
            ms += 1
            time.sleep(0.1)

    try:
        broker.create_topic("kr", partitions=2)
        p_a = spawn(out_a)
        wait_ready(out_a, p_a)
        feeder = threading.Thread(target=trickle, args=(0, 3600), daemon=True)
        feeder.start()
        try:
            deadline = time.time() + 120
            while time.time() < deadline:
                if len(read_out(out_a)) >= 10:  # >= 2 windows emitted
                    break
                assert p_a.poll() is None, (
                        "child A exited early: " + child_err(out_a)
                    )
                time.sleep(0.05)
            else:
                raise AssertionError(
                    "child A never emitted 2 windows; stderr: "
                    + child_err(out_a)
                )
            # >= 3 checkpoint intervals after the emissions: at least one
            # epoch that covers them is committed by now
            time.sleep(1.0)
            assert p_a.poll() is None
        finally:
            if p_a.poll() is None:
                os.kill(p_a.pid, signal.SIGKILL)  # REAL mid-stream kill
            p_a.wait(10)
        wins_a = read_out(out_a)
        assert len(wins_a) >= 10
        feeder.join()  # the full feed is produced either way → golden fixed

        # freeze 'needed' BEFORE the closer thread starts mutating golden
        needed = {k for k in golden if k[0] + 500 <= t0 + 3600}
        closers = threading.Thread(target=closer_trickle, daemon=True)
        closers.start()
        p_b = spawn(out_b)
        try:
            deadline = time.time() + 150
            while time.time() < deadline:
                union = dict(wins_a)
                union.update(read_out(out_b))
                if needed <= set(union):
                    break
                assert p_b.poll() is None, (
                        "child B exited early: " + child_err(out_b)
                    )
                time.sleep(0.1)
            else:
                missing = needed - set(union)
                raise AssertionError(
                    f"recovery never covered {missing}; stderr: "
                    + child_err(out_b)
                )
        finally:
            stop_closers.set()
            if p_b.poll() is None:
                os.kill(p_b.pid, signal.SIGKILL)
            p_b.wait(10)
        wins_b = read_out(out_b)

        union = dict(wins_a)
        union.update(wins_b)
        lost = []
        for k in needed:
            c, s = golden[k]
            gc, gs = union.get(k, (None, None))
            if gc != c or gs is None or abs(gs - s) > 1e-4 * max(1.0, abs(s)):
                lost.append((k, (gc, gs), (c, s)))
        assert not lost, f"windows lost/corrupt after SIGKILL: {lost[:5]}"
        # no full reprocess: at least one window child A emitted was
        # restored-past (not re-emitted) by child B
        assert set(wins_a) - set(wins_b), (
            "recovery child re-emitted every window — full reprocess"
        )
    finally:
        broker.stop()


def test_sigkill_mid_split_fetch_restore(tmp_path):
    """SIGKILL while a SPLIT fetch drains: the topic is pre-filled so the
    child's fetches arrive oversized and get sliced by max.batch.rows;
    with a 50ms barrier cadence, committed epochs land BETWEEN slices of
    one fetch, so the persisted offsets are the exact per-record slice
    boundaries (kc_rec_kafka_offsets).  A real mid-drain kill + restore
    must reproduce the golden windows exactly — a replayed slice would
    double counts, a skipped one would lose rows."""
    import json as _json
    import os
    import signal
    import subprocess
    import sys
    import threading
    import time

    from denormalized_tpu.testing.mock_kafka import MockKafkaBroker

    broker = MockKafkaBroker().start()
    t0 = 1_700_000_000_000
    keys = [f"k{i}" for i in range(5)]
    span_ms, rows_per_ms = 1500, 400  # 600K rows pre-filled
    golden: dict = {}
    payloads = []
    for ms in range(span_ms):
        for r in range(rows_per_ms):
            ts = t0 + ms
            k = keys[(ms + r) % len(keys)]
            v = float((ms * 7 + r) % 97) / 7.0
            payloads.append(
                _json.dumps({"ts": ts, "k": k, "v": v}).encode()
            )
            w = (ts // 500) * 500
            c, s = golden.get((w, k), (0, 0.0))
            golden[(w, k)] = (c + 1, s + v)

    read_out = _sigkill_read_out
    child_err = _sigkill_child_err
    out_a = str(tmp_path / "split_a.jsonl")
    out_b = str(tmp_path / "split_b.jsonl")
    env = _sigkill_env(
        broker, "krs", str(tmp_path / "state"), "0.05",
        KR_MAX_BATCH_ROWS="2048",
    )

    def spawn(out_path):
        return _sigkill_spawn(env, out_path)

    stop_closers = threading.Event()

    def closer_trickle():
        ms = 5000
        while not stop_closers.is_set():
            broker.produce(
                "krs", 0,
                [_json.dumps({"ts": t0 + ms, "k": "k0", "v": 0.0}).encode()],
                ts_ms=t0 + ms,
            )
            ms += 1
            time.sleep(0.1)

    try:
        broker.create_topic("krs", partitions=1)
        broker.produce_batched("krs", 0, payloads)  # pre-filled: big fetches
        p_a = spawn(out_a)
        try:
            # kill as soon as the first window emits + a couple more
            # barrier intervals — mid-drain, with committed epochs whose
            # offsets sit inside a split fetch
            deadline = time.time() + 90
            while time.time() < deadline:
                if len(read_out(out_a)) >= 5:
                    break
                assert p_a.poll() is None, (
                    "child A exited early: " + child_err(out_a)
                )
                time.sleep(0.02)
            else:
                raise AssertionError(
                    "child A never emitted; stderr: " + child_err(out_a)
                )
            time.sleep(0.2)  # ~4 barrier intervals
        finally:
            if p_a.poll() is None:
                os.kill(p_a.pid, signal.SIGKILL)
            p_a.wait(10)
        wins_a = read_out(out_a)
        assert wins_a, "no emission before the kill"

        needed = {k for k in golden if k[0] + 500 <= t0 + span_ms}
        closers = threading.Thread(target=closer_trickle, daemon=True)
        closers.start()
        p_b = spawn(out_b)
        try:
            deadline = time.time() + 120
            while time.time() < deadline:
                union = dict(wins_a)
                union.update(read_out(out_b))
                if needed <= set(union):
                    break
                assert p_b.poll() is None, (
                    "child B exited early: " + child_err(out_b)
                )
                time.sleep(0.1)
            else:
                missing = needed - set(union)
                raise AssertionError(
                    f"recovery never covered {missing}; stderr: "
                    + child_err(out_b)
                )
        finally:
            stop_closers.set()
            if p_b.poll() is None:
                os.kill(p_b.pid, signal.SIGKILL)
            p_b.wait(10)

        union = dict(wins_a)
        union.update(read_out(out_b))
        bad = []
        for k in needed:
            c, s = golden[k]
            gc_, gs = union.get(k, (None, None))
            if gc_ != c or gs is None or abs(gs - s) > 1e-4 * max(1.0, abs(s)):
                bad.append((k, (gc_, gs), (c, s)))
        assert not bad, (
            f"windows lost/duplicated across a mid-split kill: {bad[:5]}"
        )
        # emission is barrier-aligned (emit_on_close=False), so everything
        # child A emitted was committed — the recovery child must restore
        # PAST at least one of A's windows, not reprocess from offset 0
        assert set(wins_a) - set(read_out(out_b)), (
            "recovery child re-emitted every window — full reprocess"
        )
    finally:
        broker.stop()


def _join_pipeline(ctx, t_batches, h_batches):
    left = ctx.from_source(
        MemorySource.from_batches(t_batches, timestamp_column="occurred_at_ms"),
        name="jk_t",
    ).window(["sensor_name"], [F.avg(col("reading")).alias("avg_t")], 1000)
    right = (
        ctx.from_source(
            MemorySource.from_batches(h_batches, timestamp_column="occurred_at_ms"),
            name="jk_h",
        )
        .window(["sensor_name"], [F.avg(col("reading")).alias("avg_h")], 1000)
        .with_column_renamed("sensor_name", "hs")
        .with_column_renamed("window_start_time", "hws")
        .with_column_renamed("window_end_time", "hwe")
    )
    return left.join(
        right, "inner", ["sensor_name", "window_start_time"], ["hs", "hws"]
    )


def _join_windows(result_or_batch):
    out = {}
    r = result_or_batch
    for i in range(r.num_rows):
        k = (int(r.column(WINDOW_START_COLUMN)[i]), r.column("sensor_name")[i])
        out[k] = (
            round(float(r.column("avg_t")[i]), 4),
            round(float(r.column("avg_h")[i]), 4),
        )
    return out


@pytest.mark.parametrize("mesh", [None, 8], ids=["single", "sharded"])
def test_join_kill_and_restore(tmp_path, make_batch, mesh):
    """Join-state checkpointing (round-3 VERDICT item 9): kill after a
    committed aligned barrier, restore, and the union of join emissions
    covers every golden pair without a full reprocess.  The join snapshot
    carries both sides' retained build rows + matched flags + watermarks;
    barrier alignment BUFFERS the early side's post-marker items so the
    snapshot can never contain rows the source replay would re-insert."""
    import jax

    from denormalized_tpu.common.record_batch import RecordBatch as RB
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import Marker
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.orchestrator import Orchestrator

    if mesh and len(jax.devices()) < mesh:
        pytest.skip("needs the virtual 8-device platform")
    rng = np.random.default_rng(41)
    t0 = 1_700_000_000_000

    def batches(shift):
        out = []
        for b in range(14):
            n = 160
            ts = np.sort(t0 + b * 400 + rng.integers(0, 400, n))
            keys = np.array(
                [f"s{i}" for i in rng.integers(0, 6, n)], dtype=object
            )
            out.append(make_batch(ts, keys, rng.normal(50, 5, n) + shift))
        return out

    tb, hb = batches(0), batches(100)

    def make_cfg(path):
        return EngineConfig(
            checkpoint=path is not None,
            checkpoint_interval_s=9999,
            state_backend_path=path,
            mesh_devices=mesh,
            emit_lag_ms=0,
        )

    golden = _join_windows(
        _join_pipeline(Context(make_cfg(None)), tb, hb).collect()
    )
    assert len(golden) > 8

    state_dir = str(tmp_path / f"state_join_{mesh}")
    ctx_a = Context(make_cfg(state_dir))
    root_a = executor.build_physical(
        lp.Sink(_join_pipeline(ctx_a, tb, hb)._plan, CollectSink()), ctx_a
    )
    orch_a = Orchestrator(interval_s=9999)
    coord_a = wire_checkpointing(root_a, ctx_a, orch_a)
    emitted_a = {}
    items_seen = 0
    it = root_a.run()
    for item in it:
        if isinstance(item, RB):
            emitted_a.update(_join_windows(item))
        if items_seen == 1:
            orch_a.trigger_now()
        if isinstance(item, Marker):
            coord_a.commit(item.epoch)
            break
        items_seen += 1
    it.close()  # crash
    close_global_state_backend()

    ctx_b = Context(make_cfg(state_dir))
    root_b = executor.build_physical(
        lp.Sink(_join_pipeline(ctx_b, tb, hb)._plan, CollectSink()), ctx_b
    )
    orch_b = Orchestrator(interval_s=9999)
    coord_b = wire_checkpointing(root_b, ctx_b, orch_b)
    assert coord_b.committed_epoch is not None
    emitted_b = {}
    for item in root_b.run():
        if isinstance(item, RB):
            emitted_b.update(_join_windows(item))

    combined = dict(emitted_a)
    combined.update(emitted_b)
    assert set(combined) == set(golden), (
        set(golden) ^ set(combined)
    )
    for k in golden:
        gt, gh = golden[k]
        ct, ch = combined[k]
        assert ct == pytest.approx(gt, rel=1e-5), (k, ct, gt)
        assert ch == pytest.approx(gh, rel=1e-5), (k, ch, gh)
    # restored run resumed (upstream windows + join state restored), it
    # did not reprocess the whole stream
    assert len(emitted_b) < len(golden) or len(emitted_a) == 0


@pytest.mark.parametrize("seed", [3, 17])
def test_repeated_kill_restore_cycles(tmp_path, make_batch, seed):
    """Recovery-after-recovery: several crash/restore cycles against ONE
    backend path, each cycle checkpointing anew at a random point before
    crashing.  Exercises epoch chaining (a restored run committing fresh
    epochs over the prior run's state) and re-snapshot-after-restore —
    paths a single kill/restore never touches.  The union of all cycles'
    emissions must equal the golden windows exactly."""
    from denormalized_tpu.common.record_batch import RecordBatch as RB
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import Marker
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.orchestrator import Orchestrator

    rng = np.random.default_rng(seed)
    t0 = 1_700_000_000_000
    batches = []
    for b in range(24):
        n = 150
        ts = np.sort(t0 + b * 300 + rng.integers(0, 300, n))
        keys = np.array(
            [f"s{i}" for i in rng.integers(0, 6, n)], dtype=object
        )
        batches.append(make_batch(ts, keys, rng.normal(50, 5, n)))

    def make_cfg(path):
        return EngineConfig(
            checkpoint=path is not None,
            checkpoint_interval_s=9999,
            state_backend_path=path,
            # prompt emission: the trigger in these tests is keyed to
            # consumer-visible items, and the partial_merge deferral
            # (the 'auto' default) would otherwise let the bounded
            # source drain before the barrier has an injection point
            emit_lag_ms=0,
        )

    golden = _collect_windows(
        _pipeline(Context(make_cfg(None)), batches).collect()
    )
    state_dir = str(tmp_path / "state")

    combined = {}
    emitted_before = 0  # windows emitted across all prior cycles
    last_epoch = None
    crashed = True
    for cycle in range(5):
        ctx = Context(make_cfg(state_dir))
        root = executor.build_physical(
            lp.Sink(_pipeline(ctx, batches)._plan, CollectSink()), ctx
        )
        orch = Orchestrator(interval_s=9999)
        coord = wire_checkpointing(root, ctx, orch)
        if cycle > 0:
            assert coord.committed_epoch is not None
            if last_epoch is not None:
                assert coord.committed_epoch >= last_epoch
        crash_after = int(rng.integers(1, 5))
        items_seen = 0
        crashed = False
        cycle_emitted = {}
        it = root.run()
        for item in it:
            if isinstance(item, RB):
                cycle_emitted.update(_collect_windows(item))
            if items_seen == crash_after:
                orch.trigger_now()
            if isinstance(item, Marker) and cycle < 4:
                coord.commit(item.epoch)
                last_epoch = item.epoch
                crashed = True
                break
            if isinstance(item, Marker):
                coord.commit(item.epoch)
            items_seen += 1
        it.close()
        orch.stop()  # drain the barrier channels: a trigger on the last
        # item must not leak a stale Marker into a later run's channels
        close_global_state_backend()
        # a restored cycle resuming over prior state must NOT reprocess
        # from scratch: if anything was emitted before, this cycle can
        # only be emitting the tail (from-scratch would re-emit ~all)
        if cycle > 0 and emitted_before > 0:
            assert len(cycle_emitted) < len(golden), (
                f"cycle {cycle} re-emitted {len(cycle_emitted)} of "
                f"{len(golden)} golden windows — reprocessed from scratch?"
            )
        combined.update(cycle_emitted)
        emitted_before += len(cycle_emitted)
        if not crashed:
            break
    assert not crashed, "stream never ran to completion within 5 cycles"
    assert set(combined) == set(golden)
    for k in golden:
        got, want = combined[k], golden[k]
        assert got[0] == want[0], (k, got, want)
        np.testing.assert_allclose(
            got[1:], want[1:], rtol=1e-4, atol=1e-6, err_msg=str(k)
        )


def test_semi_join_kill_and_restore_exactly_once(tmp_path, make_batch):
    """Checkpoint/restore of a SEMI join (VERDICT-r4 #5): the matched
    flags ARE the 'already emitted' record, so after a crash at a
    committed aligned barrier the restored run must emit exactly the
    not-yet-emitted matching left rows — union == golden, intersection
    empty, no row twice."""
    from collections import Counter

    from denormalized_tpu.common.record_batch import RecordBatch as RB
    from denormalized_tpu.logical import plan as lp
    from denormalized_tpu.physical.base import Marker
    from denormalized_tpu.physical.simple_execs import CollectSink
    from denormalized_tpu.runtime import executor
    from denormalized_tpu.state.checkpoint import wire_checkpointing
    from denormalized_tpu.state.orchestrator import Orchestrator

    rng = np.random.default_rng(23)
    t0 = 1_700_000_000_000

    def batches(seed, keyspace):
        # enough batches that the triggered barrier lands mid-stream:
        # the join's pump queues (maxsize 8) backpressure the sources, so
        # with ~2 items consumed at trigger time the sources are still
        # mid-replay and the marker aligns well before EOS
        r = np.random.default_rng(seed)
        out = []
        for b in range(48):
            n = 60
            ts = np.sort(t0 + b * 400 + r.integers(0, 400, n))
            keys = np.array(
                [f"k{i}" for i in r.integers(0, keyspace, n)], dtype=object
            )
            out.append(make_batch(ts, keys, r.normal(0, 1, n)))
        return out

    lb = batches(1, 40)   # left keys k0..k39
    rb_ = batches(2, 20)  # right keys k0..k19: half the left rows match

    def pipeline(ctx):
        left = ctx.from_source(
            MemorySource.from_batches(lb, timestamp_column="occurred_at_ms"),
            name="sj_l",
        )
        right = ctx.from_source(
            MemorySource.from_batches(rb_, timestamp_column="occurred_at_ms"),
            name="sj_r",
        )
        return left.join(right, "semi", ["sensor_name"], ["sensor_name"])

    def rows_of(batch):
        return [
            (int(batch.column("occurred_at_ms")[i]),
             batch.column("sensor_name")[i],
             round(float(batch.column("reading")[i]), 6))
            for i in range(batch.num_rows)
        ]

    def make_cfg(path):
        return EngineConfig(
            checkpoint=path is not None,
            checkpoint_interval_s=9999,
            state_backend_path=path,
        )

    golden = Counter(rows_of(pipeline(Context(make_cfg(None))).collect()))
    assert golden and max(golden.values()) == 1
    close_global_state_backend()

    state_dir = str(tmp_path / "state_semi")
    ctx_a = Context(make_cfg(state_dir))
    root_a = executor.build_physical(
        lp.Sink(pipeline(ctx_a)._plan, CollectSink()), ctx_a
    )
    orch_a = Orchestrator(interval_s=9999)
    coord_a = wire_checkpointing(root_a, ctx_a, orch_a)
    emitted_a: Counter = Counter()
    items_seen = 0
    it = root_a.run()
    for item in it:
        if isinstance(item, RB):
            emitted_a.update(rows_of(item))
        if items_seen == 1:
            orch_a.trigger_now()
        if isinstance(item, Marker):
            coord_a.commit(item.epoch)
            break
        items_seen += 1
    it.close()  # crash
    close_global_state_backend()

    ctx_b = Context(make_cfg(state_dir))
    root_b = executor.build_physical(
        lp.Sink(pipeline(ctx_b)._plan, CollectSink()), ctx_b
    )
    orch_b = Orchestrator(interval_s=9999)
    coord_b = wire_checkpointing(root_b, ctx_b, orch_b)
    assert coord_b.committed_epoch is not None
    emitted_b: Counter = Counter()
    for item in root_b.run():
        if isinstance(item, RB):
            emitted_b.update(rows_of(item))
    close_global_state_backend()

    combined = emitted_a + emitted_b
    assert set(combined) == set(golden), (
        sorted(set(golden) ^ set(combined))[:5]
    )
    dupes = {k: c for k, c in combined.items() if c != 1}
    assert not dupes, f"semi rows emitted more than once: {list(dupes)[:5]}"
