"""Sliding windows, capacity growth, and null handling."""

import collections

import numpy as np

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.common.constants import WINDOW_START_COLUMN
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.sources.memory import MemorySource


def test_sliding_window_fanout(sensor_schema, make_batch):
    """1s window / 200ms slide: every row lands in exactly 5 windows
    (the reference enumerates overlapping slides at
    streaming_window.rs:1063-1075; we fan out on device)."""
    rng = np.random.default_rng(1)
    t0 = 1_700_000_000_000
    batches = [
        make_batch(
            np.sort(t0 + i * 300 + rng.integers(0, 300, 50)),
            ["s"] * 50,
            rng.normal(0, 1, 50),
        )
        for i in range(10)
    ]
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
        )
        .window(["sensor_name"], [F.count(col("reading")).alias("cnt")], 1000, 200)
        .collect()
    )
    starts = res.column(WINDOW_START_COLUMN)
    assert (np.diff(sorted(set(starts.tolist()))) == 200).all()
    assert sum(int(c) for c in res.column("cnt")) == 500 * 5


def test_sliding_window_non_multiple_slide(sensor_schema, make_batch):
    """Window length not a multiple of slide (1000ms/300ms): membership uses
    the exact ms bound, k = ceil(L/S) = 4 but some rows hit only 3 windows."""
    t0 = 1_700_000_000_000
    ts = t0 + np.arange(0, 3000, 10)
    batches = [make_batch(ts, ["s"] * len(ts), np.ones(len(ts)))]
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
        )
        .window(["sensor_name"], [F.count(col("reading")).alias("cnt")], 1000, 300)
        .collect()
    )
    got = {
        int(res.column(WINDOW_START_COLUMN)[i]): int(res.column("cnt")[i])
        for i in range(res.num_rows)
    }
    oracle = collections.Counter()
    for t in ts.tolist():
        j = t // 300
        while j * 300 + 1000 > t:
            if j * 300 <= t:
                oracle[j * 300] += 1
            j -= 1
    assert got == dict(oracle)


def test_group_capacity_growth_first_batch(sensor_schema, make_batch):
    """More distinct keys in the first batch than the initial capacity (128):
    G must grow before any scatter drops data."""
    rng = np.random.default_rng(2)
    t0 = 1_700_000_000_000
    n = 5000
    ts = np.sort(t0 + rng.integers(0, 2000, n))
    keys = np.array([f"k{i}" for i in rng.integers(0, 2000, n)], dtype=object)
    vals = rng.normal(0, 1, n)
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(
                [make_batch(ts, keys, vals)], timestamp_column="occurred_at_ms"
            )
        )
        .window(["sensor_name"], [F.sum(col("reading")).alias("s")], 1000)
        .collect()
    )
    oracle = collections.defaultdict(float)
    for t, k, v in zip(ts, keys, vals):
        oracle[((t // 1000) * 1000, k)] += v
    got = {
        (int(res.column(WINDOW_START_COLUMN)[i]), res.column("sensor_name")[i]): float(
            res.column("s")[i]
        )
        for i in range(res.num_rows)
    }
    assert set(got) == set(oracle)
    for k in oracle:
        np.testing.assert_allclose(got[k], oracle[k], rtol=1e-4, atol=1e-4)


def test_window_ring_growth(sensor_schema, make_batch):
    """A single batch spanning 40 windows grows the ring (initial 16)."""
    t0 = 1_700_000_000_000
    ts = t0 + np.arange(0, 40_000, 100)
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(
                [make_batch(ts, ["a"] * len(ts), np.ones(len(ts)))],
                timestamp_column="occurred_at_ms",
            )
        )
        .window(["sensor_name"], [F.count(col("reading")).alias("cnt")], 1000)
        .collect()
    )
    assert res.num_rows == 40
    assert all(int(c) == 10 for c in res.column("cnt"))


def test_null_values_excluded(sensor_schema):
    """Null readings are excluded from count/sum/avg/min/max
    (DataFusion null semantics the reference inherits)."""
    t0 = 1_700_000_000_000
    batch = RecordBatch(
        sensor_schema,
        [
            np.array([t0 + 10, t0 + 20, t0 + 30, t0 + 1500], dtype=np.int64),
            np.array(["a", "a", "a", "a"], dtype=object),
            np.array([1.0, 99.0, 3.0, 0.0]),
        ],
        masks=[None, None, np.array([True, False, True, True])],
    )
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches([batch], timestamp_column="occurred_at_ms")
        )
        .window(
            ["sensor_name"],
            [
                F.count(col("reading")).alias("cnt"),
                F.sum(col("reading")).alias("s"),
                F.max(col("reading")).alias("mx"),
            ],
            1000,
        )
        .collect()
    )
    i = list(res.column(WINDOW_START_COLUMN)).index(t0)
    assert int(res.column("cnt")[i]) == 2
    assert float(res.column("s")[i]) == 4.0
    assert float(res.column("mx")[i]) == 3.0


def test_multi_column_group_by():
    """2- and 3-column group keys (int64-packing fast path and the general
    row-dedup path) must match a per-row oracle exactly."""
    from denormalized_tpu.common.schema import DataType, Field, Schema

    schema = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("region", DataType.STRING, nullable=False),
            Field("sensor", DataType.STRING, nullable=False),
            Field("device_id", DataType.INT64, nullable=False),
            Field("v", DataType.FLOAT64),
        ]
    )
    rng = np.random.default_rng(3)
    t0 = 1_700_000_000_000
    batches = []
    for b in range(5):
        n = 800
        ts = np.sort(t0 + b * 400 + rng.integers(0, 400, n))
        batches.append(
            RecordBatch(
                schema,
                [
                    ts,
                    np.array([f"r{i}" for i in rng.integers(0, 4, n)], dtype=object),
                    np.array([f"s{i}" for i in rng.integers(0, 7, n)], dtype=object),
                    rng.integers(0, 3, n).astype(np.int64),
                    rng.normal(0, 1, n),
                ],
            )
        )
    for group_cols in (["region", "sensor"], ["region", "sensor", "device_id"]):
        ctx = Context()
        res = (
            ctx.from_source(
                MemorySource.from_batches(batches, timestamp_column="ts")
            )
            .window(group_cols, [F.count(col("v")).alias("c")], 1000)
            .collect()
        )
        oracle = collections.Counter()
        for bt in batches:
            for i in range(bt.num_rows):
                key = tuple(bt.column(g)[i] for g in group_cols) + (
                    (int(bt.column("ts")[i]) // 1000) * 1000,
                )
                oracle[key] += 1
        got = {
            tuple(res.column(g)[i] for g in group_cols)
            + (int(res.column("window_start_time")[i]),): int(res.column("c")[i])
            for i in range(res.num_rows)
        }
        assert got == dict(oracle)


def test_single_numeric_group_column():
    """Review regression: grouping by one numeric column must produce a
    working reverse map and capacity accounting."""
    from denormalized_tpu.common.schema import DataType, Field, Schema
    from denormalized_tpu.ops.interner import GroupInterner

    g = GroupInterner(1)
    ids = g.intern([np.array([10, 20, 10, 30], dtype=np.int64)])
    assert ids.tolist() == [0, 1, 0, 2]
    assert len(g) == 3
    kv = g.keys_of(np.array([0, 1, 2]))
    assert kv[0].tolist() == [10, 20, 30]

    schema = Schema(
        [
            Field("ts", DataType.INT64, nullable=False),
            Field("device_id", DataType.INT64, nullable=False),
            Field("v", DataType.FLOAT64),
        ]
    )
    t0 = 1_700_000_000_000
    batch = RecordBatch(
        schema,
        [
            np.array([t0, t0 + 10, t0 + 20, t0 + 1500], dtype=np.int64),
            np.array([7, 8, 7, 7], dtype=np.int64),
            np.array([1.0, 2.0, 3.0, 4.0]),
        ],
    )
    ctx = Context()
    res = (
        ctx.from_source(MemorySource.from_batches([batch], timestamp_column="ts"))
        .window(["device_id"], [F.sum(col("v")).alias("s")], 1000)
        .collect()
    )
    got = {
        (int(res.column("device_id")[i]), int(res.column(WINDOW_START_COLUMN)[i])): float(
            res.column("s")[i]
        )
        for i in range(res.num_rows)
    }
    assert got == {(7, t0): 4.0, (8, t0): 2.0, (7, t0 + 1000): 4.0}


def test_unicode_group_keys_and_restore():
    from denormalized_tpu.ops.interner import GroupInterner

    keys = np.array(["München", "東京", "München", "naïve"], dtype=object)
    g = GroupInterner(1)
    ids = g.intern([keys])
    assert ids.tolist() == [0, 1, 0, 2]
    assert g.keys_of(np.array([1]))[0][0] == "東京"
    g2 = GroupInterner.restore(g.snapshot())
    assert g2.intern([keys]).tolist() == [0, 1, 0, 2]

    # numeric restore keeps id continuity (review regression)
    gnum = GroupInterner(1)
    gnum.intern([np.array([10, 20], np.int64)])
    gnum2 = GroupInterner.restore(gnum.snapshot())
    assert gnum2.intern([np.array([30, 10], np.int64)]).tolist() == [2, 0]


def test_trailing_nul_normalization_consistent():
    """Keys differing only by trailing NULs normalize to one id, the same
    way in native and fallback paths (documented S-dtype limitation)."""
    from denormalized_tpu.ops import interner as im

    keys = np.array(["a", "a\x00"], dtype=object)
    native = im.ColumnInterner()
    ids_native = native.intern_array(keys)
    fb = im.ColumnInterner()
    fb._h = None  # force fallback
    ids_fb = fb.intern_array(keys)
    assert ids_native.tolist() == ids_fb.tolist() == [0, 0]


def test_emission_compaction_parity(make_batch):
    """emission_compaction=True must be output-identical to the full-read
    path — incl. a SPARSE shape (large padded capacity, few active keys),
    where the compacted transfer is the win."""
    import numpy as np

    from denormalized_tpu import Context, col
    from denormalized_tpu.api import functions as F
    from denormalized_tpu.api.context import EngineConfig
    from denormalized_tpu.common.constants import WINDOW_START_COLUMN
    from denormalized_tpu.sources.memory import MemorySource

    rng = np.random.default_rng(17)
    t0 = 1_700_000_000_000
    batches = []
    for b in range(10):
        n = 512
        ts = np.sort(t0 + b * 400 + rng.integers(0, 400, n))
        keys = np.array(
            [f"k{i}" for i in rng.integers(0, 9, n)], dtype=object
        )
        batches.append(make_batch(ts, keys, rng.normal(5, 2, n)))

    def run(compaction):
        ctx = Context(
            EngineConfig(
                emission_compaction=compaction,
                # sparse: capacity padded far beyond the 9 live keys
                min_group_capacity=4096,
            )
        )
        res = (
            ctx.from_source(
                MemorySource.from_batches(
                    batches, timestamp_column="occurred_at_ms"
                )
            )
            .window(
                ["sensor_name"],
                [
                    F.count(col("reading")).alias("c"),
                    F.sum(col("reading")).alias("s"),
                    F.min(col("reading")).alias("mn"),
                    F.avg(col("reading")).alias("a"),
                ],
                1000,
                500,
            )
            .collect()
        )
        return {
            (
                int(res.column(WINDOW_START_COLUMN)[i]),
                res.column("sensor_name")[i],
            ): (
                int(res.column("c")[i]),
                round(float(res.column("s")[i]), 3),
                round(float(res.column("mn")[i]), 5),
                round(float(res.column("a")[i]), 5),
            )
            for i in range(res.num_rows)
        }

    off = run(False)
    on = run(True)
    assert on == off and len(on) > 0
