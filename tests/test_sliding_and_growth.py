"""Sliding windows, capacity growth, and null handling."""

import collections

import numpy as np

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.common.constants import WINDOW_START_COLUMN
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.sources.memory import MemorySource


def test_sliding_window_fanout(sensor_schema, make_batch):
    """1s window / 200ms slide: every row lands in exactly 5 windows
    (the reference enumerates overlapping slides at
    streaming_window.rs:1063-1075; we fan out on device)."""
    rng = np.random.default_rng(1)
    t0 = 1_700_000_000_000
    batches = [
        make_batch(
            np.sort(t0 + i * 300 + rng.integers(0, 300, 50)),
            ["s"] * 50,
            rng.normal(0, 1, 50),
        )
        for i in range(10)
    ]
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
        )
        .window(["sensor_name"], [F.count(col("reading")).alias("cnt")], 1000, 200)
        .collect()
    )
    starts = res.column(WINDOW_START_COLUMN)
    assert (np.diff(sorted(set(starts.tolist()))) == 200).all()
    assert sum(int(c) for c in res.column("cnt")) == 500 * 5


def test_sliding_window_non_multiple_slide(sensor_schema, make_batch):
    """Window length not a multiple of slide (1000ms/300ms): membership uses
    the exact ms bound, k = ceil(L/S) = 4 but some rows hit only 3 windows."""
    t0 = 1_700_000_000_000
    ts = t0 + np.arange(0, 3000, 10)
    batches = [make_batch(ts, ["s"] * len(ts), np.ones(len(ts)))]
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(batches, timestamp_column="occurred_at_ms")
        )
        .window(["sensor_name"], [F.count(col("reading")).alias("cnt")], 1000, 300)
        .collect()
    )
    got = {
        int(res.column(WINDOW_START_COLUMN)[i]): int(res.column("cnt")[i])
        for i in range(res.num_rows)
    }
    oracle = collections.Counter()
    for t in ts.tolist():
        j = t // 300
        while j * 300 + 1000 > t:
            if j * 300 <= t:
                oracle[j * 300] += 1
            j -= 1
    assert got == dict(oracle)


def test_group_capacity_growth_first_batch(sensor_schema, make_batch):
    """More distinct keys in the first batch than the initial capacity (128):
    G must grow before any scatter drops data."""
    rng = np.random.default_rng(2)
    t0 = 1_700_000_000_000
    n = 5000
    ts = np.sort(t0 + rng.integers(0, 2000, n))
    keys = np.array([f"k{i}" for i in rng.integers(0, 2000, n)], dtype=object)
    vals = rng.normal(0, 1, n)
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(
                [make_batch(ts, keys, vals)], timestamp_column="occurred_at_ms"
            )
        )
        .window(["sensor_name"], [F.sum(col("reading")).alias("s")], 1000)
        .collect()
    )
    oracle = collections.defaultdict(float)
    for t, k, v in zip(ts, keys, vals):
        oracle[((t // 1000) * 1000, k)] += v
    got = {
        (int(res.column(WINDOW_START_COLUMN)[i]), res.column("sensor_name")[i]): float(
            res.column("s")[i]
        )
        for i in range(res.num_rows)
    }
    assert set(got) == set(oracle)
    for k in oracle:
        np.testing.assert_allclose(got[k], oracle[k], rtol=1e-4, atol=1e-4)


def test_window_ring_growth(sensor_schema, make_batch):
    """A single batch spanning 40 windows grows the ring (initial 16)."""
    t0 = 1_700_000_000_000
    ts = t0 + np.arange(0, 40_000, 100)
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches(
                [make_batch(ts, ["a"] * len(ts), np.ones(len(ts)))],
                timestamp_column="occurred_at_ms",
            )
        )
        .window(["sensor_name"], [F.count(col("reading")).alias("cnt")], 1000)
        .collect()
    )
    assert res.num_rows == 40
    assert all(int(c) == 10 for c in res.column("cnt"))


def test_null_values_excluded(sensor_schema):
    """Null readings are excluded from count/sum/avg/min/max
    (DataFusion null semantics the reference inherits)."""
    t0 = 1_700_000_000_000
    batch = RecordBatch(
        sensor_schema,
        [
            np.array([t0 + 10, t0 + 20, t0 + 30, t0 + 1500], dtype=np.int64),
            np.array(["a", "a", "a", "a"], dtype=object),
            np.array([1.0, 99.0, 3.0, 0.0]),
        ],
        masks=[None, None, np.array([True, False, True, True])],
    )
    ctx = Context()
    res = (
        ctx.from_source(
            MemorySource.from_batches([batch], timestamp_column="occurred_at_ms")
        )
        .window(
            ["sensor_name"],
            [
                F.count(col("reading")).alias("cnt"),
                F.sum(col("reading")).alias("s"),
                F.max(col("reading")).alias("mx"),
            ],
            1000,
        )
        .collect()
    )
    i = list(res.column(WINDOW_START_COLUMN)).index(t0)
    assert int(res.column("cnt")[i]) == 2
    assert float(res.column("s")[i]) == 4.0
    assert float(res.column("mx")[i]) == 3.0
