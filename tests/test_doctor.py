"""Pipeline doctor: plan registry, bottleneck attribution, sampled
record lineage, the HTTP introspection surface, and the sampling
profiler (obs/doctor/, docs/observability.md §"Operating the doctor").

The two acceptance tests the ISSUE names live here: a deliberately
throttled operator must be NAMED as the top suspect by node id, and a
sampled record must be traceable ingest offset → emission through the
query API.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from denormalized_tpu import Context, col, obs
from denormalized_tpu.api import functions as F
from denormalized_tpu.api.context import EngineConfig
from denormalized_tpu.common.schema import DataType
from denormalized_tpu.obs.doctor import attribution, get_query
from denormalized_tpu.obs.registry import MetricsRegistry
from denormalized_tpu.sources.memory import MemorySource


@pytest.fixture
def registry():
    reg = MetricsRegistry(enabled=True)
    prev = obs.use_registry(reg)
    yield reg
    obs.use_registry(prev)


T0 = 1_700_000_000_000


def _batches(make_batch, n_batches=8, rows=200, seed=0):
    rng = np.random.default_rng(seed)
    out = []
    for b in range(n_batches):
        ts = np.sort(T0 + b * 400 + rng.integers(0, 400, size=rows))
        names = rng.choice([f"sensor_{i}" for i in range(5)], size=rows)
        vals = rng.normal(50.0, 10.0, size=rows)
        out.append(make_batch(ts, names, vals))
    return out


def _mem(batches):
    return MemorySource.from_batches(
        batches, timestamp_column="occurred_at_ms"
    )


def _window_ds(ctx, batches):
    return ctx.from_source(_mem(batches)).window(
        [col("sensor_name")],
        [F.count(col("reading")).alias("count")],
        1000,
    )


def _get(url, timeout=5):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read()


# -- acceptance: throttled operator is NAMED --------------------------------


def test_throttled_operator_named_top_suspect(make_batch, registry):
    """A deliberately slow stage (a UDF sleeping per batch inside a
    projection) must come out as the doctor's #1 ranked suspect, by its
    exact node id — the attribution rule names the stage, the reader
    never infers it."""

    def throttle(vals):
        # 60ms per batch x 16 batches ≈ 1s: decisively above everything
        # else in the plan, including the window's first-batch compile
        time.sleep(0.06)
        return vals

    slow = F.udf(throttle, DataType.FLOAT64, "throttle")
    ctx = Context(EngineConfig(min_batch_bucket=256))
    ds = (
        ctx.from_source(_mem(_batches(make_batch, n_batches=16)))
        .with_column("reading", slow(col("reading")))
        .window(
            [col("sensor_name")],
            [F.count(col("reading")).alias("count")],
            1000,
        )
    )
    ds.collect()
    handle = ctx._last_doctor
    assert handle is not None and not handle.running
    snap = handle.snapshot()
    suspects = snap["attribution"]["suspects"]
    top = suspects[0]
    assert "ProjectExec" in top["node_id"], suspects
    assert snap["attribution"]["bottleneck"] == top["node_id"]
    # the throttle is 60ms x 16 batches ≈ 1s of measured busy time
    assert top["busy_ms"] >= 700.0
    assert top["share_of_wall"] > 0.3
    # the rule ships with the ranking, verbatim
    assert "wall time" in snap["attribution"]["rule"]


def test_attribution_rank_residual_to_uninstrumented_child():
    """Unit contract of the documented rule: a consumer's input wait
    minus its child's measured time is attributed to the child (a
    source's un-bracketed fetch/decode)."""
    nodes = [
        {"node_id": "0_Sink", "label": "sink", "children": ["1_Win"],
         "busy_ms": 5.0, "input_wait_ms": 100.0},
        {"node_id": "1_Win", "label": "win", "children": ["2_Src"],
         "busy_ms": 40.0, "input_wait_ms": 55.0},
        {"node_id": "2_Src", "label": "src", "children": [],
         "busy_ms": 0.0, "input_wait_ms": 0.0},
    ]
    ranked = attribution.rank(nodes, wall_ms=110.0)
    by_id = {r["node_id"]: r for r in ranked}
    # sink's 100ms wait is fully explained by win (40 + 55) + residual 5
    assert by_id["1_Win"]["attributed_wait_ms"] == pytest.approx(5.0)
    # win's 55ms wait is unexplained by src (0 measured) → all attributed
    assert by_id["2_Src"]["attributed_wait_ms"] == pytest.approx(55.0)
    assert by_id["2_Src"]["basis"] == "attributed"
    # ranking: src 55 > win 45 > sink 5
    assert [r["node_id"] for r in ranked] == ["2_Src", "1_Win", "0_Sink"]


def test_explain_analyze_names_bottleneck(make_batch, registry, capsys):
    ctx = Context(EngineConfig(min_batch_bucket=256))
    text = _window_ds(ctx, _batches(make_batch)).explain_analyze()
    assert "bottleneck:" in text
    assert "StreamingWindowExec" in text
    assert "rule:" in text
    # per-node annotations are live numbers, not placeholders
    assert "rows/s=" in text and "busy=" in text
    assert text in capsys.readouterr().out


# -- acceptance: sampled record lineage end to end --------------------------


def test_lineage_chain_ingest_to_emission(make_batch, registry):
    """A sampled record's chain must run ingest offset → operator hops
    → window emission, with the emission window containing the record's
    event time."""
    ctx = Context(EngineConfig(
        min_batch_bucket=256, lineage_sample_every=100,
    ))
    _window_ds(ctx, _batches(make_batch)).collect()
    handle = ctx._last_doctor
    assert handle.lineage is not None
    chains = handle.lineage.chains()
    assert len(chains) >= 8  # 1600 rows / 100
    completed = [c for c in chains if c["emissions"]]
    assert completed, "no lineage chain reached emission"
    for c in completed:
        # the source label may carry the per-process ordinal suffix
        # (_source_series_label): earlier queries claimed "memory"
        assert c["source"].startswith("memory")
        assert c["offset"].get("pos") is not None  # reader offset snapshot
        e = c["emissions"][0]
        assert (
            e["window_start_ms"] <= c["event_time_ms"] < e["window_end_ms"]
        )
        assert "StreamingWindowExec" in e["node_id"]
        # at least one pre-aggregation hop was recorded
        assert any(
            "StreamingWindowExec" in h["node_id"] for h in c["hops"]
        )
    # the "why is this window late" lookup: filter by window start
    ws = completed[0]["emissions"][0]["window_start_ms"]
    filtered = handle.lineage.chains(window_start_ms=ws)
    assert filtered
    assert all(
        any(e["window_start_ms"] == ws for e in c["emissions"])
        for c in filtered
    )


def test_lineage_session_window_chain(make_batch, registry):
    """Session emissions report per-slot [start, last+gap) interval
    ARRAYS — the multi-window emitted() path — and chains still close by
    event-time containment."""
    ctx = Context(EngineConfig(
        min_batch_bucket=256, lineage_sample_every=150,
    ))
    ds = ctx.from_source(_mem(_batches(make_batch))).session_window(
        [col("sensor_name")],
        [F.count(col("reading")).alias("count")],
        300,
    )
    ds.collect()
    chains = ctx._last_doctor.lineage.chains()
    completed = [c for c in chains if c["emissions"]]
    assert completed, "no session lineage chain reached emission"
    for c in completed:
        e = c["emissions"][0]
        assert "SessionWindowExec" in e["node_id"]
        assert (
            e["window_start_ms"] <= c["event_time_ms"] < e["window_end_ms"]
        )


def test_lineage_flow_events_on_span_stream(make_batch, registry, tmp_path):
    """Lineage lands as flow-connected (s/t/f) events on the PR-6 trace
    stream, sharing ids so Perfetto draws the chain."""
    trace_path = tmp_path / "trace.json"
    ctx = Context(EngineConfig(
        min_batch_bucket=256,
        lineage_sample_every=200,
        trace_path=str(trace_path),
    ))
    try:
        _window_ds(ctx, _batches(make_batch)).collect()
    finally:
        from denormalized_tpu.obs import spans as obs_spans

        obs_spans.disable_span_recording()
    trace = json.loads(trace_path.read_text())
    flows = [e for e in trace["traceEvents"] if e.get("ph") in "stf"]
    assert flows, "no lineage flow events in the trace"
    by_id = {}
    for e in flows:
        assert e["name"] == "lineage" and "id" in e
        by_id.setdefault(e["id"], set()).add(e["ph"])
    # at least one chain is fully connected: start, step(s), finish
    assert any({"s", "t", "f"} <= phases for phases in by_id.values())


# -- the HTTP surface -------------------------------------------------------


def test_queries_plan_and_lineage_endpoints_live(make_batch, registry):
    """Mid-stream, the doctor endpoints serve the live plan (annotated
    nodes + attribution) and the lineage chains for a running query."""
    ctx = Context(EngineConfig(
        min_batch_bucket=256, prometheus_port=0,
        lineage_sample_every=100,
    ))
    ds = _window_ds(ctx, _batches(make_batch, n_batches=12))
    it = ds.stream()
    try:
        next(it)  # at least one emission: windows have closed mid-run
        port = ctx._last_exporters.prometheus.port
        base = f"http://127.0.0.1:{port}"

        status, ctype, body = _get(f"{base}/healthz")
        assert status == 200 and ctype.startswith("application/json")
        health = json.loads(body)
        assert health["status"] == "ok"
        assert health["queries_running"] >= 1

        status, _, body = _get(f"{base}/queries")
        queries = json.loads(body)["queries"]
        running = [q for q in queries if q["state"] == "running"]
        assert running
        qid = running[0]["query_id"]

        status, _, body = _get(f"{base}/queries/{qid}/plan")
        assert status == 200
        plan = json.loads(body)
        assert plan["state"] == "running"
        node_ids = {n["node_id"] for n in plan["nodes"]}
        assert any("StreamingWindowExec" in n for n in node_ids)
        assert any("SourceExec" in n for n in node_ids)
        assert plan["attribution"]["bottleneck"] in node_ids
        for n in plan["nodes"]:
            assert {"busy_ms", "input_wait_ms", "rows_per_s"} <= set(n)

        status, _, body = _get(f"{base}/queries/{qid}/lineage")
        assert status == 200
        lineage = json.loads(body)
        assert lineage["sample_every"] == 100
        assert lineage["sampled_total"] >= 1

        # unknown query id → 404 with the known ids listed
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(f"{base}/queries/nope/plan")
        assert ei.value.code == 404
    finally:
        for _ in it:
            pass
    # after the stream ends the query is still introspectable in-process
    # via the retained finished ring (the HTTP server is down by design)
    handle = ctx._last_doctor
    assert get_query(handle.query_id) is handle
    assert handle.snapshot()["state"] == "finished"


def test_profiler_start_stop_over_http(make_batch, registry):
    ctx = Context(EngineConfig(min_batch_bucket=256, prometheus_port=0))
    ds = _window_ds(ctx, _batches(make_batch, n_batches=30, rows=2000))
    it = ds.stream()
    try:
        next(it)
        port = ctx._last_exporters.prometheus.port
        base = f"http://127.0.0.1:{port}"
        qid = json.loads(_get(f"{base}/queries")[2])["queries"][0][
            "query_id"
        ]
        status, _, body = _get(
            f"{base}/queries/{qid}/profile/start?hz=200"
        )
        assert status == 200 and json.loads(body)["profiling"] is True
        # drive the pipeline while the sampler runs
        for _ in range(8):
            next(it, None)
        time.sleep(0.05)
        status, _, body = _get(f"{base}/queries/{qid}/profile/stop")
        stopped = json.loads(body)
        assert stopped["profiling"] is False
        assert stopped["samples"] >= 1
        status, ctype, body = _get(f"{base}/queries/{qid}/profile")
        assert status == 200 and ctype.startswith("text/plain")
        folded = body.decode()
        # folded-stack grammar: "frame;frame;... count" per line
        for line in folded.splitlines():
            stack, _, count = line.rpartition(" ")
            assert stack and int(count) >= 1
    finally:
        for _ in it:
            pass


def test_profiler_folded_stacks_capture_running_code(registry):
    from denormalized_tpu.obs.doctor.profiler import SamplingProfiler

    stop = threading.Event()

    def busy_beaver():
        while not stop.is_set():
            sum(i * i for i in range(2000))

    t = threading.Thread(target=busy_beaver, name="beaver", daemon=True)
    t.start()
    prof = SamplingProfiler(hz=400).start()
    try:
        time.sleep(0.25)
    finally:
        n = prof.stop()
        stop.set()
        t.join(timeout=2)
    assert n >= 20
    folded = prof.folded()
    assert "busy_beaver" in folded
    assert any(line.startswith("beaver;") for line in folded.splitlines())


# -- teardown resilience (rides the lock witness) ---------------------------


def test_concurrent_scrapes_during_teardown_never_500(make_batch, registry):
    """Satellite acceptance: scrapes against /metrics, /healthz,
    /queries and /queries/<id>/plan racing operator + exporter teardown
    must never see a 5xx and never deadlock.  Connection errors once the
    server is down are the expected end state."""
    ctx = Context(EngineConfig(
        min_batch_bucket=256, prometheus_port=0,
        lineage_sample_every=100,
    ))
    ds = _window_ds(ctx, _batches(make_batch, n_batches=20))
    it = ds.stream()
    next(it)
    port = ctx._last_exporters.prometheus.port
    base = f"http://127.0.0.1:{port}"
    qid = json.loads(_get(f"{base}/queries")[2])["queries"][0]["query_id"]
    paths = ["/metrics", "/healthz", "/queries", f"/queries/{qid}/plan",
             f"/queries/{qid}/lineage"]
    bad: list = []
    server_down = threading.Event()

    def hammer(path):
        while not server_down.is_set():
            try:
                status, _, _ = _get(base + path, timeout=5)
                if status >= 500:
                    bad.append((path, status))
            except urllib.error.HTTPError as e:
                if e.code >= 500:
                    bad.append((path, e.code))
            except (urllib.error.URLError, ConnectionError, OSError):
                # server stopped (teardown finished): expected terminal
                server_down.set()

    threads = [
        threading.Thread(target=hammer, args=(p,), daemon=True)
        for p in paths for _ in range(2)
    ]
    for t in threads:
        t.start()
    # drain to completion → operators tear down, exporters stop, the
    # doctor freezes its final snapshot — all while the hammers run
    for _ in it:
        pass
    server_down.wait(timeout=30)
    for t in threads:
        t.join(timeout=10)
    assert not any(t.is_alive() for t in threads), "scrape thread hung"
    assert bad == [], f"5xx during teardown: {bad}"


def test_setup_failure_tears_down_started_exporters(make_batch, registry):
    """A failure while wiring per-query services (an invalid lineage
    config raising in register_query) must stop the exporters that
    already started — not leak a bound HTTP port and live threads."""
    ctx = Context(EngineConfig(
        min_batch_bucket=256, prometheus_port=0,
        lineage_sample_every=-1,  # rejected by LineageTracker
    ))
    with pytest.raises(ValueError, match="lineage_sample_every"):
        _window_ds(ctx, _batches(make_batch)).collect()
    server = ctx._last_exporters.prometheus
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        _get(f"http://127.0.0.1:{server.port}/healthz", timeout=2)
    # same teardown contract on the stream path
    ctx2 = Context(EngineConfig(
        min_batch_bucket=256, prometheus_port=0, lineage_sample_every=-1,
    ))
    with pytest.raises(ValueError, match="lineage_sample_every"):
        next(_window_ds(ctx2, _batches(make_batch)).stream())
    server2 = ctx2._last_exporters.prometheus
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        _get(f"http://127.0.0.1:{server2.port}/healthz", timeout=2)


def test_doctor_disabled_opt_out(make_batch, registry):
    ctx = Context(EngineConfig(min_batch_bucket=256, doctor_enabled=False))
    ds = _window_ds(ctx, _batches(make_batch))
    out = ds.collect()
    assert out.num_rows > 0
    assert ctx._last_doctor is None
    # explain_analyze still works, via the metrics-dump fallback
    text = _window_ds(ctx, _batches(make_batch)).explain_analyze(
        print_output=False
    )
    assert "StreamingWindowExec" in text


def test_profiler_start_after_finish_refuses(make_batch, registry):
    """A /profile/start racing query end must not leak a sampler: on a
    finished handle, start_profiler refuses (None) and the HTTP route
    404s instead of starting a thread nothing will ever stop."""
    ctx = Context(EngineConfig(min_batch_bucket=256))
    _window_ds(ctx, _batches(make_batch)).collect()
    handle = ctx._last_doctor
    assert not handle.running
    assert handle.start_profiler() is None
    assert handle.profiler is None
    from denormalized_tpu.obs.doctor import http as doctor_http

    status, _, body = doctor_http.route(
        f"/queries/{handle.query_id}/profile/start"
    )
    assert status == 404
    assert b"finished" in body


def test_finished_handle_drops_operator_tree(make_batch, registry):
    """The retained finished ring must not pin operator graphs (window
    state, prefetch buffers) — finish() freezes a plain-dict snapshot
    and drops the tree reference."""
    ctx = Context(EngineConfig(min_batch_bucket=256))
    _window_ds(ctx, _batches(make_batch)).collect()
    handle = ctx._last_doctor
    assert handle.root is None
    snap = handle.snapshot()
    assert snap["state"] == "finished"
    assert snap["attribution"]["suspects"]
    # render works from the frozen snapshot
    assert "bottleneck:" in handle.render()
