"""Property tests for session windows vs an independent oracle: interval
merging, watermark-driven closing, late-row dropping, EOS flush."""

import numpy as np
import pytest
pytest.importorskip("hypothesis", reason="hypothesis not installed in this image")
from hypothesis import given, settings, strategies as st

from denormalized_tpu import Context, col
from denormalized_tpu.api import functions as F
from denormalized_tpu.common.record_batch import RecordBatch
from denormalized_tpu.common.schema import DataType, Field, Schema
from denormalized_tpu.sources.memory import MemorySource

SCHEMA = Schema(
    [
        Field("ts", DataType.INT64, nullable=False),
        Field("k", DataType.STRING, nullable=False),
        Field("v", DataType.FLOAT64),
    ]
)
T0 = 1_700_000_000_000


def session_oracle(batches, gap):
    """Independent simulation: per key, a set of open (start, last, cnt, sum)
    sessions; a new row merges every session within `gap` in either
    direction; sessions close when the watermark passes last+gap.  A row
    with ts+gap <= watermark is dropped ONLY if it would be a closed
    singleton — if it lies within gap of a still-open session it merges
    into it (Flink event-time session semantics)."""
    wm = None
    open_s: dict[str, list[list]] = {}
    closed = []
    for ts, ks, vs in batches:
        for t, k, v in zip(ts, ks, vs):
            if wm is not None and t + gap <= wm:
                if not any(
                    t - s[1] <= gap and s[0] - t <= gap
                    for s in open_s.get(k, [])
                ):
                    continue  # late closed singleton: dropped
            merged = [t, t, 1, v]
            keep = []
            for s in open_s.get(k, []):
                if t - s[1] <= gap and s[0] - t <= gap:
                    merged[0] = min(merged[0], s[0])
                    merged[1] = max(merged[1], s[1])
                    merged[2] += s[2]
                    merged[3] += s[3]
                else:
                    keep.append(s)
            keep.append(merged)
            open_s[k] = keep
        bmin = min(ts)
        if wm is None or bmin > wm:
            wm = bmin
        for k in list(open_s):
            still = []
            for s in open_s[k]:
                if s[1] + gap <= wm:
                    closed.append((k, s[0], s[1] + gap, s[2], s[3]))
                else:
                    still.append(s)
            if still:
                open_s[k] = still
            else:
                del open_s[k]
    for k, lst in open_s.items():
        for s in lst:
            closed.append((k, s[0], s[1] + gap, s[2], s[3]))
    return {
        (k, start): (end, cnt, round(sm, 4))
        for k, start, end, cnt, sm in closed
    }


@st.composite
def session_case(draw):
    gap = draw(st.sampled_from([100, 300, 700]))
    n_batches = draw(st.integers(2, 5))
    batches = []
    base = 0
    for _ in range(n_batches):
        n = draw(st.integers(1, 20))
        base += draw(st.integers(0, 400))
        offs = draw(st.lists(st.integers(-200, 500), min_size=n, max_size=n))
        ts = sorted(max(0, base + o) + T0 for o in offs)
        ks = draw(st.lists(st.sampled_from(["a", "b"]), min_size=n, max_size=n))
        vs = [float(i % 5) for i in range(n)]
        batches.append((ts, ks, vs))
    return gap, batches


@settings(max_examples=30, deadline=None)
@given(session_case())
def test_session_engine_matches_oracle(case):
    gap, raw = case
    batches = [
        RecordBatch(
            SCHEMA,
            [np.asarray(ts, np.int64), np.asarray(ks, object), np.asarray(vs)],
        )
        for ts, ks, vs in raw
    ]
    ctx = Context()
    res = (
        ctx.from_source(MemorySource.from_batches(batches, timestamp_column="ts"))
        .session_window(
            ["k"],
            [F.count(col("v")).alias("cnt"), F.sum(col("v")).alias("s")],
            gap,
        )
        .collect()
    )
    got = {}
    for i in range(res.num_rows):
        key = (res.column("k")[i], int(res.column("window_start_time")[i]))
        assert key not in got, f"duplicate session {key}"
        got[key] = (
            int(res.column("window_end_time")[i]),
            int(res.column("cnt")[i]),
            round(float(res.column("s")[i]), 4),
        )
    want = session_oracle(raw, gap)
    assert got == want, (
        sorted(set(got) ^ set(want))[:4],
        gap,
    )


# -- per-partition watermarks: lossless partitioned session replay --------


@st.composite
def partitioned_session_case(draw):
    """2-3 time-ordered partitions with arbitrary skew.  All timestamps
    are EVEN and gaps ODD: the engine's close-at-``last+gap <= wm``
    boundary vs the merge-at-``t-last <= gap`` rule makes behavior at
    exact equality arrival-order dependent, and the union oracle below
    is order-free — the even/odd split keeps the property exact."""
    gap = draw(st.sampled_from([101, 301, 701]))
    n_parts = draw(st.integers(2, 3))
    parts = []
    for _ in range(n_parts):
        n_batches = draw(st.integers(1, 4))
        pos = draw(st.integers(0, 300))
        batches = []
        for _ in range(n_batches):
            span = draw(st.integers(1, 400))
            n = draw(st.integers(1, 12))
            offs = draw(
                st.lists(st.integers(0, span), min_size=n, max_size=n)
            )
            ts = sorted(T0 + 2 * (pos + o) for o in offs)
            ks = draw(
                st.lists(st.sampled_from(["a", "b"]), min_size=n, max_size=n)
            )
            vs = [float(i % 5) for i in range(n)]
            batches.append((ts, ks, vs))
            pos += span + draw(st.integers(1, 150))
        parts.append(batches)
    return gap, parts


@settings(max_examples=40, deadline=None)
@given(partitioned_session_case())
def test_partitioned_session_replay_is_lossless(case):
    """With per-partition watermarks (auto-on for bounded multi-partition
    sources) no row of a time-ordered partition can drop late, so the
    emitted sessions must equal classic interval merging over the UNION
    of all partitions' rows — regardless of cross-partition skew."""
    gap, parts = case
    part_batches = [
        [
            RecordBatch(
                SCHEMA,
                [
                    np.asarray(ts, np.int64),
                    np.asarray(ks, object),
                    np.asarray(vs),
                ],
            )
            for ts, ks, vs in p
        ]
        for p in parts
    ]
    ctx = Context()
    res = (
        ctx.from_source(MemorySource(part_batches, timestamp_column="ts"))
        .session_window(
            ["k"],
            [F.count(col("v")).alias("cnt"), F.sum(col("v")).alias("s")],
            gap_ms=gap,
        )
        .collect()
    )
    got = {}
    for i in range(res.num_rows):
        got[(res.column("k")[i], int(res.column("window_start_time")[i]))] = (
            int(res.column("window_end_time")[i]),
            int(res.column("cnt")[i]),
            round(float(res.column("s")[i]), 4),
        )
    # union oracle: interval merging per key over ALL rows
    rows_by_key: dict[str, list] = {}
    for p in parts:
        for ts, ks, vs in p:
            for t, k, v in zip(ts, ks, vs):
                rows_by_key.setdefault(k, []).append((t, v))
    want = {}
    for k, rows in rows_by_key.items():
        rows.sort()
        seg = [rows[0]]
        for t, v in rows[1:]:
            if t - seg[-1][0] <= gap:
                seg.append((t, v))
            else:
                want[(k, seg[0][0])] = (
                    seg[-1][0] + gap, len(seg),
                    round(sum(x[1] for x in seg), 4),
                )
                seg = [(t, v)]
        want[(k, seg[0][0])] = (
            seg[-1][0] + gap, len(seg),
            round(sum(x[1] for x in seg), 4),
        )
    assert got == want, {
        "extra": {k: v for k, v in got.items() if want.get(k) != v},
        "missing": {k: v for k, v in want.items() if got.get(k) != v},
    }


# -- vectorized operator vs the kept reference implementation -------------


@settings(max_examples=40, deadline=None)
@given(session_case())
def test_vectorized_matches_reference_operator(case):
    """Property form of tests/test_session_vectorized.py: the vectorized
    operator and the pre-vectorization reference must agree on every
    emitted session — all builtin aggregate kinds, emission-cycle grouping
    included — over arbitrary out-of-order multi-batch workloads."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).parent))
    from test_session_vectorized import assert_parity, kv

    gap, raw = case
    items = [kv(ts, ks, vs) for ts, ks, vs in raw]
    assert_parity(items, gap_ms=gap)
